"""Worker program for tests/test_multihost.py — one real JAX process
of an N-process CPU cluster (the TPU-native analog of the reference's
``mpiexec -n 1/2/10 pytest`` story, ``/root/reference/tests/test_mpi.py:1-7``;
the process count is a parameter exactly as ``-n`` was).

Run as: python _multihost_worker.py <port> <process_id> <nprocs> <tmpdir>
Exits 0 after printing WORKER-OK; any assertion/desync fails the exit
code (or hangs, which the parent's timeout converts to a failure).
"""
import os
import sys

PORT, PID, NPROCS, TMP = (sys.argv[1], int(sys.argv[2]),
                          int(sys.argv[3]), sys.argv[4])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import multigrad_tpu as mgt  # noqa: E402
from multigrad_tpu.parallel import distributed  # noqa: E402
from multigrad_tpu.models.smf import (TARGET_SUMSTATS, ParamTuple,  # noqa: E402
                                      SMFModel, load_halo_masses)

# ----------------------------------------------------------------- #
# Bootstrap (parallel/distributed.py happy path)
# ----------------------------------------------------------------- #
distributed.initialize(coordinator_address=f"localhost:{PORT}",
                       num_processes=NPROCS, process_id=PID)
distributed.initialize()  # idempotent second call must be a no-op
assert distributed.process_count() == NPROCS
assert distributed.process_index() == PID
assert distributed.is_main_process() == (PID == 0)

comm = mgt.global_comm()
NDEV = 2 * NPROCS
assert comm.size == NDEV  # NPROCS hosts x 2 virtual devices

# ----------------------------------------------------------------- #
# scatter_from_local + reduce_sum across real process boundaries
# ----------------------------------------------------------------- #
local = np.arange(2.0) + 10.0 * PID  # host p: [10p, 10p+1]
arr = mgt.scatter_from_local(local, comm)
assert arr.shape == (NDEV,)
total = mgt.reduce_sum(arr, comm=comm)  # outside-trace shard summing
expect = sum(10.0 * p + k for p in range(NPROCS) for k in (0, 1))
assert float(np.asarray(total)[0]) == expect, np.asarray(total)
# Replicated scalar contribution: multiplied by comm.size (MPI parity)
assert mgt.reduce_sum(1.0, comm=comm) == NDEV

# ----------------------------------------------------------------- #
# Golden-vector parity on N processes (reference test_mpi.py:44-53,
# which asserts the same vector under mpiexec -n 1/2/10)
# ----------------------------------------------------------------- #
TRUTH = ParamTuple(log_shmrat=-2.0, sigma_logsm=0.2)
N = 10_000  # the golden fixture size (divides 2/4-proc layouts)
log_mh = np.asarray(jnp.log10(load_halo_masses(N)))
per_proc = N // NPROCS
aux = dict(
    log_halo_masses=mgt.scatter_from_local(
        log_mh[PID * per_proc:(PID + 1) * per_proc], comm),
    smf_bin_edges=jnp.linspace(9, 10, 11),
    volume=10.0 * N,
    target_sumstats=jnp.asarray(TARGET_SUMSTATS),
    chunk_size=None,
    backend="xla",
)
model = SMFModel(aux_data=aux, comm=comm)
ss = np.asarray(model.calc_sumstats_from_params(TRUTH))
# rtol 5e-4: the N-process gloo reduction orders float32 sums
# differently from the single-host path; the sparsest bin (~9e-6)
# moves by ~4e-4 relative at 2 procs and stays within this margin
# at 4 (both parameterized cases run in CI).
np.testing.assert_allclose(ss, np.asarray(TARGET_SUMSTATS), rtol=5e-4)

# ----------------------------------------------------------------- #
# Checkpointed-Adam resume where ONLY process 0 holds the file
# (optim/adam.py broadcast-resume + fingerprint agreement + key
# re-wrap — every process_count() > 1 branch)
# ----------------------------------------------------------------- #
GUESS = ParamTuple(-1.0, 0.5)
ckpt_dir = os.path.join(TMP, f"proc{PID}")  # host-local disk
plain = np.asarray(model.run_adam(guess=GUESS, nsteps=8,
                                  learning_rate=0.02, randkey=3,
                                  progress=False))

fit1 = np.asarray(model.run_adam(guess=GUESS, nsteps=8,
                                 learning_rate=0.02, randkey=3,
                                 progress=False,
                                 checkpoint_dir=ckpt_dir,
                                 checkpoint_every=4))
np.testing.assert_allclose(fit1, plain, rtol=1e-6)
# Only the main process writes checkpoints
has_file = os.path.exists(os.path.join(ckpt_dir, "adam_state.npz"))
assert has_file == (PID == 0), (PID, has_file)

# Re-invocation: process 0 resumes from its file; process 1 has no
# file and must adopt process 0's state via the broadcast (removing
# the broadcast desyncs the collective schedules and hangs here).
fit2 = np.asarray(model.run_adam(guess=GUESS, nsteps=8,
                                 learning_rate=0.02, randkey=3,
                                 progress=False,
                                 checkpoint_dir=ckpt_dir,
                                 checkpoint_every=4))
np.testing.assert_allclose(fit2, plain, rtol=1e-6)

# Both processes must hold identical trajectories.
from jax.experimental import multihost_utils  # noqa: E402
ref = np.asarray(multihost_utils.broadcast_one_to_all(fit2))
np.testing.assert_array_equal(fit2, ref)

# ----------------------------------------------------------------- #
# BFGS determinism across real processes: every host runs the same
# scipy loop on psum-replicated inputs, so the "all ranks return an
# identical OptimizeResult" contract (reference bfgs.py:108-113) must
# hold BITWISE with no broadcast in the implementation.  Compared as
# raw uint32 words — broadcast_one_to_all would silently downcast
# float64 (x64 is off), which would weaken the check.
# ----------------------------------------------------------------- #
res = model.run_bfgs(guess=GUESS, maxsteps=40, progress=False)
packed = np.concatenate([
    np.asarray(res.x, np.float64), np.asarray(res.jac, np.float64),
    np.asarray([res.fun, float(res.nit), float(res.nfev),
                float(bool(res.success))], np.float64),
]).view(np.uint32)
ref_words = np.asarray(multihost_utils.broadcast_one_to_all(
    jnp.asarray(packed)))
np.testing.assert_array_equal(packed, ref_words)
assert res.nit > 0 and res.fun < 1e-6, (res.nit, res.fun)

# ----------------------------------------------------------------- #
# ppermute ring across the real process boundary: the wp(rp) pair
# ring's neighbor exchange must cross from host 0's devices to host
# 1's (gloo) and still reproduce the single-block totals + gradients.
# ----------------------------------------------------------------- #
from multigrad_tpu.models.wprp import (WprpModel, WprpParams,  # noqa: E402
                                       make_wprp_data)
wp_single = WprpModel(aux_data=make_wprp_data(256, 50.0, comm=None,
                                              seed=5), comm=None)
wp_mesh = WprpModel(aux_data=make_wprp_data(256, 50.0, comm=comm,
                                            seed=5), comm=comm)
wp_params = WprpParams(-1.95, -0.9)
np.testing.assert_allclose(
    np.asarray(wp_mesh.calc_sumstats_from_params(wp_params)),
    np.asarray(wp_single.calc_sumstats_from_params(wp_params)),
    rtol=5e-4)
np.testing.assert_allclose(
    np.asarray(wp_mesh.calc_dloss_dparams(wp_params)),
    np.asarray(wp_single.calc_dloss_dparams(wp_params)),
    rtol=2e-3, atol=1e-6)

# ----------------------------------------------------------------- #
# Fused same-mesh group across real processes: the joint step is ONE
# XLA program containing both members' shard_maps; the whole-fit scan
# must land bitwise-identical trajectories on every host (its inputs
# are psum products, replicated by construction).
# ----------------------------------------------------------------- #
model_b = SMFModel(aux_data=dict(aux), comm=comm)
fgroup = mgt.OnePointGroup(models=(model, model_b))
assert fgroup.fused
gtraj = np.asarray(fgroup.run_adam(guess=GUESS, nsteps=4,
                                   learning_rate=0.02,
                                   progress=False))
ref_g = np.asarray(multihost_utils.broadcast_one_to_all(
    jnp.asarray(gtraj)))
np.testing.assert_array_equal(gtraj, ref_g)
# Two identical members: the joint gradient is 2x the solo one, so
# the fused program's result is cross-checkable against the model.
# rtol 5e-4 as in the golden check above: the fused program's
# inlined reductions may be reassociated differently from the
# standalone program's (float32 summation-order noise, not math).
gl, gg = fgroup.calc_loss_and_grad_from_params(jnp.array([*GUESS]))
# Fence before dispatching the NEXT collective-bearing program: on
# the multi-process gloo CPU backend, a program dispatched while the
# previous program's collectives are still in flight can interleave
# with them on the shared communicator and return NaN (observed
# reliably at 4 processes: the first solo call after the fused-group
# program was garbage on every process, all later calls correct).
# Real accelerator backends order collectives per device; this fence
# is CPU-gloo test hygiene, not a model-code requirement.
jax.block_until_ready((gl, gg))
sl, sg = model.calc_loss_and_grad_from_params(jnp.array([*GUESS]))
np.testing.assert_allclose(np.asarray(gl), 2 * np.asarray(sl),
                           rtol=5e-4)
np.testing.assert_allclose(np.asarray(gg), 2 * np.asarray(sg),
                           rtol=5e-4, atol=1e-8)

print(f"proc {PID}: WORKER-OK", flush=True)
