"""Smoke tests for the examples layer (reference L6).

The reference's examples double as acceptance tests (SURVEY §4); run
them small and headless.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=8",
    MPLBACKEND="Agg",
    # Replace PYTHONPATH entirely: drops any TPU-tunnel sitecustomize
    # (which re-forces JAX_PLATFORMS to the hardware backend at
    # interpreter start) while keeping the package importable from a
    # scratch cwd.
    PYTHONPATH=REPO,
)


def run_example(script, *args, timeout=300):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=timeout)


@pytest.mark.parametrize("optimizer", ["gd", "adam"])
def test_smf_grad_descent_pipeline(tmp_path, optimizer):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "smf_grad_descent.py"),
         "--num-halos", "8000", "--num-steps", "50",
         "--learning-rate", "0.01", "--optimizer", optimizer],
        capture_output=True, text=True, env=ENV, cwd=tmp_path, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Final solution" in out.stdout
    for png in ("hmf_model.png", "smf_fit.png", "gd_loss.png",
                "gd_param.png", "gd_param_path.png"):
        assert (tmp_path / png).exists(), f"missing plot {png}"


def test_streaming_smf_fit_example(tmp_path):
    # Out-of-core demo: memmapped catalog, streamed fit, scan
    # cross-check.  Small enough to run in seconds on the CPU mesh.
    catalog = str(tmp_path / "halos.npy")
    import numpy as np
    np.save(catalog, np.random.default_rng(0)
            .uniform(10.0, 12.0, 20_001).astype(np.float32))
    out = run_example("streaming_smf_fit.py", "--num-halos", "20001",
                      "--chunk-rows", "4096", "--num-steps", "10",
                      "--catalog", catalog, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "chunk plan:" in out.stdout
    assert "Final solution" in out.stdout
    assert "stream stats" in out.stdout


def test_benchmark_records_result(tmp_path):
    save = str(tmp_path / "bench.txt")
    out = run_example("benchmark.py", "--num-halos", "8000",
                      "--num-steps", "10", "--optimizer", "adam",
                      "--save", save, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "iterations/sec" in out.stdout
    with open(save) as f:
        record = eval(f.read().strip())
    assert record["num_devices"] == 8
    assert record["calls_per_sec"] > 0


def test_submit_jobs_generator():
    out = run_example("submit_benchmark_jobs.py", "--print-only",
                      "--accelerators", "v4-8", "v4-32", timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.count("tpu-vm create") == 2
    assert "benchmark.py" in out.stdout

def test_multiprobe_fit_example():
    out = run_example("multiprobe_fit.py", "--num-halos", "6000",
                      "--num-clustering-halos", "512")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "MPMD" in out.stdout
    assert "SUCCESS" in out.stdout


def test_multiprobe_fit_example_shared_mesh():
    out = run_example("multiprobe_fit.py", "--num-halos", "6000",
                      "--num-clustering-halos", "512", "--shared-mesh")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "fused (one XLA program)" in out.stdout
    assert "SUCCESS" in out.stdout


def test_orbax_pod_checkpoint_preempt_resume(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    ckpt = str(tmp_path / "podfit")
    args = ["--ckpt-dir", ckpt, "--num-halos", "4000",
            "--num-steps", "60", "--segment", "20"]
    # Simulated preemption after one segment, then resume to the end.
    out1 = run_example("orbax_pod_checkpoint.py", *args,
                       "--max-segments", "1")
    assert out1.returncode == 0, out1.stderr[-2000:]
    assert "preempted at step 20" in out1.stdout
    out2 = run_example("orbax_pod_checkpoint.py", *args)
    assert out2.returncode == 0, out2.stderr[-2000:]
    assert "resumed from step 20" in out2.stdout
    assert "DONE step=60" in out2.stdout
    # Resume must reproduce the uninterrupted fit exactly (the
    # segmented scan is deterministic).
    out3 = run_example("orbax_pod_checkpoint.py", "--ckpt-dir",
                       str(tmp_path / "oneshot"), "--num-halos", "4000",
                       "--num-steps", "60", "--segment", "20")
    assert out3.returncode == 0, out3.stderr[-2000:]
    line = [l for l in out2.stdout.splitlines() if "DONE" in l][0]
    line3 = [l for l in out3.stdout.splitlines() if "DONE" in l][0]
    assert line == line3, (line, line3)


def test_galhalo_history_fit_example():
    # BASELINE config 4's example: multi-epoch diffmah-style history
    # fit, all ten parameters, sharded over the 8-device mesh.
    out = run_example("galhalo_history_fit.py", "--num-halos", "30000",
                      "--maxsteps", "300", timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RECOVERED" in out.stdout


@pytest.mark.slow  # ~23 s: captures a real profiler trace
def test_roofline_trace_summarizes_device_ops(tmp_path):
    # The profiler-trace pipeline: capture a real jax.profiler
    # perfetto trace of a short fit and aggregate per-op device time.
    # The op names differ per backend (CPU fusions here, TensorCore
    # ops on TPU) but the pipeline and the JSON summary contract are
    # identical.
    out = run_example("roofline_trace.py", "--nsteps", "20",
                      "--log-dir", str(tmp_path / "trace"),
                      timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    import json as _json
    summary = _json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["backend"] == "cpu"
    ops = summary["smf_1e6"]["top_ops"]
    assert ops and summary["smf_1e6"]["per_step_us"] > 0
    # the erf kernel's backward exp shows up as a real device op
    assert any("exponential" in o["op"] or "erf" in o["op"]
               for o in ops), ops


@pytest.mark.slow
def test_smf_posterior_pipeline(tmp_path):
    # The inference-subsystem demo: multi-start ensemble -> Fisher /
    # Laplace -> 4-chain in-graph HMC with corner stats, small enough
    # for the CPU mesh.  The script itself asserts convergence
    # (R-hat < 1.05, truth inside the posterior) before SUCCESS.
    # `slow`: the tier-1 budget is a hard 870 s and this whole
    # pipeline already runs per-push as its own CI smoke step
    # (tests.yml), so the in-suite copy is for unfiltered local runs.
    png = str(tmp_path / "corner.png")
    out = run_example("smf_posterior.py", "--num-halos", "6000",
                      "--num-starts", "3", "--fit-steps", "80",
                      "--num-samples", "120", "--num-warmup", "80",
                      "--plot", png, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Laplace (Fisher) 1-sigma" in out.stdout
    assert "corner stats" in out.stdout
    assert "SUCCESS" in out.stdout
    import os
    assert os.path.exists(png)


@pytest.mark.slow
def test_fit_service_demo(tmp_path):
    # The serving-layer demo: compile-cache warmup, a bucketed burst
    # with one NaN poison request, per-request fit_summary records,
    # and a real-HTTP /metrics self-scrape.  `slow`: it already runs
    # per-push as its own CI smoke step (tests.yml), and the tier-1
    # coverage lives in tests/test_serve.py; the in-suite copy is
    # for unfiltered local runs.
    out = run_example("fit_service_demo.py",
                      "--requests", "6", "--nsteps", "40",
                      "--num-halos", "3000",
                      "--telemetry", str(tmp_path / "serve.jsonl"),
                      "--dump-dir", str(tmp_path / "postmortems"),
                      "--metrics-out", str(tmp_path / "metrics.prom"),
                      "--compile-cache", str(tmp_path / "cc"),
                      timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SERVE OK" in out.stdout
    assert "POSTMORTEM" in out.stdout
    assert (tmp_path / "metrics.prom").exists()
    assert (tmp_path / "serve.jsonl").exists()


@pytest.mark.slow
def test_sharded_ensemble_demo():
    # The sharded-K demo: replicated-vs-sharded agreement (bitwise on
    # the exact model), the partitioned trajectory, and the R x
    # max-K headline run for real.  `slow`: it runs per-push as its
    # own CI smoke step (tests.yml), and the tier-1 coverage lives in
    # tests/test_sharded_k.py; the in-suite copy is for unfiltered
    # local runs.
    out = run_example("sharded_ensemble_demo.py",
                      "--num-halos", "4000", "--n-starts", "8",
                      "--nsteps", "15", timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SHARD OK" in out.stdout


@pytest.mark.slow
def test_fleet_chaos_demo(tmp_path):
    # The fleet preemption demo: SIGKILL a worker mid-burst, every
    # future resolves on the survivors.  `slow`: it already runs
    # per-push as its own CI smoke step (tests.yml), and the tier-1
    # coverage lives in tests/test_fleet.py; the in-suite copy is
    # for unfiltered local runs.
    out = run_example("fleet_chaos_demo.py",
                      "--requests", "20", "--num-halos", "500",
                      "--nsteps", "200", "--kill-at-inflight", "10",
                      "--telemetry-dir", str(tmp_path / "fleet"),
                      timeout=600)
    assert out.returncode == 0, out.stderr[-2000:] + out.stdout[-2000:]
    assert "FLEET OK" in out.stdout
    assert "POSTMORTEM" in out.stdout
    assert (tmp_path / "fleet").is_dir()


def test_xi_likelihood_recovers_truth():
    # BASELINE config 3's example: sharded 3D 2pt-correlation
    # likelihood, BFGS over the 8-device ring.
    out = run_example("xi_likelihood.py", "--num-halos", "1024",
                      "--box-size", "60", timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Final solution OK" in out.stdout
