"""PR 7 hot-path optimizations: fused scatter-into-bins, optimizer
buffer donation, and backward-overlapped chunk prefetch.

Three invariants, one per front:

* the fused (windowed searchsorted + segment_sum) binned kernel equals
  the dense edge sweep — values AND gradients — at float32 tolerances,
  standalone and through the sharded SMF / galhalo-hist programs;
* donating the Adam carry changes nothing numerically (trajectories
  bitwise-equal on CPU, where donation is a checked no-op) at every
  entry point that grew the knob, and never causes a use-after-donate;
* the prefetcher's per-pass counters split the two streamed passes,
  and prefetch measurably beats the serial baseline when load and
  compute can overlap.
"""
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.galhalo_hist import (GalhaloHistModel, TRUTH,
                                               make_galhalo_hist_data)
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.ops.binned import (binned_erf_counts,
                                      fused_bin_window)

RNG = np.random.default_rng(42)


def _sample(n, sigma_scalar=True, lo=7.5, hi=11.0):
    vals = jnp.asarray(RNG.uniform(lo, hi, n).astype(np.float32))
    if sigma_scalar:
        return vals, 0.05
    sig = np.clip(RNG.normal(0.05, 0.01, n), 0.02, None)
    return vals, jnp.asarray(sig.astype(np.float32))


EDGES = jnp.linspace(7.0, 11.75, 34)


# --------------------------------------------------------------------- #
# Front 1: fused scatter-into-bins
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("scalar_sigma", [True, False])
def test_fused_counts_match_dense(scalar_sigma):
    vals, sigma = _sample(4096, scalar_sigma)
    window = fused_bin_window(EDGES, float(jnp.max(jnp.asarray(sigma))))
    assert 2 <= window < EDGES.shape[0]  # genuinely partial window

    dense = binned_erf_counts(vals, EDGES, sigma)
    fused = binned_erf_counts(vals, EDGES, sigma, bin_mode="fused",
                              bin_window=window)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                               rtol=1e-5, atol=1e-4)

    g = jnp.asarray(RNG.normal(size=EDGES.shape[0] - 1
                               ).astype(np.float32))

    def weighted(mode, w):
        def fn(v, e, s):
            return jnp.sum(g * binned_erf_counts(
                v, e, s, bin_mode=mode, bin_window=w))
        return fn

    gd = jax.grad(weighted("dense", None), argnums=(0, 1, 2))(
        vals, EDGES, sigma)
    gf = jax.grad(weighted("fused", window), argnums=(0, 1, 2))(
        vals, EDGES, sigma)
    for a, b in zip(gd, gf):
        scale = max(float(jnp.max(jnp.abs(a))), 1.0)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5 * scale)


def test_fused_full_window_and_chunked_match_dense():
    vals, sigma = _sample(3000)
    dense = binned_erf_counts(vals, EDGES, sigma)
    # window >= n_edges: fused degenerates to the dense result.
    full = binned_erf_counts(vals, EDGES, sigma, bin_mode="fused",
                             bin_window=int(EDGES.shape[0]) + 5)
    np.testing.assert_allclose(np.asarray(full), np.asarray(dense),
                               rtol=1e-5, atol=1e-4)
    # chunked fused path (ragged tail pads with +inf — must be inert).
    window = fused_bin_window(EDGES, 0.05)
    chunked = binned_erf_counts(vals, EDGES, sigma, chunk_size=777,
                                bin_mode="fused", bin_window=window)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5, atol=1e-4)


def test_fused_validation():
    vals, sigma = _sample(64)
    with pytest.raises(ValueError, match="bin_window"):
        binned_erf_counts(vals, EDGES, sigma, bin_mode="fused")
    with pytest.raises(ValueError, match="bin_mode"):
        binned_erf_counts(vals, EDGES, sigma, bin_mode="sparse")
    with pytest.raises(ValueError, match="strictly increasing"):
        fused_bin_window(np.array([1.0, 1.0, 2.0]), 0.1)
    assert fused_bin_window(EDGES, 100.0) == EDGES.shape[0]
    assert 2 <= fused_bin_window(EDGES, 1e-6) <= 3


@pytest.mark.slow  # ~24 s: compiles both fused-window variants
def test_fused_auto_backend_falls_back_on_oversized_window(monkeypatch):
    # "auto" must route around the pallas fused kernel's 128-slot
    # window cap (fall back to XLA) instead of surfacing its
    # precondition error — simulate a TPU resolution on CPU.
    import multigrad_tpu.ops.binned as binned_mod

    vals = jnp.asarray(RNG.uniform(0, 2, 512).astype(np.float32))
    edges = jnp.linspace(0, 2, 201)
    monkeypatch.setattr(
        binned_mod, "_resolve_backend",
        lambda x: "pallas" if x == "auto" else x)
    out = binned_erf_counts(vals, edges, 0.3, backend="auto",
                            bin_mode="fused", bin_window=201)
    ref = binned_mod._bin_sums_fused(vals, edges, jnp.float32(0.3), 201)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
    with pytest.raises(ValueError, match="window"):
        binned_erf_counts(vals, edges, 0.3, backend="pallas",
                          bin_mode="fused", bin_window=201)


@pytest.mark.parametrize("scalar_sigma", [True, False])
def test_fused_pallas_interpret_matches_dense(scalar_sigma):
    from multigrad_tpu.ops.pallas_kernels import \
        binned_erf_counts_fused_pallas

    vals, sigma = _sample(3000, scalar_sigma)
    window = fused_bin_window(EDGES, float(jnp.max(jnp.asarray(sigma))))
    dense = binned_erf_counts(vals, EDGES, sigma)
    fused = binned_erf_counts_fused_pallas(vals, EDGES, sigma, window,
                                           block_size=1024,
                                           interpret=True)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                               rtol=1e-5, atol=1e-4)

    g = jnp.asarray(RNG.normal(size=EDGES.shape[0] - 1
                               ).astype(np.float32))
    gd = jax.grad(lambda v, e, s: jnp.sum(
        g * binned_erf_counts(v, e, s)), argnums=(0, 1, 2))(
        vals, EDGES, sigma)
    gp = jax.grad(lambda v, e, s: jnp.sum(
        g * binned_erf_counts_fused_pallas(
            v, e, s, window, block_size=1024, interpret=True)),
        argnums=(0, 1, 2))(vals, EDGES, sigma)
    for a, b in zip(gd, gp):
        scale = max(float(jnp.max(jnp.abs(a))), 1.0)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5 * scale)


def test_smf_fused_sharded_matches_dense():
    comm = mgt.global_comm()
    window = fused_bin_window(np.linspace(9, 10, 11), 0.6)
    dense = SMFModel(aux_data=make_smf_data(4000, comm=comm),
                     comm=comm)
    fused = SMFModel(aux_data=make_smf_data(4000, comm=comm,
                                            bin_mode="fused",
                                            bin_window=window),
                     comm=comm)
    # Away from truth so the loss is O(0.1), not a ~0 residual whose
    # relative error is all summation noise.
    p = jnp.array([-1.8, 0.3])
    ld, gd = dense.calc_loss_and_grad_from_params(p)
    lf, gf = fused.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-4,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=1e-3, atol=1e-6)


def test_smf_fused_pallas_emulation_sharded_matches_dense():
    # backend="pallas" + bin_mode="fused" on a CPU mesh takes the
    # fused kernel's jnp-emulation path inside shard_map (the same
    # routing decision the dense pallas kernel makes) — it must agree
    # with the dense XLA programs through the model layer.
    comm = mgt.global_comm()
    window = fused_bin_window(np.linspace(9, 10, 11), 0.6)
    dense = SMFModel(aux_data=make_smf_data(2000, comm=comm),
                     comm=comm)
    fused = SMFModel(aux_data=make_smf_data(2000, comm=comm,
                                            backend="pallas",
                                            bin_mode="fused",
                                            bin_window=window),
                     comm=comm)
    p = jnp.array([-1.8, 0.3])
    ld, gd = dense.calc_loss_and_grad_from_params(p)
    lf, gf = fused.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=1e-3, atol=1e-5)


def test_galhalo_hist_fused_sharded_matches_dense():
    comm = mgt.global_comm()
    edges = np.linspace(7.0, 11.75, 41)
    base = make_galhalo_hist_data(3000, comm=comm, bin_edges=edges)
    dense = GalhaloHistModel(aux_data=base, comm=comm)
    fused = GalhaloHistModel(
        aux_data=dict(base, bin_mode="fused",
                      bin_window=fused_bin_window(edges, 0.08)),
        comm=comm)
    # Tight-scatter parameter point: the fused window is genuinely
    # partial (~10 of 41 edges), the regime the kernel exists for.
    p = jnp.asarray(TRUTH).at[8].set(0.05).at[9].set(-0.005)
    ss_d = np.asarray(dense.calc_sumstats_from_params(p))
    ss_f = np.asarray(fused.calc_sumstats_from_params(p))
    np.testing.assert_allclose(ss_f, ss_d, rtol=2e-4,
                               atol=1e-6 * ss_d.max())
    ld, gd = dense.calc_loss_and_grad_from_params(p)
    lf, gf = fused.calc_loss_and_grad_from_params(p)
    # log10 of near-empty tail bins amplifies summation-order jitter;
    # same tolerance band as the existing shard-invariance tests.
    np.testing.assert_allclose(float(lf), float(ld), rtol=2e-3)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=5e-3, atol=1e-4)


def test_streamed_fused_matches_resident():
    # The sharded shard_map chunk programs with the fused kernel: the
    # streamed two-pass loss/grad must reproduce the resident fused
    # model exactly (additivity is bin-mode-independent).
    comm = mgt.global_comm()
    window = fused_bin_window(np.linspace(9, 10, 11), 0.6)
    aux = make_smf_data(6000, comm=None, bin_mode="fused",
                        bin_window=window)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    sm = mgt.StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm),
        streams={"log_halo_masses": log_mh}, chunk_rows=1600)
    resident = SMFModel(
        aux_data=dict(aux, log_halo_masses=jnp.asarray(log_mh)),
        comm=None)
    p = jnp.array([-1.8, 0.3])
    ls, gs = sm.calc_loss_and_grad_from_params(p)
    lr, gr = resident.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(ls), float(lr), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                               rtol=1e-4, atol=1e-7)


# --------------------------------------------------------------------- #
# Front 2: donation + remat policy
# --------------------------------------------------------------------- #
def _quad(params, key, target):
    d = params - target
    return jnp.sum(d * d), 2 * d


def test_donated_scan_trajectory_identical():
    from multigrad_tpu.optim.adam import run_adam_scan

    target = jnp.array([1.0, -2.0, 0.5])
    guess = jnp.zeros(3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # CPU: donation no-op warning
        off = run_adam_scan(_quad, guess, nsteps=40, fn_args=(target,),
                            donate_carry=False)
        on = run_adam_scan(_quad, guess, nsteps=40, fn_args=(target,),
                           donate_carry=True)
        # The caller's guess array must survive donation (defensive
        # copy) — and a (K, ndim) batched carry donates the same way.
        assert np.all(np.asarray(guess) == 0.0)
        batch = jnp.zeros((4, 3))
        b_off = run_adam_scan(_quad, batch, nsteps=25,
                              fn_args=(target,), donate_carry=False)
        b_on = run_adam_scan(_quad, batch, nsteps=25,
                             fn_args=(target,), donate_carry=True)
    assert np.array_equal(np.asarray(on), np.asarray(off))
    assert np.array_equal(np.asarray(b_on), np.asarray(b_off))


def test_donated_model_fit_and_bounded_path():
    model = SMFModel(aux_data=make_smf_data(2000), comm=None)
    guess = jnp.array([-1.5, 0.4])
    bounds = [(-4.0, 0.0), (0.05, 1.0)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t_off = model.run_adam(guess=guess, nsteps=30,
                               param_bounds=bounds, progress=False,
                               donate_carry=False)
        t_on = model.run_adam(guess=guess, nsteps=30,
                              param_bounds=bounds, progress=False,
                              donate_carry=True)
    assert np.array_equal(np.asarray(t_on), np.asarray(t_off))
    assert np.isfinite(np.asarray(t_on)).all()


def test_donate_joins_segment_program_cache_key():
    # Toggling donation must compile a SIBLING program, never retrace
    # or repurpose the other variant's executable.
    from multigrad_tpu.optim.adam import _adam_segment_program

    def fn(u, key):
        return jnp.sum(u * u), 2 * u

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        p_off = _adam_segment_program(fn, 5, 0.01, False, False, False,
                                      donate=False)
        p_on = _adam_segment_program(fn, 5, 0.01, False, False, False,
                                     donate=True)
        p_off2 = _adam_segment_program(fn, 5, 0.01, False, False,
                                       False, donate=False)
    assert p_off is p_off2
    assert p_on is not p_off


def test_streamed_loop_donated_matches():
    comm = mgt.global_comm()
    aux = make_smf_data(4000, comm=None)
    log_mh = np.asarray(aux.pop("log_halo_masses"))

    def fit(donate):
        sm = mgt.StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux), comm=comm),
            streams={"log_halo_masses": log_mh}, chunk_rows=1024)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return np.asarray(sm.run_adam(
                guess=jnp.array([-1.5, 0.4]), nsteps=6,
                progress=False, donate_carry=donate))

    assert np.array_equal(fit(True), fit(False))


def test_remat_policy_variants_match_and_validate():
    from multigrad_tpu.core.model import resolve_remat_policy

    comm = mgt.global_comm()
    aux = make_smf_data(4000, comm=None)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    p = jnp.array([-2.0, 0.2])
    results = {}
    for policy in (None, "dots", "everything"):
        sm = mgt.StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux), comm=comm),
            streams={"log_halo_masses": log_mh}, chunk_rows=1024,
            remat_policy=policy)
        results[policy] = sm.calc_loss_and_grad_scan(p)
    l0, g0 = results["dots"]
    for policy, (loss, grad) in results.items():
        np.testing.assert_allclose(float(loss), float(l0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(g0),
                                   rtol=1e-5, atol=1e-8)
    assert resolve_remat_policy(None) is None
    assert resolve_remat_policy("nothing") is None
    assert callable(resolve_remat_policy("dots"))
    custom = jax.checkpoint_policies.everything_saveable
    assert resolve_remat_policy(custom) is custom
    with pytest.raises(ValueError, match="remat_policy"):
        resolve_remat_policy("bogus")


def test_remat_policy_is_a_distinct_cached_program():
    model = SMFModel(aux_data=make_smf_data(1000), comm=None)
    names = ("log_halo_masses",)
    a = model.chunk_scan_loss_and_grad_fn(names, remat_policy="dots")
    b = model.chunk_scan_loss_and_grad_fn(names, remat_policy=None)
    a2 = model.chunk_scan_loss_and_grad_fn(names, remat_policy="dots")
    assert a is a2
    assert a is not b


# --------------------------------------------------------------------- #
# Front 3: backward-overlapped prefetch + per-pass counters
# --------------------------------------------------------------------- #
def test_streamed_two_pass_counters_split_per_pass():
    comm = mgt.global_comm()
    aux = make_smf_data(4000, comm=None)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    sm = mgt.StreamingOnePointModel(
        model=SMFModel(aux_data=dict(aux), comm=comm),
        streams={"log_halo_masses": log_mh}, chunk_rows=1024)
    sm.calc_loss_and_grad_from_params(jnp.array([-2.0, 0.2]))
    stats = sm.last_stats
    n_chunks = sm.plan().n_chunks
    per = stats.pass_summary()
    assert set(per) == {"sumstats", "vjp"}
    for name in ("sumstats", "vjp"):
        assert per[name]["chunks"] == n_chunks
        assert 0.0 <= per[name]["overlap_frac"] <= 1.0
    assert stats.chunks == 2 * n_chunks
    summary = stats.summary()
    assert summary["passes"] == per
    assert "overlap_frac" in summary
    assert summary["max_live_buffers"] <= 2


def test_prefetch_overlap_beats_serial_stall():
    from multigrad_tpu.data.prefetch import prefetch_chunks
    from multigrad_tpu.utils.profiling import StreamStats

    n_chunks, load_s, compute_s = 6, 0.015, 0.02

    def load(_k):
        time.sleep(load_s)
        return np.zeros(16, np.float32)

    def consume(prefetch):
        stats = StreamStats()
        for _k, _chunk in prefetch_chunks(load, n_chunks,
                                          prefetch=prefetch,
                                          stats=stats, pass_name="p"):
            time.sleep(compute_s)  # stand-in for synchronous compute
        return stats

    serial = consume(False)
    overlapped = consume(True)
    # Serial pays every load in-line (recorded as stall after chunk
    # 0); with the loader running behind a slower consumer the stalls
    # must collapse.
    assert serial.stall_s > 0.8 * (n_chunks - 1) * load_s
    assert overlapped.stall_s < 0.5 * serial.stall_s
    assert overlapped.overlap_fraction > serial.overlap_fraction
    assert overlapped.passes["p"]["chunks"] == n_chunks


def test_fit_summary_reports_overlap_fraction():
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger

    aux = make_smf_data(3000, comm=None)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    sm = mgt.StreamingOnePointModel(
        model=SMFModel(aux_data=dict(aux), comm=None),
        streams={"log_halo_masses": log_mh}, chunk_rows=1024)
    sink = MemorySink()
    telemetry = MetricsLogger(sink)
    sm.run_adam(guess=jnp.array([-1.5, 0.4]), nsteps=3,
                progress=False, telemetry=telemetry, log_every=1)
    telemetry.close()
    summaries = [r for r in sink.records
                 if r.get("event") == "fit_summary"]
    assert len(summaries) == 1
    rec = summaries[0]
    assert 0.0 <= rec["overlap_frac"] <= 1.0
    assert set(rec["pass_overlap"]) == {"sumstats", "vjp"}


# --------------------------------------------------------------------- #
# Shard-safety: the analyzer covers every new program variant
# --------------------------------------------------------------------- #
def test_assert_clean_on_new_hot_paths():
    comm = mgt.global_comm()
    p = jnp.array([-2.0, 0.2])
    window = fused_bin_window(np.linspace(9, 10, 11), 0.6)
    fused = SMFModel(aux_data=make_smf_data(800, comm=comm,
                                            bin_mode="fused",
                                            bin_window=window),
                     comm=comm)
    mgt.assert_clean(fused, p, kinds=("loss_and_grad",))

    aux = make_smf_data(800, comm=None, bin_mode="fused",
                        bin_window=window)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    for policy in ("dots", None):
        sm = mgt.StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux), comm=comm),
            streams={"log_halo_masses": log_mh},
            chunk_rows=max(comm.size, 200), remat_policy=policy)
        mgt.assert_clean(sm, p)

    # The donated whole-fit scan traces identically (donation is an
    # executable attribute, not a jaxpr change) — the analyzer must
    # stay clean through the donate-keyed program cache.
    from multigrad_tpu.analysis import analyze_fit

    dense = SMFModel(aux_data=make_smf_data(800, comm=comm), comm=comm)
    assert analyze_fit(dense, p, nsteps=2) == []
