"""Pallas TPU kernel parity tests (interpret mode on CPU).

The hand-written kernels in :mod:`multigrad_tpu.ops.pallas_kernels`
must match their XLA counterparts — forward values AND analytic-VJP
gradients — since either backend can sit inside the framework's fused
SPMD loss-and-grad program.  Off-TPU the kernels auto-select Pallas
interpret mode, so the same code paths run here (conftest pins the
CPU platform) and compiled on real chips.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.ops.binned import binned_erf_counts
from multigrad_tpu.ops.pairwise import _block_counts, \
    ring_weighted_pair_counts
from multigrad_tpu.ops.pallas_kernels import (binned_erf_counts_pallas,
                                              pair_counts_pallas)

EDGES = jnp.linspace(9, 10, 11)


def _halo_sample(n, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(9.5, 0.4, size=n), jnp.float32)


@pytest.mark.parametrize("n", [1024, 3333])
def test_erf_counts_forward_matches_xla(n):
    vals = _halo_sample(n)
    ref = binned_erf_counts(vals, EDGES, 0.2)
    pal = binned_erf_counts_pallas(vals, EDGES, 0.2, block_size=1024)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)


def test_erf_counts_gradients_match_xla():
    vals = _halo_sample(4000)
    sigma = jnp.float32(0.2)
    cot = jnp.arange(10.0)

    def loss(fn):
        return lambda v, e, s: jnp.sum(fn(v, e, s) * cot)

    g_ref = jax.grad(loss(lambda v, e, s: binned_erf_counts(v, e, s)),
                     argnums=(0, 1, 2))(vals, EDGES, sigma)
    g_pal = jax.grad(loss(lambda v, e, s: binned_erf_counts_pallas(
        v, e, s, block_size=1024)), argnums=(0, 1, 2))(vals, EDGES, sigma)
    for ref, pal in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   rtol=1e-3, atol=1e-5)


def test_erf_counts_jit_and_vmap_compose():
    vals = _halo_sample(2048)
    f = jax.jit(lambda s: binned_erf_counts_pallas(vals, EDGES, s,
                                                   block_size=1024))
    np.testing.assert_allclose(
        np.asarray(f(jnp.float32(0.2))),
        np.asarray(binned_erf_counts(vals, EDGES, 0.2)), rtol=2e-5)
    sigmas = jnp.array([0.15, 0.2, 0.3], jnp.float32)
    batched = jax.vmap(f)(sigmas)
    for i, s in enumerate(np.asarray(sigmas)):
        np.testing.assert_allclose(
            np.asarray(batched[i]),
            np.asarray(binned_erf_counts(vals, EDGES, float(s))),
            rtol=2e-5)


def test_erf_counts_inf_padding_neutral_grads():
    """inf-padded particles (the framework's shard padding) must be
    neutral in forward AND backward passes — no 0·inf NaNs in the
    analytic dsigma/dvalues (regression: unclipped z gave NaN)."""
    vals = jnp.concatenate([_halo_sample(1000), jnp.full(24, jnp.inf)])
    ref = binned_erf_counts(vals[:1000], EDGES, 0.2)
    pal = binned_erf_counts_pallas(vals, EDGES, 0.2, block_size=1024)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)
    g = jax.grad(lambda v, s: jnp.sum(binned_erf_counts_pallas(
        v, EDGES, s, block_size=1024)), argnums=(0, 1))(
        vals, jnp.float32(0.2))
    assert np.all(np.isfinite(np.asarray(g[0])))
    assert np.isfinite(float(g[1]))
    np.testing.assert_allclose(np.asarray(g[0][1000:]), 0.0)


def test_erf_counts_rejects_bad_args():
    vals = _halo_sample(256)
    with pytest.raises(ValueError, match="match values"):
        binned_erf_counts_pallas(vals, EDGES, jnp.full(100, 0.2))
    with pytest.raises(ValueError, match="multiple"):
        binned_erf_counts_pallas(vals, EDGES, 0.2, block_size=1000)


# --------------------------------------------------------------------------
# Per-particle sigma (mass-dependent scatter) kernel path
# --------------------------------------------------------------------------


def _vec_sigma(n, seed=5):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(0.1, 0.4, size=n), jnp.float32)


@pytest.mark.parametrize("n", [1024, 3333])
def test_erf_counts_vec_sigma_forward_matches_xla(n):
    vals = _halo_sample(n)
    sigmas = _vec_sigma(n)
    ref = binned_erf_counts(vals, EDGES, sigmas, backend="xla")
    pal = binned_erf_counts_pallas(vals, EDGES, sigmas, block_size=1024)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)


def test_erf_counts_vec_sigma_gradients_match_xla():
    vals = _halo_sample(4000)
    sigmas = _vec_sigma(4000)
    cot = jnp.arange(10.0)

    def loss(fn):
        return lambda v, e, s: jnp.sum(fn(v, e, s) * cot)

    g_ref = jax.grad(loss(lambda v, e, s: binned_erf_counts(
        v, e, s, backend="xla")), argnums=(0, 1, 2))(vals, EDGES, sigmas)
    g_pal = jax.grad(loss(lambda v, e, s: binned_erf_counts_pallas(
        v, e, s, block_size=1024)), argnums=(0, 1, 2))(
        vals, EDGES, sigmas)
    for ref, pal in zip(g_ref, g_pal):
        assert np.shape(pal) == np.shape(ref)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   rtol=1e-3, atol=1e-5)


def test_erf_counts_vec_sigma_padding_neutral():
    # inf-padded particles with arbitrary pad sigmas must be neutral
    # in forward and backward (the shard/chunk padding contract).
    vals = jnp.concatenate([_halo_sample(1000), jnp.full(24, jnp.inf)])
    sigmas = jnp.concatenate([_vec_sigma(1000), jnp.full(24, 0.3)])
    ref = binned_erf_counts(vals[:1000], EDGES, sigmas[:1000],
                            backend="xla")
    pal = binned_erf_counts_pallas(vals, EDGES, sigmas, block_size=1024)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)
    gv, gs = jax.grad(lambda v, s: jnp.sum(binned_erf_counts_pallas(
        v, EDGES, s, block_size=1024)), argnums=(0, 1))(vals, sigmas)
    assert np.all(np.isfinite(np.asarray(gv)))
    assert np.all(np.isfinite(np.asarray(gs)))
    np.testing.assert_allclose(np.asarray(gv[1000:]), 0.0)
    np.testing.assert_allclose(np.asarray(gs[1000:]), 0.0)


def test_vec_sigma_dispatch_routes_to_kernel():
    # Per-particle sigma is now inside the pallas envelope: the
    # dispatch layer must route an explicit backend="pallas" call to
    # the kernel (interpret mode off-TPU — on CPU "auto" resolves to
    # XLA, so the explicit backend is what exercises the routing).
    vals = _halo_sample(2048)
    sigmas = _vec_sigma(2048)
    xla = binned_erf_counts(vals, EDGES, sigmas, backend="xla")
    pal = binned_erf_counts(vals, EDGES, sigmas, backend="pallas")
    np.testing.assert_allclose(np.asarray(pal), np.asarray(xla),
                               rtol=2e-5, atol=1e-5)


def test_chunked_ragged_tail_matches_unchunked():
    # chunk_size need not divide N: the XLA chunked path pads the
    # ragged tail with inf (exactly neutral), matching the unchunked
    # result — forward and gradients, scalar and per-particle sigma.
    vals = _halo_sample(3_333)
    sigmas = _vec_sigma(3_333)
    for sig in (jnp.float32(0.2), sigmas):
        full = binned_erf_counts(vals, EDGES, sig, backend="xla")
        chunked = binned_erf_counts(vals, EDGES, sig, chunk_size=1_000,
                                    backend="xla")
        np.testing.assert_allclose(np.asarray(chunked),
                                   np.asarray(full), rtol=1e-5)
    g_full = jax.grad(lambda v: jnp.sum(binned_erf_counts(
        v, EDGES, sigmas, backend="xla")))(vals)
    g_chunk = jax.grad(lambda v: jnp.sum(binned_erf_counts(
        v, EDGES, sigmas, chunk_size=1_000, backend="xla")))(vals)
    np.testing.assert_allclose(np.asarray(g_chunk), np.asarray(g_full),
                               rtol=1e-4, atol=1e-7)


def test_pair_row_chunk_ragged_tail_matches():
    from multigrad_tpu.ops.pairwise import _block_counts_chunked

    pos, w = _mock_points(700, 50.0)
    redges = jnp.asarray(np.geomspace(0.5, 15, 9), jnp.float32)
    full = _block_counts(pos, w, pos, w, redges ** 2, 50.0, None)
    ragged = _block_counts_chunked(pos, w, pos, w, redges ** 2, 50.0,
                                   None, row_chunk=300)
    np.testing.assert_allclose(np.asarray(ragged), np.asarray(full),
                               rtol=1e-4)


def test_broadcastable_sigma_falls_back_to_xla(monkeypatch):
    # A broadcastable-but-not-(N,) sigma — e.g. shape (1,) — is
    # outside the kernel's tile layout; "auto" must fall back to XLA
    # (exercised by faking a TPU default so auto resolves to pallas),
    # while an explicit "pallas" raises the precondition error.
    from multigrad_tpu.ops import binned as binned_mod

    vals = _halo_sample(512)
    sig1 = jnp.full(1, 0.2, jnp.float32)
    ref = binned_erf_counts(vals, EDGES, sig1, backend="xla")
    monkeypatch.setattr(binned_mod, "_resolve_backend",
                        lambda b: "pallas" if b == "auto" else b)
    out = binned_mod.binned_erf_counts(vals, EDGES, sig1,
                                       backend="auto")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-5)
    with pytest.raises(ValueError, match="match values"):
        binned_mod.binned_erf_counts(vals, EDGES, sig1,
                                     backend="pallas")


def _mock_points(n, box, seed=1):
    rng = np.random.default_rng(seed)
    pos = jnp.asarray(rng.uniform(0, box, size=(n, 3)), jnp.float32)
    w = jnp.asarray(rng.uniform(0.2, 1.0, size=n), jnp.float32)
    return pos, w


@pytest.mark.parametrize("pimax", [None, 10.0])
def test_pair_counts_forward_matches_xla(pimax):
    pos, w = _mock_points(700, 50.0)
    redges = jnp.asarray(np.geomspace(0.5, 15, 9), jnp.float32)
    ref = _block_counts(pos, w, pos, w, redges ** 2, 50.0, pimax)
    pal = pair_counts_pallas(pos, w, pos, w, redges, box_size=50.0,
                             pimax=pimax, tile=256)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=1e-4)


def test_pair_counts_weight_gradients_match_xla():
    pos, w = _mock_points(500, 50.0)
    redges = jnp.asarray(np.geomspace(0.5, 15, 9), jnp.float32)
    cot = jnp.arange(8.0)

    g_pal = jax.grad(lambda w_: jnp.sum(pair_counts_pallas(
        pos, w_, pos, w_, redges, box_size=50.0, tile=256) * cot))(w)
    g_ref = jax.grad(lambda w_: jnp.sum(_block_counts(
        pos, w_, pos, w_, redges ** 2, 50.0, None) * cot))(w)
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


def test_pair_counts_asymmetric_blocks():
    pos1, w1 = _mock_points(300, 50.0, seed=2)
    pos2, w2 = _mock_points(450, 50.0, seed=3)
    redges = jnp.asarray(np.geomspace(0.5, 15, 6), jnp.float32)
    ref = _block_counts(pos1, w1, pos2, w2, redges ** 2, None, None)
    pal = pair_counts_pallas(pos1, w1, pos2, w2, redges, tile=128)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=1e-4)
    # grads flow to both sides
    g1, g2 = jax.grad(lambda a, b: jnp.sum(pair_counts_pallas(
        pos1, a, pos2, b, redges, tile=128)), argnums=(0, 1))(w1, w2)
    r1, r2 = jax.grad(lambda a, b: jnp.sum(_block_counts(
        pos1, a, pos2, b, redges ** 2, None, None)),
        argnums=(0, 1))(w1, w2)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(r1),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(r2),
                               rtol=1e-3, atol=1e-4)


def test_ring_pair_counts_pallas_backend():
    """The ring-sharded op with backend='pallas' totals the same DD."""
    pos, w = _mock_points(512, 60.0, seed=4)
    redges = jnp.asarray(np.geomspace(1.0, 20, 7), jnp.float32)
    single = ring_weighted_pair_counts(pos, w, redges, box_size=60.0,
                                       backend="pallas")
    ref = ring_weighted_pair_counts(pos, w, redges, box_size=60.0)
    np.testing.assert_allclose(np.asarray(single), np.asarray(ref),
                               rtol=1e-4)


def test_smf_model_pallas_backend_end_to_end():
    """SMF pipeline with the Pallas sumstats kernel: golden parity +
    fused loss-and-grad consistency (test_mpi.py:44-66 analogues)."""
    from multigrad_tpu.models.smf import (SMFModel, TARGET_SUMSTATS,
                                          ParamTuple, make_smf_data)
    comm = mgt.MeshComm(jax.devices()[:4], axis_name="data")
    model = SMFModel(aux_data=make_smf_data(10_000, comm=comm,
                                            backend="pallas"),
                     comm=comm)
    truth = ParamTuple(-2.0, 0.2)
    ss = model.calc_sumstats_from_params(truth)
    np.testing.assert_allclose(np.asarray(ss), TARGET_SUMSTATS,
                               rtol=1e-4)
    loss, grad = model.calc_loss_and_grad_from_params(truth)
    assert float(loss) < 1e-8
    # CPU interpret mode evaluates erf with libm while the kernel uses
    # XLA's f32 polynomial; at the loss minimum the last-ulp mismatch
    # surfaces as a ~1e-4 gradient residue.
    np.testing.assert_allclose(np.asarray(grad), 0.0, atol=5e-4)

    xla_model = SMFModel(aux_data=make_smf_data(10_000, comm=comm),
                         comm=comm)
    l2, g2 = xla_model.calc_loss_and_grad_from_params(
        ParamTuple(-1.8, 0.3))
    l1, g1 = model.calc_loss_and_grad_from_params(ParamTuple(-1.8, 0.3))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-5)


def test_auto_backend_falls_back_outside_pallas_envelope():
    # "auto" is pick-what-works: per-particle sigma and >128 edges are
    # outside the pallas kernel's envelope and must route to XLA
    # rather than surfacing the kernel's precondition error.  (On CPU
    # auto is already XLA; the check is that these calls simply work.)
    import numpy as np
    from multigrad_tpu.ops.binned import binned_erf_counts
    from multigrad_tpu.ops.pairwise import ring_weighted_pair_counts

    vals = jnp.linspace(9.0, 10.0, 256)
    sigmas = jnp.full(256, 0.05)                  # per-particle sigma
    edges = jnp.linspace(9, 10, 11)
    out = binned_erf_counts(vals, edges, sigmas, backend="auto")
    assert out.shape == (10,)

    many_edges = jnp.linspace(9, 10, 200)         # >128 edges
    out = binned_erf_counts(vals, many_edges, 0.05, backend="auto")
    assert out.shape == (199,)

    pos = jnp.zeros((64, 3)).at[:, 0].set(jnp.linspace(0, 10, 64))
    w = jnp.ones(64)
    many_bins = jnp.linspace(0.1, 5.0, 140)       # >128 bins
    out = ring_weighted_pair_counts(pos, w, many_bins, backend="auto")
    assert out.shape == (139,)
    # Explicit "pallas" outside the envelope still raises.
    with pytest.raises(ValueError, match="128"):
        binned_erf_counts(vals, many_edges, 0.05, backend="pallas")
