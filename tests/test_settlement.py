"""Settlement analysis (multigrad_tpu/analysis/settlement.py).

The acceptance contract of the settlement pass:

* the shipped tree reports ZERO unexplained findings — every real
  root-after-resolve / missing-backstop / unguarded-setter hazard
  the pass surfaced was FIXED in this PR (not allowlisted), so a
  finding here is a regression;
* every check id flags its seeded fixture shape at the documented
  line — the PR-13 root-after-resolve race, the PR-16 unrecorded
  stage death, the unguarded double-settle, the orphaned future;
* ``# settle-ok:`` annotations are verified, not trusted: unknown
  check ids and missing justifications are ERRORs, a valid
  suppression is consumed without a stale warning;
* the fixed shipped code KEEPS its ordering guarantees — each fix
  carries a deterministic probe that snapshots the counters at the
  instant the future settles (no racing threads needed).
"""
import time

import pytest

from multigrad_tpu.analysis.findings import ERROR, WARNING
from multigrad_tpu.analysis.settlement import (SETTLE_CHECK_IDS,
                                               analyze_settlement,
                                               scan_settlement)
from multigrad_tpu.serve import (FitScheduler, FleetRouter,
                                 FleetSaturatedError, WorkerLostError)
from multigrad_tpu.serve.fleet import WorkerHandle
from multigrad_tpu.serve.jobs import (Job, JobFuture, JobRunner,
                                      JobResult)
from multigrad_tpu.serve.queue import (FitCancelled,
                                       FitDeadlineExceeded,
                                       FitFailed)
from multigrad_tpu.serve.stages import Stage

import os
from dataclasses import dataclass

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "settlement")


# ------------------------------------------------------------------ #
# shipped tree
# ------------------------------------------------------------------ #
def test_shipped_tree_zero_unexplained_findings():
    findings = analyze_settlement()
    assert findings == [], (
        "unexplained settlement findings on the shipped tree:\n"
        + "\n".join(f"  [{f.check}] {f.where}: {f.message}"
                    for f in findings))


def test_settle_check_registry_is_stable():
    # The registry is API: lint --checks validates against it and
    # settle-ok annotations name ids out of it.
    assert SETTLE_CHECK_IDS == (
        "settle-orphan", "settle-no-backstop",
        "settle-root-after-resolve", "settle-under-lock",
        "settle-double", "settle-first-wins", "settle-allowlist")


# ------------------------------------------------------------------ #
# seeded fixtures: each check flags its intended shape
# ------------------------------------------------------------------ #
@pytest.fixture(scope="module")
def fixture_findings():
    return analyze_settlement(root=FIXTURES)


def _by_check(findings, check):
    return [f for f in findings if f.check == check]


def test_fixture_root_after_resolve(fixture_findings):
    hits = _by_check(fixture_findings, "settle-root-after-resolve")
    wheres = sorted(f.where for f in hits)
    assert len(hits) == 2, wheres
    # Both late-accounting lines in settle_ok are named.
    assert any("root_after_resolve.py:61" in w for w in wheres)
    assert any("root_after_resolve.py:62" in w for w in wheres)
    assert all("settle_ok" in w for w in wheres)
    assert all(f.severity == ERROR for f in hits)


def test_fixture_settle_under_lock(fixture_findings):
    hits = _by_check(fixture_findings, "settle-under-lock")
    assert len(hits) == 1, [f.where for f in hits]
    assert "root_after_resolve.py:66" in hits[0].where
    # The annotated twin (allowed_under_lock, line 70) is suppressed.
    assert not any("root_after_resolve.py:70" in f.where
                   for f in fixture_findings)


def test_fixture_settle_double(fixture_findings):
    hits = _by_check(fixture_findings, "settle-double")
    assert len(hits) == 1, [f.where for f in hits]
    assert "root_after_resolve.py:74" in hits[0].where
    assert "settle_twice" in hits[0].where


def test_fixture_settle_orphan(fixture_findings):
    hits = _by_check(fixture_findings, "settle-orphan")
    assert len(hits) == 1, [f.where for f in hits]
    assert "root_after_resolve.py:77" in hits[0].where
    assert "fut" in hits[0].message


def test_fixture_first_wins(fixture_findings):
    hits = _by_check(fixture_findings, "settle-first-wins")
    wheres = sorted(f.where for f in hits)
    # Both terminal setters of UnguardedFuture lack the guard.
    assert len(hits) == 2, wheres
    assert any("root_after_resolve.py:36" in w for w in wheres)
    assert any("root_after_resolve.py:41" in w for w in wheres)


def test_fixture_no_backstop(fixture_findings):
    hits = _by_check(fixture_findings, "settle-no-backstop")
    assert len(hits) == 1, [f.where for f in hits]
    # The PR-16 shape: the stage worker thread's body resolves a
    # future but has no broad exception backstop.
    assert "stage_death.py:24" in hits[0].where
    assert "_run_stage" in hits[0].where


def test_fixture_allowlist_verification(fixture_findings):
    hits = _by_check(fixture_findings, "settle-allowlist")
    assert len(hits) == 2, [(f.where, f.message) for f in hits]
    by_line = {f.where: f for f in hits}
    unknown = next(f for f in hits
                   if "root_after_resolve.py:80" in f.where)
    assert "not-a-real-check" in unknown.message
    assert unknown.severity == ERROR
    no_reason = next(f for f in hits
                     if "root_after_resolve.py:81" in f.where)
    assert no_reason.severity == ERROR
    # The valid suppression was CONSUMED: no stale warning.
    assert not any(f.severity == WARNING for f in hits), by_line


def test_fixture_total_finding_count(fixture_findings):
    # The fixture battery is exactly its documented 10 findings — a
    # new unexplained finding (or a lost one) is a pass regression.
    assert len(fixture_findings) == 10, sorted(
        (f.check, f.where) for f in fixture_findings)


def test_checks_subsetting():
    only_double = analyze_settlement(root=FIXTURES,
                                     checks=("settle-double",))
    assert {f.check for f in only_double} == {"settle-double"}
    # Allowlist verification only rides along when selected.
    no_allow = analyze_settlement(
        root=FIXTURES, checks=("settle-orphan",))
    assert {f.check for f in no_allow} == {"settle-orphan"}


def test_scan_model_reuse():
    # One scan, many analyses: the model= hook avoids re-parsing.
    model = scan_settlement(root=FIXTURES)
    a = analyze_settlement(root=FIXTURES, model=model)
    b = analyze_settlement(root=FIXTURES)
    assert sorted((f.check, f.where) for f in a) \
        == sorted((f.check, f.where) for f in b)


# ------------------------------------------------------------------ #
# lint CLI integration
# ------------------------------------------------------------------ #
def test_lint_cli_settlement_target(capsys):
    from multigrad_tpu.analysis.lint import main
    rc = main(["--targets", "settlement"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[settlement] clean" in out


def test_lint_cli_settlement_checks_subset(capsys):
    from multigrad_tpu.analysis.lint import main
    import json
    rc = main(["--json", "--checks",
               "settle-first-wins,settle-double"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["clean"] is True
    assert payload["findings"] == []


# ------------------------------------------------------------------ #
# regression probes for the shipped fixes
#
# Each fix moved accounting (trace root / counters) BEFORE the
# future's resolve, or added a backstop / first-wins guard.  The
# probe wraps the future's terminal setter to snapshot the counter
# AT THE INSTANT of settlement — count-before-resolve becomes a
# deterministic assertion, no thread race required.
# ------------------------------------------------------------------ #
class _StubModel:
    """Just enough model for a never-dispatching FitScheduler."""
    k_shard_axis = None

    def aux_leaves(self):
        return ()


def _probe(fut, snapshot):
    """Wrap fut's terminal setters; record snapshot() at settle."""
    taken = {}
    for name in ("_set_result", "_set_exception"):
        orig = getattr(fut, name)

        def wrapped(value, _orig=orig):
            taken.setdefault("at_settle", snapshot())
            return _orig(value)

        setattr(fut, name, wrapped)
    return taken


@pytest.fixture()
def stub_sched():
    sched = FitScheduler(_StubModel(), buckets=(4,), start=False,
                         batch_window_s=0.0,
                         monitor_resources=False)
    yield sched
    sched.close(drain=False)


def test_scheduler_close_counts_before_cancel(stub_sched):
    fut = stub_sched.submit([0.1, 0.2], nsteps=5)
    taken = _probe(
        fut, lambda: stub_sched.stats.get("cancelled", 0))
    stub_sched.close(drain=False)
    # The woken caller must already see the cancellation counted.
    assert taken["at_settle"] == 1
    with pytest.raises(FitCancelled):
        fut.result(timeout=1)


def test_fail_group_counts_before_resolve(stub_sched):
    fut = stub_sched.submit([0.1, 0.2], nsteps=5)
    stub_sched.queue.close()
    reqs = stub_sched.queue.drain_pending()
    assert [r.future for r in reqs] == [fut]
    taken = _probe(fut, lambda: stub_sched.stats.get("failed", 0))
    stub_sched._fail_group(reqs, RuntimeError("boom"), "test")
    assert taken["at_settle"] == 1
    with pytest.raises(FitFailed) as err:
        fut.result(timeout=1)
    assert isinstance(err.value.__cause__, RuntimeError)


class _FakeChan:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass


@pytest.fixture()
def fake_fleet(tmp_path):
    router = FleetRouter(n_workers=0, base_dir=str(tmp_path),
                         compile_cache=None,
                         heartbeat_timeout_s=1e6, max_requeues=2)
    handle = WorkerHandle("w0", chan=_FakeChan())
    router.workers.append(handle)
    yield router, handle
    router.close(drain=False, timeout=0)


def test_on_error_counts_before_resolve(fake_fleet):
    router, handle = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    taken = _probe(fut, lambda: router.stats.get("failed", 0))
    router._on_error(handle, {"op": "error",
                              "rid": fut.request_id,
                              "etype": "RuntimeError",
                              "message": "boom"})
    assert taken["at_settle"] == 1
    with pytest.raises(RuntimeError, match="boom"):
        fut.result(timeout=1)


def test_on_reject_shed_counts_before_resolve(fake_fleet):
    router, handle = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    taken = _probe(fut, lambda: router.stats.get("shed", 0))
    # The only worker rejects: no reroute target -> typed shed.
    router._on_reject(handle, {"op": "reject",
                               "rid": fut.request_id,
                               "reason": "queue_full"})
    assert taken["at_settle"] == 1
    with pytest.raises(FleetSaturatedError):
        fut.result(timeout=1)


def test_requeue_expired_counts_before_resolve(fake_fleet):
    router, handle = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5, deadline_s=0.02)
    taken = _probe(fut, lambda: router.stats.get("expired", 0))
    time.sleep(0.05)
    router._worker_lost(handle, "test kill")
    assert taken["at_settle"] == 1
    with pytest.raises(FitDeadlineExceeded):
        fut.result(timeout=1)


def test_settle_lost_counts_before_resolve(fake_fleet):
    router, handle = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    req = router._requests[fut.request_id]
    taken = _probe(fut, lambda: router.stats.get("lost", 0))
    router._settle_lost(req, "test lost")
    assert taken["at_settle"] == 1
    with pytest.raises(WorkerLostError):
        fut.result(timeout=1)


def test_reader_backstop_disconnects_on_handler_crash(fake_fleet):
    router, _ = fake_fleet

    class _CrashChan:
        def __iter__(self):
            # queue_depth int() raises inside the heartbeat handler.
            yield {"op": "heartbeat", "queue_depth": "not-an-int"}

        def send(self, msg):
            pass

        def close(self):
            pass

    handle = WorkerHandle("w-crash", chan=_CrashChan())
    router.workers.append(handle)
    # The regression: a handler exception must NOT escape the reader
    # thread — the backstop logs it and the finally-disconnect still
    # writes the worker off (requeueing its inflight futures).
    router._reader(handle)
    assert handle.state == "dead"


def test_monitor_backstop_survives_tick_crash(tmp_path):
    router = FleetRouter(n_workers=0, base_dir=str(tmp_path),
                         compile_cache=None,
                         heartbeat_timeout_s=0.08, max_requeues=2)
    try:
        calls = []

        def crashing_tick():
            calls.append(1)
            if len(calls) >= 2:
                router._monitor_stop.set()
            raise RuntimeError("tick boom")

        router._monitor_tick = crashing_tick
        # The regression: one bad tick used to kill the monitor
        # thread, leaving every later worker loss undetected.  The
        # per-iteration backstop keeps the loop alive.
        router._monitor_loop()
        assert len(calls) >= 2
    finally:
        router._monitor_stop.set()
        router.close(drain=False, timeout=0)


def test_job_future_first_wins():
    fut = JobFuture("job-test")
    won = JobResult(job_id="job-test", ok=True, stages={},
                    elapsed_s=0.0)
    fut._set_result(won)
    # A late duplicate settle (the crash backstop racing the normal
    # completion path) must not clobber the delivered outcome.
    fut._set_exception(RuntimeError("late backstop"))
    fut._set_result(JobResult(job_id="job-test", ok=False,
                              stages={}, elapsed_s=1.0))
    assert fut.result(timeout=1) is won
    fut2 = JobFuture("job-test-2")
    err = RuntimeError("first")
    fut2._set_exception(err)
    fut2._set_result(won)
    assert fut2.exception(timeout=1) is err


@dataclass
class _BoomStage(Stage):
    def run(self, rt):
        raise RuntimeError("stage boom")


@dataclass
class _OkStage(Stage):
    def run(self, rt):
        return {}


def test_execute_dag_counts_skipped_before_settle():
    runner = JobRunner(backend=None)
    job = Job(stages=(_BoomStage("up"),
                      _OkStage("down", deps=("up",))))
    future = JobFuture(job.job_id)
    events = []
    runner._count_stage = \
        lambda job, outcome: events.append(("count", outcome))
    orig_settled = future._stage_settled

    def settled(result):
        events.append(("settled", result.name, result.outcome))
        return orig_settled(result)

    future._stage_settled = settled
    runner._execute_dag(job, future, None, {})
    # The skipped dependent is COUNTED before its future-side settle
    # (same order _run_stage_guarded uses for executed stages).
    skipped_count = events.index(("count", "skipped"))
    skipped_settle = events.index(("settled", "down", "skipped"))
    assert skipped_count < skipped_settle
    assert future.stage_results["down"].outcome == "skipped"
