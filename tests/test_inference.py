"""Inference subsystem: Fisher/Laplace + in-graph HMC + ensembles.

The pinned contracts (ISSUE 2 acceptance):

* the distributed Gauss–Newton Fisher matches a dense ``jax.hessian``
  of the loss at the MLE to rtol 1e-4 on an analytic Gaussian model
  (where Gauss–Newton IS the exact Hessian — sumstats linear in
  params);
* 4-chain in-graph HMC on that model recovers the known Gaussian
  posterior's mean and covariance within 3 Monte-Carlo standard
  errors, with split R-hat < 1.05;

both running under ``shard_map`` on the multi-device CPU mesh
(``tests/conftest.py``'s 8 virtual devices).
"""
import numpy as np
import pytest
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

import multigrad_tpu as mgt
from multigrad_tpu.core.model import OnePointModel
from multigrad_tpu.inference import (effective_sample_size,
                                     fisher_diagnostics,
                                     fisher_information,
                                     hmc_init_from_ensemble,
                                     laplace_covariance, run_hmc,
                                     run_multistart_adam,
                                     run_multistart_lbfgs, split_rhat,
                                     sumstats_jacobian)

N_ROWS, N_STATS, N_DIM = 64, 4, 3


@dataclass
class GaussianLinearModel(OnePointModel):
    """Sumstats linear in params, Gaussian loss: y = Σ_i x_i (u_iᵀ p),
    L = ½ (y-t)ᵀ P (y-t).  Posterior ∝ exp(-L) is exactly
    N(μ, (JᵀPJ)⁻¹) with J = Σ_i x_i u_iᵀ — every inference quantity
    has a closed form."""

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        x = jnp.asarray(self.aux_data["x"])
        u = jnp.asarray(self.aux_data["u"])
        return (x * (u @ params)[:, None]).sum(axis=0)

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        r = sumstats - jnp.asarray(self.aux_data["target"])
        return 0.5 * r @ jnp.asarray(self.aux_data["prec"]) @ r


def _problem():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N_ROWS, N_STATS)).astype(np.float32)
    u = rng.normal(size=(N_ROWS, N_DIM)).astype(np.float32)
    jac = x.T @ u
    prec = np.diag(rng.uniform(0.5, 2.0, N_STATS)).astype(np.float32)
    p_true = np.array([0.5, -0.3, 0.8], np.float32)
    target = (jac @ p_true).astype(np.float32)
    fisher = jac.T @ prec @ jac
    mle = np.linalg.solve(fisher, jac.T @ prec @ target)
    cov = np.linalg.inv(fisher)
    return dict(x=x, u=u, jac=jac, prec=prec, target=target,
                fisher=fisher, mle=mle, cov=cov)


@pytest.fixture(scope="module")
def prob():
    return _problem()


@pytest.fixture(scope="module")
def model(prob):
    comm = mgt.MeshComm(jax.devices()[:4], axis_name="data")
    aux = dict(
        x=mgt.scatter_nd(jnp.asarray(prob["x"]), axis=0, comm=comm,
                         pad_value=0.0),
        u=mgt.scatter_nd(jnp.asarray(prob["u"]), axis=0, comm=comm,
                         pad_value=0.0),
        target=jnp.asarray(prob["target"]),
        prec=jnp.asarray(prob["prec"]))
    return GaussianLinearModel(aux_data=aux, comm=comm)


def _dense_loss(prob):
    jac = jnp.asarray(prob["jac"])
    target = jnp.asarray(prob["target"])
    prec = jnp.asarray(prob["prec"])

    def loss(p):
        r = jac @ p - target
        return 0.5 * r @ prec @ r
    return loss


# ------------------------------------------------------------------ #
# Fisher / Laplace
# ------------------------------------------------------------------ #
def test_sumstats_jacobian_fwd_rev_match_dense(model, prob):
    p = jnp.asarray(prob["mle"])
    for mode in ("fwd", "rev"):
        y, jac = model.calc_sumstats_and_jac_from_params(p, mode=mode)
        np.testing.assert_allclose(np.asarray(jac), prob["jac"],
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(y),
                                   prob["jac"] @ prob["mle"],
                                   rtol=1e-4, atol=1e-4)


def test_fisher_matches_dense_hessian_at_mle(model, prob):
    """ISSUE 2 acceptance: distributed Fisher == dense jax.hessian of
    the loss at the MLE, rtol 1e-4, under shard_map on a 4-device
    mesh."""
    fr = fisher_information(model, prob["mle"])
    dense = np.asarray(jax.hessian(_dense_loss(prob))(
        jnp.asarray(prob["mle"])))
    np.testing.assert_allclose(np.asarray(fr.fisher), dense, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fr.fisher), prob["fisher"],
                               rtol=1e-3)


def test_laplace_covariance_and_stderr(model, prob):
    fr = fisher_information(model, prob["mle"])
    cov = np.asarray(fr.covariance())
    np.testing.assert_allclose(cov, prob["cov"], rtol=1e-3)
    np.testing.assert_allclose(np.asarray(fr.stderr()),
                               np.sqrt(np.diag(prob["cov"])), rtol=1e-3)
    diag = fr.diagnostics()
    assert diag["identifiable"]
    assert np.isfinite(diag["condition_number"])


def test_laplace_pinv_fallback_on_singular():
    singular = jnp.asarray(np.diag([1.0, 0.0]).astype(np.float32))
    with pytest.warns(RuntimeWarning, match="not positive definite"):
        cov = laplace_covariance(singular)
    np.testing.assert_allclose(np.asarray(cov), np.diag([1.0, 0.0]),
                               atol=1e-6)
    diag = fisher_diagnostics(singular)
    assert diag["n_unidentifiable"] == 1 and not diag["identifiable"]


def test_streaming_fisher_matches_resident(model, prob):
    """The chunk-accumulated Jacobian (1e9-halo path, scaled down)
    reproduces the resident SPMD program; fisher_information accepts
    the streaming wrapper directly."""
    from multigrad_tpu.data import StreamingOnePointModel

    aux = {k: v for k, v in model.aux_data.items() if k not in ("x", "u")}
    streamed = StreamingOnePointModel(
        model=GaussianLinearModel(aux_data=aux, comm=model.comm),
        streams={"x": prob["x"], "u": prob["u"]},
        chunk_rows=16, pad_values=0.0)
    p = jnp.asarray(prob["mle"])
    y_s, jac_s = sumstats_jacobian(streamed, p)
    y_r, jac_r = sumstats_jacobian(model, p)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_r),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(jac_s), np.asarray(jac_r),
                               rtol=1e-4, atol=1e-4)
    fr = fisher_information(streamed, p)
    np.testing.assert_allclose(np.asarray(fr.fisher), prob["fisher"],
                               rtol=1e-3)


def test_fisher_on_smf_model_is_sane():
    """Fisher on a real (nonlinear) model family: symmetric, positive
    definite at the truth, and consistent between jac modes."""
    from multigrad_tpu.models.smf import SMFModel, make_smf_data

    comm = mgt.MeshComm(jax.devices()[:4], axis_name="data")
    m = SMFModel(aux_data=make_smf_data(4_000, comm=comm), comm=comm)
    p = jnp.array([-2.0, 0.2])
    fr = fisher_information(m, p)
    f = np.asarray(fr.fisher)
    np.testing.assert_allclose(f, f.T, rtol=1e-6)
    assert np.all(np.linalg.eigvalsh(f) > 0)
    fr_rev = fisher_information(m, p, mode="rev")
    np.testing.assert_allclose(f, np.asarray(fr_rev.fisher), rtol=1e-4)


# ------------------------------------------------------------------ #
# HMC
# ------------------------------------------------------------------ #
@pytest.fixture(scope="module")
def hmc_result(model, prob):
    return run_hmc(model, jnp.asarray(prob["mle"]), num_samples=800,
                   num_warmup=400, num_chains=4, step_size=0.1,
                   num_leapfrog=8, randkey=3, init_spread=0.3)


def test_hmc_recovers_gaussian_posterior(hmc_result, prob):
    """ISSUE 2 acceptance: 4-chain in-graph HMC recovers the known
    Gaussian posterior's mean and covariance within 3 Monte-Carlo
    standard errors, with split R-hat < 1.05."""
    res = hmc_result
    assert res.samples.shape == (4, 800, N_DIM)
    assert np.all(res.rhat < 1.05), res.rhat
    assert np.all(res.divergences == 0)

    sd = np.sqrt(np.diag(prob["cov"]))
    mcse_mean = sd / np.sqrt(res.ess)
    np.testing.assert_array_less(
        np.abs(res.mean() - prob["mle"]), 3.0 * mcse_mean)

    # Covariance, elementwise: se of a Gaussian covariance estimate is
    # sqrt((Σ_ii Σ_jj + Σ_ij²) / ESS) — use the most conservative
    # (minimum) ESS across dimensions.
    cov = res.cov()
    se_cov = np.sqrt((np.outer(np.diag(prob["cov"]),
                               np.diag(prob["cov"]))
                      + prob["cov"] ** 2) / float(np.min(res.ess)))
    np.testing.assert_array_less(np.abs(cov - prob["cov"]),
                                 3.0 * se_cov)


def test_hmc_adaptation_and_accounting(hmc_result):
    res = hmc_result
    # Dual averaging pulled the acceptance rate into a usable band
    # around the 0.8 target.
    assert np.all(res.accept_prob > 0.6)
    assert np.all(res.accept_prob < 0.99)
    assert np.all(res.warmup_accept_prob > 0.5)
    assert np.all(res.step_size > 0)
    assert np.all(res.ess > 50)
    s = res.summary()
    assert s["num_chains"] == 4 and s["min_ess"] > 0


def test_hmc_chain_init_shapes(model, prob):
    # Explicit (C, D) init: leading dim wins over num_chains.
    init = np.tile(prob["mle"], (2, 1)) + 0.01
    res = run_hmc(model, init, num_samples=20, num_warmup=10,
                  num_chains=7, num_leapfrog=3, randkey=0)
    assert res.samples.shape == (2, 20, N_DIM)
    with pytest.raises(ValueError, match="init must be"):
        run_hmc(model, np.zeros((2, 2, 2)), num_samples=4,
                num_warmup=0)
    with pytest.raises(ValueError, match="inv_mass"):
        run_hmc(model, prob["mle"], num_samples=4, num_warmup=0,
                inv_mass=np.ones((N_DIM, N_DIM)))
    # A zero entry (pinv-fallback stderr of an unidentifiable
    # direction) would blow up the momentum draw — rejected loudly.
    with pytest.raises(ValueError, match="strictly positive"):
        run_hmc(model, prob["mle"], num_samples=4, num_warmup=0,
                inv_mass=np.array([1.0, 0.0, 1.0]))


def test_hmc_single_device_path(prob):
    """comm=None exercises the plain-jit (no shard_map) compile."""
    aux = dict(x=jnp.asarray(prob["x"]), u=jnp.asarray(prob["u"]),
               target=jnp.asarray(prob["target"]),
               prec=jnp.asarray(prob["prec"]))
    m = GaussianLinearModel(aux_data=aux, comm=None)
    res = run_hmc(m, prob["mle"], num_samples=50, num_warmup=30,
                  num_chains=2, num_leapfrog=4, randkey=1,
                  init_spread=0.1)
    assert res.samples.shape == (2, 50, N_DIM)
    assert np.all(np.isfinite(res.samples))


# ------------------------------------------------------------------ #
# Convergence diagnostics
# ------------------------------------------------------------------ #
def test_rhat_and_ess_on_iid_chains():
    rng = np.random.default_rng(1)
    iid = rng.normal(size=(4, 500, 2))
    rhat = split_rhat(iid)
    ess = effective_sample_size(iid)
    assert np.all(rhat < 1.02)
    # iid draws: ESS ≈ total draw count (Geyer truncation noise aside)
    assert np.all(ess > 0.5 * 4 * 500)
    assert np.all(ess <= 4 * 500 + 1e-9)


def test_rhat_flags_unmixed_chains():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 500, 1))
    x[0] += 10.0                       # one chain stuck elsewhere
    assert split_rhat(x)[0] > 1.5
    # ...and the pooled-variance deflation tanks the ESS too.
    assert effective_sample_size(x)[0] < 100


# ------------------------------------------------------------------ #
# Multi-start ensembles
# ------------------------------------------------------------------ #
def test_multistart_adam_finds_mle(model, prob):
    bounds = [(-3.0, 3.0)] * N_DIM
    ens = run_multistart_adam(model, param_bounds=bounds, n_starts=6,
                              nsteps=300, learning_rate=0.05, seed=0)
    assert ens.params.shape == (6, N_DIM)
    assert ens.inits.shape == (6, N_DIM)
    np.testing.assert_allclose(np.asarray(ens.best_params),
                               prob["mle"], atol=5e-2)
    assert ens.best_loss == pytest.approx(
        float(np.min(np.asarray(ens.losses))))


def test_multistart_adam_matches_solo_fits(model):
    """The (K, ndim) batched scan IS K independent fits: each row of
    the batched result equals a solo run_adam from the same init."""
    inits = jnp.asarray([[0.1, 0.2, -0.4], [-1.0, 0.5, 0.3]],
                        jnp.float32)
    ens = run_multistart_adam(model, inits=inits, nsteps=40,
                              learning_rate=0.05, bound_fits=False)
    for k in range(2):
        solo = model.run_adam(guess=inits[k], nsteps=40,
                              learning_rate=0.05, progress=False)
        np.testing.assert_allclose(np.asarray(ens.params[k]),
                                   np.asarray(solo[-1]), rtol=1e-5,
                                   atol=1e-6)


def test_multistart_lbfgs_polish(model, prob):
    ens = run_multistart_lbfgs(
        model, inits=np.tile(prob["mle"], (2, 1))
        + np.array([[0.2, -0.1, 0.1], [-0.3, 0.2, -0.2]]),
        maxsteps=60)
    np.testing.assert_allclose(np.asarray(ens.best_params),
                               prob["mle"], atol=1e-3)


def test_multistart_requires_bounds_or_inits(model):
    with pytest.raises(ValueError, match="param_bounds"):
        run_multistart_adam(model, n_starts=2, nsteps=2)
    with pytest.raises(ValueError, match="finite"):
        run_multistart_adam(model, param_bounds=[(None, 1.0)] * N_DIM,
                            n_starts=2, nsteps=2)


def test_hmc_init_from_ensemble(model, prob):
    bounds = [(-3.0, 3.0)] * N_DIM
    ens = run_multistart_adam(model, param_bounds=bounds, n_starts=4,
                              nsteps=100, learning_rate=0.05)
    init = hmc_init_from_ensemble(ens, num_chains=5, spread=0.1,
                                  randkey=0)
    assert init.shape == (5, N_DIM)
    # scattered around the winner, not collapsed onto it
    d = np.linalg.norm(np.asarray(init)
                       - np.asarray(ens.best_params), axis=1)
    assert np.all(d > 0) and np.all(d < 2.0)


def test_batched_loss_and_grad_matches_fused(model):
    p = jnp.asarray([[0.5, -0.3, 0.8], [0.0, 0.0, 0.0]], jnp.float32)
    losses, grads = model.batched_loss_and_grad_fn()(
        p, model.aux_leaves(), jnp.zeros(()))
    for k in range(2):
        loss_k, grad_k = model.calc_loss_and_grad_from_params(p[k])
        np.testing.assert_allclose(float(losses[k]), float(loss_k),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(grad_k), rtol=1e-5,
                                   atol=1e-6)
