"""Error classification in the multi-host bootstrap.

`distributed.initialize` must swallow ONLY the benign "runtime is
already up" RuntimeErrors (idempotent re-init) and re-raise every
failed bootstrap — silently degrading to single-host would run a fit
on a fraction of the data with no error.  The original classifier
spelled the condition ``a or b and c`` and silently depended on
Python's operator binding; these tests pin the intended grouping.
"""
import pytest

from multigrad_tpu.parallel import distributed


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch):
    monkeypatch.setattr(distributed, "_initialized", False)
    yield


def test_classifier_swallows_already_initialized():
    for msg in (
        "jax.distributed.initialize has already been called",
        "Distributed runtime already initialized",
        "initialize() can only be called once",
    ):
        assert distributed._is_already_initialized_error(
            RuntimeError(msg)), msg


def test_classifier_reraises_failed_bootstrap():
    # Messages that mention "initialize" but NOT because the runtime
    # is up — the case `a or b and c` gets right only by luck of
    # operator binding — plus plain connection failures.
    for msg in (
        "Failed to initialize distributed runtime: coordinator "
        "unreachable",
        "could not connect to coordinator at 10.0.0.1:1234: timeout",
        "initialization failed",
        # "already" alone must not be enough: this is a FAILED
        # bootstrap (stale process holding the coordinator port).
        "failed to bind coordinator: address already in use",
    ):
        assert not distributed._is_already_initialized_error(
            RuntimeError(msg)), msg


def test_initialize_swallows_already_initialized(monkeypatch):
    def fake_init(**kwargs):
        raise RuntimeError("jax.distributed.initialize has already "
                           "been called")

    monkeypatch.setattr(distributed.jax.distributed, "initialize",
                        fake_init)
    distributed.initialize()  # must not raise
    assert distributed._initialized


def test_initialize_reraises_failed_bootstrap(monkeypatch):
    def fake_init(**kwargs):
        raise RuntimeError("could not connect to coordinator: timeout")

    monkeypatch.setattr(distributed.jax.distributed, "initialize",
                        fake_init)
    with pytest.raises(RuntimeError, match="coordinator"):
        distributed.initialize()
    assert not distributed._initialized


def test_initialize_value_error_means_standalone(monkeypatch):
    def fake_init(**kwargs):
        raise ValueError("coordinator_address should be defined")

    monkeypatch.setattr(distributed.jax.distributed, "initialize",
                        fake_init)
    distributed.initialize()  # single-process standalone: fine
    assert distributed._initialized


def test_initialize_is_idempotent(monkeypatch):
    calls = []

    def fake_init(**kwargs):
        calls.append(1)

    monkeypatch.setattr(distributed.jax.distributed, "initialize",
                        fake_init)
    distributed.initialize()
    distributed.initialize()
    assert len(calls) == 1
