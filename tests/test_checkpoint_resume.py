"""Checkpointed Adam: preemption-safe resume (SURVEY §5.4 addition).

The reference has no checkpointing; its restart story is returning
the full trajectory.  These tests pin the added contract: a fit with
``checkpoint_dir`` produces the exact same trajectory as one without,
survives a mid-fit crash (resuming from the last completed segment),
and re-invocation after completion is a pure checkpoint read.
"""
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data
from multigrad_tpu.utils import checkpoint as ckpt
from multigrad_tpu.utils import debug

import jax
import jax.numpy as jnp


@pytest.fixture
def model():
    comm = mgt.MeshComm(jax.devices()[:4], axis_name="data")
    return SMFModel(aux_data=make_smf_data(4_000, comm=comm), comm=comm)


GUESS = ParamTuple(-1.0, 0.5)


def test_checkpointed_fit_matches_plain(model, tmp_path):
    plain = model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                           progress=False)
    ckpted = model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                            progress=False,
                            checkpoint_dir=str(tmp_path),
                            checkpoint_every=5)
    np.testing.assert_allclose(np.asarray(ckpted), np.asarray(plain),
                               rtol=1e-6)
    assert (tmp_path / "adam_state.npz").exists()


def test_resume_after_simulated_preemption(model, tmp_path,
                                           monkeypatch):
    plain = model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                           progress=False)

    # Crash the driver after the second segment's checkpoint lands.
    real_save = ckpt.save
    calls = {"n": 0}

    def crashing_save(path, tree):
        real_save(path, tree)
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated preemption")

    monkeypatch.setattr(ckpt, "save", crashing_save)
    with pytest.raises(RuntimeError, match="simulated preemption"):
        model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path),
                       checkpoint_every=4)
    monkeypatch.setattr(ckpt, "save", real_save)

    # Fresh invocation resumes from step 8 and completes.
    resumed = model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                             progress=False,
                             checkpoint_dir=str(tmp_path),
                             checkpoint_every=4)
    np.testing.assert_allclose(np.asarray(resumed), np.asarray(plain),
                               rtol=1e-6)

    # Completed fit: pure checkpoint read, identical result.
    again = model.run_adam(guess=GUESS, nsteps=12, learning_rate=0.02,
                           progress=False, checkpoint_dir=str(tmp_path),
                           checkpoint_every=4)
    np.testing.assert_allclose(np.asarray(again), np.asarray(resumed))


def test_checkpointed_fit_with_bounds_and_key(model, tmp_path):
    bounds = [(-3.0, 0.0), (0.01, 1.0)]
    plain = model.run_adam(guess=GUESS, nsteps=10, learning_rate=0.02,
                           param_bounds=bounds, randkey=7,
                           progress=False)
    ckpted = model.run_adam(guess=GUESS, nsteps=10, learning_rate=0.02,
                            param_bounds=bounds, randkey=7,
                            progress=False,
                            checkpoint_dir=str(tmp_path),
                            checkpoint_every=3)
    np.testing.assert_allclose(np.asarray(ckpted), np.asarray(plain),
                               rtol=1e-6)


def test_config_mismatch_rejected(model, tmp_path):
    model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                   progress=False, checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="different nsteps"):
        model.run_adam(guess=GUESS, nsteps=9, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path))
    # Same nsteps, different guess / learning rate: must not silently
    # return the stale fit.
    with pytest.raises(ValueError, match="different fit configuration"):
        model.run_adam(guess=ParamTuple(-1.5, 0.3), nsteps=6,
                       learning_rate=0.02, progress=False,
                       checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="different fit configuration"):
        model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.05,
                       progress=False, checkpoint_dir=str(tmp_path))


def test_structure_mismatch_rejected(model, tmp_path):
    """A checkpoint whose pytree structure doesn't match (written by a
    different optimizer/version) must surface a resume error that
    names the checkpoint_dir AND carries checkpoint.load's specific
    cause (ADVICE r3: the cause used to be rewritten away)."""
    model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                   progress=False, checkpoint_dir=str(tmp_path))
    # Overwrite with a structurally different (but valid) archive.
    ckpt.save(str(tmp_path / "adam_state"), {"bogus": np.zeros(3)})
    with pytest.raises(ValueError, match="cannot resume") as excinfo:
        model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path))
    msg = str(excinfo.value)
    assert str(tmp_path) in msg
    assert "different state structure" in msg  # load()'s specific cause


def test_data_change_rejected(model, tmp_path):
    """Resuming against a silently-changed dataset must fail loudly —
    same shapes/dtypes, different values (the fingerprint's CRC term)."""
    model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                   progress=False, checkpoint_dir=str(tmp_path))
    mutated_aux = dict(model.aux_data,
                       log_halo_masses=(
                           jnp.asarray(model.aux_data["log_halo_masses"])
                           * 1.01))
    other = SMFModel(aux_data=mutated_aux, comm=model.comm)
    with pytest.raises(ValueError, match="different training data"):
        other.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path))


def test_single_element_data_edit_rejected(model, tmp_path):
    """The data guard digests EVERY element on device (VERDICT r3:
    a strided 16-sample CRC let a '17th-element' edit alias to the
    same fingerprint and resume against a stale trajectory prefix).
    A one-element nudge at an unsampled index must be caught, and so
    must a pure permutation (which preserves every elementwise sum)."""
    model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                   progress=False, checkpoint_dir=str(tmp_path))
    masses = np.array(model.aux_data["log_halo_masses"])
    edited = masses.copy()
    edited[17] += 1e-4
    other = SMFModel(aux_data=dict(model.aux_data,
                                   log_halo_masses=jnp.asarray(edited)),
                     comm=model.comm)
    with pytest.raises(ValueError, match="different training data"):
        other.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path))

    permuted = np.roll(masses, 1)
    shuffled = SMFModel(aux_data=dict(model.aux_data,
                                      log_halo_masses=jnp.asarray(
                                          permuted)),
                        comm=model.comm)
    with pytest.raises(ValueError, match="different training data"):
        shuffled.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                          progress=False, checkpoint_dir=str(tmp_path))


def test_old_guard_version_reported_as_such(model, tmp_path):
    """A checkpoint whose data-guard predates the current fingerprint
    scheme must be reported as a version mismatch, NOT as 'your data
    changed' — the old digest says nothing about the data."""
    model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                   progress=False, checkpoint_dir=str(tmp_path))
    path = str(tmp_path / "adam_state.npz")
    data = dict(np.load(path))
    # The state dict flattens with sorted keys, so config_args is
    # leaf_1 (after config); sanity-check before rewriting it to the
    # v1 layout — a bare CRC word with no version prefix.
    assert data["leaf_1"].dtype == np.uint32
    assert data["leaf_1"].shape == (2,)
    data["leaf_1"] = np.asarray([1234567], np.uint32)
    np.savez(path, **data)
    with pytest.raises(ValueError, match="older data-guard format"):
        model.run_adam(guess=GUESS, nsteps=6, learning_rate=0.02,
                       progress=False, checkpoint_dir=str(tmp_path))


def test_fingerprint_distinguishes_one_ulp():
    # The digest bitcasts rather than value-casts, so even a 1-ulp
    # float32 nudge at an arbitrary index changes it.
    from multigrad_tpu.optim.adam import _args_fingerprint
    a = np.full(1000, 1.0, np.float32)
    b = a.copy()
    b[17] = np.nextafter(b[17], np.float32(2.0), dtype=np.float32)
    assert _args_fingerprint((a,)) != _args_fingerprint((b,))


def test_fingerprint_exact_for_64bit_dtypes():
    """64-bit leaves must digest their full bit width (a float32
    value-cast would alias sub-f32 edits and >32-bit int diffs).
    Runs under x64 in a subprocess — flipping x64 in-process would
    poison the session's other compiled programs."""
    import subprocess, sys, os
    script = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['JAX_ENABLE_X64']='1';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import numpy as np;"
        "from multigrad_tpu.optim.adam import _args_fingerprint as fp;"
        "a=np.array([1.0,2.0,3.0]);b=a.copy();b[1]+=1e-12;"
        "assert fp((a,))!=fp((b,)), 'f64 nudge aliased';"
        "i=np.array([2**33]);j=np.array([2**34]);"
        "assert fp((i,))!=fp((j,)), 'int64 high bits aliased';"
        "print('X64-DIGEST-OK')")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=120,
                         env=dict(os.environ, PYTHONPATH=repo))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "X64-DIGEST-OK" in out.stdout


# --------------------------------------------------------------------------
# Streamed fit loop: checkpoint/resume (run_adam_streamed)
# --------------------------------------------------------------------------


@pytest.fixture
def streamed_model(model):
    from multigrad_tpu.data import StreamingOnePointModel
    from multigrad_tpu.models.smf import load_halo_masses
    import jax.numpy as jnp

    aux = {k: v for k, v in model.aux_data.items()
           if k != "log_halo_masses"}
    log_mh = np.asarray(jnp.log10(load_halo_masses(4_000)))
    return StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=model.comm),
        streams={"log_halo_masses": log_mh}, chunk_rows=1024)


def test_streamed_checkpointed_fit_matches_plain(streamed_model,
                                                 tmp_path):
    plain = streamed_model.run_adam(guess=GUESS, nsteps=8,
                                    learning_rate=0.02, progress=False)
    ckpted = streamed_model.run_adam(guess=GUESS, nsteps=8,
                                     learning_rate=0.02, progress=False,
                                     checkpoint_dir=str(tmp_path),
                                     checkpoint_every=3)
    np.testing.assert_allclose(np.asarray(ckpted), np.asarray(plain),
                               rtol=1e-6)
    assert (tmp_path / "adam_streamed_state.npz").exists()


def test_streamed_resume_after_preemption(streamed_model, tmp_path,
                                          monkeypatch):
    """The streamed host loop (the LONGEST fits: out-of-core catalogs)
    must survive a mid-fit crash exactly like the resident scan path:
    resume from the last checkpointed step, finish, and match the
    uninterrupted trajectory."""
    plain = streamed_model.run_adam(guess=GUESS, nsteps=8,
                                    learning_rate=0.02, progress=False)

    real_save = ckpt.save
    calls = {"n": 0}

    def crashing_save(path, tree):
        real_save(path, tree)
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated preemption")

    monkeypatch.setattr(ckpt, "save", crashing_save)
    with pytest.raises(RuntimeError, match="simulated preemption"):
        streamed_model.run_adam(guess=GUESS, nsteps=8,
                                learning_rate=0.02, progress=False,
                                checkpoint_dir=str(tmp_path),
                                checkpoint_every=3)
    monkeypatch.setattr(ckpt, "save", real_save)

    # The interrupted state is mid-fit, not complete.
    saved = dict(np.load(str(tmp_path / "adam_streamed_state.npz")))
    resumed = streamed_model.run_adam(guess=GUESS, nsteps=8,
                                      learning_rate=0.02,
                                      progress=False,
                                      checkpoint_dir=str(tmp_path),
                                      checkpoint_every=3)
    np.testing.assert_allclose(np.asarray(resumed), np.asarray(plain),
                               rtol=1e-6)
    del saved

    # Config/nsteps mismatches fail loudly, same contract as the
    # resident path.
    with pytest.raises(ValueError, match="different nsteps"):
        streamed_model.run_adam(guess=GUESS, nsteps=12,
                                learning_rate=0.02, progress=False,
                                checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="different fit configuration"):
        streamed_model.run_adam(guess=GUESS, nsteps=8,
                                learning_rate=0.05, progress=False,
                                checkpoint_dir=str(tmp_path),
                                checkpoint_every=3)


def test_streamed_checkpoint_with_bounds_and_key(streamed_model,
                                                 tmp_path):
    bounds = [(-3.0, 0.0), (0.01, 1.0)]
    plain = streamed_model.run_adam(guess=GUESS, nsteps=6,
                                    learning_rate=0.02,
                                    param_bounds=bounds, randkey=7,
                                    progress=False)
    ckpted = streamed_model.run_adam(guess=GUESS, nsteps=6,
                                     learning_rate=0.02,
                                     param_bounds=bounds, randkey=7,
                                     progress=False,
                                     checkpoint_dir=str(tmp_path),
                                     checkpoint_every=2)
    np.testing.assert_allclose(np.asarray(ckpted), np.asarray(plain),
                               rtol=1e-6)


# --------------------------------------------------------------------------
# Debug-mode replicated invariants (SURVEY §5.2)
# --------------------------------------------------------------------------


def _mesh_map(fn):
    from jax.sharding import PartitionSpec as P
    from multigrad_tpu.parallel._shard_map_compat import shard_map
    comm = mgt.MeshComm(jax.devices()[:8], axis_name="data")
    return shard_map(fn, mesh=comm.mesh, in_specs=P("data"),
                     out_specs=P("data"))


def test_replication_spread_inside_shard_map():
    def fn(x):
        rep = jnp.float32(1.5)
        varying = jnp.float32(jax.lax.axis_index("data"))
        return x + jnp.stack([
            debug.replication_spread(rep, "data"),
            debug.replication_spread(varying, "data"),
        ])[None]

    out = np.asarray(jax.jit(_mesh_map(fn))(jnp.zeros((8, 2))))
    np.testing.assert_allclose(out[:, 0], 0.0)
    np.testing.assert_allclose(out[:, 1], 7.0)  # pmax - pmin = 7


def test_assert_replicated_raises_on_divergence():
    def good(x):
        val = debug.assert_replicated(jnp.float32(2.0), "data")
        return x + val

    np.asarray(jax.jit(_mesh_map(good))(jnp.zeros((8, 2))))
    debug.check_replication()                  # no raise

    def bad(x):
        val = debug.assert_replicated(
            jnp.float32(jax.lax.axis_index("data")), "data",
            name="params")
        return x + val

    np.asarray(jax.jit(_mesh_map(bad))(jnp.zeros((8, 2))))
    with pytest.raises(AssertionError, match="replication invariant"):
        debug.check_replication()
    debug.check_replication()                  # record was drained

    # Context-manager form: raises on exit, program results unaffected.
    with pytest.raises(AssertionError, match="replication invariant"):
        with debug.replication_check():
            out = np.asarray(jax.jit(_mesh_map(bad))(jnp.zeros((8, 2))))
            np.testing.assert_allclose(out[:, 1],
                                       np.arange(8, dtype=np.float32))
