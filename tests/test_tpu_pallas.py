"""Compiled Mosaic kernels under ``shard_map`` on a real TPU chip.

The pod configuration — distributed model + compiled Pallas kernels —
is exercised here on a 1-device TPU mesh: ``backend="pallas"`` with a
``comm`` makes every kernel operand device-varying (vma), so the
genuine ``pallas_call`` (not the CPU jnp emulation, not interpret
mode) runs with a mesh axis present, forward and backward.  Runs in a
subprocess because the suite's conftest pins the CPU platform; skips
cleanly where no TPU is attached (e.g. GitHub CI).
"""
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
import jax
if jax.default_backend() != "tpu":
    print("NO-TPU")
    sys.exit(0)
import numpy as np
import jax.numpy as jnp
import multigrad_tpu as mgt
from multigrad_tpu.models.smf import SMFModel, make_smf_data, ParamTuple
from multigrad_tpu.models.wprp import (WprpModel, WprpParams,
                                       make_wprp_data)

comm = mgt.MeshComm(jax.devices()[:1], axis_name="data")

# SMF: compiled Mosaic erf kernel inside the sharded SPMD program
TRUTH = ParamTuple(-2.0, 0.2)
n = 100_000
xla = SMFModel(aux_data=make_smf_data(n, comm=None), comm=None)
pal = SMFModel(aux_data=make_smf_data(n, comm=comm, backend="pallas"),
               comm=comm)
ss_x = np.asarray(xla.calc_sumstats_from_params(TRUTH))
ss_p = np.asarray(pal.calc_sumstats_from_params(TRUTH))
np.testing.assert_allclose(ss_p, ss_x, rtol=2e-3)
lx, gx = xla.calc_loss_and_grad_from_params(ParamTuple(-1.9, 0.25))
lp, gp = pal.calc_loss_and_grad_from_params(ParamTuple(-1.9, 0.25))
np.testing.assert_allclose(float(lp), float(lx), rtol=5e-3)
np.testing.assert_allclose(np.asarray(gp), np.asarray(gx), rtol=5e-3,
                           atol=1e-5)
print("SMF-PALLAS-MESH-OK")

# wp(rp): compiled Mosaic pair kernel through the ppermute ring
WTRUTH = WprpParams()
xlaw = WprpModel(aux_data=make_wprp_data(512, 50.0, comm=None, seed=3),
                 comm=None)
palw = WprpModel(aux_data=make_wprp_data(512, 50.0, comm=comm, seed=3,
                                         backend="pallas"),
                 comm=comm)
params = WprpParams(-1.95, -0.9)
np.testing.assert_allclose(
    np.asarray(palw.calc_sumstats_from_params(params)),
    np.asarray(xlaw.calc_sumstats_from_params(params)), rtol=2e-3)
np.testing.assert_allclose(
    np.asarray(palw.calc_dloss_dparams(params)),
    np.asarray(xlaw.calc_dloss_dparams(params)), rtol=5e-3, atol=1e-6)
print("WPRP-PALLAS-MESH-OK")
print("TPU-PALLAS-OK")
"""


@pytest.mark.slow  # ~120 s: spawns a worker against the real chip/tunnel
def test_compiled_pallas_under_shard_map_on_tpu():
    env = dict(os.environ)
    # Undo the suite's CPU pinning so the worker sees the real chip.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # A tunneled TPU backend that is down hangs in backend init
    # (before even the NO-TPU guard can run).  Probe backend health
    # with a trivial dispatch first so an infra outage skips, while a
    # hang in the *workload* (e.g. a collective deadlock — what this
    # test exists to catch) still fails below.
    probe = ("import jax, jax.numpy as jnp; "
             "print('PROBE', jax.default_backend(), "
             "float(jnp.zeros(()) + 1.0))")
    try:
        ok = subprocess.run([sys.executable, "-c", probe], text=True,
                            capture_output=True, timeout=120, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend unresponsive (tunnel outage)")
    if "PROBE" not in ok.stdout:
        pytest.skip(f"TPU backend init failed: {ok.stderr[-500:]}")
    out = subprocess.run([sys.executable, "-c", WORKER], text=True,
                         capture_output=True, timeout=900, env=env)
    if "NO-TPU" in out.stdout:
        pytest.skip("no TPU attached")
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    assert "TPU-PALLAS-OK" in out.stdout, out.stdout + out.stderr[-2000:]
