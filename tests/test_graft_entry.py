"""Driver-contract tests for __graft_entry__.py.

The driver compile-checks ``entry()`` single-chip and executes
``dryrun_multichip(N)`` on N virtual CPU devices; pin both here so a
refactor can't silently break the round's validation artifacts.
Subprocesses because dryrun demands a fresh backend (and the suite's
conftest already initialized one).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, PYTHONPATH=REPO)
ENV.pop("JAX_PLATFORMS", None)
ENV.pop("XLA_FLAGS", None)


def _run(code, timeout=600):
    return subprocess.run([sys.executable, "-c", code], text=True,
                          capture_output=True, env=ENV, cwd=REPO,
                          timeout=timeout)


@pytest.mark.slow  # ~9 s: subprocess dry-run on 5 virtual devices
def test_dryrun_multichip_odd_device_count():
    # 5 devices: no even split, so the hybrid-mesh branch falls back
    # to the flat data axis and split_subcomms produces uneven groups
    # — the path an 8-device run never exercises.
    out = _run("import __graft_entry__ as g; g.dryrun_multichip(5); "
               "print('DRYRUN-OK')")
    assert out.returncode == 0, out.stderr[-3000:]
    assert "DRYRUN-OK" in out.stdout


def test_entry_compiles_on_cpu():
    out = _run(
        "import os; os.environ['JAX_PLATFORMS']='cpu'; "
        "import jax; jax.config.update('jax_platforms','cpu'); "
        "import __graft_entry__ as g; fn, args = g.entry(); "
        "loss, ss = jax.jit(fn)(*args); "
        "print('ENTRY-OK', float(loss), ss.shape)")
    assert out.returncode == 0, out.stderr[-3000:]
    assert "ENTRY-OK" in out.stdout
