"""In-graph compat module tests (reference C9, mpi4jax experiment)."""
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu import ingraph


@pytest.fixture(scope="module")
def comm():
    return mgt.global_comm()


def test_distribute_data(comm):
    data = np.arange(16.0)
    sharded = ingraph.distribute_data(data, comm=comm)
    np.testing.assert_array_equal(np.asarray(sharded), data)
    assert {s.data.shape for s in sharded.addressable_shards} == {(2,)}


def test_distribute_data_ragged_pads(comm):
    data = np.arange(10.0)
    sharded = ingraph.distribute_data(data, comm=comm, pad_value=0.0)
    assert sharded.shape == (16,)
    np.testing.assert_array_equal(np.asarray(sharded[:10]), data)


def _quadratic_problem(comm):
    # Per-shard quadratic: global loss = sum over shards of
    # |x_shard * p - t_shard|^2; additive, so gradients allreduce.
    x = ingraph.distribute_data(np.arange(1.0, 17.0), comm=comm)
    t = ingraph.distribute_data(2.0 * np.arange(1.0, 17.0), comm=comm)
    data = {"x": x, "t": t}

    def loss_and_grad(dd, params):
        resid = dd["x"] * params[0] - dd["t"]
        loss = jnp.sum(resid ** 2)
        grad = jnp.array([jnp.sum(2.0 * resid * dd["x"])])
        return loss, grad

    return data, loss_and_grad


def test_simple_grad_descent_converges(comm):
    data, fn = _quadratic_problem(comm)
    df = ingraph.simple_grad_descent(
        data, fn, guess=jnp.array([0.0]), learning_rate=3e-4, nsteps=200,
        comm=comm)
    assert len(df) == 200
    final = np.asarray(df["params"].iloc[-1])
    np.testing.assert_allclose(final, [2.0], atol=1e-3)
    # loss column is the global (allreduced) loss, decreasing
    assert df["loss"].iloc[-1] < df["loss"].iloc[0]


def test_simple_grad_descent_caches_program(comm):
    # Regression: repeat calls with the same shapes must reuse the
    # compiled scan (it used to rebuild jit(shard_map(...)) per call).
    data, fn = _quadratic_problem(comm)
    kwargs = dict(guess=jnp.array([0.0]), learning_rate=3e-4, nsteps=20,
                  comm=comm)
    df1 = ingraph.simple_grad_descent(data, fn, **kwargs)
    n_cached = len(fn._mgt_program_cache)
    df2 = ingraph.simple_grad_descent(data, fn, **kwargs)
    assert len(fn._mgt_program_cache) == n_cached == 1
    np.testing.assert_array_equal(np.asarray(df1["loss"].tolist()),
                                  np.asarray(df2["loss"].tolist()))


def test_simple_grad_descent_single_device_matches(comm):
    data, fn = _quadratic_problem(comm)
    df_dist = ingraph.simple_grad_descent(
        data, fn, guess=jnp.array([0.0]), learning_rate=3e-4, nsteps=50,
        comm=comm)
    local_data = {k: np.asarray(v) for k, v in data.items()}
    df_single = ingraph.simple_grad_descent(
        local_data, fn, guess=jnp.array([0.0]), learning_rate=3e-4,
        nsteps=50, comm=None)
    np.testing.assert_allclose(
        np.asarray(df_dist["loss"].tolist()),
        np.asarray(df_single["loss"].tolist()), rtol=1e-4)