"""Utility-layer tests: LHS sampling, diffdesi index utils, checkpoint,
profiling, aux-data plumbing (randkey / has_aux flags)."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dataclasses import dataclass, field

import multigrad_tpu as mgt
from multigrad_tpu.utils import checkpoint, diffdesi, profiling


def test_latin_hypercube_sampler():
    # Parity: util.py:56-62 — stratified draws scaled into [xmin, xmax].
    s = mgt.latin_hypercube_sampler(-1.0, 1.0, n_dim=3,
                                    num_evaluations=16, seed=0)
    assert s.shape == (16, 3)
    assert np.all(s >= -1.0) and np.all(s <= 1.0)
    # One sample per stratum along each dimension
    for d in range(3):
        strata = np.floor((s[:, d] + 1.0) / 2.0 * 16).astype(int)
        assert len(set(strata)) == 16


def test_find_ultimate_top_indices():
    # chains 3 -> 1 -> 0 -> 0 resolve to 0 (diffdesi util.py:18-28)
    idx = np.array([0, 0, 1, 1, 3])
    out = diffdesi.find_ultimate_top_indices(idx)
    np.testing.assert_array_equal(out, [0, 0, 0, 0, 0])
    out_jax, converged = diffdesi.find_ultimate_top_indices_jax(
        jnp.asarray(idx))
    np.testing.assert_array_equal(np.asarray(out_jax), out)
    assert bool(converged)


def test_find_ultimate_top_indices_cycle():
    # A 3-cycle oscillates under index-squaring and never resolves
    # (a 2-cycle squares to the identity, which *is* a fixpoint):
    # NumPy raises, JAX reports converged=False.
    cyc = np.array([1, 2, 0])
    import pytest as _pytest
    with _pytest.raises(RecursionError):
        diffdesi.find_ultimate_top_indices(cyc)
    _, converged = diffdesi.find_ultimate_top_indices_jax(jnp.asarray(cyc))
    assert not bool(converged)


def test_sort_and_reindex_consistency():
    idx = np.array([2, 2, 0, 2, 4, 4])
    sorted_arrays, reindexed = diffdesi.sort_all_by_ultimate_top_dump(
        idx, arrays_to_sort=[np.arange(6.0)],
        arrays_to_sort_and_reindex=[idx])
    assert len(sorted_arrays) == 1 and len(reindexed) == 1
    assert sorted_arrays[0].shape == (6,)


def test_checkpoint_round_trip(tmp_path):
    state = {
        "step": np.int64(7),
        "params": jnp.array([1.0, 2.0]),
        "opt": {"m": jnp.zeros(2), "v": jnp.ones(2)},
        "key": jax.random.key(3),
    }
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    restored = checkpoint.load(path, state)
    np.testing.assert_array_equal(np.asarray(restored["params"]), [1.0, 2.0])
    assert int(restored["step"]) == 7
    np.testing.assert_array_equal(
        jax.random.key_data(restored["key"]),
        jax.random.key_data(state["key"]))
    # restored key must be usable
    jax.random.normal(restored["key"], (2,))


def test_checkpoint_structure_mismatch_raises(tmp_path):
    state = {"params": jnp.array([1.0, 2.0]), "step": np.int64(0)}
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    wrong_like = {"params": jnp.zeros(2), "step": np.int64(0),
                  "extra": jnp.zeros(3)}
    with pytest.raises(ValueError, match="different state structure"):
        checkpoint.load(path, wrong_like)


def test_checkpoint_format_version_mismatch_raises(tmp_path):
    state = {"params": jnp.array([1.0, 2.0])}
    path = os.path.join(tmp_path, "ckpt")
    checkpoint.save(path, state)
    npz = path + ".npz"
    data = dict(np.load(npz))
    # A future-format archive must be rejected with a "format" error.
    data["__meta__"] = np.frombuffer(
        json.dumps({"version": 999, "n": 1,
                    "is_key": []}).encode(), dtype=np.uint8)
    np.savez(npz, **data)
    with pytest.raises(ValueError, match="format version"):
        checkpoint.load(path, state)
    # A pre-version-field archive shares version 1's layout exactly
    # and must still load (no retroactive invalidation of resumes).
    data["__meta__"] = np.frombuffer(
        json.dumps({"n": 1, "is_key": []}).encode(), dtype=np.uint8)
    np.savez(npz, **data)
    restored = checkpoint.load(path, state)
    np.testing.assert_array_equal(np.asarray(restored["params"]),
                                  [1.0, 2.0])


def test_orbax_checkpointer_round_trip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    state = {"step": np.int64(5),
             "params": np.asarray([1.5, -0.5], np.float32),
             "opt": {"m": np.zeros(2, np.float32),
                     "v": np.ones(2, np.float32)}}
    ckpt = checkpoint.OrbaxCheckpointer(str(tmp_path / "orbax"))
    assert ckpt.restore_latest(state) is None  # empty dir: no state
    ckpt.save(5, state)
    ckpt.wait()
    like = jax.tree_util.tree_map(np.zeros_like, state)
    restored = checkpoint.OrbaxCheckpointer(
        str(tmp_path / "orbax")).restore_latest(like)
    assert int(restored["step"]) == 5
    np.testing.assert_array_equal(np.asarray(restored["params"]),
                                  [1.5, -0.5])
    np.testing.assert_array_equal(np.asarray(restored["opt"]["v"]),
                                  np.ones(2))


def test_timer_counts_calls():
    timer = profiling.Timer(jax.jit(lambda x: x * 2), warmup=1)
    out = timer(5, jnp.ones(4))
    assert out["n_calls"] == 5
    assert out["calls_per_sec"] > 0


# --------------------------------------------------------------------- #
# aux plumbing through the model core (reference flags, multigrad.py:200-210)
# --------------------------------------------------------------------- #
@dataclass
class AuxModel(mgt.OnePointModel):
    aux_data: dict = field(default_factory=dict)
    sumstats_func_has_aux: bool = True
    loss_func_has_aux: bool = True

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        x = jnp.asarray(self.aux_data["x"])
        y = jnp.array([jnp.sum(x * params[0]), jnp.sum(x ** 2 * params[1])])
        return y, {"n_eff": jnp.float32(x.shape[0])}

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        loss = jnp.sum((sumstats - 1.0) ** 2)
        return loss, {"sumstats_copy": sumstats}


def _aux_models():
    comm = mgt.global_comm()
    x = jnp.arange(16.0)
    dist = AuxModel(aux_data={"x": mgt.scatter_nd(x, comm=comm)}, comm=comm)
    single = AuxModel(aux_data={"x": x}, comm=None)
    return single, dist


def test_aux_flags_single_vs_distributed():
    single, dist = _aux_models()
    params = jnp.array([0.1, 0.2])
    ys, auxs = single.calc_sumstats_from_params(params)
    yd, auxd = dist.calc_sumstats_from_params(params)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yd), rtol=1e-5)
    # aux from the distributed path is replicated-per-shard; totals differ
    ls, gs = single.calc_loss_and_grad_from_params(params)
    ld, gd = dist.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(np.asarray(ls[0]), np.asarray(ld[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd), rtol=1e-5)


def test_randkey_plumbing():
    @dataclass
    class NoisyModel(mgt.OnePointModel):
        aux_data: dict = field(default_factory=dict)

        def calc_partial_sumstats_from_params(self, params, randkey=None):
            noise = (0.0 if randkey is None
                     else 0.01 * jax.random.normal(randkey, (2,)))
            return params * 2.0 + noise

        def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                    randkey=None):
            return jnp.sum(sumstats ** 2)

    model = NoisyModel(aux_data={})
    p = jnp.array([1.0, 2.0])
    clean = model.calc_sumstats_from_params(p)
    np.testing.assert_allclose(np.asarray(clean), [2.0, 4.0])
    n1 = model.calc_sumstats_from_params(p, randkey=1)
    n2 = model.calc_sumstats_from_params(p, randkey=1)
    n3 = model.calc_sumstats_from_params(p, randkey=2)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
    assert not np.array_equal(np.asarray(n1), np.asarray(n3))
