"""Optimizer tests: Adam (scan + host loop), bounds bijections, BFGS.

Covers the reference's optimizer contracts (SURVEY §2.1 C6/C7/C8):
trajectory shapes, bounded-parameter bijections, BFGS OptimizeResult
fields, and convergence on the tutorial SMF problem (the reference's
recorded anecdote: converged in ~16 iterations, intro.ipynb cell 16).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data
from multigrad_tpu.optim import (bounds_to_arrays, inverse_transform_array,
                                 inverse_transform_diag_jacobian,
                                 transform_array)

TRUTH = ParamTuple(log_shmrat=-2.0, sigma_logsm=0.2)


@pytest.fixture(scope="module")
def model():
    comm = mgt.global_comm()
    return SMFModel(aux_data=make_smf_data(10_000, comm=comm), comm=comm)


# --------------------------------------------------------------------- #
# Bounds bijections (reference adam.py:192-239)
# --------------------------------------------------------------------- #
BOUNDS_CASES = [
    [(-3.0, -1.0), (0.05, 1.0)],          # two-sided
    [(-3.0, None), (None, 1.0)],          # one-sided each way
    [None, (0.05, 1.0)],                  # mixed unbounded
    None,                                 # fully unbounded
]


@pytest.mark.parametrize("bounds", BOUNDS_CASES)
def test_transform_round_trip(bounds):
    params = jnp.array([-2.0, 0.2])
    low, high = bounds_to_arrays(bounds, 2)
    u = transform_array(params, low, high)
    back = inverse_transform_array(u, low, high)
    np.testing.assert_allclose(np.asarray(back), np.asarray(params),
                               rtol=1e-5)


@pytest.mark.parametrize("bounds", BOUNDS_CASES[:3])
def test_inverse_maps_into_bounds(bounds):
    low, high = bounds_to_arrays(bounds, 2)
    u = jnp.array([-57.0, 123.0])
    p = np.asarray(inverse_transform_array(u, low, high))
    assert np.all(p > np.asarray(low)) and np.all(p < np.asarray(high))


def test_diag_jacobian_matches_dense():
    bounds = [(-3.0, -1.0), (0.05, None)]
    low, high = bounds_to_arrays(bounds, 2)
    u = jnp.array([0.3, -1.7])
    dense = jax.jacobian(lambda x: inverse_transform_array(x, low, high))(u)
    diag = inverse_transform_diag_jacobian(u, low, high)
    np.testing.assert_allclose(np.asarray(jnp.diag(dense)),
                               np.asarray(diag), rtol=1e-5)
    # Off-diagonal must vanish: the bijection is separable.
    np.testing.assert_allclose(np.asarray(dense - jnp.diag(jnp.diag(dense))),
                               0.0, atol=1e-7)


def test_transform_gradients_nan_free():
    low, high = bounds_to_arrays([(-3.0, -1.0), None], 2)
    g = jax.grad(lambda p: transform_array(p, low, high).sum())(
        jnp.array([-2.0, 0.5]))
    assert np.all(np.isfinite(np.asarray(g)))
    g2 = jax.grad(lambda u: inverse_transform_array(u, low, high).sum())(
        jnp.array([0.1, 0.5]))
    assert np.all(np.isfinite(np.asarray(g2)))


def test_scalar_parity_api():
    # The reference's scalar static-bounds signatures (adam.py:202-239).
    assert np.isclose(float(mgt.transform(0.5, None)), 0.5)
    t = float(mgt.transform(0.5, (0.0, 1.0)))
    assert np.isclose(float(mgt.inverse_transform(t, (0.0, 1.0))), 0.5)
    t = float(mgt.transform(2.0, (1.0, None)))
    assert np.isclose(float(mgt.inverse_transform(t, (1.0, None))), 2.0)
    t = float(mgt.transform(-2.0, (None, 1.0)))
    assert np.isclose(float(mgt.inverse_transform(t, (None, 1.0))), -2.0)


# --------------------------------------------------------------------- #
# Adam
# --------------------------------------------------------------------- #
def test_adam_trajectory_contract(model):
    guess = ParamTuple(log_shmrat=-1.0, sigma_logsm=0.5)
    traj = model.run_adam(guess=guess, nsteps=10, progress=False)
    assert traj.shape == (11, 2)
    np.testing.assert_allclose(np.asarray(traj[0]), [-1.0, 0.5], rtol=1e-6)


def test_adam_bounded_respects_bounds(model):
    bounds = [(-2.5, -0.5), (0.05, 0.6)]
    traj = model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=50,
                          param_bounds=bounds, learning_rate=0.05,
                          progress=False)
    p = np.asarray(traj)
    assert np.all(p[:, 0] > -2.5) and np.all(p[:, 0] < -0.5)
    assert np.all(p[:, 1] > 0.05) and np.all(p[:, 1] < 0.6)


def test_adam_bounded_converges(model):
    bounds = [(-3.0, -1.0), (0.05, 1.0)]
    traj = model.run_adam(guess=ParamTuple(-1.5, 0.5), nsteps=300,
                          param_bounds=bounds, learning_rate=0.02,
                          progress=False)
    np.testing.assert_allclose(np.asarray(traj[-1]), [*TRUTH], atol=0.03)


def test_adam_progress_path_matches_whole_scan(model, capsys):
    # progress=True drives the fit in fenced segments for a live bar
    # (reference UX, adam.py:32-36); the segment programs are the
    # same cached family as the whole-fit scan, so the trajectories
    # must be bit-identical — including with a randkey, whose
    # per-step split chain crosses segment boundaries.  nsteps is
    # chosen to force >1 segment of unequal lengths past the
    # _PROGRESS_MIN_SEG floor.
    from multigrad_tpu.optim.adam import _PROGRESS_MIN_SEG

    nsteps = 2 * _PROGRESS_MIN_SEG + 37
    kwargs = dict(guess=ParamTuple(-1.0, 0.5), nsteps=nsteps,
                  learning_rate=0.02, randkey=3)
    t_plain = model.run_adam(progress=False, **kwargs)
    t_prog = model.run_adam(progress=True, **kwargs)
    np.testing.assert_array_equal(np.asarray(t_plain),
                                  np.asarray(t_prog))
    # the bar ran and reported the full count (render cadence is
    # tqdm's business — asserting on redraw counts is flaky)
    err = capsys.readouterr().err
    assert "Adam Gradient Descent Progress" in err
    assert f"{nsteps}/{nsteps}" in err


def test_adam_progress_short_fit_stays_one_program(model, capsys):
    # A fit shorter than the floor must not be sliced at all: the
    # live-progress path may never degrade a short fit to per-step
    # dispatch (the host-loop pattern the scan fast path replaces).
    from multigrad_tpu.optim import adam as adam_mod

    calls = []
    orig = adam_mod._adam_segment_program

    def spy(fn, seg_len, *args, **kw):
        calls.append(seg_len)
        return orig(fn, seg_len, *args, **kw)

    adam_mod._adam_segment_program = spy
    try:
        model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=30,
                       progress=True)
    finally:
        adam_mod._adam_segment_program = orig
    assert calls == [30]


def test_adam_randkey_reproducible(model):
    kwargs = dict(guess=ParamTuple(-1.0, 0.5), nsteps=5, progress=False)
    t1 = model.run_adam(randkey=7, **kwargs)
    t2 = model.run_adam(randkey=7, **kwargs)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    t3 = model.run_adam(randkey=7, const_randkey=True, **kwargs)
    assert t3.shape == t1.shape


def test_generic_run_adam_host_loop():
    # The generic entry point works on an arbitrary callable
    # (reference adam.py:133-189 contract).
    target = jnp.array([1.0, -2.0, 3.0])

    def loss_and_grad(p, _data):
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    traj = mgt.run_adam(loss_and_grad, jnp.zeros(3), data=None, nsteps=200,
                        learning_rate=0.1, progress=False)
    assert traj.shape == (201, 3)
    np.testing.assert_allclose(np.asarray(traj[-1]), np.asarray(target),
                               atol=0.05)


def test_generic_run_adam_bounded():
    target = jnp.array([0.8])

    def loss_and_grad(p, _data):
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    traj = mgt.run_adam(loss_and_grad, jnp.array([0.1]), data=None,
                        nsteps=300, param_bounds=[(0.0, 1.0)],
                        learning_rate=0.05, progress=False)
    assert np.all(np.asarray(traj) > 0.0) and np.all(np.asarray(traj) < 1.0)
    np.testing.assert_allclose(np.asarray(traj[-1]), [0.8], atol=0.05)


def test_adam_scan_accepts_array_learning_rate(model):
    # Regression: learning_rate is a jit-static of the scan program and
    # must be coerced, not passed through as an (unhashable) jax array.
    traj = model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=5,
                          learning_rate=jnp.float32(0.01), progress=False)
    assert traj.shape == (6, 2)


def test_scan_program_cache_lives_on_callable():
    # Regression: compiled whole-fit programs must be cached on the
    # callable itself (not jit's global cache, which would pin the
    # model's aux data for the process lifetime) and reused across
    # calls with the same config.
    from multigrad_tpu.optim.adam import _adam_segment_program

    def fn(p, key):
        return jnp.sum(p ** 2), 2.0 * p

    p1 = _adam_segment_program(fn, 5, 0.01, False, False, False)
    p2 = _adam_segment_program(fn, 5, 0.01, False, False, False)
    assert p1 is p2
    assert ("adam_segment", 5, 0.01, False, False, False, False) in [
        k[1] for k in fn._mgt_program_cache]  # trailing False: donate
    p3 = _adam_segment_program(fn, 6, 0.01, False, False, False)
    assert p3 is not p1


def test_init_randkey_and_gen_new_key():
    key = mgt.init_randkey(123)
    assert jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
    key2 = mgt.gen_new_key(key)
    assert not np.array_equal(jax.random.key_data(key),
                              jax.random.key_data(key2))
    with pytest.raises(TypeError):
        mgt.init_randkey("not a key")


# --------------------------------------------------------------------- #
# BFGS
# --------------------------------------------------------------------- #
def test_bfgs_converges_like_reference(model):
    # The reference tutorial records nit=16, nfev=29, loss ~5e-12
    # (intro.ipynb cell 16).  This float32 build measures nit=16,
    # nfev~20, fun~8e-9 on the same problem — identical iteration
    # count; only the final loss floor differs (f32 noise floor vs
    # the reference's f64 run), so the quality bar is tight.
    guess = ParamTuple(log_shmrat=-1.0, sigma_logsm=0.5)
    result = model.run_bfgs(guess=guess, maxsteps=100, progress=False)
    assert result.success
    assert result.nit <= 25
    assert result.fun < 1e-8
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)
    # OptimizeResult contract (reference multigrad.py:332-347)
    for field in ("message", "success", "fun", "x", "jac", "nfev", "nit"):
        assert hasattr(result, field)


def test_bfgs_bounded(model):
    result = model.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                            param_bounds=[(-3.0, -1.0), (0.05, 1.0)],
                            progress=False)
    assert result.success
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)


def test_adam_rejects_guess_on_bounds(model):
    # A guess on the boundary maps to +-inf through the tan/arctan
    # bijection and the fit silently pins to the bound; both Adam
    # entry points must reject it at setup.
    with pytest.raises(ValueError, match="strictly inside"):
        model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=5,
                       param_bounds=[(-3.0, -1.0), (0.05, 1.0)],
                       progress=False)
    with pytest.raises(ValueError, match="strictly inside"):
        mgt.run_adam(lambda p, _d: (jnp.sum(p ** 2), 2 * p),
                     jnp.array([0.5]), None, nsteps=5,
                     param_bounds=[(0.5, 1.0)], progress=False)


def test_bfgs_bounded_with_const_randkey(model):
    # Bounded + randkey case: the key is held constant across scipy
    # iterations by design (deterministic loss is required for the
    # line search — reference bfgs.py:47-48,63-66), so convergence
    # must match the keyless fit.
    result = model.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                            param_bounds=[(-3.0, -1.0), (0.05, 1.0)],
                            randkey=42, progress=False)
    assert result.success
    assert result.nit <= 25
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)
    # Same key -> bitwise-identical deterministic result
    again = model.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                           param_bounds=[(-3.0, -1.0), (0.05, 1.0)],
                           randkey=42, progress=False)
    np.testing.assert_array_equal(np.asarray(result.x),
                                  np.asarray(again.x))


def test_lbfgs_scan_in_graph(model):
    # In-graph L-BFGS addition: fully on-device fit.
    params, losses = mgt.run_lbfgs_scan(
        model.calc_loss_and_grad_from_params,
        jnp.array([-1.5, 0.4]), maxsteps=40)
    assert losses.shape == (40,)
    np.testing.assert_allclose(np.asarray(params), [*TRUTH], atol=5e-3)


def test_lbfgs_scan_scalar_params():
    # 0-d params worked before bounds support landed; keep it that way.
    def fn(p):
        return (p - 1.0) ** 2, 2.0 * (p - 1.0)

    p, losses = mgt.run_lbfgs_scan(fn, 0.3, maxsteps=20)
    assert np.asarray(p).shape == ()
    assert abs(float(p) - 1.0) < 1e-5


def test_lbfgs_scan_scalar_params_with_bounds():
    # Scalar params compose with param_bounds (one entry, 0-d ride):
    # the in-scan objective still sees a true scalar, and an excluding
    # box pins the iterate at its edge.
    shapes = []

    def fn(p):
        shapes.append(jnp.shape(p))
        return (p - 1.0) ** 2, 2.0 * (p - 1.0)

    # 100 steps: convergence rate through the float32 bounds
    # bijection varies by XLA version (25 sufficed on some, reaches
    # only ~1e-3 on others); the quadratic is exact at the limit.
    p, losses = mgt.run_lbfgs_scan(fn, 0.3, maxsteps=100,
                                   param_bounds=[(0.0, 2.0)])
    assert np.asarray(p).shape == ()
    assert abs(float(p) - 1.0) < 1e-4
    assert all(s == () for s in shapes)

    p_edge, _ = mgt.run_lbfgs_scan(fn, 0.3, maxsteps=25,
                                   param_bounds=[(0.0, 0.5)])
    # The open-interval bijection saturates to the edge itself at
    # float32 resolution, so the boundary value is reachable.
    assert 0.4 < float(p_edge) <= 0.5


def test_lbfgs_scan_bounded_matches_run_bfgs(model):
    # Bounded in-graph L-BFGS (the L-BFGS-B counterpart): the
    # transforms bijections composed into the scan must land on the
    # same solution as scipy's L-BFGS-B on the same box.
    bounds = [(-3.0, -1.0), (0.05, 1.0)]
    scipy_result = model.run_bfgs(guess=ParamTuple(-1.5, 0.4),
                                  maxsteps=100, param_bounds=bounds,
                                  progress=False)
    params, losses = mgt.run_lbfgs_scan(
        model.calc_loss_and_grad_from_params,
        jnp.array([-1.5, 0.4]), maxsteps=60, param_bounds=bounds)
    np.testing.assert_allclose(np.asarray(params),
                               np.asarray(scipy_result.x), atol=2e-3)
    # Every iterate stays strictly inside the box by construction;
    # the final loss reaches the same floor.
    assert np.all(np.isfinite(np.asarray(losses)))
    assert float(losses[-1]) < 1e-7


def test_lbfgs_scan_bounded_pins_active_bound(model):
    # A box that EXCLUDES the truth: the fit must ride the active
    # constraint (sigma's lower edge) without escaping or going NaN —
    # the bijection's job.
    bounds = [(-3.0, -1.0), (0.3, 1.0)]  # truth sigma=0.2 is outside
    params, losses = mgt.run_lbfgs_scan(
        model.calc_loss_and_grad_from_params,
        jnp.array([-1.5, 0.5]), maxsteps=60, param_bounds=bounds)
    p = np.asarray(params)
    assert np.all(np.isfinite(p)) and np.isfinite(float(losses[-1]))
    assert -3.0 < p[0] < -1.0
    assert 0.3 <= p[1] < 1.0
    # With the reference's two-sided tan bijection the constrained
    # optimum hugs the sigma edge.
    assert p[1] < 0.32, p

    with pytest.raises(ValueError, match="strictly inside"):
        mgt.run_lbfgs_scan(model.calc_loss_and_grad_from_params,
                           jnp.array([-1.0, 0.3]), maxsteps=5,
                           param_bounds=bounds)


# --------------------------------------------------------------------- #
# Simple GD variants
# --------------------------------------------------------------------- #
def test_simple_grad_descent_scan_matches_host_loop(model):
    guess = jnp.array([-1.9, 0.25])
    host = model.run_simple_grad_descent(guess=guess, nsteps=5,
                                         learning_rate=0.01)
    from multigrad_tpu.utils import simple_grad_descent_scan

    def fn(p):
        return model.calc_loss_and_grad_from_params(p)

    scan = simple_grad_descent_scan(fn, guess, nsteps=5, learning_rate=0.01)
    # scan-fused vs per-step-dispatched programs differ only at
    # float32 rounding level — but XLA's fusion choices (and hence
    # the rounding) vary by version, so the bound is loose.
    np.testing.assert_allclose(np.asarray(host.loss), np.asarray(scan.loss),
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(host.params),
                               np.asarray(scan.params), rtol=1e-3)
