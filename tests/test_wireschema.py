"""Wire-schema extraction + drift gate
(multigrad_tpu/analysis/wireschema.py).

The acceptance contract of the wire pass:

* the shipped tree is clean under ALL wire checks — writer/reader
  key symmetry holds for every codec and message, no reader splats a
  wire dict into a constructor, and the extracted schema matches the
  committed ``analysis/protocol.json`` manifest exactly;
* the extracted schema is the REAL protocol: codec bases, message
  ops, per-key required/optional, and direction are asserted against
  the shapes ``serve/wire.py`` / ``serve/fleet.py`` /
  ``serve/worker.py`` actually implement (submit's trace/qos
  decorations are optional; heartbeat's resource snapshot is
  optional; a legacy peer must keep decoding);
* seeded fixture bugs are flagged — the ``**d`` constructor splat
  and the read-but-never-written key;
* a deliberate codec key rename FAILS the drift gate with a
  key-level diff naming both the added and the removed field — the
  CI contract that no protocol change lands without a manifest bump.
"""
import json
import os
import shutil

import pytest

from multigrad_tpu.analysis.findings import ERROR, WARNING
from multigrad_tpu.analysis.wireschema import (DEFAULT_MANIFEST_PATH,
                                               PROTOCOL_VERSION,
                                               WIRE_CHECK_IDS,
                                               analyze_wire,
                                               diff_schema,
                                               dump_schema,
                                               extract_schema,
                                               protocol_markdown)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "wire")


# ------------------------------------------------------------------ #
# shipped tree
# ------------------------------------------------------------------ #
def test_shipped_tree_clean_and_undrifted():
    findings = analyze_wire()
    errors = [f for f in findings if f.severity == ERROR]
    assert errors == [], (
        "wire-protocol findings on the shipped tree:\n"
        + "\n".join(f"  [{f.check}] {f.where}: {f.message}"
                    for f in errors))
    # Warnings (written-never-read) must also be zero on the shipped
    # tree: every key a writer emits, some reader consumes.
    assert findings == [], [(f.check, f.where) for f in findings]


def test_wire_check_registry_is_stable():
    assert WIRE_CHECK_IDS == ("wire-key-asymmetry",
                              "wire-reader-splat",
                              "wire-manifest-drift")


def test_committed_manifest_matches_extraction():
    with open(DEFAULT_MANIFEST_PATH, encoding="utf-8") as f:
        manifest = json.load(f)
    model = extract_schema()
    assert diff_schema(manifest, model.schema) == []
    # And the emitter reproduces the committed bytes exactly — the
    # CI artifact is deterministic.
    with open(DEFAULT_MANIFEST_PATH, encoding="utf-8") as f:
        assert f.read() == dump_schema(model.schema)


# ------------------------------------------------------------------ #
# extracted schema content: the protocol the code actually speaks
# ------------------------------------------------------------------ #
@pytest.fixture(scope="module")
def schema():
    return extract_schema().schema


def test_schema_codecs(schema):
    assert schema["version"] == PROTOCOL_VERSION
    assert sorted(schema["codecs"]) == [
        "config", "qos", "resources", "result", "rollup", "shed"]
    result = schema["codecs"]["result"]
    # Every writer key is consumed; the decode-side optionality is
    # the forward-compat contract (new fields default, not KeyError).
    assert result["writer"]["loss"] == "required"
    assert result["reader"]["loss"] == "required"
    assert result["reader"]["trace_id"] == "optional"
    assert result["reader"]["hops"] == "optional"
    cfg = schema["codecs"]["config"]
    assert cfg["reader"]["job_id"] == "optional"
    assert cfg["reader"]["nsteps"] == "required"


def test_schema_message_ops(schema):
    assert sorted(schema["messages"]) == [
        "chaos", "drain", "drained", "draining", "error",
        "heartbeat", "ping", "poison_retry", "pong", "ready",
        "reject", "result", "stop", "submit"]


def test_schema_submit_shape(schema):
    submit = schema["messages"]["submit"]
    assert submit["direction"] == "router_to_worker"
    w = submit["writer"]
    assert w["rid"] == "required"
    assert w["guess"] == "required"
    assert w["config"] == "required"
    # The tracing/QoS decorations are post-hoc `msg[...] =` writes
    # behind feature flags: optional on the wire, by construction.
    assert w["trace"] == "optional"
    assert w["qos"] == "optional"
    r = submit["reader"]
    assert r["rid"] == "required"
    assert r["trace"] == "optional"
    assert r["qos"] == "optional"


def test_schema_heartbeat_and_mixed_version_fleet(schema):
    hb = schema["messages"]["heartbeat"]
    assert hb["direction"] == "worker_to_router"
    # The resource snapshot is the mixed-version escape hatch on
    # BOTH sides: an old worker omits it, an old router ignores it.
    assert hb["writer"]["resources"] == "optional"
    assert hb["reader"]["resources"] == "optional"
    reject = schema["messages"]["reject"]
    assert reject["writer"]["shed"] == "optional"
    assert reject["reader"]["shed"] == "optional"


def test_schema_directions_and_special_cases(schema):
    msgs = schema["messages"]
    # stop is router-side only (the worker just breaks its loop).
    assert msgs["stop"]["direction"] == "router_to_worker"
    assert msgs["stop"]["writer"] is None
    # ready is the line-protocol handshake, not a dict literal.
    assert msgs["ready"]["direction"] == "worker_to_router"
    assert msgs["ready"]["writer"]["pid"] == "required"
    # chaos fans an arbitrary payload through (**spec): dynamic.
    assert msgs["chaos"]["dynamic"] is True


def test_dump_schema_is_deterministic(schema):
    assert dump_schema(schema) == dump_schema(
        json.loads(json.dumps(schema)))
    assert dump_schema(schema).endswith("\n")


def test_protocol_markdown_renders_every_op(schema):
    md = protocol_markdown(schema)
    for op in schema["messages"]:
        assert f"`{op}`" in md, op
    for base in schema["codecs"]:
        assert base in md
    assert "--emit-protocol" in md      # the manifest-bump recipe


# ------------------------------------------------------------------ #
# seeded fixtures
# ------------------------------------------------------------------ #
@pytest.fixture(scope="module")
def fixture_findings():
    return analyze_wire(root=FIXTURES,
                        checks=("wire-key-asymmetry",
                                "wire-reader-splat"))


def test_fixture_reader_splat_flagged(fixture_findings):
    hits = [f for f in fixture_findings
            if f.check == "wire-reader-splat"]
    assert len(hits) == 1, [(f.where, f.message) for f in hits]
    assert "splat_reader.py:33" in hits[0].where
    assert hits[0].severity == ERROR


def test_fixture_key_asymmetry_flagged(fixture_findings):
    errors = [f for f in fixture_findings
              if f.check == "wire-key-asymmetry"
              and f.severity == ERROR]
    assert len(errors) == 1, [(f.where, f.message) for f in errors]
    # frame_from_wire requires "t"; frame_to_wire never writes it.
    assert "'t'" in errors[0].message
    # The splatted codec's written keys are never read -> warnings.
    warns = [f for f in fixture_findings
             if f.check == "wire-key-asymmetry"
             and f.severity == WARNING]
    assert {k for f in warns for k in ("'a'", "'b'")
            if k in f.message} == {"'a'", "'b'"}


# ------------------------------------------------------------------ #
# the drift gate: a protocol change without a manifest bump fails
# ------------------------------------------------------------------ #
def test_codec_key_rename_fails_drift_gate(tmp_path):
    serve_src = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "multigrad_tpu", "serve")
    scratch = tmp_path / "serve"
    shutil.copytree(serve_src, scratch,
                    ignore=shutil.ignore_patterns("__pycache__"))
    wire = scratch / "wire.py"
    src = wire.read_text()
    assert '"loss":' in src
    wire.write_text(src.replace('"loss":', '"final_loss":'))
    model = extract_schema(root=str(tmp_path))
    findings = analyze_wire(model=model,
                            checks=("wire-manifest-drift",))
    drift = sorted(f.where for f in findings)
    # The key-level diff names BOTH sides of the rename.
    assert any("codecs.result.writer.final_loss" in w
               for w in drift), drift
    assert any("codecs.result.writer.loss" in w
               for w in drift), drift
    assert all(f.severity == ERROR for f in findings)


def test_missing_manifest_is_an_error(tmp_path):
    findings = analyze_wire(
        checks=("wire-manifest-drift",),
        manifest_path=str(tmp_path / "nope.json"))
    assert len(findings) == 1
    assert findings[0].check == "wire-manifest-drift"
    assert "--emit-protocol" in findings[0].message


def test_diff_schema_key_level():
    a = {"x": {"k": "required", "gone": "optional"}}
    b = {"x": {"k": "optional", "new": "required"}}
    diffs = diff_schema(a, b)
    assert any(d.startswith("x.gone: removed") for d in diffs)
    assert any(d.startswith("x.new: added") for d in diffs)
    assert any("x.k:" in d and "required" in d and "optional" in d
               for d in diffs)
    assert diff_schema(a, json.loads(json.dumps(a))) == []


# ------------------------------------------------------------------ #
# lint CLI integration
# ------------------------------------------------------------------ #
def test_lint_cli_wire_target(capsys):
    from multigrad_tpu.analysis.lint import main
    rc = main(["--targets", "wire"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[wire] clean" in out


def test_lint_cli_emit_protocol_round_trip(tmp_path, capsys):
    from multigrad_tpu.analysis.lint import main
    out_path = tmp_path / "protocol.json"
    rc = main(["--targets", "wire",
               "--emit-protocol", str(out_path)])
    capsys.readouterr()
    assert rc == 0
    with open(DEFAULT_MANIFEST_PATH, encoding="utf-8") as f:
        assert out_path.read_text() == f.read()


def test_lint_cli_tampered_manifest_exits_nonzero(tmp_path, capsys):
    from multigrad_tpu.analysis.lint import main
    with open(DEFAULT_MANIFEST_PATH, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest["messages"]["submit"]["writer"]["rid"] = "optional"
    tampered = tmp_path / "protocol.json"
    tampered.write_text(json.dumps(manifest))
    rc = main(["--json", "--targets", "wire",
               "--manifest", str(tampered)])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    assert payload["clean"] is False
    assert any(f["check"] == "wire-manifest-drift"
               and "messages.submit.writer.rid" in f["where"]
               for f in payload["findings"])
