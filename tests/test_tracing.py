"""Distributed request tracing (telemetry/tracing + trace CLI).

Four tiers:

* **Context/recorder units** — W3C-traceparent round trips, the
  deliberately tolerant parse side (a malformed header degrades to
  untraced, never an exception), and the :class:`Tracer`'s span
  records (context-manager failure capture included).
* **Merge/render units** — :func:`trace_summary` completeness
  verdicts (one root, parents resolve), interval-*union* coverage
  (overlapping hops counted once), the requeue waterfall label the
  chaos CI greps for, and the stdlib CLI end to end over real
  JSONL files.
* **Wire forward-compatibility** — the mixed-version-fleet contract
  pinned: decorated (trace-carrying) messages at handlers that
  predate tracing, undecorated results at a decorated router, and
  unknown config fields through ``config_from_wire`` — none of it
  may crash or drop a request.
* **Single-process scheduler tracing** — a real
  :class:`FitScheduler` with a ``tracer=``: every served fit yields
  a complete parent-linked trace whose hops land on
  ``FitResult.hops``, and the latency histograms feed
  ``/status``-shape p50/p95/p99 quantiles with exemplar trace ids.

The full fleet waterfall (router + worker subprocesses + SIGKILL)
is asserted in ``tests/test_fleet.py``.
"""
import json

import numpy as np
import pytest

from multigrad_tpu.telemetry.tracing import (TraceContext, Tracer,
                                             new_trace,
                                             parse_traceparent)
from multigrad_tpu.telemetry import trace as trace_cli


# ------------------------------------------------------------------ #
# context units: mint, child, traceparent round trip
# ------------------------------------------------------------------ #
def test_new_trace_mints_root_context():
    ctx = new_trace()
    assert len(ctx.trace_id) == 32
    assert len(ctx.span_id) == 16
    assert ctx.parent_span_id is None
    int(ctx.trace_id, 16)       # hex or bust
    int(ctx.span_id, 16)
    assert new_trace().trace_id != ctx.trace_id


def test_child_keeps_trace_and_parents_under_span():
    root = new_trace()
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    assert child.span_id != root.span_id
    grand = child.child()
    assert grand.parent_span_id == child.span_id


def test_traceparent_round_trip():
    root = new_trace()
    parsed = parse_traceparent(root.traceparent)
    assert parsed.trace_id == root.trace_id
    assert parsed.span_id == root.span_id
    # The header does NOT carry the parent link (W3C shape): the
    # receiver's spans parent to span_id.
    assert parsed.parent_span_id is None
    assert TraceContext.from_wire(root.to_wire()).trace_id \
        == root.trace_id


@pytest.mark.parametrize("bad", [
    None, 17, "", "00-short-short-01",
    "00-" + "g" * 32 + "-" + "a" * 16 + "-01",     # non-hex
    "00-" + "a" * 32 + "-" + "b" * 16,             # 3 parts
    "a" * 32,                                      # no dashes
])
def test_parse_traceparent_tolerates_malformed(bad):
    # Mixed-version fleet: a malformed/missing header means "serve
    # untraced", never an exception out of the handler.
    assert parse_traceparent(bad) is None


@pytest.mark.parametrize("wire", [None, "x", [], {},
                                  {"traceparent": 3},
                                  {"other_field": True}])
def test_from_wire_tolerates_garbage(wire):
    assert TraceContext.from_wire(wire) is None


# ------------------------------------------------------------------ #
# recorder units
# ------------------------------------------------------------------ #
def test_tracer_records_spans_in_memory():
    with Tracer(service="unit") as tracer:
        root = tracer.new_trace()
        tracer.record(root, "request", 10.0, 11.0, outcome="ok")
        with tracer.span(root, "hop", worker="w0"):
            pass
        with pytest.raises(RuntimeError):
            with tracer.span(root, "boom"):
                raise RuntimeError("x")
    recs = tracer.records
    assert [r["name"] for r in recs] == ["request", "hop", "boom"]
    assert all(r["event"] == "trace_span" for r in recs)
    assert all(r["trace_id"] == root.trace_id for r in recs)
    assert recs[0]["elapsed_s"] == pytest.approx(1.0)
    assert recs[1]["parent_span_id"] == root.span_id
    assert recs[1]["worker"] == "w0" and recs[1]["ok"] is True
    assert recs[2]["ok"] is False       # raised block still records
    assert recs[1]["service"] == "unit"


def test_tracer_file_sink_and_cli_load(tmp_path):
    path = str(tmp_path / "sub" / "proc.trace.jsonl")
    with Tracer(path, service="w0") as tracer:
        root = tracer.new_trace()
        tracer.record(root, "request", 1.0, 2.0)
        tracer.log("trace_rtt", worker="w0", rtt_s=0.001)
    spans = trace_cli.load_spans([path])
    assert len(spans) == 1              # trace_rtt is not a span
    assert spans[0]["name"] == "request"
    records = trace_cli.load_records([path])
    assert {r["event"] for r in records} == {"trace_span",
                                             "trace_rtt"}


# ------------------------------------------------------------------ #
# merge/render units (synthetic spans)
# ------------------------------------------------------------------ #
def _span(ctx, name, t0, t1, **attrs):
    return {"event": "trace_span", "t": t1,
            "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            "parent_span_id": ctx.parent_span_id, "name": name,
            "t_start": t0, "t_end": t1, "elapsed_s": t1 - t0,
            "ok": True, "service": attrs.pop("service", None),
            **attrs}


def _synthetic_trace(requeue=False):
    """root [0, 10] with hops covering [0, 9.5] (union)."""
    root = new_trace()
    spans = [_span(root, "request", 0.0, 10.0, outcome="ok")]
    a, b, c = root.child(), root.child(), root.child()
    spans.append(_span(a, "route", 0.0, 0.5))
    # Overlapping with route — the union must count [0, 4] once.
    spans.append(_span(b, "queue_wait", 0.0, 4.0))
    spans.append(_span(c, "dispatch", 4.0, 9.5, bucket=4,
                       compiled=False, worker="w1"))
    spans.append(_span(c.child(), "adam_segments", 4.0, 9.0))
    if requeue:
        spans.append(_span(root.child(), "requeue", 1.0, 3.0,
                           from_worker="w0", to_worker="w1",
                           reason="worker w0 lost",
                           bundle="/tmp/b.json",
                           outcome="redispatched"))
    return root, spans


def test_trace_summary_complete_and_union_coverage():
    root, spans = _synthetic_trace()
    summary = trace_cli.trace_summary(root.trace_id, spans)
    assert summary["complete"] is True
    assert summary["orphans"] == []
    assert summary["elapsed_s"] == pytest.approx(10.0)
    assert summary["outcome"] == "ok"
    # Union, not sum: route ⊂ queue_wait, adam ⊂ dispatch — the
    # covered window is [0, 9.5] of [0, 10].
    assert summary["coverage"] == pytest.approx(0.95)
    assert summary["hops"]["dispatch"] == pytest.approx(5.5)
    assert summary["requeues"] == []


def test_trace_summary_flags_orphans_and_multiroot():
    root, spans = _synthetic_trace()
    stray = TraceContext(root.trace_id, "feedfeedfeedfeed",
                         "0000000000000000")   # unresolvable parent
    incomplete = spans + [_span(stray, "dispatch", 1.0, 2.0)]
    summary = trace_cli.trace_summary(root.trace_id, incomplete)
    assert summary["complete"] is False
    assert summary["orphans"] == ["feedfeedfeedfeed"]
    two_roots = spans + [_span(new_trace(), "request", 0.0, 1.0)]
    assert trace_cli.trace_summary(root.trace_id,
                                   two_roots)["complete"] is False


def test_requeue_waterfall_names_both_generations():
    root, spans = _synthetic_trace(requeue=True)
    summary = trace_cli.trace_summary(root.trace_id, spans)
    assert summary["requeues"] == [{"from": "w0", "to": "w1",
                                    "reason": "worker w0 lost",
                                    "bundle": "/tmp/b.json"}]
    text = trace_cli.render_waterfall(root.trace_id, spans)
    # The exact grep target of the chaos CI smoke.
    assert "requeue w0->w1" in text
    assert "1 requeue(s)" in text
    # Nesting renders: adam_segments is indented under dispatch.
    dispatch = next(ln for ln in text.splitlines()
                    if "dispatch" in ln)
    adam = next(ln for ln in text.splitlines()
                if "adam_segments" in ln)
    assert "K=4" in dispatch and "cached" in dispatch
    assert adam.index("adam_segments") \
        > dispatch.index("dispatch")


def test_trace_cli_end_to_end(tmp_path, capsys):
    # Two per-process files, two traces (one requeued) — exactly
    # what a router + worker pair leaves behind.
    r1, s1 = _synthetic_trace(requeue=True)
    r2, s2 = _synthetic_trace()
    router_file, worker_file = (str(tmp_path / "router.jsonl"),
                                str(tmp_path / "w0.jsonl"))
    with open(router_file, "w") as f:
        for s in s1:
            f.write(json.dumps(s) + "\n")
        f.write(json.dumps({"event": "trace_rtt", "t": 0.0,
                            "worker": "w0", "rtt_s": 0.002}) + "\n")
        f.write("{torn tail line\n")    # SIGKILL leftovers parse past
    with open(worker_file, "w") as f:
        for s in s2:
            f.write(json.dumps(s) + "\n")

    assert trace_cli.main([router_file, worker_file]) == 0
    out = capsys.readouterr().out
    assert "2 traces over 2 file(s): 1 with requeue hops, " \
           "0 incomplete" in out
    assert "rpc rtt median 2.00ms" in out
    assert "requeue w0->w1" in out      # slowest waterfall rendered

    assert trace_cli.main([router_file, worker_file, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["n_traces"] == 2
    assert payload["rpc_rtt"]["n"] == 1
    by_id = {t["trace_id"]: t for t in payload["traces"]}
    assert by_id[r1.trace_id]["complete"] is True
    assert len(by_id[r2.trace_id]["spans"]) == len(s2)

    # --trace prefix match; ambiguous/absent prefixes are errors.
    assert trace_cli.main([router_file, worker_file,
                           "--trace", r2.trace_id[:10]]) == 0
    assert r2.trace_id[:12] in capsys.readouterr().out
    assert trace_cli.main([router_file, worker_file,
                           "--trace", "zz"]) == 1
    capsys.readouterr()


def test_merge_traces_groups_by_trace_id(tmp_path):
    from multigrad_tpu.telemetry.aggregate import merge_traces
    r1, s1 = _synthetic_trace()
    r2, s2 = _synthetic_trace()
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    # The same trace's spans split across both process files — the
    # merge is exactly the cross-process reassembly.
    with open(p1, "w") as f:
        for s in s1[:2] + s2[3:]:
            f.write(json.dumps(s) + "\n")
    with open(p2, "w") as f:
        for s in s1[2:] + s2[:3]:
            f.write(json.dumps(s) + "\n")
    merged = merge_traces([p1, p2])
    assert set(merged) == {r1.trace_id, r2.trace_id}
    assert len(merged[r1.trace_id]) == len(s1)
    assert merged[r1.trace_id][0]["name"] == "request"  # root-first


# ------------------------------------------------------------------ #
# latency histograms: labels, quantiles, exemplars
# ------------------------------------------------------------------ #
def test_histogram_quantiles_and_exemplars():
    from multigrad_tpu.telemetry import LiveMetrics
    m = LiveMetrics()
    for i, v in enumerate([0.01, 0.02, 0.03, 0.04, 0.05,
                           0.06, 0.07, 0.08, 0.09, 2.0]):
        m.observe("lat", v, exemplar=f"trace{i}")
    p50, p95, p99 = (m.quantile("lat", q)
                     for q in (0.5, 0.95, 0.99))
    assert 0.02 <= p50 <= 0.08
    assert p50 <= p95 <= p99 <= 2.0
    # The exemplar is the slowest observation's id — the trace a
    # tail-latency alarm links to.
    assert m.exemplar("lat") == "trace9"
    assert m.histogram_stats("lat") == {
        "count": 10, "sum": pytest.approx(2.45), "max": 2.0}
    # Labeled series are independent; label_sets discovers them.
    m.observe("hop", 0.1, labels={"hop": "dispatch"}, exemplar="tA")
    m.observe("hop", 0.2, labels={"hop": "queue_wait"})
    assert sorted(ls["hop"] for ls in m.label_sets("hop")) \
        == ["dispatch", "queue_wait"]
    assert m.exemplar("hop", labels={"hop": "dispatch"}) == "tA"
    assert m.quantile("lat", 0.5, labels={"hop": "absent"}) is None
    # Labeled buckets render per-series in the text exposition.
    text = m.render()
    assert 'hop_bucket{hop="dispatch",le="+Inf"} 1' in text
    assert 'hop_sum{hop="dispatch"}' in text
    # An un-exemplared new maximum clears the max slot (a stale
    # smaller observation's id must not pose as the worst trace);
    # exemplar() falls back to the slowest exemplared bucket.
    m.observe("lat", 9.0)
    h = next(iter(m.snapshot()["lat"]["samples"].values()))
    assert h["max"] == 9.0 and h["max_exemplar"] is None
    assert m.exemplar("lat") == "trace9"


def test_gauge_replace_drops_stale_label_series():
    from multigrad_tpu.telemetry import LiveMetrics
    m = LiveMetrics()
    m.set("slowest", 1.0, labels={"trace_id": "aaa"}, replace=True)
    m.set("slowest", 2.0, labels={"trace_id": "bbb"}, replace=True)
    snap = m.snapshot()["slowest"]["samples"]
    # The superseded trace's series is gone — the exposition cannot
    # grow one series per slow fit ever seen.
    assert list(snap) == ['{trace_id="bbb"}']


# ------------------------------------------------------------------ #
# wire forward compatibility (mixed-version fleet)
# ------------------------------------------------------------------ #
def test_config_from_wire_ignores_unknown_fields():
    from multigrad_tpu.serve.queue import FitConfig
    from multigrad_tpu.serve.wire import (config_from_wire,
                                          config_to_wire)
    cfg = FitConfig(nsteps=7, learning_rate=0.05, randkey=3,
                    param_bounds=((-3.0, 0.0), None))
    decorated = {**config_to_wire(cfg),
                 "compression": "zstd",        # fields from the
                 "priority": 9,                # future
                 "trace_level": "verbose"}
    assert config_from_wire(decorated) == cfg


def test_result_codec_tolerates_both_directions():
    from multigrad_tpu.serve.queue import FitResult
    from multigrad_tpu.serve.wire import (result_from_wire,
                                          result_to_wire)
    result = FitResult(request_id="r1",
                       params=np.array([1.0, 2.0]), loss=0.5,
                       traj=np.zeros((3, 2)), steps=2, bucket=4,
                       wait_s=0.1, fit_s=0.2,
                       trace_id="a" * 32,
                       hops={"dispatch": 0.2})
    # Decorated worker -> decorated router: trace fields survive.
    back = result_from_wire(result_to_wire(result), "r1", worker="w0")
    assert back.trace_id == result.trace_id
    assert back.hops == {"dispatch": 0.2}
    # Undecorated (pre-tracing) worker -> decorated router: absent
    # trace fields decode to None, nothing raises.
    legacy = {k: v for k, v in result_to_wire(result).items()
              if k not in ("trace_id", "hops")}
    back = result_from_wire(legacy, "r1")
    assert back.trace_id is None and back.hops is None
    # Future worker -> this router: unknown keys (and a non-dict
    # hops encoding) are ignored, not fatal.
    future_wire = {**result_to_wire(result), "gpu_seconds": 1.0,
                   "hops": "opaque-v9-blob"}
    assert result_from_wire(future_wire, "r1").hops is None


class FakeChan:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass


@pytest.fixture()
def fake_traced_fleet(tmp_path):
    from multigrad_tpu.serve import FleetRouter
    from multigrad_tpu.serve.fleet import WorkerHandle
    router = FleetRouter(n_workers=0, base_dir=str(tmp_path),
                         compile_cache=None,
                         heartbeat_timeout_s=1e6)
    handle = WorkerHandle("w0", chan=FakeChan())
    router.workers.append(handle)
    yield router, handle
    router.close(drain=False, timeout=0)


def test_submit_message_carries_traceparent(fake_traced_fleet):
    router, handle = fake_traced_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    msg = handle.chan.sent[0]
    assert fut.trace_id is not None
    ctx = TraceContext.from_wire(msg["trace"])
    assert ctx.trace_id == fut.trace_id
    # An undecorated worker's handler reads known keys only — the
    # trace field must be droppable without touching the fit
    # payload (this is the other half of the contract, pinned here
    # as "the decoration is strictly additive").
    undecorated = {k: v for k, v in msg.items() if k != "trace"}
    assert set(undecorated) == {"op", "rid", "guess", "config",
                                "deadline_t", "retried",
                                "submitted_t"}


def test_undecorated_worker_result_still_traced(fake_traced_fleet):
    # A pre-tracing worker answers with no trace fields, no sent_t,
    # plus an unknown key: the router must settle the future, keep
    # its own hops, and close the trace.
    router, handle = fake_traced_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    rid = handle.chan.sent[0]["rid"]
    router._on_result(handle, {
        "rid": rid, "some_future_field": {"x": 1},
        "result": {"params": [1.0, 2.0], "loss": 0.25,
                   "traj": [[0.0, 0.0]], "steps": 5, "bucket": 1,
                   "wait_s": 0.0, "fit_s": 0.1}})
    result = fut.result(timeout=5)
    assert result.trace_id == fut.trace_id    # router backfills
    # No result_return hop: the legacy result carried no sent_t to
    # anchor it — the router records only what it measured itself.
    assert set(result.hops) == {"route", "rpc_send"}
    router.close(drain=False, timeout=0)
    spans = trace_cli.load_spans(router.trace_paths)
    summary = trace_cli.trace_summary(fut.trace_id, spans)
    assert summary["complete"] is True
    assert summary["outcome"] == "ok"


def test_requeue_without_survivor_records_truthful_span(
        fake_traced_fleet):
    # The last worker dies: the requeue cannot redispatch and the
    # request settles WorkerLostError — the requeue span must say
    # so, not claim 'redispatched' onto the dead worker.
    from multigrad_tpu.serve import WorkerLostError
    router, handle = fake_traced_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    router._worker_lost(handle, "test kill")
    assert isinstance(fut.exception(timeout=5), WorkerLostError)
    router.close(drain=False, timeout=0)
    spans = trace_cli.load_spans(router.trace_paths)
    requeue = next(s for s in spans if s["name"] == "requeue")
    assert requeue["outcome"] == "not_redispatched"
    assert requeue["to_worker"] is None
    summary = trace_cli.trace_summary(fut.trace_id, [
        s for s in spans if s["trace_id"] == fut.trace_id])
    assert summary["complete"] is True
    assert summary["outcome"] == "lost"


def test_pong_without_t0_is_ignored(fake_traced_fleet):
    # Old workers echo pings without the t0 RTT field.
    router, handle = fake_traced_fleet
    router._on_pong(handle, {"worker": "w0", "unknown": True})
    assert handle.rpc_rtt_s is None
    import time
    router._on_pong(handle, {"worker": "w0",
                             "t0": time.time() - 0.01})
    assert handle.rpc_rtt_s == pytest.approx(0.01, abs=0.25)


# ------------------------------------------------------------------ #
# single-process scheduler tracing, end to end
# ------------------------------------------------------------------ #
HOPS = ("queue_wait", "bucket_coalesce", "dispatch",
        "adam_segments", "finalize")


def test_scheduler_traces_served_fits():
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler
    from multigrad_tpu.telemetry.live import LiveSink

    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)
    tracer = Tracer(service="sched")
    live = LiveSink()
    with FitScheduler(model, buckets=(1, 4), batch_window_s=0.0,
                      tracer=tracer, live=live,
                      start=False) as sched:
        # Queue the whole burst first: one deterministic bucket-4
        # coalesce, every dispatch span flagged compiled=True.
        futs = [sched.submit([-1.9 - 0.01 * i, 0.5], nsteps=5)
                for i in range(3)]
        sched.start()
        results = [f.result(timeout=240) for f in futs]
        # Second round re-uses program identities already dispatched
        # — whatever windows it lands in, some span must be flagged
        # cached.
        futs2 = [sched.submit([-1.8 - 0.01 * i, 0.5], nsteps=5)
                 for i in range(3)]
        [f.result(timeout=240) for f in futs2]

    for fut, result in zip(futs, results):
        # The mint point: submit stamped the future, the result
        # carries the same id and the full hop vector.
        assert fut.trace_id is not None
        assert result.trace_id == fut.trace_id
        assert set(result.hops) >= set(HOPS)
        assert result.hops["queue_wait"] \
            == pytest.approx(result.wait_s, abs=0.05)

    traces = trace_cli.group_traces(list(tracer.records))
    assert set(traces) == {f.trace_id for f in futs + futs2}
    for fut in futs:
        summary = trace_cli.trace_summary(fut.trace_id,
                                          traces[fut.trace_id])
        # Complete parent-linked waterfall covering >= 90% of the
        # request's end-to-end latency (the acceptance bar).
        assert summary["complete"] is True
        assert summary["outcome"] == "ok"
        assert summary["coverage"] >= 0.9
        assert set(summary["hops"]) >= set(HOPS)
    # compile-vs-cached is flagged on dispatch spans: the first
    # dispatch of each program identity compiled; any later window
    # at an already-seen (config, ndim, bucket) is flagged cached —
    # and per bucket the flag is monotone (never compiled again).
    dispatches = sorted((r for r in tracer.records
                         if r["name"] == "dispatch"),
                        key=lambda r: r["t_start"])
    assert all(isinstance(r["compiled"], bool) for r in dispatches)
    round1 = {f.trace_id for f in futs}
    assert all(r["compiled"] for r in dispatches
               if r["trace_id"] in round1)
    assert any(not r["compiled"] for r in dispatches
               if r["trace_id"] not in round1)
    seen_cached = set()
    for r in dispatches:
        if r["compiled"]:
            assert r["bucket"] not in seen_cached
        else:
            seen_cached.add(r["bucket"])

    # The /status latency section: quantiles + exemplar trace ids,
    # per hop too.
    latency = live.latency_summary()
    assert latency["source"] == "multigrad_serve_fit_latency_seconds"
    assert latency["count"] == 6
    assert 0 < latency["p50_s"] <= latency["p95_s"] \
        <= latency["p99_s"] <= latency["max_s"]
    assert latency["exemplar_trace"] in {f.trace_id for f in futs}
    assert set(latency["hops"]) >= set(HOPS)
    assert latency["hops"]["dispatch"]["exemplar_trace"] \
        in {f.trace_id for f in futs}
    status = live.status()
    assert status["latency"]["p99_s"] == latency["p99_s"]


def test_scheduler_failed_fit_trace_names_bundle(tmp_path):
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler
    from multigrad_tpu.serve.queue import FitFailed

    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)
    tracer = Tracer(service="sched")
    with FitScheduler(model, buckets=(1,), batch_window_s=0.0,
                      tracer=tracer, retry_poisoned=False,
                      flight_dir=str(tmp_path)) as sched:
        fut = sched.submit([np.nan, 0.5], nsteps=5)
        exc = fut.exception(timeout=240)
    assert isinstance(exc, FitFailed)
    root = next(r for r in tracer.records if r["name"] == "request")
    # Navigable from either end: the trace root names the postmortem
    # bundle, the bundle names the trace.
    assert root["outcome"] == "failed"
    assert root["bundle"] == exc.bundle_path
    with open(exc.bundle_path) as f:
        assert json.load(f)["detail"]["trace_id"] == fut.trace_id
