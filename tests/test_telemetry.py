"""Telemetry subsystem: taps, comm accounting, sinks, report, spans.

The load-bearing assertions:

* in-graph taps emit exactly ``nsteps // log_every`` records from
  inside a jitted ``lax.scan`` on the multi-device CPU mesh, with
  ZERO extra traces vs. taps disabled (the no-retrace contract);
* the collective counter reproduces the paper's communication claim —
  ``(|sumstats| + |params|) · itemsize`` bytes per loss-and-grad step,
  *independent of catalog size* — for both the resident and the
  streamed SMF model (the acceptance criterion's two-catalog check);
* the report CLI round-trips a JSONL stream written by MetricsLogger.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu import telemetry
from multigrad_tpu.data import StreamingOnePointModel
from multigrad_tpu.models.smf import (ParamTuple, SMFChi2Model, SMFModel,
                                      load_halo_masses, make_smf_data)
from multigrad_tpu.optim.adam import run_adam_scan
from multigrad_tpu.telemetry import report as report_mod
from multigrad_tpu.utils import profiling

N_DEV = len(jax.devices())
F32 = np.dtype(np.float32).itemsize
N_BINS = 10          # SMF sumstats size
N_PARAMS = 2


def drain():
    """Flush in-flight (unordered) debug callbacks before asserting."""
    jax.effects_barrier()


def new_logger(*extra_sinks, **kwargs):
    sink = telemetry.MemorySink()
    return telemetry.MetricsLogger(sink, *extra_sinks, **kwargs), sink


def events(sink, name):
    return [r for r in sink.records if r["event"] == name]


# ------------------------------------------------------------------ #
# Metrics sinks + run record
# ------------------------------------------------------------------ #
def test_run_record_provenance_and_digest():
    rec = telemetry.run_record({"lr": 0.01, "n": 4})
    assert rec["event"] == "run"
    assert rec["jax_version"] == jax.__version__
    assert rec["backend"] == "cpu"
    assert rec["device_count"] == N_DEV
    # digest is order-invariant and value-sensitive
    assert rec["config_digest"] == telemetry.config_digest(
        {"n": 4, "lr": 0.01})
    assert rec["config_digest"] != telemetry.config_digest(
        {"n": 5, "lr": 0.01})


def test_sinks_roundtrip(tmp_path):
    path = tmp_path / "run.jsonl"
    csv_path = tmp_path / "run.csv"
    logger, sink = new_logger(
        telemetry.JsonlSink(str(path)),
        telemetry.CsvSink(str(csv_path), fields=["event", "step", "x"]),
        run_config={"seed": 1})
    logger.log("adam", step=0, x=1.5)
    logger.log("adam", step=5, x=0.5)
    logger.close()
    # memory ring buffer: run header first, then the records
    assert [r["event"] for r in sink.records] == ["run", "adam", "adam"]
    # jsonl: parseable, same stream
    lines = [json.loads(line) for line in
             path.read_text().strip().splitlines()]
    assert [r["event"] for r in lines] == ["run", "adam", "adam"]
    assert lines[0]["config"] == {"seed": 1}
    # csv: projected onto the pinned columns
    rows = csv_path.read_text().strip().splitlines()
    assert rows[0] == "event,step,x"
    assert rows[-1].startswith("adam,5,")


def test_memory_sink_is_a_ring_buffer():
    sink = telemetry.MemorySink(capacity=3)
    logger = telemetry.MetricsLogger(sink)
    for i in range(10):
        logger.log("x", i=i)
    assert len(sink.records) == 3
    assert [r["i"] for r in sink.records] == [7, 8, 9]


# ------------------------------------------------------------------ #
# In-graph taps (the tentpole's no-retrace contract)
# ------------------------------------------------------------------ #
def test_tap_emits_exact_count_with_zero_extra_traces():
    target = jnp.array([1.0, -2.0])
    traces = []

    def loss_and_grad(p, _key):
        traces.append(1)          # increments once per (re)trace
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    # Baseline: taps disabled.
    run_adam_scan(loss_and_grad, jnp.zeros(2), nsteps=20,
                  learning_rate=0.1)
    baseline_traces = len(traces)

    logger, sink = new_logger()
    traces.clear()
    run_adam_scan(loss_and_grad, jnp.zeros(2), nsteps=20,
                  learning_rate=0.1, telemetry=logger, log_every=5)
    drain()
    # exactly nsteps // log_every records, steps 0/5/10/15
    recs = events(sink, "adam")
    assert len(recs) == 20 // 5
    assert [r["step"] for r in recs] == [0, 5, 10, 15]
    for r in recs:
        assert {"loss", "grad_norm", "param_norm",
                "update_norm"} <= set(r)
    # loss decreased across the tapped window
    assert recs[-1]["loss"] < recs[0]["loss"]
    # enabling the tap traced the program the same number of times as
    # the untapped build — and a SECOND fit through the same tap hits
    # the program cache: zero additional traces.
    assert len(traces) == baseline_traces
    run_adam_scan(loss_and_grad, jnp.ones(2), nsteps=20,
                  learning_rate=0.1, telemetry=logger, log_every=5)
    drain()
    assert len(traces) == baseline_traces
    assert len(events(sink, "adam")) == 2 * (20 // 5)


def test_tap_cache_keeps_one_variant_per_logger():
    # A tap's program-cache key embeds its logger; fresh loggers per
    # fit must EVICT the predecessor's program, not accumulate one
    # compiled executable (pinning a closed logger) per fit.
    def loss_and_grad(p, _key):
        return jnp.sum(p ** 2), 2.0 * p

    def tapped_entries():
        # 8 = the 7-element base key (incl. the donate flag) + tap.
        return [k for k in loss_and_grad._mgt_program_cache
                if len(k[1]) == 8 and k[1][0] == "adam_segment"]

    for _ in range(3):
        logger, _sink = new_logger()
        run_adam_scan(loss_and_grad, jnp.ones(2), nsteps=5,
                      telemetry=logger, log_every=2)
        logger.close()
    drain()
    assert len(tapped_entries()) == 1
    # the untapped program (if any) is untouched by eviction
    run_adam_scan(loss_and_grad, jnp.ones(2), nsteps=5)
    assert len(tapped_entries()) == 1


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_tap_on_multidevice_mesh_one_record_per_step():
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(4096, comm=comm), comm=comm)
    logger, sink = new_logger()
    model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=20,
                   progress=False, telemetry=logger, log_every=5)
    drain()
    recs = events(sink, "adam")
    # one record per tapped step — the callback fires once (the tap
    # lives outside the shard_map block, values replicated), never
    # once per device
    assert [r["step"] for r in recs] == [0, 5, 10, 15]
    # the comm record rode along (model.run_adam emits it up front)
    comm_recs = events(sink, "comm")
    assert len(comm_recs) == 1
    assert comm_recs[0]["bytes_per_step"] == (N_BINS + N_PARAMS) * F32


def test_tap_checkpointed_drive_numbers_steps_globally(tmp_path):
    target = jnp.array([0.5])

    def loss_and_grad(p, _key):
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    logger, sink = new_logger()
    run_adam_scan(loss_and_grad, jnp.zeros(1), nsteps=12,
                  learning_rate=0.1, telemetry=logger, log_every=4,
                  checkpoint_dir=str(tmp_path), checkpoint_every=3)
    drain()
    # segments of 3 steps; the tap sees global step numbers across
    # segment boundaries
    assert [r["step"] for r in events(sink, "adam")] == [0, 4, 8]
    # checkpoint saves recorded as spans
    ckpt_spans = [r for r in events(sink, "span")
                  if r["name"] == "checkpoint"]
    assert len(ckpt_spans) == 4  # 12 steps / checkpoint_every=3


# ------------------------------------------------------------------ #
# Comm accounting (the paper's claim, measured)
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
@pytest.mark.parametrize("n_halos", [4096, 16384])
def test_comm_counter_matches_hand_computed_bytes(n_halos):
    # loss_and_grad = psum(y) + psum(grad): (|y| + |params|) * 4 bytes,
    # independent of the catalog size (the paper's claim).
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(n_halos, comm=comm),
                     comm=comm)
    cc = telemetry.measure_model_comm(model, jnp.array([-1.0, 0.5]))
    assert cc.total_bytes == (N_BINS + N_PARAMS) * F32
    assert cc.total_calls == 2
    assert set(cc.calls) == {"psum"}


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_comm_counter_unwraps_vmap_batch():
    # Collectives inside jax.vmap move the BATCHED payload; the
    # counter must not read the unbatched tracer shape.
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(2048, comm=comm), comm=comm)
    # 3 parameter vectors through the vmapped fused kernel: 3x the
    # solo traffic.
    cc = telemetry.measure_model_comm(
        model, jnp.tile(jnp.array([-1.0, 0.5]), (3, 1)),
        kind="batched_loss_and_grad")
    assert cc.total_bytes == 3 * (N_BINS + N_PARAMS) * F32
    # Reverse-mode Jacobian: psum(y) + one vmapped |params|-row psum
    # per sumstat = |y| + |y|*|params| floats.
    cc = telemetry.measure_model_comm(
        model, jnp.array([-1.0, 0.5]), kind="sumstats_jac_rev")
    assert cc.total_bytes == (N_BINS + N_BINS * N_PARAMS) * F32


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_comm_counter_single_device_is_zero():
    model = SMFModel(aux_data=make_smf_data(2048, comm=None), comm=None)
    cc = telemetry.measure_model_comm(model, jnp.array([-1.0, 0.5]))
    assert cc.total_bytes == 0 and cc.total_calls == 0


def _streamed_smf(n_halos, chunk_rows, comm):
    log_mh = np.asarray(jnp.log10(load_halo_masses(n_halos)))
    aux = make_smf_data(n_halos, comm=None)
    del aux["log_halo_masses"]
    return StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm),
        streams={"log_halo_masses": log_mh}, chunk_rows=chunk_rows)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_streamed_comm_bytes_independent_of_catalog_size():
    # Two catalog sizes, same chunk COUNT: per-chunk traffic is
    # (|y| + |params|) floats regardless of rows-per-chunk, so
    # bytes/step is identical although the catalogs differ 4x — the
    # acceptance criterion's two-catalog check.
    comm = mgt.global_comm()
    small = _streamed_smf(8192, 2048, comm)
    large = _streamed_smf(32768, 8192, comm)
    p = jnp.array([-1.0, 0.5])
    c_small = small.measure_comm(p)
    c_large = large.measure_comm(p)
    assert c_small["n_chunks"] == c_large["n_chunks"] == 4
    assert c_small["bytes_per_chunk"] == c_large["bytes_per_chunk"] \
        == (N_BINS + N_PARAMS) * F32
    assert c_small["bytes_per_step"] == c_large["bytes_per_step"]
    # scan path: the psums fire once per step, after in-scan
    # accumulation — chunk count drops out entirely
    c_scan = small.measure_comm(p, use_scan=True)
    assert c_scan["bytes_per_step"] == (N_BINS + N_PARAMS) * F32


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_streamed_fit_emits_full_telemetry(tmp_path):
    path = tmp_path / "stream.jsonl"
    sm = _streamed_smf(8192, 2048, mgt.global_comm())
    logger, sink = new_logger(telemetry.JsonlSink(str(path)))
    sm.run_adam(guess=jnp.array([-1.0, 0.5]), nsteps=4,
                progress=False, telemetry=logger, log_every=2)
    logger.close()
    assert [r["step"] for r in events(sink, "adam")] == [0, 2]
    assert len(events(sink, "comm")) == 1
    stream_recs = events(sink, "stream")
    assert len(stream_recs) == 1
    assert stream_recs[0]["max_live_buffers"] <= 2
    fit_spans = [r for r in events(sink, "span") if r["name"] == "fit"]
    assert len(fit_spans) == 1 and fit_spans[0]["ok"]
    summary = events(sink, "fit_summary")[0]
    assert summary["steps"] == 4
    assert np.isfinite(summary["final_loss"])
    # the JSONL twin carries the identical stream
    assert len(report_mod.load_records(str(path))) == len(sink.records)


# ------------------------------------------------------------------ #
# HMC taps
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_hmc_taps_emit_windowed_records():
    comm = mgt.global_comm()
    model = SMFChi2Model(aux_data=make_smf_data(4096, comm=comm),
                         comm=comm)
    logger, sink = new_logger()
    res = mgt.run_hmc(model, jnp.array([-2.0, 0.2]), num_samples=30,
                      num_warmup=15, num_chains=2, num_leapfrog=4,
                      telemetry=logger, log_every=10, randkey=3)
    drain()
    recs = events(sink, "hmc")
    # windows close at draws 10/20/30 — num_samples // log_every of
    # them, ONE record each (shard-0 gated, not once per device)
    assert [r["step"] for r in recs] == [10, 20, 30]
    for r in recs:
        assert 0.0 <= min(1.0, float(np.mean(r["accept"])))
        assert len(r["step_size"]) == 2            # per chain
        assert r["divergences"] >= 0
    # cumulative divergence count agrees with the result's total
    assert recs[-1]["divergences"] == int(np.sum(res.divergences))


# ------------------------------------------------------------------ #
# Spans + heartbeat
# ------------------------------------------------------------------ #
def test_spans_nest_and_record_failures():
    logger, sink = new_logger()
    with telemetry.span(logger, "outer"):
        with telemetry.span(logger, "inner"):
            pass
    with pytest.raises(RuntimeError):
        with telemetry.span(logger, "broken"):
            raise RuntimeError("boom")
    spans = events(sink, "span")
    assert [(r["path"], r["depth"], r["ok"]) for r in spans] == [
        ("outer/inner", 1, True), ("outer", 0, True),
        ("broken", 0, False)]
    # logger=None is a no-op context
    with telemetry.span(None, "ignored"):
        pass


def test_heartbeat_detects_stall_and_recovery():
    logger, sink = new_logger()
    with telemetry.Heartbeat(logger, interval=0.05,
                             stall_after=0.12) as hb:
        hb.tick(1)
        time.sleep(0.3)            # silent: stall fires
        hb.tick(2)                 # progress: recovery fires
        time.sleep(0.12)
    beats = events(sink, "heartbeat")
    stalls = events(sink, "stall")
    assert beats and beats[0]["process"] == 0
    assert len(stalls) == 1        # one record per episode, not per beat
    assert stalls[0]["stalled_s"] > 0.12
    assert len(events(sink, "stall_recovered")) == 1


# ------------------------------------------------------------------ #
# Report CLI
# ------------------------------------------------------------------ #
def test_report_cli_roundtrip(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    logger = telemetry.MetricsLogger(telemetry.JsonlSink(path),
                                     run_config={"demo": True})
    logger.log("adam", step=0, loss=4.0, grad_norm=1.0)
    time.sleep(0.01)
    logger.log("adam", step=100, loss=0.25, grad_norm=0.1)
    logger.log("comm", bytes_per_step=48, calls_per_step=2,
               bytes_by_op={"psum": 48})
    logger.log("stream", stall_fraction=0.01, chunks_per_sec=12.0,
               bytes_streamed=1 << 20, max_live_buffers=2,
               overlap_frac=0.97,
               passes={"vjp": {"stall_fraction": 0.02,
                               "overlap_frac": 0.97, "chunks": 4,
                               "bytes_streamed": 1 << 19}})
    logger.log("fit_summary", steps=100, steps_per_sec=20.0,
               final_loss=0.25, overlap_frac=0.97,
               pass_overlap={"sumstats": 0.95, "vjp": 0.97})
    logger.log("hmc", step=50, accept=0.87, divergences=1,
               step_size=[0.1, 0.2])
    logger.log("stall", stalled_s=2.5)
    logger.close()

    assert report_mod.main([path]) == 0
    out = capsys.readouterr().out
    assert "backend=cpu" in out
    assert "4 -> 0.25" in out
    assert "48 bytes/step" in out
    assert "stall_fraction=0.01" in out
    assert "divergences=1" in out
    assert "1 stalls" in out
    # the PR-7 streaming records are surfaced, not dropped: overlap
    # on the fit line, per-pass splits under the stream line
    assert "overlap_frac=0.97" in out
    assert "pass overlap: sumstats=0.95  vjp=0.97" in out
    assert "pass vjp:" in out
    # machine-readable mode round-trips as JSON
    assert report_mod.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["fit"]["final_loss"] == 0.25
    assert summary["comm"]["bytes_per_step"] == 48
    assert summary["fit"]["steps_per_sec"] > 0
    assert summary["fit"]["pass_overlap"]["vjp"] == 0.97
    assert summary["stream"]["passes"]["vjp"]["chunks"] == 4
    # truncated tail (crashed writer) must not kill the report
    with open(path, "a") as f:
        f.write('{"event": "adam", "step"')
    assert report_mod.main([path]) == 0
    capsys.readouterr()

    # a reused path appends a second run: the report must summarize
    # the LAST run, not stitch the two fit curves together
    logger2 = telemetry.MetricsLogger(telemetry.JsonlSink(path))
    logger2.log("adam", step=0, loss=9.0)
    logger2.log("adam", step=10, loss=8.0)
    logger2.close()
    summary = report_mod.summarize(report_mod.load_records(path))
    assert summary["runs_in_file"] == 2
    assert summary["fit"]["first_loss"] == 9.0
    assert summary["fit"]["final_loss"] == 8.0
    assert "comm" not in summary          # run 1's records excluded
    assert report_mod.main([path]) == 0
    assert "holds 2 runs" in capsys.readouterr().out


# ------------------------------------------------------------------ #
# Satellites: Timer percentiles, StepsPerSecond reset, bench records
# ------------------------------------------------------------------ #
def test_timer_records_percentiles():
    timer = profiling.Timer(jax.jit(lambda x: x + 1.0), warmup=1)
    out = timer(8, jnp.zeros(()))
    assert 0.0 < out["p50"] <= out["p95"]
    assert len(out["latencies"]) == 8
    # the aggregate keys are still there (old contract)
    assert out["n_calls"] == 8 and out["calls_per_sec"] > 0


def test_steps_per_second_reset_drops_warmup():
    meter = profiling.StepsPerSecond()
    meter.tick()                   # "compile" step
    time.sleep(0.2)
    meter.reset()
    assert meter.rate == 0.0 and meter.steps == 0
    meter.tick()
    time.sleep(0.01)
    meter.tick(4)
    # without the reset the 0.2 s warm-up would cap the rate at ~30/s
    assert meter.rate > 100.0


def test_bench_partial_records_provenance(tmp_path, monkeypatch):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    monkeypatch.setattr(bench, "PARTIAL_TEMPLATE",
                        str(tmp_path / "partial.{backend}.json"))
    now = time.time()
    bench.save_partial("cpu", {"smf_1e6_xla_steps_per_sec": 20.0},
                       {"smf_1e6_xla_steps_per_sec": now})
    saved = json.loads((tmp_path / "partial.cpu.json").read_text())
    prov = saved["provenance"]
    assert prov["jax_version"] == jax.__version__
    assert prov["device_kind"] == "cpu"
    # the stamp must not disturb the resume contract
    loaded, _ = bench.load_partial("cpu")
    assert loaded == {"smf_1e6_xla_steps_per_sec": 20.0}
