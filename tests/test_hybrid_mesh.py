"""Two-axis (hybrid ICI/DCN) mesh support.

The reference's node-aware topology is ``split_subcomms_by_node``
(``/root/reference/multigrad/multigrad.py:48-85``): collectives that
respect the host/interconnect hierarchy.  The TPU-native analog is a
two-axis mesh — ``("hosts", "data")`` — where the model's psums reduce
over both axes as one collective that XLA lowers hierarchically (ICI
inside a host group, DCN across).  These tests run a (2, 4) virtual
mesh: 8 CPU devices standing in for 2 hosts x 4 chips.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import (TARGET_SUMSTATS, ParamTuple,
                                      SMFModel, make_smf_data)

TRUTH = ParamTuple(-2.0, 0.2)


@pytest.fixture(scope="module")
def hybrid_comm_24():
    if len(jax.devices()) < 8:
        pytest.skip("hybrid (2,4) fixtures need 8 devices (conftest "
                    "provides them unless XLA_FLAGS overrides)")
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("hosts", "data"))
    return mgt.MeshComm.from_mesh(mesh, axes=("hosts", "data"))


@pytest.fixture(scope="module")
def hybrid_model(hybrid_comm_24):
    return SMFModel(aux_data=make_smf_data(10_000, comm=hybrid_comm_24),
                    comm=hybrid_comm_24)


def test_from_mesh_properties(hybrid_comm_24):
    comm = hybrid_comm_24
    assert comm.size == 8
    assert comm.axes == ("hosts", "data")
    assert comm.mesh.shape["hosts"] == 2 and comm.mesh.shape["data"] == 4


def test_from_mesh_rejects_unknown_axis():
    n = min(len(jax.devices()), 8)
    devices = np.asarray(jax.devices()[:n]).reshape(1, n)
    mesh = Mesh(devices, ("hosts", "data"))
    with pytest.raises(ValueError, match="not in mesh axes"):
        mgt.MeshComm.from_mesh(mesh, axes=("model",))


def test_scatter_shards_over_both_axes(hybrid_comm_24):
    arr = np.arange(16.0)
    sharded = mgt.scatter_nd(arr, comm=hybrid_comm_24)
    assert sharded.shape == (16,)
    # 8 shards of 2 elements, host-major order.
    shards = sorted(sharded.addressable_shards,
                    key=lambda s: s.index[0].start)
    np.testing.assert_allclose(np.asarray(shards[0].data), [0.0, 1.0])
    np.testing.assert_allclose(np.asarray(shards[-1].data), [14.0, 15.0])


def test_reduce_sum_over_hybrid_comm(hybrid_comm_24):
    # Sharded contribution: shards are summed.
    arr = mgt.scatter_nd(np.ones((8,)), comm=hybrid_comm_24)
    np.testing.assert_allclose(np.asarray(
        mgt.reduce_sum(arr, comm=hybrid_comm_24)), 8.0)
    # Replicated scalar: multiplied by comm.size (MPI Allreduce of
    # identical buffers).
    assert mgt.reduce_sum(1.0, comm=hybrid_comm_24) == 8.0


def test_golden_sumstats_on_hybrid_mesh(hybrid_model):
    # Additivity makes the totals mesh-topology-invariant: the golden
    # vector must match on a (2, 4) mesh exactly as on 1 or 8 devices.
    ss = np.asarray(hybrid_model.calc_sumstats_from_params(TRUTH))
    np.testing.assert_allclose(ss, TARGET_SUMSTATS, rtol=1e-4, atol=1e-8)


def test_loss_and_grad_matches_single_device(hybrid_model):
    p = ParamTuple(-1.7, 0.4)
    loss_h, grad_h = hybrid_model.calc_loss_and_grad_from_params(p)
    clean = SMFModel(aux_data=make_smf_data(10_000, comm=None), comm=None)
    loss_c, grad_c = clean.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(np.asarray(loss_h), np.asarray(loss_c),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_h), np.asarray(grad_c),
                               rtol=1e-4)


def test_adam_fit_on_hybrid_mesh(hybrid_model):
    # The VERDICT gate: a full OnePointModel fit on a two-axis mesh.
    traj = hybrid_model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=300,
                                 learning_rate=0.02, progress=False)
    np.testing.assert_allclose(np.asarray(traj[-1]), [*TRUTH], atol=0.05)


def test_partial_sumstats_stacked_over_shards(hybrid_model):
    partial = hybrid_model.calc_sumstats_from_params(TRUTH, total=False)
    assert partial.shape == (8, 10)
    np.testing.assert_allclose(np.asarray(partial.sum(axis=0)),
                               TARGET_SUMSTATS, rtol=1e-4, atol=1e-8)


def test_single_axis_subcomm_of_hybrid_mesh(hybrid_comm_24):
    # A comm over just the "data" sub-axis: size 4, reduces over ICI
    # only — the building block for per-host-group models.
    sub = mgt.MeshComm.from_mesh(hybrid_comm_24.mesh, axes="data")
    assert sub.size == 4
    assert sub.axis_name == "data"


def test_split_subcomms_of_hybrid_comm(hybrid_comm_24):
    # Splitting a multi-axis comm yields one-axis subcomms named after
    # the parent's innermost (ICI) axis.
    subcomms, n, _ = mgt.split_subcomms(num_groups=2,
                                        comm=hybrid_comm_24)
    assert n == 2
    for sc in subcomms:
        assert sc.size == 4
        assert sc.axis_name == "data"
    by_node, n_nodes, _ = mgt.split_subcomms_by_node(hybrid_comm_24)
    assert n_nodes == 1  # single process owns all virtual devices
    assert by_node[0].axis_name == "data"


def test_from_mesh_rejects_out_of_order_axes(hybrid_comm_24):
    with pytest.raises(ValueError, match="mesh-major order"):
        mgt.MeshComm.from_mesh(hybrid_comm_24.mesh,
                               axes=("data", "hosts"))


def test_hybrid_comm_convenience():
    comm = mgt.hybrid_comm()
    assert comm.size == len(jax.devices())
    assert comm.axes == ("hosts", "data")
    model = SMFModel(aux_data=make_smf_data(4_000, comm=comm), comm=comm)
    ss = np.asarray(model.calc_sumstats_from_params(TRUTH))
    clean = SMFModel(aux_data=make_smf_data(4_000, comm=None), comm=None)
    np.testing.assert_allclose(
        ss, np.asarray(clean.calc_sumstats_from_params(TRUTH)), rtol=1e-4)


# --------------------------------------------------------------------- #
# Ring pair counting over the flattened (hosts, data) axis product
# --------------------------------------------------------------------- #
def test_wprp_ring_shard_invariance_on_hybrid_mesh(hybrid_comm_24):
    # The ppermute ring rides the linearized 2x4 axis product; totals
    # and gradients must match the single-block all-pairs path — the
    # flagship pod workload (BASELINE config 5) shards particles over
    # exactly this kind of hybrid mesh.
    from multigrad_tpu.models.wprp import (WprpModel, WprpParams,
                                           make_wprp_data)
    n, box = 512, 50.0
    single = WprpModel(aux_data=make_wprp_data(n, box, seed=3),
                       comm=None)
    hybrid = WprpModel(
        aux_data=make_wprp_data(n, box, comm=hybrid_comm_24, seed=3),
        comm=hybrid_comm_24)
    assert hybrid.aux_data["ring_axis"] == ("hosts", "data")

    params = WprpParams(-1.95, -0.9)
    np.testing.assert_allclose(
        np.asarray(hybrid.calc_sumstats_from_params(params)),
        np.asarray(single.calc_sumstats_from_params(params)), rtol=2e-4)
    np.testing.assert_allclose(
        np.asarray(hybrid.calc_dloss_dparams(params)),
        np.asarray(single.calc_dloss_dparams(params)),
        rtol=1e-3, atol=1e-6)
