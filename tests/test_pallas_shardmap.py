"""Real ``pallas_call`` under ``shard_map`` (no jnp emulation).

tests/test_pallas.py covers the kernels' math without a mesh; models
on CPU meshes normally route through the jnp emulation for speed.
These tests pass ``interpret=True`` explicitly, which overrides the
emulation (see ``ops.pallas_kernels._use_jnp_emulation``) so the
genuine interpret-mode kernel — and with it the varying-manual-axes
(vma) machinery ``_out_struct``/``_unify_vma``/``_match_vma`` — runs
with a mesh axis present, forward and backward.  On real chips the
same configuration is compiled Mosaic (tests/test_tpu_pallas.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from multigrad_tpu.ops import binned, pairwise
from multigrad_tpu.ops.pallas_kernels import (binned_erf_counts_pallas,
                                              pair_counts_pallas)
from multigrad_tpu.parallel._shard_map_compat import shard_map


@pytest.fixture(scope="module")
def mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("data",))


def test_erf_kernel_under_shard_map_matches_xla(mesh2):
    vals = jnp.linspace(9.0, 10.0, 4096)
    edges = jnp.linspace(9, 10, 11)
    sigma = 0.05

    def pallas_total(v):
        c = binned_erf_counts_pallas(v, edges, sigma, block_size=1024,
                                     interpret=True)
        return jax.lax.psum(c, "data")

    def xla_total(v):
        c = binned.binned_erf_counts(v, edges, sigma)
        return jax.lax.psum(c, "data")

    run = lambda f: jax.jit(shard_map(
        f, mesh=mesh2, in_specs=P("data"), out_specs=P()))(vals)
    np.testing.assert_allclose(np.asarray(run(pallas_total)),
                               np.asarray(run(xla_total)), rtol=1e-5)


def test_erf_kernel_gradient_under_shard_map(mesh2):
    vals = jnp.linspace(9.0, 10.0, 2048)
    edges = jnp.linspace(9, 10, 11)

    def make_grad(kernel):
        def g(v, sigma):
            def loss(vv, s):
                c = kernel(vv, s)
                return jnp.sum(jax.lax.psum(c, "data") ** 2)
            dv, ds = jax.grad(loss, argnums=(0, 1))(v, sigma)
            # sigma is replicated: its cotangent psums over shards
            # inside _match_vma; dv stays device-varying.
            return dv, ds
        return jax.jit(shard_map(g, mesh=mesh2,
                                 in_specs=(P("data"), P()),
                                 out_specs=(P("data"), P())))

    g_pallas = make_grad(lambda v, s: binned_erf_counts_pallas(
        v, edges, s, block_size=1024, interpret=True))
    g_xla = make_grad(lambda v, s: binned.binned_erf_counts(v, edges, s))
    dv_p, ds_p = g_pallas(vals, 0.05)
    dv_x, ds_x = g_xla(vals, 0.05)
    # atol covers near-zero gradient elements (values span ±2.7e3;
    # the two erf implementations agree to ~1e-3 absolute there).
    np.testing.assert_allclose(np.asarray(dv_p), np.asarray(dv_x),
                               rtol=2e-3, atol=5e-3)
    np.testing.assert_allclose(float(ds_p), float(ds_x), rtol=2e-3)


def test_pair_kernel_under_shard_map_matches_xla(mesh2):
    rng = np.random.default_rng(0)
    n = 512  # 256 per shard
    pos = jnp.asarray(rng.uniform(0, 50, (n, 3)), jnp.float32)
    w = jnp.asarray(rng.uniform(0.2, 1.0, n), jnp.float32)
    edges = jnp.asarray(np.linspace(1.0, 20.0, 6), jnp.float32)

    def pallas_ring(p, ww):
        return pairwise.ring_weighted_pair_counts(
            p, ww, edges, axis_name="data", box_size=50.0,
            backend="pallas")

    def xla_ring(p, ww):
        return pairwise.ring_weighted_pair_counts(
            p, ww, edges, axis_name="data", box_size=50.0,
            backend="xla")

    # Force the genuine kernel through the ring by patching the
    # entry's auto-interpret to an explicit True.  The ring imports
    # the symbol from pallas_kernels at call time, so the patch must
    # land on that module (patching ops.pairwise would be unread).
    from multigrad_tpu.ops import pallas_kernels as pk
    orig = pk.pair_counts_pallas
    calls = {"n": 0}

    def explicit_interpret(*args, **kwargs):
        calls["n"] += 1
        kwargs.setdefault("interpret", True)
        return orig(*args, **kwargs)

    run = lambda f: jax.jit(shard_map(
        lambda p, ww: jax.lax.psum(f(p, ww), "data"), mesh=mesh2,
        in_specs=(P("data"), P("data")), out_specs=P()))(pos, w)
    try:
        pk.pair_counts_pallas = explicit_interpret
        got = np.asarray(run(pallas_ring))
    finally:
        pk.pair_counts_pallas = orig
    assert calls["n"] > 0, "patch was never exercised"
    want = np.asarray(run(xla_ring))
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_pair_kernel_gradient_under_shard_map(mesh2):
    rng = np.random.default_rng(1)
    n = 256
    pos = jnp.asarray(rng.uniform(0, 50, (n, 3)), jnp.float32)
    w = jnp.asarray(rng.uniform(0.2, 1.0, n), jnp.float32)
    edges = jnp.asarray(np.linspace(1.0, 20.0, 6), jnp.float32)

    def make_grad(interpret_kw):
        def g(p, ww):
            def loss(w2):
                c = pair_counts_pallas(p, w2, p, w2, edges,
                                       box_size=50.0, tile=128,
                                       **interpret_kw)
                return jnp.sum(jax.lax.psum(c, "data"))
            return jax.grad(loss)(ww)
        return jax.jit(shard_map(g, mesh=mesh2,
                                 in_specs=(P("data"), P("data")),
                                 out_specs=P("data")))

    # interpret=True -> real kernel; default (None) -> jnp emulation.
    g_kernel = np.asarray(make_grad({"interpret": True})(pos, w))
    g_emul = np.asarray(make_grad({})(pos, w))
    np.testing.assert_allclose(g_kernel, g_emul, rtol=1e-4, atol=1e-5)
