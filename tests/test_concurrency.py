"""Concurrency analysis: static pass, lockdep shadow, interleaving
harness — and the seeded historical-bug fixtures each must catch.

The acceptance contract of the concurrency subsystem:

* the static pass reports ZERO unexplained findings on the shipped
  tree (real hazards were fixed; deliberate ones carry verified
  ``lock-ok`` justifications);
* both seeded historical-bug fixtures (the PR-10 ``_purge_cancelled``
  deadlock shape, the PR-9 sink re-entrancy shape) are flagged
  statically AND deadlock under the interleaving harness — while the
  shipped, fixed implementations do not;
* every lock-acquisition edge the lockdep runtime shadow records
  during real serve-layer execution is present in the static graph
  (derived or declared) — the both-ways cross-check;
* ``analysis.lint --json`` keeps its output schema across ALL
  targets, including the new ``threads`` target.
"""
import importlib.util
import json
import os
import sys
import threading

import numpy as np
import pytest

from multigrad_tpu.analysis.concurrency import (
    THREAD_CHECK_IDS, analyze_concurrency, crosscheck_runtime,
    lock_order_dot)
from multigrad_tpu.analysis.lockgraph import scan_package
from multigrad_tpu.utils import lockdep
from multigrad_tpu.utils.testing import (InterleaveController,
                                         run_interleavings)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "concurrency")


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FIXTURES, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def lockdep_on():
    lockdep.enable()
    lockdep.reset()
    yield lockdep
    lockdep.disable()
    lockdep.reset()
    lockdep.set_logger(None)


# ------------------------------------------------------------------ #
# static pass
# ------------------------------------------------------------------ #
def test_shipped_tree_zero_unexplained_findings():
    """THE merge gate: the package's own concurrency surface is
    clean — every deliberate hazard carries a verified lock-ok
    justification, every real one was fixed."""
    findings = analyze_concurrency()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lockgraph_inventory_and_declarations():
    model = scan_package()
    names = set(model.locks)
    # the serve layer's condition-variable queue, with sharing
    assert "serve.queue.FitQueue._lock" in names
    cond = model.locks["serve.queue.FitQueue._not_full"]
    assert cond.kind == "condition"
    assert cond.shares == "serve.queue.FitQueue._lock"
    # the runtime factories' literal names agree with the AST
    assert model.locks[
        "telemetry.metrics.MetricsLogger._lock"].kind == "rlock"
    # declared (dynamic-dispatch) edges the AST cannot derive
    assert ("serve.fleet.FleetRouter._lock",
            "serve.queue.FitFuture._lock") in model.edge_pairs()
    assert "telemetry.metrics.MetricsLogger._lock" \
        in model.wildcard_sources()
    # every Thread spawn in the package is named
    assert all(s.has_name for s in model.spawns
               if s.kind == "thread"), model.spawns


def test_lock_order_dot_export(tmp_path):
    dot = lock_order_dot()
    assert dot.startswith("digraph lock_order")
    assert '"serve.queue.FitQueue._lock"' in dot
    # declared edges render dashed
    assert "style=dashed" in dot and "declared" in dot
    p = tmp_path / "graph.dot"
    p.write_text(dot)
    assert p.stat().st_size > 0


def test_purge_fixture_flagged_statically():
    findings = analyze_concurrency(root=FIXTURES)
    waits = [f for f in findings
             if f.check == "cond-wait-no-while"
             and "purge_deadlock" in f.where]
    assert len(waits) == 1
    assert "_not_full" in waits[0].message


def test_sink_fixture_flagged_statically():
    findings = analyze_concurrency(root=FIXTURES)
    cbs = [f for f in findings
           if f.check == "callback-under-lock"
           and "sink_reentrancy" in f.where]
    assert len(cbs) == 1
    assert "BuggyLogger._lock" in cbs[0].message


def test_hygiene_fixture_thread_name_and_allowlist():
    findings = analyze_concurrency(root=FIXTURES)
    by_check = {}
    for f in findings:
        if "hygiene" in f.where:
            by_check.setdefault(f.check, []).append(f)
    assert len(by_check["thread-unnamed"]) == 1
    # the no-justification entry is an ERROR and does NOT suppress
    assert len(by_check["blocking-under-lock"]) == 1
    allow = by_check["allowlist"]
    assert any("no justification" in f.message for f in allow)
    assert any("stale" in f.message for f in allow)


# ------------------------------------------------------------------ #
# interleaving harness + seeded bugs
# ------------------------------------------------------------------ #
def test_purge_fixture_deadlocks_under_harness():
    purge = _load_fixture("purge_deadlock")
    outs = run_interleavings(purge.deadlock_scenario,
                             deadlock_timeout_s=0.4, timeout_s=8.0)
    assert any(o.deadlocked for o in outs), outs
    bad = next(o for o in outs if o.deadlocked)
    # the verdict names the stuck threads with stacks
    assert bad.stuck and all(v for v in bad.stuck.values())


def test_fixed_fitqueue_survives_same_scenario(lockdep_on):
    """The shipped FitQueue (with the PR-10 fix: _purge_cancelled
    notifies _not_full itself) runs the exact same scenario shape
    under every schedule without deadlocking — and, with lockdep on,
    without recording any violation."""
    from multigrad_tpu._lockdep import sched_point
    from multigrad_tpu.serve.queue import (FitConfig, FitFuture,
                                           FitQueue, FitRequest)

    def build():
        q = FitQueue(max_pending=1)
        config = FitConfig(nsteps=5)

        def req():
            rid = q.next_id()
            return FitRequest(id=rid,
                              guess=np.array([0.0, 0.0]),
                              config=config,
                              future=FitFuture(rid))

        a, b = req(), req()

        def producer():
            q.submit(a)
            sched_point("submitted-a")
            q.submit(b, block=True)     # backpressure block

        def consumer():
            sched_point("pre-cancel")
            a.future.cancel()
            sched_point("pre-take")
            q.take_group(4, timeout=0.3)

        return [producer, consumer]

    outs = run_interleavings(build, deadlock_timeout_s=1.2,
                             timeout_s=15.0)
    assert not any(o.deadlocked for o in outs), outs
    assert not any(o.errors for o in outs), outs
    assert lockdep.violations() == []


def test_sink_fixture_deadlocks_under_harness():
    sink = _load_fixture("sink_reentrancy")
    outs = run_interleavings(sink.reentrancy_scenario,
                             schedules=[(0,)],
                             deadlock_timeout_s=0.4, timeout_s=5.0)
    assert outs[0].deadlocked
    assert "t0" in outs[0].stuck


def test_sink_fixture_lockdep_detects_deterministically(lockdep_on):
    """With a wrapped lock injected, the silent same-thread hang
    becomes a raised LockdepViolation naming the lock — and the
    violation record survives for the report."""
    sink = _load_fixture("sink_reentrancy")
    workers = sink.reentrancy_scenario(
        lock=lockdep.make_lock("fixture.BuggyLogger._lock"))
    with pytest.raises(lockdep.LockdepViolation,
                       match="BuggyLogger"):
        workers[0]()
    kinds = [v["kind"] for v in lockdep.violations()]
    assert "self-deadlock" in kinds


def test_first_wins_result_race_under_harness():
    """The PR-11 FitFuture shape: a requeued request can complete on
    the survivor AND on the woken original worker — under every
    interleaving exactly one resolution wins and repeated reads are
    stable."""
    from multigrad_tpu._lockdep import sched_point
    from multigrad_tpu.serve.queue import FitFuture

    seen = []

    def build():
        fut = FitFuture(0)

        def survivor():
            sched_point("survivor-pre")
            fut._set_result("survivor")

        def late_original():
            sched_point("original-pre")
            fut._set_exception(RuntimeError("late"))

        def check():
            winner = ("exc" if fut.exception(timeout=5.0)
                      is not None else fut._result)
            seen.append(winner)

        return [survivor, late_original, check]

    outs = run_interleavings(build, timeout_s=10.0)
    assert not any(o.deadlocked or o.errors for o in outs), outs
    # every schedule produced exactly one stable winner
    assert all(w in ("survivor", "exc") for w in seen)


def test_root_after_resolve_race_replay_and_fixed_twin():
    """The PR-13 ordering bug, replayed: a settle path that bumps
    its counter AFTER resolving lets a woken waiter read stale
    accounting under some schedule — while the fixed shape
    (count-before-resolve, what ``analysis.settlement`` proves
    statically for every shipped path) is stale-free under EVERY
    schedule.  The dynamic twin of ``settle-root-after-resolve``."""
    from multigrad_tpu._lockdep import sched_point
    from multigrad_tpu.serve.queue import FitFuture

    def drive(count_first):
        observed = []

        def build():
            fut = FitFuture(0)
            stats = {"completed": 0}

            def settler():
                sched_point("settle-pre")
                if count_first:
                    stats["completed"] += 1
                    fut._set_result("ok")
                else:
                    fut._set_result("ok")
                    sched_point("accounting-window")
                    stats["completed"] += 1

            def waiter():
                assert fut.result(timeout=5.0) == "ok"
                sched_point("waiter-read")
                observed.append(stats["completed"])

            return [settler, waiter]

        outs = run_interleavings(build, timeout_s=10.0)
        assert not any(o.deadlocked or o.errors for o in outs), outs
        return observed

    # Buggy shape: at least one schedule wakes the waiter inside
    # the resolve->accounting window and it reads the stale count.
    assert 0 in drive(count_first=False)
    # Fixed shape: no schedule can — the count is part of what the
    # resolve publishes.
    assert all(n == 1 for n in drive(count_first=True))


def test_dequeue_vs_shed_double_settle_under_harness():
    """The dequeue-vs-shed races of the fleet router: a request can
    complete normally while an admission-reject path sheds it (the
    two writers the static ``settle-double``/``settle-first-wins``
    checks police).  Under every interleaving the real ``FitFuture``
    settles EXACTLY once — one terminal state, stable on re-read."""
    from multigrad_tpu._lockdep import sched_point
    from multigrad_tpu.serve import FleetSaturatedError
    from multigrad_tpu.serve.queue import FitFuture

    states = []

    def build():
        fut = FitFuture(0)

        def dequeue():
            sched_point("dequeue-pre")
            fut._set_result("served")

        def shed():
            sched_point("shed-pre")
            fut._set_exception(FleetSaturatedError("all rejected"))

        def check():
            first = fut.exception(timeout=5.0)
            second = fut.exception(timeout=5.0)
            states.append((fut._result, first, second))

        return [dequeue, shed, check]

    outs = run_interleavings(build, timeout_s=10.0)
    assert not any(o.deadlocked or o.errors for o in outs), outs
    assert states
    for result, first, second in states:
        # exactly one terminal state, and it is sticky
        assert (result is None) != (first is None)
        assert type(first) is type(second)
        if result is not None:
            assert result == "served"
        else:
            assert isinstance(first, FleetSaturatedError)


# ------------------------------------------------------------------ #
# lockdep runtime shadow
# ------------------------------------------------------------------ #
def test_lockdep_edges_and_cycle_detection(lockdep_on):
    a = lockdep.make_lock("test.A")
    b = lockdep.make_lock("test.B")
    with a:
        with b:
            pass
    assert ("test.A", "test.B") in lockdep.edges()
    # reverse order later = a cycle in the edge graph: the violation
    # names both stacks (this acquisition + the recorded first edge)
    with b:
        with a:
            pass
    cyc = [v for v in lockdep.violations()
           if v["kind"] == "lock-order-cycle"]
    assert len(cyc) == 1
    assert cyc[0]["stack"] and cyc[0]["other_stack"]
    assert set(cyc[0]["edge"]) == {"test.A", "test.B"}


def test_lockdep_violations_emitted_as_telemetry(lockdep_on):
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger
    sink = MemorySink()
    logger = MetricsLogger(sink)
    lockdep.set_logger(logger)
    a = lockdep.make_lock("test.tele.A")
    b = lockdep.make_lock("test.tele.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    events = [r["event"] for r in sink.records]
    assert "lockdep_violation" in events


def test_lockdep_crosscheck_and_dump_roundtrip(lockdep_on, tmp_path):
    a = lockdep.make_lock("test.X")
    b = lockdep.make_lock("test.Y")
    with a:
        with b:
            pass
    # hole when the static graph lacks the edge...
    holes = lockdep.crosscheck([])
    assert [tuple(h["edge"]) for h in holes] == [("test.X",
                                                 "test.Y")]
    # ...clean when derived or declared (wildcard included)
    assert lockdep.crosscheck([("test.X", "test.Y")]) == []
    assert lockdep.crosscheck([], wildcard_sources={"test.X"}) == []
    # dump -> load -> crosscheck_runtime produces typed findings
    path = lockdep.dump(str(tmp_path / "lockdep-1.json"))
    edges, violations, loaded = lockdep.load_edge_dumps(
        str(tmp_path))
    assert ("test.X", "test.Y") in edges
    assert loaded == [path]
    findings = crosscheck_runtime(path, root=FIXTURES)
    assert any(f.check == "runtime-coverage"
               and "test.X -> test.Y" in f.message
               for f in findings)


def test_crosscheck_fails_when_no_dumps_found(tmp_path):
    """The CI gate must not launder a crashed (or mis-pathed)
    MGT_LOCKDEP run as a clean cross-check: zero loaded dumps is
    itself an error finding."""
    findings = crosscheck_runtime(str(tmp_path / "nowhere"),
                                  root=FIXTURES)
    assert len(findings) == 1
    assert findings[0].check == "runtime-coverage"
    assert "no lockdep dumps found" in findings[0].message
    from multigrad_tpu.analysis.lint import main
    rc = main(["--targets", "threads",
               "--runtime-edges", str(tmp_path / "nowhere")])
    assert rc == 1


def test_lint_checks_flag_spans_both_registries(tmp_path, capsys):
    """--checks accepts thread check ids, subsets the threads
    target, and a thread-only selection skips the model targets."""
    from multigrad_tpu.analysis.lint import main
    rc = main(["--targets", "threads", "--checks",
               "lock-order-cycle,thread-unnamed", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["clean"]
    # unknown id in NEITHER registry still errors out (argparse
    # exit code 2)
    with pytest.raises(SystemExit) as exc:
        main(["--targets", "threads", "--checks", "nonsense"])
    assert exc.value.code == 2


def test_runtime_edges_covered_by_static_graph(lockdep_on):
    """The acceptance criterion, in-process: drive the REAL logger/
    live/flight fan-out (the lock nestings a serve burst exercises)
    with lockdep on, then require every recorded edge to be in the
    static graph — derived or declared.  A new hold-across-call in
    the telemetry plumbing that the AST cannot see fails here until
    it is declared."""
    from multigrad_tpu.telemetry import (FlightRecorder, MemorySink,
                                         MetricsLogger)
    from multigrad_tpu.telemetry.live import (LatencyObserver,
                                              LiveMetrics, LiveSink)

    metrics = LiveMetrics()
    live = LiveSink(metrics)
    logger = MetricsLogger(MemorySink())
    logger.add_sink(live)
    recorder = FlightRecorder(dump_dir=None, trip_on_stall=False)
    logger.add_sink(recorder)
    logger.log("adam", step=1, loss=1.0, grad_norm=0.5)
    logger.log("fit_summary", steps=1, steps_per_sec=10.0)
    obs = LatencyObserver(metrics, "multigrad_serve", "served fit")
    obs.observe(0.01, {"queue_wait": 0.001}, "deadbeef")
    obs.observe(0.02, None, "cafebabe")

    assert lockdep.edges(), "no runtime edges recorded?"
    model = scan_package()
    holes = lockdep.crosscheck(model.edge_pairs(),
                               model.wildcard_sources())
    assert holes == [], holes
    assert lockdep.violations() == []


def test_lockdep_off_returns_plain_primitives():
    lockdep.disable()
    assert type(lockdep.make_lock("x")) is type(threading.Lock())
    cond = lockdep.make_condition("c")
    assert isinstance(cond, threading.Condition)


# ------------------------------------------------------------------ #
# lint CLI: threads target + JSON schema across ALL targets
# ------------------------------------------------------------------ #
def _validate_lint_json(out):
    payload = json.loads(out)
    assert set(payload) == {"findings", "clean"}
    assert isinstance(payload["clean"], bool)
    assert isinstance(payload["findings"], list)
    for f in payload["findings"]:
        assert set(f) == {"check", "severity", "message", "program",
                          "where", "path"}
        assert isinstance(f["check"], str)
        assert f["severity"] in ("error", "warning")
    return payload


def test_lint_threads_target_clean_and_dot(tmp_path, capsys):
    from multigrad_tpu.analysis.lint import main
    dot = tmp_path / "lock_order.dot"
    rc = main(["--targets", "threads", "--dot", str(dot)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[threads] clean" in out
    assert dot.read_text().startswith("digraph lock_order")


def test_lint_json_schema_all_targets(capsys):
    """Downstream consumers read --json; its schema must hold for
    EVERY target — the model families AND the threads target — so a
    new target cannot silently break the contract."""
    from multigrad_tpu.analysis.lint import ALL_TARGETS, main
    assert "threads" in ALL_TARGETS
    rc = main(["--json", "--num-halos", "200",
               "--targets", ",".join(ALL_TARGETS)])
    payload = _validate_lint_json(capsys.readouterr().out)
    assert rc == 0 and payload["clean"]


def test_lint_json_schema_carries_findings(capsys, tmp_path,
                                           lockdep_on):
    """The schema holds (and exit code flips) when findings exist:
    a runtime-edge dump the static graph cannot cover."""
    a = lockdep.make_lock("schema.A")
    b = lockdep.make_lock("schema.B")
    with a:
        with b:
            pass
    lockdep.dump(str(tmp_path / "lockdep-7.json"))
    from multigrad_tpu.analysis.lint import main
    rc = main(["--json", "--targets", "threads",
               "--runtime-edges", str(tmp_path)])
    payload = _validate_lint_json(capsys.readouterr().out)
    assert rc == 1 and not payload["clean"]
    assert any(f["check"] == "runtime-coverage"
               for f in payload["findings"])


def test_thread_check_registry_is_stable():
    # the doc table / allowlist ids downstream rely on
    for check in ("lock-order-cycle", "cond-wait-no-while",
                  "notify-outside-lock", "blocking-under-lock",
                  "callback-under-lock", "unlocked-shared-write",
                  "thread-unnamed", "allowlist",
                  "runtime-coverage"):
        assert check in THREAD_CHECK_IDS


def test_interleave_controller_passthrough_when_unmanaged():
    """sched_point outside a harness run is a no-op (production code
    paths hit wrapped locks constantly; only managed threads park)."""
    from multigrad_tpu._lockdep import sched_point
    sched_point("free")           # must not block or raise
    ctrl = InterleaveController()
    assert not ctrl.managed(threading.get_ident())
