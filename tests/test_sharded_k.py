"""Sharded-K ensembles: 2-level mesh + ZeRO-partitioned Adam state.

The PR's acceptance battery:

* K-sharded vs replicated equivalence at EVERY entry point —
  bitwise on an exact-arithmetic mesh model (nonzero data on shard 0
  only, so every reduction is exact in any association AND any
  participant count — the regime where trajectories of different
  data-axis widths can match bit-for-bit), tolerance twin on the
  real SMF model: the batched ``run_adam_scan``,
  ``run_multistart_adam``, HMC chains, and a served bucket;
* cache-key isolation — toggling ``k_sharded`` builds sibling
  programs and never retraces an existing one;
* the memory model and its consumers — ``max_k_for_budget`` scales
  exactly ×R, the scheduler's bucket-ladder cap splits oversized
  groups, and a device OOM surfaces as the typed
  :class:`~multigrad_tpu.serve.FitOOMError` with the estimate and
  the sharded-K remedy;
* the static side — the ``ensemble_sharded`` lint target is clean,
  and the k-scaling check catches a seeded super-linear coupling.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import multigrad_tpu as mgt
from multigrad_tpu.inference import run_hmc, run_multistart_adam
from multigrad_tpu.inference.ensemble import (
    ENSEMBLE_STATE_ROWS, batched_fit_wrapper, ensemble_memory_model,
    max_k_for_budget, resolve_k_sharded)
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.optim import adam as _adam
from multigrad_tpu.parallel import ensemble_comm
from multigrad_tpu.serve import FitOOMError, FitScheduler
from multigrad_tpu.utils.testing import (bitwise_trajectory_pair,
                                          make_exact_shard_model)

N_DEV = len(jax.devices())
R = 4
BOUNDS = [(-5.0, 1.0), (0.01, 2.0)]

pytestmark = pytest.mark.skipif(
    N_DEV < 2 or N_DEV % R,
    reason=f"needs a mesh divisible into {R} replica slices")


def make_exact_model(comm):
    # The shared bitwise-equivalence fixture (see
    # multigrad_tpu/utils/testing.py for the exactness argument).
    return make_exact_shard_model(comm, n_devices=N_DEV)


@pytest.fixture(scope="module")
def ecomm():
    return ensemble_comm(R)


@pytest.fixture(scope="module")
def gcomm():
    return mgt.global_comm()


@pytest.fixture(scope="module")
def smf_pair(ecomm, gcomm):
    """(replicated-layout model, sharded-layout model), one catalog."""
    return (SMFModel(aux_data=make_smf_data(800, comm=gcomm),
                     comm=gcomm),
            SMFModel(aux_data=make_smf_data(800, comm=ecomm),
                     comm=ecomm))


def _inits(k):
    return jnp.asarray(np.column_stack(
        [np.linspace(-2.0, -1.0, k),
         np.linspace(0.3, 0.8, k)]).astype(np.float32))


# ------------------------------------------------------------------ #
# equivalence: batched scan / ensemble / HMC / served bucket
# ------------------------------------------------------------------ #
def test_batched_scan_bitwise_on_exact_model(ecomm, gcomm):
    # The shared harness (utils/testing.py) — the same protocol the
    # bench gate and the demo receipt run.
    t_rep, t_sh = bitwise_trajectory_pair(gcomm, ecomm,
                                          n_devices=N_DEV)
    # The whole trajectory — params, every step — is bit-identical
    # across the two mesh layouts.
    assert np.array_equal(np.asarray(t_rep), np.asarray(t_sh))
    # ... and the sharded one's K axis is genuinely partitioned over
    # the replica axis (the ZeRO layout, not a gathered copy).
    spec = [s for s in jax.tree_util.tree_leaves(
        tuple(t_sh.sharding.spec)) if isinstance(s, str)]
    assert "replica" in spec


def test_multistart_adam_sharded_matches_replicated_smf(smf_pair):
    m_rep, m_sh = smf_pair
    # n_starts NOT divisible by R: exercises the inert row-0 padding
    # and the result slice-back.
    kwargs = dict(param_bounds=BOUNDS, n_starts=6, nsteps=15, seed=3)
    res_rep = run_multistart_adam(m_rep, k_sharded=False, **kwargs)
    res_sh = run_multistart_adam(m_sh, k_sharded=True, **kwargs)
    assert res_sh.k_sharded and not res_rep.k_sharded
    assert res_sh.n_starts == 6 and res_sh.losses.shape == (6,)
    pr, ps = np.asarray(res_rep.params), np.asarray(res_sh.params)
    # The layouts must agree on WHICH basins diverged, and agree to
    # float tolerance on the rest (the data-axis reduction width
    # differs, so bitwise is the exact model's claim, not SMF's).
    finite_r = np.isfinite(pr).all(axis=1)
    finite_s = np.isfinite(ps).all(axis=1)
    assert np.array_equal(finite_r, finite_s)
    assert np.allclose(pr[finite_r], ps[finite_s], rtol=0, atol=1e-4)
    assert res_sh.best_loss == pytest.approx(res_rep.best_loss,
                                             abs=1e-5)


def test_multistart_adam_auto_rule(smf_pair):
    m_rep, m_sh = smf_pair
    # Tiny budget: auto must shard on the 2-level mesh...
    res = run_multistart_adam(m_sh, param_bounds=BOUNDS, n_starts=8,
                              nsteps=4, k_sharded="auto",
                              k_budget_bytes=1)
    assert res.k_sharded
    # ... a huge budget keeps the replicated layout ...
    res = run_multistart_adam(m_sh, param_bounds=BOUNDS, n_starts=8,
                              nsteps=4, k_sharded="auto",
                              k_budget_bytes=1 << 40)
    assert not res.k_sharded
    # ... and a flat mesh can never shard: auto is a no-op, explicit
    # True raises with the ensemble_comm pointer.
    assert not resolve_k_sharded(m_rep, 64, 2, 100,
                                 k_sharded="auto", k_budget_bytes=1)
    with pytest.raises(ValueError, match="ensemble_comm"):
        run_multistart_adam(m_rep, param_bounds=BOUNDS, n_starts=4,
                            nsteps=2, k_sharded=True)
    with pytest.raises(ValueError, match="k_sharded"):
        run_multistart_adam(m_sh, param_bounds=BOUNDS, n_starts=4,
                            nsteps=2, k_sharded="maybe")


def test_hmc_sharded_chains_bitwise_on_exact_model(ecomm, gcomm):
    m_rep = make_exact_model(gcomm)
    m_sh = make_exact_model(ecomm)
    init = _inits(8) * 0.1 + jnp.asarray([-0.09, 0.05])
    kwargs = dict(num_samples=25, num_warmup=10, num_leapfrog=4,
                  step_size=0.05, randkey=7)
    out_rep = run_hmc(m_rep, init, **kwargs)
    out_sh = run_hmc(m_sh, init, k_sharded=True, **kwargs)
    # Chain randomness is drawn as the full (C, ...) array and
    # row-sliced per replica slice, so the sharded sampler follows
    # the replicated sampler's exact streams — with exact arithmetic
    # the draws are bit-identical chain by chain.
    assert np.array_equal(out_rep.samples, out_sh.samples)
    assert np.array_equal(out_rep.potential, out_sh.potential)
    assert np.array_equal(out_rep.step_size, out_sh.step_size)
    assert np.array_equal(out_rep.divergences, out_sh.divergences)


def test_hmc_sharded_tap_records_whole_ensemble(ecomm, gcomm):
    # The sharded sampler's tap records must carry WHOLE-ensemble
    # quantities — divergences psum'd and step sizes gathered across
    # replica slices (behind the emit cond, so the slow axis only
    # carries traffic on log_every draws) — matching the replicated
    # sampler's records on the exact model.
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger

    init = _inits(8) * 0.1 + jnp.asarray([-0.09, 0.05])
    kwargs = dict(num_samples=20, num_warmup=5, num_leapfrog=4,
                  step_size=0.05, randkey=7, log_every=5)
    records = {}
    for tag, comm, sharded in (("rep", gcomm, False),
                               ("sh", ecomm, True)):
        sink = MemorySink()
        logger = MetricsLogger(sink)
        run_hmc(make_exact_model(comm), init, k_sharded=sharded,
                telemetry=logger, **kwargs)
        logger.close()
        jax.effects_barrier()
        records[tag] = [r for r in sink.records
                        if r["event"] == "hmc"]
    assert len(records["sh"]) == len(records["rep"]) == 4
    for r_rep, r_sh in zip(records["rep"], records["sh"]):
        assert len(r_sh["step_size"]) == 8       # full (C,) vector
        assert r_sh["divergences"] == r_rep["divergences"]
        assert r_sh["accept"] == pytest.approx(r_rep["accept"],
                                               abs=1e-6)
        assert np.allclose(r_sh["step_size"], r_rep["step_size"])


def test_hmc_sharded_chains_divisibility(smf_pair):
    _, m_sh = smf_pair
    with pytest.raises(ValueError, match="divisible"):
        run_hmc(m_sh, jnp.asarray([-1.0, 0.5]), num_samples=4,
                num_warmup=2, num_chains=R + 1, k_sharded=True)


def test_hmc_sharded_smf_same_posterior(smf_pair):
    # Real-model twin: chains diverge at reduction tolerance (HMC
    # amplifies ULPs into different accept decisions), so the claim
    # is statistical — both samplers draw from the same posterior.
    m_rep, m_sh = smf_pair
    best = jnp.asarray([-1.0, 0.5])
    kwargs = dict(num_samples=150, num_warmup=80, num_leapfrog=6,
                  step_size=0.05, randkey=5)
    out_rep = run_hmc(m_rep, best, num_chains=8, init_spread=0.05,
                      **kwargs)
    out_sh = run_hmc(m_sh, best, num_chains=8, init_spread=0.05,
                     k_sharded=True, **kwargs)
    spread = np.maximum(out_rep.samples.reshape(-1, 2).std(axis=0),
                        1e-3)
    assert np.all(np.abs(out_rep.mean() - out_sh.mean())
                  < 5.0 * spread)
    assert abs(out_rep.accept_prob.mean()
               - out_sh.accept_prob.mean()) < 0.25


def test_served_bucket_sharded_bitwise_on_exact_model(ecomm, gcomm):
    guesses = [np.asarray(g) for g in np.asarray(_inits(8))]
    results = {}
    for tag, comm in (("rep", gcomm), ("sh", ecomm)):
        model = make_exact_model(comm)
        with FitScheduler(model, buckets=(8,), start=False,
                          batch_window_s=0.0) as sched:
            if tag == "sh":
                assert sched.k_sharded      # "auto" saw the mesh
            else:
                assert not sched.k_sharded
            futs = [sched.submit(g, nsteps=15, learning_rate=0.05)
                    for g in guesses]
            sched.start()
            results[tag] = [f.result(timeout=120) for f in futs]
    for r_rep, r_sh in zip(results["rep"], results["sh"]):
        assert np.array_equal(r_rep.traj, r_sh.traj)
        assert r_rep.loss == r_sh.loss
        assert r_sh.bucket == 8


# ------------------------------------------------------------------ #
# cache-key isolation: toggling sharding never retraces
# ------------------------------------------------------------------ #
def test_toggling_k_sharded_never_retraces(smf_pair):
    _, m_sh = smf_pair
    traces = []

    def fn(u, key):
        traces.append(tuple(u.shape))
        return jnp.sum(u ** 2, axis=-1), 2.0 * u

    inits = _inits(8)
    ks = m_sh.k_sharding(2)

    def run(carry_sharding):
        _adam.run_adam_scan(fn, inits, nsteps=3, progress=False,
                            carry_sharding=carry_sharding)

    run(None)
    assert len(traces) == 1
    run(ks)                     # sibling program: ONE new trace
    assert len(traces) == 2
    run(None)                   # both variants now cached: no new
    run(ks)
    assert len(traces) == 2

    # The model's program cache keeps the variants as siblings too.
    p_rep = m_sh.batched_loss_and_grad_fn(False)
    p_sh = m_sh.batched_loss_and_grad_fn(False, k_sharded=True)
    assert p_rep is not p_sh
    assert m_sh.batched_loss_and_grad_fn(False) is p_rep
    assert m_sh.batched_loss_and_grad_fn(False, k_sharded=True) \
        is p_sh
    # ... and the cached fit wrappers likewise.
    w_rep = batched_fit_wrapper(m_sh, False)
    w_sh = batched_fit_wrapper(m_sh, False, k_sharded=True)
    assert w_rep is not w_sh
    assert batched_fit_wrapper(m_sh, False) is w_rep
    assert batched_fit_wrapper(m_sh, False, k_sharded=True) is w_sh


def test_flat_model_has_no_k_shard_axis(smf_pair):
    m_rep, m_sh = smf_pair
    assert m_rep.k_shard_axis is None
    assert m_rep.k_shard_replicas == 1
    assert m_sh.k_shard_axis == "replica"
    assert m_sh.k_shard_replicas == R
    with pytest.raises(ValueError, match="ensemble_comm"):
        m_rep.k_sharding(2)


# ------------------------------------------------------------------ #
# memory model + scheduler cap + typed OOM
# ------------------------------------------------------------------ #
def test_memory_model_arithmetic():
    per_member = 2 * 4 * (10 + 1 + ENSEMBLE_STATE_ROWS)
    assert ensemble_memory_model(16, 2, 10, itemsize=4) \
        == 16 * per_member
    # Sharding divides the state term exactly by R ...
    assert ensemble_memory_model(16, 2, 10, n_replicas=4,
                                 itemsize=4) == 4 * per_member
    # ... and the catalog term grows by R (each replica slice holds
    # a full catalog copy over fewer data shards).
    full = ensemble_memory_model(16, 2, 10, n_replicas=4, itemsize=4,
                                 catalog_bytes=8000, n_devices=8)
    assert full == 4 * per_member + 8000 * 4 // 8
    # max K at a fixed budget scales exactly x R.
    budget = 256 * per_member
    assert max_k_for_budget(budget, 2, 10, itemsize=4) == 256
    assert max_k_for_budget(budget, 2, 10, n_replicas=4,
                            itemsize=4) == 1024
    assert max_k_for_budget(10, 2, 10, itemsize=4) == 0


def test_scheduler_bucket_cap_splits_oversized_groups():
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)
    # Budget admits K=4 at nsteps=5 (per-member 80 B): the 16-bucket
    # is capped away and one 8-request group splits into two
    # 4-dispatches instead of risking an OOM-sized bucket.
    per_member = 2 * 4 * (5 + 1 + ENSEMBLE_STATE_ROWS)
    with FitScheduler(model, buckets=(1, 4, 16), start=False,
                      batch_window_s=0.0,
                      k_budget_bytes=4 * per_member) as sched:
        assert sched._allowed_buckets(
            type("C", (), {"nsteps": 5})(), 2) == (1, 4)
        futs = [sched.submit([-1.0 - 0.05 * i, 0.5], nsteps=5,
                             learning_rate=0.05) for i in range(8)]
        sched.start()
        results = [f.result(timeout=120) for f in futs]
    assert all(np.isfinite(r.loss) for r in results)
    assert all(r.bucket == 4 for r in results)
    stats = sched.stats
    assert stats["bucket_dispatches"].get(4) == 2
    assert stats["completed"] == 8


def test_scheduler_oom_is_typed_and_actionable(monkeypatch, tmp_path):
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)

    def boom(*a, **k):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 123456789 bytes")

    monkeypatch.setattr(_adam, "run_adam_scan", boom)
    with FitScheduler(model, buckets=(4,), start=False,
                      batch_window_s=0.0, retry_poisoned=False,
                      flight_dir=str(tmp_path)) as sched:
        futs = [sched.submit([-1.0, 0.5], nsteps=50,
                             learning_rate=0.05) for _ in range(3)]
        sched.start()
        excs = [f.exception(timeout=120) for f in futs]
    for exc in excs:
        assert isinstance(exc, FitOOMError)
        # Actionable: the estimate and the sharded-K remedy are in
        # the message, typed fields carry the numbers.
        assert exc.estimated_bytes == ensemble_memory_model(4, 2, 50)
        assert exc.bucket == 4
        assert "ensemble_comm" in str(exc)
        assert "k_sharded" in str(exc)
        assert exc.bundle_path
    import json
    with open(excs[0].bundle_path) as f:
        bundle = json.load(f)
    assert bundle["detail"]["oom"] is True
    assert bundle["detail"]["estimated_bytes"] \
        == ensemble_memory_model(4, 2, 50)


def test_allowed_buckets_judge_each_rung_by_its_own_layout(ecomm):
    # Indivisible rungs dispatch REPLICATED at full per-device state,
    # so the sharded cap must not admit them: budget admitting K=1
    # replicated / K=4 sharded keeps (1, 4) and drops the
    # replicated-layout 2-rung that would run at 2x the budget.
    model = SMFModel(aux_data=make_smf_data(800, comm=ecomm),
                     comm=ecomm)
    per_member = 2 * 4 * (5 + 1 + ENSEMBLE_STATE_ROWS)
    with FitScheduler(model, buckets=(1, 2, 4, 8), start=False,
                      batch_window_s=0.0,
                      k_budget_bytes=per_member) as sched:
        assert sched.k_sharded and sched._k_replicas == R
        cfg = type("C", (), {"nsteps": 5})()
        assert sched._allowed_buckets(cfg, 2) == (1, 4)


def test_oom_reports_the_bucket_that_actually_failed(monkeypatch,
                                                     tmp_path):
    # A budget-split group fails far more pending requests than the
    # failed bucket held: the typed error must name the dispatched
    # bucket (4), not one re-derived from the pending count (16).
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)
    per_member = 2 * 4 * (5 + 1 + ENSEMBLE_STATE_ROWS)

    def boom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory")

    monkeypatch.setattr(_adam, "run_adam_scan", boom)
    with FitScheduler(model, buckets=(1, 4, 16), start=False,
                      batch_window_s=0.0, retry_poisoned=False,
                      k_budget_bytes=4 * per_member,
                      flight_dir=str(tmp_path)) as sched:
        futs = [sched.submit([-1.0 - 0.02 * i, 0.5], nsteps=5,
                             learning_rate=0.05) for i in range(8)]
        sched.start()
        excs = [f.exception(timeout=120) for f in futs]
    for exc in excs:
        assert isinstance(exc, FitOOMError)
        assert exc.bucket == 4
        assert exc.estimated_bytes == ensemble_memory_model(4, 2, 5)


def test_oom_classifier_is_not_fooled_by_substrings(monkeypatch,
                                                    tmp_path):
    # "bloom"/"room" contain "oom": an innocent failure must NOT be
    # reclassified as out-of-memory (its real cause would be hidden
    # behind the sharded-K remedy).
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)

    def boom(*a, **k):
        raise FileNotFoundError("/home/bloomfield/cache/weights.npz")

    monkeypatch.setattr(_adam, "run_adam_scan", boom)
    with FitScheduler(model, buckets=(2,), start=False,
                      batch_window_s=0.0, retry_poisoned=False,
                      flight_dir=str(tmp_path)) as sched:
        fut = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)
        sched.start()
        exc = fut.exception(timeout=120)
    assert not isinstance(exc, FitOOMError)
    assert "bloomfield" in str(exc)


def test_oom_message_names_the_layout_that_ran(monkeypatch, ecomm,
                                               tmp_path):
    # A sharded scheduler whose failing bucket is NOT divisible by
    # the replica count dispatched the REPLICATED program: the
    # estimate and the layout in the message must say so (a /R
    # estimate would understate the real footprint 4x).
    model = SMFModel(aux_data=make_smf_data(800, comm=ecomm),
                     comm=ecomm)

    def boom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory")

    monkeypatch.setattr(_adam, "run_adam_scan", boom)
    with FitScheduler(model, buckets=(2,), start=False,
                      batch_window_s=0.0, retry_poisoned=False,
                      flight_dir=str(tmp_path)) as sched:
        assert sched.k_sharded          # knob on (auto saw the mesh)
        futs = [sched.submit([-1.0, 0.5], nsteps=5,
                             learning_rate=0.05) for _ in range(2)]
        sched.start()
        excs = [f.exception(timeout=120) for f in futs]
    for exc in excs:
        assert isinstance(exc, FitOOMError)
        # Truthful layout + full (n_replicas=1) estimate ...
        assert "replicated" in str(exc)
        assert exc.estimated_bytes == ensemble_memory_model(2, 2, 5)
        # ... and the remedy targets bucket divisibility, not the
        # already-enabled k_sharded knob.
        assert "divisible" in str(exc)


# ------------------------------------------------------------------ #
# static proofs: lint target, k-scaling check, costmodel split
# ------------------------------------------------------------------ #
def test_lint_ensemble_sharded_target_is_clean():
    from multigrad_tpu.analysis.lint import main as lint_main
    assert lint_main(["--targets", "ensemble_sharded",
                      "--num-halos", "400"]) == 0


def test_k_scaling_check_catches_superlinear_coupling(ecomm):
    from jax.sharding import PartitionSpec as P

    from multigrad_tpu.analysis import check_k_scaling, trace_program
    from multigrad_tpu.parallel._shard_map_compat import shard_map

    def bad_local(params):
        # A cross-member coupling: every member interacts with the
        # FULL gathered batch, so the psum payload is O(K^2/R).
        full = jax.lax.all_gather(params, "replica", axis=0,
                                  tiled=True)
        inter = params @ full.T
        return jax.lax.psum(inter, "data")

    def program(k):
        mapped = shard_map(bad_local, mesh=ecomm.mesh,
                           in_specs=(P("replica", None),),
                           out_specs=P("replica", None))
        return trace_program(
            jax.jit(mapped),
            jax.ShapeDtypeStruct((k, 2), jnp.float32))

    findings = check_k_scaling(program(8), program(16),
                               program="bad", scale=2)
    assert findings, "super-linear coupling not flagged"
    assert any("SUPER-linear" in f.message for f in findings)


def test_costmodel_splits_comm_by_axis(smf_pair):
    from multigrad_tpu.telemetry.costmodel import (model_cost,
                                                   predicted_time_s)

    m_rep, m_sh = smf_pair
    solo = model_cost(m_rep, jnp.zeros(2))
    # The flat model's whole payload rides the (fast) data axis.
    assert solo.comm_bytes_by_axis == {"shards": solo.comm_bytes}
    c8 = model_cost(m_sh, jnp.zeros((8, 2)),
                    kind="batched_loss_and_grad_sharded")
    c16 = model_cost(m_sh, jnp.zeros((16, 2)),
                     kind="batched_loss_and_grad_sharded")
    # Sharded-K: per-device payload is (K/R)·(|y|+|params|)·4 on the
    # data axis, NOTHING on the replica axis, and doubling K doubles
    # it — the costmodel twin of the k-scaling lint proof.
    assert c8.comm_bytes_by_axis == {"data": (8 // R) * 48}
    assert "replica" not in c8.comm_bytes_by_axis
    assert c16.comm_bytes_by_axis["data"] \
        == 2 * c8.comm_bytes_by_axis["data"]
    p8, p16 = predicted_time_s(c8), predicted_time_s(c16)
    assert p16["comm_s"] == pytest.approx(2 * p8["comm_s"])
    assert p8["predicted_s"] >= p8["comm_s"]


# ------------------------------------------------------------------ #
# tune + warmup + lbfgs satellites
# ------------------------------------------------------------------ #
def test_tune_buckets_measures_sharded_rungs(smf_pair, tmp_path):
    from multigrad_tpu.tune import TuningTable, tune_buckets
    from multigrad_tpu.tune.space import bucket_candidates

    _, m_sh = smf_pair
    # The candidate set derives its cap from the memory model (no
    # hardcoded max): a budget admitting K=8 replicated admits the
    # 4x-wider sharded rungs.
    per_member = 2 * 4 * (5 + 1 + ENSEMBLE_STATE_ROWS)
    cands = bucket_candidates(m_sh, 5, ndim=2, k_sharded=True,
                              budget_bytes=8 * per_member)
    assert max(cands) == 32 and 1 in cands
    cands_flat = bucket_candidates(m_sh, 5, ndim=2, k_sharded=False,
                                   budget_bytes=8 * per_member)
    assert max(cands_flat) == 8

    # ... and each rung is judged under its OWN layout: a budget
    # admitting only K=1 replicated / K=4 sharded must drop the
    # replicated-layout 2-rung (it would run at 2x the budget).
    assert bucket_candidates(m_sh, 5, ndim=2, k_sharded=True,
                             budget_bytes=per_member) == (1, 4)

    table = TuningTable(str(tmp_path / "table.json"))
    res = tune_buckets(m_sh, np.array([-1.0, 0.5]), nsteps=5,
                       reps=1, candidates=(1, 4, 8), table=table)
    # The sharded rungs ran through the K-partitioned program; the
    # K=1 singleton kept the replicated one (the dispatch rule).
    flags = {c["knobs"]["bucket"]: c["k_sharded"]
             for c in res.candidates}
    assert flags == {1: False, 4: True, 8: True}
    assert 1 in res.chosen["buckets"]
    entry = table.lookup(res.key)
    assert entry["k_sharded"] is True
    assert entry["n_replicas"] == R


def test_warmup_buckets_sharded(smf_pair):
    from multigrad_tpu.serve import FitConfig, warmup_buckets

    _, m_sh = smf_pair
    entries = warmup_buckets(
        m_sh, FitConfig(nsteps=3, param_bounds=BOUNDS),
        buckets=(1, R), k_sharded=True)
    assert [(e["bucket"], e["k_sharded"]) for e in entries] \
        == [(1, False), (R, True)]


def test_multistart_lbfgs_reuses_cached_program(smf_pair):
    m_rep, _ = smf_pair
    from multigrad_tpu.inference.ensemble import \
        _lbfgs_polish_objective
    from multigrad_tpu.inference import run_multistart_lbfgs

    # The objective is a stable cached callable per model — the fix
    # for the polish re-tracing its whole L-BFGS scan every call.
    obj1 = _lbfgs_polish_objective(m_rep, False)
    assert _lbfgs_polish_objective(m_rep, False) is obj1

    res1 = run_multistart_lbfgs(m_rep, param_bounds=BOUNDS,
                                n_starts=2, maxsteps=8)
    cache = m_rep._mgt_program_cache
    keys_after_first = set(cache)
    res2 = run_multistart_lbfgs(m_rep, param_bounds=BOUNDS,
                                n_starts=2, maxsteps=8, seed=1)
    # A repeat polish (same schedule) adds ZERO compiled programs.
    assert set(cache) == keys_after_first
    assert np.isfinite(res1.best_loss)
    assert np.isfinite(res2.best_loss)
