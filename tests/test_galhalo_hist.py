"""GalhaloHistModel (diffmah-style MAH + SFH family) tests.

Covers the physics invariants (monotone anchored histories, padding
neutrality), the execution contract (chunked == unchunked, sharded ==
single-device, both kernel backends), differentiability of all ten
parameters, and multi-epoch truth recovery — with the honestly-flat
``k_t`` direction given its own tolerance (the rollover sharpness
trades against the alpha contrast; see the recovery test's note).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models import (GalhaloHistModel, GalhaloHistParams,
                                  make_galhalo_hist_data,
                                  mean_log_mstar, scatter_sigma)
from multigrad_tpu.models.galhalo_hist import (TRUTH, default_time_grid,
                                               log_mh_at_t)
from multigrad_tpu.models.galhalo import sample_log_halo_masses

TRUTH_ARR = np.array(TRUTH)
BOUNDS = [(1.0, 4.0), (0.1, 2.0), (-0.5, 1.0), (1.0, 6.0),
          (-2.0, 0.5), (10.5, 13.5), (0.3, 3.0), (0.2, 2.5),
          (0.05, 0.5), (-0.1, 0.05)]


@pytest.fixture(scope="module")
def data():
    return make_galhalo_hist_data(50_000)


@pytest.fixture(scope="module")
def model(data):
    return GalhaloHistModel(aux_data=data)


def test_mah_monotone_and_anchored():
    # Histories grow monotonically and end exactly at the observed
    # mass: Mh(T0) = 10**logm0.
    t = default_time_grid()
    for lm in (11.0, 13.0, 15.0):
        mh = np.asarray(log_mh_at_t(jnp.full((1, 1), lm), t[None, :],
                                    jnp.array(TRUTH)))[0]
        assert abs(mh[-1] - lm) < 1e-5
        assert np.all(np.diff(mh) > 0)


def test_more_massive_halos_make_more_stars():
    lm = jnp.array([11.0, 12.0, 13.0, 14.0])
    logsm = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH)))
    assert np.all(np.diff(logsm) > 0)
    # Sensible absolute scale: M*/Mh never exceeds the baryon fraction.
    assert np.all(logsm < np.asarray(lm) + np.log10(0.156) + 1e-5)


def test_chunked_matches_unchunked():
    lm = sample_log_halo_masses(20_000)
    a = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH)))
    b = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH),
                                  chunk_size=5_000))
    np.testing.assert_allclose(a, b, rtol=1e-6)
    # Ragged tail (chunk does not divide N — the shard-local case):
    # padded internally with the neutral sentinel, sliced back.
    c = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH),
                                  chunk_size=3_000))
    np.testing.assert_allclose(a, c, rtol=1e-6)


def test_ragged_shard_chunking_end_to_end():
    # The documented pod invocation: chunk_size need not divide the
    # shard-local halo count the mesh hands each device (review
    # finding r4: this crashed at trace time before).
    comm = mgt.global_comm()                  # 8 devices
    model = GalhaloHistModel(
        aux_data=make_galhalo_hist_data(16_000, comm=comm,
                                        chunk_size=1_500),
        comm=comm)                            # 2000 per shard, ragged
    loss, grad = model.calc_loss_and_grad_from_params(
        jnp.array(TRUTH_ARR + 0.03))
    assert np.isfinite(float(loss))
    assert np.all(np.isfinite(np.asarray(grad)))
    single = GalhaloHistModel(
        aux_data=make_galhalo_hist_data(16_000, chunk_size=1_500))
    l1, g1 = single.calc_loss_and_grad_from_params(
        jnp.array(TRUTH_ARR + 0.03))
    np.testing.assert_allclose(float(loss), float(l1), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(g1),
                               rtol=2e-3, atol=1e-6)


def test_fused_chunk_scan_matches_block_path():
    # The sumstats pipeline folds the binned reduction into the chunk
    # scan (no (N, K) readout is materialized); the fused scan must
    # agree with the single-block path in value AND gradient —
    # including a ragged tail, where the sentinel pad flows through
    # history, readout, and erf kernel.
    data_block = make_galhalo_hist_data(20_000)
    data_fused = dict(data_block, chunk_size=3_000)  # ragged: 6×3000+2000
    m_block = GalhaloHistModel(aux_data=data_block)
    m_fused = GalhaloHistModel(aux_data=data_fused)
    p = jnp.array(TRUTH_ARR + 0.04)
    s_block = np.asarray(m_block.calc_sumstats_from_params(p))
    s_fused = np.asarray(m_fused.calc_sumstats_from_params(p))
    # float32 summation-order tolerance: the fused path accumulates
    # per-chunk densities, the block path one global sum.  atol covers
    # near-empty tail bins (~1e-8 densities), whose absolute
    # summation-order jitter (~1e-12) is far above rtol.
    np.testing.assert_allclose(s_block, s_fused, rtol=1e-4, atol=1e-10)
    l0, g0 = m_block.calc_loss_and_grad_from_params(p)
    l1, g1 = m_fused.calc_loss_and_grad_from_params(p)
    # The log-space MSE loss amplifies the tail-bin jitter above
    # (log10 of ~1e-8 densities), so its bound is looser than the
    # sumstats'.
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-3, atol=1e-7)


def test_array_obs_indices_normalized_by_model():
    # An array-typed aux obs_indices would be promoted to a traced
    # jit argument by the model core; the model normalizes it to the
    # static-tuple convention at construction, so the natural array
    # form keeps working.
    data = make_galhalo_hist_data(2_000)
    data_arr = dict(data, obs_indices=np.array(data["obs_indices"]))
    m_tup = GalhaloHistModel(aux_data=data)
    m_arr = GalhaloHistModel(aux_data=data_arr)
    assert m_arr.aux_data["obs_indices"] == data["obs_indices"]
    p = jnp.array(TRUTH_ARR + 0.02)
    np.testing.assert_array_equal(
        np.asarray(m_tup.calc_sumstats_from_params(p)),
        np.asarray(m_arr.calc_sumstats_from_params(p)))


def test_traced_obs_indices_rejected():
    # A traced epoch index cannot be range-checked, and index 0 would
    # silently alias to the final epoch through jnp.take's wraparound;
    # epochs are configuration and must stay concrete.
    lm = sample_log_halo_masses(100)

    def f(oi):
        return mean_log_mstar(lm, jnp.array(TRUTH), obs_indices=oi)

    with pytest.raises(TypeError, match="concrete"):
        jax.jit(f)(jnp.array([7, 12]))


def test_obs_index_zero_rejected():
    # Grid index 0 has no cumulative integral; jnp.take would wrap
    # 0 - 1 to the LAST column and silently return the z=0 masses.
    lm = sample_log_halo_masses(100)
    with pytest.raises(ValueError, match="obs_indices"):
        mean_log_mstar(lm, jnp.array(TRUTH),
                       obs_indices=jnp.array([0, 7]))
    with pytest.raises(ValueError, match="obs_indices"):
        make_galhalo_hist_data(100, obs_indices=(0, 7, 15))


def test_multi_epoch_readout_is_cumulative():
    # M*(t) is non-decreasing across observation epochs.
    lm = sample_log_halo_masses(1_000)
    out = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH),
                                    obs_indices=jnp.array([7, 12, 15])))
    assert out.shape == (1_000, 3)
    assert np.all(np.diff(out, axis=1) >= 0)


def test_padding_neutral_forward_and_backward():
    lm = jnp.concatenate([sample_log_halo_masses(2_000),
                          jnp.full(48, 1e9)])
    out = np.asarray(mean_log_mstar(lm, jnp.array(TRUTH)))
    assert np.all(out[2_000:] == 1e18)          # the erf-kernel sentinel
    assert np.all(np.isfinite(out[:2_000]))

    def total(p):
        return jnp.sum(jnp.where(lm > 100.0, 0.0,
                                 mean_log_mstar(lm, p)))
    g = np.asarray(jax.grad(total)(jnp.array(TRUTH)))
    assert np.all(np.isfinite(g))


def test_all_ten_parameters_differentiable(model):
    params = jnp.array(TRUTH_ARR + 0.05)
    loss, grad = model.calc_loss_and_grad_from_params(params)
    g = np.asarray(grad)
    assert np.all(np.isfinite(g))
    assert np.all(np.abs(g) > 0), g              # every param matters
    # FD cross-check on two representative params.  eps must stay
    # coarse: the float32 loss (~0.06 here) resolves differences only
    # to ~1e-6, so eps below ~1e-2 measures reduction noise, not the
    # derivative (verified: eps=1e-3 flips the FD sign while 1e-2
    # matches autodiff to 4% on one XLA version and ~10% on another —
    # the tolerance bounds FD truncation noise, not autodiff quality).
    eps = 1e-2
    for i in (0, 8):
        e = jnp.zeros(10).at[i].set(eps)
        fd = (float(model.calc_loss_from_params(params + e))
              - float(model.calc_loss_from_params(params - e))) / (2 * eps)
        np.testing.assert_allclose(g[i], fd, rtol=1.5e-1, atol=1e-6)


def test_loss_zero_at_truth(model):
    loss, grad = model.calc_loss_and_grad_from_params(jnp.array(TRUTH))
    assert float(loss) < 1e-10
    assert np.all(np.isfinite(np.asarray(grad)))


def test_truth_recovery_multi_epoch(model):
    # Multi-epoch SMFs identify the history: from a perturbed guess,
    # BFGS recovers every parameter except the rollover sharpness k_t
    # tightly; k_t is honestly flat (it trades against the alpha
    # contrast at the few-1e-5 loss level) and gets a loose tolerance
    # rather than a false claim of identifiability.
    guess = TRUTH_ARR + np.array([0.15, -0.1, 0.05, -0.2, 0.08,
                                  -0.1, 0.1, -0.08, 0.02, 0.005])
    res = model.run_bfgs(guess=jnp.array(guess), maxsteps=500,
                         param_bounds=BOUNDS, progress=False)
    assert res.fun < 5e-5, res.fun
    err = np.abs(res.x - TRUTH_ARR)
    k_t_index = GalhaloHistParams._fields.index("k_t")
    loose = np.zeros(10, bool)
    loose[k_t_index] = True
    assert np.all(err[~loose] < 0.15), (res.x, err)
    assert err[k_t_index] < 0.5, res.x


def test_lhs_param_scan_on_history_model(model):
    # The reference's LHS survey API works on every family: one
    # vmapped SPMD dispatch over the 10-dim parameter space.
    t = TRUTH_ARR
    params, ss, losses = model.run_lhs_param_scan(
        xmins=t - 0.05, xmaxs=t + 0.05, n_dim=10,
        num_evaluations=8, seed=0)
    assert params.shape == (8, 10)
    assert ss.shape == (8, len(np.asarray(
        model.aux_data["target_sumstats"])))
    assert losses.shape == (8,)
    assert np.all(np.isfinite(ss)) and np.all(np.isfinite(losses))


def test_sharded_matches_single_device(data):
    comm = mgt.global_comm()
    sharded = GalhaloHistModel(
        aux_data=make_galhalo_hist_data(50_000, comm=comm), comm=comm)
    single = GalhaloHistModel(aux_data=data)
    p = jnp.array(TRUTH_ARR + 0.03)
    ss_s = np.asarray(sharded.calc_sumstats_from_params(p))
    ss_1 = np.asarray(single.calc_sumstats_from_params(p))
    np.testing.assert_allclose(ss_s, ss_1, rtol=2e-4, atol=1e-10)
    l_s, g_s = sharded.calc_loss_and_grad_from_params(p)
    l_1, g_1 = single.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(l_s), float(l_1), rtol=1e-3,
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_1),
                               rtol=2e-3, atol=1e-6)


@pytest.mark.slow  # ~10 s: interpret-mode pallas over the full model
def test_pallas_backend_matches_xla():
    # The per-particle (mass-dependent) scatter rides the vec-sigma
    # erf kernel; both backends must agree through the model layer.
    xla = GalhaloHistModel(
        aux_data=make_galhalo_hist_data(2_000, backend="xla"))
    pal = GalhaloHistModel(
        aux_data=make_galhalo_hist_data(2_000, backend="pallas"))
    p = jnp.array(TRUTH_ARR + 0.04)
    np.testing.assert_allclose(
        np.asarray(pal.calc_sumstats_from_params(p)),
        np.asarray(xla.calc_sumstats_from_params(p)), rtol=1e-3,
        atol=1e-9)
    # The loss tolerance is looser than the sumstats one: near-empty
    # early-epoch bins sit in the erf's deep tail, where the kernel's
    # clamped f32 polynomial and libm erf differ relatively, and the
    # log-space loss amplifies exactly those bins (~3% observed).
    lx, gx = xla.calc_loss_and_grad_from_params(p)
    lp, gp = pal.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(lp), float(lx), rtol=5e-2)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               rtol=1e-1, atol=1e-4)
