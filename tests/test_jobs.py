"""Job-DAG pipeline subsystem (multigrad_tpu/serve/jobs.py).

The PR-16 acceptance battery:

* DAG hygiene — duplicate names, unknown deps and cycles fail at
  ``Job()`` construction; a failed stage fails the job but settles
  the future, skipping (not running) its dependents;
* stage retry + checkpoint restore — a stage failing once re-runs
  within the job; a job re-submitted after a "crash" restores its
  completed stages from the stage-boundary checkpoint AND keeps its
  original trace identity;
* wire forward compatibility — ``job_id``/``stage`` decorated configs
  at an undecorated worker (the mixed-version-fleet invariant, same
  shape as the tracing tests);
* the joint SMF+wprp likelihood — the fused
  ``OnePointGroup([SMFChi2Model, WprpModel])`` loss/grad matches the
  sum of the solo members (tolerance twin of the static
  ``joint_smf_wprp`` lint target, which is also asserted clean here);
* the north-star end-to-end: ONE submitted job runs scan → ensemble
  → Laplace → HMC → predictive check for the joint likelihood
  through a live ``FitScheduler``, converges, settles ok, and yields
  a single COMPLETE trace whose waterfall holds every stage — plus
  the ``job_summary``/``predictive_check`` telemetry the report CLI
  folds into its ``job:`` section.

Host-only DAG tests use backend-free stages (no jax); the end-to-end
test runs a tiny joint catalog and short chains.
"""
import json
import os
import time
from dataclasses import dataclass, field

import numpy as np
import pytest

from multigrad_tpu.serve import (EnsembleStage, FitScheduler,
                                 FitStage, HmcStage, Job, JobRunner,
                                 LaplaceStage, PredictiveCheckStage,
                                 Stage, SweepStage)
from multigrad_tpu.serve.jobs import StageResult
from multigrad_tpu.telemetry import (JsonlSink, MemorySink,
                                     MetricsLogger)
from multigrad_tpu.telemetry import trace as trace_cli
from multigrad_tpu.telemetry import report as report_cli
from multigrad_tpu.telemetry.tracing import Tracer

JOINT_BOUNDS = ((-3.5, -0.5), (0.02, 1.0), (-2.5, 0.5))


# ------------------------------------------------------------------ #
# DAG hygiene
# ------------------------------------------------------------------ #
@dataclass
class NoteStage(Stage):
    """Backend-free stage: appends its name to a shared log and
    returns a tiny artifact (host-only DAG-machinery tests)."""

    log: list = field(default_factory=list)
    fail_times: int = 0
    payload: dict = field(default_factory=dict)

    def run(self, rt):
        self.log.append(self.name)
        if self.fail_times > 0:
            self.fail_times -= 1
            raise RuntimeError(f"{self.name} injected failure")
        return {"stage": self.name, **self.payload,
                "upstream": sorted(rt.artifacts)}


def test_job_validation():
    a, b = NoteStage("a"), NoteStage("b", deps=("a",))
    job = Job(stages=(a, b))
    assert job.job_id.startswith("job-")
    # single stage coerces to a tuple
    assert len(Job(stages=NoteStage("solo")).stages) == 1
    with pytest.raises(ValueError, match="duplicate"):
        Job(stages=(NoteStage("x"), NoteStage("x")))
    with pytest.raises(ValueError, match="unknown"):
        Job(stages=(NoteStage("x", deps=("ghost",)),))
    with pytest.raises(ValueError, match="cycle"):
        Job(stages=(NoteStage("x", deps=("y",)),
                    NoteStage("y", deps=("x",))))
    with pytest.raises(ValueError, match="at least one"):
        Job(stages=())


def test_failed_stage_skips_dependents_and_settles():
    log = []
    sink = MemorySink()
    telemetry = MetricsLogger(sink)
    runner = JobRunner(backend=None, telemetry=telemetry,
                       max_stage_attempts=1)
    job = Job(stages=(
        NoteStage("a", log=log),
        NoteStage("boom", deps=("a",), log=log, fail_times=5),
        NoteStage("after", deps=("boom",), log=log),
        NoteStage("side", deps=("a",), log=log),
    ))
    result = runner.run(job, timeout=30)
    assert not result.ok
    assert result.outcomes() == {
        "a": "ok", "boom": "failed", "after": "skipped",
        "side": "ok"}
    # the skipped stage never executed
    assert "after" not in log
    assert result.stages["boom"].error is not None
    # job_summary telemetry carries the per-stage outcomes
    recs = [r for r in sink.records if r["event"] == "job_summary"]
    assert len(recs) == 1 and recs[0]["ok"] is False
    outcomes = {s["stage"]: s["outcome"] for s in recs[0]["stages"]}
    assert outcomes["after"] == "skipped"


def test_stage_retry_succeeds_within_job():
    log = []
    runner = JobRunner(backend=None, max_stage_attempts=2)
    job = Job(stages=(NoteStage("flaky", log=log, fail_times=1),))
    result = runner.run(job, timeout=30)
    assert result.ok
    assert result.stages["flaky"].attempts == 2
    assert log == ["flaky", "flaky"]     # ran twice, settled once


def test_artifacts_flow_to_dependents():
    runner = JobRunner(backend=None)
    job = Job(stages=(
        NoteStage("up", payload={"value": 7}),
        NoteStage("down", deps=("up",)),
    ))
    result = runner.run(job, timeout=30)
    assert result.ok
    assert result.artifact("up")["value"] == 7
    assert result.artifact("down")["upstream"] == ["up"]


def test_duplicate_submit_rejected_while_running():
    runner = JobRunner(backend=None)
    slow = NoteStage("slow")
    orig_run = slow.run

    def stalling_run(rt):
        time.sleep(0.3)
        return orig_run(rt)

    slow.run = stalling_run
    job = Job(stages=(slow,), job_id="job-dup")
    fut = runner.submit(job)
    with pytest.raises(ValueError, match="already running"):
        runner.submit(Job(stages=(NoteStage("other"),),
                          job_id="job-dup"))
    assert fut.result(timeout=30).ok


# ------------------------------------------------------------------ #
# checkpoint restore (the lost-runner story)
# ------------------------------------------------------------------ #
def test_checkpoint_restores_completed_stages(tmp_path):
    trace_path = tmp_path / "trace.jsonl"
    tracer = Tracer(sink=str(trace_path), service="test")
    ckpt = str(tmp_path / "ckpt")
    log = []

    def make_job(fail_times):
        return Job(job_id="job-ck", stages=(
            NoteStage("a", log=log, payload={"value": 1}),
            NoteStage("b", deps=("a",), log=log,
                      fail_times=fail_times),
        ))

    runner = JobRunner(backend=None, tracer=tracer,
                       checkpoint_dir=ckpt, max_stage_attempts=1)
    r1 = runner.run(make_job(fail_times=5), timeout=30)
    assert not r1.ok and r1.stages["a"].outcome == "ok"
    # stage a is checkpointed; the torn run's trace id is too
    state = json.load(open(os.path.join(ckpt, "job-ck.json")))
    assert set(state["stages"]) == {"a"}
    assert state["trace"]["trace_id"] == r1.trace_id

    r2 = runner.run(make_job(fail_times=0), timeout=30)
    assert r2.ok
    assert r2.stages["a"].outcome == "restored"
    assert r2.stages["b"].outcome == "ok"
    assert log.count("a") == 1           # a never re-ran
    assert r2.artifact("a")["value"] == 1
    # ONE trace across runner generations
    assert r2.trace_id == r1.trace_id
    spans = trace_cli.load_spans([str(trace_path)])
    mine = [s for s in spans if s["trace_id"] == r2.trace_id]
    ids = {s["span_id"] for s in mine}
    assert not [s for s in mine if s.get("parent_span_id")
                and s["parent_span_id"] not in ids]


def test_unwritable_checkpoint_dir_fails_soft(tmp_path):
    # REVIEW regression: an OSError out of _write_checkpoint used to
    # escape the stage thread unrecorded — dependents never became
    # ready and the DAG loop spun forever.  Durability failures must
    # degrade (stage ok, telemetry notes the error), never hang.
    blocked = tmp_path / "blocked"
    blocked.write_text("a file where the checkpoint dir should be")
    sink = MemorySink()
    runner = JobRunner(backend=None,
                       telemetry=MetricsLogger(sink),
                       checkpoint_dir=str(blocked))
    log = []
    job = Job(stages=(
        NoteStage("a", log=log),
        NoteStage("b", deps=("a",), log=log),
    ))
    result = runner.run(job, timeout=30)
    assert result.ok
    assert log == ["a", "b"]             # dependent still ran
    errs = [r for r in sink.records
            if r["event"] == "job_bookkeeping_error"]
    assert errs and "a" in {r["stage"] for r in errs}


def test_stage_bookkeeping_crash_records_failed_stage():
    # REVIEW regression: an exception escaping _run_stage OUTSIDE the
    # per-attempt try (here: the success-path tracer.record) used to
    # kill the worker thread with no StageResult — the job either
    # hung or settled ok with the stage silently absent.  It must
    # settle as a failed stage with dependents skipped.
    class Ctx:
        trace_id, span_id = "t-1", "s-1"

        def child(self):
            return Ctx()

    class ExplodingTracer:
        def new_trace(self):
            return Ctx()

        def record(self, ctx, name, *a, **k):
            if name == "stage":
                raise OSError("trace sink is gone")

    runner = JobRunner(backend=None, tracer=ExplodingTracer(),
                       max_stage_attempts=1)
    log = []
    job = Job(stages=(
        NoteStage("a", log=log),
        NoteStage("b", deps=("a",), log=log),
    ))
    result = runner.run(job, timeout=30)   # must not hang
    assert not result.ok
    assert result.outcomes() == {"a": "failed", "b": "skipped"}
    assert "trace sink is gone" in result.stages["a"].error
    assert log == ["a"]                    # the stage body DID run


def test_fanout_checkpoint_reflects_all_settled_stages(tmp_path):
    # REVIEW regression: concurrent fan-out writers shared one
    # pid-keyed tmp file and snapshotted `results` unlocked, so the
    # published checkpoint could be torn or omit a concurrently
    # settled sibling.  The final checkpoint must hold every ok
    # stage.
    @dataclass
    class SlowStage(NoteStage):
        sleep_s: float = 0.05

        def run(self, rt):
            time.sleep(self.sleep_s)
            return super().run(rt)

    ckpt = tmp_path / "ckpt"
    runner = JobRunner(backend=None, checkpoint_dir=str(ckpt))
    job = Job(job_id="job-fan", stages=(
        SlowStage("left"), SlowStage("right"), SlowStage("mid"),
    ))
    assert runner.run(job, timeout=30).ok
    state = json.load(open(ckpt / "job-fan.json"))
    assert set(state["stages"]) == {"left", "right", "mid"}


def test_torn_checkpoint_restores_nothing(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "job-torn.json").write_text('{"job_id": "job-to')
    log = []
    runner = JobRunner(backend=None, checkpoint_dir=str(ckpt))
    job = Job(job_id="job-torn",
              stages=(NoteStage("a", log=log),))
    assert runner.run(job, timeout=30).ok
    assert log == ["a"]                  # ran from the top


# ------------------------------------------------------------------ #
# wire forward compatibility (job-decorated configs, mixed fleet)
# ------------------------------------------------------------------ #
def test_job_decorated_config_at_undecorated_worker():
    from multigrad_tpu.serve.queue import FitConfig
    from multigrad_tpu.serve.wire import (config_from_wire,
                                          config_to_wire)
    decorated = FitConfig(nsteps=7, learning_rate=0.05,
                          param_bounds=((-3.0, 0.0), None),
                          job_id="job-abc", stage="ensemble")
    wire = config_to_wire(decorated)
    # decorated router -> decorated worker: stamps survive
    assert config_from_wire(wire) == decorated
    assert config_from_wire(wire).job_id == "job-abc"
    # decorated router -> UNDECORATED worker: a pre-jobs worker reads
    # known keys only, so dropping the stamps must leave a valid
    # config (the strictly-additive-decoration contract)
    undecorated_view = {k: v for k, v in wire.items()
                        if k not in ("job_id", "stage")}
    legacy = config_from_wire(undecorated_view)
    assert legacy == FitConfig(nsteps=7, learning_rate=0.05,
                               param_bounds=((-3.0, 0.0), None))
    # undecorated worker -> decorated router: absent stamps decode
    # to None on results too
    from multigrad_tpu.serve.queue import FitResult
    from multigrad_tpu.serve.wire import (result_from_wire,
                                          result_to_wire)
    result = FitResult(request_id="r1", params=np.zeros(2), loss=0.1,
                       traj=np.zeros((1, 2)), steps=1, bucket=1,
                       wait_s=0.0, fit_s=0.1, job_id="job-abc",
                       stage="scan")
    assert result_from_wire(result_to_wire(result), "r1").stage \
        == "scan"
    legacy_wire = {k: v for k, v in result_to_wire(result).items()
                   if k not in ("job_id", "stage")}
    back = result_from_wire(legacy_wire, "r1")
    assert back.job_id is None and back.stage is None


def test_stage_stamp_separates_dispatch_groups():
    # Same knobs, different stage -> different batchability identity
    # (each stage's burst coalesces into its own bucket family and
    # keys its own fleet affinity); same stamp -> same identity.
    from multigrad_tpu.serve.queue import FitConfig
    base = dict(nsteps=5, learning_rate=0.01)
    scan = FitConfig(**base, job_id="j", stage="scan")
    assert scan == FitConfig(**base, job_id="j", stage="scan")
    assert scan != FitConfig(**base, job_id="j", stage="ensemble")
    assert scan != FitConfig(**base)
    with pytest.raises(TypeError, match="str or None"):
        FitConfig(**base, job_id=7)


# ------------------------------------------------------------------ #
# the joint SMF+wprp likelihood (satellite of the payoff workload)
# ------------------------------------------------------------------ #
@pytest.fixture(scope="module")
def joint_model():
    from multigrad_tpu.models import make_joint_smf_wprp
    return make_joint_smf_wprp(num_halos=256, smf_num_halos=1024,
                               comm="auto", seed=2)


def test_joint_group_matches_solo_sum(joint_model):
    import jax

    group = joint_model
    p = np.array([-2.1, 0.25, -0.9])
    loss, grad = group.calc_loss_and_grad_from_params(p)
    # tolerance twin: the fused program's joint loss/grad vs the solo
    # members evaluated through their param views and summed
    solo_loss, solo_grad = 0.0, np.zeros(3)
    for view in group.models:
        l_m, g_m = view.calc_loss_and_grad_from_params(p)
        solo_loss += float(l_m)
        solo_grad = solo_grad + np.asarray(g_m)
    np.testing.assert_allclose(float(loss), solo_loss, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), solo_grad,
                               rtol=1e-4, atol=1e-8)
    # both members actually contribute to the joint gradient
    g_members = [np.asarray(jax.grad(
        lambda q, m=m: m.calc_loss_from_params(q))(p))
        for m in group.models]
    assert all(np.linalg.norm(g) > 0 for g in g_members)


def test_joint_lint_target_clean():
    # The static half of the equivalence story: the fused group's
    # comm bound holds under catalog growth — every reduction
    # invariant, the wprp ring exchange at most linear.
    from multigrad_tpu.analysis import analyze
    from multigrad_tpu.analysis.findings import ERROR
    from multigrad_tpu.analysis.lint import (MODEL_TARGETS,
                                             _build_targets)
    assert "joint_smf_wprp" in MODEL_TARGETS
    targets = list(_build_targets(("joint_smf_wprp",), 256))
    assert len(targets) == 1
    name, group, params, kwargs = targets[0]
    findings = analyze(group, params, **kwargs)
    assert not [f for f in findings if f.severity == ERROR], findings


def test_joint_ring_exchange_not_exempt_without_declaration():
    # Guard the allowance's scope: WITHOUT the declared-linear list
    # the same fused trace still flags the ring exchange — the
    # exemption is opt-in per target, not a global loosening.
    from multigrad_tpu.analysis import analyze
    from multigrad_tpu.analysis.lint import _build_targets
    name, group, params, kwargs = \
        list(_build_targets(("joint_smf_wprp",), 256))[0]
    findings = analyze(group, params, checks=("comm-scaling",))
    assert any("ppermute" in f.message for f in findings)


# ------------------------------------------------------------------ #
# predictive-check verdict semantics
# ------------------------------------------------------------------ #
class _FixedLossModel:
    """Fake model: the batched program returns canned per-row losses
    (row 0 is the posterior mean by the stage's batch layout)."""

    def __init__(self, losses):
        self._losses = np.asarray(losses, dtype=float)

    def batched_loss_and_grad_fn(self, include_grad):
        def program(batch, aux, z):
            return self._losses[: batch.shape[0]], None
        return program

    def aux_leaves(self):
        return ()


def _run_check(losses, **kwargs):
    from multigrad_tpu.serve.stages import StageRuntime
    stage = PredictiveCheckStage("check", deps=("hmc",), **kwargs)
    n_draws = len(losses) - 1
    rt = StageRuntime(
        job_id="j", stage="check", model=_FixedLossModel(losses),
        artifacts={"hmc": {"draws": [[0.0]] * n_draws,
                           "posterior_mean": [0.0]}})
    return stage.run(rt)


def test_predictive_check_negative_losses_can_fail():
    # REVIEW regression: with log-likelihood-style (negative) losses
    # the old median/|loss_at_mean| ratio was negative for ANY
    # negative median, so no threshold could ever fail a posterior
    # that wandered off its basin.  The shifted excess can.
    wandered = [-1000.0] + [-1.0] * 8     # 999 units off the basin
    art = _run_check(wandered, max_median_excess=0.5)
    assert art["verdicts"]["concentrated"] is False
    assert not art["ok"]
    assert art["median_excess"] == pytest.approx(0.999)
    # ... while a posterior hugging the basin passes the same gate
    tight = [-1000.0] + [-999.5] * 8
    assert _run_check(tight, max_median_excess=0.5)["ok"]


def test_predictive_check_positive_losses_unchanged():
    # Positive (chi2-style) losses keep the old semantics: a median
    # draw loss far above the basin fails, a nearby one passes.
    assert not _run_check([2.0] + [250.0] * 8)["verdicts"][
        "concentrated"]
    assert _run_check([2.0] + [2.5] * 8)["ok"]


# ------------------------------------------------------------------ #
# the north-star: one job, whole pipeline, one trace
# ------------------------------------------------------------------ #
def test_job_pipeline_end_to_end(joint_model, tmp_path):
    from multigrad_tpu.models import JOINT_TRUTH

    tel_path = tmp_path / "telemetry.jsonl"
    trace_path = tmp_path / "trace.jsonl"
    telemetry = MetricsLogger(JsonlSink(str(tel_path)))
    tracer = Tracer(sink=str(trace_path), service="test")
    from multigrad_tpu.telemetry.live import LiveMetrics
    metrics = LiveMetrics()

    job = Job(job_id="job-e2e", stages=(
        SweepStage("scan", n_points=4, nsteps=15, learning_rate=0.1,
                   param_bounds=JOINT_BOUNDS),
        EnsembleStage("ensemble", deps=("scan",), n_starts=2,
                      nsteps=100, learning_rate=0.02,
                      param_bounds=JOINT_BOUNDS),
        LaplaceStage("laplace", deps=("ensemble",)),
        HmcStage("hmc", deps=("ensemble", "laplace"),
                 num_samples=25, num_warmup=20, num_chains=2,
                 num_leapfrog=3),
        PredictiveCheckStage("check", deps=("hmc",), max_draws=16),
    ))
    with FitScheduler(joint_model, telemetry=telemetry,
                      tracer=tracer) as sched:
        runner = JobRunner(sched, live=metrics,
                           checkpoint_dir=str(tmp_path / "ckpt"))
        assert runner.model is joint_model
        fut = runner.submit(job)
        result = fut.result(timeout=600)

    # -- settles ok, every stage ran, posterior converged ------------
    assert result.ok
    assert result.outcomes() == {
        "scan": "ok", "ensemble": "ok", "laplace": "ok",
        "hmc": "ok", "check": "ok"}
    ens = result.artifact("ensemble")
    np.testing.assert_allclose(ens["best_params"], JOINT_TRUTH,
                               atol=0.3)
    assert result.artifact("laplace")["stderr"]
    assert result.artifact("check")["ok"]
    assert fut.stage_results["hmc"].ok

    # -- gauges --------------------------------------------------------
    snap = metrics.snapshot()
    assert "multigrad_jobs_total" in snap
    assert any("ok" in labels for labels
               in snap["multigrad_jobs_total"]["samples"])
    assert "multigrad_job_stages_total" in snap
    assert "multigrad_job_active" in snap

    # -- ONE complete trace, waterfall holds every stage ---------------
    spans = trace_cli.load_spans([str(trace_path)])
    traces = trace_cli.group_traces(spans)
    assert result.trace_id in traces
    summary = trace_cli.trace_summary(result.trace_id,
                                      traces[result.trace_id])
    assert summary["complete"], summary
    assert summary["root"]["name"] == "job"
    assert set(summary["stages"]) == {"scan", "ensemble", "laplace",
                                      "hmc", "check"}
    assert all(st["ok"] for st in summary["stages"].values())
    waterfall = trace_cli.render_waterfall(result.trace_id,
                                           traces[result.trace_id])
    for stage_name in ("scan", "ensemble", "laplace", "hmc",
                       "check"):
        assert f"stage {stage_name}" in waterfall
    # per-fit request spans are grouped under their stage
    assert "request [scan]" in waterfall

    # -- telemetry: report CLI renders the job: section ----------------
    records = report_cli.load_records(str(tel_path))
    folded = report_cli.summarize(records)
    assert folded["job"]["jobs"][0]["job_id"] == "job-e2e"
    assert folded["job"]["jobs"][0]["ok"]
    rendered = report_cli.render(folded)
    assert "job: job-e2e" in rendered
    assert "stage hmc: ok" in rendered
    assert "check check: ok" in rendered
    checks = [r for r in records
              if r.get("event") == "predictive_check"]
    assert checks and checks[0]["job_id"] == "job-e2e"
    # fit_summary records carry the stage stamp through the scheduler
    fits = [r for r in records if r.get("event") == "fit_summary"]
    assert {r.get("stage") for r in fits} >= {"scan", "ensemble"}
