"""Autotuner suite: table persistence, the two-stage tune loop, the
canonical fused-bins fixture, "auto" resolution through every
consumer, bucket-ladder tuning, and the regress/report satellites."""
import json
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.serve.compile_cache import DEFAULT_BUCKETS
from multigrad_tpu.serve.scheduler import FitScheduler
from multigrad_tpu.tune import (TuningTable, make_key,
                                model_shape_key, tune_buckets,
                                tune_model, tune_streaming,
                                within_noise)
from multigrad_tpu.tune.resolve import (resolve_donate_carry,
                                        resolve_stream_knobs)
from multigrad_tpu.tune.tuner import model_key

GUESS = jnp.array([-1.0, 0.5])


def small_smf(n=4000, **kw):
    return SMFModel(aux_data=make_smf_data(n, **kw))


# ------------------------------------------------------------------ #
# Tuning table
# ------------------------------------------------------------------ #
def test_table_round_trip_and_merge(tmp_path):
    path = str(tmp_path / "t.json")
    t1 = TuningTable(path)
    assert t1.lookup("model|X|rows2^10|cpu|cpu") is None
    t1.record("k1", {"bin_mode": "fused", "bin_window": 10},
              measured_s=0.1, predicted_s=0.09)
    # A fresh instance on the same path (the process-restart proxy)
    # sees the entry, fully typed.
    t2 = TuningTable(path)
    entry = t2.lookup("k1")
    assert entry["knobs"] == {"bin_mode": "fused", "bin_window": 10}
    assert entry["measured_s"] == 0.1
    # Writes merge: a second key through a third instance keeps k1.
    TuningTable(path).record("k2", {"chunk_size": None})
    assert set(TuningTable(path).entries()) == {"k1", "k2"}
    # A torn table is a cache miss, not a crash.
    with open(path, "w") as f:
        f.write('{"entries": {"k1"')
    assert TuningTable(path).lookup("k1") is None


def test_table_across_real_process_restart(tmp_path):
    """Warm-start asset proof: an entry written here resolves in a
    genuinely fresh interpreter (the fleet-worker scenario)."""
    path = str(tmp_path / "t.json")
    TuningTable(path).record("model|SMFModel|rows2^12|e11|w11|cpu|cpu",
                             {"bin_mode": "dense"})
    out = subprocess.run(
        [sys.executable, "-c",
         "import json, sys\n"
         "from multigrad_tpu.tune.table import TuningTable\n"
         "e = TuningTable(sys.argv[1]).lookup("
         "'model|SMFModel|rows2^12|e11|w11|cpu|cpu')\n"
         "print(json.dumps(e['knobs']))", path],
        capture_output=True, text=True, timeout=120,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"})
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout.strip()) == {"bin_mode": "dense"}


# ------------------------------------------------------------------ #
# tune_model: two-stage loop + warm start
# ------------------------------------------------------------------ #
def test_tune_model_measures_then_warm_starts(tmp_path):
    table = TuningTable(str(tmp_path / "t.json"))
    model = small_smf()
    res = tune_model(model, GUESS, sigma_max=0.6, table=table,
                     reps=1, trial="eval")
    assert not res.warm and res.n_trials >= 2
    # Every candidate carries the static prediction; survivors carry
    # the measured confirmation; exactly one is chosen.
    assert all(c["predicted_s"] is not None for c in res.candidates)
    assert sum(c["chosen"] for c in res.candidates) == 1
    assert res.chosen["bin_mode"] in ("dense", "fused")
    entry = table.lookup(res.key)
    assert entry["knobs"] == res.chosen
    assert entry["baseline_s"] is not None
    # Warm start: the table resolves with ZERO measured trials.
    res2 = tune_model(model, GUESS, sigma_max=0.6, table=table)
    assert res2.warm and res2.n_trials == 0
    assert res2.chosen == res.chosen
    # force=True re-measures.
    res3 = tune_model(model, GUESS, sigma_max=0.6, table=table,
                      reps=1, trial="eval", force=True)
    assert not res3.warm and res3.n_trials >= 2
    # Package exports.
    assert mgt.tune_model is tune_model
    assert mgt.TuningTable is TuningTable


def test_within_noise_tolerance_rules():
    assert within_noise(1.0, 1.05, pct=10.0, floor_ms=0.0)
    assert not within_noise(1.3, 1.0, pct=10.0, floor_ms=0.0)
    # The absolute floor quiets sub-RTT deltas at any percentage.
    assert within_noise(0.0021, 0.001, pct=10.0, floor_ms=2.0)
    assert within_noise(0.9, 1.0, pct=0.0, floor_ms=0.0)  # faster


# ------------------------------------------------------------------ #
# The canonical fixture: BENCH_r06's fused-bins A/B pair
# ------------------------------------------------------------------ #
@pytest.mark.slow  # ~26 s: measured A/B trials of both bin backends
def test_canonical_fused_bins_fixture(tmp_path, monkeypatch):
    """bin_mode="auto" must resolve to fused at sigma~0.05 and dense
    at sigma~0.2 — the tuner's measured stage must keep the 2.15x and
    eliminate the 0.57x regression (the static model alone would pick
    fused in BOTH regimes: fewer transcendentals either way)."""
    from multigrad_tpu.models.galhalo_hist import (GalhaloHistModel,
                                                   TRUTH,
                                                   make_galhalo_hist_data)

    table_path = str(tmp_path / "t.json")
    monkeypatch.setenv("MGT_TUNING_TABLE", table_path)
    table = TuningTable(table_path)
    edges = np.linspace(7.0, 11.75, 41)
    obs = (5, 7, 9, 11, 13, 15)
    n = 120_000
    truth = np.asarray(TRUTH)
    tight = truth.copy()
    tight[8], tight[9] = 0.05, -0.005

    expected = {"sigma005": "fused", "sigma02": "dense"}
    for tag, params, sigma_max in (("sigma005", tight, 0.08),
                                   ("sigma02", truth, 0.32)):
        aux = make_galhalo_hist_data(n, bin_edges=edges,
                                     obs_indices=obs)
        res = tune_model(GalhaloHistModel(aux_data=aux),
                         jnp.asarray(params), sigma_max=sigma_max,
                         table=table, reps=2, trial="eval")
        assert res.chosen["bin_mode"] == expected[tag], \
            f"{tag}: {res.candidates}"
        # Static prediction AND measured confirmation both recorded
        # for the chosen candidate (the "why" the report shows).
        chosen = [c for c in res.candidates if c["chosen"]][0]
        assert chosen["predicted_s"] is not None
        assert chosen["measured_s"] is not None
        # End to end: an "auto" model resolves through the table.
        auto = GalhaloHistModel(aux_data=make_galhalo_hist_data(
            n, bin_edges=edges, obs_indices=obs, bin_mode="auto",
            sigma_max=sigma_max))
        assert auto.aux_data["bin_mode"] == expected[tag]
        if expected[tag] == "fused":
            assert auto.aux_data["bin_window"] == \
                res.chosen["bin_window"]
    # The two regimes live under DIFFERENT keys (the window is the
    # sigma-regime discriminator) — both model entries coexist
    # (standalone-op alias entries ride alongside).
    model_keys = [k for k in table.entries()
                  if k.startswith("model|GalhaloHistModel|")]
    assert len(model_keys) == 2


# ------------------------------------------------------------------ #
# "auto" resolution: cold-table fallbacks everywhere
# ------------------------------------------------------------------ #
def test_auto_resolution_cold_table(tmp_path, monkeypatch):
    monkeypatch.setenv("MGT_TUNING_TABLE",
                       str(tmp_path / "missing.json"))
    model = small_smf(bin_mode="auto", chunk_size="auto")
    assert model.aux_data["bin_mode"] == "dense"      # historical
    assert model.aux_data["chunk_size"] is None       # defaults
    assert model.aux_data["bin_window"] == 11         # derived, kept
    # Standalone op call with "auto" == dense on a cold table.
    from multigrad_tpu.ops.binned import binned_erf_counts
    vals = jnp.linspace(9.0, 10.0, 512)
    edges = jnp.linspace(9, 10, 11)
    np.testing.assert_array_equal(
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="auto")),
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="dense")))
    # A fit on the auto model runs (donate pickup is a no-op cold).
    traj = model.run_adam(guess=GUESS, nsteps=3, progress=False)
    assert np.all(np.isfinite(np.asarray(traj)))


def test_auto_resolution_applies_table_entry(tmp_path, monkeypatch):
    table_path = str(tmp_path / "t.json")
    monkeypatch.setenv("MGT_TUNING_TABLE", table_path)
    model = small_smf(bin_mode="auto")      # resolves cold -> dense
    key = model_key(model, bin_window=model.aux_data["bin_window"])
    TuningTable(table_path).record(
        key, {"bin_mode": "fused", "bin_window": 11,
              "chunk_size": 2048, "donate_carry": False})
    tuned = small_smf(bin_mode="auto", chunk_size="auto")
    assert tuned.aux_data["bin_mode"] == "fused"
    assert tuned.aux_data["bin_window"] == 11
    assert tuned.aux_data["chunk_size"] == 2048
    # Fused(full-window) == dense bin-for-bin: same loss either way.
    np.testing.assert_allclose(
        float(tuned.calc_loss_from_params(GUESS)),
        float(model.calc_loss_from_params(GUESS)), rtol=1e-6)
    # donate_carry rides the same entry.
    assert resolve_donate_carry(tuned) is False
    assert resolve_donate_carry(small_smf(n=16_000)) is None  # miss


def test_windowless_sigma_aux_keys_agree(tmp_path, monkeypatch):
    """An aux carrying ``sigma_max`` but no stored ``bin_window`` —
    the CLI's own shape: ``make_smf_data(n, sigma_max=...)`` with the
    default dense mode — must key identically on the write side
    (``model_key`` derives the window from the sigma bound) and the
    read side (``aux_model_key`` on the auto-rewritten aux), or a
    tuned winner silently resolves cold."""
    from multigrad_tpu.tune.resolve import aux_model_key

    table_path = str(tmp_path / "t.json")
    monkeypatch.setenv("MGT_TUNING_TABLE", table_path)
    aux = make_smf_data(4000, sigma_max=0.6)   # dense: no window stored
    assert aux.get("bin_window") is None
    model = SMFModel(aux_data=aux)
    wkey = model_key(model, sigma_max=0.6)
    rkey = aux_model_key("SMFModel",
                         dict(aux, bin_mode="auto", chunk_size="auto"))
    assert wkey == rkey
    # End to end: a non-default winner under the write key is what the
    # auto model comes up on.
    TuningTable(table_path).record(
        wkey, {"bin_mode": "fused", "bin_window": 11,
               "chunk_size": 2048})
    tuned = SMFModel(aux_data=dict(aux, bin_mode="auto",
                                   chunk_size="auto"))
    assert tuned.aux_data["bin_mode"] == "fused"
    assert tuned.aux_data["chunk_size"] == 2048


def test_tune_model_writes_op_alias(tmp_path, monkeypatch):
    """A binned-kernel tune also records the standalone-op key, so a
    direct ``binned_erf_counts(bin_mode="auto")`` call on the tuned
    shape WITH the matching window resolves to the model-level
    winner.  Only the windowed key is aliased — the window is the
    sigma-regime discriminator, so a windowless call must stay dense
    rather than inherit another regime's fused window (wrong counts,
    not just a slow path)."""
    table_path = str(tmp_path / "t.json")
    monkeypatch.setenv("MGT_TUNING_TABLE", table_path)
    model = small_smf(sigma_max=0.6)
    tune_model(model, np.asarray(GUESS), sigma_max=0.6,
               table=TuningTable(table_path), trial_steps=2, reps=1)
    keys = sorted(TuningTable(table_path).entries())
    aliases = [k for k in keys if "binned_erf_counts" in k]
    assert len(aliases) == 1                    # windowed only
    assert "|w0|" not in aliases[0]
    from multigrad_tpu.ops.binned import binned_erf_counts
    vals = jnp.asarray(model.aux_data["log_halo_masses"])
    edges = jnp.asarray(model.aux_data["smf_bin_edges"])
    # Windowless "auto" stays dense (no regime info = no fused).
    np.testing.assert_allclose(
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="auto")),
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="dense")), rtol=1e-6)
    # Force a fused winner under the windowed alias: the matching
    # windowed "auto" call picks it up, the windowless one cannot.
    TuningTable(table_path).record(
        aliases[0], {"bin_mode": "fused", "bin_window": 11})
    window = int(aliases[0].split("|")[4][1:])
    # (fused accumulates in a different order — near-empty bins carry
    # float32 noise that is absolutely tiny but relatively large)
    np.testing.assert_allclose(
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="auto",
                                     bin_window=window)),
        np.asarray(binned_erf_counts(vals, edges, 0.1,
                                     bin_mode="dense")),
        rtol=1e-4, atol=1e-5)


def test_tune_eval_trial_collapses_donate_variants(tmp_path):
    """An explicit ``trial="eval"`` never exercises carry donation —
    the donate variants run identical programs — so the tuner must
    not persist a donate_carry verdict from it (ranking identical
    programs is pure timing noise)."""
    model = small_smf(sigma_max=0.6)
    cands = [
        {"bin_mode": "dense", "bin_window": None, "chunk_size": None,
         "donate_carry": None},
        {"bin_mode": "dense", "bin_window": None, "chunk_size": None,
         "donate_carry": True},
        {"bin_mode": "dense", "bin_window": None, "chunk_size": None,
         "donate_carry": False},
    ]
    res = tune_model(model, np.asarray(GUESS), sigma_max=0.6,
                     table=TuningTable(str(tmp_path / "t.json")),
                     trial="eval", reps=1, candidates=cands)
    assert res.chosen.get("donate_carry") is None
    # All three collapsed to ONE candidate: one trial set, no noise
    # ranking between identical programs.
    assert len(res.candidates) == 1


def test_tune_buckets_max_sizes_one(tmp_path):
    """``max_sizes=1`` keeps exactly the K=1 rung (the cap slice must
    not wrap around to the whole ladder)."""
    model = small_smf(n=1000)
    res = tune_buckets(model, np.asarray(GUESS), candidates=(1, 2),
                       nsteps=2, reps=1, max_sizes=1,
                       table=TuningTable(str(tmp_path / "t.json")))
    assert res.chosen["buckets"] == [1]


# ------------------------------------------------------------------ #
# Cost model over chunked/streamed programs
# ------------------------------------------------------------------ #
def test_model_cost_chunked_invariance_and_scan_scaling():
    """Chunked execution must not change the statically-predicted
    work (same data, different tiling), and the streamed scan's cost
    must scale EXACTLY with the chunk count — the costmodel twin of
    the analyzer's comm-scaling trick."""
    import jax

    from multigrad_tpu.telemetry.costmodel import (
        estimate_program_cost, model_cost)

    n = 8192
    resident = small_smf(n)
    chunked = small_smf(n, chunk_size=1024)
    c_res = model_cost(resident, GUESS)
    c_chn = model_cost(chunked, GUESS)
    # (B+1)·N erf forward; identical whether or not the particle axis
    # is tiled (the scan-trip multiplier restores the total).
    assert c_res.transcendentals["erf"] == n * 11
    assert c_chn.transcendentals["erf"] == n * 11

    # Streamed scan program at 2 vs 4 chunks of the same chunk size:
    # twice the data, exactly twice the transcendental count.
    aux = make_smf_data(n)
    del aux["log_halo_masses"]
    model = SMFModel(aux_data=aux)
    program = model.chunk_scan_loss_and_grad_fn(
        ("log_halo_masses",))
    params = jax.ShapeDtypeStruct((2,), jnp.result_type(float))
    key = jnp.zeros(())

    def cost_at(n_chunks):
        stack = [jax.ShapeDtypeStruct((n_chunks, 1024),
                                      jnp.result_type(float))]
        return estimate_program_cost(program, params, stack,
                                     model.aux_leaves(), key)

    c2, c4 = cost_at(2), cost_at(4)
    assert c4.transcendentals["erf"] == 2 * c2.transcendentals["erf"]
    assert c4.transcendentals["exp"] == 2 * c2.transcendentals["exp"]


# ------------------------------------------------------------------ #
# Bucket-ladder tuning + scheduler/worker resolution
# ------------------------------------------------------------------ #
def test_tune_buckets_and_scheduler_boot(tmp_path):
    table = TuningTable(str(tmp_path / "t.json"))
    model = small_smf(n=1000)
    res = tune_buckets(model, np.asarray(GUESS),
                       candidates=(1, 2, 4), nsteps=5, reps=1,
                       table=table)
    ladder = res.chosen["buckets"]
    assert ladder[0] == 1 and all(b in (1, 2, 4) for b in ladder)
    assert all(c.get("fits_per_hour") for c in res.candidates)
    # The scheduler boots on the tuned ladder...
    sched = FitScheduler(model, buckets="auto", tuning_table=table,
                         start=False)
    assert sched.buckets == tuple(sorted(set(ladder)))
    sched.close(drain=False)
    # ...serves on it...
    sched = FitScheduler(model, buckets="auto", tuning_table=table,
                         start=False)
    fut = sched.submit(np.asarray(GUESS), nsteps=5)
    sched.start()
    assert np.all(np.isfinite(fut.result(timeout=60).params))
    sched.close()
    # ...and a warm re-tune costs zero trials.
    assert tune_buckets(model, np.asarray(GUESS),
                        table=table).warm


def test_scheduler_auto_cold_falls_back_to_defaults(tmp_path):
    sched = FitScheduler(small_smf(n=1000), buckets="auto",
                         tuning_table=str(tmp_path / "none.json"),
                         start=False)
    assert sched.buckets == DEFAULT_BUCKETS
    sched.close(drain=False)
    with pytest.raises(ValueError):
        FitScheduler(small_smf(n=1000), buckets="buckets",
                     start=False)


# ------------------------------------------------------------------ #
# Streaming knobs
# ------------------------------------------------------------------ #
def test_stream_auto_resolution_and_tune(tmp_path, monkeypatch):
    from multigrad_tpu.data import StreamingOnePointModel

    table_path = str(tmp_path / "t.json")
    monkeypatch.setenv("MGT_TUNING_TABLE", table_path)
    n = 8192
    from multigrad_tpu.models.smf import load_halo_masses
    log_mh = np.asarray(jnp.log10(load_halo_masses(n)))
    aux = make_smf_data(n)
    del aux["log_halo_masses"]

    def smodel(**kw):
        return StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux)),
            streams={"log_halo_masses": log_mh}, **kw)

    # Cold: bounded power-of-two fallback + the "dots" default.
    cold = smodel(chunk_rows="auto", remat_policy="auto")
    assert cold.chunk_rows == n and cold.remat_policy == "dots"
    # Tuned: short measured trials pick a chunk size; "auto" applies.
    res = tune_streaming(smodel(chunk_rows=2048), GUESS,
                         table=TuningTable(table_path),
                         trial_steps=1, reps=1)
    assert res.chosen["chunk_rows"] >= 1024
    assert table_entry_rows(table_path) == res.chosen["chunk_rows"]
    tuned = smodel(chunk_rows="auto")
    assert tuned.chunk_rows == res.chosen["chunk_rows"]
    # resolve_stream_knobs is the underlying hook.
    rows, policy = resolve_stream_knobs(
        "SMFModel", n, None, table=table_path)
    assert rows == res.chosen["chunk_rows"] and policy == "dots"


def table_entry_rows(path):
    entries = TuningTable(path).entries()
    key = [k for k in entries if k.startswith("stream|")][0]
    return entries[key]["knobs"]["chunk_rows"]


# ------------------------------------------------------------------ #
# Satellites: regress tuned gate + report tune section
# ------------------------------------------------------------------ #
def test_regress_compare_tuned_and_cli(tmp_path):
    from multigrad_tpu.telemetry import regress

    dossier = {
        "configs": {
            "tuned_defaults": {
                "sigma005": {"handset_s": 1.0, "tuned_s": 0.45,
                             "bin_window": 10},
                "sigma02": {"handset_s": 1.0, "tuned_s": 1.04},
            },
            "smf_1e6_tuned": {"handset_steps_per_sec": 100.0,
                              "tuned_steps_per_sec": 101.0},
        },
        "tunnel_rtt_ms": 0.03,
    }
    path = tmp_path / "BENCH_rX.json"
    path.write_text(json.dumps(dossier))
    round_ = regress.load_dossier(str(path))
    results = {r["metric"]: r["status"]
               for r in regress.compare_tuned(round_)}
    assert results["tuned_defaults.sigma005.tuned_s"] == "improved"
    assert results["tuned_defaults.sigma02.tuned_s"] == "ok"
    assert results["smf_1e6_tuned.tuned_steps_per_sec"] == "ok"
    # bin_window is bookkeeping: no pair judged for it.
    assert "tuned_defaults.sigma005.bin_window" not in results
    assert regress.main(["--tuned", str(path)]) == 0

    # A tuner pick slower than the hand-set default fails the gate.
    dossier["configs"]["tuned_defaults"]["sigma02"]["tuned_s"] = 1.8
    bad = tmp_path / "BENCH_rY.json"
    bad.write_text(json.dumps(dossier))
    round_bad = regress.load_dossier(str(bad))
    statuses = {r["metric"]: r["status"]
                for r in regress.compare_tuned(round_bad)}
    assert statuses["tuned_defaults.sigma02.tuned_s"] == "regressed"
    assert regress.main(["--tuned", str(bad)]) == 1
    assert regress.main(["--tuned", "--warn-only", str(bad)]) == 0
    # Direction on throughput pairs: a tuned slowdown regresses too.
    dossier["configs"]["smf_1e6_tuned"]["tuned_steps_per_sec"] = 50.0
    worse = tmp_path / "BENCH_rZ.json"
    worse.write_text(json.dumps(dossier))
    assert {r["metric"]: r["status"] for r in regress.compare_tuned(
        regress.load_dossier(str(worse)))}[
        "smf_1e6_tuned.tuned_steps_per_sec"] == "regressed"


def test_report_tune_section():
    from multigrad_tpu.telemetry import report

    records = [
        {"event": "run", "t": 0.0, "jax_version": "x",
         "backend": "cpu"},
        {"event": "tune", "t": 1.0, "key": "model|SMFModel|s|cpu|cpu",
         "scope": "model", "knobs": {"bin_mode": "dense"},
         "predicted_s": 1e-4, "measured_s": 2e-3, "chosen": False},
        {"event": "tune", "t": 1.1, "key": "model|SMFModel|s|cpu|cpu",
         "scope": "model", "knobs": {"bin_mode": "fused",
                                     "bin_window": 10},
         "predicted_s": 9e-5, "measured_s": 1e-3, "chosen": True},
    ]
    summary = report.summarize(records)
    assert summary["tune"]["records"] == 2
    assert summary["tune"]["chosen"][0]["knobs"]["bin_mode"] \
        == "fused"
    rendered = report.render(summary)
    assert "tune:" in rendered and "fused" in rendered


# ------------------------------------------------------------------ #
# CLI
# ------------------------------------------------------------------ #
def test_tune_cli_receipt_and_telemetry(tmp_path, capsys):
    from multigrad_tpu.tune.__main__ import main

    table = str(tmp_path / "t.json")
    telem = str(tmp_path / "tune.jsonl")
    rc = main(["--num-halos", "3000", "--trial-steps", "3",
               "--reps", "1", "--table", table,
               "--telemetry", telem, "--tune-buckets",
               "--bucket-candidates", "1,2", "--bucket-nsteps", "4"])
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "TUNE OK" in out.out
    assert "TUNE scheduler boots buckets=" in out.err
    tune_recs = [json.loads(line) for line
                 in open(telem) if '"tune"' in line]
    assert any(r.get("chosen") for r in tune_recs)
    keys = TuningTable(table).entries()
    assert len([k for k in keys if k.startswith("model|SMFModel|")]) \
        == 1                                 # model key …
    assert len([k for k in keys if k.startswith("buckets|")]) == 1
    # … plus the standalone-op alias entries riding alongside.
    # Warm second invocation: zero measured trials, same receipt.
    rc2 = main(["--num-halos", "3000", "--table", table,
                "--tune-buckets", "--bucket-candidates", "1,2"])
    out2 = capsys.readouterr()
    assert rc2 == 0
    assert "warm=True" in out2.err


def test_key_shape_helpers():
    assert model_shape_key(1_000_000, 41, 10) == "rows2^20|e41|w10"
    assert model_shape_key(4096) == "rows2^12"
    key = make_key("model", "SMFModel", "rows2^12",
                   backend="cpu", device_kind="TFRT CPU")
    assert key == "model|SMFModel|rows2^12|cpu|tfrt_cpu"
