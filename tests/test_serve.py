"""Fit-fleet serving layer (multigrad_tpu/serve/).

The PR-10 tentpole's acceptance battery:

* pad-and-pack correctness — bucketed batched results bitwise-match
  a sequential solo fit per request (Adam's elementwise update makes
  batch rows exact independent fits; padding rows never perturb real
  ones);
* bounded retraces — for N >> bucket-count same-config requests, the
  segment program traces at most once per bucket size (the same
  trace-counting assertion shape as the telemetry tap tests);
* NaN poison-request isolation — batch-mates succeed bitwise, the
  poisoned request alone errors with a flight-recorder bundle path
  (plus the retry-once-on-a-fresh-bucket policy);
* deadline / cancel / backpressure semantics and graceful drain;
* compile-cache warm start — after ``jax.clear_caches()`` (the
  fresh-process stand-in) a dispatch recompiles entirely from the
  persistent on-disk cache: zero new cache entries.

Everything runs tiny catalogs (hundreds of halos) and short fits, so
the whole module is a few seconds of tier-1 budget.
"""
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dataclasses import dataclass, field

import multigrad_tpu as mgt
from multigrad_tpu.core.model import OnePointModel
from multigrad_tpu.inference import run_multistart_adam
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.parallel.collectives import scatter_nd
from multigrad_tpu.serve import (FitCancelled, FitConfig,
                                 FitDeadlineExceeded, FitFailed,
                                 FitScheduler, QueueFullError,
                                 cache_entries, enable_compile_cache,
                                 warmup_buckets)
from multigrad_tpu.telemetry import LiveSink, MemorySink, MetricsLogger

BOUNDS = [(-5.0, 1.0), (0.01, 2.0)]
POISON = np.array([np.nan, 0.5])


@dataclass
class ExactModel(OnePointModel):
    """A model whose every reduction is EXACT in float32.

    The data are equal powers of two, so partial sums are exact in
    any association — the one arithmetic regime where "bucketed
    batched result == solo result" is a bitwise guarantee by
    construction, not an accident of XLA's reduce order.  (Real
    models' float reductions can differ in the last ULP between the
    vmapped and solo program shapes; the SMF checks below use
    tolerances for exactly that reason.)
    """

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        x = jnp.asarray(self.aux_data["x"])
        return jnp.sum(x) * params          # y_j = (n * 2^-10) * p_j

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.asarray(self.aux_data["target"])
        return jnp.sum((sumstats - target) ** 2)


def make_exact_model(comm):
    n = 64 * (comm.size if comm is not None else 1)
    x = jnp.full((n,), 2.0 ** -10, jnp.result_type(float))
    if comm is not None:
        x = scatter_nd(x, axis=0, comm=comm, pad_value=0.0)
    scale = n * 2.0 ** -10
    return ExactModel(aux_data=dict(
        x=x, target=jnp.asarray([scale * -1.5, scale * 0.4])),
        comm=comm)


@pytest.fixture(scope="module")
def mesh_model():
    comm = mgt.global_comm()
    return SMFModel(aux_data=make_smf_data(800, comm=comm), comm=comm)


@pytest.fixture(scope="module")
def local_model():
    return SMFModel(aux_data=make_smf_data(600, comm=None), comm=None)


def _await(futures, timeout=120):
    return [f.result(timeout=timeout) for f in futures]


# ------------------------------------------------------------------ #
# pad-and-pack correctness
# ------------------------------------------------------------------ #
def test_bucketed_results_bitwise_match_solo_fits():
    # Exact-arithmetic model on the 8-device mesh: pad-and-pack must
    # reproduce each sequential solo fit BITWISE — trajectory and
    # final point — with the padding row demonstrably inert.
    model = make_exact_model(mgt.global_comm())
    guesses = [np.array([-1.0, 0.5]), np.array([-2.2, 0.3]),
               np.array([-0.5, 1.0])]
    with FitScheduler(model, buckets=(4,), start=False,
                      batch_window_s=0.0) as sched:
        futs = [sched.submit(g, nsteps=20, learning_rate=0.05,
                             param_bounds=BOUNDS) for g in guesses]
        sched.start()
        results = _await(futs)

    assert [r.bucket for r in results] == [4, 4, 4]
    for g, r in zip(guesses, results):
        solo = np.asarray(model.run_adam(
            guess=jnp.asarray(g), nsteps=20, param_bounds=BOUNDS,
            learning_rate=0.05, progress=False))
        # The whole per-request trajectory — not just the final
        # point — is bitwise identical to the sequential solo fit.
        assert r.traj.shape == solo.shape
        assert np.array_equal(r.traj, solo)
        assert np.array_equal(r.params, solo[-1])
        assert np.isfinite(r.loss)
    # 3 requests in a 4-bucket: exactly one padded row, one dispatch.
    stats = sched.stats
    assert stats["dispatches"] == 1
    assert stats["rows_padded"] == 1
    assert stats["completed"] == 3


def test_bucketed_smf_mesh_matches_solo_to_tolerance(mesh_model):
    # The real SMF model on the mesh: same pad-and-pack path, value
    # agreement with the sequential solo fits at float32 tolerance
    # (the solo and vmapped programs may round reductions' last ULP
    # differently; ExactModel above pins the bitwise claim).
    guesses = [np.array([-1.0, 0.5]), np.array([-2.2, 0.3]),
               np.array([-0.5, 1.0])]
    with FitScheduler(mesh_model, buckets=(4,), start=False,
                      batch_window_s=0.0) as sched:
        futs = [sched.submit(g, nsteps=20, learning_rate=0.05,
                             param_bounds=BOUNDS) for g in guesses]
        sched.start()
        results = _await(futs)
    for g, r in zip(guesses, results):
        solo = np.asarray(mesh_model.run_adam(
            guess=jnp.asarray(g), nsteps=20, param_bounds=BOUNDS,
            learning_rate=0.05, progress=False))
        assert np.allclose(r.traj, solo, rtol=0, atol=1e-6)
        assert np.isfinite(r.loss)


def test_mixed_configs_never_share_a_bucket(local_model):
    # Two interleaved configs: grouping is by config — every request
    # runs its OWN schedule (the trajectory length proves it: a
    # request batched under the wrong config would come back with
    # the wrong step count) and lands on its own solo result.
    with FitScheduler(local_model, buckets=(1, 4), start=False,
                      batch_window_s=0.0) as sched:
        fa = [sched.submit([-1.0 - 0.1 * i, 0.5], nsteps=8,
                           learning_rate=0.05) for i in range(3)]
        fb = [sched.submit([-1.0 - 0.1 * i, 0.5], nsteps=4,
                           learning_rate=0.1) for i in range(2)]
        # A keyed config rides along: int seeds are batchable (the
        # typed key is built at dispatch) and group separately.
        fk = sched.submit([-1.1, 0.5], nsteps=4, learning_rate=0.1,
                          randkey=7)
        sched.start()
        ra, rb = _await(fa), _await(fb)
        rk = fk.result(timeout=120)
    assert [r.traj.shape for r in ra] == [(9, 2)] * 3
    assert [r.traj.shape for r in rb] == [(5, 2)] * 2
    solo_k = np.asarray(local_model.run_adam(
        guess=jnp.array([-1.1, 0.5]), nsteps=4, learning_rate=0.1,
        randkey=7, progress=False))
    assert np.allclose(rk.traj, solo_k, rtol=0, atol=1e-6)
    for i, r in enumerate(ra):
        solo = np.asarray(local_model.run_adam(
            guess=jnp.array([-1.0 - 0.1 * i, 0.5]), nsteps=8,
            learning_rate=0.05, progress=False))
        # Value check vs the solo program: tolerance-level, not
        # bitwise — the unsharded solo kernel's loss reduction may
        # round its last ULP differently than the vmapped batch row
        # (the bitwise guarantees live in the mesh test above and
        # the clean-batch comparison of the poison test below).
        assert np.allclose(r.traj, solo, rtol=0, atol=1e-6)
    assert sched.stats["dispatches"] >= 2


def test_mismatched_ndim_requests_never_share_a_bucket(local_model):
    # A stray 3-parameter guess must not be packed into (nor fail)
    # the 2-parameter tenants' bucket — ndim is part of the
    # batchability key — and its own failure must not kill the
    # dispatcher thread.
    with FitScheduler(local_model, buckets=(4,), start=False,
                      batch_window_s=0.0) as sched:
        good = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)
        stray = sched.submit([-1.0, 0.5, 0.1], nsteps=5,
                             learning_rate=0.05)
        sched.start()
        r = good.result(timeout=120)
        exc = stray.exception(timeout=120)
        assert np.isfinite(r.loss)
        # The 3-param request fails alone (SMF is a 2-param model).
        assert exc is not None
        # Results own their rows — no view pinning the whole bucket.
        assert r.traj.base is None and r.params.base is None
        # ... and the dispatcher survived to serve more work.
        later = sched.submit([-1.2, 0.5], nsteps=5,
                             learning_rate=0.05)
        assert np.isfinite(later.result(timeout=120).loss)


# ------------------------------------------------------------------ #
# bucket quantization bounds retraces
# ------------------------------------------------------------------ #
def test_retraces_bounded_by_bucket_count(local_model):
    sched = FitScheduler(local_model, buckets=(1, 4), start=False,
                         batch_window_s=0.0)
    # Count traces of the segment program through its wrapper: the
    # wrapper body runs once per (re)trace of the batched scan, and
    # the traced batch shape is visible on its first argument — the
    # same assertion shape as the telemetry tap no-retrace tests.
    inner = sched._wrapper(False)
    shapes = []

    def counting(p, key, dynamic):
        shapes.append(tuple(p.shape))
        return inner(p, key, dynamic)

    sched._wrappers[False] = counting

    def burst(n, offset=0.0):
        return [sched.submit([-1.0 - 0.05 * i - offset, 0.5],
                             nsteps=5, learning_rate=0.05)
                for i in range(n)]

    futs = burst(11)           # 11 >> 2 buckets: groups of 4, 4, 3
    sched.start()
    _await(futs)
    # Trace count <= bucket count: only quantized batch shapes were
    # ever traced, however many requests flowed through.
    first_wave = list(shapes)
    assert set(first_wave) <= {(4, 2), (1, 2)}
    assert len(set(first_wave)) <= 2       # <= len(buckets)

    # A second burst over already-dispatched shapes hits the cached
    # programs: ZERO new traces.
    _await(burst(8, offset=1.0))
    assert shapes == first_wave
    sched.close()
    assert sched.stats["completed"] == 19
    assert len(set(shapes)) <= 2


# ------------------------------------------------------------------ #
# poison isolation
# ------------------------------------------------------------------ #
def test_nan_poison_isolated_to_its_row(local_model, tmp_path):
    mates_g = [np.array([-1.0, 0.5]), np.array([-2.0, 0.3]),
               np.array([-0.7, 0.8])]
    # A CLEAN reference batch first — same bucket, same program — so
    # the mate comparison below is same-executable bitwise, the
    # strongest possible "the NaN never leaked across the batch
    # axis" statement.
    with FitScheduler(local_model, buckets=(4,), start=False,
                      batch_window_s=0.0) as ref:
        futs = [ref.submit(g, nsteps=10, learning_rate=0.05)
                for g in [mates_g[0], np.array([-1.5, 0.6]),
                          mates_g[1], mates_g[2]]]
        ref.start()
        clean = _await(futs)

    with FitScheduler(local_model, buckets=(4,), start=False,
                      batch_window_s=0.0, retry_poisoned=False,
                      flight_dir=str(tmp_path)) as sched:
        futs = [sched.submit(g, nsteps=10, learning_rate=0.05)
                for g in [mates_g[0], POISON, mates_g[1],
                          mates_g[2]]]
        sched.start()
        mates = [futs[i].result(timeout=120) for i in (0, 2, 3)]
        exc = futs[1].exception(timeout=120)

    # The poisoned request alone errored, with a bundle on disk.
    assert isinstance(exc, FitFailed)
    assert exc.bundle_path and os.path.exists(exc.bundle_path)
    with open(exc.bundle_path) as f:
        bundle = json.load(f)
    assert bundle["reason"] == "non_finite_request"
    assert bundle["detail"]["request_id"] == futs[1].request_id
    assert bundle["detail"]["bucket"] == 4
    # ...and the monitor's resource ring rode along: the postmortem
    # answers "was the device near its limit / the process
    # saturated" without a live process to ask.
    ring = bundle["detail"]["resources"]
    assert ring and ring[-1]["rss_bytes"] > 0

    # Batch-mates are bitwise identical to the clean batch: rows 0,
    # 2, 3 had identical inputs through the identical executable, so
    # ANY cross-row contamination would show.
    for r_clean, r_poisoned in zip(
            [clean[0], clean[2], clean[3]], mates):
        assert np.array_equal(r_poisoned.traj, r_clean.traj)
        assert r_poisoned.loss == r_clean.loss
    stats = sched.stats
    assert stats["completed"] == 3 and stats["failed"] == 1


def test_poisoned_request_retried_once_on_fresh_bucket(local_model,
                                                       tmp_path):
    with FitScheduler(local_model, buckets=(1, 4), start=False,
                      batch_window_s=0.0, retry_poisoned=True,
                      flight_dir=str(tmp_path)) as sched:
        mate = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)
        poison = sched.submit(POISON, nsteps=5, learning_rate=0.05)
        sched.start()
        assert np.isfinite(mate.result(timeout=120).loss)
        exc = poison.exception(timeout=120)
    assert isinstance(exc, FitFailed) and exc.bundle_path
    stats = sched.stats
    # One retry happened, in its own K=1 bucket, then failed for good.
    assert stats["retried"] == 1 and stats["failed"] == 1
    assert stats["bucket_dispatches"].get(1, 0) >= 1


# ------------------------------------------------------------------ #
# deadline / cancel / backpressure / drain
# ------------------------------------------------------------------ #
def test_deadline_enforced_at_dispatch(local_model):
    sched = FitScheduler(local_model, buckets=(1, 4), start=False,
                         batch_window_s=0.0)
    doomed = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05,
                          deadline_s=1e-4)
    alive = sched.submit([-1.2, 0.5], nsteps=5, learning_rate=0.05)
    time.sleep(0.01)           # the deadline passes while queued
    sched.start()
    with pytest.raises(FitDeadlineExceeded):
        doomed.result(timeout=120)
    assert np.isfinite(alive.result(timeout=120).loss)
    sched.close()
    assert sched.stats["expired"] == 1


def test_cancel_pending_request(local_model):
    sched = FitScheduler(local_model, buckets=(1, 4), start=False,
                         batch_window_s=0.0)
    victim = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)
    alive = sched.submit([-1.2, 0.5], nsteps=5, learning_rate=0.05)
    assert victim.cancel() is True
    assert victim.cancelled() and victim.done()
    sched.start()
    with pytest.raises(FitCancelled):
        victim.result(timeout=120)
    result = alive.result(timeout=120)
    assert np.isfinite(result.loss)
    # A served future can no longer be cancelled.
    assert alive.cancel() is False
    sched.close()


def test_backpressure_bounds_the_queue(local_model):
    sched = FitScheduler(local_model, buckets=(4,), max_pending=2,
                         start=False, batch_window_s=0.0)
    f1 = sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)
    f2 = sched.submit([-1.1, 0.5], nsteps=5, learning_rate=0.05)
    with pytest.raises(QueueFullError):
        sched.submit([-1.2, 0.5], nsteps=5, learning_rate=0.05)
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        sched.submit([-1.2, 0.5], nsteps=5, learning_rate=0.05,
                     block=True, timeout=0.05)
    assert time.perf_counter() - t0 >= 0.05
    sched.start()
    _await([f1, f2])
    # The dispatcher drained headroom; admission opens again.
    f3 = sched.submit([-1.2, 0.5], nsteps=5, learning_rate=0.05)
    assert np.isfinite(f3.result(timeout=120).loss)
    sched.close()


def test_graceful_drain_serves_pending_then_refuses(local_model):
    sched = FitScheduler(local_model, buckets=(1, 4), start=False,
                         batch_window_s=0.0)
    futs = [sched.submit([-1.0 - 0.1 * i, 0.5], nsteps=5,
                         learning_rate=0.05) for i in range(5)]
    sched.start()
    sched.close(drain=True)
    for f in futs:
        assert np.isfinite(f.result(timeout=1).loss)
    with pytest.raises(RuntimeError):
        sched.submit([-1.0, 0.5], nsteps=5, learning_rate=0.05)


def test_admission_control_rejects_invalid_requests(local_model):
    with FitScheduler(local_model, start=False) as sched:
        with pytest.raises(ValueError):
            sched.submit(np.zeros((2, 2)), nsteps=5)      # not 1-D
        with pytest.raises(ValueError):                   # outside box
            sched.submit([-10.0, 0.5], nsteps=5,
                         param_bounds=BOUNDS)
        with pytest.raises(ValueError):                   # bad bounds
            sched.submit([-1.0, 0.5], nsteps=5,
                         param_bounds=[(-5.0, 1.0)])
        with pytest.raises(ValueError):                   # bad config
            FitConfig(nsteps=0)
        with pytest.raises(TypeError):
            # Configs key dispatch groups: a PRNG-key ARRAY would
            # make config equality raise inside the dispatcher
            # thread (which would strand every pending future) —
            # rejected at construction instead.
            FitConfig(nsteps=5, randkey=jax.random.key(0))


# ------------------------------------------------------------------ #
# compile cache warm start
# ------------------------------------------------------------------ #
def test_compile_cache_warm_start(tmp_path):
    cache_dir = str(tmp_path / "xla_cache")
    old_dir = jax.config.jax_compilation_cache_dir
    try:
        assert enable_compile_cache(cache_dir) == cache_dir
        # A fresh model: every one of its programs compiles with the
        # cache active (the shared fixtures' programs predate it).
        model = SMFModel(aux_data=make_smf_data(500, comm=None),
                         comm=None)
        config = FitConfig(nsteps=6, learning_rate=0.07)
        with FitScheduler(model, buckets=(2,),
                          batch_window_s=0.0) as sched:
            # Warmup is trace-only (AOT lower+compile, nothing
            # executes) and already persists executables to disk.
            entries = sched.warmup(config, ndim=2)
            assert [e["bucket"] for e in entries] == [2]
            assert cache_entries(cache_dir) > 0

            def serve_two():
                futs = [sched.submit([-1.0, 0.5], config=config),
                        sched.submit([-2.0, 0.3], config=config)]
                return _await(futs)

            first = serve_two()
            # Flush cycle: one clear + re-serve pushes every
            # executable the dispatch path touches — including tiny
            # helper programs the suite may have compiled before the
            # cache existed — into the persistent cache.
            jax.clear_caches()
            serve_two()
            n_warm = cache_entries(cache_dir)
            assert n_warm > 0

            # The fresh-process stand-in: drop every in-memory
            # executable, then serve the same bucket again.  All
            # compiles must be persistent-cache READS — zero new
            # entries on disk — and the results bitwise reproduce.
            jax.clear_caches()
            second = serve_two()
        assert cache_entries(cache_dir) == n_warm
        for a, b in zip(first, second):
            assert np.array_equal(a.traj, b.traj)
            assert a.loss == b.loss
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        try:
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:
            pass


def test_warmup_needs_ndim_for_unbounded_configs(local_model):
    with pytest.raises(ValueError):
        warmup_buckets(local_model, FitConfig(nsteps=3), buckets=(1,))
    entries = warmup_buckets(local_model,
                             FitConfig(nsteps=3, param_bounds=BOUNDS),
                             buckets=(1,))
    assert entries and entries[0]["nsteps"] == 3


# ------------------------------------------------------------------ #
# observability wiring
# ------------------------------------------------------------------ #
def test_scheduler_gauges_and_fit_summary_records(local_model):
    sink = MemorySink()
    logger = MetricsLogger(sink)
    live = LiveSink()
    with FitScheduler(local_model, buckets=(1, 4), telemetry=logger,
                      live=live, start=False,
                      batch_window_s=0.0) as sched:
        futs = [sched.submit([-1.0 - 0.1 * i, 0.5], nsteps=5,
                             learning_rate=0.05) for i in range(3)]
        sched.start()
        _await(futs)

    summaries = [r for r in sink.records
                 if r["event"] == "fit_summary"]
    assert len(summaries) == 3
    ids = {f.request_id for f in futs}
    for rec in summaries:
        assert rec["request"] in ids
        assert rec["serve"] is True
        assert rec["bucket"] == 4 and rec["occupancy"] == 0.75
        assert np.isfinite(rec["final_loss"])
    dispatches = [r for r in sink.records
                  if r["event"] == "serve_dispatch"]
    assert len(dispatches) == 1 and dispatches[0]["n_requests"] == 3

    snap = live.metrics.snapshot()
    for gauge in ("multigrad_serve_queue_depth",
                  "multigrad_serve_occupancy",
                  "multigrad_serve_fits_total",
                  "multigrad_serve_dispatches_total"):
        assert gauge in snap, f"missing {gauge}"
    rendered = live.metrics.render()
    assert 'multigrad_serve_fits_total{outcome="ok"} 3' in rendered
    logger.close()


def test_multistart_adam_emits_fit_summary(local_model):
    # PR-10 satellite: the ensemble driver no longer closes its
    # stream silently — its closing fit_summary carries the winning
    # basin, so live views flip to "done" for ensemble runs too.
    sink = MemorySink()
    logger = MetricsLogger(sink)
    result = run_multistart_adam(
        local_model, param_bounds=BOUNDS, n_starts=3, nsteps=5,
        telemetry=logger, log_every=2)
    logger.close()
    jax.effects_barrier()
    summaries = [r for r in sink.records
                 if r["event"] == "fit_summary"]
    assert summaries, "ensemble run closed its stream silently"
    closing = summaries[-1]
    assert closing["n_starts"] == 3
    assert closing["final_loss"] == result.best_loss
    assert closing["best_start"] == int(
        np.argmin(np.asarray(result.losses)))
    plans = [r for r in sink.records if r["event"] == "fit_plan"]
    assert plans and plans[0]["nsteps"] == 5


# ------------------------------------------------------------------ #
# static verification of the bucketed program (lint target)
# ------------------------------------------------------------------ #
def test_serve_bucket_lint_target_is_clean():
    from multigrad_tpu.analysis.lint import main as lint_main
    assert lint_main(["--targets", "serve_bucket",
                      "--num-halos", "400"]) == 0
