"""Real multi-process coverage (the reference's ``mpiexec -n 2`` story).

The reference's whole multi-node test strategy is "the same module
passes under ``mpiexec -n 1/2/10``" (``tests/test_mpi.py:1-7``).  The
rest of this suite covers N-device SPMD in one process; these tests
launch **N actual processes** (parameterized, like ``-n``) with
``jax.distributed.initialize`` on the CPU backend (gloo collectives),
exercising every
``process_count() > 1`` branch: ``scatter_from_local``,
``is_main_process``, outside-trace ``reduce_sum``, the golden-vector
parity, and the checkpointed-Adam broadcast-resume where only process
0 holds the checkpoint file.
"""
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # The workers set their own platform/device-count config.
    env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.parametrize(
    "nprocs",
    [pytest.param(2, marks=pytest.mark.slow),     # n=2: ~29 s
     pytest.param(4, marks=pytest.mark.slow)])    # n=4: ~45 s
def test_n_process_cluster(tmp_path, nprocs):
    # The reference's whole multi-node strategy is "same module under
    # mpiexec -n 1/2/10"; the process count is the parameter here too
    # (sizes must divide the 10k golden fixture over 2 devices/proc).
    # The 4-process case gets ONE retry as a backstop against gloo
    # CPU-backend scheduling flakes (the known in-flight-collective
    # interleave is fenced in the worker itself — see
    # _multihost_worker.py — but the backend has shown timing
    # sensitivity at 4 processes; a genuine regression fails both
    # attempts).
    attempts = 2 if nprocs >= 4 else 1
    for attempt in range(attempts):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, WORKER, str(port), str(i),
                 str(nprocs), str(tmp_path / f"a{attempt}")],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=_clean_env())
            for i in range(nprocs)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        ok = all(p.returncode == 0 for p in procs) and all(
            f"proc {i}: WORKER-OK" in out
            for i, out in enumerate(outs))
        if ok:
            return
        if attempt < attempts - 1:
            continue
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, \
                f"worker {i} failed (rc={p.returncode}):\n{out[-4000:]}"
            assert f"proc {i}: WORKER-OK" in out


@pytest.mark.slow  # ~11 s: waits out a real bootstrap timeout
def test_initialize_unreachable_coordinator_fails_loudly(tmp_path):
    # A *failed* bootstrap must raise, not silently degrade to
    # single-host (parallel/distributed.py error taxonomy): the fit
    # would otherwise run on a fraction of the data with no error.
    code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from multigrad_tpu.parallel import distributed
try:
    distributed.initialize(coordinator_address="localhost:9",
                           num_processes=2, process_id=1,
                           initialization_timeout=5)
except RuntimeError:
    print("RAISED-OK")
else:
    print("SILENT-DEGRADE")
"""
    out = subprocess.run([sys.executable, "-c", code], text=True,
                         capture_output=True, timeout=120,
                         env=_clean_env())
    # Loud failure comes in two shapes depending on the JAX build: a
    # Python RuntimeError, or the coordination client's LOG(FATAL)
    # process abort.  Either is acceptable; silently continuing
    # single-host is the one forbidden outcome.
    assert "SILENT-DEGRADE" not in out.stdout, out.stdout + out.stderr
    assert ("RAISED-OK" in out.stdout or out.returncode != 0), \
        out.stdout + out.stderr


def test_initialize_standalone_degrades_gracefully():
    # No coordinator at all -> single-process standalone (the
    # reference's mpi4py-less fallback, multigrad.py:23-27).
    code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from multigrad_tpu.parallel import distributed
distributed.initialize()
assert distributed.process_count() == 1
assert distributed.is_main_process()
print("STANDALONE-OK")
"""
    out = subprocess.run([sys.executable, "-c", code], text=True,
                         capture_output=True, timeout=120,
                         env=_clean_env())
    assert "STANDALONE-OK" in out.stdout, out.stdout + out.stderr
