"""Multi-tenant QoS subsystem (multigrad_tpu/serve/qos.py + slo.py).

The PR-17 tentpole's acceptance battery:

* tag / policy mechanics — :class:`QosTag` validation, the
  ``make_tag`` submit-surface coercion, wire codecs (known-keys-only
  forward compatibility, untagged traffic stays off the wire);
* admission — a queue full of EXPIRED requests still admits a fresh
  submit (dead deadlines don't hold slots), per-tenant quotas reject
  before the global queue-full verdict, and a full queue sheds its
  lowest priority class (most slack first) to admit strictly-higher
  work — equal classes never shed each other;
* scheduling — deficit round-robin keeps a light tenant's p95 queue
  wait within 2x of its solo baseline under a 10x-heavier tenant
  (while FIFO starves it), EDF meets strictly more deadlines than
  arrival order on the same ladder, and a head-of-line deadline
  tighter than the batch window collapses the window;
* co-batching — same-config fits from FOUR different tenants share
  one bucket and one trace (the tag is not the batchability key);
* fleet — a tagged reject round-trips at an untagged (legacy)
  worker, ``tenant_quota`` rejects don't mark the worker saturated,
  and cumulative shed counters fold into
  :class:`FleetSaturatedError`;
* concurrency — the dequeue-vs-shed race replayed under the
  deterministic-interleaving harness: no deadlock, and no request is
  ever both shed and dispatched;
* observability — :class:`SloMonitor` verdicts and the LiveSink
  ``/status`` ``qos`` section.

Everything except the one co-batch scheduler test is pure-Python
queue/policy mechanics — milliseconds of tier-1 budget.
"""
import time

import numpy as np
import pytest

from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.serve import (FitConfig, FitDeadlineExceeded,
                                 FitScheduler, FitShedError,
                                 QosPolicy, QosTag, QueueFullError,
                                 Slo, SloMonitor, TenantQuotaError,
                                 parse_slo)
from multigrad_tpu.serve.fleet import (FleetRouter,
                                       FleetSaturatedError,
                                       WorkerHandle)
from multigrad_tpu.serve.qos import (DEFAULT_CLASS, DEFAULT_TENANT,
                                     class_rank, deadlines_met,
                                     edf_sorted, jain_fairness,
                                     make_tag, request_tag)
from multigrad_tpu.serve.queue import FitFuture, FitQueue, FitRequest
from multigrad_tpu.serve.wire import (qos_from_wire, qos_to_wire,
                                      shed_from_wire, shed_to_wire)
from multigrad_tpu._lockdep import sched_point
from multigrad_tpu.utils.testing import run_interleavings
from multigrad_tpu.telemetry import LiveSink


def _req(q, tenant=None, cls=None, deadline=None, nsteps=5,
         guess=(-1.0, 0.5)):
    rid = q.next_id()
    return FitRequest(id=rid, guess=np.asarray(guess, float),
                      config=FitConfig(nsteps=nsteps),
                      future=FitFuture(rid), deadline=deadline,
                      qos=make_tag(None, tenant, cls, None))


# ------------------------------------------------------------------ #
# tag mechanics + wire codecs
# ------------------------------------------------------------------ #
def test_qostag_validation_and_make_tag():
    tag = QosTag("acme", "interactive", 1.5)
    assert tag.slo_deadline_s == 1.5
    with pytest.raises(TypeError):
        QosTag(tenant="")
    with pytest.raises(TypeError):
        QosTag(priority_class=None)
    with pytest.raises(ValueError):
        QosTag(slo_deadline_s=-1.0)

    # All-defaults submit surface stays untagged (and off the wire).
    assert make_tag() is None
    t = make_tag(tenant="acme")
    assert t == QosTag("acme", DEFAULT_CLASS)
    # A prebuilt tag wins over the piecewise fields.
    assert make_tag(tag, tenant="other") is tag
    with pytest.raises(TypeError):
        make_tag(qos="not-a-tag")

    # Unknown classes rank LOWEST: never give work you can't
    # identify precedence over work you can.
    assert class_rank("interactive") > class_rank("standard") \
        > class_rank("batch")
    assert class_rank("mystery-v99") == class_rank("batch")

    # Untagged requests schedule as the shared default tenant.
    class Bare:
        pass
    assert request_tag(Bare()) == QosTag(DEFAULT_TENANT, DEFAULT_CLASS)


def test_qos_wire_roundtrip_known_keys_only():
    tag = QosTag("acme", "interactive", 2.5)
    assert qos_from_wire(qos_to_wire(tag)) == tag
    # Untagged traffic is byte-identical to the pre-QoS protocol.
    assert qos_to_wire(None) is None
    assert qos_from_wire(None) is None
    assert qos_from_wire({}) is None
    # A newer peer's extra keys must not crash admission.
    decorated = dict(qos_to_wire(tag), shiny_new_field={"x": 1})
    assert qos_from_wire(decorated) == tag
    # Partial dict: known keys read explicitly with defaults.
    t = qos_from_wire({"tenant": "solo"})
    assert t == QosTag("solo", DEFAULT_CLASS)

    shed = {"by_class": {"batch": 3}, "by_tenant": {"hog": 3}}
    assert shed_from_wire(shed_to_wire(shed)) == shed
    # Mixed-version fleet: garbage decodes to empty counters.
    empty = {"by_class": {}, "by_tenant": {}}
    assert shed_from_wire(None) == empty
    assert shed_from_wire("nonsense") == empty
    assert shed_from_wire({"by_class": "nope"}) == empty
    assert shed_to_wire(None) == empty


def test_jain_fairness_index():
    assert jain_fairness([]) == 1.0
    assert jain_fairness([0, 0]) == 1.0
    assert jain_fairness([3, 3, 3, 3]) == pytest.approx(1.0)
    assert jain_fairness([1, 0, 0, 0]) == pytest.approx(0.25)


# ------------------------------------------------------------------ #
# satellite: expired-request purge at admission
# ------------------------------------------------------------------ #
def test_full_queue_of_expired_requests_admits_fresh_submit():
    settled = []
    q = FitQueue(max_pending=4,
                 on_settle=lambda r, k: settled.append((r.id, k)))
    stale = [_req(q, deadline=time.time() - 1.0) for _ in range(4)]
    for r in stale:
        q.submit(r)
    # Queue is at max_pending, but every occupant's deadline has
    # passed: the fresh submit purges them and admits — no
    # QueueFullError, no blocking.
    fresh = _req(q)
    q.submit(fresh)
    for r in stale:
        exc = r.future.exception(timeout=5)
        assert isinstance(exc, FitDeadlineExceeded)
    # The settle hook saw every purge (root-before-resolve order).
    assert settled == [(r.id, "expired") for r in stale]
    group, _ = q.take_group(4, timeout=1.0)
    assert [r.id for r in group] == [fresh.id]
    q.close()


# ------------------------------------------------------------------ #
# tenant quotas reject before the global queue-full verdict
# ------------------------------------------------------------------ #
def test_tenant_quota_rejects_before_queue_full():
    q = FitQueue(max_pending=16, qos=QosPolicy(tenant_quota=2))
    q.submit(_req(q, tenant="a"))
    q.submit(_req(q, tenant="a"))
    with pytest.raises(TenantQuotaError) as ei:
        q.submit(_req(q, tenant="a"))
    assert ei.value.tenant == "a"
    assert (ei.value.queued, ei.value.quota) == (2, 2)
    # The quota is PER TENANT: the queue itself has headroom.
    q.submit(_req(q, tenant="b"))
    # A quota error is still a QueueFullError subclass — existing
    # backpressure handlers keep working.
    assert isinstance(ei.value, QueueFullError)
    q.close()


def test_expired_requests_do_not_count_against_quota():
    q = FitQueue(max_pending=16, qos=QosPolicy(tenant_quota=2))
    q.submit(_req(q, tenant="a", deadline=time.time() - 1.0))
    q.submit(_req(q, tenant="a", deadline=time.time() - 1.0))
    # Both queued requests are dead: a backlog of expired work must
    # not lock the live tenant out.
    q.submit(_req(q, tenant="a"))
    q.close()


# ------------------------------------------------------------------ #
# class-aware shedding
# ------------------------------------------------------------------ #
def test_full_queue_sheds_lowest_class_with_most_slack():
    settled = []
    q = FitQueue(max_pending=2, qos=QosPolicy(),
                 on_settle=lambda r, k: settled.append((r.id, k)))
    far = time.time() + 100.0
    b_no_deadline = _req(q, cls="batch")
    b_deadlined = _req(q, cls="batch", deadline=far)
    q.submit(b_no_deadline)
    q.submit(b_deadlined)

    # Interactive work arrives at a full queue: the no-deadline
    # batch request has the most slack — it is the victim.
    inter = _req(q, cls="interactive")
    q.submit(inter)
    exc = b_no_deadline.future.exception(timeout=5)
    assert isinstance(exc, FitShedError)
    assert exc.priority_class == "batch"
    assert exc.shed_for == "interactive"
    assert settled == [(b_no_deadline.id, "shed")]

    # Standard work cannot evict interactive (only strictly-lower
    # classes shed) — but the remaining batch request can still go.
    std = _req(q, cls="standard")
    q.submit(std)
    assert isinstance(b_deadlined.future.exception(timeout=5),
                      FitShedError)

    # Queue now holds {interactive, standard}: a second standard
    # submit finds nothing strictly below itself → plain
    # QueueFullError, never a same-class eviction.
    with pytest.raises(QueueFullError) as ei:
        q.submit(_req(q, cls="standard"))
    assert not isinstance(ei.value, FitShedError)

    counts = q.qos_counts()
    assert counts["by_class"] == {"batch": 2}
    assert counts["by_tenant"] == {DEFAULT_TENANT: 2}
    q.close()


# ------------------------------------------------------------------ #
# satellite: starvation property — DRR vs FIFO under 10x overload
# ------------------------------------------------------------------ #
def _drive(q, arrivals, service_s=1.0):
    """Serve ``arrivals`` ([(t, request)] on a virtual clock) one
    dispatch per ``service_s``; returns per-tenant queue waits."""
    arrivals = sorted(arrivals, key=lambda p: p[0])
    arrive_t = {r.id: t for t, r in arrivals}
    waits: dict = {}
    t, i, served = 0.0, 0, 0
    while served < len(arrivals):
        while i < len(arrivals) and arrivals[i][0] <= t:
            q.submit(arrivals[i][1])
            i += 1
        if len(q) == 0:
            t = arrivals[i][0]      # idle until the next arrival
            continue
        group, _ = q.take_group(1, window_s=0.0, timeout=1.0)
        for r in group:
            waits.setdefault(request_tag(r).tenant, []).append(
                t - arrive_t[r.id])
            served += 1
        t += service_s
    return waits


def test_drr_protects_light_tenant_from_heavy_one():
    service_s = 1.0
    heavy = [(0.2 * i, "hog") for i in range(60)]       # 5/s
    light = [(2.0 * i, "mouse") for i in range(10)]     # 0.5/s

    def arrivals(q, spec):
        return [(t, _req(q, tenant=tenant)) for t, tenant in spec]

    # Solo baseline: the light tenant alone is served at arrival.
    q = FitQueue(max_pending=1024)
    solo = _drive(q, arrivals(q, light), service_s)["mouse"]
    q.close()
    solo_p95 = float(np.percentile(solo, 95))

    # FIFO under 10x overload: the light tenant queues behind the
    # heavy tenant's entire backlog — starved.
    q = FitQueue(max_pending=1024)
    fifo = _drive(q, arrivals(q, heavy + light), service_s)
    q.close()
    fifo_p95 = float(np.percentile(fifo["mouse"], 95))

    # DRR under the same load: fair share, not arrival share.
    q = FitQueue(max_pending=1024, qos=QosPolicy())
    drr = _drive(q, arrivals(q, heavy + light), service_s)
    q.close()
    drr_p95 = float(np.percentile(drr["mouse"], 95))

    floor = max(solo_p95, service_s)
    assert fifo_p95 > 2.0 * floor          # FIFO really does starve
    assert drr_p95 <= 2.0 * floor          # the property under test
    # ... and fairness over the contended window reflects it: the
    # heavy tenant got the leftover capacity, not 10x.
    n = len(drr["mouse"])
    fair = jain_fairness([n, n])           # equal service counts
    assert fair == pytest.approx(1.0)
    assert len(drr["hog"]) == 60           # nobody starves either way


# ------------------------------------------------------------------ #
# satellite: EDF meets strictly more deadlines than arrival order
# ------------------------------------------------------------------ #
def test_edf_meets_strictly_more_deadlines_than_arrival_order():
    q = FitQueue(max_pending=64)
    # Arrival order interleaves far and near deadlines (the worst
    # case for FIFO packing): deadlines 8,1,7,2,6,3,5,4 on a
    # virtual clock starting at 0.
    ladder = [8.0, 1.0, 7.0, 2.0, 6.0, 3.0, 5.0, 4.0]
    reqs = [_req(q, deadline=d) for d in ladder]
    fifo_met = deadlines_met(reqs, service_s=1.0, batch=1, now=0.0)
    edf_met = deadlines_met(edf_sorted(reqs), service_s=1.0,
                            batch=1, now=0.0)
    assert edf_met > fifo_met
    assert edf_met == len(reqs)            # EDF is optimal here
    q.close()


def test_take_group_returns_edf_packing_order():
    pol = QosPolicy()
    q = FitQueue(max_pending=64, qos=pol)
    now = time.time()
    # Future-anchored deadlines (nothing expires at take time),
    # submitted in scrambled order; one deadline-less straggler.
    offsets = [50.0, 20.0, 80.0, 35.0]
    reqs = [_req(q, deadline=now + off) for off in offsets]
    reqs.append(_req(q, deadline=None))
    for r in reqs:
        q.submit(r)
    group, _ = q.take_group(8, window_s=0.0, timeout=1.0)
    got = [r.deadline for r in group]
    # EDF within the config home: ascending deadlines, the
    # deadline-less request last (infinite slack by definition).
    assert got[:-1] == sorted(d for d in got[:-1])
    assert got[-1] is None
    q.close()


def test_tight_head_deadline_collapses_batch_window():
    pol = QosPolicy()
    q = FitQueue(max_pending=64, qos=pol)
    # Head slack (~0.5 s) is inside two batch windows (2 x 5 s):
    # waiting for a fuller bucket would spend the very slack the
    # deadline protects — take_group must return immediately.
    q.submit(_req(q, deadline=time.time() + 0.5))
    t0 = time.time()
    group, _ = q.take_group(4, window_s=5.0, timeout=1.0)
    assert len(group) == 1
    assert time.time() - t0 < 2.0
    q.close()


# ------------------------------------------------------------------ #
# acceptance: tenants co-batch — the tag is NOT the batchability key
# ------------------------------------------------------------------ #
def test_four_tenants_one_bucket_one_trace():
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)
    with FitScheduler(model, buckets=(4,), start=False,
                      batch_window_s=0.0, qos=True) as sched:
        inner = sched._wrapper(False)
        shapes = []

        def counting(p, key, dynamic):
            shapes.append(tuple(p.shape))
            return inner(p, key, dynamic)

        sched._wrappers[False] = counting
        futs = [sched.submit([-1.0 - 0.05 * i, 0.5], nsteps=5,
                             learning_rate=0.05,
                             tenant=f"tenant-{i}",
                             priority_class="standard")
                for i in range(4)]
        sched.start()
        results = [f.result(timeout=120) for f in futs]
    assert all(np.isfinite(r.loss) for r in results)
    # Four tenants, ONE (4, 2) bucket, ONE trace: same-config fits
    # from different tenants still share the batched program.
    assert set(shapes) == {(4, 2)}
    assert len(shapes) == 1
    # Every request really did ride the same bucket.
    assert {r.bucket for r in results} == {4}


# ------------------------------------------------------------------ #
# satellite: tagged rejects round-trip at untagged (legacy) workers
# ------------------------------------------------------------------ #
class FakeChan:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass

    def submits(self):
        return [m for m in self.sent if m["op"] == "submit"]


@pytest.fixture()
def fake_fleet(tmp_path):
    router = FleetRouter(n_workers=0, base_dir=str(tmp_path),
                         compile_cache=None,
                         heartbeat_timeout_s=1e6, max_requeues=2)
    a = WorkerHandle("w0", chan=FakeChan())
    b = WorkerHandle("w1", chan=FakeChan())
    router.workers += [a, b]
    yield router, a, b
    router.close(drain=False, timeout=0)


def _home_and_other(a, b, fut_id):
    if any(m["rid"] == fut_id for m in a.chan.submits()):
        return a, b
    return b, a


def test_tagged_reject_roundtrips_at_untagged_worker(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5, tenant="acme",
                        priority_class="interactive")
    home, other = _home_and_other(a, b, fut.request_id)
    # The tag rode the wire...
    sent = home.chan.submits()[0]
    assert sent["qos"] == {"tenant": "acme",
                           "priority_class": "interactive",
                           "slo_deadline_s": None}
    # ... but an UNTAGGED worker rejects with the legacy message —
    # no reason, no shed counters.  The router must not crash, must
    # default the reason, and must steal onto the next worker.
    router._on_reject(home, {"rid": fut.request_id})
    assert any(m["rid"] == fut.request_id
               for m in other.chan.submits())
    # The second (QoS-aware) worker rejects WITH cumulative shed
    # counters: they fold into the fleet-wide accounting and the
    # typed error names the victim classes.
    router._on_reject(other, {
        "rid": fut.request_id, "reason": "queue_full",
        "shed": {"by_class": {"batch": 2}, "by_tenant": {"hog": 2}}})
    exc = fut.exception(timeout=5)
    assert isinstance(exc, FleetSaturatedError)
    assert exc.reason == "queue_full"
    assert exc.shed_by_class == {"batch": 2}
    assert exc.shed_by_tenant == {"hog": 2}
    # The fleet-wide shed gets recorded against the request's class.
    assert router.slo is None or True  # slo off: no monitor wired
    by_class, by_tenant = router.shed_counts()
    assert by_class == {"batch": 2}


def test_untagged_submit_keeps_qos_key_off_the_wire(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    home, _ = _home_and_other(a, b, fut.request_id)
    # Untagged traffic is byte-identical to the pre-QoS protocol.
    assert "qos" not in home.chan.submits()[0]


def test_tenant_quota_reject_does_not_mark_worker_saturated(
        fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5, tenant="acme")
    home, other = _home_and_other(a, b, fut.request_id)
    assert home.saturated_until == 0.0
    # "tenant_quota" is a per-TENANT verdict, not fleet saturation:
    # other tenants keep routing to this worker...
    router._on_reject(home, {"rid": fut.request_id,
                             "reason": "tenant_quota",
                             "tenant": "acme"})
    assert home.saturated_until == 0.0
    # ... though THIS request still moves on (a different worker has
    # a different quota ledger).
    assert any(m["rid"] == fut.request_id
               for m in other.chan.submits())
    # A plain queue_full reject DOES mark the worker saturated.
    router._on_reject(other, {"rid": fut.request_id,
                              "reason": "queue_full"})
    assert other.saturated_until > time.time()
    exc = fut.exception(timeout=5)
    assert isinstance(exc, FleetSaturatedError)
    assert exc.reason == "queue_full"


# ------------------------------------------------------------------ #
# satellite: the dequeue-vs-shed race, deterministically interleaved
# ------------------------------------------------------------------ #
def test_dequeue_vs_shed_race_never_double_settles():
    runs = []

    def build():
        state = {"took": None, "shed": [], "admitted": None}
        runs.append(state)
        q = FitQueue(max_pending=1, qos=QosPolicy(),
                     on_settle=lambda r, k:
                     state["shed"].append((r.id, k)))
        low = _req(q, cls="batch")
        q.submit(low)
        high = _req(q, cls="interactive")
        state["low_id"], state["high_id"] = low.id, high.id

        def taker():
            sched_point("pre-take")
            group, _ = q.take_group(1, window_s=0.0, timeout=2.0)
            state["took"] = tuple(r.id for r in group)

        def shedder():
            sched_point("pre-submit")
            try:
                q.submit(high)
                state["admitted"] = True
            except QueueFullError:
                state["admitted"] = False

        return [taker, shedder]

    outs = run_interleavings(build, deadlock_timeout_s=1.2,
                             timeout_s=20.0)
    assert not any(o.deadlocked for o in outs), outs
    assert not any(o.errors for o in outs), outs
    for st in runs:
        took = st["took"] or ()
        shed_ids = [rid for rid, kind in st["shed"]
                    if kind == "shed"]
        # The interactive submit always lands: either the taker
        # drained the queue first (room) or the batch request was
        # shed to make room.
        assert st["admitted"] is True
        # The race's invariant: the low request is dispatched XOR
        # shed — never both, never neither.
        low_took = st["low_id"] in took
        low_shed = st["low_id"] in shed_ids
        assert low_took != low_shed
        # Exactly one request was dispatched per take.
        assert len(took) == 1
        # If low was shed, the taker got the interactive request.
        if low_shed:
            assert took == (st["high_id"],)


# ------------------------------------------------------------------ #
# SLOs: declarative objectives, live verdicts, /status export
# ------------------------------------------------------------------ #
def test_parse_slo_forms_and_validation():
    s = parse_slo("p95 < 2 s for interactive")
    assert s == Slo("interactive", 2.0, 0.95)
    # `class` keyword and the `s` unit are optional; case-blind.
    assert parse_slo("P50<0.5 for class batch") == \
        Slo("batch", 0.5, 0.50)
    with pytest.raises(ValueError):
        parse_slo("latency should be ok")
    with pytest.raises(ValueError):
        Slo("interactive", -1.0)
    with pytest.raises(ValueError):
        Slo("interactive", 1.0, quantile=1.5)
    # At most one SLO per class.
    with pytest.raises(ValueError, match="duplicate"):
        SloMonitor(slos=["p95 < 2 s for interactive",
                         "p50 < 1 s for interactive"])


def test_slo_monitor_verdicts_and_shed_accounting():
    mon = SloMonitor(slos=["p95 < 1.0 s for interactive"])
    # No data yet: the verdict is None, and None doesn't FAIL ok().
    assert mon.evaluate()["interactive"]["slo"]["ok"] is None
    assert mon.ok() is True
    for v in (0.1, 0.2, 0.3, 0.4):
        mon.observe("interactive", "acme", v)
    ev = mon.evaluate()["interactive"]
    assert ev["count"] == 4
    assert ev["slo"]["ok"] is True
    assert mon.ok() is True
    # One giant outlier blows p95 past the threshold.
    for _ in range(20):
        mon.observe("interactive", "acme", 5.0)
    assert mon.ok() is False
    # Undeclared classes are observed but never judged.
    mon.observe("batch", "hog", 9.0)
    assert "slo" not in mon.evaluate()["batch"]
    assert mon.ok() is False
    mon.record_shed("batch", "hog")
    snap = mon.snapshot()
    assert snap["classes"]["batch"]["shed"] == 1
    assert snap["shed_by_tenant"] == {"hog": 1}


def test_live_status_exports_qos_section():
    sink = LiveSink()
    # A bare sink has no qos section (QoS off → key absent).
    assert "qos" not in sink.status()
    mon = SloMonitor(sink.metrics, ["p95 < 2 s for interactive"])
    # The declared threshold is visible BEFORE the first
    # observation: /status judges from the registry alone.
    qos = sink.status()["qos"]
    assert qos["classes"]["interactive"]["slo"]["threshold_s"] == 2.0
    assert qos["classes"]["interactive"]["slo"]["ok"] is None
    for v in (0.2, 0.3, 0.4):
        mon.observe("interactive", "acme", v, trace_id="tr-42")
    mon.record_shed("standard", "hog")
    qos = sink.status()["qos"]
    entry = qos["classes"]["interactive"]
    assert entry["count"] == 3
    assert entry["slo"]["ok"] is True
    assert entry["slo"]["measured_s"] <= 2.0
    assert entry["exemplar_trace"] == "tr-42"
    assert qos["shed_by_tenant"] == {"hog": 1}


# ------------------------------------------------------------------ #
# scheduler end-to-end: SLO observation + shed accounting in stats
# ------------------------------------------------------------------ #
def test_scheduler_qos_stats_and_slo_wiring():
    model = SMFModel(aux_data=make_smf_data(600, comm=None),
                     comm=None)
    with FitScheduler(model, buckets=(1,), start=False,
                      batch_window_s=0.0, qos=True,
                      slo=["p95 < 300 s for standard"]) as sched:
        fut = sched.submit([-1.0, 0.5], nsteps=5,
                           learning_rate=0.05, tenant="acme")
        sched.start()
        assert np.isfinite(fut.result(timeout=120).loss)
        # The served fit landed in the monitor under its class.
        ev = sched.slo.evaluate()["standard"]
        assert ev["count"] == 1
        assert ev["slo"]["ok"] is True
        assert sched.slo.ok() is True
        # Queue-level shed counters surface through stats.
        assert sched.stats["qos_shed"] == {"by_class": {},
                                           "by_tenant": {}}
