"""Test environment: N virtual CPU devices replace `mpiexec -n N`.

The reference's test strategy (SURVEY §4) runs one test module under
1/2/10 MPI ranks on a single host.  The TPU-native equivalent is
``--xla_force_host_platform_device_count=8``: eight fake CPU devices
in one process exercise the same mesh/shard_map code paths that run
on real TPU chips, so the whole distributed surface is testable in CI
without TPUs.  Must run before the first jax import.
"""
import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"

# Hermetic autotuning: point "auto" knob resolution at a fresh
# per-session table so tier-1 results can never depend on whatever a
# developer's (or an earlier CI step's) real tuning table holds —
# unconditionally, like JAX_PLATFORMS above: an inherited
# MGT_TUNING_TABLE would leak real tuned knobs into the suite.
# Tune tests pass explicit table paths and are unaffected.
os.environ["MGT_TUNING_TABLE"] = os.path.join(
    tempfile.mkdtemp(prefix="mgt_test_tuning_"), "table.json")

# Hermetic concurrency shadow: tier-1 measures the lockdep-off
# default (the off-by-default wall-clock contract); the lockdep
# tests flip it programmatically and restore it.
os.environ.pop("MGT_LOCKDEP", None)
os.environ.pop("MGT_LOCKDEP_DUMP", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Site customization (e.g. a TPU-tunnel sitecustomize) may force
# JAX_PLATFORMS back to a hardware backend; the config API wins over
# the env var, so pin the CPU platform explicitly too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
