"""OnePointGroup (MPMD composition) tests.

Mirrors the reference's group semantics (``multigrad.py:547-607``):
joint loss/grad is the sum over component models, each model owning a
sub-communicator; optimizer proxies work on the group.
"""
import os

import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data

TRUTH = ParamTuple(log_shmrat=-2.0, sigma_logsm=0.2)


@pytest.fixture(scope="module")
def group_and_models():
    comm = mgt.global_comm()
    subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
    # Two probes of the same parameter space: the same SMF model on
    # different data sizes, each on its own 4-device sub-mesh.
    m1 = SMFModel(aux_data=make_smf_data(10_000, comm=subcomms[0]),
                  comm=subcomms[0])
    m2 = SMFModel(aux_data=make_smf_data(20_000, comm=subcomms[1]),
                  comm=subcomms[1])
    # Self-consistent targets (see test_smf_pipeline.py): "stays at
    # truth" invariants need each model's own float32 sumstats.
    for m in (m1, m2):
        m.aux_data["target_sumstats"] = jnp.asarray(
            m.calc_sumstats_from_params(TRUTH))
    return mgt.OnePointGroup(models=(m1, m2)), (m1, m2)


def test_group_sums_losses_and_grads(group_and_models):
    group, (m1, m2) = group_and_models
    params = jnp.array([-1.8, 0.3])
    loss, grad = group.calc_loss_and_grad_from_params(params)
    l1, g1 = m1.calc_loss_and_grad_from_params(params)
    l2, g2 = m2.calc_loss_and_grad_from_params(params)
    # (sum on host: the component results live on disjoint sub-meshes)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(l1) + np.asarray(l2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad),
                               np.asarray(g1) + np.asarray(g2), rtol=1e-6)


def test_single_model_group(group_and_models):
    _, (m1, _) = group_and_models
    group = mgt.OnePointGroup(models=m1)
    assert isinstance(group.models, tuple)
    params = jnp.array([-2.0, 0.2])
    loss, _ = group.calc_loss_and_grad_from_params(params)
    l1, _ = m1.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(l1), rtol=1e-6)


def test_group_bfgs(group_and_models):
    # Box bounds keep the line search away from sigma <= 0 (where the
    # log-loss is undefined) — the joint gradient is ~2x a single
    # model's, so the unbounded first step would overshoot.
    group, _ = group_and_models
    result = group.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                            param_bounds=[(-4.0, 0.0), (0.01, 1.0)],
                            progress=False)
    # scipy may flag ABNORMAL when it grinds into the float32 noise
    # floor; judge by solution quality (loss + recovered params).
    assert result.fun < 1e-9
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)


def test_group_adam(group_and_models):
    group, _ = group_and_models
    traj = group.run_adam(guess=ParamTuple(-1.8, 0.3), nsteps=100,
                          learning_rate=0.02, progress=False)
    assert traj.shape == (101, 2)
    np.testing.assert_allclose(np.asarray(traj[-1]), [*TRUTH], atol=0.05)


def test_group_simple_gd(group_and_models):
    group, _ = group_and_models
    res = group.run_simple_grad_descent(guess=jnp.array([*TRUTH]), nsteps=2)
    assert jnp.isclose(res.loss[-1], 0.0, atol=1e-8)
    np.testing.assert_allclose(np.asarray(res.params[-1]), [*TRUTH],
                               rtol=1e-5)


# --------------------------------------------------------------------------
# Fused same-mesh path: the joint step as ONE XLA program
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shared_mesh_group():
    comm = mgt.global_comm()
    m1 = SMFModel(aux_data=make_smf_data(8_000, comm=comm), comm=comm)
    m2 = SMFModel(aux_data=make_smf_data(16_000, comm=comm), comm=comm)
    for m in (m1, m2):
        m.aux_data["target_sumstats"] = jnp.asarray(
            m.calc_sumstats_from_params(TRUTH))
    return mgt.OnePointGroup(models=(m1, m2)), (m1, m2)


def test_fused_detection(shared_mesh_group, group_and_models):
    shared_group, _ = shared_mesh_group
    disjoint_group, _ = group_and_models
    assert shared_group.fused           # one mesh -> one program
    assert not disjoint_group.fused     # disjoint sub-meshes -> MPMD


def test_fused_none_comm_group_is_fused():
    m = SMFModel(aux_data=make_smf_data(1_000, comm=None), comm=None)
    m.aux_data["target_sumstats"] = jnp.asarray(
        m.calc_sumstats_from_params(TRUTH))
    group = mgt.OnePointGroup(models=(m, m))
    assert group.fused


def test_fused_matches_componentwise_sum(shared_mesh_group):
    group, (m1, m2) = shared_mesh_group
    params = jnp.array([-1.8, 0.3])
    loss, grad = group.calc_loss_and_grad_from_params(params)
    l1, g1 = m1.calc_loss_and_grad_from_params(params)
    l2, g2 = m2.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(l1) + np.asarray(l2),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad),
                               np.asarray(g1) + np.asarray(g2),
                               rtol=1e-6)


def test_fused_adam_matches_host_loop(shared_mesh_group, monkeypatch):
    # The fused whole-fit lax.scan and the host-loop driver must agree
    # step for step (same optax math, same PRNG chain).
    group, _ = shared_mesh_group
    kwargs = dict(guess=ParamTuple(-1.8, 0.3), nsteps=25,
                  learning_rate=0.02, randkey=7,
                  param_bounds=[(-4.0, 0.0), (0.01, 1.0)],
                  progress=False)
    traj_fused = group.run_adam(**kwargs)
    monkeypatch.setattr(type(group), "fused", property(lambda self: False))
    traj_host = group.run_adam(**kwargs)
    np.testing.assert_allclose(np.asarray(traj_fused),
                               np.asarray(traj_host), rtol=1e-5,
                               atol=1e-7)


def test_fused_bfgs_recovers_truth(shared_mesh_group):
    group, _ = shared_mesh_group
    result = group.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                            param_bounds=[(-4.0, 0.0), (0.01, 1.0)],
                            progress=False)
    assert result.fun < 1e-9
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)


def test_fused_group_checkpoint_resume(shared_mesh_group, tmp_path):
    group, _ = shared_mesh_group
    kwargs = dict(guess=ParamTuple(-1.8, 0.3), nsteps=20,
                  learning_rate=0.02, progress=False,
                  checkpoint_dir=str(tmp_path), checkpoint_every=5)
    traj = group.run_adam(**kwargs)
    # A finished fit is a pure checkpoint read: identical trajectory.
    traj_resumed = group.run_adam(**kwargs)
    np.testing.assert_array_equal(np.asarray(traj),
                                  np.asarray(traj_resumed))


def test_disjoint_group_checkpoint_raises(group_and_models, tmp_path):
    group, _ = group_and_models
    with pytest.raises(ValueError, match="fused"):
        group.run_adam(guess=ParamTuple(-1.8, 0.3), nsteps=5,
                       checkpoint_dir=str(tmp_path), progress=False)


def test_aux_member_group_sums_scalar_losses(tmp_path):
    # A loss_func_has_aux member forces the host path even on one
    # shared mesh (aux has no fused-sum semantics); the group must
    # unwrap (loss, aux) and sum plain scalars — the reference's
    # group crashes on this case (multigrad.py:576-577).
    comm = mgt.global_comm()
    data = make_smf_data(4_000, comm=comm)

    class AuxSMF(SMFModel):
        def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                    randkey=None):
            base = super().calc_loss_from_sumstats(sumstats)
            return base, jnp.stack([base, 2.0 * base])

    aux_m = AuxSMF(aux_data=data, comm=comm, loss_func_has_aux=True)
    plain = SMFModel(aux_data=data, comm=comm)
    group = mgt.OnePointGroup(models=(aux_m, plain))
    assert not group.fused
    p = ParamTuple(-1.8, 0.3)
    loss, grad = group.calc_loss_and_grad_from_params(p)
    l_aux, _ = aux_m.calc_loss_and_grad_from_params(p)
    l_plain, g_plain = plain.calc_loss_and_grad_from_params(p)
    np.testing.assert_allclose(float(loss),
                               float(l_aux[0]) + float(l_plain),
                               rtol=1e-6)
    assert np.asarray(grad).shape == np.asarray(g_plain).shape
    # the checkpoint_dir diagnostic names the condition
    with pytest.raises(ValueError, match="loss_func_has_aux"):
        group.run_adam(guess=p, nsteps=2,
                       checkpoint_dir=str(tmp_path), progress=False)


# --------------------------------------------------------------------------
# Multi-probe joint fit: SMF + wp(rp) over a shared parameter space
# (BASELINE config 5; param_view adapters)
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def multiprobe_group():
    from multigrad_tpu.models.wprp import WprpModel, make_wprp_data

    comm = mgt.global_comm()
    subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
    smf = SMFModel(aux_data=make_smf_data(10_000, comm=subcomms[0]),
                   comm=subcomms[0])
    smf.aux_data["target_sumstats"] = jnp.asarray(
        smf.calc_sumstats_from_params(TRUTH))
    wp = WprpModel(aux_data=make_wprp_data(768, comm=subcomms[1]),
                   comm=subcomms[1])
    # Joint parameter space: (log_shmrat, sigma_logsm, log_softness).
    # log_shmrat is shared between the probes; the other slots belong
    # to one model each.
    group = mgt.OnePointGroup(models=(
        mgt.param_view(smf, [0, 1]),
        mgt.param_view(wp, [0, 2]),
    ))
    return group, smf, wp


JOINT_TRUTH = jnp.array([-2.0, 0.2, -1.0])


def test_param_view_slices_and_scatters_grads(multiprobe_group):
    group, smf, wp = multiprobe_group
    joint = jnp.array([-1.8, 0.3, -0.7])
    loss, grad = group.calc_loss_and_grad_from_params(joint)

    ls, gs = smf.calc_loss_and_grad_from_params(joint[:2])
    lw, gw = wp.calc_loss_and_grad_from_params(
        jnp.stack([joint[0], joint[2]]))
    gs, gw = np.asarray(gs), np.asarray(gw)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(ls) + np.asarray(lw),
                               rtol=1e-6)
    expected = np.array([gs[0] + gw[0], gs[1], gw[1]])
    np.testing.assert_allclose(np.asarray(grad), expected, rtol=1e-5)


def test_param_view_model_standalone(multiprobe_group):
    # A view is a full OnePointModel: sumstats at joint truth match
    # the wrapped model's at its own truth.
    _, smf, _ = multiprobe_group
    view = mgt.param_view(smf, [0, 1])
    np.testing.assert_allclose(
        np.asarray(view.calc_sumstats_from_params(JOINT_TRUTH)),
        np.asarray(smf.calc_sumstats_from_params(TRUTH)), rtol=1e-6)


def test_param_view_rejects_bad_indices(multiprobe_group):
    # jnp.take clamps negative/out-of-range indices under jit, so they
    # must be rejected eagerly, not silently read the wrong slot.
    _, smf, _ = multiprobe_group
    with pytest.raises(ValueError, match="non-negative"):
        mgt.param_view(smf, [0, -1])
    with pytest.raises(ValueError, match="at least one index"):
        mgt.param_view(smf, [])
    view = mgt.param_view(smf, [0, 3])
    with pytest.raises(ValueError, match="out of range"):
        view.calc_sumstats_from_params(JOINT_TRUTH)


def test_fused_multiprobe_matches_disjoint(multiprobe_group):
    # The same multi-probe fit on ONE shared mesh fuses into a single
    # program and agrees with the disjoint-submesh MPMD group.
    from multigrad_tpu.models.wprp import WprpModel, make_wprp_data

    disjoint, _, _ = multiprobe_group
    comm = mgt.global_comm()
    smf = SMFModel(aux_data=make_smf_data(10_000, comm=comm), comm=comm)
    smf.aux_data["target_sumstats"] = jnp.asarray(
        smf.calc_sumstats_from_params(TRUTH))
    wp = WprpModel(aux_data=make_wprp_data(768, comm=comm), comm=comm)
    fused = mgt.OnePointGroup(models=(
        mgt.param_view(smf, [0, 1]),
        mgt.param_view(wp, [0, 2]),
    ))
    assert fused.fused and not disjoint.fused
    joint = jnp.array([-1.8, 0.3, -0.7])
    loss_f, grad_f = fused.calc_loss_and_grad_from_params(joint)
    loss_h, grad_h = disjoint.calc_loss_and_grad_from_params(joint)
    np.testing.assert_allclose(np.asarray(loss_f), np.asarray(loss_h),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_f), np.asarray(grad_h),
                               rtol=1e-4, atol=1e-7)


def test_multiprobe_joint_fit_recovers_truth(multiprobe_group):
    group, _, _ = multiprobe_group
    result = group.run_bfgs(
        guess=jnp.array([-1.7, 0.35, -0.6]), maxsteps=150,
        param_bounds=[(-4.0, 0.0), (0.01, 1.0), (-2.0, 0.0)],
        progress=False)
    assert result.fun < 1e-5
    np.testing.assert_allclose(result.x, np.asarray(JOINT_TRUTH),
                               atol=0.05)


# --------------------------------------------------------------------- #
# Async MPMD dispatch (the claim behind core/group.py's design)
# --------------------------------------------------------------------- #
def _timed_min(fn, reps=5):
    import time
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


@pytest.fixture(scope="module")
def heavy_disjoint_models():
    import jax
    comm = mgt.global_comm()
    subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
    n = 4_000_000  # big enough that one step is O(100ms) on CPU
    models = tuple(
        SMFModel(aux_data=make_smf_data(n, comm=sub), comm=sub)
        for sub in subcomms)
    p = ParamTuple(-1.9, 0.25)
    for m in models:  # compile + warm up
        np.asarray(m.calc_loss_and_grad_from_params(p)[1])
    return models, p


@pytest.mark.slow  # ~15 s incl. fixture: deliberately heavy members
def test_group_dispatch_is_async(heavy_disjoint_models):
    # The joint step dispatches every model's program before blocking
    # on any result (core/group.py:123-135).  Dispatch must therefore
    # cost a small fraction of the blocked step — that slack is what
    # disjoint sub-meshes overlap into.  Measured here: ~2ms dispatch
    # vs ~600ms blocked on the 8-virtual-device CPU mesh.
    models, p = heavy_disjoint_models

    def dispatch_only():
        return [m.calc_loss_and_grad_from_params(p) for m in models]

    def blocked():
        for r in dispatch_only():
            np.asarray(r[0]); np.asarray(r[1])

    t_dispatch = _timed_min(dispatch_only)
    t_blocked = _timed_min(blocked)
    assert t_dispatch < 0.2 * t_blocked, (t_dispatch, t_blocked)


@pytest.mark.skipif(
    os.environ.get("MGT_TIMING_TESTS") != "1",
    reason="wall-clock test: opt in with MGT_TIMING_TESTS=1 "
           "(contended CI runners flake it; the overlap *mechanism* "
           "is covered by test_group_dispatch_is_async's "
           "contention-insensitive dispatch/blocked ratio)")
@pytest.mark.skipif((os.cpu_count() or 1) < 3,
                    reason="wall-clock overlap needs >=2 free cores")
def test_group_overlap_beats_serialized(heavy_disjoint_models):
    # With real parallel hardware under the two sub-meshes, the joint
    # step should approach max(t1, t2) rather than t1 + t2.  Generous
    # bound.  The core-count guard can't see *contention* (noisy CI
    # neighbors), so even opted-in the wall-clock assertion gets a few
    # fresh measurement rounds before it is allowed to fail.
    models, p = heavy_disjoint_models
    group = mgt.OnePointGroup(models=models)
    np.asarray(group.calc_loss_and_grad_from_params(p)[1])  # warm

    def serialized():
        for m in models:
            r = m.calc_loss_and_grad_from_params(p)
            np.asarray(r[0]); np.asarray(r[1])

    def joint():
        r = group.calc_loss_and_grad_from_params(p)
        np.asarray(r[0]); np.asarray(r[1])

    observed = []
    for _attempt in range(3):
        t_serial = _timed_min(serialized)
        t_joint = _timed_min(joint)
        observed.append((t_joint, t_serial))
        if t_joint < 0.85 * t_serial:
            return
    pytest.fail(f"no overlap speedup in any round: {observed}")
