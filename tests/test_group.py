"""OnePointGroup (MPMD composition) tests.

Mirrors the reference's group semantics (``multigrad.py:547-607``):
joint loss/grad is the sum over component models, each model owning a
sub-communicator; optimizer proxies work on the group.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data

TRUTH = ParamTuple(log_shmrat=-2.0, sigma_logsm=0.2)


@pytest.fixture(scope="module")
def group_and_models():
    comm = mgt.global_comm()
    subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
    # Two probes of the same parameter space: the same SMF model on
    # different data sizes, each on its own 4-device sub-mesh.
    m1 = SMFModel(aux_data=make_smf_data(10_000, comm=subcomms[0]),
                  comm=subcomms[0])
    m2 = SMFModel(aux_data=make_smf_data(20_000, comm=subcomms[1]),
                  comm=subcomms[1])
    # Self-consistent targets (see test_smf_pipeline.py): "stays at
    # truth" invariants need each model's own float32 sumstats.
    for m in (m1, m2):
        m.aux_data["target_sumstats"] = jnp.asarray(
            m.calc_sumstats_from_params(TRUTH))
    return mgt.OnePointGroup(models=(m1, m2)), (m1, m2)


def test_group_sums_losses_and_grads(group_and_models):
    group, (m1, m2) = group_and_models
    params = jnp.array([-1.8, 0.3])
    loss, grad = group.calc_loss_and_grad_from_params(params)
    l1, g1 = m1.calc_loss_and_grad_from_params(params)
    l2, g2 = m2.calc_loss_and_grad_from_params(params)
    # (sum on host: the component results live on disjoint sub-meshes)
    np.testing.assert_allclose(np.asarray(loss),
                               np.asarray(l1) + np.asarray(l2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grad),
                               np.asarray(g1) + np.asarray(g2), rtol=1e-6)


def test_single_model_group(group_and_models):
    _, (m1, _) = group_and_models
    group = mgt.OnePointGroup(models=m1)
    assert isinstance(group.models, tuple)
    params = jnp.array([-2.0, 0.2])
    loss, _ = group.calc_loss_and_grad_from_params(params)
    l1, _ = m1.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(l1), rtol=1e-6)


def test_group_bfgs(group_and_models):
    # Box bounds keep the line search away from sigma <= 0 (where the
    # log-loss is undefined) — the joint gradient is ~2x a single
    # model's, so the unbounded first step would overshoot.
    group, _ = group_and_models
    result = group.run_bfgs(guess=ParamTuple(-1.5, 0.4), maxsteps=100,
                            param_bounds=[(-4.0, 0.0), (0.01, 1.0)],
                            progress=False)
    # scipy may flag ABNORMAL when it grinds into the float32 noise
    # floor; judge by solution quality (loss + recovered params).
    assert result.fun < 1e-9
    np.testing.assert_allclose(result.x, [*TRUTH], atol=1e-3)


def test_group_adam(group_and_models):
    group, _ = group_and_models
    traj = group.run_adam(guess=ParamTuple(-1.8, 0.3), nsteps=100,
                          learning_rate=0.02, progress=False)
    assert traj.shape == (101, 2)
    np.testing.assert_allclose(np.asarray(traj[-1]), [*TRUTH], atol=0.05)


def test_group_simple_gd(group_and_models):
    group, _ = group_and_models
    res = group.run_simple_grad_descent(guess=jnp.array([*TRUTH]), nsteps=2)
    assert jnp.isclose(res.loss[-1], 0.0, atol=1e-8)
    np.testing.assert_allclose(np.asarray(res.params[-1]), [*TRUTH],
                               rtol=1e-5)
