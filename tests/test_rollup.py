"""Telemetry history plane (telemetry/rollup + telemetry/budget).

The PR-20 acceptance battery:

* **windowed store** — counters/gauges/distributions fold into tiered
  aligned windows; `delta`/`rate`/`mean_over`/`quantile_over`/`trend`
  hand-check against a fake clock; memory stays bounded by the fixed
  rings;
* **the ramp** — a deterministic rising-latency ramp is *visible* to
  the windowed p95 + trend and *invisible* to the old cumulative
  histogram quantile (the whole point of the history plane);
* **budget arithmetic** — remaining fraction, multi-window burn rate
  and exhaustion ETA against hand-computed values; the fast pair
  pages only when BOTH windows exceed the threshold;
* **rising edge** — one `BurnRateAlert` record per burn episode, a
  second episode after the first clears;
* **wire** — the heartbeat `rollup` codec round-trips and is
  forward-compatible BOTH directions (decorated delta at a legacy
  reader, legacy heartbeat at a decorated router, junk types
  null out);
* **usage accounting** — per-(tenant, class) records flow into the
  report's `usage:` section, `telemetry.top --tenants`, and the
  dashboard's budget line;
* **end-to-end** — a real scheduler with `history=True` populates
  the rollup store, emits `tenant_usage`/`slo_budget` records, and
  feeds autoscaler v2 via the exported gauges.
"""
import json

import numpy as np
import pytest

from multigrad_tpu.telemetry import (AlertEngine, BurnRateAlert,
                                     LiveMetrics, MemorySink,
                                     MetricsLogger, RollupStore,
                                     SloBudget)
from multigrad_tpu.telemetry.budget import (FAST_BURN_THRESHOLD,
                                            FAST_WINDOWS)
from multigrad_tpu.telemetry.resources import autoscaler_inputs
from multigrad_tpu.telemetry.rollup import (BUSY_FRAC, DELTA_KEYS,
                                            FITS, QUEUE_WAIT_S,
                                            SHEDS)
from multigrad_tpu.serve.wire import rollup_from_wire, rollup_to_wire

T0 = 1_000_000.0


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------------ #
# windowed store
# ------------------------------------------------------------------ #
def test_rollup_windowed_queries_hand_checked():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    # 12 increments of 2 over 120 s, one busy_frac gauge per window
    for i in range(12):
        t = T0 + 10.0 * i
        store.inc(FITS, 2, t=t)
        store.set(BUSY_FRAC, 0.5, t=t)
    clock.t = T0 + 120.0
    assert store.delta(FITS, 60.0) == pytest.approx(12.0)   # 6 windows
    assert store.rate(FITS, 60.0) == pytest.approx(0.2)
    assert store.delta(FITS, 600.0) == pytest.approx(24.0)  # all of it
    assert store.mean_over(BUSY_FRAC, 600.0) == pytest.approx(0.5)
    # distributions: exact interpolated quantile over kept samples
    for i, v in enumerate([0.1, 0.2, 0.3, 0.4, 1.0]):
        store.observe(QUEUE_WAIT_S, v, t=T0 + 100.0 + i)
    assert store.max_over(QUEUE_WAIT_S, 60.0) == pytest.approx(1.0)
    assert store.quantile_over(QUEUE_WAIT_S, 0.5, 60.0) \
        == pytest.approx(0.3)
    # unknown series and empty windows answer None, never 0
    assert store.delta("nope", 60.0) is None
    assert store.quantile_over(FITS, 0.5, 60.0) is None


def test_rollup_retention_is_bounded():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    # a day of 1 Hz traffic must not grow beyond the fixed rings
    for i in range(0, 86_400, 60):
        store.inc(FITS, 1, t=T0 + i)
        store.observe(QUEUE_WAIT_S, 0.01, t=T0 + i)
    clock.t = T0 + 86_400.0
    s = store._series[FITS]
    for width, ring in s.tiers:
        assert len(ring) <= ring.maxlen
    # samples capped too (decimation keeps the ring bounded)
    qs = store._series[QUEUE_WAIT_S]
    width, ring = qs.tiers[0]
    assert sum(len(w.samples or ()) for w in ring) <= 512
    # old data aged out of the coarse tier: only the trailing 8 h
    assert store.delta(FITS, 28_800.0) < 86_400 / 60


def test_trend_needs_min_windows():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    store.observe(QUEUE_WAIT_S, 1.0, t=T0)
    store.observe(QUEUE_WAIT_S, 2.0, t=T0 + 10.0)
    clock.t = T0 + 20.0
    # two windows are not a trend
    assert store.trend(QUEUE_WAIT_S, 300.0) is None
    store.observe(QUEUE_WAIT_S, 3.0, t=T0 + 20.0)
    store.observe(QUEUE_WAIT_S, 4.0, t=T0 + 30.0)
    clock.t = T0 + 40.0
    slope = store.trend(QUEUE_WAIT_S, 300.0)
    # 1.0 per 10 s window = 0.1 units/s, exactly (noise-free ramp)
    assert slope == pytest.approx(0.1)


# ------------------------------------------------------------------ #
# THE acceptance ramp: windowed sees it, cumulative cannot
# ------------------------------------------------------------------ #
def test_rising_ramp_visible_windowed_invisible_cumulative():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    lm = LiveMetrics()

    def feed(v, t):
        store.observe(QUEUE_WAIT_S, v, t=t)
        lm.observe("multigrad_fleet_hop_seconds", v,
                   labels={"hop": "queue_wait"})

    # 10 minutes of healthy traffic: 200 fast fits at 50 ms
    for i in range(200):
        feed(0.05, T0 + 3.0 * i)
    # then queue wait RAMPS: 8 recent fits climbing 0.5 s -> 2.25 s —
    # under 4 % of total traffic, so a cumulative quantile stays put
    ramp_t0 = T0 + 600.0
    for i in range(8):
        feed(0.5 + 0.25 * i, ramp_t0 + 35.0 * i)
    clock.t = now = T0 + 900.0

    windowed_p95 = store.quantile_over(QUEUE_WAIT_S, 0.95, 300.0,
                                       now=now)
    slope = store.trend(QUEUE_WAIT_S, 300.0, now=now)
    # the windowed path tracks the ramp...
    assert windowed_p95 > 1.0
    assert slope > 0.0
    # ...while the lifetime-cumulative histogram p95 still reports
    # the fast steady state (the ramp is <5% of all samples), which
    # is exactly why v1's autoscaler could not see this coming
    cumulative = autoscaler_inputs(lm)
    assert cumulative["queue_wait_p95_s"] is not None
    assert cumulative["queue_wait_p95_s"] < 0.5
    assert cumulative["queue_wait_p95_s"] < windowed_p95
    # autoscaler v2 reads the windowed path when given the store
    v2 = autoscaler_inputs(lm, rollup=store)
    assert v2["queue_wait_p95_s"] == pytest.approx(windowed_p95)
    assert v2["queue_wait_p95_trend"] == pytest.approx(slope)
    # ...or, with zero plumbing, via the exported gauges
    store.export(lm, window_s=300.0)
    gauges = autoscaler_inputs(lm)
    assert gauges["queue_wait_p95_s"] == pytest.approx(windowed_p95)
    assert gauges["queue_wait_p95_trend"] == pytest.approx(slope)


# ------------------------------------------------------------------ #
# budget arithmetic, hand-computed
# ------------------------------------------------------------------ #
def test_budget_arithmetic_hand_checked():
    clock = FakeClock()
    ledger = SloBudget("interactive", threshold_s=1.0, budget=0.05,
                       clock=clock)
    # 100 requests in one minute, 2 over the objective
    for i in range(100):
        bad = i in (10, 50)
        ledger.observe(2.0 if bad else 0.5, t=T0 + 0.6 * i)
    clock.t = T0 + 60.0
    snap = ledger.snapshot()
    # remaining = 1 - bad/(total*budget) = 1 - 2/(100*0.05) = 0.6
    assert snap["total"] == 100 and snap["violations"] == 2
    assert snap["remaining_frac"] == pytest.approx(0.6)
    # burn = (2/100)/0.05 = 0.4 on every window (same samples)
    assert snap["burn_rate"] == pytest.approx(0.4)
    # eta = remaining * window / burn = 0.6 * 21600 / 0.4 = 32400
    assert snap["exhaustion_eta_s"] == pytest.approx(32_400.0)
    assert snap["fast_burning"] is False
    assert snap["slow_burning"] is False
    # a shed burns like a violation
    ledger.record_shed(t=T0 + 61.0)
    snap = ledger.snapshot()
    assert snap["violations"] == 3
    # flood: 300 violations push bad/total over the 14.4x fast pair
    for i in range(300):
        ledger.observe(5.0, t=T0 + 70.0 + 0.1 * i)
    clock.t = T0 + 110.0
    snap = ledger.snapshot()
    # burn = (303/401)/0.05 = 15.11 > 14.4 on BOTH fast windows
    assert snap["burn_rate"] == pytest.approx(303 / 401 / 0.05,
                                              rel=1e-6)
    assert snap["fast_burning"] is True
    # budget overspent: remaining clamps at 0, eta says "now"
    assert snap["remaining_frac"] == 0.0
    assert snap["exhaustion_eta_s"] == 0.0


def test_budget_pair_needs_both_windows():
    # the long window vetoes a one-spike page: a burst that exceeds
    # the threshold over 5 m but not over 1 h must NOT page
    clock = FakeClock(T0 + 3000.0)
    ledger = SloBudget("interactive", threshold_s=1.0, budget=0.05,
                       clock=clock)
    # an hour's worth of good traffic first...
    for i in range(0, 2900, 10):
        ledger.observe(0.1, t=T0 + i)
    # ...then a short violation spike
    for i in range(60):
        ledger.observe(5.0, t=T0 + 2940.0 + i)
    short = ledger.burn_rate(FAST_WINDOWS[0])
    long = ledger.burn_rate(FAST_WINDOWS[1])
    assert short > FAST_BURN_THRESHOLD
    assert long < FAST_BURN_THRESHOLD
    assert ledger.fast_burning() is False


def test_budget_no_traffic_is_none_not_zero():
    ledger = SloBudget("interactive", threshold_s=1.0,
                       clock=FakeClock())
    assert ledger.burn_rate(300.0) is None
    snap = ledger.snapshot()
    assert snap["remaining_frac"] == 1.0
    assert snap["exhaustion_eta_s"] is None


def test_budget_exports_gauges_and_exemplar():
    lm = LiveMetrics()
    clock = FakeClock()
    ledger = SloBudget("interactive", threshold_s=1.0, budget=0.05,
                       live=lm, clock=clock)
    for _ in range(19):
        ledger.observe(0.2, t=T0)
    ledger.observe(3.0, trace_id="trace-abc", t=T0 + 1.0)
    labels = {"priority_class": "interactive"}
    # 1 bad / 20 total at 5% budget: remaining = 1 - 1/(20*0.05) = 0
    assert lm.value("multigrad_slo_budget_remaining_frac",
                    labels=labels) == pytest.approx(0.0)
    assert lm.value("multigrad_slo_budget_burn_rate",
                    labels=labels) == pytest.approx(1.0)
    assert lm.value("multigrad_slo_budget_fast_burning",
                    labels=labels) == 0.0
    # the violating fit's trace id rode along as the exemplar
    hist = lm.snapshot()["multigrad_slo_budget_violation_seconds"]
    assert "trace-abc" in json.dumps(hist)


# ------------------------------------------------------------------ #
# burn-rate alert: one record per episode
# ------------------------------------------------------------------ #
def test_burn_rate_alert_rising_edge():
    clock = FakeClock()
    ledger = SloBudget("batch", threshold_s=0.001, budget=0.05,
                       clock=clock)
    for i in range(10):
        ledger.observe(1.0, t=T0 + i)       # all violations: burn 20x
    clock.t = T0 + 20.0
    engine = AlertEngine(rules=[BurnRateAlert({"batch": ledger})])
    for _ in range(5):                      # condition HELD across...
        engine.write({"event": "heartbeat"})
    fired = [a for a in engine.alerts
             if a.get("rule") == "slo_burn_rate"]
    assert len(fired) == 1                  # ...but fires ONCE
    assert "batch" in fired[0]["classes"]
    assert fired[0]["classes"]["batch"]["burn_rate"] \
        == pytest.approx(20.0)
    # burn clears (windows age out) -> rule re-arms silently
    clock.t = T0 + 20_000.0
    engine.write({"event": "heartbeat"})
    assert len(engine.alerts) == 1
    # a second burn episode fires a second alert
    for i in range(10):
        ledger.observe(1.0, t=clock.t + i)
    clock.t += 20.0
    engine.write({"event": "heartbeat"})
    engine.write({"event": "heartbeat"})
    assert len(engine.alerts) == 2


# ------------------------------------------------------------------ #
# heartbeat wire codec: round trip + forward compat both directions
# ------------------------------------------------------------------ #
def test_rollup_wire_roundtrip():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    store.inc(FITS, 3, t=T0 + 1.0)
    store.inc(SHEDS, 1, t=T0 + 2.0)
    store.observe(QUEUE_WAIT_S, 0.25, t=T0 + 3.0)
    clock.t = T0 + 10.0
    delta = store.take_delta()
    wire = rollup_to_wire(delta)
    assert set(wire) <= set(DELTA_KEYS)
    back = rollup_from_wire(json.loads(json.dumps(wire)))
    assert back["fits"] == 3 and back["sheds"] == 1
    assert back["queue_wait_count"] == 1
    assert back["queue_wait_sum_s"] == pytest.approx(0.25)
    # idle worker: no delta, the key stays OFF the heartbeat
    assert store.take_delta() is None
    assert rollup_to_wire(None) is None


def test_rollup_wire_forward_compat_both_directions():
    # a NEWER worker decorates the delta with fields this router
    # predates: unknown keys dropped, known keys decode
    decorated = {"fits": 4, "queue_wait_count": 2,
                 "queue_wait_sum_s": 0.5,
                 "from_the_future": {"x": 1}}
    back = rollup_from_wire(decorated)
    assert "from_the_future" not in back
    assert back["fits"] == 4
    # a LEGACY worker ships no rollup at all: decodes to "no
    # history", never fabricated zeros
    assert rollup_from_wire(None) is None
    assert rollup_from_wire("bogus") is None
    # junk types for known keys null out instead of raising
    junk = rollup_from_wire({"fits": "3", "span_s": "soon",
                             "queue_wait_max_s": None})
    assert junk["fits"] is None
    assert junk["span_s"] is None


def test_take_delta_and_fleet_merge():
    clock = FakeClock()
    worker = RollupStore(clock=clock)
    router = RollupStore(clock=clock)
    worker.inc(FITS, 5, t=T0 + 1.0)
    worker.observe(QUEUE_WAIT_S, 0.2, t=T0 + 1.0)
    worker.observe(QUEUE_WAIT_S, 0.6, t=T0 + 2.0)
    clock.t = T0 + 10.0
    d1 = worker.take_delta()
    assert d1["fits"] == 5 and d1["queue_wait_count"] == 2
    router.merge_delta(d1, worker="w0")
    # cursors reset: the next take only carries NEW work
    worker.inc(FITS, 2, t=T0 + 12.0)
    clock.t = T0 + 20.0
    d2 = worker.take_delta()
    assert d2["fits"] == 2
    assert d2["span_s"] == pytest.approx(10.0)
    router.merge_delta(d2, worker="w0")
    clock.t = T0 + 30.0
    assert router.delta("fleet.fits", 300.0) == pytest.approx(7.0)
    assert router.delta(("worker_fits", "w0"), 300.0) \
        == pytest.approx(7.0)
    # merged stats are aggregate-only: mean/max answer, exact
    # quantiles honestly decline (no raw samples crossed the wire)
    assert router.mean_over("fleet.queue_wait_s", 300.0) \
        == pytest.approx(0.4)
    assert router.max_over("fleet.queue_wait_s", 300.0) \
        == pytest.approx(0.6)
    assert router.quantile_over("fleet.queue_wait_s", 0.95,
                                300.0) is None


# ------------------------------------------------------------------ #
# usage accounting -> report / top / dashboard surfaces
# ------------------------------------------------------------------ #
def test_usage_records_and_report_sections():
    clock = FakeClock()
    store = RollupStore(clock=clock)
    store.note_usage("hog", "batch", fits=3, busy_s=1.5, t=T0)
    store.note_usage("hog", "batch", sheds=2, violations=1,
                     t=T0 + 1.0)
    store.note_usage("lab", "interactive", fits=1, busy_s=0.2,
                     t=T0 + 2.0)
    clock.t = T0 + 10.0
    recs = store.usage_records()
    assert [(r["tenant"], r["priority_class"]) for r in recs] \
        == [("hog", "batch"), ("lab", "interactive")]
    hog = recs[0]
    assert hog["fits"] == 3 and hog["sheds"] == 2
    assert hog["violations"] == 1
    assert hog["busy_s"] == pytest.approx(1.5)
    assert hog["fits_windowed"] == 3

    from multigrad_tpu.telemetry.report import render, summarize
    stream = [{"event": "tenant_usage", "t": T0 + 10.0, **r}
              for r in recs]
    stream.append({"event": "slo_budget", "t": T0 + 11.0,
                   "priority_class": "batch", "budget": 0.05,
                   "remaining_frac": 0.25, "burn_rate": 16.0,
                   "fast_burning": True, "violations": 1})
    summary = summarize(stream)
    assert summary["usage"]["hog/batch"]["fits"] == 3
    assert summary["slo_budget"]["batch"]["fast_burning"] is True
    text = render(summary)
    assert "usage (tenant/class):" in text
    assert "hog/batch: 3 fits" in text
    assert "slo budget: batch: 25% left, burn=16!" in text


def test_top_slo_column_and_tenants_mode(tmp_path, capsys):
    from multigrad_tpu.telemetry import top

    path = tmp_path / "w0.jsonl"
    recs = [
        {"event": "resource_sample", "t": T0, "busy_frac": 0.5,
         "rss_bytes": 1 << 20},
        {"event": "slo_budget", "priority_class": "batch",
         "remaining_frac": 0.37, "burn_rate": 16.2,
         "fast_burning": True},
        {"event": "slo_budget", "priority_class": "interactive",
         "remaining_frac": 1.0, "burn_rate": 0.0,
         "fast_burning": False},
        {"event": "tenant_usage", "tenant": "hog",
         "priority_class": "batch", "fits": 12, "busy_s": 3.4,
         "sheds": 2, "violations": 9},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert top.main(["--once", str(path)]) == 0
    out = capsys.readouterr().out
    assert "SLO" in out
    # worst class (batch) summarized, fast-burn flagged with `!`
    assert "37% b=16.2!" in out
    assert top.main(["--once", "--tenants", str(path)]) == 0
    out = capsys.readouterr().out
    assert "TENANT/CLASS" in out
    assert "hog/batch" in out and "12" in out
    # a source with no declared SLOs renders `-`, never zero
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps(recs[0]) + "\n")
    assert top.main(["--once", str(bare)]) == 0
    row = capsys.readouterr().out.splitlines()[-1]
    assert " - " in row


def test_dashboard_budget_line():
    from multigrad_tpu.telemetry.dashboard import collect, render

    view = collect([
        {"event": "slo_budget", "priority_class": "batch",
         "remaining_frac": 0.4, "burn_rate": 15.0,
         "fast_burning": True},
        {"event": "slo_budget", "priority_class": "batch",
         "remaining_frac": 0.3, "burn_rate": 16.0,
         "fast_burning": True},               # newest per class wins
        {"event": "slo_budget", "priority_class": "interactive",
         "remaining_frac": 1.0, "burn_rate": 0.0,
         "fast_burning": False},
    ])
    text = render(view)
    assert "slo  batch 30% b=16.0!  interactive 100% b=0.0" in text


# ------------------------------------------------------------------ #
# end-to-end: a real scheduler populates the history plane
# ------------------------------------------------------------------ #
def test_scheduler_history_end_to_end():
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler

    sink = MemorySink()
    logger = MetricsLogger(sink)
    lm = LiveMetrics()
    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)
    with FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                      telemetry=logger, live=lm, qos=True,
                      slo=["p95 < 60 s for interactive"],
                      monitor_resources=False) as sched:
        assert sched.rollup is not None
        futs = [sched.submit(np.array([-1.8, 0.45]), nsteps=4,
                             learning_rate=0.05, randkey=k,
                             tenant="lab",
                             priority_class="interactive")
                for k in (1, 2, 3)]
        for f in futs:
            f.result(timeout=240)
        assert sched.rollup.delta(FITS, 600.0) == pytest.approx(3.0)
        assert sched.rollup.quantile_over(QUEUE_WAIT_S, 0.95,
                                          600.0) is not None
        usage = sched.rollup.usage_records()
        assert usage and usage[0]["tenant"] == "lab"
        assert usage[0]["fits"] == 3
        # budget ledger fed from the settle path, whole budget left
        snap = sched.slo.budgets["interactive"].snapshot()
        assert snap["total"] == 3
        assert snap["remaining_frac"] == 1.0
        # the worker-side heartbeat delta is ready to ship
        delta = sched.rollup.take_delta()
        assert delta["fits"] == 3
        assert rollup_to_wire(delta)["fits"] == 3
    # the stream carries the usage/budget records for report/top
    events = {r["event"] for r in sink.records}
    assert "tenant_usage" in events
    assert "slo_budget" in events
    # history=False turns the whole plane off
    with FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                      history=False,
                      monitor_resources=False) as off:
        assert off.rollup is None
        off.submit(np.array([-1.8, 0.45]), nsteps=2,
                   learning_rate=0.05).result(timeout=240)
    logger.close()
