"""Fleet resource observability (telemetry/resources + telemetry/top).

The PR-18 acceptance battery:

* **degrade, never die** — every probe returns ``None`` fields on the
  CPU backend (no ``memory_stats()``, maybe no procfs) with exactly
  ONE ``resource_monitor_degraded`` telemetry note, and a full fit
  through a monitored scheduler succeeds regardless;
* **duty cycle** — the dispatch enter/exit hooks accumulate busy
  seconds re-entrantly and each sample folds them into a window
  ``busy_frac`` in [0, 1]; the sample ring stays bounded;
* **compile accounting** — the single program-cache boundary reports
  miss-then-hit for a repeated key, and the totals survive into the
  monitor's samples;
* **memory truth** — the scheduler emits one ``measured_vs_modeled``
  record per bucket dispatch (fields null on CPU — the regress gate
  warns instead of failing, so CPU CI never flakes);
* **wire** — the heartbeat ``resources`` codec round-trips the known
  keys and is forward-compatible BOTH directions (a decorated
  snapshot at a legacy reader, a legacy heartbeat at a decorated
  router);
* **fleet top** — the CLI renders per-worker columns from a router
  stats snapshot and from a telemetry JSONL stream (the live-fleet
  leg rides in ``test_fleet.py`` on an already-spawned fleet).
"""
import json
import time

import numpy as np
import pytest

from multigrad_tpu.telemetry import (LiveMetrics, MemorySink,
                                     MetricsLogger)
from multigrad_tpu.telemetry.resources import (SNAPSHOT_KEYS,
                                               ResourceMonitor,
                                               autoscaler_inputs,
                                               compile_totals,
                                               device_memory,
                                               measured_vs_modeled,
                                               read_rss_bytes,
                                               reset_compile_totals)
from multigrad_tpu.serve.wire import (resources_from_wire,
                                      resources_to_wire)


def new_logger():
    sink = MemorySink()
    return MetricsLogger(sink), sink


def events(sink, name):
    return [r for r in sink.records if r["event"] == name]


# ------------------------------------------------------------------ #
# probes
# ------------------------------------------------------------------ #
def test_probes_never_raise_on_cpu():
    rss = read_rss_bytes()
    assert rss is None or (isinstance(rss, int) and rss > 0)
    dev = device_memory()
    assert set(dev) == {"bytes_in_use", "peak_bytes", "bytes_limit",
                        "supported"}
    # CPU backend: unsupported, all fields null — never fabricated 0s
    assert dev["supported"] is False
    assert dev["bytes_in_use"] is None
    assert dev["peak_bytes"] is None
    assert dev["bytes_limit"] is None


def test_measured_vs_modeled_fields():
    exact = measured_vs_modeled(1000, 1000)
    assert exact["measured_ratio"] == pytest.approx(1.0)
    assert exact["accuracy_frac"] == pytest.approx(1.0)
    off = measured_vs_modeled(1500, 1000)
    assert off["measured_ratio"] == pytest.approx(1.5)
    assert off["accuracy_frac"] == pytest.approx(0.5)
    # unmeasurable (CPU): null ratio fields, never a crash or a zero
    null = measured_vs_modeled(None, 1000)
    assert null["modeled_bytes"] == 1000
    assert null["measured_peak_bytes"] is None
    assert null["measured_ratio"] is None
    assert null["accuracy_frac"] is None


# ------------------------------------------------------------------ #
# the monitor: degrade note, duty cycle, ring bounds
# ------------------------------------------------------------------ #
def test_monitor_degrades_once_with_null_device_fields():
    logger, sink = new_logger()
    mon = ResourceMonitor(live=LiveMetrics(), logger=logger,
                          interval_s=60.0, emit_every=1)
    for _ in range(3):
        mon.sample()
    sample = mon.snapshot()
    assert sample["device_bytes_in_use"] is None
    assert sample["device_peak_bytes"] is None
    assert mon.degraded
    # the note is one-shot: three degraded samples, ONE record
    assert len(events(sink, "resource_monitor_degraded")) == 1
    mon.close()
    logger.close()


def test_busy_hooks_reentrant_and_busy_frac_clamped():
    mon = ResourceMonitor(interval_s=60.0)
    mon.sample()
    with mon.dispatching():
        with mon.dispatching():          # nested: depth-counted once
            time.sleep(0.03)
    busy = mon.busy_seconds
    assert 0.02 <= busy < 1.0
    sample = mon.sample()
    assert sample["busy_s_total"] == pytest.approx(busy, abs=0.05)
    assert 0.0 <= sample["busy_frac"] <= 1.0
    # an open dispatch is counted up to "now", not lost
    mon.dispatch_enter()
    time.sleep(0.02)
    assert mon.busy_seconds > busy
    mon.dispatch_exit()
    mon.close()


def test_sample_ring_is_bounded():
    mon = ResourceMonitor(interval_s=60.0, capacity=4)
    for _ in range(9):
        mon.sample()
    ring = mon.ring()
    assert len(ring) == 4
    # snapshot() is the newest ring entry, minus the event tag
    snap = mon.snapshot()
    assert set(snap) == set(SNAPSHOT_KEYS)
    assert snap["t"] == ring[-1]["t"]
    mon.close()


def test_monitor_thread_samples_and_exports_gauges():
    lm = LiveMetrics()
    with ResourceMonitor(live=lm, interval_s=0.02) as mon:
        with mon.dispatching():
            time.sleep(0.06)
        time.sleep(0.05)
    assert len(mon.ring()) >= 3
    assert lm.value("multigrad_resource_uptime_seconds") > 0
    assert lm.value("multigrad_resource_busy_seconds_total") \
        == pytest.approx(mon.busy_seconds, abs=0.05)
    # some mid-burst window saw the dispatch
    fracs = [s["busy_frac"] for s in mon.ring()
             if s["busy_frac"] is not None]
    assert any(f > 0.2 for f in fracs)


# ------------------------------------------------------------------ #
# compile accounting at the program-cache boundary
# ------------------------------------------------------------------ #
def test_compile_accounting_miss_then_hit():
    from multigrad_tpu.utils.util import cached_program

    mon = ResourceMonitor(interval_s=60.0)    # installs the observer
    reset_compile_totals()

    def owner():                              # fresh cache owner
        pass

    built = []

    def build():
        built.append(1)
        time.sleep(0.01)
        return "program"

    key = ("test_compile_accounting", 1)
    assert cached_program(owner, key, build) == "program"
    assert cached_program(owner, key, build) == "program"
    assert built == [1]                       # second call: cache hit
    totals = compile_totals()
    assert totals["misses"] == 1
    assert totals["hits"] == 1
    assert totals["count"] == 1
    sample = mon.sample()
    assert sample["compile_misses"] == 1
    assert sample["compile_hits"] == 1
    mon.close()


def test_real_fit_records_backend_compile_seconds():
    import jax
    import jax.numpy as jnp
    from multigrad_tpu.utils.util import cached_program

    ResourceMonitor(interval_s=60.0).close()  # ensure listener is on
    reset_compile_totals()

    def owner():
        pass

    # jax.monitoring's backend_compile events fire at first CALL of
    # the jitted program (compilation is lazy), not at build time —
    # the seconds total must reflect the real XLA wall time.
    program = cached_program(owner, ("t", 2),
                             lambda: jax.jit(lambda x: jnp.sin(x) * 2))
    float(program(jnp.float32(0.5)))
    totals = compile_totals()
    assert totals["seconds"] > 0.0


# ------------------------------------------------------------------ #
# the satellite: a monitored full fit on CPU never raises
# ------------------------------------------------------------------ #
def test_monitored_scheduler_full_fit_on_cpu():
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler

    logger, sink = new_logger()
    lm = LiveMetrics()
    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)
    with FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                      telemetry=logger, live=lm) as sched:
        futs = [sched.submit(np.array([-1.8, 0.45]), nsteps=6,
                             learning_rate=0.05, randkey=k)
                for k in (1, 1, 2)]
        results = [f.result(timeout=240) for f in futs]
        assert sched.resources is not None
        snap = sched.resources.snapshot()
    assert all(np.isfinite(r.loss) for r in results)
    # CPU: degraded (no memory_stats), exactly one note, fit fine
    assert sched.resources.degraded
    assert len(events(sink, "resource_monitor_degraded")) == 1
    assert snap["busy_s_total"] > 0
    assert snap["rss_bytes"] > 0
    # one memory-truth record per bucket dispatch, measured fields
    # null on CPU -> the regress gate warns instead of failing
    mvm = events(sink, "measured_vs_modeled")
    assert len(mvm) >= 2
    assert len(mvm) == len(events(sink, "serve_dispatch"))
    for rec in mvm:
        assert rec["bucket"] == 4
        assert rec["modeled_bytes"] > 0
        assert rec["measured_peak_bytes"] is None
        assert rec["accuracy_frac"] is None
        assert rec["n_replicas"] == 1
    # monitor-off path stays available and skips the records
    with FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                      monitor_resources=False) as off:
        assert off.resources is None
        off.submit(np.array([-1.8, 0.45]), nsteps=2,
                   learning_rate=0.05).result(timeout=240)


# ------------------------------------------------------------------ #
# autoscaler inputs
# ------------------------------------------------------------------ #
def test_autoscaler_inputs_contract():
    lm = LiveMetrics()
    # v2 contract: the three v1 keys plus the PR-20 windowed trend
    # signals — all None on an empty registry, never absent
    assert autoscaler_inputs(lm) == {"busy_frac": None,
                                     "queue_wait_p95_s": None,
                                     "headroom_bytes": None,
                                     "queue_wait_p95_trend": None,
                                     "busy_frac_sustained": None,
                                     "slo_burn_rate": None}
    lm.set("multigrad_resource_busy_frac", 0.8)
    lm.set("multigrad_resource_device_bytes_limit", 16 * 2 ** 30)
    lm.set("multigrad_resource_device_peak_bytes", 10 * 2 ** 30)
    for v in (0.01, 0.05, 0.2):
        lm.observe("multigrad_fleet_hop_seconds", v,
                   labels={"hop": "queue_wait"})
        lm.observe("multigrad_fleet_hop_seconds", 9.0,
                   labels={"hop": "device_fit"})  # wrong hop: ignored
    out = autoscaler_inputs(lm)
    assert out["busy_frac"] == pytest.approx(0.8)
    assert out["headroom_bytes"] == 6 * 2 ** 30
    assert out["queue_wait_p95_s"] is not None
    assert out["queue_wait_p95_s"] < 9.0
    # a live monitor's snapshot takes precedence over the gauges
    mon = ResourceMonitor(interval_s=60.0)
    mon.sample()
    monitored = autoscaler_inputs(lm, monitor=mon)
    assert monitored["headroom_bytes"] is None    # CPU: no limit
    mon.close()


# ------------------------------------------------------------------ #
# heartbeat wire codec: round trip + forward compat both directions
# ------------------------------------------------------------------ #
def test_resources_wire_roundtrip():
    mon = ResourceMonitor(interval_s=60.0)
    mon.sample()
    snap = mon.snapshot()
    wire = resources_to_wire(snap)
    assert set(wire) == set(SNAPSHOT_KEYS)
    back = resources_from_wire(json.loads(json.dumps(wire)))
    assert back == wire
    mon.close()


def test_resources_wire_forward_compat_both_directions():
    # a NEWER worker decorates the snapshot with fields this router
    # predates: unknown keys are dropped, known keys decode
    decorated = {"rss_bytes": 123, "busy_frac": 0.5,
                 "from_the_future": {"x": 1}}
    back = resources_from_wire(decorated)
    assert back["rss_bytes"] == 123
    assert back["busy_frac"] == pytest.approx(0.5)
    assert "from_the_future" not in back
    assert back["device_peak_bytes"] is None      # absent -> None
    # a LEGACY worker sends no resources field at all
    assert resources_from_wire(None) is None
    assert resources_from_wire("garbage") is None
    assert resources_to_wire(None) is None
    # a buggy peer put strings on the wire: coerced to None, the
    # router's arithmetic never meets a str
    weird = resources_from_wire({"rss_bytes": "1e9", "busy_frac": []})
    assert weird["rss_bytes"] is None
    assert weird["busy_frac"] is None


# ------------------------------------------------------------------ #
# fleet top
# ------------------------------------------------------------------ #
def test_top_renders_router_stats_per_worker(capsys):
    from multigrad_tpu.telemetry.top import (_rows_from_status,
                                             render_rows)

    stats = {"workers": {
        "w0": {"state": "up", "queue_depth": 3, "heartbeat_age_s": 0.2,
               "resources": {"busy_frac": 0.9, "rss_bytes": 2 ** 30,
                             "device_bytes_in_use": 5 * 2 ** 30,
                             "device_bytes_limit": 16 * 2 ** 30,
                             "device_peak_bytes": 6 * 2 ** 30,
                             "compile_count": 4,
                             "compile_s_total": 12.5}},
        "w1": {"state": "lost", "queue_depth": 0,
               "heartbeat_age_s": 9.0, "resources": None},
    }}
    rows = _rows_from_status("router", stats, now=0.0)
    assert [r["name"] for r in rows] == ["w0", "w1"]
    out = render_rows(rows)
    assert "WORKER" in out and "BUSY%" in out and "COMPILE" in out
    assert "90.0" in out                   # w0 busy percent
    assert "1.0GiB" in out                 # w0 rss
    assert "5.0GiB/16.0GiB" in out         # device in-use / limit
    assert "4 (12.5s)" in out              # compile count (seconds)
    assert "w1 [lost]" in out              # dead worker flagged
    # a worker the router never sampled renders dashes, not zeros
    w1 = out.splitlines()[-1]
    assert "-" in w1


def test_top_once_over_jsonl_stream(tmp_path, capsys):
    from multigrad_tpu.telemetry import JsonlSink
    from multigrad_tpu.telemetry.top import main as top_main

    path = tmp_path / "w0.jsonl"
    logger = MetricsLogger(JsonlSink(str(path)))
    logger.log("resource_sample", rss_bytes=256 * 2 ** 20,
               busy_frac=0.25, device_bytes_in_use=None,
               compile_count=2, compile_s_total=1.0)
    logger.close()
    assert top_main(["--once", "--json", str(path)]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 1
    assert rows[0]["rss_bytes"] == 256 * 2 ** 20
    assert rows[0]["busy_frac"] == pytest.approx(0.25)
    assert rows[0]["compile_count"] == 2
    # table mode over the same stream
    assert top_main(["--once", str(path)]) == 0
    out = capsys.readouterr().out
    assert "256.0MiB" in out and "25.0" in out
    # a dead URL is a "down" row, not a crash
    assert top_main(["--once", "--json",
                     "http://127.0.0.1:9/status", str(path)]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows[0]["state"] == "down"
    assert rows[1]["rss_bytes"] == 256 * 2 ** 20
