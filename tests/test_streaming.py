"""Streaming data subsystem: chunk plan, sources, prefetch, equivalence.

The acceptance contract: a chunked/streamed fit must reproduce the
resident ``OnePointModel``'s loss and gradient to fp32 tolerance on
the SMF workload — including a ragged (non-divisible) catalog length
and ``sumstats_func_has_aux=True`` — for BOTH the two-pass streamed
path and the single-dispatch ``lax.scan`` path, on a 4-device CPU
mesh; and the double-buffered prefetcher must never hold more than
two chunk buffers on device.
"""
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.data import (ArraySource, ChunkPrefetcher,
                                MemmapSource, NpzSource,
                                StreamingOnePointModel, as_source,
                                plan_chunks, prefetch_chunks)
from multigrad_tpu.models.smf import (ParamTuple, SMFModel,
                                      load_halo_masses, make_smf_data)
from multigrad_tpu.utils.profiling import StreamStats

N_RAGGED = 10_001  # 10_001 % 4 == 1 and % 1536 != 0: doubly ragged
CHUNK_ROWS = 1536
PARAMS = jnp.asarray(ParamTuple(log_shmrat=-1.7, sigma_logsm=0.35))


# --------------------------------------------------------------------- #
# Chunk plan
# --------------------------------------------------------------------- #
def test_plan_chunks_even():
    plan = plan_chunks(1024, 256, n_shards=4)
    assert plan.n_chunks == 4
    assert plan.rows_per_chunk == 256
    assert plan.shard_rows == 64
    assert plan.pad_rows == 0
    assert [c.start for c in plan.chunks] == [0, 256, 512, 768]
    assert all(c.pad == 0 for c in plan.chunks)


def test_plan_chunks_ragged_tail():
    plan = plan_chunks(1000, 256, n_shards=4)
    assert plan.n_chunks == 4
    last = plan.chunks[-1]
    assert (last.start, last.stop, last.pad) == (768, 1000, 24)
    assert plan.pad_rows == 24
    # Uniform padded shape: one compiled program serves every chunk.
    assert all(c.rows + c.pad == plan.rows_per_chunk
               for c in plan.chunks)


def test_plan_chunks_rounds_to_shard_multiple():
    # chunk_rows=100 over 8 shards -> 104 rows/chunk (13 per shard).
    plan = plan_chunks(1000, 100, n_shards=8)
    assert plan.rows_per_chunk == 104
    assert plan.shard_rows == 13


def test_plan_chunks_chunk_larger_than_catalog():
    plan = plan_chunks(10, 256, n_shards=4)
    assert plan.n_chunks == 1
    assert plan.chunks[0].pad == 246


def test_plan_chunks_validates():
    with pytest.raises(ValueError, match="n_rows"):
        plan_chunks(0, 16)
    with pytest.raises(ValueError, match="chunk_rows"):
        plan_chunks(16, 0)


# --------------------------------------------------------------------- #
# Sources
# --------------------------------------------------------------------- #
def test_array_source_read_and_pad():
    src = ArraySource(np.arange(10.0))
    assert len(src) == 10
    plan = src.plan(4, n_shards=2)
    np.testing.assert_array_equal(src.read(2, 5), [2.0, 3.0, 4.0])
    last = plan.chunks[-1]
    chunk = src.load_chunk(last, pad_value=np.inf)
    assert chunk.shape == (4,)
    np.testing.assert_array_equal(chunk[:2], [8.0, 9.0])
    assert np.all(np.isinf(chunk[2:]))


def test_npz_source(tmp_path):
    path = str(tmp_path / "catalog.npz")
    arr = np.arange(20.0).reshape(10, 2)
    np.savez(path, halos=arr)
    src = NpzSource(path, "halos")
    assert src.n_rows == 10
    np.testing.assert_array_equal(src.read(3, 6), arr[3:6])
    with pytest.raises(KeyError, match="nope"):
        NpzSource(path, "nope")


def test_memmap_source_npy(tmp_path):
    path = str(tmp_path / "catalog.npy")
    arr = np.linspace(0, 1, 17).astype(np.float32)
    np.save(path, arr)
    src = MemmapSource(path)
    assert src.n_rows == 17
    np.testing.assert_array_equal(src.read(5, 9), arr[5:9])
    # reads are plain host copies, not live mappings
    assert not isinstance(src.read(0, 4), np.memmap)


def test_memmap_source_raw_requires_meta(tmp_path):
    path = str(tmp_path / "catalog.bin")
    arr = np.arange(12.0, dtype=np.float64)
    arr.tofile(path)
    with pytest.raises(ValueError, match="dtype"):
        MemmapSource(path)
    src = MemmapSource(path, dtype=np.float64, shape=(12,))
    np.testing.assert_array_equal(src.read(0, 3), [0.0, 1.0, 2.0])


def test_as_source_coercions(tmp_path):
    src = ArraySource(np.arange(4.0))
    assert as_source(src) is src
    assert isinstance(as_source(np.arange(4.0)), ArraySource)
    path = str(tmp_path / "c.npy")
    np.save(path, np.arange(4.0))
    assert isinstance(as_source(path), MemmapSource)
    with pytest.raises(ValueError, match="NpzSource"):
        as_source(str(tmp_path / "c.npz"))


# --------------------------------------------------------------------- #
# Prefetcher
# --------------------------------------------------------------------- #
def test_prefetcher_yields_all_chunks_in_order():
    chunks = [np.full(8, float(k)) for k in range(5)]
    stats = StreamStats()
    got = []
    for k, dev in ChunkPrefetcher(lambda k: chunks[k], 5, stats=stats):
        got.append((k, float(np.asarray(dev)[0])))
    assert got == [(k, float(k)) for k in range(5)]
    assert stats.chunks == 5
    assert stats.bytes_streamed == 5 * chunks[0].nbytes


def test_prefetcher_holds_at_most_two_buffers():
    # Slow consumer, instant producer: the semaphore must cap live
    # device buffers at two (double buffering) no matter the backlog.
    stats = StreamStats()
    for _k, _dev in ChunkPrefetcher(lambda k: np.zeros(16), 8,
                                    stats=stats):
        time.sleep(0.01)
    assert stats.max_live_buffers <= 2
    assert stats.chunks == 8


def test_prefetcher_propagates_loader_errors():
    def load(k):
        if k == 2:
            raise RuntimeError("disk on fire")
        return np.zeros(4)

    with pytest.raises(RuntimeError, match="disk on fire"):
        for _ in ChunkPrefetcher(load, 5):
            pass


def test_prefetcher_close_unblocks_producer():
    pf = ChunkPrefetcher(lambda k: np.zeros(4), 100)
    it = iter(pf)
    next(it)
    pf.close()  # must not hang on the backlogged loader
    assert not pf._thread.is_alive()


def test_prefetch_chunks_sync_path_matches():
    chunks = [np.full(4, float(k)) for k in range(3)]
    stats = StreamStats()
    got = [float(np.asarray(dev)[0]) for _k, dev in prefetch_chunks(
        lambda k: chunks[k], 3, prefetch=False, stats=stats)]
    assert got == [0.0, 1.0, 2.0]
    assert stats.chunks == 3
    assert stats.max_live_buffers == 1


def test_prefetcher_applies_sharding():
    comm = mgt.MeshComm(jax.devices()[:4])
    sharding = comm.sharding(axis=0, ndim=1)
    for _k, dev in ChunkPrefetcher(lambda k: [np.arange(8.0)], 2,
                                   sharding=[sharding]):
        assert dev[0].sharding == sharding


# --------------------------------------------------------------------- #
# Streaming vs resident equivalence (the acceptance contract)
# --------------------------------------------------------------------- #
def _streaming_smf(comm, n=N_RAGGED, chunk_rows=CHUNK_ROWS,
                   model_cls=SMFModel, prefetch=True):
    log_mh = np.asarray(jnp.log10(load_halo_masses(n)))
    aux = make_smf_data(n, comm=None)
    del aux["log_halo_masses"]
    template = model_cls(aux_data=aux, comm=comm)
    return StreamingOnePointModel(
        model=template, streams={"log_halo_masses": log_mh},
        chunk_rows=chunk_rows, prefetch=prefetch)


@pytest.fixture(scope="module")
def comm4():
    return mgt.MeshComm(jax.devices()[:4])


@pytest.fixture(scope="module")
def resident():
    model = SMFModel(aux_data=make_smf_data(N_RAGGED, comm=None),
                     comm=None)
    loss, grad = model.calc_loss_and_grad_from_params(PARAMS)
    return model, float(loss), np.asarray(grad)


def test_streamed_sumstats_match_resident(comm4, resident):
    model, _, _ = resident
    sm = _streaming_smf(comm4)
    y_res = np.asarray(model.calc_sumstats_from_params(PARAMS))
    y_str = np.asarray(sm.calc_sumstats_from_params(PARAMS))
    np.testing.assert_allclose(y_str, y_res, rtol=1e-5)


def test_two_pass_streamed_loss_and_grad_match_resident(comm4, resident):
    _, loss_r, grad_r = resident
    sm = _streaming_smf(comm4)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), loss_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), grad_r, rtol=1e-5)
    # both passes streamed the full plan; double buffering held
    stats = sm.last_stats
    assert stats.chunks == 2 * sm.plan().n_chunks
    assert stats.bytes_streamed > 0
    assert stats.max_live_buffers <= 2


def test_scan_path_loss_and_grad_match_resident(comm4, resident):
    _, loss_r, grad_r = resident
    sm = _streaming_smf(comm4)
    loss_c, grad_c = sm.calc_loss_and_grad_scan(PARAMS)
    np.testing.assert_allclose(float(loss_c), loss_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_c), grad_r, rtol=1e-5)


def test_streamed_single_device_matches_resident(resident):
    # comm=None: the chunk programs run un-shard_mapped.
    _, loss_r, grad_r = resident
    sm = _streaming_smf(None)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), loss_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), grad_r, rtol=1e-5)


def test_streamed_matches_distributed_resident(comm4):
    # The streamed mesh fit also matches a RESIDENT fit on the same
    # mesh (scatter_nd catalog) — shard count cannot leak into totals.
    res = SMFModel(aux_data=make_smf_data(N_RAGGED, comm=comm4),
                   comm=comm4)
    loss_r, grad_r = res.calc_loss_and_grad_from_params(PARAMS)
    sm = _streaming_smf(comm4)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), float(loss_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), np.asarray(grad_r),
                               rtol=1e-5)


def test_chunk_size_invariance(comm4, resident):
    # Totals and gradients are chunk-size independent (additivity).
    _, loss_r, grad_r = resident
    for chunk_rows in (512, 4096, 2 * N_RAGGED):
        sm = _streaming_smf(comm4, chunk_rows=chunk_rows)
        loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
        np.testing.assert_allclose(float(loss_s), loss_r, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grad_s), grad_r,
                                   rtol=1e-5)


def test_no_prefetch_path_matches(comm4, resident):
    _, loss_r, grad_r = resident
    sm = _streaming_smf(comm4, prefetch=False)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), loss_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), grad_r, rtol=1e-5)


def test_streaming_from_memmap_source(tmp_path, comm4, resident):
    # End-to-end out-of-core: catalog on disk, never fully resident.
    _, loss_r, grad_r = resident
    path = str(tmp_path / "halos.npy")
    np.save(path, np.asarray(jnp.log10(load_halo_masses(N_RAGGED))))
    aux = make_smf_data(N_RAGGED, comm=None)
    del aux["log_halo_masses"]
    sm = StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm4),
        streams={"log_halo_masses": MemmapSource(path)},
        chunk_rows=CHUNK_ROWS)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), loss_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), grad_r, rtol=1e-5)


# --------------------------------------------------------------------- #
# sumstats_func_has_aux=True
# --------------------------------------------------------------------- #
@dataclass
class SMFModelWithAux(SMFModel):
    """SMF variant exercising the additive-aux streaming contract."""

    sumstats_func_has_aux: bool = True

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        y = super().calc_partial_sumstats_from_params(params,
                                                      randkey=randkey)
        # Additive aux: total smoothed count (sums over shards/chunks
        # exactly like the sumstats themselves).
        return y, jnp.sum(y)

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        base = super().calc_loss_from_sumstats(sumstats)
        return base + 0.1 * jnp.log1p(sumstats_aux)


def test_streamed_with_sumstats_aux_matches_resident(comm4):
    res = SMFModelWithAux(aux_data=make_smf_data(N_RAGGED, comm=None),
                          comm=None)
    loss_r, grad_r = res.calc_loss_and_grad_from_params(PARAMS)
    sm = _streaming_smf(comm4, model_cls=SMFModelWithAux)
    y_tot, aux_tot = sm.calc_sumstats_from_params(PARAMS)
    y_res, aux_res = res.calc_sumstats_from_params(PARAMS)
    np.testing.assert_allclose(np.asarray(y_tot), np.asarray(y_res),
                               rtol=1e-5)
    np.testing.assert_allclose(float(aux_tot), float(aux_res),
                               rtol=1e-5)
    loss_s, grad_s = sm.calc_loss_and_grad_from_params(PARAMS)
    np.testing.assert_allclose(float(loss_s), float(loss_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_s), np.asarray(grad_r),
                               rtol=1e-5)
    loss_c, grad_c = sm.calc_loss_and_grad_scan(PARAMS)
    np.testing.assert_allclose(float(loss_c), float(loss_r), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_c), np.asarray(grad_r),
                               rtol=1e-5)


# --------------------------------------------------------------------- #
# Fit loop + validation
# --------------------------------------------------------------------- #
def test_streamed_adam_tracks_resident_fit(comm4):
    n, steps = 4_000, 5
    res = SMFModel(aux_data=make_smf_data(n, comm=None), comm=None)
    traj_r = res.run_adam(guess=(-1.5, 0.4), nsteps=steps,
                          learning_rate=0.05, progress=False)
    log_mh = np.asarray(jnp.log10(load_halo_masses(n)))
    aux = make_smf_data(n, comm=None)
    del aux["log_halo_masses"]
    sm = StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm4),
        streams={"log_halo_masses": log_mh}, chunk_rows=1024)
    for use_scan in (False, True):
        traj_s = sm.run_adam(guess=(-1.5, 0.4), nsteps=steps,
                             learning_rate=0.05, progress=False,
                             use_scan=use_scan)
        assert traj_s.shape == (steps + 1, 2)
        np.testing.assert_allclose(np.asarray(traj_s),
                                   np.asarray(traj_r),
                                   rtol=1e-4, atol=1e-5)


def test_streamed_adam_with_bounds(comm4):
    sm = _streaming_smf(comm4, n=2_000, chunk_rows=1024)
    traj = sm.run_adam(guess=(-1.5, 0.4), nsteps=3, learning_rate=0.05,
                       param_bounds=[(-3.0, 0.0), (0.05, 1.0)],
                       progress=False)
    assert traj.shape == (4, 2)
    assert np.all(np.asarray(traj[:, 0]) > -3.0)
    assert np.all(np.asarray(traj[:, 1]) > 0.05)


def test_streaming_model_validates():
    aux = make_smf_data(100, comm=None)
    template = SMFModel(aux_data=aux, comm=None)
    # resident aux already holds the streamed key -> must refuse
    with pytest.raises(ValueError, match="disjoint"):
        StreamingOnePointModel(
            model=template,
            streams={"log_halo_masses": np.arange(8.0)}, chunk_rows=4)
    del aux["log_halo_masses"]
    with pytest.raises(ValueError, match="at least one"):
        StreamingOnePointModel(model=template, streams={}, chunk_rows=4)
    with pytest.raises(ValueError, match="row-aligned"):
        StreamingOnePointModel(
            model=SMFModel(aux_data=aux, comm=None),
            streams={"a": np.arange(8.0), "b": np.arange(9.0)},
            chunk_rows=4)


def test_replace_aux_rebinds():
    model = SMFModel(aux_data=make_smf_data(1_000, comm=None), comm=None)
    rebound = model.replace_aux(volume=123.0)
    assert rebound.aux_data["volume"] == 123.0
    assert model.aux_data["volume"] != 123.0  # original untouched
    assert rebound is not model
