"""Preemption-resilient distributed fit-fleet (serve/fleet + chaos).

The PR-11 tentpole's acceptance battery, in two tiers:

* **Real-process chaos suite** — a live :class:`FleetRouter` over
  actual ``multigrad_tpu.serve.worker`` subprocesses (own jax
  runtime each, shared on-disk compile cache), driven by the
  :class:`ChaosController`: SIGKILL mid-burst with ≥ 16 in-flight
  requests (every future resolves, requeued work completes on the
  survivor — and every request's merged trace reconstructs a
  complete parent-linked waterfall covering ≥ 90 % of its observed
  latency, the killed requests' with an explicit ``requeue`` hop
  naming both worker generations), SIGTERM graceful drain, forced
  queue-full → work stealing → typed admission reject, and
  heartbeat-loss requeue of a stalled worker.
* **Requeue-semantics unit tests** — the router's migration
  bookkeeping against in-process fake workers: original wall-clock
  deadlines survive a requeue, a consumed poison retry is forwarded
  (never double-fired), cancelled-while-requeued futures stay
  cancelled, and requeues are bounded by the typed
  :class:`WorkerLostError`.

Plus the satellite proofs: the scheduler's dispatcher-death backstop
settles every pending future with the cause chain + postmortem
bundle attached, and ``LiveServer`` probes forward on ``EADDRINUSE``
instead of crashing a fleet worker at startup.
"""
import json
import socket
import time
import urllib.request

import numpy as np
import pytest

from multigrad_tpu.serve import (ChaosController, FitFailed,
                                 FleetRouter, FleetSaturatedError,
                                 FitScheduler, WorkerLostError)
from multigrad_tpu.serve.fleet import FleetRequest, WorkerHandle
from multigrad_tpu.serve.queue import (FitCancelled, FitConfig,
                                       FitDeadlineExceeded,
                                       FitFuture)

# One compile cache for the whole module: the fleet-wide warm asset —
# the first worker of the first test pays XLA, every later worker
# (across routers and tests) reads executables back from disk.
@pytest.fixture(scope="module")
def fleet_cache(tmp_path_factory):
    return str(tmp_path_factory.mktemp("fleet_xla_cache"))


def make_router(tmp_path, fleet_cache, n_workers=2, **kw):
    kw.setdefault("model_kwargs", {"num_halos": 300})
    kw.setdefault("devices", 1)
    kw.setdefault("buckets", (1, 4, 16))
    kw.setdefault("batch_window_s", 0.02)
    kw.setdefault("heartbeat_s", 0.1)
    kw.setdefault("heartbeat_timeout_s", 1.5)
    kw.setdefault("chaos", True)
    return FleetRouter(n_workers=n_workers, base_dir=str(tmp_path),
                       compile_cache=fleet_cache, **kw)


def affinity_home(router, config, ndim=2):
    """The worker a config's traffic lands on (deterministic —
    rendezvous hashing over the batchability key)."""
    req = FleetRequest(id="probe", guess=np.zeros(ndim),
                       config=config, future=FitFuture("probe"))
    return router._affinity_order(req.key)[0]


# Guesses inside the SMF loss's well-behaved region (bench/demo
# convention): fits from here converge, so "every future resolves
# with a RESULT" is assertable without divergence noise.
def safe_guesses(n, lo=-2.2, hi=-1.5):
    return [np.array([lo + (hi - lo) * i / max(n - 1, 1),
                      0.4 + 0.02 * (i % 5)]) for i in range(n)]


# ------------------------------------------------------------------ #
# real-process fleet: routing, affinity, /fleet plane
# ------------------------------------------------------------------ #
def test_fleet_serves_with_config_affinity(tmp_path, fleet_cache):
    from multigrad_tpu.telemetry import LiveServer
    live = LiveServer(port=0)
    try:
        with make_router(tmp_path, fleet_cache, live=live,
                         worker_live_port=0) as router:
            configs = [FitConfig(nsteps=8, learning_rate=0.03,
                                 randkey=k) for k in (1, 2, 3)]
            futs = {k: [router.submit(g, config=cfg)
                        for g in safe_guesses(4)]
                    for k, cfg in zip((1, 2, 3), configs)}
            results = {k: [f.result(timeout=240) for f in fs]
                       for k, fs in futs.items()}

            for k, cfg in zip((1, 2, 3), configs):
                # Config affinity: every request of one config landed
                # on its (deterministic) home worker.
                home = affinity_home(router, cfg).id
                assert {r.worker for r in results[k]} == {home}
                assert all(np.isfinite(r.loss) for r in results[k])
            stats = router.stats
            assert stats["submitted"] == 12
            assert stats["completed"] == 12
            assert stats["workers_alive"] == 2
            assert stats["fits_per_hour"] > 0

            # Fleet gauges landed in the live registry...
            snap = live.metrics.snapshot()
            for gauge in ("multigrad_fleet_workers_alive",
                          "multigrad_fleet_inflight",
                          "multigrad_fleet_worker_up",
                          "multigrad_fleet_fits_per_hour"):
                assert gauge in snap, f"missing {gauge}"
            # ...and the /fleet endpoint aggregates the per-worker
            # telemetry streams (distinct ranks: each worker stamps
            # its fleet rank, not its jax process_index of 0).
            with urllib.request.urlopen(live.url + "/fleet",
                                        timeout=10) as resp:
                fleet = json.loads(resp.read())
            assert set(map(int, fleet["ranks"])) == {0, 1}
            assert fleet["n_records"] > 0

            # Every worker's heartbeats carried resource snapshots
            # into the router's live fleet-utilization view (PR 18):
            # per-worker numbers in stats, fleet aggregates, and the
            # per-worker labelled busy gauge.
            stats = router.stats
            for wid, w in stats["workers"].items():
                res = w["resources"]
                assert res is not None, f"{wid} never sampled"
                assert res["rss_bytes"] > 0
                assert res["busy_s_total"] >= 0
                assert w["live_port"] > 0
            assert stats["fleet_rss_bytes"] > 0
            fleet_busy = stats["fleet_busy_frac"]
            assert fleet_busy is None or 0.0 <= fleet_busy <= 1.0
            snap = live.metrics.snapshot()
            assert "multigrad_fleet_worker_busy_frac" in snap

            # The fleet-top acceptance: ``top --once`` over the live
            # workers' /status endpoints renders one column row per
            # worker with real utilization numbers.
            from multigrad_tpu.telemetry.top import (collect_rows,
                                                     render_rows)
            urls = [f"http://127.0.0.1:{w['live_port']}/status"
                    for w in stats["workers"].values()]
            rows = collect_rows(urls, {}, {})
            assert len(rows) == 2
            for row in rows:
                assert row["state"] != "down"
                assert row["rss_bytes"] > 0
            top_out = render_rows(rows)
            assert top_out.splitlines()[0].startswith("WORKER")
            assert len(top_out.splitlines()) == 4  # header+rule+2
            assert "MiB" in top_out or "GiB" in top_out
    finally:
        live.stop()


# ------------------------------------------------------------------ #
# heartbeat resources: wire forward-compat in both directions
# ------------------------------------------------------------------ #
def test_heartbeat_resources_wire_forward_compat():
    from multigrad_tpu.serve.wire import (resources_from_wire,
                                          resources_to_wire)

    # Decorated heartbeat (a FUTURE worker) at this router: unknown
    # keys are dropped, known keys decode, nothing raises.
    future_msg = {"type": "heartbeat", "inflight": 1,
                  "resources": {"rss_bytes": 10 ** 9,
                                "busy_frac": 0.25,
                                "gpu_temp_c": 61,       # future field
                                "numa_domains": [0, 1]}}
    res = resources_from_wire(future_msg.get("resources"))
    assert res["rss_bytes"] == 10 ** 9
    assert res["busy_frac"] == pytest.approx(0.25)
    assert "gpu_temp_c" not in res and "numa_domains" not in res

    # Legacy heartbeat (a PRE-resources worker) at this router: no
    # resources key at all -> None, the fleet view stays unpopulated
    # (never zeroed).
    legacy_msg = {"type": "heartbeat", "inflight": 0}
    assert resources_from_wire(legacy_msg.get("resources")) is None

    # This worker's snapshot at a LEGACY router: the encoded field is
    # a plain known-keys dict a reader that predates it can ignore
    # wholesale, and an UNMONITORED worker keeps the key off the
    # message entirely (byte-identical to the old protocol).
    wire = resources_to_wire({"rss_bytes": 5, "busy_frac": 0.5,
                              "t": 1.0})
    assert json.loads(json.dumps(wire)) == wire
    assert resources_to_wire(None) is None
    msg = {"type": "heartbeat", "inflight": 0}
    snap = resources_to_wire(None)
    if snap is not None:
        msg["resources"] = snap
    assert "resources" not in msg


# ------------------------------------------------------------------ #
# the acceptance chaos run: SIGKILL mid-burst, nothing lost
# ------------------------------------------------------------------ #
def test_fleet_sigkill_mid_burst_loses_no_request(tmp_path,
                                                  fleet_cache):
    from multigrad_tpu.telemetry import LiveServer
    from multigrad_tpu.telemetry.aggregate import merge_traces
    from multigrad_tpu.telemetry.trace import trace_summary
    live = LiveServer(port=0)
    try:
        with make_router(tmp_path, fleet_cache, live=live) as router:
            chaos = ChaosController(router)
            cfg = FitConfig(nsteps=300, learning_rate=0.03,
                            randkey=7)
            victim = affinity_home(router, cfg)
            survivor = next(w for w in router.workers
                            if w.id != victim.id)
            futs = [router.submit(g, config=cfg)
                    for g in safe_guesses(20)]
            seen = {}

            def _kill():
                seen["inflight"] = len(victim.inflight)
                chaos.kill(victim.id)

            fired = chaos.when_inflight(16, _kill, worker=victim.id)
            assert fired.wait(60), "kill injection never fired"
            assert seen["inflight"] >= 16

            # THE invariant: every future resolves — result or typed
            # error, none lost, none hung.
            results = [f.result(timeout=300) for f in futs]
            assert all(np.isfinite(r.loss) for r in results)

            # The victim's in-flight requests were re-enqueued and
            # completed on the surviving worker, history on the
            # future.
            requeued = [f for f in futs if f.requeues]
            assert len(requeued) >= 16
            for f in requeued:
                assert f._result.worker == survivor.id
                entry = f.requeues[0]
                assert entry["worker"] == victim.id
                assert "lost" in entry["reason"]
            stats = router.stats
            assert stats["worker_deaths"] == 1
            assert stats["completed"] == 20
            assert stats.get("lost") is None    # typed-error count: 0
            assert stats["workers"][victim.id]["state"] == "dead"
            # The worker_lost postmortem bundle names the stranded
            # ids AND their trace ids (bundle -> trace navigation).
            bundle = requeued[0].requeues[0]["bundle"]
            with open(bundle) as f:
                detail = json.load(f)["detail"]
            assert detail["worker"] == victim.id
            assert set(detail["inflight"]) >= {f.request_id
                                               for f in requeued}
            assert set(detail["trace_ids"]) >= {f.trace_id
                                                for f in requeued}

            # /status carries the fit-latency quantiles with an
            # exemplar trace id — a tail-latency alarm links
            # straight to an offending waterfall.
            with urllib.request.urlopen(live.url + "/status",
                                        timeout=10) as resp:
                latency = json.loads(resp.read())["latency"]
            assert latency["source"] \
                == "multigrad_fleet_fit_latency_seconds"
            assert latency["count"] == 20
            assert 0 < latency["p50_s"] <= latency["p95_s"] \
                <= latency["p99_s"] <= latency["max_s"]
            all_traces = {f.trace_id for f in futs}
            assert latency["exemplar_trace"] in all_traces
            assert latency["hops"]["requeue"]["exemplar_trace"] \
                in {f.trace_id for f in requeued}
            # The RPC RTT gauge (link-latency noise floor) is live,
            # labeled per worker.
            rtt = live.metrics.snapshot()["multigrad_fleet_rpc_rtt"]
            assert f'{{worker="{survivor.id}"}}' in rtt["samples"]

            trace_paths = router.trace_paths
            e2e = {f.trace_id: f._result.wait_s + f._result.fit_s
                   for f in futs}
            chaos.close()

        # Router closed: every surviving process flushed its trace
        # file; the victim's spans survived the SIGKILL because the
        # sink appends line-atomically.  The merged JSONLs alone
        # must reconstruct every request's journey.
        assert len(trace_paths) == 3        # router + 2 workers
        by_trace = merge_traces(trace_paths)
        assert set(by_trace) >= all_traces
        killed = {f.trace_id for f in requeued}
        for f in futs:
            summary = trace_summary(f.trace_id,
                                    by_trace[f.trace_id])
            # Complete parent-linked waterfall: one root, every
            # parent id resolves, no orphan spans...
            assert summary["complete"] is True, summary
            assert summary["outcome"] == "ok"
            # ...whose spans account for >= 90% of the observed
            # end-to-end latency (interval union over the root
            # request window).
            assert summary["coverage"] >= 0.9, summary
            if f.trace_id in killed:
                # The migration is an explicit hop naming both
                # worker generations and the worker_lost bundle.
                assert summary["requeues"], summary
                hop = summary["requeues"][0]
                assert hop["from"] == victim.id
                assert hop["to"] == survivor.id
                assert hop["bundle"] is not None
                assert set(summary["services"]) \
                    >= {"router", f"worker:{survivor.id}"}
        # Root elapsed agrees with the future's own bookkeeping.
        for f in futs:
            summary = trace_summary(f.trace_id,
                                    by_trace[f.trace_id])
            assert summary["elapsed_s"] \
                == pytest.approx(e2e[f.trace_id], rel=0.5, abs=2.0)

        # The stdlib CLI renders the whole story from files alone:
        # the killed requests' waterfalls carry the requeue hop line.
        from multigrad_tpu.telemetry.trace import main as trace_main
        import contextlib
        import io
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            assert trace_main(trace_paths
                              + ["--slowest", "20"]) == 0
        text = out.getvalue()
        assert f"{len(by_trace)} traces over 3 file(s)" in text
        assert "0 incomplete" in text
        assert f"requeue {victim.id}->{survivor.id}" in text
    finally:
        live.stop()


# ------------------------------------------------------------------ #
# graceful preemption: SIGTERM drains, traffic routes around
# ------------------------------------------------------------------ #
def test_fleet_sigterm_drains_gracefully(tmp_path, fleet_cache):
    with make_router(tmp_path, fleet_cache) as router:
        chaos = ChaosController(router)
        cfg = FitConfig(nsteps=60, learning_rate=0.03, randkey=5)
        victim = affinity_home(router, cfg)
        # Prove the victim's serve loop is live first (on a loaded
        # host a SIGTERM can otherwise land before the worker ever
        # accepts — also survivable, but then nothing drains).
        probe = router.submit(np.array([-1.9, 0.5]), config=cfg)
        assert probe.result(timeout=240).worker == victim.id

        futs = [router.submit(g, config=cfg)
                for g in safe_guesses(8)]
        chaos.preempt(victim.id)
        results = [f.result(timeout=240) for f in futs]
        # Graceful preemption loses nothing: queued work is served
        # (by the draining victim) or rejected-and-rerouted to the
        # survivor — and either way every future resolves.
        assert all(np.isfinite(r.loss) for r in results)

        deadline = time.time() + 30
        while victim.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        assert victim.proc.poll() == 0      # drained exit, not a kill
        # New traffic routes around the drained worker.
        post = router.submit(np.array([-1.8, 0.5]), config=cfg)
        assert post.result(timeout=240).worker != victim.id
        chaos.close()


# ------------------------------------------------------------------ #
# saturation: forced queue-full → steal → typed admission reject
# ------------------------------------------------------------------ #
def test_fleet_queue_full_steals_then_sheds(tmp_path, fleet_cache):
    with make_router(tmp_path, fleet_cache) as router:
        chaos = ChaosController(router)
        cfg = FitConfig(nsteps=8, learning_rate=0.03, randkey=11)
        home = affinity_home(router, cfg)
        other = next(w for w in router.workers if w.id != home.id)

        # One forced reject: the request is stolen by the other
        # worker instead of failing.
        chaos.inject_queue_full(home.id, n=1)
        stolen = router.submit(np.array([-1.9, 0.5]), config=cfg)
        assert stolen.result(timeout=240).worker == other.id
        assert router.stats["rejected"] >= 1

        # Every live worker rejecting → typed admission error.
        chaos.inject_queue_full(home.id, n=1)
        chaos.inject_queue_full(other.id, n=1)
        shed = router.submit(np.array([-1.9, 0.5]), config=cfg)
        with pytest.raises(FleetSaturatedError):
            shed.result(timeout=240)
        assert router.stats["shed"] == 1

        # The injections are consumed; the fleet serves again.
        again = router.submit(np.array([-1.9, 0.5]), config=cfg)
        assert np.isfinite(again.result(timeout=240).loss)
        chaos.close()


# ------------------------------------------------------------------ #
# stalled worker: heartbeat loss → requeue on the survivor
# ------------------------------------------------------------------ #
@pytest.mark.slow   # ~20 s: waits out a real heartbeat timeout
def test_fleet_stalled_worker_requeues(tmp_path, fleet_cache):
    with make_router(tmp_path, fleet_cache,
                     heartbeat_timeout_s=1.0) as router:
        chaos = ChaosController(router)
        cfg = FitConfig(nsteps=200, learning_rate=0.03, randkey=3)
        victim = affinity_home(router, cfg)
        probe = router.submit(np.array([-1.9, 0.5]), config=cfg)
        assert probe.result(timeout=240).worker == victim.id

        # Freeze the whole process: heartbeats stop mid-burst.
        futs = [router.submit(g, config=cfg)
                for g in safe_guesses(6)]
        chaos.suspend(victim.id)
        results = [f.result(timeout=240) for f in futs]
        assert all(np.isfinite(r.loss) for r in results)
        assert any(f.requeues for f in futs)
        assert router.stats["worker_deaths"] == 1
        # The router writes off AND reaps the frozen worker (SIGKILL
        # lands even on a stopped process), so a thaw can never
        # produce split-brain duplicates.
        deadline = time.time() + 10
        while victim.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        assert victim.proc.poll() is not None
        chaos.close()


# ------------------------------------------------------------------ #
# requeue semantics (unit level: fake workers, no subprocesses)
# ------------------------------------------------------------------ #
class FakeChan:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass

    def submits(self):
        return [m for m in self.sent if m["op"] == "submit"]


@pytest.fixture()
def fake_fleet(tmp_path):
    router = FleetRouter(n_workers=0, base_dir=str(tmp_path),
                         compile_cache=None,
                         heartbeat_timeout_s=1e6, max_requeues=2)
    a = WorkerHandle("w0", chan=FakeChan())
    b = WorkerHandle("w1", chan=FakeChan())
    router.workers += [a, b]
    yield router, a, b
    router.close(drain=False, timeout=0)


def _home_and_other(router, a, b, fut_id):
    if any(m["rid"] == fut_id for m in a.chan.submits()):
        return a, b
    return b, a


def test_requeue_respects_original_deadline(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5, deadline_s=0.03)
    home, other = _home_and_other(router, a, b, fut.request_id)
    msg = home.chan.submits()[0]
    # The wire carries the ABSOLUTE deadline: a worker admits
    # against the original wall clock, not a per-hop budget.
    assert msg["deadline_t"] is not None
    time.sleep(0.06)
    router._worker_lost(home, "test kill")
    with pytest.raises(FitDeadlineExceeded):
        fut.result(timeout=5)
    # Never resubmitted: the deadline predates the requeue.
    assert not any(m["rid"] == fut.request_id
                   for m in other.chan.submits())
    assert len(fut.requeues) == 1


def test_requeue_cancelled_future_stays_cancelled(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    home, other = _home_and_other(router, a, b, fut.request_id)
    # The cancel window a real fleet hits between worker death and
    # resubmission: the future is back to pending...
    fut._requeued()
    assert fut.cancel() is True
    router._worker_lost(home, "test kill")
    with pytest.raises(FitCancelled):
        fut.result(timeout=5)
    assert fut.cancelled()
    assert not any(m["rid"] == fut.request_id
                   for m in other.chan.submits())


def test_requeue_forwards_consumed_poison_retry(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    home, other = _home_and_other(router, a, b, fut.request_id)
    assert home.chan.submits()[0]["retried"] is False
    # The worker reported the poison retry firing, then died: the
    # resubmission must carry retried=True — the fresh worker's
    # scheduler gets no second retry to fire.
    router._on_poison_retry(home, {"rid": fut.request_id})
    router._worker_lost(home, "test kill")
    resubmit = [m for m in other.chan.submits()
                if m["rid"] == fut.request_id]
    assert len(resubmit) == 1
    assert resubmit[0]["retried"] is True


def test_scheduler_submit_retried_skips_second_retry():
    # The worker-side half of the no-double-fire contract: a request
    # admitted with retried=True (its retry was consumed on a dead
    # worker) poisons ONCE and fails — no second retry dispatch.
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)
    with FitScheduler(model, buckets=(1,), start=False,
                      batch_window_s=0.0,
                      retry_poisoned=True) as sched:
        fut = sched.submit(np.array([np.nan, 0.5]), nsteps=5,
                           retried=True)
        sched.start()
        exc = fut.exception(timeout=120)
    assert isinstance(exc, FitFailed)
    assert sched.stats.get("retried", 0) == 0


def test_requeues_bounded_by_typed_worker_lost_error(fake_fleet):
    router, a, b = fake_fleet
    router.max_requeues = 1
    fut = router.submit([-1.9, 0.5], nsteps=5)
    home, other = _home_and_other(router, a, b, fut.request_id)
    router._worker_lost(home, "first kill")
    assert any(m["rid"] == fut.request_id
               for m in other.chan.submits())
    router._worker_lost(other, "second kill")
    exc = fut.exception(timeout=5)
    assert isinstance(exc, WorkerLostError)
    assert exc.request_id == fut.request_id
    assert len(exc.requeues) == 2
    assert exc.requeues == fut.requeues


def test_reject_reroutes_then_typed_saturation_error(fake_fleet):
    router, a, b = fake_fleet
    fut = router.submit([-1.9, 0.5], nsteps=5)
    home, other = _home_and_other(router, a, b, fut.request_id)
    router._on_reject(home, {"rid": fut.request_id,
                             "reason": "queue_full"})
    assert any(m["rid"] == fut.request_id
               for m in other.chan.submits())
    router._on_reject(other, {"rid": fut.request_id,
                              "reason": "queue_full"})
    with pytest.raises(FleetSaturatedError):
        fut.result(timeout=5)


# ------------------------------------------------------------------ #
# satellite: dispatcher-death backstop (cause chain + bundle)
# ------------------------------------------------------------------ #
def test_dispatcher_death_settles_all_futures_with_cause(tmp_path):
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    model = SMFModel(aux_data=make_smf_data(300, comm=None),
                     comm=None)

    class DispatcherDied(BaseException):
        # BaseException: escapes the per-group Exception handler,
        # killing the dispatcher thread itself — the failure mode
        # the backstop exists for.
        pass

    sched = FitScheduler(model, buckets=(4,), start=False,
                         batch_window_s=0.0,
                         flight_dir=str(tmp_path))
    futs = [sched.submit([-1.9 - 0.01 * i, 0.5], nsteps=5)
            for i in range(4)]

    def die(group):
        raise DispatcherDied("chaos: dispatcher thread killed")

    sched._dispatch = die
    sched.start()
    for fut in futs:
        exc = fut.exception(timeout=60)
        # No future hangs, and each carries the whole story: typed
        # error, originating exception as the cause, bundle on disk.
        assert isinstance(exc, FitFailed)
        assert isinstance(exc.__cause__, DispatcherDied)
        assert exc.bundle_path is not None
        with open(exc.bundle_path) as f:
            assert json.load(f)["reason"] == "dispatcher_died"
    # The dead dispatcher refuses new work instead of queueing it
    # into the void.
    with pytest.raises(RuntimeError):
        sched.submit([-1.9, 0.5], nsteps=5)


# ------------------------------------------------------------------ #
# satellite: LiveServer EADDRINUSE bind retry
# ------------------------------------------------------------------ #
def test_live_server_bind_retry_probes_forward():
    from multigrad_tpu.telemetry import LiveServer
    # Occupy a port, then ask two LiveServers for it: both must come
    # up on probed-forward ports (the fleet-workers-share-a-host
    # case), reporting the bound port in /status.
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    base = blocker.getsockname()[1]
    s1 = s2 = None
    try:
        s1 = LiveServer(port=base)
        assert s1.port != base and base < s1.port <= base + 16
        s2 = LiveServer(port=base)
        assert s2.port not in (base, s1.port)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{s2.port}/status",
                timeout=10) as resp:
            status = json.loads(resp.read())
        assert status["port"] == s2.port
    finally:
        for s in (s1, s2):
            if s is not None:
                s.stop()
        blocker.close()


def test_live_server_exhausted_probe_range_raises():
    from multigrad_tpu.telemetry import LiveServer
    blockers = []
    base_sock = socket.socket()
    base_sock.bind(("127.0.0.1", 0))
    base = base_sock.getsockname()[1]
    blockers.append(base_sock)
    try:
        for off in range(1, 3):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + off))
                blockers.append(s)
            except OSError:
                s.close()
                pytest.skip("neighboring port externally taken")
        with pytest.raises(OSError):
            LiveServer(port=base, port_probe=3)
    finally:
        for s in blockers:
            s.close()
