"""Ring-sharded pair counting + wp(rp) model invariants.

The clustering workload has no reference implementation to port
(``BASELINE.json`` configs 3/5 name it; the reference ships only halo
bookkeeping in ``diffdesi_experimental``), so the invariants here are
first-principles: brute-force pair counts, shard-count invariance of
the ring (1 vs 8 devices), gradient flow through ``lax.ppermute``
checked against finite differences, and fit recovery of truth.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.wprp import (TRUTH, WprpModel, WprpParams,
                                       make_galaxy_mock, make_wprp_data,
                                       selection_weights)
from multigrad_tpu.ops.pairwise import (analytic_rr_counts,
                                        ring_weighted_pair_counts,
                                        wp_from_counts, xi_from_counts)

N_HALOS = 512
BOX = 60.0


def _brute_force_counts(pos, w, edges, box=None, pimax=None):
    """O(N²) numpy reference: ordered weighted pair counts."""
    pos, w, edges = map(np.asarray, (pos, w, edges))
    diff = pos[:, None, :] - pos[None, :, :]
    if box is not None:
        diff = diff - box * np.round(diff / box)
    if pimax is None:
        sep = np.sqrt((diff ** 2).sum(-1))
        ok = np.ones(sep.shape, dtype=bool)
    else:
        sep = np.sqrt(diff[..., 0] ** 2 + diff[..., 1] ** 2)
        ok = np.abs(diff[..., 2]) < pimax
    ok &= ~np.eye(len(pos), dtype=bool)  # exclude self-pairs
    wprod = np.outer(w, w)
    counts = np.zeros(len(edges) - 1)
    for b in range(len(edges) - 1):
        mask = ok & (sep >= edges[b]) & (sep < edges[b + 1])
        counts[b] = (wprod * mask).sum()
    return counts


@pytest.fixture(scope="module")
def mock():
    pos, logm = make_galaxy_mock(N_HALOS, BOX, seed=1)
    w = selection_weights(logm, TRUTH)
    return pos, logm, w


def test_local_counts_match_brute_force_3d(mock):
    pos, _, w = mock
    edges = jnp.array([0.5, 2.0, 5.0, 10.0])
    got = ring_weighted_pair_counts(pos, w, edges, box_size=BOX)
    want = _brute_force_counts(pos, w, edges, box=BOX)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_local_counts_match_brute_force_projected(mock):
    pos, _, w = mock
    edges = jnp.array([0.3, 1.0, 3.0, 8.0])
    got = ring_weighted_pair_counts(pos, w, edges, box_size=BOX,
                                    pimax=15.0)
    want = _brute_force_counts(pos, w, edges, box=BOX, pimax=15.0)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_self_pair_exclusion_zero_edge(mock):
    pos, _, w = mock
    edges = jnp.array([0.0, 1.0])
    incl = ring_weighted_pair_counts(pos, w, edges, box_size=BOX,
                                     exclude_self=False)
    excl = ring_weighted_pair_counts(pos, w, edges, box_size=BOX,
                                     exclude_self=True)
    np.testing.assert_allclose(np.asarray(incl - excl),
                               np.sum(np.asarray(w) ** 2), rtol=1e-6)


def test_row_chunking_matches_unchunked(mock):
    pos, _, w = mock
    edges = jnp.array([0.5, 2.0, 5.0, 10.0])
    full = ring_weighted_pair_counts(pos, w, edges, box_size=BOX)
    chunked = ring_weighted_pair_counts(pos, w, edges, box_size=BOX,
                                        row_chunk=128)
    np.testing.assert_allclose(chunked, full, rtol=1e-6)


def test_xi_of_uniform_randoms_is_zero():
    # Natural estimator sanity: uniform randoms give ξ ≈ 0 on scales
    # with many pairs (shot-noise-limited tolerance).
    key = jax.random.PRNGKey(3)
    pos = jax.random.uniform(key, (2048, 3)) * BOX
    w = jnp.ones(2048)
    edges = jnp.array([5.0, 10.0, 15.0])
    dd = ring_weighted_pair_counts(pos, w, edges, box_size=BOX)
    xi = xi_from_counts(dd, jnp.sum(w), edges, BOX ** 3)
    assert np.all(np.abs(np.asarray(xi)) < 0.1)


def test_analytic_rr_matches_shell_volume():
    rr = analytic_rr_counts(10.0, jnp.array([0.0, 1.0]), 1000.0)
    np.testing.assert_allclose(np.asarray(rr),
                               100.0 * 4 * np.pi / 3 / 1000.0, rtol=1e-6)


# --------------------------------------------------------------------- #
# Sharded model invariants
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def single_model():
    data = make_wprp_data(N_HALOS, BOX, comm=None, seed=2)
    return WprpModel(aux_data=data, comm=None)


@pytest.fixture(scope="module")
def mesh_model():
    comm = mgt.global_comm()
    data = make_wprp_data(N_HALOS, BOX, comm=comm, seed=2)
    return WprpModel(aux_data=data, comm=comm)


def test_ring_matches_single_device(single_model, mesh_model):
    # Shard-count invariance: the 8-device ppermute ring reproduces
    # the single-block all-pairs totals (the N-invariance property
    # SURVEY §4 calls out for additive sumstats).
    params = WprpParams(-1.9, -0.9)
    y1 = single_model.calc_sumstats_from_params(params)
    y8 = mesh_model.calc_sumstats_from_params(params)
    np.testing.assert_allclose(np.asarray(y8), np.asarray(y1),
                               rtol=2e-4)


def test_loss_zero_and_grad_vanishes_at_truth(mesh_model):
    loss = mesh_model.calc_loss_from_params(TRUTH)
    assert float(loss) < 1e-6
    grad = mesh_model.calc_dloss_dparams(TRUTH)
    np.testing.assert_allclose(np.asarray(grad), 0.0, atol=1e-4)


def test_fused_path_matches_separate(mesh_model):
    params = WprpParams(-2.05, -1.1)
    loss, grad = mesh_model.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(
        float(loss), float(mesh_model.calc_loss_from_params(params)),
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(mesh_model.calc_dloss_dparams(params)),
        rtol=1e-5, atol=1e-8)


def test_ring_gradient_matches_finite_differences(mesh_model):
    # The VJP runs backward through the ppermute ring; check it
    # against central finite differences of the sharded loss.
    params = np.array([-1.95, -0.95])
    grad = np.asarray(mesh_model.calc_dloss_dparams(params))
    eps = 1e-3
    for i in range(2):
        dp = np.zeros(2)
        dp[i] = eps
        f_hi = float(mesh_model.calc_loss_from_params(params + dp))
        f_lo = float(mesh_model.calc_loss_from_params(params - dp))
        fd = (f_hi - f_lo) / (2 * eps)
        np.testing.assert_allclose(grad[i], fd, rtol=2e-2, atol=1e-5)


def test_adam_recovers_truth(mesh_model):
    traj = mesh_model.run_adam(guess=WprpParams(-1.8, -0.8), nsteps=150,
                               learning_rate=0.02, progress=False)
    final = np.asarray(traj[-1])
    np.testing.assert_allclose(final, np.asarray(TRUTH), atol=0.05)
    assert float(mesh_model.calc_loss_from_params(tuple(final))) < 1e-3


def test_ragged_padding_is_neutral():
    # 510 halos over 8 devices: pads 2 rows with weight-0 mass;
    # totals AND gradients must match the unpadded single-device
    # model (a -inf mass pad would be forward-neutral but poison the
    # gradient with 0 * inf = NaN — regression check).
    n = 510  # not divisible by 8
    comm = mgt.global_comm()
    single = WprpModel(aux_data=make_wprp_data(n, BOX, seed=4), comm=None)
    sharded = WprpModel(aux_data=make_wprp_data(n, BOX, comm=comm, seed=4),
                        comm=comm)
    params = WprpParams(-2.0, -1.0)
    np.testing.assert_allclose(
        np.asarray(sharded.calc_sumstats_from_params(params)),
        np.asarray(single.calc_sumstats_from_params(params)), rtol=2e-4)
    g_sharded = np.asarray(sharded.calc_dloss_dparams(params))
    assert np.all(np.isfinite(g_sharded)), g_sharded
    np.testing.assert_allclose(g_sharded,
                               np.asarray(single.calc_dloss_dparams(params)),
                               rtol=1e-3, atol=1e-6)


def test_xi_model_shard_invariance():
    # XiModel (3D 2pt likelihood, BASELINE config 3): mesh totals and
    # gradients match the single-block path; loss ~ 0 at truth.
    from multigrad_tpu.models.wprp import XiModel, make_xi_data
    comm = mgt.global_comm()
    single = XiModel(aux_data=make_xi_data(512, BOX, seed=6), comm=None)
    sharded = XiModel(aux_data=make_xi_data(512, BOX, comm=comm, seed=6),
                      comm=comm)
    params = WprpParams(-1.9, -0.9)
    np.testing.assert_allclose(
        np.asarray(sharded.calc_sumstats_from_params(params)),
        np.asarray(single.calc_sumstats_from_params(params)), rtol=2e-4)
    np.testing.assert_allclose(
        np.asarray(sharded.calc_dloss_dparams(params)),
        np.asarray(single.calc_dloss_dparams(params)),
        rtol=1e-3, atol=1e-6)
    assert float(sharded.calc_loss_from_params(TRUTH)) < 1e-8
