"""Galaxy–halo model family invariants.

Workload of ``BASELINE.json`` config 4 (diffmah-style differentiable
galaxy–halo model at scale); same invariant pattern as the SMF
pipeline tests (reference ``test_mpi.py:38-66``): truth is a fixed
point, fused path equals separate paths, mesh totals are
shard-count-invariant, and the optimizer recovers truth.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu.models.galhalo import (GalhaloModel, GalhaloParams,
                                          TRUTH, make_galhalo_data,
                                          mean_logsm)

N_HALOS = 20_000


@pytest.fixture(scope="module")
def single_model():
    return GalhaloModel(aux_data=make_galhalo_data(N_HALOS), comm=None)


@pytest.fixture(scope="module")
def mesh_model():
    comm = mgt.global_comm()
    return GalhaloModel(aux_data=make_galhalo_data(N_HALOS, comm=comm),
                        comm=comm)


def test_shmr_limiting_slopes():
    # Far below/above the break the local slope approaches alpha_lo /
    # alpha_hi: check via finite differences of the closed form.
    p = TRUTH
    lo = (mean_logsm(9.01, p) - mean_logsm(9.0, p)) / 0.01
    hi = (mean_logsm(15.99, p) - mean_logsm(15.98, p)) / 0.01
    np.testing.assert_allclose(lo, p.alpha_lo, rtol=1e-2)
    np.testing.assert_allclose(hi, p.alpha_hi, rtol=1e-2)
    # continuity anchor: logsm at the critical mass is logsm_crit
    np.testing.assert_allclose(mean_logsm(p.logmh_crit, p),
                               p.logsm_crit, rtol=1e-6)


def test_mesh_matches_single_device(single_model, mesh_model):
    params = GalhaloParams(10.4, 12.6, 1.8, 0.6, 0.25)
    y1 = single_model.calc_sumstats_from_params(params)
    y8 = mesh_model.calc_sumstats_from_params(params)
    np.testing.assert_allclose(np.asarray(y8), np.asarray(y1), rtol=2e-4)


def test_truth_is_fixed_point(mesh_model):
    assert float(mesh_model.calc_loss_from_params(TRUTH)) < 1e-10
    grad = np.asarray(mesh_model.calc_dloss_dparams(TRUTH))
    np.testing.assert_allclose(grad, 0.0, atol=1e-5)


def test_fused_path_matches_separate(mesh_model):
    params = GalhaloParams(10.6, 12.4, 2.1, 0.4, 0.18)
    loss, grad = mesh_model.calc_loss_and_grad_from_params(params)
    np.testing.assert_allclose(
        float(loss), float(mesh_model.calc_loss_from_params(params)),
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grad),
        np.asarray(mesh_model.calc_dloss_dparams(params)),
        rtol=1e-5, atol=1e-8)


def test_bfgs_recovers_truth(mesh_model):
    guess = GalhaloParams(10.3, 12.7, 1.7, 0.7, 0.3)
    res = mesh_model.run_bfgs(guess=guess, maxsteps=200, progress=False)
    # float32 noise floor for a 5-param fit: fun bottoms out ~1e-7
    assert res.success, res
    assert res.fun < 1e-5, res
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(TRUTH),
                               atol=0.1)


def test_chunked_matches_unchunked(single_model):
    params = GalhaloParams(10.5, 12.5, 2.0, 0.5, 0.2)
    data_chunked = make_galhalo_data(N_HALOS, chunk_size=4000)
    chunked = GalhaloModel(aux_data=data_chunked, comm=None)
    np.testing.assert_allclose(
        np.asarray(chunked.calc_sumstats_from_params(params)),
        np.asarray(single_model.calc_sumstats_from_params(params)),
        rtol=1e-5)


def test_ragged_padding_neutral_forward_and_grad():
    n = 20_002  # not divisible by 8
    comm = mgt.global_comm()
    single = GalhaloModel(aux_data=make_galhalo_data(n), comm=None)
    sharded = GalhaloModel(aux_data=make_galhalo_data(n, comm=comm),
                           comm=comm)
    params = GalhaloParams(10.45, 12.55, 1.9, 0.55, 0.22)
    np.testing.assert_allclose(
        np.asarray(sharded.calc_sumstats_from_params(params)),
        np.asarray(single.calc_sumstats_from_params(params)), rtol=2e-4)
    g = np.asarray(sharded.calc_dloss_dparams(params))
    assert np.all(np.isfinite(g)), g
    np.testing.assert_allclose(
        g, np.asarray(single.calc_dloss_dparams(params)),
        rtol=1e-3, atol=1e-6)
