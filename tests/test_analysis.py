"""Shard-safety analyzer: each check vs its seeded bug, clean bills.

Every check is verified BOTH ways: a deliberately-broken mini-model
produces exactly the expected finding (with the right check id and
severity), and the shipped models come back clean.  Everything here is
trace-only — ``jax.make_jaxpr`` over abstract arguments — so the whole
module costs seconds, no compiles, no device math (the tier-1 budget
is tight; keep it that way).
"""
import json
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import multigrad_tpu as mgt
from multigrad_tpu import OnePointModel, scatter_nd
from multigrad_tpu.analysis import (ERROR, WARNING, Finding,
                                    analyze_fit, analyze_model,
                                    analyze_program, assert_clean,
                                    check_dtype_promotion,
                                    collect_collectives,
                                    format_findings, trace_program)
from multigrad_tpu.analysis.lint import (ALL_TARGETS, MODEL_TARGETS,
                                         _build_targets, main)
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.parallel._shard_map_compat import shard_map


@pytest.fixture(scope="module")
def comm():
    return mgt.global_comm()


@pytest.fixture(scope="module")
def smf(comm):
    return SMFModel(aux_data=make_smf_data(800, comm=comm), comm=comm)


# --------------------------------------------------------------------- #
# Seeded bugs: one deliberately-broken mini-model per check
# --------------------------------------------------------------------- #
@dataclass
class GatherModel(OnePointModel):
    """BROKEN: all_gathers the sharded catalog — O(data) collective."""

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        x = jnp.asarray(self.aux_data["x"])
        full = lax.all_gather(x, "shards", tiled=True)
        return jnp.array([jnp.sum(full * params[0]), jnp.sum(params)])

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        return jnp.sum(sumstats ** 2)


@dataclass
class CallbackModel(OnePointModel):
    """BROKEN: ungated host callback in the sumstats kernel."""

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        x = jnp.asarray(self.aux_data["x"])
        jax.debug.callback(lambda v: None, jnp.sum(x))
        return jnp.array([jnp.sum(x * params[0]), jnp.sum(params)])

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        return jnp.sum(sumstats ** 2)


def test_comm_scaling_catches_gather_statically(comm):
    # The headline acceptance case: a mutation that breaks the
    # O(|y|+|params|) bound is caught with NO device execution —
    # analyze_model only ever traces (make_jaxpr over
    # ShapeDtypeStructs), which this test proves by the absence of
    # any concrete math: the model's sumstats would all_gather 64
    # floats, yet analysis runs on abstract values only.
    m = GatherModel(aux_data={"x": scatter_nd(jnp.ones(64), comm=comm)},
                    comm=comm)
    findings = analyze_model(m, jnp.zeros(2), kinds=("loss_and_grad",))
    comm_findings = [f for f in findings if f.check == "comm-scaling"]
    assert len(comm_findings) == 1
    f = comm_findings[0]
    assert f.severity == ERROR
    assert "all_gather" in f.message
    assert "SCALES" in f.message
    # The offending collective eqn is named by source location.
    assert "test_analysis.py" in f.where


def test_comm_scaling_clean_on_smf(smf):
    findings = analyze_model(smf, jnp.zeros(2),
                             kinds=("loss_and_grad",))
    assert findings == []


def test_comm_site_payloads_match_paper_bound(smf):
    # The static trace sees exactly the two psums of the fused
    # program: |y|=10 and |params|=2 floats — the bound itself.
    program = smf._build_program("loss_and_grad", False)
    structs = [jax.ShapeDtypeStruct(np.shape(leaf),
                                    np.asarray(leaf).dtype)
               if hasattr(leaf, "shape") else leaf
               for leaf in smf.aux_leaves()]
    closed = trace_program(program,
                           jax.ShapeDtypeStruct((2,), jnp.float32),
                           structs,
                           jax.ShapeDtypeStruct((), jnp.float32))
    sites = collect_collectives(closed)
    assert sorted(s.executed_bytes for s in sites
                  if s.op == "psum") == [2 * 4, 10 * 4]


def test_replication_catches_missing_psum(comm):
    # The check_rep=False wrong-answer bug: output declared
    # replicated, but each device returns its own shard sum.
    bad = jax.jit(shard_map(lambda x: jnp.sum(x), mesh=comm.mesh,
                            in_specs=(P("shards"),), out_specs=P()))
    findings = analyze_program(bad, jnp.ones(8), program="bad")
    assert [f.check for f in findings] == ["replication"]
    assert findings[0].severity == ERROR
    assert "psum" in findings[0].message

    good = jax.jit(shard_map(
        lambda x: lax.psum(jnp.sum(x), "shards"), mesh=comm.mesh,
        in_specs=(P("shards"),), out_specs=P()))
    assert analyze_program(good, jnp.ones(8)) == []


def test_replication_catches_varying_while_trip_count(comm):
    # A device-varying LOOP PREDICATE diverges the carry even when
    # the body math is replicated: each device iterates a different
    # number of times (axis_index + 1 here), so the "replicated"
    # output differs per device.  The dataflow must union the
    # predicate's variance into the whole carry.
    def body(x):
        def loop_cond(c):
            return c[0] < lax.axis_index("shards") + 1

        def loop_body(c):
            return (c[0] + 1, c[1] + 1.0)

        # Carry starts replicated; only the trip count varies.
        return lax.while_loop(loop_cond, loop_body,
                              (jnp.int32(0), jnp.sum(x) * 0.0))[1]

    bad = jax.jit(shard_map(body, mesh=comm.mesh,
                            in_specs=(P("shards"),), out_specs=P()))
    findings = analyze_program(bad, jnp.ones(8), program="while")
    assert [f.check for f in findings] == ["replication"]

    # Replicated predicate + replicated body stays clean.
    def good_body(x):
        def loop_cond(c):
            return c[0] < 3

        def loop_body(c):
            return (c[0] + 1, c[1] * 2.0)

        total = lax.psum(jnp.sum(x), "shards")
        return lax.while_loop(loop_cond, loop_body,
                              (jnp.int32(0), total))[1]

    good = jax.jit(shard_map(good_body, mesh=comm.mesh,
                             in_specs=(P("shards"),), out_specs=P()))
    assert analyze_program(good, jnp.ones(8)) == []


def test_replication_sharded_outputs_not_flagged(comm):
    # A genuinely shard-varying output declared sharded is fine.
    ok = jax.jit(shard_map(lambda x: x * 2.0, mesh=comm.mesh,
                           in_specs=(P("shards"),),
                           out_specs=P("shards")))
    assert analyze_program(ok, jnp.ones(8)) == []


def test_callback_in_scan_caught_in_fit_program(comm):
    m = CallbackModel(
        aux_data={"x": scatter_nd(jnp.ones(64), comm=comm)},
        comm=comm)
    findings = analyze_fit(m, jnp.zeros(2), nsteps=3)
    cb = [f for f in findings if f.check == "callback-in-scan"]
    assert len(cb) == 1
    assert cb[0].severity == WARNING
    assert "scan" in cb[0].path


def test_telemetry_tap_is_exempt(smf):
    # The shipped cond-gated tap is the sanctioned shape: a tapped
    # whole-fit program must come back clean.
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger
    from multigrad_tpu.telemetry.taps import make_tap

    logger = MetricsLogger(MemorySink())
    tap = make_tap(logger, "adam", 2)
    findings = analyze_fit(smf, jnp.zeros(2), nsteps=4, tap=tap)
    assert findings == []


def test_dtype_promotion_catches_f64_leak():
    from jax.experimental import enable_x64

    with enable_x64():
        def leaky(x):
            # The classic weak-type leak: one np.float64 scalar
            # promotes the whole chain under x64.
            return jnp.sum(jnp.asarray(x, jnp.float64)
                           * np.float64(2.0))

        closed = trace_program(
            jax.jit(leaky), jax.ShapeDtypeStruct((4,), jnp.float32))
        findings = check_dtype_promotion(closed, "leaky",
                                         expected_dtype=jnp.float32)
    assert findings
    assert all(f.check == "dtype-promotion" and f.severity == ERROR
               for f in findings)
    assert any("float64" in f.message for f in findings)

    def clean(x):
        return jnp.sum(x * 2.0)

    closed = trace_program(jax.jit(clean),
                           jax.ShapeDtypeStruct((4,), jnp.float32))
    assert check_dtype_promotion(closed, "clean") == []


def test_captured_const_caught_and_threshold_respected():
    big = jnp.ones((1 << 18,))  # 1 MiB of f32

    def cap(x):
        return jnp.sum(big * x)

    findings = analyze_program(jax.jit(cap), 1.0, program="cap")
    assert [f.check for f in findings] == ["captured-const"]
    assert "1.0 MB" in findings[0].message
    # Raising the threshold clears it.
    assert analyze_program(jax.jit(cap), 1.0,
                           const_threshold=1 << 21) == []


# --------------------------------------------------------------------- #
# Clean bill over every shipped model family (the CI gate's content)
# --------------------------------------------------------------------- #
def test_clean_bill_all_shipped_models():
    ran = []
    for name, obj, params, *extra in _build_targets(MODEL_TARGETS, 800):
        assert_clean(obj, params, **(extra[0] if extra else {}))
        ran.append(name)
    assert set(ran) == set(MODEL_TARGETS)
    # the AST targets are not models: they ride the same CLI but
    # scan the package source (covered in tests/test_concurrency.py,
    # tests/test_settlement.py and tests/test_wireschema.py)
    assert set(ALL_TARGETS) == set(MODEL_TARGETS) \
        | {"threads", "settlement", "wire"}


def test_check_shard_safety_one_call(smf, comm):
    # The wired-through surface: one call on the model object.
    assert smf.check_shard_safety(jnp.zeros(2)) == []
    # ... and on a streaming wrapper.
    aux = make_smf_data(800, comm=None)
    log_mh = np.asarray(aux.pop("log_halo_masses"))
    sm = mgt.StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm),
        streams={"log_halo_masses": log_mh}, chunk_rows=200)
    assert sm.check_shard_safety(jnp.zeros(2)) == []
    # ... and on a fused group.
    group = mgt.OnePointGroup(models=(smf,))
    assert group.check_shard_safety(jnp.zeros(2)) == []


def test_assert_clean_raises_with_report(comm):
    m = GatherModel(aux_data={"x": scatter_nd(jnp.ones(64), comm=comm)},
                    comm=comm)
    with pytest.raises(AssertionError, match="comm-scaling"):
        assert_clean(m, jnp.zeros(2), kinds=("loss_and_grad",))


def test_randkey_variants_trace(smf):
    # The randkey-taking program variants trace and come back clean.
    assert analyze_model(smf, jnp.zeros(2), randkey=7,
                         kinds=("loss_and_grad",)) == []


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #
def test_lint_cli_clean_exit(capsys):
    rc = main(["--targets", "smf", "--json", "--num-halos", "400"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["clean"] is True
    assert out["findings"] == []


def test_lint_cli_check_and_target_validation():
    with pytest.raises(SystemExit):
        main(["--targets", "nope"])
    with pytest.raises(SystemExit):
        main(["--checks", "nope"])


def test_lint_cli_subset_of_checks(capsys):
    rc = main(["--targets", "smf", "--checks", "comm-scaling,replication",
               "--num-halos", "400"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


# --------------------------------------------------------------------- #
# Findings plumbing
# --------------------------------------------------------------------- #
def test_finding_formatting_and_roundtrip():
    f = Finding("comm-scaling", ERROR, "boom", program="M:kind",
                where="x.py:3", path="pjit/shard_map")
    assert "ERROR comm-scaling" in str(f)
    assert f.to_dict()["where"] == "x.py:3"
    report = format_findings([f])
    assert "1 error(s)" in report
    assert format_findings([]) == "clean: no findings"
