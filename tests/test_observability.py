"""Flight recorder & perf-attribution layer (ISSUE 8's tentpole).

The acceptance-criteria assertions live here:

* the static cost model reproduces BENCH_NOTES §2's hand arithmetic
  for the SMF step — ``N·E`` erf forward, ``N·E`` exp backward, and
  ``(|y| + |params|) · 4`` collective bytes per step — from a
  zero-FLOP abstract trace;
* a NaN-seeded Adam fit on the 8-virtual-CPU mesh trips the in-graph
  sentinel, dumps a postmortem bundle holding the last tapped steps
  and the run record, stamps the bundle path into ``fit_summary``,
  and raises;
* ``telemetry.regress`` flags an injected 2× regression and stays
  quiet for deltas inside the recorded ``tunnel_rtt_ms`` noise floor;
* ``telemetry.aggregate`` merges per-rank files and names the
  straggler;
* the report CLI renders the PR-7 streaming records (overlap/pass
  splits) and survives mixed-schema multi-run files with a truncated
  tail.

Everything except the two tiny mesh fits and one profiler capture is
trace-only/pure-host, to protect the tier-1 budget.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu import telemetry
from multigrad_tpu.data import StreamingOnePointModel
from multigrad_tpu.models.smf import (SMFChi2Model, SMFModel,
                                      load_halo_masses, make_smf_data)
from multigrad_tpu.telemetry import (FlightRecorder,
                                     FlightRecorderTripped,
                                     MemorySink, MetricsLogger,
                                     aggregate as agg_mod,
                                     model_cost, predicted_time_s,
                                     profiled_fit, regress as reg_mod,
                                     report as report_mod,
                                     roofline_record)

N_DEV = len(jax.devices())
F32 = np.dtype(np.float32).itemsize
N_BINS = 10
N_PARAMS = 2
E = N_BINS + 1                      # bin EDGES: the erf count per halo


def drain():
    jax.effects_barrier()


def events(sink, name):
    return [r for r in sink.records if r["event"] == name]


def nan_seeded_smf(n_halos, comm):
    """SMF model whose loss is NaN from step 0 (negative target →
    log10 NaN) — the deterministic anomaly seed."""
    aux = make_smf_data(n_halos, comm=comm)
    aux["target_sumstats"] = -jnp.asarray(aux["target_sumstats"])
    return SMFModel(aux_data=aux, comm=comm)


# ------------------------------------------------------------------ #
# Cost model vs BENCH_NOTES §2 hand arithmetic
# ------------------------------------------------------------------ #
def test_costmodel_matches_bench_notes_arithmetic():
    n = 20_000
    model = SMFModel(aux_data=make_smf_data(n, comm=None), comm=None)
    cost = model_cost(model, jnp.array([-1.0, 0.5]))
    # Forward: one erf per (halo, edge).  Backward: erf's derivative
    # is (2/√π)·exp(−z²) — one exp per (halo, edge).  Nothing else in
    # the program touches erf; the only other exp-family op is the
    # loss's log10 on |y| elements.
    assert cost.transcendentals["erf"] == n * E
    assert cost.transcendentals["exp"] == n * E
    assert cost.transcendentals.get("log", 0) < 100   # loss-side only
    # The catalog dominates the program's input footprint.
    assert n * F32 <= cost.arg_bytes < n * F32 + 4096
    # Single-device model: zero collective traffic.
    assert cost.comm_bytes == 0 and cost.comm_calls == 0


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_costmodel_comm_bytes_and_per_shard_counts():
    n = 16_384                       # divides the 8-device mesh
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(n, comm=comm), comm=comm)
    cost = model_cost(model, jnp.array([-1.0, 0.5]))
    # The paper's claim, from the cost model's collective collection:
    # psum(y) + psum(grad) = (|y| + |params|) * 4 bytes per step.
    assert cost.comm_bytes == (N_BINS + N_PARAMS) * F32
    assert cost.comm_calls == 2
    # shard_map body shapes are per-shard: the per-device roofline
    # denominator counts N/devices halos.
    assert cost.transcendentals["erf"] == (n // N_DEV) * E


def test_costmodel_roofline_fold_and_record():
    model = SMFModel(aux_data=make_smf_data(4096, comm=None),
                     comm=None)
    cost = model_cost(model, jnp.array([-1.0, 0.5]))
    pred = predicted_time_s(cost, device_kind="TPU v5 lite")
    assert pred["predicted_s"] > 0
    assert pred["bound"] in ("compute", "memory")
    assert pred["predicted_s"] == max(pred["compute_s"],
                                      pred["memory_s"])
    rec = roofline_record(cost, measured_s=1e-3,
                          device_kind="TPU v5 lite", config="test")
    assert rec["roofline_frac"] == pytest.approx(
        pred["predicted_s"] / 1e-3)
    assert rec["config"] == "test"
    assert rec["transcendentals"]["erf"] == 4096 * E
    # scan-trip multipliers: a 7-step whole-fit scan runs 7x the
    # per-step transcendentals.
    from multigrad_tpu.optim.adam import adam_fit_program
    from multigrad_tpu.telemetry import estimate_program_cost
    import optax

    def loss_and_grad(p, _key):
        return jnp.sum(jnp.exp(p)), jnp.exp(p)

    program = adam_fit_program(loss_and_grad, 7, donate_carry=False)
    p0 = jnp.zeros(3)
    fit_cost = estimate_program_cost(
        program, p0, optax.adam(0.01).init(p0), jax.random.key(0),
        jnp.full(3, -jnp.inf), jnp.full(3, jnp.inf), ())
    assert fit_cost.transcendentals["exp"] == 7 * 2 * 3


# ------------------------------------------------------------------ #
# Flight recorder: NaN-seeded fits (the acceptance scenario)
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_nan_seeded_mesh_fit_dumps_postmortem(tmp_path):
    model = nan_seeded_smf(4096, mgt.global_comm())
    recorder = FlightRecorder(dump_dir=str(tmp_path))
    sink = MemorySink()
    logger = MetricsLogger(sink, recorder)
    with pytest.raises(FlightRecorderTripped) as exc:
        model.run_adam(guess=jnp.array([-1.0, 0.5]), nsteps=6,
                       progress=False, telemetry=logger, log_every=1,
                       flight=recorder)
    drain()
    bundle_path = exc.value.bundle_path
    assert bundle_path and os.path.exists(bundle_path)
    # strict RFC-8259 JSON: no bare NaN/Infinity tokens, although the
    # trip detail embeds non-finite floats by construction
    text = open(bundle_path).read()
    bundle = json.loads(
        text, parse_constant=lambda tok: pytest.fail(
            f"bare {tok} token in postmortem bundle"))
    # the ring preserved the run record and the tapped steps
    ring_events = [r["event"] for r in bundle["ring"]]
    assert "run" in ring_events and "adam" in ring_events
    assert bundle["run"]["jax_version"] == jax.__version__
    assert bundle["reason"].startswith("non_finite")
    assert bundle["jaxpr_digests"].get("adam_segment_program")
    # the fit_summary record carries the bundle path
    summaries = events(sink, "fit_summary")
    assert summaries and summaries[-1]["postmortem_bundle"] \
        == bundle_path
    # a healthy fit through the SAME recorder after reset is clean
    recorder.reset()
    healthy = SMFModel(aux_data=make_smf_data(4096,
                                              comm=mgt.global_comm()),
                       comm=mgt.global_comm())
    healthy.run_adam(guess=jnp.array([-1.0, 0.5]), nsteps=4,
                     progress=False, telemetry=logger, log_every=2,
                     flight=recorder)
    drain()
    assert not recorder.tripped


def test_nan_seeded_streamed_fit_trips_host_sentinel(tmp_path):
    n = 4096
    log_mh = np.asarray(jnp.log10(load_halo_masses(n)))
    aux = make_smf_data(n, comm=None)
    aux["target_sumstats"] = -jnp.asarray(aux["target_sumstats"])
    del aux["log_halo_masses"]
    sm = StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=None),
        streams={"log_halo_masses": log_mh}, chunk_rows=1024)
    recorder = FlightRecorder(dump_dir=str(tmp_path / "pm"))
    sink = MemorySink()
    logger = MetricsLogger(sink, recorder)
    with pytest.raises(FlightRecorderTripped):
        sm.run_adam(guess=jnp.array([-1.0, 0.5]), nsteps=5,
                    progress=False, telemetry=logger, log_every=1,
                    checkpoint_dir=str(tmp_path / "ckpt"),
                    flight=recorder)
    assert recorder.bundle_path and os.path.exists(
        recorder.bundle_path)
    summaries = events(sink, "fit_summary")
    assert summaries[-1]["postmortem_bundle"] == recorder.bundle_path
    # the bundle points triage at the streamed restart state
    bundle = json.load(open(recorder.bundle_path))
    assert bundle["context"]["last_checkpoint"].endswith(
        "adam_streamed_state.npz")


def test_hmc_flight_sentinel_trips_on_nan_potential(tmp_path):
    # sigma_frac = 0 divides the chi2 loss by zero: NaN potential.
    aux = make_smf_data(2048, comm=None)
    aux["sigma_frac"] = 0.0
    model = SMFChi2Model(aux_data=aux, comm=None)
    recorder = FlightRecorder(dump_dir=str(tmp_path))
    # num_warmup > 0: the sentinel must be armed during the warmup
    # scan too, not only post-warmup (a NaN-from-draw-0 likelihood
    # would otherwise burn the whole warmup on NaNs silently).
    with pytest.raises(FlightRecorderTripped):
        mgt.run_hmc(model, jnp.array([-2.0, 0.2]), num_samples=6,
                    num_warmup=4, num_chains=2, num_leapfrog=2,
                    randkey=1, flight=recorder)
    assert recorder.reason.startswith("non_finite")
    assert os.path.exists(recorder.bundle_path)
    bundle = json.load(open(recorder.bundle_path))
    assert "warmup_potential" in bundle["detail"]["values"]


def test_flight_recorder_stall_and_divergence_triggers(tmp_path):
    recorder = FlightRecorder(dump_dir=str(tmp_path),
                              divergence_spike=10)
    logger = MetricsLogger(MemorySink(), recorder)
    # heartbeat stall: non-fatal bundle, the fit would NOT raise
    logger.log("stall", step=7, stalled_s=12.5)
    assert recorder.tripped and not recorder.fatal
    first_bundle = recorder.bundle_path
    assert first_bundle and os.path.exists(first_bundle)
    bundle = json.load(open(first_bundle))
    assert bundle["reason"] == "heartbeat_stall"
    recorder.raise_if_fatal()           # no-op: non-fatal
    # divergence spike between consecutive hmc records
    recorder.reset()
    logger.log("hmc", step=10, divergences=2)
    logger.log("hmc", step=20, divergences=3)   # +1: quiet
    assert not recorder.tripped
    logger.log("hmc", step=30, divergences=40)  # +37: spike
    assert recorder.tripped and recorder.reason == "divergence_spike"
    # a FATAL trip after a non-fatal one must escalate: fresh bundle,
    # and the raised reason names the trip that killed the fit, not
    # the survived stall/spike
    spike_bundle = recorder.bundle_path
    recorder.trip("non_finite_adam", fatal=True, step=99)
    assert recorder.fatal
    assert recorder.reason == "non_finite_adam"
    assert recorder.bundle_path != spike_bundle
    with pytest.raises(FlightRecorderTripped) as exc:
        recorder.raise_if_fatal()
    assert exc.value.reason == "non_finite_adam"
    assert exc.value.bundle_path == recorder.bundle_path


def test_checkpointed_fit_keeps_last_good_state_on_trip(tmp_path):
    # The drive must check the sentinel BEFORE on_segment: the NaN
    # segment's carry must never overwrite the restart state the
    # postmortem bundle points at.
    def loss_and_grad(p, _key):
        loss = jnp.sqrt(2.0 - jnp.sum(p))       # NaN once sum(p) > 2
        return loss, -0.5 / loss * jnp.ones_like(p)

    recorder = FlightRecorder(dump_dir=str(tmp_path / "pm"))
    ckpt = tmp_path / "ckpt"
    from multigrad_tpu.optim.adam import run_adam_scan
    with pytest.raises(FlightRecorderTripped):
        run_adam_scan(loss_and_grad, jnp.zeros(1), nsteps=12,
                      learning_rate=0.3, flight=recorder,
                      checkpoint_dir=str(ckpt), checkpoint_every=3)
    drain()
    assert recorder.bundle_path
    # the saved restart state predates the failure and is NaN-free
    # (config rows legitimately hold +-inf bounds; NaN is the poison)
    data = np.load(str(ckpt / "adam_state.npz"), allow_pickle=True)
    for key in data.files:
        arr = np.asarray(data[key])
        if arr.dtype.kind == "f":
            assert not np.any(np.isnan(arr)), key


def test_sentinel_is_cache_stable_and_untripped_fits_are_free():
    # Arming the sentinel must behave like the tap: one build, zero
    # retraces across repeat fits with the same recorder, and a
    # finite fit returns normally.
    traces = []
    target = jnp.array([1.0, -2.0])

    def loss_and_grad(p, _key):
        traces.append(1)
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    recorder = FlightRecorder()
    from multigrad_tpu.optim.adam import run_adam_scan
    out1 = run_adam_scan(loss_and_grad, jnp.zeros(2), nsteps=10,
                         learning_rate=0.1, flight=recorder)
    n_traces = len(traces)
    out2 = run_adam_scan(loss_and_grad, jnp.ones(2), nsteps=10,
                         learning_rate=0.1, flight=recorder)
    drain()
    assert len(traces) == n_traces       # cache hit: zero retraces
    assert not recorder.tripped
    assert np.all(np.isfinite(out1)) and np.all(np.isfinite(out2))


# ------------------------------------------------------------------ #
# Regression gate (telemetry.regress)
# ------------------------------------------------------------------ #
def write_dossier(path, configs, rtt_ms):
    with open(path, "w") as f:
        json.dump({"metric": "test", "value": None,
                   "configs": configs, "tunnel_rtt_ms": rtt_ms}, f)
    return str(path)


def test_regress_flags_2x_and_respects_rtt_floor(tmp_path, capsys):
    prev = write_dossier(tmp_path / "r1.json", {
        "smf_1e6_xla_steps_per_sec": 4000.0,
        "pair_1e5_fwdbwd_s_xla": 0.2,
        "galhalo": {"speedup": 2.1},
    }, rtt_ms=50.0)
    cur = write_dossier(tmp_path / "r2.json", {
        "smf_1e6_xla_steps_per_sec": 2000.0,    # injected 2x drop
        # +40% — over pct, but the 80 ms delta sits under the
        # 2x50 ms tunnel-derived floor: noise, not regression
        "pair_1e5_fwdbwd_s_xla": 0.28,
        "galhalo": {"speedup": 2.0},            # -4.8%: within pct
    }, rtt_ms=40.0)
    rc = reg_mod.main([prev, cur])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION: smf_1e6_xla_steps_per_sec" in out
    # the +40% time delta sits under the rtt-derived floor: the table
    # marks it noise, and it never reaches the REGRESSION list
    assert "(noise floor)" in out
    assert "REGRESSION: pair_1e5_fwdbwd_s_xla" not in out
    # same dossiers inside the noise envelope: quiet, rc 0
    quiet = write_dossier(tmp_path / "r3.json", {
        "smf_1e6_xla_steps_per_sec": 3900.0,
        "pair_1e5_fwdbwd_s_xla": 0.21,
        "galhalo": {"speedup": 2.05},
    }, rtt_ms=50.0)
    assert reg_mod.main([prev, quiet]) == 0
    capsys.readouterr()
    # --warn-only downgrades the gate
    assert reg_mod.main([prev, cur, "--warn-only"]) == 0
    capsys.readouterr()


def test_regress_null_metrics_warn_only(tmp_path, capsys):
    prev = write_dossier(tmp_path / "a.json", {
        "smf_1e6_xla_steps_per_sec": 100.0,
        "smf_1e9_pallas_steps_per_sec": None,       # BENCH_r05 shape
        "wprp_8192_fwdbwd_ms_xla": 4.8,
    }, rtt_ms=10.0)
    cur = write_dossier(tmp_path / "b.json", {
        "smf_1e6_xla_steps_per_sec": 101.0,
        "smf_1e9_pallas_steps_per_sec": 3.2,        # newly measured
        "wprp_8192_fwdbwd_ms_xla": None,            # lost this round
    }, rtt_ms=10.0)
    rc = reg_mod.main([prev, cur])
    out = capsys.readouterr().out
    assert rc == 0                  # nulls never fail the gate
    assert "warn: smf_1e9_pallas_steps_per_sec" in out
    assert "warn: wprp_8192_fwdbwd_ms_xla" in out
    # the real committed dossiers load (schema compatibility)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r5 = reg_mod.load_dossier(os.path.join(repo, "BENCH_r05.json"))
    r6 = reg_mod.load_dossier(os.path.join(repo, "BENCH_r06.json"))
    assert r5["configs"] and r6["configs"]
    results = reg_mod.compare_rounds(r5, r6)
    assert any(r["status"] == "null" for r in results)


def test_regress_include_and_json(tmp_path, capsys):
    prev = write_dossier(tmp_path / "p.json",
                         {"a_steps_per_sec": 100.0,
                          "b_steps_per_sec": 100.0}, 1.0)
    cur = write_dossier(tmp_path / "c.json",
                        {"a_steps_per_sec": 10.0,
                         "b_steps_per_sec": 10.0}, 1.0)
    # --include restricts the gate to matching metrics
    rc = reg_mod.main([prev, cur, "--include", "b_*", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [r["metric"] for r in out["results"]] \
        == ["b_steps_per_sec"]


# ------------------------------------------------------------------ #
# Cross-rank aggregation
# ------------------------------------------------------------------ #
def write_rank_file(path, rank, t0, fit_end):
    records = [
        {"event": "run", "t": t0, "process_index": rank,
         "backend": "cpu", "jax_version": jax.__version__},
        {"event": "adam", "t": t0 + 0.5, "process_index": rank,
         "step": 0, "loss": 1.0},
        {"event": "span", "t": fit_end, "process_index": rank,
         "name": "fit", "path": "fit",
         "elapsed_s": fit_end - t0, "ok": True},
    ]
    if rank == 1:
        records.append({"event": "stall", "t": t0 + 2.0,
                        "process_index": rank, "stalled_s": 3.0})
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def test_aggregate_merges_and_flags_straggler(tmp_path, capsys):
    t0 = 1000.0
    paths = [write_rank_file(tmp_path / "rank0.jsonl", 0, t0, t0 + 10),
             write_rank_file(tmp_path / "rank1.jsonl", 1, t0, t0 + 19)]
    summary = agg_mod.aggregate(paths, threshold_s=1.0,
                                threshold_frac=0.2)
    assert summary["n_records"] == 7
    assert summary["ranks"][1]["stalls"] == 1
    skew = summary["span_skew"]["fit"]
    assert skew["end_spread_s"] == pytest.approx(9.0)
    stragglers = summary["stragglers"]
    assert len(stragglers) == 1
    assert stragglers[0]["rank"] == 1 and stragglers[0]["span"] == "fit"
    # CLI renders and exits 0; merged stream lands in --out
    out_path = str(tmp_path / "merged.jsonl")
    assert agg_mod.main(paths + ["--out", out_path]) == 0
    rendered = capsys.readouterr().out
    assert "STRAGGLER rank 1" in rendered
    merged = [json.loads(line) for line in open(out_path)]
    assert len(merged) == 7
    assert all("process_index" in rec for rec in merged)
    # in-job single-process gather round-trips
    local = agg_mod.gather_to_rank0([{"event": "x", "t": 1.0,
                                      "process_index": 0}])
    assert local and local[0]["event"] == "x"


def test_legacy_files_without_process_index_still_merge(tmp_path):
    # pre-stamp streams: ranks inferred from run records / file order
    path = tmp_path / "old.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"event": "run", "t": 1.0}) + "\n")
        f.write(json.dumps({"event": "adam", "t": 2.0, "step": 0})
                + "\n")
    merged = agg_mod.load_rank_records([str(path)])
    assert all(rec["process_index"] == 0 for rec in merged)


# ------------------------------------------------------------------ #
# Report: PR-7 streaming records + mixed-schema files (satellites)
# ------------------------------------------------------------------ #
def test_report_surfaces_overlap_and_pass_splits(capsys):
    logger_records = [
        {"event": "run", "t": 1.0, "backend": "cpu",
         "process_index": 0},
        {"event": "fit_summary", "t": 2.0, "steps": 10,
         "steps_per_sec": 12.5, "final_loss": 0.5,
         "overlap_frac": 0.91,
         "pass_overlap": {"sumstats": 0.88, "vjp": 0.94}},
        {"event": "stream", "t": 2.1, "stall_fraction": 0.05,
         "overlap_frac": 0.91, "chunks_per_sec": 40.0,
         "bytes_streamed": 1 << 20, "max_live_buffers": 2,
         "passes": {"sumstats": {"stall_fraction": 0.1,
                                 "overlap_frac": 0.88, "chunks": 8,
                                 "bytes_streamed": 1 << 19},
                    "vjp": {"stall_fraction": 0.02,
                            "overlap_frac": 0.94, "chunks": 8,
                            "bytes_streamed": 1 << 19}}},
    ]
    summary = report_mod.summarize(logger_records)
    assert summary["fit"]["overlap_frac"] == 0.91
    assert summary["fit"]["pass_overlap"]["vjp"] == 0.94
    assert summary["stream"]["passes"]["sumstats"]["chunks"] == 8
    out = report_mod.render(summary)
    assert "overlap_frac=0.91" in out
    assert "pass overlap: sumstats=0.88  vjp=0.94" in out
    assert "pass sumstats:" in out and "pass vjp:" in out


def test_report_mixed_schema_multirun_with_truncated_tail(tmp_path,
                                                          capsys):
    # One JSONL holding bench records + a fit run + stream records +
    # profile/roofline records appended across two runs, then a
    # crash-truncated tail — the artifact shape CI actually produces.
    path = str(tmp_path / "mixed.jsonl")
    log1 = MetricsLogger(telemetry.JsonlSink(path))
    log1.log("bench", config="smf_1e6_xla_steps_per_sec", value=18.6)
    log1.log("bench", config="galhalo_hist_fused_bins_ab",
             value={"sigma005": {"speedup": 2.1}})
    log1.close()
    log2 = MetricsLogger(telemetry.JsonlSink(path))
    log2.log("adam", step=0, loss=3.0, grad_norm=1.0)
    log2.log("adam", step=50, loss=0.1, grad_norm=0.05)
    log2.log("stream", stall_fraction=0.01, overlap_frac=0.99,
             chunks_per_sec=50.0, bytes_streamed=1 << 16,
             max_live_buffers=2,
             passes={"vjp": {"overlap_frac": 0.99,
                             "stall_fraction": 0.01, "chunks": 4,
                             "bytes_streamed": 1 << 15}})
    log2.log("profile", name="fit", total_device_us=1234.5,
             per_step_us=24.7, roofline_frac=0.41, bound="compute",
             tunnel_rtt_ms=0.05,
             top_ops=[{"op": "fusion", "us": 1000.0, "count": 50,
                       "frac": 0.81}])
    log2.log("roofline", config="smf", predicted_s=1e-4,
             measured_s=2e-4, roofline_frac=0.5, bound="compute",
             device_kind="cpu")
    log2.log("fit_summary", steps=50, steps_per_sec=20.0,
             final_loss=0.1, overlap_frac=0.99)
    log2.close()
    with open(path, "a") as f:
        f.write('{"event": "adam", "step"')       # crashed writer
    records = report_mod.load_records(path)
    summary = report_mod.summarize(records)
    # only the LAST run is summarized; the bench run is counted
    assert summary["runs_in_file"] == 2
    assert "bench" not in summary
    assert summary["fit"]["final_loss"] == 0.1
    assert summary["profile"]["roofline_frac"] == 0.41
    assert summary["roofline"]["measured_s"] == 2e-4
    assert report_mod.main([path]) == 0
    out = capsys.readouterr().out
    assert "profile:" in out and "roofline_frac=0.41" in out
    assert "roofline: predicted=" in out
    # appending the truncated tail plus a NEW run keeps working
    log3 = MetricsLogger(telemetry.JsonlSink(path))
    log3.log("bench", config="later", value=1.0)
    log3.close()
    summary = report_mod.summarize(report_mod.load_records(path))
    assert summary["runs_in_file"] == 3
    assert summary["bench"] == {"later": 1.0}


# ------------------------------------------------------------------ #
# Profiler capture + trace tmpdir (satellites)
# ------------------------------------------------------------------ #
def test_trace_defaults_to_private_tmpdir():
    import tempfile

    from multigrad_tpu.utils.profiling import trace

    f = jax.jit(lambda x: x * 2.0)
    with trace() as d1:
        np.asarray(f(jnp.ones(8)))
    with trace() as d2:
        np.asarray(f(jnp.ones(8)))
    assert d1 != d2                       # parallel jobs can't clobber
    tmp = tempfile.gettempdir()
    assert d1.startswith(os.path.join(tmp, "multigrad_tpu_trace_"))
    assert os.path.isdir(d1)


def test_profiled_fit_buckets_device_time_and_joins_roofline():
    n = 50_000
    model = SMFModel(aux_data=make_smf_data(n, comm=None), comm=None)
    guess = jnp.array([-1.0, 0.5])
    nsteps = 25
    np.asarray(model.run_adam(guess=guess, nsteps=nsteps,
                              progress=False))      # warm-up/compile
    cost = model_cost(model, guess)
    sink = MemorySink()
    logger = MetricsLogger(sink)
    with profiled_fit(logger, name="smf_test", nsteps=nsteps,
                      cost=cost) as prof:
        np.asarray(model.run_adam(guess=guess + 0.01, nsteps=nsteps,
                                  progress=False))
    assert prof.error is None, prof.error
    rec = prof.record
    assert rec["total_device_us"] > 0
    assert rec["per_step_us"] > 0
    assert rec["top_ops"] and rec["top_ops"][0]["frac"] > 0
    assert rec["tunnel_rtt_ms"] >= 0
    # the roofline join landed (cpu spec: just a sanity band)
    assert rec["bound"] in ("compute", "memory")
    assert rec["roofline_frac"] is None or rec["roofline_frac"] > 0
    assert rec["transcendentals"]["erf"] == n * E
    # the record also flowed to the logger
    recs = events(sink, "profile")
    assert len(recs) == 1 and recs[0]["name"] == "smf_test"


def test_process_index_stamped_on_every_record():
    sink = MemorySink()
    logger = MetricsLogger(sink)
    logger.log("adam", step=0, loss=1.0)
    with telemetry.span(logger, "fit"):
        pass
    logger.close()
    assert all(rec.get("process_index") == 0 for rec in sink.records)
