"""Live observability layer: endpoint, dashboard, alerts, diagnostics.

The load-bearing assertions:

* ``/metrics`` serves valid Prometheus text exposition and ``/status``
  reports step/loss/steps-per-sec/ETA **during** a live mesh fit,
  scraped over a real local HTTP request (the fit is gated on the
  scrape, so mid-flight capture is deterministic, not a race);
* the terminal dashboard renders a structurally complete frame from
  the same JSONL a fit writes, and its tail reader never parses a
  half-written line (the ``--follow`` safety contract);
* every alert rule fires on an injected trigger and stays quiet on a
  clean fit; fired alerts land back in the shared record stream and
  can escalate to the flight recorder (non-fatally);
* the gradient-noise-scale tap matches a hand computation over
  per-shard gradients, and the new diagnostics taps add ZERO retraces
  (same trace-counting assertion as the PR-3 tap tests).
"""
import json
import re
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multigrad_tpu as mgt
from multigrad_tpu import telemetry
from multigrad_tpu.core.model import GNS_EPS
from multigrad_tpu.models.smf import (ParamTuple, SMFChi2Model, SMFModel,
                                      make_smf_data)
from multigrad_tpu.optim.adam import run_adam_scan, run_adam_streamed
from multigrad_tpu.telemetry import alerts as alerts_mod
from multigrad_tpu.telemetry import dashboard as dash_mod
from multigrad_tpu.telemetry import report as report_mod
from multigrad_tpu.telemetry.alerts import (AlertEngine, DivergenceRate,
                                            GradExplosion, HeartbeatStall,
                                            LossPlateau, ThroughputDrop)
from multigrad_tpu.telemetry.dashboard import TailReader
from multigrad_tpu.telemetry.live import LiveMetrics, LiveServer, LiveSink

N_DEV = len(jax.devices())


def drain():
    jax.effects_barrier()


def new_logger(*extra_sinks, **kwargs):
    sink = telemetry.MemorySink()
    return telemetry.MetricsLogger(sink, *extra_sinks, **kwargs), sink


def events(sink, name):
    return [r for r in sink.records if r["event"] == name]


# The exposition grammar the smoke checks enforce: comment lines are
# HELP/TYPE, sample lines are name[{labels}] value.
_META_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"(NaN|[+-]Inf|[-+0-9.eE]+)$")


def assert_prometheus_wellformed(text: str) -> int:
    n = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert _META_RE.match(line), f"bad meta line: {line!r}"
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        n += 1
    assert n > 0, "no samples in exposition"
    return n


# ------------------------------------------------------------------ #
# LiveMetrics registry
# ------------------------------------------------------------------ #
def test_live_metrics_registry_renders_valid_exposition():
    m = LiveMetrics()
    m.inc("demo_total", 2, help="a counter", labels={"kind": "a"})
    m.inc("demo_total", 1, labels={"kind": "b"})
    m.set("demo_gauge", 1.5, help="a gauge")
    for v in (0.003, 0.02, 0.02, 7.0):
        m.observe("demo_seconds", v, help="a histogram")
    text = m.render()
    assert_prometheus_wellformed(text)
    assert 'demo_total{kind="a"} 2' in text
    assert "# TYPE demo_total counter" in text
    assert "demo_gauge 1.5" in text
    # histogram: cumulative buckets, +Inf == count, sum matches
    assert 'demo_seconds_bucket{le="+Inf"} 4' in text
    assert "demo_seconds_count 4" in text
    assert "demo_seconds_sum 7.043" in text
    buckets = [int(line.rsplit(" ", 1)[1])
               for line in text.splitlines()
               if line.startswith("demo_seconds_bucket")]
    assert buckets == sorted(buckets)        # cumulative
    # a name cannot change type mid-stream
    with pytest.raises(ValueError):
        m.set("demo_total", 3.0)
    with pytest.raises(ValueError):
        m.inc("bad name!")


def test_live_sink_status_eta_from_fit_plan():
    sink = LiveSink()
    t0 = 1000.0
    sink.write({"event": "run", "t": t0, "backend": "cpu"})
    sink.write({"event": "fit_plan", "t": t0, "kind": "adam_scan",
                "nsteps": 101})
    for k in range(6):
        sink.write({"event": "adam", "t": t0 + 0.1 * k,
                    "step": 10 * k, "loss": 1.0 - 0.1 * k,
                    "grad_norm": 0.5})
    st = sink.status(now=t0 + 1.0)
    # 50 steps over 0.5 s -> 100 steps/s; 50 of 101 remain -> 0.5 s
    assert st["phase"] == "fitting"
    assert st["steps_per_sec"] == pytest.approx(100.0)
    assert st["eta_s"] == pytest.approx(0.5)
    assert st["loss"] == pytest.approx(0.5)
    assert st["nsteps"] == 101 and st["step"] == 50
    assert st["last_record_age_s"] == pytest.approx(0.5)
    sink.write({"event": "fit_summary", "t": t0 + 0.6, "steps": 101,
                "final_loss": 0.4})
    st = sink.status(now=t0 + 1.0)
    assert st["phase"] == "done" and st["eta_s"] == 0.0
    # comm + heartbeat + alert records land in the view too
    sink.write({"event": "comm", "t": t0 + 0.7, "bytes_per_step": 48})
    sink.write({"event": "heartbeat", "t": t0 + 0.8, "step": 100})
    sink.write({"event": "alert", "t": t0 + 0.9, "rule": "x"})
    st = sink.status(now=t0 + 1.0)
    assert st["comm_bytes_per_step"] == 48
    assert st["last_heartbeat_age_s"] == pytest.approx(0.2)
    assert st["alerts"] == 1


def test_status_resources_section_from_monitor_gauges():
    from multigrad_tpu.telemetry.resources import ResourceMonitor

    sink = LiveSink()
    # No monitor has exported yet: the section stays off the JSON
    # entirely (same absent-not-empty contract as qos/latency).
    assert "resources" not in sink.status()

    mon = ResourceMonitor(live=sink, interval_s=60.0)
    with mon.dispatching():
        time.sleep(0.02)
    mon.sample()
    # Queue-wait observations land in the serve hop histogram the
    # autoscaler contract reads its p95 from.
    for v in (0.01, 0.02, 0.5):
        sink.metrics.observe("multigrad_serve_hop_seconds", v,
                             labels={"hop": "queue_wait"})
    res = sink.status()["resources"]
    assert res["rss_bytes"] > 0 and isinstance(res["rss_bytes"], int)
    assert res["busy_s_total"] > 0
    assert res["uptime_s"] >= 0
    # CPU backend: device fields are null, never fabricated zeros
    assert res["device_bytes_in_use"] is None
    assert res["device_bytes_limit"] is None
    assert set(res["compile"]) == {"count", "seconds_total",
                                   "cache_hits", "cache_misses"}
    # the documented autoscaler-inputs contract (v2), same endpoint
    auto = res["autoscaler"]
    assert set(auto) == {"busy_frac", "queue_wait_p95_s",
                         "headroom_bytes", "queue_wait_p95_trend",
                         "busy_frac_sustained", "slo_burn_rate"}
    assert auto["queue_wait_p95_s"] is not None
    assert auto["queue_wait_p95_s"] >= 0.02
    assert auto["headroom_bytes"] is None    # no device limit on CPU
    # no rollup store behind this registry: trend-aware signals are
    # honestly None, never fabricated
    assert auto["queue_wait_p95_trend"] is None
    assert auto["busy_frac_sustained"] is None
    mon.close()


# ------------------------------------------------------------------ #
# The endpoint, scraped over real HTTP during a mesh fit
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_live_http_scrape_during_mesh_fit():
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(4096, comm=comm), comm=comm)
    base = model.calc_loss_and_grad_from_params

    # Deterministic mid-fit capture: the fit's 8th loss evaluation
    # BLOCKS until the scraper thread has successfully read /status
    # mid-flight — no sleep-and-hope racing.
    scraped = threading.Event()
    captured = {}
    calls = [0]

    def loss_and_grad(p):
        calls[0] += 1
        if calls[0] == 8:
            scraped.wait(timeout=60)
        return base(p)                      # mesh program dispatch

    live = LiveServer(port=0)

    def scraper():
        deadline = time.time() + 60
        try:
            while time.time() < deadline:
                try:
                    status = json.load(urllib.request.urlopen(
                        live.url + "/status", timeout=5))
                except OSError:
                    time.sleep(0.01)
                    continue
                if status.get("step") is not None \
                        and status.get("steps_per_sec"):
                    captured["status"] = status
                    captured["metrics"] = urllib.request.urlopen(
                        live.url + "/metrics",
                        timeout=5).read().decode()
                    return
                time.sleep(0.01)
        finally:
            scraped.set()

    thread = threading.Thread(target=scraper, daemon=True)
    thread.start()
    try:
        traj = run_adam_streamed(
            loss_and_grad, jnp.array([-1.0, 0.5]), nsteps=12,
            learning_rate=0.05, progress=False, live=live,
            log_every=1)
        thread.join(timeout=60)
        assert "status" in captured, "scraper never saw a live status"
        st = captured["status"]
        # mid-fit: the gate held the loop at its 8th evaluation, so
        # the capture happened while the fit was demonstrably running
        assert st["phase"] == "fitting"
        assert 1 <= st["step"] <= 7
        assert st["nsteps"] == 12 and st["fit_kind"] == "adam_streamed"
        assert np.isfinite(st["loss"])
        assert st["steps_per_sec"] > 0
        assert st["eta_s"] is not None and st["eta_s"] >= 0
        # the scrape is valid Prometheus text exposition
        assert_prometheus_wellformed(captured["metrics"])
        assert "multigrad_step " in captured["metrics"]
        assert "multigrad_loss " in captured["metrics"]
        assert "# TYPE multigrad_step_seconds histogram" \
            in captured["metrics"]
        # after the fit: done, ETA pinned to zero, healthz up
        final = json.load(urllib.request.urlopen(live.url + "/status"))
        assert final["phase"] == "done" and final["eta_s"] == 0.0
        assert urllib.request.urlopen(
            live.url + "/healthz").read() == b"ok\n"
        assert traj.shape == (13, 2)
    finally:
        scraped.set()
        live.stop()
    assert live.url is None              # stopped servers report it


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_model_fit_with_live_only_wires_a_logger():
    # live= with NO telemetry logger: the driver creates (and closes)
    # one internally; the sink still sees the whole stream, comm
    # record included.
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(2048, comm=comm), comm=comm)
    sink = LiveSink()
    model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=6,
                   progress=False, live=sink, log_every=2)
    drain()
    st = sink.status()
    assert st["phase"] == "done"
    assert st["comm_bytes_per_step"] == 48
    assert st["step"] is not None and np.isfinite(st["loss"])


# ------------------------------------------------------------------ #
# Dashboard: --once render + the follow tail reader
# ------------------------------------------------------------------ #
def _write_demo_stream(path):
    logger = telemetry.MetricsLogger(telemetry.JsonlSink(str(path)),
                                     run_config={"demo": True})
    logger.log("fit_plan", kind="adam_scan", nsteps=40)
    for k in range(8):
        logger.log("adam", step=5 * k, loss=4.0 / (k + 1),
                   grad_norm=1.0 / (k + 1), loss_ema=4.0 / (k + 1),
                   loss_ema_slope=-0.01)
    logger.log("comm", bytes_per_step=48, calls_per_step=2)
    logger.log("hmc", step=20, accept=0.85, divergences=[1, 0],
               step_size=[0.1, 0.2])
    logger.log("stall", stalled_s=2.0)
    logger.log("resource_sample", rss_bytes=512 * 1024 * 1024,
               busy_frac=0.75, device_bytes_in_use=None,
               compile_count=3, compile_s_total=2.5)
    logger.log("alert", rule="loss_plateau",
               message="loss EMA has plateaued", step=30)
    logger.close()


def test_dashboard_once_renders_structure(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    _write_demo_stream(path)
    assert dash_mod.main([str(path), "--once"]) == 0
    out = capsys.readouterr().out
    # golden-ish: structure, not exact bytes
    assert "step 35/40" in out
    assert "loss" in out and "|grad|" in out and "ema" in out
    assert any(ch in out for ch in dash_mod.SPARK_CHARS)
    assert "steps/s" in out and "ETA" in out
    assert "comm 48 B/step" in out
    assert "hmc  draw 20" in out and "divergences=1" in out
    # the PR-18 resource line: RSS + duty cycle + compile accounting
    # (device field None on the CPU stream -> simply absent)
    assert "res  rss 512.0MiB  busy 75%  compiles 3 (2.5s)" in out
    assert "STALL" in out
    assert "ALERT [loss_plateau]" in out
    assert "records:" in out
    # missing file is a clean error, not a traceback
    assert dash_mod.main([str(tmp_path / "nope.jsonl"), "--once"]) == 1


def test_dashboard_follow_renders_frames(tmp_path, capsys):
    path = tmp_path / "run.jsonl"
    _write_demo_stream(path)
    # the hidden test hook bounds the loop; stdout is not a tty here,
    # so frames are separated by --- instead of cursor control
    assert dash_mod.main([str(path), "--follow", "--interval", "0.01",
                          "--max-frames", "2"]) == 0
    out = capsys.readouterr().out
    assert out.count("records:") == 2


def test_dashboard_resets_fit_state_at_fit_plan_boundary():
    # A second fit through the same logger must not inherit the first
    # fit's summary ("done"/ETA 0) or stitch its loss series, and the
    # follow path must be incrementally feedable record-by-record.
    collector = dash_mod.Collector()
    collector.feed([{"event": "run", "t": 0.0},
                    {"event": "fit_plan", "t": 0.0, "nsteps": 100}])
    for k in range(5):
        collector.feed([{"event": "adam", "t": 0.1 * k, "step": k,
                         "loss": 5.0 - k}])
    collector.feed([{"event": "fit_summary", "t": 1.0, "steps": 100,
                     "final_loss": 1.0}])
    assert collector.view()["eta_s"] == 0.0
    # fit 2 begins: fresh plan, one step in
    collector.feed([{"event": "fit_plan", "t": 2.0, "nsteps": 50},
                    {"event": "adam", "t": 2.0, "step": 0,
                     "loss": 9.0},
                    {"event": "adam", "t": 2.5, "step": 10,
                     "loss": 8.0}])
    view = collector.view()
    assert view["summary"] is None          # fit 1's "done" is gone
    assert view["nsteps"] == 50
    assert view["loss"] == [9.0, 8.0]       # no stitched series
    assert view["eta_s"] is not None and view["eta_s"] > 0
    out = dash_mod.render(view)
    assert "done" not in out and "step 10/50" in out
    # memory stays bounded under a long follow
    for k in range(2000):
        collector.feed([{"event": "adam", "t": 3.0 + 0.1 * k,
                         "step": k, "loss": 1.0}])
    assert len(collector.loss) <= 512


def test_dashboard_rate_pairs_timestamps_with_steps():
    # t-less records must not mismatch the (t, step) rate endpoints
    c = dash_mod.Collector()
    c.feed([{"event": "fit_plan", "nsteps": 100}])
    for k in range(10):
        c.feed([{"event": "adam", "step": k, "loss": 1.0,
                 "t": float(k) if k % 2 == 0 else None}])
    view = c.view()
    assert view["steps_per_sec"] == pytest.approx(1.0)   # true rate
    assert view["eta_s"] == pytest.approx(90.0)


def test_default_rules_route_rule_specific_overrides():
    rules = alerts_mod.default_rules(escalate=True, rel_slope=1e-3)
    assert all(r.escalate for r in rules)                 # global knob
    plateau = [r for r in rules if isinstance(r, LossPlateau)][0]
    assert plateau.rel_slope == 1e-3                      # routed knob
    assert len(rules) == 5


def test_live_sink_stall_flag_resets_on_new_fit():
    sink = LiveSink()
    sink.write({"event": "fit_plan", "t": 0.0, "nsteps": 10})
    sink.write({"event": "stall", "t": 1.0, "stalled_s": 9.0})
    assert sink.status(now=2.0)["stalled"] is True
    # fit aborted mid-stall; a NEW fit through the same (long-lived)
    # server must not report the dead fit's stall forever
    sink.write({"event": "fit_plan", "t": 3.0, "nsteps": 10})
    st = sink.status(now=4.0)
    assert st["stalled"] is False
    assert st["stalls"] == 1                # the counter is history


def test_tail_reader_never_parses_partial_lines(tmp_path):
    path = tmp_path / "run.jsonl"
    reader = TailReader(str(path))
    assert reader.poll() == []           # not created yet
    with open(path, "w") as f:
        f.write('{"event":"adam","step":0}\n{"event":"adam"')
        f.flush()
    # the torn tail stays buffered — never parsed, never dropped
    assert [r["step"] for r in reader.poll()] == [0]
    assert reader.poll() == []
    with open(path, "a") as f:
        f.write(',"step":1}\n')
    assert [r["step"] for r in reader.poll()] == [1]
    # truncation/rotation resets to the top
    with open(path, "w") as f:
        f.write('{"event":"adam","step":9}\n')
    assert [r["step"] for r in reader.poll()] == [9]


def test_jsonl_sink_is_line_atomic_for_followers(tmp_path):
    # satellite: flush-per-record (unbuffered single-write lines) so a
    # live tail sees each record the moment write() returns; fsync
    # knob accepted; the truncated-tail repair also covers the follow
    # path (the torn line is skipped, later records parse).
    path = str(tmp_path / "run.jsonl")
    sink = telemetry.JsonlSink(path, fsync=True)
    reader = TailReader(path)
    sink.write({"event": "x", "i": 1})
    assert [r["i"] for r in reader.poll()] == [1]   # no close needed
    sink.write({"event": "x", "i": 2})
    assert [r["i"] for r in reader.poll()] == [2]
    sink.close()
    with open(path, "a") as f:
        f.write('{"event":"x","i":3')               # crash mid-record
    assert reader.poll() == []
    sink2 = telemetry.JsonlSink(path)               # repairs the tail
    sink2.write({"event": "x", "i": 4})
    sink2.close()
    assert [r["i"] for r in reader.poll()] == [4]   # torn line skipped
    # the offline reader agrees
    assert [r["i"] for r in report_mod.load_records(path)] \
        == [1, 2, 4]


# ------------------------------------------------------------------ #
# Alert rules: fire on injected triggers, quiet on clean fits
# ------------------------------------------------------------------ #
def _engine_with(rule):
    engine = AlertEngine(rules=[rule])
    logger, sink = new_logger(engine)
    engine.bind_logger(logger)
    return engine, logger, sink


def test_loss_plateau_fires_on_flat_loss_only():
    engine, logger, sink = _engine_with(
        LossPlateau(min_records=5, patience=2))
    for k in range(20):                      # healthy: loss falling
        logger.log("adam", step=k, loss=4.0 * 0.8 ** k)
    assert engine.alerts == []
    # a new fit (fit_plan resets rule state) that sits flat from the
    # start: the EMA goes motionless and the rule must fire ONCE
    logger.log("fit_plan", kind="adam_scan", nsteps=100)
    for k in range(30):
        logger.log("adam", step=k, loss=0.5)
    fired = events(sink, "alert")
    assert len(fired) == 1                   # rising edge, no flood
    assert fired[0]["rule"] == "loss_plateau"
    assert abs(fired[0]["ema_slope"]) < fired[0]["slope_limit"]


def test_grad_explosion_fires_and_rearms():
    engine, logger, sink = _engine_with(GradExplosion(factor=50.0))
    for k in range(10):
        logger.log("adam", step=k, loss=1.0, grad_norm=1.0)
    assert engine.alerts == []
    logger.log("adam", step=10, loss=1.0, grad_norm=1e5)   # spike
    logger.log("adam", step=11, loss=1.0, grad_norm=1.0)   # recovers
    logger.log("adam", step=12, loss=1.0, grad_norm=1e5)   # again
    fired = events(sink, "alert")
    assert [a["rule"] for a in fired] == ["grad_explosion"] * 2
    assert fired[0]["grad_norm"] == 1e5


def test_throughput_drop_fires_on_rate_collapse():
    engine, logger, sink = _engine_with(ThroughputDrop(frac=0.5))
    t0 = 1000.0
    engine.write({"event": "adam", "t": t0, "step": 0})
    for k in range(1, 10):                   # steady 100 steps/s
        engine.write({"event": "adam", "t": t0 + 0.1 * k,
                      "step": 10 * k})
    assert engine.alerts == []
    engine.write({"event": "adam", "t": t0 + 0.9 + 5.0,
                  "step": 100})              # 2 steps/s: collapsed
    assert [a["rule"] for a in engine.alerts] == ["throughput_drop"]
    assert engine.alerts[0]["steps_per_sec"] < 0.5 * 100


def test_divergence_rate_fires_above_threshold():
    engine, logger, sink = _engine_with(
        DivergenceRate(max_rate=0.1, min_draws=20))
    logger.log("hmc", step=10, accept=0.8, divergences=0)
    logger.log("hmc", step=20, accept=0.8, divergences=1)
    assert engine.alerts == []
    logger.log("hmc", step=30, accept=0.3, divergences=[6, 4])
    fired = events(sink, "alert")
    assert [a["rule"] for a in fired] == ["divergence_rate"]
    assert fired[0]["rate"] > 0.1


def test_heartbeat_stall_alert_follows_episodes():
    engine, logger, sink = _engine_with(HeartbeatStall())
    logger.log("heartbeat", step=5)
    logger.log("stall", step=5, stalled_s=9.0)
    logger.log("heartbeat", step=5)          # still stalled: no flood
    logger.log("stall_recovered", step=6)
    logger.log("stall", step=9, stalled_s=4.0)
    fired = events(sink, "alert")
    assert [a["rule"] for a in fired] == ["heartbeat_stall"] * 2
    assert fired[0]["stalled_s"] == 9.0


def test_alert_escalates_to_flight_recorder(tmp_path):
    recorder = telemetry.FlightRecorder(dump_dir=str(tmp_path))
    engine = AlertEngine(
        rules=[GradExplosion(factor=50.0, escalate=True)],
        flight=recorder)
    seen = []
    engine.on_alert = seen.append
    logger, sink = new_logger(engine)
    engine.bind_logger(logger)
    for k in range(8):
        logger.log("adam", step=k, grad_norm=1.0)
    logger.log("adam", step=8, grad_norm=1e6)
    # non-fatal: bundle dumped, nothing raises, fit would continue
    assert recorder.bundle_path is not None and not recorder.fatal
    bundle = json.load(open(recorder.bundle_path))
    assert bundle["reason"] == "alert_grad_explosion"
    assert seen and seen[0]["rule"] == "grad_explosion"
    # the alert record reached the OTHER sinks through the logger
    # (the re-entrant emit contract)
    assert [a["rule"] for a in events(sink, "alert")] \
        == ["grad_explosion"]


def test_broken_rule_is_disabled_not_fatal():
    class Broken(alerts_mod.AlertRule):
        name = "broken"

        def check(self, record):
            if record.get("event") != "adam":
                return None          # breaks once real records flow
            raise RuntimeError("boom")

    engine = AlertEngine(rules=[Broken(), GradExplosion()])
    logger, sink = new_logger(engine)
    engine.bind_logger(logger)
    for k in range(10):
        logger.log("adam", step=k, grad_norm=1.0)
    logger.log("adam", step=10, grad_norm=1e6)
    fired = events(sink, "alert")
    # one error report for the broken rule, then it stays out of the
    # way; the healthy rule still fires
    assert [a["rule"] for a in fired] == ["broken", "grad_explosion"]
    assert fired[0]["severity"] == "error"


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_alert_rules_stay_quiet_on_clean_mesh_fit():
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(2048, comm=comm), comm=comm)
    engine = AlertEngine()                   # full default rule set
    logger, sink = new_logger()
    model.run_adam(guess=ParamTuple(-1.0, 0.5), nsteps=20,
                   progress=False, telemetry=logger, log_every=5,
                   alerts=engine)
    drain()
    assert engine.alerts == []
    assert events(sink, "alert") == []


# ------------------------------------------------------------------ #
# In-graph convergence diagnostics
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_gradient_noise_scale_tap_matches_hand_computation():
    comm = mgt.global_comm()
    n_halos = 4096
    model = SMFModel(aux_data=make_smf_data(n_halos, comm=comm),
                     comm=comm)
    logger, sink = new_logger()
    guess = jnp.array([-1.0, 0.5])
    model.run_adam(guess=guess, nsteps=1, progress=False,
                   telemetry=logger, log_every=1, diagnostics=True)
    drain()
    rec = events(sink, "adam")[0]
    assert {"grad_noise_scale", "grad_norm_shard", "loss_ema",
            "loss_ema_slope"} <= set(rec)

    # Hand computation: per-shard local gradients g_r = J_rᵀ (dL/dy)
    # via single-device models over each shard's contiguous rows (the
    # scatter_nd layout), cotangent taken at the TOTAL sumstats.
    full = SMFModel(aux_data=make_smf_data(n_halos, comm=None),
                    comm=None)
    y_total = full.calc_partial_sumstats_from_params(guess)
    dL_dy = jax.grad(full.calc_loss_from_sumstats)(y_total)
    rows = np.asarray(full.aux_data["log_halo_masses"])
    size = comm.size
    g_rs = []
    for r in range(size):
        aux_r = dict(full.aux_data)
        aux_r["log_halo_masses"] = jnp.asarray(
            rows[r * n_halos // size:(r + 1) * n_halos // size])
        _, vjp = jax.vjp(
            SMFModel(aux_data=aux_r,
                     comm=None).calc_partial_sumstats_from_params,
            guess)
        g_rs.append(np.asarray(vjp(dL_dy)[0]))
    g_rs = np.stack(g_rs)
    g_total = g_rs.sum(0)
    mean_sq = float(np.mean(np.sum(g_rs ** 2, -1)))
    sq_mean = float(np.sum((g_total / size) ** 2))
    gns_hand = max(mean_sq - sq_mean, 0.0) / (sq_mean + GNS_EPS)

    assert rec["grad_noise_scale"] == pytest.approx(gns_hand,
                                                    rel=1e-4)
    assert rec["grad_norm_shard"] == pytest.approx(
        float(np.sqrt(mean_sq)), rel=1e-4)
    assert rec["grad_norm"] == pytest.approx(
        float(np.linalg.norm(g_total)), rel=1e-4)
    # step 0: the bias-corrected EMA equals the loss; slope defined 0
    assert rec["loss_ema"] == pytest.approx(rec["loss"], rel=1e-5)
    assert rec["loss_ema_slope"] == 0.0


def test_diagnostics_taps_add_zero_extra_retraces():
    # Same assertion shape as the PR-3 tap tests: the traced-fn
    # counter must not move between repeat diagnostics fits, and
    # enabling diagnostics costs the same single trace as any build.
    target = jnp.array([1.0, -2.0])
    traces = []

    def loss_and_grad(p, _key):
        traces.append(1)
        diff = p - target
        return jnp.sum(diff ** 2), 2.0 * diff

    run_adam_scan(loss_and_grad, jnp.zeros(2), nsteps=20,
                  learning_rate=0.1)
    baseline = len(traces)

    logger, sink = new_logger()
    traces.clear()
    run_adam_scan(loss_and_grad, jnp.zeros(2), nsteps=20,
                  learning_rate=0.1, telemetry=logger, log_every=5,
                  diagnostics=True)
    drain()
    assert len(traces) == baseline          # one build, like untapped
    recs = events(sink, "adam")
    assert [r["step"] for r in recs] == [0, 5, 10, 15]
    assert all("loss_ema" in r and "loss_ema_slope" in r
               for r in recs)
    # EMA tracks the loss downward; slopes are negative once warmed
    assert recs[-1]["loss_ema"] < recs[0]["loss_ema"]
    assert recs[-1]["loss_ema_slope"] < 0
    # repeat fit through the same logger: ZERO additional traces
    run_adam_scan(loss_and_grad, jnp.ones(2), nsteps=20,
                  learning_rate=0.1, telemetry=logger, log_every=5,
                  diagnostics=True)
    drain()
    assert len(traces) == baseline


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_gns_program_cached_across_fits():
    comm = mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(2048, comm=comm), comm=comm)
    logger, sink = new_logger()
    kwargs = dict(guess=ParamTuple(-1.0, 0.5), nsteps=4,
                  progress=False, telemetry=logger, log_every=2,
                  diagnostics=True)
    model.run_adam(**kwargs)
    wrapper = model._program_cache[
        ("adam_scan_wrapper", False, "loss_and_grad_gns")]
    n_programs = len(wrapper._mgt_program_cache)
    assert n_programs == 1
    model.run_adam(**kwargs)                 # same logger: cache hit
    drain()
    assert len(wrapper._mgt_program_cache) == n_programs
    assert len(events(sink, "adam")) == 2 * 2


# ------------------------------------------------------------------ #
# HMC + live wiring
# ------------------------------------------------------------------ #
@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_hmc_live_status_and_divergence_view():
    comm = mgt.global_comm()
    model = SMFChi2Model(aux_data=make_smf_data(2048, comm=comm),
                         comm=comm)
    sink = LiveSink()
    res = mgt.run_hmc(model, jnp.array([-2.0, 0.2]), num_samples=20,
                      num_warmup=10, num_chains=2, num_leapfrog=3,
                      live=sink, log_every=10, randkey=3)
    drain()
    st = sink.status()
    assert st["fit_kind"] == "hmc" and st["nsteps"] == 20
    assert st["step"] == 20
    assert st["hmc"]["divergences"] == int(np.sum(res.divergences))
    assert "multigrad_hmc_accept" in sink.metrics.render()
    # the closing fit_summary flips the live view to done/ETA 0
    assert st["phase"] == "done" and st["eta_s"] == 0.0
    assert st["fit_summary"]["divergences"] \
        == int(np.sum(res.divergences))


# ------------------------------------------------------------------ #
# Report satellite: multi-run selection
# ------------------------------------------------------------------ #
def test_report_run_selection_and_listing(tmp_path, capsys):
    path = str(tmp_path / "runs.jsonl")
    for first, last in [(5.0, 4.0), (9.0, 8.0)]:
        logger = telemetry.MetricsLogger(telemetry.JsonlSink(path))
        logger.log("adam", step=0, loss=first)
        logger.log("adam", step=10, loss=last)
        logger.close()
    records = report_mod.load_records(path)
    # --run selects; negative counts from the end; default = last
    s1 = report_mod.summarize(records, run=1)
    assert s1["run_index"] == 1 and s1["fit"]["final_loss"] == 4.0
    s2 = report_mod.summarize(records, run=-1)
    assert s2["run_index"] == 2 and s2["fit"]["final_loss"] == 8.0
    assert report_mod.summarize(records)["fit"]["final_loss"] == 8.0
    with pytest.raises(IndexError):
        report_mod.summarize(records, run=3)
    with pytest.raises(IndexError):
        report_mod.summarize(records, run=0)
    # CLI: --run renders the selected run and says so
    assert report_mod.main([path, "--run", "1"]) == 0
    out = capsys.readouterr().out
    assert "summarizing run 1" in out and "5 -> 4" in out
    assert report_mod.main([path, "--run", "5"]) == 1   # out of range
    capsys.readouterr()
    # --list-runs: one row per run
    assert report_mod.main([path, "--list-runs"]) == 0
    out = capsys.readouterr().out
    assert "run 1:" in out and "run 2:" in out
    assert report_mod.main([path, "--list-runs", "--json"]) == 0
    listing = json.loads(capsys.readouterr().out)
    assert [r["run"] for r in listing["runs"]] == [1, 2]
    assert listing["runs"][0]["final_loss"] == 4.0
