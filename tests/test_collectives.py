"""Collectives & topology tests.

Ports the reference's ``test_reduce_sum`` (``tests/test_mpi.py:19-35``
— each rank contributes its rank id; everyone must see the total) and
adds coverage for scatter/all_gather/subcomm-splitting that the
reference exercised only implicitly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

import multigrad_tpu as mgt
from multigrad_tpu.parallel._shard_map_compat import shard_map


@pytest.fixture(scope="module")
def comm():
    return mgt.global_comm()


def test_device_count():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"


def test_reduce_sum_identity_without_comm():
    # Parity: comm=None is the single-process identity
    # (reference multigrad.py:168-169).
    value = jnp.arange(5.0)
    assert mgt.reduce_sum(value, comm=None) is value


def test_reduce_sum_sharded_contributions(comm):
    # Each device contributes its index (the MPI test's "each rank
    # contributes its rank", test_mpi.py:19-35).
    value = mgt.scatter_nd(jnp.arange(comm.size, dtype=jnp.float32),
                           comm=comm)
    total = mgt.reduce_sum(value, comm=comm)
    expected = np.arange(comm.size).sum()
    np.testing.assert_allclose(np.asarray(total), [expected])


def test_reduce_sum_replicated_matches_mpi_semantics(comm):
    # MPI.Allreduce of identical buffers returns size * value.
    total = mgt.reduce_sum(jnp.float32(2.0), comm=comm)
    assert total == 2.0 * comm.size


def test_reduce_sum_scalar_round_trip(comm):
    # Scalars round-trip through arrays (reference multigrad.py:170-183).
    out = mgt.reduce_sum(3.0, comm=comm)
    assert np.isclose(out, 3.0 * comm.size)
    assert np.ndim(out) == 0


def test_reduce_sum_inside_graph(comm):
    # The in-graph path: reduce_sum under shard_map is lax.psum.
    def f(x):
        return mgt.reduce_sum(x, comm=comm)

    x = mgt.scatter_nd(jnp.arange(8.0), comm=comm)
    out = jax.jit(shard_map(
        f, mesh=comm.mesh, in_specs=PartitionSpec(comm.axis_name),
        out_specs=PartitionSpec()))(x)
    np.testing.assert_allclose(np.asarray(out), [28.0])


def test_scatter_nd_shards_evenly(comm):
    arr = np.arange(32.0).reshape(16, 2)
    sharded = mgt.scatter_nd(arr, axis=0, comm=comm)
    assert isinstance(sharded.sharding, NamedSharding)
    np.testing.assert_array_equal(np.asarray(sharded), arr)
    # Each device holds 2 rows
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(2, 2)}


def test_scatter_nd_rejects_ragged(comm):
    # Without a pad convention a ragged axis must fail loudly (no
    # universally sumstat-neutral filler exists) and the error must
    # name the remedy.
    with pytest.raises(ValueError,
                       match="not divisible.*pad_value"):
        mgt.scatter_nd(np.arange(10.0), comm=comm)


def test_scatter_nd_ragged_pad_value(comm):
    # The reference's scatter_nd accepts any length (np.array_split,
    # util.py:65-77); pad_value= restores that contract under XLA's
    # equal-shards constraint.
    sharded = mgt.scatter_nd(np.arange(10.0), comm=comm,
                             pad_value=np.inf)
    assert sharded.shape == (16,)
    np.testing.assert_array_equal(np.asarray(sharded)[:10],
                                  np.arange(10.0))
    assert np.all(np.isinf(np.asarray(sharded)[10:]))
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(2,)}
    # pad_value on an already-even axis is a no-op
    even = mgt.scatter_nd(np.arange(8.0), comm=comm, pad_value=np.inf)
    assert even.shape == (8,)


def test_scatter_nd_exposes_pad_count(comm):
    # Regression: the pad count used to be computed and discarded;
    # callers (e.g. the streaming chunk planner) need it to mask
    # padded rows without re-deriving the pad arithmetic.
    sharded, pad = mgt.scatter_nd(np.arange(10.0), comm=comm,
                                  pad_value=np.inf,
                                  return_pad_count=True)
    assert pad == 6
    assert sharded.shape == (16,)
    assert np.all(np.isinf(np.asarray(sharded)[10:]))
    # Evenly divisible: zero pad, same tuple contract.
    even, pad0 = mgt.scatter_nd(np.arange(16.0), comm=comm,
                                return_pad_count=True)
    assert pad0 == 0 and even.shape == (16,)
    # comm=None identity path keeps the contract too.
    solo, padn = mgt.scatter_nd(np.arange(3.0), comm=None,
                                return_pad_count=True)
    assert padn == 0 and solo.shape == (3,)
    # Default signature unchanged: a bare array comes back.
    bare = mgt.scatter_nd(np.arange(16.0), comm=comm)
    assert not isinstance(bare, tuple)


def test_scatter_nd_ragged_axis1(comm):
    sharded = mgt.scatter_nd(np.ones((2, 5)), axis=1, comm=comm,
                             pad_value=0.0)
    assert sharded.shape == (2, 8)
    assert float(np.asarray(sharded).sum()) == 10.0


def test_ragged_catalog_sumstats_match_unsharded(comm):
    # End-to-end pad neutrality: a catalog whose size does not divide
    # the mesh must produce the SAME sumstats sharded as unsharded —
    # the inf pad's erf-CDF contribution is exactly zero.
    from multigrad_tpu.models import SMFModel, make_smf_data

    n = 1003  # 1003 % 8 = 3: forces 5 pad halos
    assert n % comm.size
    params = (-1.9, 0.23)
    solo = SMFModel(aux_data=make_smf_data(n, comm=None), comm=None)
    sharded = SMFModel(aux_data=make_smf_data(n, comm=comm), comm=comm)
    np.testing.assert_allclose(
        np.asarray(solo.calc_sumstats_from_params(params)),
        np.asarray(sharded.calc_sumstats_from_params(params)),
        rtol=1e-6)


def test_pad_to_multiple():
    from multigrad_tpu.utils import pad_to_multiple
    padded, n = pad_to_multiple(np.arange(10.0), 8, pad_value=np.inf)
    assert n == 10
    assert padded.shape == (16,)
    assert np.all(np.isinf(np.asarray(padded[10:])))


def test_split_subcomms_even(comm):
    subcomms, num_groups, my_group = mgt.split_subcomms(num_groups=2,
                                                        comm=comm)
    assert num_groups == 2
    assert len(subcomms) == 2
    assert [sc.size for sc in subcomms] == [4, 4]
    assert my_group == 0
    # Disjoint device sets covering the communicator
    all_devs = {d for sc in subcomms for d in sc.devices}
    assert all_devs == set(comm.devices)


def test_split_subcomms_uneven_never_empty(comm):
    # Regression: 8 devices into 5 groups must follow the reference's
    # array_split rule — sizes [1, 1, 2, 2, 2], no empty groups.
    subcomms, num_groups, _ = mgt.split_subcomms(num_groups=5, comm=comm)
    assert num_groups == 5
    assert [sc.size for sc in subcomms] == [1, 1, 2, 2, 2]


def test_split_subcomms_explicit_sizes(comm):
    subcomms, num_groups, _ = mgt.split_subcomms(
        ranks_per_group=[2, 6], comm=comm)
    assert num_groups == 2
    assert [sc.size for sc in subcomms] == [2, 6]


def test_split_subcomms_validates():
    comm = mgt.global_comm()
    # Explicit ValueError (not assert) so validation survives -O.
    with pytest.raises(ValueError):
        mgt.split_subcomms(num_groups=2, ranks_per_group=[4, 4], comm=comm)
    with pytest.raises(ValueError):
        mgt.split_subcomms(ranks_per_group=[4, 5], comm=comm)


def test_split_subcomms_by_node(comm):
    # Single host: one group holding every device.
    subcomms, num_groups, my_group = mgt.split_subcomms_by_node(comm)
    assert num_groups == 1
    assert my_group == 0
    assert subcomms[0].size == comm.size


def test_subcomm_collective_scoped(comm):
    # A collective over a subcomm must only reduce that group's devices.
    subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
    sub = subcomms[1]
    value = mgt.scatter_nd(jnp.arange(sub.size, dtype=jnp.float32),
                           comm=sub)
    total = mgt.reduce_sum(value, comm=sub)
    np.testing.assert_allclose(np.asarray(total),
                               [np.arange(sub.size).sum()])


def test_all_gather_inside_graph(comm):
    # The gathered value is a shard-local full copy ("varying" in vma
    # terms); stack per-device results to inspect every copy.
    def f(x):
        return mgt.all_gather(x, comm=comm)[None]

    x = mgt.scatter_nd(jnp.arange(8.0), comm=comm)
    out = jax.jit(shard_map(
        f, mesh=comm.mesh, in_specs=PartitionSpec(comm.axis_name),
        out_specs=PartitionSpec(comm.axis_name)))(x)
    assert out.shape == (8, 8)
    np.testing.assert_allclose(np.asarray(out),
                               np.tile(np.arange(8.0), (8, 1)))
