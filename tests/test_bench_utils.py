"""Pin bench.py's measurement-protocol helpers.

The benchmark's numbers are only as good as its protocol
(BENCH_NOTES.md §1); these tests keep the RTT-floor subtraction and
its refuse-to-eat-signal clamp from silently regressing.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def test_sub_rtt_subtracts_floor():
    assert bench._sub_rtt(1.0, 0.1) == 0.9


def test_sub_rtt_refuses_to_eat_signal(capsys):
    # rtt > 50% of the measurement: report the raw time (and say so on
    # stderr) instead of producing a near-zero or negative duration.
    assert bench._sub_rtt(0.1, 0.08) == 0.1
    assert "unsubtracted" in capsys.readouterr().err


def test_measure_fetch_rtt_positive():
    rtt = bench.measure_fetch_rtt()
    assert 0.0 < rtt < 5.0  # CPU backend: microseconds to ms


import time  # noqa: E402


def test_partial_dossier_roundtrip(tmp_path, monkeypatch):
    # The incremental dossier must survive a kill/re-run: what was
    # saved comes back verbatim, including deliberate nulls (key
    # presence means "measured", even when the value is None).
    monkeypatch.setattr(bench, "PARTIAL_TEMPLATE",
                        str(tmp_path / "partial.{backend}.json"))
    cfgs = {"smf_1e6_xla_steps_per_sec": 4446.0,
            "smf_1e6_pallas_steps_per_sec": None}
    now = time.time()
    bench.save_partial("tpu", cfgs, {k: now for k in cfgs})
    loaded, times = bench.load_partial("tpu")
    assert loaded == cfgs
    assert "smf_1e6_pallas_steps_per_sec" in loaded
    assert set(times) == set(cfgs)


def test_partial_dossier_per_backend_isolation(tmp_path, monkeypatch):
    # A CPU-fallback run while the tunnel is down must never clobber
    # the TPU dossier it exists to protect: the two backends persist
    # to different files.
    monkeypatch.setattr(bench, "PARTIAL_TEMPLATE",
                        str(tmp_path / "partial.{backend}.json"))
    now = time.time()
    bench.save_partial("tpu", {"smf_1e6_xla_steps_per_sec": 4446.0},
                       {"smf_1e6_xla_steps_per_sec": now})
    bench.save_partial("cpu", {"smf_1e6_xla_steps_per_sec": 20.0},
                       {"smf_1e6_xla_steps_per_sec": now})
    assert bench.load_partial(
        "tpu")[0]["smf_1e6_xla_steps_per_sec"] == 4446.0
    assert bench.load_partial(
        "cpu")[0]["smf_1e6_xla_steps_per_sec"] == 20.0


def test_partial_dossier_expires_stale_entries(tmp_path, monkeypatch,
                                               capsys):
    # The cache is a crash-resume aid within a round, not an archive:
    # a completed dossier from a previous round (entries older than
    # MAX_PARTIAL_AGE_S) must be re-measured, not replayed as fresh
    # evidence.
    monkeypatch.setattr(bench, "PARTIAL_TEMPLATE",
                        str(tmp_path / "partial.{backend}.json"))
    now = time.time()
    bench.save_partial(
        "tpu",
        {"old_cfg": 1.0, "new_cfg": 2.0},
        {"old_cfg": now - bench.MAX_PARTIAL_AGE_S - 60, "new_cfg": now})
    loaded, _ = bench.load_partial("tpu")
    assert loaded == {"new_cfg": 2.0}
    assert "expiring" in capsys.readouterr().err


def test_partial_dossier_missing_or_corrupt(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "PARTIAL_TEMPLATE",
                        str(tmp_path / "nope.{backend}.json"))
    assert bench.load_partial("tpu") == ({}, {})
    (tmp_path / "nope.tpu.json").write_text("{not json")
    assert bench.load_partial("tpu") == ({}, {})


def test_bench_constants_consistent():
    # The chunk must divide the big config (the XLA chunked path
    # requires it) and the headline region must dwarf any plausible
    # tunnel floor (>=10x of 100 ms at the slowest measured rate).
    assert bench.BIG_HALOS % bench.BIG_CHUNK == 0
    assert bench.NSTEPS >= 3000
