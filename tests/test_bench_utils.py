"""Pin bench.py's measurement-protocol helpers.

The benchmark's numbers are only as good as its protocol
(BENCH_NOTES.md §1); these tests keep the RTT-floor subtraction and
its refuse-to-eat-signal clamp from silently regressing.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def test_sub_rtt_subtracts_floor():
    assert bench._sub_rtt(1.0, 0.1) == 0.9


def test_sub_rtt_refuses_to_eat_signal(capsys):
    # rtt > 50% of the measurement: report the raw time (and say so on
    # stderr) instead of producing a near-zero or negative duration.
    assert bench._sub_rtt(0.1, 0.08) == 0.1
    assert "unsubtracted" in capsys.readouterr().err


def test_measure_fetch_rtt_positive():
    rtt = bench.measure_fetch_rtt()
    assert 0.0 < rtt < 5.0  # CPU backend: microseconds to ms


def test_bench_constants_consistent():
    # The chunk must divide the big config (the XLA chunked path
    # requires it) and the headline region must dwarf any plausible
    # tunnel floor (>=10x of 100 ms at the slowest measured rate).
    assert bench.BIG_HALOS % bench.BIG_CHUNK == 0
    assert bench.NSTEPS >= 3000
