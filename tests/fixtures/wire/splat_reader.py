"""Seeded wire-protocol bugs.

Deliberately NOT part of the package tree: scanned by
``tests/test_wireschema.py`` via ``extract_schema(root=...)``.

* ``thing_from_wire`` splats the wire dict into a constructor — the
  known-keys-only violation (``wire-reader-splat``): a newer peer's
  extra field becomes an unexpected-keyword crash instead of being
  ignored.
* ``frame_from_wire`` requires key ``"t"`` that ``frame_to_wire``
  never writes (``wire-key-asymmetry``): every decode of a real
  message raises KeyError.
"""


class Thing:
    def __init__(self, a=None, b=None):
        self.a = a
        self.b = b


class Frame:
    def __init__(self, seq, t=None):
        self.seq = seq
        self.t = t


def thing_to_wire(thing) -> dict:
    return {"a": thing.a, "b": thing.b}


def thing_from_wire(d) -> Thing:
    return Thing(**d)


def frame_to_wire(frame) -> dict:
    return {"seq": frame.seq}


def frame_from_wire(d) -> Frame:
    return Frame(seq=d["seq"], t=d["t"])
