"""Seeded settlement-ordering bugs (the PR-13 bug class).

Deliberately NOT part of the package tree: scanned by
``tests/test_settlement.py`` via ``analyze_settlement(root=...)`` to
prove each check flags its intended shape.

The seeded findings, by check id:

* ``settle-root-after-resolve`` — ``settle_ok`` records the trace
  root and the dispatch counter AFTER ``_set_result``: exactly the
  shape PR 13 needed three review passes to purge (a caller waking
  on ``result()`` raced the accounting).
* ``settle-under-lock`` — ``settle_under_lock`` resolves while
  holding the owning lock, so the woken waiters' callbacks run
  inside it.
* ``settle-double`` — ``settle_twice`` settles the same future twice
  unconditionally on one path.
* ``settle-orphan`` — ``orphan`` mints a future and drops it.
* ``settle-first-wins`` — ``UnguardedFuture`` lacks the
  already-settled early-return both terminal setters need.
* ``settle-allowlist`` — one unknown-check annotation, one with no
  justification; plus a VALID suppression (``allowed_under_lock``)
  that must be consumed without a stale warning.
"""
import threading


class UnguardedFuture:
    """A future whose terminal setters lack the first-wins guard."""

    def __init__(self):
        self._cond = threading.Condition()
        self._result = None
        self._exception = None

    def _set_result(self, result):
        with self._cond:
            self._result = result
            self._cond.notify_all()

    def _set_exception(self, err):
        with self._cond:
            self._exception = err
            self._cond.notify_all()


class BuggyScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}

    def _trace_root(self, req, outcome):
        req.outcome = outcome

    def _count(self, kind):
        self._counts[kind] = self._counts.get(kind, 0) + 1

    def settle_ok(self, req, result):
        req.future._set_result(result)
        # Too late on both lines: the caller is already awake.
        self._trace_root(req, "ok")
        self._count("ok")

    def settle_under_lock(self, req, err):
        with self._lock:
            req.future._set_exception(err)

    def allowed_under_lock(self, req, err):
        with self._lock:
            req.future._set_exception(err)  # settle-ok: settle-under-lock fixture: a justified suppression the verifier must mark used

    def settle_twice(self, req, result):
        req.future._set_result(result)
        req.future._set_exception(RuntimeError("also failed"))

    def orphan(self, job_id):
        fut = UnguardedFuture()

    def bad_annotations(self, req):
        req.touch()  # settle-ok: not-a-real-check bogus id
        req.touch()  # settle-ok: settle-double
