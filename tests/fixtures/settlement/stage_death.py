"""Seeded unrecorded-stage-death bug (the PR-16 bug class).

A stage worker thread that settles its future only at the END of a
body with no broad exception backstop: any raise in ``job.run()``
kills the thread silently and the obligation never settles — the
job hangs forever, unrecorded.  ``analyze_settlement`` must flag
``_run_stage`` with ``settle-no-backstop`` (the thread-root
attribution rides on the PR-15 spawn/root fixpoint).

The fixed shape is ``serve/jobs.py``'s ``_run_stage_guarded``:
try/except BaseException that settles a failed StageResult.
"""
import threading


class StageRunner:
    def start(self, job, future):
        t = threading.Thread(target=self._run_stage,
                             args=(job, future),
                             name="fixture-stage")
        t.start()
        return t

    def _run_stage(self, job, future):
        result = job.run()          # a raise here strands the slot
        future._stage_settled(result)
        future._set_result(result)
