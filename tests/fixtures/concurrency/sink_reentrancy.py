"""Seeded-bug fixture: the PR-9 ``MetricsLogger`` sink re-entrancy.

A logger that holds a **plain** (non-reentrant) lock while fanning a
record out to its sinks, wired with a sink that logs BACK into the
same logger from inside ``write()`` — the exact same-thread recursion
the AlertEngine performs by design (alerts are logged into the
stream they fire on), which is why the shipped ``MetricsLogger``
uses an RLock.  With the plain lock the fit thread deadlocks on
itself; seeded here so the machinery proves it:

* the **static pass** must flag the sink callback invoked under the
  lock (``callback-under-lock``);
* the **lockdep shadow** (inject a wrapped lock) must convert the
  silent same-thread hang into a deterministic
  :class:`~multigrad_tpu.utils.lockdep.LockdepViolation`
  (self-deadlock), and the **interleaving harness** must report the
  plain-lock variant as deadlocked.
"""
import threading


class BuggyLogger:
    """MetricsLogger shape with the seeded bug: plain Lock + sink
    fan-out inside the critical section."""

    def __init__(self):
        # BUG: not an RLock — a sink that re-enters log() from
        # write() deadlocks its own thread.
        self._lock = threading.Lock()
        self._sinks = []

    def add_sink(self, sink):
        self._sinks.append(sink)

    def log(self, record: dict):
        with self._lock:
            for sink in self._sinks:
                sink.write(record)


class EchoAlertSink:
    """The AlertEngine shape: folds the stream and logs fired
    alerts back into the same stream — from inside ``write()``."""

    def __init__(self, logger):
        self.logger = logger

    def write(self, record: dict):
        if record.get("event") != "alert":
            self.logger.log({"event": "alert",
                             "trigger": record.get("event")})


def reentrancy_scenario(lock=None):
    """One worker whose single ``log()`` call re-enters through the
    echo sink and deadlocks.  ``lock`` substitutes the logger's lock
    (tests inject a lockdep-wrapped one to get the deterministic
    violation instead of the hang)."""
    logger = BuggyLogger()
    if lock is not None:
        logger._lock = lock
    logger.add_sink(EchoAlertSink(logger))

    def fit_thread():
        logger.log({"event": "adam", "step": 0})

    return [fit_thread]
