"""Seeded hygiene fixture: thread naming + allowlist verification.

Three deliberate violations the linter must report:

* an anonymous ``threading.Thread`` (``thread-unnamed``);
* a ``lock-ok`` annotation with **no justification** — suppresses
  nothing, and is itself an ``allowlist`` error (so the underlying
  ``blocking-under-lock`` finding surfaces too);
* a ``lock-ok`` annotation at a line with no matching finding — a
  **stale** allowlist entry.
"""
import threading
import time


def spawn_unnamed():
    t = threading.Thread(target=time.sleep, args=(0,), daemon=True)
    return t


class Sleepy:
    def __init__(self):
        self._lock = threading.Lock()

    def bad_allowlist(self):
        with self._lock:
            # lock-ok: blocking-under-lock
            time.sleep(0.001)

    def stale_allowlist(self):
        # lock-ok: thread-unnamed there is no such finding here
        pass
