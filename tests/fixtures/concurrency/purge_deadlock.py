"""Seeded-bug fixture: the PR-10 ``_purge_cancelled`` deadlock shape.

A bounded queue whose consumer purges cancelled items and — on the
everything-was-cancelled early return — forgets to notify
``_not_full``: the producer blocked on backpressure sleeps forever on
a queue that now has headroom.  This is the exact lost-wakeup class
the PR-10 review round caught by eye in ``FitQueue`` (fixed by having
``_purge_cancelled`` notify ``_not_full`` itself); seeded here so the
machinery that should have caught it proves it now does:

* the **static pass** must flag it (the producer's wait is an
  ``if``-guarded ``Condition.wait`` — ``cond-wait-no-while``, the
  same lost-wakeup class);
* the **interleaving harness** must find a schedule that deadlocks
  (producer parks on ``_not_full``, consumer purges and returns
  without notifying, nothing ever moves again) — and must find none
  on the shipped, fixed ``FitQueue`` under the same scenario shape.

Deliberately NOT part of the package tree: the shipped-tree lint
must stay clean; tests point ``analyze_concurrency(root=...)`` here.
"""
import threading

from multigrad_tpu._lockdep import sched_point


class Item:
    def __init__(self):
        self.cancelled = False


class BuggyBoundedQueue:
    """Minimal bounded FIFO reproducing the seeded bug pair."""

    def __init__(self, max_pending: int = 1):
        self.max_pending = max_pending
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._pending = []

    def submit(self, item: Item):
        with self._not_full:
            # BUG (static signature): `if`, not `while` — a spurious
            # or stale wakeup falls through on a still-full queue.
            if len(self._pending) >= self.max_pending:
                self._not_full.wait()
            self._pending.append(item)
            self._not_empty.notify()

    def take(self):
        with self._not_empty:
            purged = [i for i in self._pending if i.cancelled]
            if purged:
                self._pending = [i for i in self._pending
                                 if not i.cancelled]
                # BUG (dynamic signature): the purge freed
                # backpressure headroom but does NOT notify
                # _not_full — a producer blocked in submit() never
                # learns the queue has space (the PR-10 shape).
            if not self._pending:
                return None
            item = self._pending.pop(0)
            self._not_full.notify()
            return item


def deadlock_scenario(queue=None):
    """Two workers whose unlucky schedule wedges the buggy queue:
    the producer fills the 1-slot queue and blocks on a second
    submit; the consumer cancels the queued item and takes — the
    purge path returns without a notify.  Returns worker callables
    for :func:`multigrad_tpu.utils.testing.run_interleavings`."""
    q = queue if queue is not None else BuggyBoundedQueue(1)
    a, b = Item(), Item()

    def producer():
        q.submit(a)
        sched_point("submitted-a")
        q.submit(b)                   # blocks at max_pending=1

    def consumer():
        sched_point("pre-cancel")
        a.cancelled = True
        sched_point("pre-take")
        q.take()                      # purge without notify

    return [producer, consumer]
