# Sphinx configuration (RTD equivalent of the reference's
# docs/source/conf.py, retargeted to multigrad_tpu).
import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "multigrad_tpu"
copyright = "2026, multigrad_tpu contributors"
author = "multigrad_tpu contributors"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.autosummary",
    "sphinx.ext.napoleon",      # numpydoc-style docstrings
    "sphinx.ext.viewcode",
    "myst_parser",              # the markdown guides in docs/
    "nbsphinx",                 # the executed tutorial notebook
]

# The notebook ships pre-executed (docs/source/notebooks/intro.ipynb
# carries recorded outputs, like the reference's intro.ipynb cell 16).
nbsphinx_execute = "never"

autodoc_default_options = {
    "members": True,
    "undoc-members": False,
    "inherited-members": False,
}
autosummary_generate = True

source_suffix = {
    ".rst": "restructuredtext",
    ".md": "markdown",
}

templates_path = []
exclude_patterns = []

html_theme = "sphinx_rtd_theme"
