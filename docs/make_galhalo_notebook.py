"""Regenerate docs/source/notebooks/galhalo_history.ipynb (executed).

Companion to make_intro_notebook.py for the diffmah-style history
family; run after API changes:
    python docs/make_galhalo_notebook.py
"""
import nbformat as nbf
from nbclient import NotebookClient

nb = nbf.v4.new_notebook()
md = nbf.v4.new_markdown_cell
code = nbf.v4.new_code_cell

cells = [
md("""# Galaxy–halo histories: a diffmah-style multi-epoch fit

BASELINE config 4's workload shape: every halo grows along a smooth
differentiable **mass-accretion history**, stars form from the
accreted baryons at a mass-dependent efficiency, and the model
predicts the **stellar mass function at several observation epochs**
— all ten parameters fit by gradient descent through the whole
pipeline (`multigrad_tpu.models.galhalo_hist`)."""),

code("""# Simulate an 8-device TPU mesh on CPU (remove on a real pod).
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.devices()"""),

md("""## 1. The physics: anchored histories, integrated star formation

`log10 Mh(t) = logm0 + alpha(t) * log10(t/T0)` with a sigmoid
rollover of the accretion index `alpha(t)` — each history ends
exactly at the halo's observed mass.  Star formation is
`SFR = eps(Mh) * F_B * dMh/dt` with a two-slope peaked efficiency,
integrated on a fixed time grid."""),

code("""import numpy as np
import jax.numpy as jnp
import matplotlib.pyplot as plt
from multigrad_tpu.models.galhalo_hist import (
    TRUTH, default_time_grid, log_mh_at_t, lg_sfr_efficiency)

t = default_time_grid(64)
fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9, 3.2))
for lm0 in (11.5, 12.5, 13.5, 14.5):
    ax1.plot(t, log_mh_at_t(jnp.full((1, 1), lm0), t[None, :],
                            jnp.array(TRUTH))[0], label=f"$logM_0$={lm0}")
ax1.set(xlabel="t [Gyr]", ylabel="log10 Mh(t)", xscale="log")
ax1.legend(fontsize=7)
m = jnp.linspace(10.5, 14.5, 100)
ax2.plot(m, lg_sfr_efficiency(m, jnp.array(TRUTH)))
ax2.set(xlabel="log10 Mh", ylabel="log10 SF efficiency")
fig.tight_layout()"""),

md("""## 2. Build the fit: multi-epoch targets on a sharded catalog

The aux builder samples a power-law halo catalog, computes the target
SMFs at three epochs at the truth parameters, and shards the halo
axis over the mesh.  The mass-dependent scatter rides the
per-particle-sigma erf kernel."""),

code("""import multigrad_tpu as mgt
from multigrad_tpu.models import GalhaloHistModel, make_galhalo_hist_data

comm = mgt.global_comm()
data = make_galhalo_hist_data(50_000, comm=comm)
model = GalhaloHistModel(aux_data=data, comm=comm)
[float(x) for x in data["time_grid"][jnp.array(data["obs_indices"])]]
"""),

md("""The three observation epochs (Gyr).  Early-epoch mass functions
are what identify the assembly-history parameters — the z=0 SMF
alone is degenerate along history directions."""),

code("""loss, grad = model.calc_loss_and_grad_from_params(jnp.array(TRUTH))
print(f"loss at truth: {float(loss):.2e}")
print("gradient magnitudes:",
      np.round(np.abs(np.asarray(grad)), 10))"""),

md("""## 3. Fit all ten parameters"""),

code("""from multigrad_tpu.models.galhalo_hist import GalhaloHistParams

BOUNDS = [(1.0, 4.0), (0.1, 2.0), (-0.5, 1.0), (1.0, 6.0),
          (-2.0, 0.5), (10.5, 13.5), (0.3, 3.0), (0.2, 2.5),
          (0.05, 0.5), (-0.1, 0.05)]
truth = np.array(TRUTH)
guess = jnp.array(truth + np.array([0.15, -0.1, 0.05, -0.2, 0.08,
                                    -0.1, 0.1, -0.08, 0.02, 0.005]))
result = model.run_bfgs(guess=guess, maxsteps=300, param_bounds=BOUNDS,
                        progress=False)
print(f"nit={result.nit} nfev={result.nfev} fun={result.fun:.2e}")
for name, tv, xv in zip(GalhaloHistParams._fields, truth, result.x):
    print(f"{name:>12} truth {tv:7.3f}  fit {xv:8.4f}")"""),

md("""Every parameter recovers tightly except `k_t` (the rollover
sharpness), which is honestly flat — it trades against the
early/late-index contrast at the ~1e-5 loss level.  The same fit runs
unchanged at 1e8 halos on a TPU pod (`chunk_size=1_000_000`, halo
axis sharded with `scatter_nd`)."""),
]

nb["cells"] = cells
client = NotebookClient(nb, timeout=1200)
client.execute()
import os
out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "source", "notebooks", "galhalo_history.ipynb")
nbf.write(nb, out)
print(f"wrote {out} (executed)")
