"""Regenerate docs/source/notebooks/intro.ipynb (executed).

The tutorial ships with recorded outputs (like the reference's
intro.ipynb); run this after API changes:
    python docs/make_intro_notebook.py
"""
import nbformat as nbf
from nbclient import NotebookClient

nb = nbf.v4.new_notebook()
md = nbf.v4.new_markdown_cell
code = nbf.v4.new_code_cell

cells = [
md("""# multigrad_tpu quickstart

Runnable twin of the reference tutorial
(`/root/reference/docs/source/notebooks/intro.ipynb`): define a model,
inspect the truth, fit it with BFGS — on a TPU/CPU device mesh instead
of MPI ranks. Prose version: `docs/intro.md`."""),

code("""# Simulate an 8-device TPU mesh on CPU (remove on a real TPU pod:
# the mesh then spans the pod's chips automatically).
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.devices()"""),

md("""## 1. Define the model

A model maps `params -> partial sumstats -> loss`, where *partial*
means "this shard's contribution" and total sumstats are the sum over
shards. Subclass `OnePointModel` (as a dataclass) and implement the
two methods:"""),

code("""from dataclasses import dataclass, field
from typing import NamedTuple
import jax.numpy as jnp
import numpy as np
import multigrad_tpu as mgt
from multigrad_tpu.ops import binned_density


class ParamTuple(NamedTuple):
    log_shmrat: float = -2.0
    sigma_logsm: float = 0.2


@dataclass
class MySMFModel(mgt.OnePointModel):
    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        p = ParamTuple(*params)
        mean_logsm = self.aux_data["log_halo_masses"] + p.log_shmrat
        return binned_density(mean_logsm, self.aux_data["smf_bin_edges"],
                              p.sigma_logsm, self.aux_data["volume"])

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.log10(self.aux_data["target_sumstats"])
        return jnp.mean((jnp.log10(sumstats) - target) ** 2)"""),

md("""## 2. Build the data, sharded over a mesh

The sharding contract is carried by the arrays: leaves sharded over the
comm's axis enter the SPMD program shard-by-shard (the model sees only
its local chunk, exactly like an MPI rank); everything else is
replicated."""),

code("""from multigrad_tpu.models.smf import load_halo_masses, TARGET_SUMSTATS

comm = mgt.global_comm()          # every device, one named axis
num_halos = 10_000

data = dict(
    log_halo_masses=mgt.scatter_nd(          # sharded over the mesh
        jnp.log10(load_halo_masses(num_halos)), comm=comm),
    smf_bin_edges=jnp.linspace(9, 10, 11),   # replicated
    volume=10.0 * num_halos,
    target_sumstats=jnp.asarray(TARGET_SUMSTATS),
)
model = MySMFModel(aux_data=data, comm=comm)
comm"""),

md("""## 3. Inspect loss and gradient at the truth

One fused XLA program computes the user kernel, both `psum`
collectives, the loss gradient and the VJP — communication is
O(|sumstats| + |params|) regardless of data size."""),

code("""truth = ParamTuple()
print("sumstats at truth:", np.asarray(model.calc_sumstats_from_params(truth))[:4])
print("target:           ", np.asarray(TARGET_SUMSTATS)[:4])
loss, grad = model.calc_loss_and_grad_from_params(truth)
print("loss:", float(loss), " grad:", np.asarray(grad))"""),

md("""## 4. Fit with BFGS

The scipy L-BFGS-B driver runs identically on every host: its inputs
are psum results (replicated bitwise), so all hosts follow the same
control flow — no root/worker protocol, no result broadcast. The
reference tutorial records convergence in `nit=16, nfev=29`; this
implementation reproduces that iteration count."""),

code("""guess = ParamTuple(log_shmrat=-1.0, sigma_logsm=0.5)
result = model.run_bfgs(guess=guess, maxsteps=100, progress=False)
print("x =", result.x, "\\nfun =", result.fun, "\\nnit =", result.nit,
      " nfev =", result.nfev)"""),

md("""## 5. Or Adam / simple gradient descent

`run_adam` executes the whole optimization as a single `lax.scan` on
device; bounds are handled by tan/arctan (two-sided) and
shifted-reciprocal (one-sided) bijections, vectorized and
recompile-free. The guess must lie strictly inside the bounds
(boundary points map to infinity; `run_adam` raises otherwise). Both return the full parameter trajectory like the
reference."""),

code("""traj = model.run_adam(guess, nsteps=500, learning_rate=0.02,
                      param_bounds=[(-3, 0), (0.05, 1)], progress=False)
print("adam final:", np.asarray(traj)[-1])
res = model.run_simple_grad_descent(guess, nsteps=100, learning_rate=1e-3)
print("simple GD loss: first", float(res.loss[0]), "-> last", float(res.loss[-1]))"""),

md("""## Scaling up

- **Multiple hosts**: call `mgt.distributed.initialize()` first; load
  per-host data and use `mgt.scatter_from_local`.
- **Huge particle counts**: pass `chunk_size` to the binned kernels to
  bound HBM working set (the 1e8-halo benchmark config uses this).
- **Hybrid ICI/DCN meshes**: `mgt.hybrid_comm()` — see
  `docs/distributed.md` for topology and multi-model
  (`OnePointGroup`) fits."""),
]
nb.cells = cells
client = NotebookClient(nb, timeout=600, kernel_name="python3")
client.execute()
import os
out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "source", "notebooks", "intro.ipynb")
nbf.write(nb, out)
print("notebook written and executed:", out)
