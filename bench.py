"""Benchmark driver: SMF Adam fit throughput on the current backend.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: the reference's canonical benchmark shape
(``/root/reference/tests/smf_example/benchmark.py``) — the SMF
gradient-descent fit, warm-up run first, then timed steps — scaled to
1M halos and 1000 Adam steps.

Measurement protocol: the timed region ends with a **device-to-host
fetch of the result trajectory** (``np.asarray``), because on a
tunneled/async runtime ``block_until_ready`` can return before the
computation drains; fetching the output is the only watertight fence.
The tunnel's round-trip latency is measured separately (trivial
kernel + fetch) and subtracted, and 1000 steps amortize what remains.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
is measured fresh *on the same hardware* against a faithful port of
the reference's execution shape: per-bin jitted sumstats kernels
driven from a host Python loop, the two-stage VJP with collectives
outside jit (``multigrad.py:508-538``), and a host-loop optimizer
(``adam.py:52-68``).  Ours is the same math as one fused in-graph
``lax.scan`` (plus a Pallas sumstats kernel on TPU).  The ratio is
therefore "TPU-native redesign vs reference architecture, same chip".
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

NUM_HALOS = 1_000_000
NSTEPS = 1_000
LR = 1e-3
GUESS = (-1.0, 0.5)  # plain floats: no device op until the backend is up


def init_backend_with_retry(attempts=6, base_delay=5.0):
    """First contact with a tunneled TPU backend can fail transiently.

    Retry backend init with exponential backoff; on final failure fall
    back to CPU so the benchmark still produces a (labelled) number
    rather than voiding the round's perf evidence.
    """
    last_err = None
    for k in range(attempts):
        try:
            devs = jax.devices()
            return jax.default_backend(), devs
        except RuntimeError as e:          # backend setup error
            last_err = e
            print(f"backend init attempt {k + 1}/{attempts} failed: {e}",
                  file=sys.stderr)
            if k < attempts - 1:           # no pointless final backoff
                time.sleep(base_delay * (2 ** k))
    # Last resort: pin CPU so we still measure *something*.
    print(f"falling back to cpu after {attempts} failures: {last_err}",
          file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.default_backend(), jax.devices()


def measure_fetch_rtt():
    """Round-trip latency of a trivial dispatch + host fetch."""
    f = jax.jit(lambda a: a + 1.0)
    np.asarray(f(jnp.float32(0.0)))
    t0 = time.perf_counter()
    reps = 5
    for i in range(reps):
        np.asarray(f(jnp.float32(i)))
    return (time.perf_counter() - t0) / reps


def build_data():
    from multigrad_tpu.models.smf import make_smf_data
    backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    return make_smf_data(NUM_HALOS, comm=None, backend=backend)


def bench_ours(data, rtt, guess):
    """Fused in-graph fit: one lax.scan over the SPMD loss-and-grad."""
    from multigrad_tpu.models.smf import SMFModel

    model = SMFModel(aux_data=data, comm=None)

    def run(g, nsteps):
        traj = model.run_adam(guess=g, nsteps=nsteps,
                              learning_rate=LR, progress=False)
        return np.asarray(traj)           # host fetch = hard fence

    run(guess, NSTEPS)                    # warm-up/compile
    t0 = time.perf_counter()
    traj = run(guess + 0.01, NSTEPS)      # fresh inputs: no replay
    dt = time.perf_counter() - t0 - rtt
    return NSTEPS / dt, traj[-1]


def bench_reference_style(data, rtt, guess):
    """The reference's execution shape, ported faithfully: per-bin
    jitted kernels in a Python loop, vjp/grad/collectives interleaved
    on the host, optimizer stepping in Python."""
    log_mh = jnp.asarray(data["log_halo_masses"])
    edges = np.asarray(data["smf_bin_edges"])
    volume = data["volume"]
    target = jnp.log10(jnp.asarray(data["target_sumstats"]))

    @jax.jit
    def calc_smf_bin(params, lo, hi):
        mean = log_mh + params[0]
        cdf_hi = 0.5 * (1 + jax.scipy.special.erf(
            (hi - mean) / (jnp.sqrt(2.0) * params[1])))
        cdf_lo = 0.5 * (1 + jax.scipy.special.erf(
            (lo - mean) / (jnp.sqrt(2.0) * params[1])))
        return jnp.sum(cdf_hi - cdf_lo) / volume / (hi - lo)

    def sumstats_fn(params):
        return jnp.array([calc_smf_bin(params, lo, hi)
                          for lo, hi in zip(edges[:-1], edges[1:])])

    def loss_fn(y):
        return jnp.mean((jnp.log10(y) - target) ** 2)

    grad_loss = jax.grad(loss_fn)

    def loss_and_grad(params):
        y, vjp = jax.vjp(sumstats_fn, params)
        dloss_dy = grad_loss(y)
        return loss_fn(y), vjp(dloss_dy)[0]

    tx = optax.adam(LR)

    def run(guess, nsteps):
        params = guess
        state = tx.init(params)
        for _ in range(nsteps):
            _, g = loss_and_grad(params)
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
        return np.asarray(params)         # host fetch = hard fence

    run(guess, 3)                         # warm-up/compile
    n = 20                                # host-loop is slow; sample
    t0 = time.perf_counter()
    run(guess + 0.01, n)
    dt = time.perf_counter() - t0 - rtt
    return n / dt


def main():
    backend, _ = init_backend_with_retry()
    guess = jnp.array(GUESS)
    rtt = measure_fetch_rtt()
    data = build_data()
    ours_sps, final = bench_ours(data, rtt, guess)
    ref_sps = bench_reference_style(data, rtt, guess)
    print(json.dumps({
        "metric": f"adam_steps_per_sec_smf_{NUM_HALOS:.0e}_halos_{backend}",
        "value": round(ours_sps, 2),
        "unit": "steps/s",
        "vs_baseline": round(ours_sps / ref_sps, 2),
    }))


if __name__ == "__main__":
    main()
