"""Benchmark driver: SMF Adam fit throughput on the current backend.

Prints ONE JSON line whose required keys are
{"metric", "value", "unit", "vs_baseline"}; extra keys carry the
dossier: the Pallas-vs-XLA A/B, the 1e8-halo chunked config, the
wp(rp) kernel A/B, and the provenance of the baseline number.  The
roofline analysis behind these numbers is in BENCH_NOTES.md.

Workload: the reference's canonical benchmark shape
(``/root/reference/tests/smf_example/benchmark.py``) — the SMF
gradient-descent fit, warm-up run first, then timed steps — scaled to
1M halos / 5000 Adam steps (headline; long enough that the tunnel's
per-call floor is <10% of the timed region) and 1e8 halos with the
chunked kernel (BASELINE config 4's scale, single chip).

Measurement protocol: warm-up, then the **best of N timed reps**,
each with fresh inputs and ending in a device-to-host fetch of the
result trajectory (the only watertight fence on a tunneled/async
runtime).  Best-of-N matters: the first post-warm-up run with new
inputs pays a one-time ~0.6 s runtime cost on the tunneled backend
(measured round 3; a single-rep protocol under-reported steady-state
throughput 2.2x in round 2).  The tunnel's round-trip latency is
measured separately and subtracted.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the baseline is measured fresh *on the same hardware* against a
faithful port of the reference's execution shape — per-bin jitted
sumstats kernels driven from a host Python loop, the two-stage VJP
with collectives outside jit (``multigrad.py:508-538``), and a
host-loop optimizer (``adam.py:52-68``).  Ours is the same math as
one fused in-graph ``lax.scan`` (plus Pallas sumstats kernels on
TPU).  The ratio is "TPU-native redesign vs reference architecture,
same chip"; its provenance rides in the JSON's "baseline" key.
"""
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

NUM_HALOS = 1_000_000
NSTEPS = 5_000
BIG_HALOS = 100_000_000
BIG_CHUNK = 4_000_000          # divides 1e8; (B+1) x chunk ~ 176 MB HBM
BIG_NSTEPS = 50
HUGE_HALOS = 1_000_000_000     # BASELINE config 5's full-pod dataset
HUGE_NSTEPS = 10
LR = 1e-3
GUESS = (-1.0, 0.5)  # plain floats: no device op until the backend is up


def _probe_backend(timeout=120):
    """Probe the default backend in a subprocess with a hard timeout.

    A *dead* tunneled backend does not raise — it HANGS in backend
    init, which no in-process retry can interrupt (observed: a ~3 h
    tunnel outage where ``jax.devices()`` blocked forever).  The probe
    subprocess inherits the same platform selection.  Returns "ok",
    "hang" (the case CPU-pinning targets), or "error" (a raise-type
    transient — the in-process retry loop's job, NOT grounds to pin).
    """
    import subprocess

    probe = ("import jax, jax.numpy as jnp; "
             "print('BENCH-PROBE', jax.default_backend(), "
             "float(jnp.zeros(()) + 1.0))")
    try:
        out = subprocess.run([sys.executable, "-c", probe], text=True,
                             capture_output=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return "hang"
    if out.returncode == 0 and "BENCH-PROBE" in out.stdout:
        return "ok"
    return "error"


def init_backend_with_retry(attempts=6, base_delay=5.0):
    """First contact with a tunneled TPU backend can fail transiently.

    Probe responsiveness out-of-process first (a down tunnel hangs
    rather than raises — see :func:`_probe_backend`), then retry
    backend init with exponential backoff; on final failure fall back
    to CPU so the benchmark still produces a (labelled) number rather
    than voiding the round's perf evidence.
    """
    # Hang guard: only a plausibly-tunneled backend can hang, and
    # only a TIMED-OUT probe is evidence of a hang — a probe that
    # *raises* quickly is a transient the retry loop below already
    # handles with backoff (pinning CPU on those would silently
    # produce fallback numbers for a round where the TPU recovers).
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The env var alone is NOT a reliable pin: the TPU-tunnel
        # site customization initializes the hardware plugin anyway,
        # and with the tunnel down that init hangs even for an
        # env-pinned-cpu process (observed round 5).  The config API
        # wins over everything — same pattern as tests/conftest.py.
        jax.config.update("jax_platforms", "cpu")
    else:
        probe_rounds = 3                   # ~6 min worst case total
        for k in range(probe_rounds):
            status = _probe_backend(timeout=120)
            if status != "hang":
                break
            print(f"backend probe {k + 1}/{probe_rounds} hung",
                  file=sys.stderr)
            if k < probe_rounds - 1:
                time.sleep(base_delay * (2 ** k))
        else:
            print("backend hung in every probe; pinning cpu",
                  file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
            return jax.default_backend(), jax.devices()

    last_err = None
    for k in range(attempts):
        try:
            devs = jax.devices()
            return jax.default_backend(), devs
        except RuntimeError as e:          # backend setup error
            last_err = e
            print(f"backend init attempt {k + 1}/{attempts} failed: {e}",
                  file=sys.stderr)
            if k < attempts - 1:           # no pointless final backoff
                time.sleep(base_delay * (2 ** k))
    # Last resort: pin CPU so we still measure *something*.
    print(f"falling back to cpu after {attempts} failures: {last_err}",
          file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.default_backend(), jax.devices()


def measure_fetch_rtt():
    """Round-trip latency of a trivial dispatch + host fetch.

    Min over reps, not mean: the subtraction below corrects for the
    *floor* cost every measurement pays; a mean polluted by one tunnel
    hiccup would over-subtract (negative times were observed with a
    5-rep mean in round 3).
    """
    f = jax.jit(lambda a: a + 1.0)
    np.asarray(f(jnp.float32(0.0)))
    best = float("inf")
    for i in range(10):
        t0 = time.perf_counter()
        np.asarray(f(jnp.float32(i)))
        best = min(best, time.perf_counter() - t0)
    return best


def _sub_rtt(elapsed, rtt):
    """Subtract the dispatch floor, refusing to eat real signal: if
    rtt would remove more than half the measurement, the config is
    too short relative to tunnel noise — keep the raw time and say so."""
    if elapsed - rtt < 0.5 * elapsed:
        print(f"rtt {rtt * 1e3:.1f} ms > 50% of measured "
              f"{elapsed * 1e3:.1f} ms; reporting unsubtracted time",
              file=sys.stderr)
        return elapsed
    return elapsed - rtt


# One partial file PER BACKEND: a CPU-fallback re-run while the
# tunnel is down must never clobber the TPU dossier it exists to
# protect (they are different files, so it can't).
PARTIAL_TEMPLATE = os.environ.get(
    "MGT_BENCH_PARTIAL",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".bench_partial.{backend}.json"))

# Entries older than this are re-measured, not served: the cache is a
# crash-resume aid *within* a round, not an archive — without expiry a
# completed dossier would be replayed verbatim forever, silently
# reporting stale numbers as fresh evidence.
MAX_PARTIAL_AGE_S = float(os.environ.get("MGT_BENCH_MAX_AGE_S",
                                         12 * 3600))


def _partial_path(backend):
    if "{backend}" not in PARTIAL_TEMPLATE:
        # An override without the placeholder must still keep the
        # backends' dossiers apart — a shared file would let a CPU
        # fallback overwrite the TPU dossier it exists to protect.
        return PARTIAL_TEMPLATE + "." + backend
    return PARTIAL_TEMPLATE.format(backend=backend)


def load_partial(backend):
    """Load the incremental dossier for *this* backend.

    Key presence means "measured" — a present ``null`` is a config
    deliberately skipped on this backend, not a hole to re-measure.
    Entries older than :data:`MAX_PARTIAL_AGE_S` (and files recorded
    under a mismatched backend, possible only via the env override)
    are dropped.  Returns ``(configs, measured_at)``.
    """
    try:
        with open(_partial_path(backend)) as f:
            saved = json.load(f)
    except (OSError, ValueError):
        return {}, {}
    if not isinstance(saved, dict):
        return {}, {}
    if (not isinstance(saved.get("configs", {}), dict)
            or not isinstance(saved.get("measured_at", {}), dict)
            or not all(isinstance(t, (int, float))
                       for t in saved.get("measured_at", {}).values())):
        # Valid JSON, malformed structure: same graceful contract as
        # an unreadable file — re-measure rather than crash.
        return {}, {}
    if saved.get("backend") != backend:
        print(f"discarding partial dossier measured on "
              f"{saved.get('backend')!r} (now on {backend!r})",
              file=sys.stderr)
        return {}, {}
    configs = saved.get("configs", {})
    times = saved.get("measured_at", {})
    now = time.time()
    fresh = {k: v for k, v in configs.items()
             if now - times.get(k, 0.0) <= MAX_PARTIAL_AGE_S}
    stale = sorted(set(configs) - set(fresh))
    if stale:
        print(f"expiring stale partial entries (>"
              f"{MAX_PARTIAL_AGE_S / 3600:.0f}h old): {stale}",
              file=sys.stderr)
    if fresh:
        print(f"resuming partial dossier: {sorted(fresh)} already "
              f"measured", file=sys.stderr)
    return fresh, {k: times[k] for k in fresh if k in times}


@functools.cache
def _provenance():
    """The telemetry run-record header, minus its stream framing —
    stamped into every saved dossier so a number can always be tied
    to the jax/jaxlib/backend/device that produced it."""
    from multigrad_tpu.telemetry import run_record

    rec = run_record()
    return {k: v for k, v in rec.items() if k not in ("event", "t")}


def save_partial(backend, configs, measured_at):
    """Atomically persist the dossier-so-far (tmp + rename): a crash
    mid-write must not corrupt the file a resume depends on.  Each
    save re-stamps provenance (jax/jaxlib versions, device kind) so
    the file records what measured it, not what first created it."""
    path = _partial_path(backend)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"backend": backend, "configs": configs,
                   "measured_at": measured_at,
                   "provenance": _provenance()}, f, indent=1)
    os.replace(tmp, path)


def build_smf_data(n_halos, chunk_size=None):
    """Build one halo dataset per (n_halos, chunk_size); the backend
    A/B legs share it (the 1e8 build is the expensive part) and only
    override the aux dict's "backend" key."""
    from multigrad_tpu.models.smf import make_smf_data
    return make_smf_data(n_halos, comm=None, chunk_size=chunk_size)


def bench_fused_fit(data, nsteps, rtt, guess, backend="auto", reps=3):
    """Fused in-graph fit: one lax.scan over the SPMD loss-and-grad.

    Returns best-of-`reps` steps/sec (see module docstring for why
    best-of, not single-shot).
    """
    from multigrad_tpu.models.smf import SMFModel

    model = SMFModel(aux_data=dict(data, backend=backend), comm=None)

    def run(g):
        traj = model.run_adam(guess=g, nsteps=nsteps,
                              learning_rate=LR, progress=False)
        return np.asarray(traj)           # host fetch = hard fence

    run(guess)                            # warm-up/compile
    best = 0.0
    for k in range(reps):
        g = guess + 0.01 * (k + 1)        # fresh inputs: no replay
        t0 = time.perf_counter()
        run(g)
        dt = _sub_rtt(time.perf_counter() - t0, rtt)
        best = max(best, nsteps / dt)
    return best


def bench_wprp_eval(rtt, backend, n=8192, inner=50):
    """wp(rp) fwd+bwd evaluation time (ms) — the pair-kernel A/B.

    `inner` evaluations run inside one lax.scan dispatch so the
    tunnel's per-call latency is amortized out of the per-eval time.
    """
    from multigrad_tpu.models.wprp import make_galaxy_mock, \
        selection_weights
    from multigrad_tpu.ops.pairwise import ring_weighted_pair_counts

    pos, logm = make_galaxy_mock(n, 100.0)
    edges = jnp.logspace(-0.5, 1.2, 9)
    params0 = jnp.array([-2.0, -1.0])

    @jax.jit
    def many(params):
        def body(c, i):
            # Jitter the positions per iteration: with them fixed,
            # XLA hoists the loop-invariant (N, N) bin masks out of
            # the scan and both backends collapse to matvec cost —
            # a real regime for small-N fixed-position fits (masks
            # cached in HBM), but not a kernel measurement.
            pos_i = pos + 1e-6 * i

            def loss(p):
                w = selection_weights(logm, p)
                dd = ring_weighted_pair_counts(
                    pos_i, w, edges, box_size=100.0, pimax=20.0,
                    backend=backend)
                return jnp.sum(dd) * 1e-6
            val, grad = jax.value_and_grad(loss)(params + 1e-4 * i)
            return c + val + grad[0], None
        out, _ = jax.lax.scan(body, 0.0, jnp.arange(float(inner)))
        return out

    np.asarray(many(params0))             # warm-up/compile
    best = float("inf")
    for k in range(2):
        t0 = time.perf_counter()
        np.asarray(many(params0 + 0.01 * (k + 1)))
        best = min(best, _sub_rtt(time.perf_counter() - t0, rtt) / inner)
    return best * 1e3


def bench_galhalo_hist(rtt, reps=2, nsteps=20, **data_kwargs):
    """Diffmah-style history model at 1e8 halos (BASELINE config 4).

    Each Adam step integrates 1e8 sixteen-point mass-accretion +
    star-formation histories (chunked, rematerialized), reads out
    three observation epochs, and pushes three SMFs through the
    per-particle-sigma erf kernel — the heaviest per-step workload in
    the dossier.  ``data_kwargs`` forward to
    ``make_galhalo_hist_data`` (the ``galhalo_hist_1e8_fused`` config
    passes the fine-binned fused-kernel setup through here).
    """
    import jax.numpy as jnp
    from multigrad_tpu.models import (GalhaloHistModel,
                                      make_galhalo_hist_data)
    from multigrad_tpu.models.galhalo_hist import TRUTH

    data = make_galhalo_hist_data(BIG_HALOS, chunk_size=1_000_000,
                                  **data_kwargs)
    model = GalhaloHistModel(aux_data=data)
    guess = jnp.array(TRUTH) + 0.05

    def run(g):
        traj = model.run_adam(guess=g, nsteps=nsteps,
                              learning_rate=1e-3, progress=False)
        return np.asarray(traj)

    run(guess)                            # warm-up/compile
    best = 0.0
    for k in range(reps):
        t0 = time.perf_counter()
        run(guess + 0.003 * (k + 1))
        best = max(best,
                   nsteps / _sub_rtt(time.perf_counter() - t0, rtt))
    return best


def bench_galhalo_hist_1e9(rtt):
    """Single loss-and-grad evaluation at 1e9 halos (seconds).

    The capability probe for the history model's fused chunk scan
    (history integration + epoch readout + binned reduction all
    inside one rematerialized ``lax.scan``): with no (N, K) epoch
    readout materialized, the full-pod dataset size streams through
    ONE chip exactly like the SMF family's 1e9 config.  One timed
    fwd+bwd (best of 2) — a fit would take hours and add nothing:
    the per-step cost IS the number.
    """
    import jax.numpy as jnp
    from multigrad_tpu.models import (GalhaloHistModel,
                                      make_galhalo_hist_data)
    from multigrad_tpu.models.galhalo_hist import TRUTH

    data = make_galhalo_hist_data(HUGE_HALOS, chunk_size=4_000_000)
    model = GalhaloHistModel(aux_data=data)
    p = jnp.array(TRUTH) + 0.05

    def run(params):
        loss, grad = model.calc_loss_and_grad_from_params(params)
        return float(loss), np.asarray(grad)   # host fetch = fence

    run(p)                                     # warm-up/compile
    best = float("inf")
    for k in range(2):
        t0 = time.perf_counter()
        loss, grad = run(p + 0.003 * (k + 1))
        if not (np.isfinite(loss) and np.all(np.isfinite(grad))):
            # Explicit raise, not a bare assert: under `python -O`
            # asserts vanish and a non-finite measurement would enter
            # the incremental dossier as a real number.
            raise RuntimeError(
                f"non-finite 1e9-halo measurement (rep {k}): "
                f"loss={loss!r}, grad finite="
                f"{bool(np.all(np.isfinite(grad)))}")
        best = min(best, _sub_rtt(time.perf_counter() - t0, rtt))
    return best


def bench_pair_counts_scale(rtt, backend, n, row_chunk=None,
                            inner=1, reps=2):
    """Pair-count fwd+bwd at catalog scale (BASELINE config 3).

    Wall-clock per evaluation (seconds) of the weighted wp(rp) DD
    kernel on n halos — O(n²) pair blocks, row_chunk-streamed on the
    XLA path, (tile, tile) VMEM blocks on the Pallas path.  The
    positions are offset by the *traced* scan index, which is what
    actually stops XLA constant-folding/hoisting the bin masks even
    at ``inner=1`` (the measured regime is the recompute regime,
    which BENCH_NOTES §3 argues is the real one at this scale).
    """
    from multigrad_tpu.models.wprp import make_galaxy_mock, \
        selection_weights
    from multigrad_tpu.ops.pairwise import ring_weighted_pair_counts

    box = 250.0
    pos, logm = make_galaxy_mock(n, box)
    edges = jnp.logspace(-0.5, 1.2, 9)
    params0 = jnp.array([-2.0, -1.0])

    @jax.jit
    def many(params):
        def body(c, i):
            pos_i = pos + 1e-6 * i

            def loss(p):
                w = selection_weights(logm, p)
                dd = ring_weighted_pair_counts(
                    pos_i, w, edges, box_size=box, pimax=20.0,
                    row_chunk=row_chunk, backend=backend)
                return jnp.sum(dd) * 1e-6
            val, grad = jax.value_and_grad(loss)(params + 1e-4 * i)
            return c + val + grad[0], None
        out, _ = jax.lax.scan(body, 0.0, jnp.arange(float(inner)))
        return out

    np.asarray(many(params0))             # warm-up/compile
    best = float("inf")
    for k in range(reps):
        t0 = time.perf_counter()
        np.asarray(many(params0 + 0.01 * (k + 1)))
        best = min(best,
                   _sub_rtt(time.perf_counter() - t0, rtt) / inner)
    return best


def bench_streaming(rtt, guess, n_halos, chunk_rows_list, nsteps=5,
                    reps=2):
    """Streamed SMF fit throughput: the out-of-core chunk-size sweep.

    Runs a short Adam fit whose every step is the exact two-pass
    streamed loss-and-grad (``multigrad_tpu.data``), per chunk size.
    Reports steps/s plus the stream counters — chunks/s, bytes
    streamed, prefetch-stall fraction, and the live-buffer high-water
    mark (must be <= 2: double buffering is the subsystem's HBM
    contract).  The catalog itself is held by an in-memory source so
    the sweep measures the streaming machinery (chunk programs +
    prefetch overlap), not disk bandwidth.
    """
    import multigrad_tpu as mgt
    from multigrad_tpu.data import StreamingOnePointModel
    from multigrad_tpu.models.smf import (SMFModel, load_halo_masses,
                                          make_smf_data)

    log_mh = np.asarray(jnp.log10(load_halo_masses(n_halos)))
    aux = make_smf_data(n_halos, comm=None)
    del aux["log_halo_masses"]
    comm = mgt.global_comm() if len(jax.devices()) > 1 else None

    sweep = {}
    for chunk_rows in chunk_rows_list:
        sm = StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux), comm=comm),
            streams={"log_halo_masses": log_mh},
            chunk_rows=chunk_rows)

        def run(g):
            traj = sm.run_adam(guess=g, nsteps=nsteps,
                               learning_rate=LR, progress=False)
            return np.asarray(traj)       # host fetch = hard fence

        run(guess)                        # warm-up/compile
        best, stats = 0.0, None
        for k in range(reps):
            t0 = time.perf_counter()
            run(guess + 0.01 * (k + 1))
            sps = nsteps / _sub_rtt(time.perf_counter() - t0, rtt)
            if sps > best:
                best, stats = sps, sm.last_stats
        entry = dict(steps_per_sec=round(best, 3), **stats.summary())
        assert entry["max_live_buffers"] <= 2, entry
        sweep[str(chunk_rows)] = entry
    return sweep


def bench_group_fit(rtt, guess, reps=3, nsteps=2000, host_nsteps=100):
    """Joint (OnePointGroup) Adam fit: fused one-program scan vs the
    host-loop MPMD driver.

    Two SMF members of NUM_HALOS/2 each — the same total work as the
    solo headline fit — so "fused joint fit within ~2x of a solo fit's
    steps/s" is directly readable off the JSON.  The host-loop leg
    measures what the fused path replaces: one host round-trip per
    member per step (RTT-bound on a tunneled runtime).
    """
    from multigrad_tpu import OnePointGroup
    from multigrad_tpu.models.smf import SMFModel

    data = build_smf_data(NUM_HALOS // 2)
    models = tuple(SMFModel(aux_data=data, comm=None) for _ in range(2))
    group = OnePointGroup(models=models)
    assert group.fused

    def run(g, n):
        traj = group.run_adam(guess=g, nsteps=n, learning_rate=LR,
                              progress=False)
        return np.asarray(traj)           # host fetch = hard fence

    run(guess, nsteps)                    # warm-up/compile
    fused_best = 0.0
    for k in range(reps):
        g = guess + 0.01 * (k + 1)
        t0 = time.perf_counter()
        run(g, nsteps)
        fused_best = max(fused_best,
                         nsteps / _sub_rtt(time.perf_counter() - t0, rtt))

    # Host-loop leg: the same group forced onto the per-step dispatch
    # path (fewer steps — every one costs >= 2 RTTs).
    class _HostLoopGroup(OnePointGroup):
        fused = property(lambda self: False)

    host_group = _HostLoopGroup(models=models)

    def run_host(g, n):
        traj = host_group.run_adam(guess=g, nsteps=n, learning_rate=LR,
                                   progress=False)
        return np.asarray(traj)

    run_host(guess, 3)                    # warm-up/compile
    t0 = time.perf_counter()
    run_host(guess + 0.04, host_nsteps)
    host_sps = host_nsteps / _sub_rtt(time.perf_counter() - t0, rtt)
    return fused_best, host_sps


def bench_inference(rtt, n_halos, num_samples=200, num_warmup=100,
                    num_chains=4, num_leapfrog=8):
    """Inference-subsystem throughput: Fisher seconds + HMC rates.

    Two numbers for the fourth workload (fit -> stream -> *infer*):

    * ``fisher_s`` — one distributed Gauss–Newton Fisher matrix of
      the χ²-likelihood SMF model (sumstats Jacobian psum + the
      O(|y|²) host-program Hessian), best of 2;
    * the in-graph 4-chain HMC program (warmup + sampling as ONE
      dispatch): ``hmc_draws_per_sec`` (chain-draws/s) and
      ``hmc_leapfrog_steps_per_sec`` — each leapfrog step is a full
      fused loss-and-grad over the catalog, so this is the number to
      compare against Adam steps/s.

    Sampler-quality counters (max R-hat, min ESS, divergences) ride
    along so a rate regression caused by a *broken* sampler (diverging
    chains reject everything — cheap and useless) is visible in the
    dossier.
    """
    import multigrad_tpu as mgt
    from multigrad_tpu.models.smf import SMFChi2Model, make_smf_data

    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    model = SMFChi2Model(
        aux_data=make_smf_data(n_halos, comm=comm), comm=comm)
    p0 = jnp.array([-2.0, 0.2])

    last_fr = {}

    def fisher_once():
        fr = mgt.fisher_information(model, p0)
        last_fr["fr"] = fr
        return np.asarray(fr.fisher)       # host fetch = fence

    fisher_once()                          # warm-up/compile
    fisher_best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        fisher_once()
        fisher_best = min(fisher_best,
                          _sub_rtt(time.perf_counter() - t0, rtt))

    stderr = np.asarray(last_fr["fr"].stderr())

    def hmc_once(seed):
        res = mgt.run_hmc(model, p0, num_samples=num_samples,
                          num_warmup=num_warmup, num_chains=num_chains,
                          num_leapfrog=num_leapfrog, step_size=0.1,
                          inv_mass=stderr ** 2, randkey=seed,
                          init_spread=1e-3)
        return res                         # samples fetched inside

    hmc_once(0)                            # warm-up/compile
    t0 = time.perf_counter()
    res = hmc_once(1)
    dt = _sub_rtt(time.perf_counter() - t0, rtt)
    total_draws = num_chains * (num_warmup + num_samples)
    return {
        "fisher_s": round(fisher_best, 4),
        "hmc_draws_per_sec": round(total_draws / dt, 2),
        "hmc_leapfrog_steps_per_sec": round(
            total_draws * num_leapfrog / dt, 1),
        "max_rhat": round(float(np.max(res.rhat)), 4),
        "min_ess": round(float(np.min(res.ess)), 1),
        "divergences": int(np.sum(res.divergences)),
    }


def bench_bfgs_tutorial(guess):
    """BFGS iterations-to-convergence on the tutorial problem — the
    second half of the BASELINE metric ("Adam grad-steps/sec/chip;
    BFGS iters to convergence").  Same shape as the reference's
    recorded anecdote (intro.ipynb cell 16: 10k halos, 2 params,
    nit=16, nfev=29, ~5.26 it/s): convergence is an iteration-count
    metric, so no RTT games — just run the fit and read the
    OptimizeResult.
    """
    from multigrad_tpu.models.smf import SMFModel

    model = SMFModel(aux_data=build_smf_data(10_000), comm=None)
    # warm-up/compile so it/s reflects the solve, not the first trace
    model.calc_loss_and_grad_from_params(guess)
    t0 = time.perf_counter()
    res = model.run_bfgs(guess=guess, maxsteps=100, progress=False)
    dt = time.perf_counter() - t0
    return {
        "nit": int(res.nit),
        "nfev": int(res.nfev),
        "fun": float(res.fun),
        "iters_per_sec": round(res.nit / dt, 2),
        "reference_anecdote": "nit=16 nfev=29 (intro.ipynb cell 16)",
    }


def bench_fused_bins_ab(rtt, n_halos, reps=2):
    """Fused-vs-dense scatter-into-bins A/B on the history model.

    One full model forward+backward (``calc_loss_and_grad_from_params``
    — history integration, multi-epoch readout, and the binned
    reduction) at ``n_halos`` rows on a fine 40-bin grid with a
    six-epoch readout, measured with ``bin_mode="dense"`` vs
    ``bin_mode="fused"`` at two scatter regimes:

    * ``sigma005`` — tight scatter (sigma_0 = 0.05), the regime the
      fused window targets: each particle's Gaussian spans ~2 of the
      40 bins, so the dense path's 41-edge sweep wastes ~4/5 of its
      transcendentals on exactly-zero masses;
    * ``sigma02`` — the TRUTH scatter (sigma_0 = 0.2), where the
      window covers most of the grid and fused ~ dense (recorded so
      the dossier shows where the switch does NOT pay).

    Windows come from ``fused_bin_window`` at each regime's maximum
    sigma, so both legs are float32-exact A/Bs of the same numbers.
    """
    from multigrad_tpu.models import (GalhaloHistModel,
                                      make_galhalo_hist_data)
    from multigrad_tpu.models.galhalo_hist import TRUTH
    from multigrad_tpu.ops.binned import fused_bin_window

    edges = np.linspace(7.0, 11.75, 41)
    obs_indices = (5, 7, 9, 11, 13, 15)
    base = make_galhalo_hist_data(n_halos, bin_edges=edges,
                                  obs_indices=obs_indices)
    out = {"n_rows": n_halos, "n_bins": len(edges) - 1,
           "n_epochs": len(obs_indices)}

    truth = np.asarray(TRUTH)
    tight = truth.copy()
    tight[8], tight[9] = 0.05, -0.005      # sigma_0, sigma_slope
    for tag, params, sigma_max in (("sigma005", tight, 0.08),
                                   ("sigma02", truth, 0.32)):
        window = fused_bin_window(edges, sigma_max)
        p = jnp.asarray(params)
        entry = {"bin_window": window}
        for mode in ("dense", "fused"):
            aux = dict(base, bin_mode=mode,
                       bin_window=(window if mode == "fused" else None))
            model = GalhaloHistModel(aux_data=aux)

            def run():
                loss, grad = model.calc_loss_and_grad_from_params(p)
                return float(loss), np.asarray(grad)  # fetch = fence

            run()                          # warm-up/compile
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                run()
                best = min(best,
                           _sub_rtt(time.perf_counter() - t0, rtt))
            entry[f"{mode}_s"] = round(best, 4)
        entry["speedup"] = round(entry["dense_s"] / entry["fused_s"], 3)
        out[tag] = entry
    return out


def bench_tuned_defaults(rtt, n_halos, table_path, telemetry=None,
                         reps=2):
    """Tuner-resolved defaults vs hand-set knobs on the BENCH_r06
    fused-bins A/B pair — the autotuner's canonical fixture.

    Same workload shapes as :func:`bench_fused_bins_ab` (history
    model, fine 40-bin grid, six-epoch readout) at the two scatter
    regimes that flip the fused-vs-dense verdict.  Per regime:

    * ``handset_s`` — the hand-set *default* (``bin_mode="dense"``);
    * ``fused_handset_s`` — the hand-set fused alternative (the 2.15x
      win at sigma≈0.05, the 0.57x regression at sigma≈0.2);
    * the **tuner** runs (static prune → measured confirm; a warm
      table resolves with zero trials — ``provenance`` records it),
      then ``tuned_s`` measures the end-to-end ``bin_mode="auto"``
      resolution path.

    The acceptance bar: ``tuned_s`` within noise of the BETTER
    hand-set leg in BOTH regimes — the 2.15x kept, the 0.57x
    regression eliminated.  ``telemetry.regress --tuned`` gates the
    ``tuned_s``-vs-``handset_s`` pairs (a tuner pick slower than the
    old default fails), and the ``tuned_vs_best_speedup`` ratio
    tracks the stronger claim cross-round.
    """
    from multigrad_tpu.models import (GalhaloHistModel,
                                      make_galhalo_hist_data)
    from multigrad_tpu.models.galhalo_hist import TRUTH
    from multigrad_tpu.ops.binned import fused_bin_window
    from multigrad_tpu.tune import TuningTable, tune_model

    edges = np.linspace(7.0, 11.75, 41)
    obs_indices = (5, 7, 9, 11, 13, 15)
    table = TuningTable(table_path)
    out = {"n_rows": n_halos, "n_bins": len(edges) - 1,
           "n_epochs": len(obs_indices), "table": table.path}

    truth = np.asarray(TRUTH)
    tight = truth.copy()
    tight[8], tight[9] = 0.05, -0.005      # sigma_0, sigma_slope
    provenance = {}
    for tag, params, sigma_max in (("sigma005", tight, 0.08),
                                   ("sigma02", truth, 0.32)):
        base = make_galhalo_hist_data(n_halos, bin_edges=edges,
                                      obs_indices=obs_indices)
        window = fused_bin_window(edges, sigma_max)
        p = jnp.asarray(params)

        def timed(model):
            def run():
                loss, grad = model.calc_loss_and_grad_from_params(p)
                return float(loss), np.asarray(grad)  # fetch = fence
            run()                          # warm-up/compile
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                run()
                best = min(best,
                           _sub_rtt(time.perf_counter() - t0, rtt))
            return round(best, 4)

        entry = {"bin_window": window}
        entry["handset_s"] = timed(GalhaloHistModel(
            aux_data=dict(base, bin_mode="dense")))
        entry["fused_handset_s"] = timed(GalhaloHistModel(
            aux_data=dict(base, bin_mode="fused",
                          bin_window=window)))
        res = tune_model(GalhaloHistModel(aux_data=dict(base)), p,
                         sigma_max=sigma_max, table=table,
                         telemetry=telemetry, trial="eval",
                         reps=reps)
        # The tuned leg runs the exact path a consumer takes:
        # bin_mode="auto" resolved through the table at model
        # construction.
        tuned_model = GalhaloHistModel(aux_data=dict(
            base, bin_mode="auto", bin_window=window,
            sigma_max=float(sigma_max)))
        entry["tuned_bin_mode"] = tuned_model.aux_data["bin_mode"]
        entry["tuned_s"] = timed(tuned_model)
        best_hand = min(entry["handset_s"], entry["fused_handset_s"])
        entry["tuned_speedup"] = round(
            entry["handset_s"] / entry["tuned_s"], 3)
        entry["tuned_vs_best_speedup"] = round(
            best_hand / entry["tuned_s"], 3)
        # The acceptance pair the regress --tuned gate judges: the
        # tuner-resolved default vs the BETTER hand-set variant (the
        # 2.15x kept AND the 0.57x regression eliminated).
        entry["vsbest_handset_s"] = best_hand
        entry["vsbest_tuned_s"] = entry["tuned_s"]
        provenance[tag] = {"key": res.key, "warm": res.warm,
                           "trials": res.n_trials,
                           "chosen": res.chosen}
        out[tag] = entry
    out["provenance"] = provenance
    return out


def bench_smf_tuned(data, nsteps, rtt, guess, table_path,
                    telemetry=None, reps=2):
    """The headline config through tuner-resolved settings: the same
    SMF whole-fit scan with hand-set default knobs
    (``handset_steps_per_sec``) vs the ``bin_mode="auto"`` /
    ``chunk_size="auto"`` resolution path (``tuned_steps_per_sec``)
    after a tuning pass.  On the coarse 10-bin SMF grid the fused
    window covers every edge, so the honest tuned pick is dense —
    the gate proves "tuned is never worse", not "tuned always wins".
    """
    from multigrad_tpu.models.smf import DEFAULT_SIGMA_MAX, SMFModel
    from multigrad_tpu.ops.binned import fused_bin_window
    from multigrad_tpu.tune import TuningTable, tune_model

    table = TuningTable(table_path)
    out = {"nsteps": nsteps, "table": table.path}

    def timed(model):
        def run(g):
            traj = model.run_adam(guess=g, nsteps=nsteps,
                                  learning_rate=LR, progress=False)
            return np.asarray(traj)        # host fetch = hard fence
        run(guess)                         # warm-up/compile
        best = 0.0
        for k in range(reps):
            t0 = time.perf_counter()
            run(guess + 0.01 * (k + 1))
            best = max(best, nsteps
                       / _sub_rtt(time.perf_counter() - t0, rtt))
        return round(best, 2)

    model = SMFModel(aux_data=dict(data), comm=None)
    out["handset_steps_per_sec"] = timed(model)
    res = tune_model(model, jnp.asarray(guess),
                     sigma_max=DEFAULT_SIGMA_MAX, table=table,
                     telemetry=telemetry, trial="eval", reps=reps)
    window = fused_bin_window(np.asarray(data["smf_bin_edges"]),
                              DEFAULT_SIGMA_MAX)
    tuned_model = SMFModel(aux_data=dict(
        data, bin_mode="auto", bin_window=window,
        sigma_max=DEFAULT_SIGMA_MAX, chunk_size="auto"), comm=None)
    out["tuned_bin_mode"] = tuned_model.aux_data["bin_mode"]
    out["tuned_steps_per_sec"] = timed(tuned_model)
    out["tuned_speedup"] = round(out["tuned_steps_per_sec"]
                                 / out["handset_steps_per_sec"], 3)
    out["provenance"] = {"key": res.key, "warm": res.warm,
                         "trials": res.n_trials,
                         "chosen": res.chosen}
    return out


def bench_adam_donated(data, nsteps, rtt, guess, reps=2):
    """Donated-vs-copied Adam carry A/B: the same SMF whole-fit scan
    with ``donate_carry`` forced on vs off.  On CPU donation is a
    no-op (ratio ~1, recorded as such); on TPU/GPU the donated leg
    aliases the ``(params, mu, nu, key)`` carry buffers per segment.
    The resolved default for this backend rides along as provenance.
    """
    import warnings

    from multigrad_tpu.models.smf import SMFModel
    from multigrad_tpu.optim.adam import resolve_donate

    model = SMFModel(aux_data=dict(data), comm=None)
    out = {"nsteps": nsteps, "donate_default": resolve_donate(None)}
    for tag, donate in (("donated", True), ("copied", False)):

        def run(g):
            with warnings.catch_warnings():
                # CPU: "donated buffers not usable" is expected noise.
                warnings.simplefilter("ignore")
                traj = model.run_adam(guess=g, nsteps=nsteps,
                                      learning_rate=LR, progress=False,
                                      donate_carry=donate)
            return np.asarray(traj)        # host fetch = hard fence

        run(guess)                         # warm-up/compile
        best = 0.0
        for k in range(reps):
            t0 = time.perf_counter()
            run(guess + 0.01 * (k + 1))
            best = max(best,
                       nsteps / _sub_rtt(time.perf_counter() - t0, rtt))
        out[f"{tag}_steps_per_sec"] = round(best, 2)
    out["speedup"] = round(out["donated_steps_per_sec"]
                           / out["copied_steps_per_sec"], 3)
    return out


def bench_streaming_overlap(rtt, guess, n_halos, chunk_rows, nsteps=3):
    """Overlapped-vs-serial streamed loss-and-grad A/B.

    Runs a short streamed SMF fit twice — double-buffered prefetch on
    vs off — and records the per-pass stall/overlap counters
    (``passes["sumstats"]`` / ``passes["vjp"]``) alongside steps/s.
    The ``vjp`` overlap is the number PR 7's backward-overlap front
    exists for: pass 2's prefetcher now starts before the cotangent
    computation, so its chunks transfer while dL/dy is evaluated and
    while each chunk's VJP runs.
    """
    import multigrad_tpu as mgt
    from multigrad_tpu.data import StreamingOnePointModel
    from multigrad_tpu.models.smf import (SMFModel, load_halo_masses,
                                          make_smf_data)

    log_mh = np.asarray(jnp.log10(load_halo_masses(n_halos)))
    aux = make_smf_data(n_halos, comm=None)
    del aux["log_halo_masses"]
    comm = mgt.global_comm() if len(jax.devices()) > 1 else None

    out = {"n_rows": n_halos, "chunk_rows": chunk_rows}
    if jax.default_backend() == "cpu":
        # With an in-memory source on the CPU backend, "load" is a
        # memcpy and the loader thread only contends with compute for
        # cores — the per-pass overlap fractions are the meaningful
        # columns here; absolute steps/s favors serial.  The TPU leg
        # (real host->HBM transfer hidden behind device compute) is
        # where the throughput delta is read.
        out["note"] = ("cpu backend: in-memory loads make the "
                       "prefetch thread pure overhead; compare "
                       "overlap_frac, not steps/s")
    for tag, prefetch in (("overlapped", True), ("serial", False)):
        sm = StreamingOnePointModel(
            model=SMFModel(aux_data=dict(aux), comm=comm),
            streams={"log_halo_masses": log_mh},
            chunk_rows=chunk_rows, prefetch=prefetch)

        def run(g):
            traj = sm.run_adam(guess=g, nsteps=nsteps,
                               learning_rate=LR, progress=False)
            return np.asarray(traj)        # host fetch = hard fence

        run(guess)                         # warm-up/compile
        t0 = time.perf_counter()
        run(guess + 0.01)
        sps = nsteps / _sub_rtt(time.perf_counter() - t0, rtt)
        stats = sm.last_stats
        out[tag] = {
            "steps_per_sec": round(sps, 3),
            "overlap_frac": round(stats.overlap_fraction, 4),
            "stall_fraction": round(stats.stall_fraction, 4),
            "passes": stats.pass_summary(),
        }
    return out


def bench_ensemble_sharded(rtt, n_halos, nsteps=40, wide_nsteps=10,
                           wide_halos=2_000, n_replicas=4, ab_k=64,
                           reps=2):
    """Sharded-K vs replicated ensembles on the 2-level mesh.

    Three claims, one record:

    * **max-runnable-K at equal per-device budget** — the sharded-K
      memory model (:func:`multigrad_tpu.inference
      .ensemble_memory_model`) caps the replicated layout at
      ``max_k_replicated`` for a given budget; the same budget on R
      replica slices admits exactly R× that, and the sharded path is
      *actually run* at ``max_k_sharded`` (a width whose replicated
      state estimate exceeds the budget R-fold) to prove the rungs
      are real, with the trajectory's K axis verified partitioned.
      Off-TPU the budget is the model's arbiter (a CPU host has no
      HBM wall to hit); on TPU it is real HBM headroom.
    * **fits/hour A/B at a common K** — the same ``(ab_k, ndim)``
      batched burst through the replicated program on the flat mesh
      vs the K-partitioned program + ZeRO-partitioned Adam carry on
      the ``(replica, data)`` mesh.  On a single-core CPU host the
      compute serializes either way, so parity (~1x) is the honest
      expectation — the number exists to catch a sharded-path
      dispatch/collective regression, not to claim CPU speedup.
    * **bitwise equivalence** — an exact-arithmetic model (equal
      powers of two: every reduction exact in any association) run
      through both layouts must produce bit-identical trajectories;
      float models agree to reduction tolerance (the data-axis width
      differs between the layouts).
    """
    import multigrad_tpu as mgt
    from multigrad_tpu.inference.ensemble import (
        batched_fit_wrapper, ensemble_memory_model, max_k_for_budget)
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.optim import adam as _adam
    from multigrad_tpu.parallel import ensemble_comm

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % n_replicas:
        return None
    gcomm = mgt.global_comm()
    ecomm = ensemble_comm(n_replicas)
    rng = np.random.default_rng(0)

    def burst_rate(model, k, steps, sharded):
        guesses = np.column_stack([rng.uniform(-2.3, -1.2, k),
                                   rng.uniform(0.3, 0.8, k)])
        wrapper = batched_fit_wrapper(model, False,
                                      k_sharded=sharded)
        dynamic = model.aux_leaves()
        inits = jnp.asarray(guesses)
        carry = model.k_sharding(2) if sharded else None
        if sharded:
            inits = jax.device_put(inits, carry)

        def run():
            traj = _adam.run_adam_scan(
                wrapper, inits, nsteps=steps, learning_rate=0.02,
                progress=False, fn_args=(dynamic,),
                carry_sharding=carry)
            return traj

        traj = run()                           # warm-up/compile
        np.asarray(traj)
        spec = getattr(getattr(traj, "sharding", None), "spec", None)
        k_axis_sharded = spec is not None and "replica" in [
            s for s in jax.tree_util.tree_leaves(tuple(spec))
            if isinstance(s, str)]
        best = float("inf")
        finals = None
        for _ in range(max(int(reps), 1)):
            t0 = time.perf_counter()
            traj = run()
            arr = np.asarray(traj)             # host fetch = fence
            best = min(best, _sub_rtt(time.perf_counter() - t0, rtt))
            finals = arr[-1]
        finite = bool(np.all(np.isfinite(finals)))
        return {"fits_per_hour": round(k * 3600.0 / best, 1),
                "wall_s": round(best, 4), "k": k,
                "finite": finite,
                "k_axis_sharded": k_axis_sharded}

    # --- max-runnable-K at equal per-device budget -----------------
    per_member = ensemble_memory_model(1, 2, wide_nsteps)
    budget = 256 * per_member          # admits K=256 replicated
    max_k_rep = max_k_for_budget(budget, 2, wide_nsteps)
    max_k_sh = max_k_for_budget(budget, 2, wide_nsteps,
                                n_replicas=n_replicas)
    wide_model = SMFModel(
        aux_data=make_smf_data(wide_halos, comm=ecomm), comm=ecomm)
    wide = burst_rate(wide_model, max_k_sh, wide_nsteps,
                      sharded=True)

    # --- fits/hour A/B at a common K -------------------------------
    rep_model = SMFModel(
        aux_data=make_smf_data(n_halos, comm=gcomm), comm=gcomm)
    sh_model = SMFModel(
        aux_data=make_smf_data(n_halos, comm=ecomm), comm=ecomm)
    replicated = burst_rate(rep_model, ab_k, nsteps,
                            sharded=False)
    sharded = burst_rate(sh_model, ab_k, nsteps, sharded=True)

    # --- bitwise equivalence on the exact-arithmetic model ---------
    # The shared harness (multigrad_tpu/utils/testing.py): exact
    # fixture + paired replicated/sharded scan — one protocol for
    # the bench gate, the demo receipt and the test suite.
    from multigrad_tpu.utils.testing import bitwise_trajectory_pair

    t_rep, t_sh = bitwise_trajectory_pair(gcomm, ecomm,
                                          n_devices=n_dev)
    bitwise = bool(np.array_equal(np.asarray(t_rep),
                                  np.asarray(t_sh)))

    return {
        "n_halos": n_halos, "nsteps": nsteps, "ndim": 2,
        "mesh_devices": n_dev, "n_replicas": n_replicas,
        "budget_bytes": int(budget),
        "wide_nsteps": wide_nsteps, "wide_halos": wide_halos,
        "max_k_replicated": int(max_k_rep),
        "max_k_sharded": int(max_k_sh),
        "max_k_speedup": round(max_k_sh / max_k_rep, 3),
        "wide_run": wide,
        "ab_k": ab_k,
        "replicated": replicated,
        "sharded": sharded,
        "fits_per_hour_speedup": round(
            sharded["fits_per_hour"] / replicated["fits_per_hour"],
            3),
        "bitwise_match": bitwise,
        "note": ("max_k_* from the sharded-K memory model at the "
                 "recorded budget; wide_run executes max_k_sharded "
                 "for real on the (replica, data) mesh — off-TPU "
                 "the budget is the model's arbiter, on TPU it is "
                 "HBM headroom.  The single-core CPU A/B expects "
                 "~1x (compute serializes); the gated claims are "
                 "max_k_speedup and bitwise equivalence."),
    }


def bench_serve(n_requests, n_halos, nsteps=200, learning_rate=0.01):
    """Fit-fleet serving throughput: batched-bucket vs sequential
    dispatch, the ROADMAP's stated success metric (fits/hour on the
    mesh at batched vs. sequential dispatch).

    Both legs run the SAME burst of ``n_requests`` SMF fit requests
    through :class:`multigrad_tpu.serve.FitScheduler` — the only
    difference is bucket quantization: the batched leg packs
    compatible requests into ``(K, ndim)`` buckets dispatched through
    ONE batched Adam scan each, the sequential leg is the scheduler
    pinned to K=1 (one dispatch per request, the hand-driven serving
    posture this layer replaces).  A warm-up burst first, so both
    legs measure steady-state dispatch, not compile.

    The default catalog is deliberately modest off-TPU: the batched
    win is the amortized per-step fixed cost (program dispatch, scan
    bookkeeping, collective launches), and a single-core CPU host
    serializes the K-row compute that a real mesh runs in parallel —
    so the overhead-dominated regime is the honest CPU proxy for the
    serving workload (many small tenant fits), and the knobs ride in
    the record.
    """
    import multigrad_tpu as mgt
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler

    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    model = SMFModel(aux_data=make_smf_data(n_halos, comm=comm),
                     comm=comm)
    # Tenant guesses inside the SMF loss's well-behaved region (a
    # tiny-sigma start empties every bin — log10(0) — and an
    # unbounded fit from there goes non-finite by design, which is
    # the robustness tests' job, not the throughput bench's).
    rng = np.random.default_rng(0)
    guesses = np.column_stack([rng.uniform(-2.3, -1.2, n_requests),
                               rng.uniform(0.3, 0.8, n_requests)])
    out = {"n_requests": n_requests, "n_halos": n_halos,
           "nsteps": nsteps, "learning_rate": learning_rate,
           "mesh_devices": len(jax.devices())}

    for tag, buckets in (("batched", (1, 4, 16)),
                         ("sequential", (1,))):
        sched = FitScheduler(model, buckets=buckets,
                             batch_window_s=0.2, start=False,
                             retry_poisoned=False)

        def burst():
            futs = [sched.submit(g, nsteps=nsteps,
                                 learning_rate=learning_rate)
                    for g in guesses]
            return [f.result(timeout=600) for f in futs]

        try:
            sched.start()
            burst()                        # warm-up: compile buckets
            warm = sched.stats             # counters cover warm-up...
            t0 = time.perf_counter()
            burst()
            dt = time.perf_counter() - t0
            stats = sched.stats
        finally:
            sched.close(drain=False)
        out[tag] = {
            "buckets": list(buckets),
            "fits_per_hour": round(n_requests / dt * 3600.0, 1),
            "wall_s": round(dt, 3),
            # ...so the record reports timed-burst DELTAS, consistent
            # with wall_s/fits_per_hour.
            "dispatches": stats["dispatches"] - warm["dispatches"],
            "rows_padded": (stats.get("rows_padded", 0)
                            - warm.get("rows_padded", 0)),
        }
    out["speedup"] = round(out["batched"]["fits_per_hour"]
                           / out["sequential"]["fits_per_hour"], 3)
    return out


def bench_fleet(max_workers, n_requests, n_halos, nsteps=20,
                devices=8, batch_window_s=0.25, group=4):
    """Fleet scaling: aggregate fits/hour at 1/2/4 worker processes.

    The ROADMAP's fleet success metric: the same multi-tenant burst
    (``n_requests`` SMF fits split into ``n_requests/group`` distinct
    configs — distinct randkeys, one compiled program) served by a
    :class:`multigrad_tpu.serve.FleetRouter` over N worker processes,
    each its own jax runtime on an 8-virtual-device CPU mesh, all
    sharing ONE persistent on-disk compile cache (the fleet-wide warm
    asset: the first leg's workers pay XLA, every later worker reads
    executables back).  A warm burst precedes each timed burst, so
    the legs measure steady-state dispatch.

    What scales and why, honestly: a request's serve latency is
    coalescing window + host-side dispatch overhead + device compute.
    Independent worker processes overlap the first two; on a
    multi-core (or real fleet) host they overlap the compute too.  A
    single-core CI/container host serializes compute across workers,
    so this config keeps per-dispatch compute small and measures the
    latency-overlap regime — the honest single-host proxy for
    workers that would live on separate spot hosts, with
    ``host_cpus`` recorded so the number is never read as a
    compute-parallelism claim.
    """
    import tempfile

    from multigrad_tpu.serve import FitConfig, FleetRouter

    cache = tempfile.mkdtemp(prefix="mgt_fleet_bench_cc_")
    n_groups = max(1, n_requests // group)
    out = {"n_requests": n_requests, "n_halos": n_halos,
           "nsteps": nsteps, "group_size": group,
           "n_configs": n_groups,
           "batch_window_s": batch_window_s,
           "devices_per_worker": devices,
           "host_cpus": os.cpu_count(),
           "note": ("aggregate fits/hour over the timed burst, "
                    "coalescing windows included; on a single-core "
                    "host the 1->N scaling measures dispatch-latency "
                    "overlap across worker processes (compute "
                    "serializes), the honest proxy for workers on "
                    "separate hosts")}
    rng = np.random.default_rng(0)
    guesses = np.column_stack([
        rng.uniform(-2.3, -1.5, n_requests),
        rng.uniform(0.35, 0.6, n_requests)])
    configs = [FitConfig(nsteps=nsteps, learning_rate=0.03,
                         randkey=1000 + g) for g in range(n_groups)]
    base = None
    for n in [w for w in (1, 2, 4) if w <= max_workers]:
        router = FleetRouter(
            n_workers=n, model_kwargs={"num_halos": n_halos},
            devices=devices, buckets=(group * 2,),
            batch_window_s=batch_window_s, shed_inflight=group,
            compile_cache=cache, heartbeat_s=0.1,
            heartbeat_timeout_s=10.0)

        def burst():
            # min(): a trailing partial group (n_requests not a
            # multiple of group) rides with the last config.
            futs = [router.submit(
                        guesses[i],
                        config=configs[min(i // group,
                                           n_groups - 1)])
                    for i in range(n_requests)]
            return [f.result(timeout=900) for f in futs]

        try:
            burst()                    # warm: compile + prime cache
            t0 = time.perf_counter()
            burst()
            dt = time.perf_counter() - t0
            stats = router.stats
        finally:
            router.close(drain=False)
        leg = {"workers": n,
               "fits_per_hour": round(n_requests / dt * 3600.0, 1),
               "wall_s": round(dt, 3),
               "requeued": stats.get("requeued", 0),
               "rejected": stats.get("rejected", 0),
               "worker_deaths": stats.get("worker_deaths", 0)}
        if base is None:
            base = leg["fits_per_hour"]
        else:
            leg["speedup"] = round(leg["fits_per_hour"] / base, 3)
        out[f"workers{n}"] = leg
    return out


def bench_posterior_pipeline(rtt, n_halos, n_points=8, n_starts=8,
                             sweep_nsteps=40, nsteps=120,
                             hmc_samples=80, hmc_warmup=100):
    """Joint-posterior pipeline throughput: the north-star workload
    as ONE submitted job (PR 16's tentpole).

    A single :class:`multigrad_tpu.serve.Job` — scan → ensemble →
    Laplace → HMC → posterior-predictive check over the fused
    SMF+wprp joint likelihood — runs through a
    :class:`~multigrad_tpu.serve.JobRunner` over the serve
    scheduler.  A warm job first (bucket + HMC compiles), then the
    timed job; the record carries wall per stage, fleet-dispatched
    fits/hour, and jobs/hour.

    The gated number is ``fit_stage_dispatch_speedup``: the same
    scan+ensemble fit burst submitted RAW to the scheduler (no job
    machinery) over the pipeline's fit-stage wall — the job layer's
    dispatch overhead as a host-independent ratio (~1.0 when stage
    fan-out adds nothing over hand-driven submits; a collapse means
    the runner serialized or re-dispatched work).  Absolute
    fits/hour rides in the record but is untracked across hosts.
    """
    from multigrad_tpu.models.joint import make_joint_smf_wprp
    from multigrad_tpu.serve import (EnsembleStage, FitConfig,
                                     FitScheduler, HmcStage, Job,
                                     JobRunner, LaplaceStage,
                                     PredictiveCheckStage,
                                     SweepStage)

    bounds = ((-3.5, -0.5), (0.02, 1.0), (-2.5, 0.5))
    model = make_joint_smf_wprp(num_halos=n_halos, seed=1)
    n_fits = n_points + n_starts

    def make_job():
        return Job(stages=[
            SweepStage(name="scan", n_points=n_points,
                       nsteps=sweep_nsteps, learning_rate=0.1,
                       param_bounds=bounds),
            EnsembleStage(name="ensemble", deps=("scan",),
                          n_starts=n_starts, nsteps=nsteps,
                          learning_rate=0.02, param_bounds=bounds),
            LaplaceStage(name="laplace", deps=("ensemble",)),
            HmcStage(name="hmc", deps=("laplace",),
                     num_samples=hmc_samples,
                     num_warmup=hmc_warmup, num_chains=2),
            PredictiveCheckStage(name="check", deps=("hmc",),
                                 max_draws=16),
        ])

    rng = np.random.default_rng(0)
    low = np.array([b[0] for b in bounds])
    high = np.array([b[1] for b in bounds])
    guesses = low + rng.random((n_fits, 3)) * (high - low)
    cfg_scan = FitConfig(nsteps=sweep_nsteps, learning_rate=0.1,
                         param_bounds=bounds)
    cfg_ens = FitConfig(nsteps=nsteps, learning_rate=0.02,
                        param_bounds=bounds)

    def raw_burst():
        futs = [sched.submit(g, config=cfg_scan)
                for g in guesses[:n_points]]
        futs += [sched.submit(g, config=cfg_ens)
                 for g in guesses[n_points:]]
        return [f.result(timeout=900) for f in futs]

    sched = FitScheduler(model, buckets=(1, 4, 8),
                         batch_window_s=0.02, retry_poisoned=False)
    runner = JobRunner(sched)
    try:
        runner.run(make_job(), timeout=1800)   # warm: compiles
        t0 = time.perf_counter()
        result = runner.run(make_job(), timeout=1800)
        wall = time.perf_counter() - t0
        raw_burst()                            # warm the raw path
        t0 = time.perf_counter()
        raw_burst()
        raw_wall = time.perf_counter() - t0
    finally:
        sched.close(drain=False)

    fit_wall = sum(result.stages[s].elapsed_s
                   for s in ("scan", "ensemble"))
    return {
        "n_halos": n_halos, "n_points": n_points,
        "n_starts": n_starts, "sweep_nsteps": sweep_nsteps,
        "nsteps": nsteps,
        "hmc": {"num_samples": hmc_samples,
                "num_warmup": hmc_warmup, "num_chains": 2},
        "stages_ok": sum(r.ok for r in result.stages.values()),
        "outcomes": result.outcomes(),
        "check_ok": bool(result.artifact("check").get("ok"))
        if result.ok else None,
        "wall_s": round(wall, 3),
        "jobs_per_hour": round(3600.0 / wall, 1),
        "fits_per_hour": round(n_fits / wall * 3600.0, 1),
        "stage_wall": {name: round(r.elapsed_s, 3)
                       for name, r in result.stages.items()},
        "fit_stage_wall_s": round(fit_wall, 3),
        "raw_burst_wall_s": round(raw_wall, 3),
        "fit_stage_dispatch_speedup": round(raw_wall / fit_wall, 3),
        "note": ("one full posterior pipeline per timed job; "
                 "fits/hour counts the fleet-dispatched scan+"
                 "ensemble fits over the WHOLE job wall (Laplace/"
                 "HMC/check ride in it), so it is a pipeline "
                 "number, not a dispatch number; the gated "
                 "dispatch_speedup cancels host speed"),
    }


def bench_qos_mixed_load(n_heavy, n_interactive, n_halos,
                         nsteps=10):
    """Multi-tenant QoS under a 10:1 mixed-tenant overload (PR 17's
    tentpole): FIFO vs policy-driven scheduling, same burst.

    Both legs run the SAME worst-case arrival order through
    :class:`multigrad_tpu.serve.FitScheduler` — a heavy ``hog``
    tenant floods ``n_heavy`` batch-class fits FIRST, then a light
    ``lab`` tenant submits ``n_interactive`` interactive-class fits
    behind them (distinct configs per tenant, so nothing co-batches
    across the boundary and the dequeue policy alone decides who
    runs).  The FIFO leg drains in arrival order: the light tenant
    waits out the entire heavy backlog.  The QoS leg
    (``qos=True``) runs deficit round-robin over tenants + EDF, so
    the light tenant gets its fair share of dispatch slots the
    moment it shows up.

    Two gated, host-independent numbers:

    * ``interactive_p95_speedup`` — the light tenant's p95 queue
      wait, FIFO over QoS (how much tail latency the policy
      returns to the protected class; ~1.0 would mean the policy
      does nothing);
    * ``fairness_index`` — Jain's index over per-tenant dispatch
      counts inside the contended window (while BOTH tenants are
      backlogged, equal weights say they split slots evenly: QoS
      ≈ 1.0, FIFO ≈ the heavy tenant taking every slot).

    A warm-up burst over both configs precedes the legs (through
    the persistent compile cache both legs then read), so the
    waits measure steady-state scheduling, not compile — the FIFO
    leg runs first and would otherwise absorb XLA alone, inflating
    the ratio with host-dependent compile cost.  Absolute waits
    ride in the record untracked.
    """
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler
    from multigrad_tpu.serve.qos import jain_fairness

    model = SMFModel(aux_data=make_smf_data(n_halos, comm=None),
                     comm=None)
    rng = np.random.default_rng(0)

    def guesses(n):
        return np.column_stack([rng.uniform(-2.3, -1.5, n),
                                rng.uniform(0.35, 0.6, n)])

    # Warm-up: compile the (4, 2) bucket program for both configs
    # so neither timed leg pays XLA inside a measured wait.
    warm = FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                        retry_poisoned=False)
    try:
        done = [warm.submit(g, nsteps=nsteps, learning_rate=0.03,
                            randkey=k)
                for k in (7, 9) for g in guesses(4)]
        for f in done:
            f.result(timeout=600)
    finally:
        warm.close(drain=False)

    def leg(qos):
        sched = FitScheduler(model, buckets=(4,),
                             batch_window_s=0.0, start=False,
                             retry_poisoned=False, qos=qos)
        try:
            t0 = time.perf_counter()
            heavy = [sched.submit(g, nsteps=nsteps,
                                  learning_rate=0.03, randkey=7,
                                  tenant="hog",
                                  priority_class="batch")
                     for g in guesses(n_heavy)]
            light = [sched.submit(g, nsteps=nsteps,
                                  learning_rate=0.03, randkey=9,
                                  tenant="lab",
                                  priority_class="interactive")
                     for g in guesses(n_interactive)]
            sched.start()
            hres = [f.result(timeout=600) for f in heavy]
            lres = [f.result(timeout=600) for f in light]
            wall = time.perf_counter() - t0
        finally:
            sched.close(drain=False)
        lwaits = sorted(r.wait_s for r in lres)
        # The contended window: while the light tenant still has
        # queued work.  Equal-weight fairness says the tenants
        # split dispatch slots evenly inside it.
        window_end = max(lwaits)
        heavy_in_window = sum(1 for r in hres
                              if r.wait_s <= window_end)
        return {
            "interactive_p95_wait_s": round(
                float(np.percentile(lwaits, 95)), 4),
            "interactive_mean_wait_s": round(
                float(np.mean(lwaits)), 4),
            "heavy_mean_wait_s": round(
                float(np.mean([r.wait_s for r in hres])), 4),
            "heavy_in_window": heavy_in_window,
            "fairness_index": round(jain_fairness(
                [heavy_in_window, n_interactive]), 4),
            "wall_s": round(wall, 3),
        }

    fifo = leg(qos=False)
    qos = leg(qos=True)
    return {
        "n_heavy": n_heavy, "n_interactive": n_interactive,
        "n_halos": n_halos, "nsteps": nsteps,
        "fifo": fifo, "qos": qos,
        "interactive_p95_speedup": round(
            fifo["interactive_p95_wait_s"]
            / max(qos["interactive_p95_wait_s"], 1e-9), 3),
        "fairness_index": qos["fairness_index"],
        "note": ("worst-case arrival (heavy burst first); waits "
                 "are queue waits from FitResult.wait_s; the "
                 "gated speedup and fairness_index cancel host "
                 "speed and compile cost"),
    }


def bench_resource_monitor_overhead(n_fits, n_halos, nsteps=10,
                                    reps=2):
    """Scheduler throughput with the PR-18 :class:`~multigrad_tpu
    .telemetry.ResourceMonitor` on vs off — the "observability is
    free" claim, measured.

    Both legs push the same ``n_fits`` single-config burst through
    :class:`multigrad_tpu.serve.FitScheduler`; the monitored leg
    additionally runs the default sampler thread (0.5 s interval),
    the dispatch duty-cycle hooks, the compile observer, and the
    per-dispatch memory-truth record.  A warm-up burst precedes the
    legs (both then read the warm program cache), and each leg takes
    best-of-``reps``, so the gated number compares steady-state
    dispatch loops, not compile or a scheduling hiccup.

    Gated: ``monitored_speedup`` — monitored over unmonitored
    fits/hour (~1.0; regress fails if monitoring costs more than the
    round's ``--pct``).  Rides along untracked:
    ``memory_model_accuracy_frac`` — mean ``1 - |measured peak −
    modeled| / modeled`` over the monitored leg's per-dispatch
    ``measured_vs_modeled`` records (null on CPU where
    ``memory_stats()`` is unavailable → the regress gate warns
    instead of failing; on TPU rounds it gates memory-model drift).
    """
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger

    model = SMFModel(aux_data=make_smf_data(n_halos, comm=None),
                     comm=None)
    rng = np.random.default_rng(3)

    def guesses(n):
        return np.column_stack([rng.uniform(-2.3, -1.5, n),
                                rng.uniform(0.35, 0.6, n)])

    warm = FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                        retry_poisoned=False,
                        monitor_resources=False)
    try:
        for f in [warm.submit(g, nsteps=nsteps, learning_rate=0.03)
                  for g in guesses(4)]:
            f.result(timeout=600)
    finally:
        warm.close(drain=False)

    def leg(monitored):
        # BOTH legs log telemetry to a MemorySink so the only delta
        # is the monitor itself (sampler thread, dispatch hooks,
        # compile observer, memory-truth records) — not the cost of
        # having a telemetry logger at all.
        sink = MemorySink()
        logger = MetricsLogger(sink)
        best_wall, extra = None, {}
        for _ in range(reps):
            sched = FitScheduler(model, buckets=(4,), start=False,
                                 batch_window_s=0.0,
                                 retry_poisoned=False,
                                 telemetry=logger,
                                 monitor_resources=monitored)
            try:
                t0 = time.perf_counter()
                futs = [sched.submit(g, nsteps=nsteps,
                                     learning_rate=0.03)
                        for g in guesses(n_fits)]
                sched.start()
                for f in futs:
                    f.result(timeout=600)
                wall = time.perf_counter() - t0
                if monitored and sched.resources is not None:
                    extra = {
                        "samples": len(sched.resources.ring()),
                        "degraded": sched.resources.degraded,
                    }
            finally:
                sched.close(drain=False)
            if best_wall is None or wall < best_wall:
                best_wall = wall
        out = {"wall_s": round(best_wall, 3),
               "fits_per_hour": round(3600.0 * n_fits / best_wall,
                                      1), **extra}
        if monitored:
            accs = [r["accuracy_frac"] for r in sink.records
                    if r.get("event") == "measured_vs_modeled"
                    and r.get("accuracy_frac") is not None]
            out["memory_model_accuracy_frac"] = (
                round(float(np.mean(accs)), 4) if accs else None)
        logger.close()
        return out

    off = leg(monitored=False)
    on = leg(monitored=True)
    return {
        "n_fits": n_fits, "n_halos": n_halos, "nsteps": nsteps,
        "unmonitored": off, "monitored": on,
        "monitored_speedup": round(
            on["fits_per_hour"] / max(off["fits_per_hour"], 1e-9),
            3),
        "memory_model_accuracy_frac":
            on.get("memory_model_accuracy_frac"),
        "note": ("same burst, warm program cache, best-of-reps per "
                 "leg; speedup ~1.0 means the sampler thread + "
                 "dispatch hooks + memory-truth records are free; "
                 "accuracy_frac null off-TPU (no memory_stats)"),
    }


def bench_rollup_overhead(n_fits, n_halos, nsteps=10, reps=2):
    """Scheduler throughput with the PR-20 telemetry history plane
    (:class:`~multigrad_tpu.telemetry.RollupStore` + SLO error-budget
    ledgers) on vs off — "history is free", measured.

    Both legs push the same ``n_fits`` burst with QoS tagging and a
    declared interactive SLO through :class:`multigrad_tpu.serve
    .FitScheduler`; the history leg additionally folds every settle
    into the tiered rollup windows, runs the 10 s scrape thread,
    feeds the per-class :class:`~multigrad_tpu.telemetry.SloBudget`
    burn-rate ledgers, and emits ``tenant_usage`` / ``slo_budget``
    records.  The baseline leg passes ``history=False`` and an
    externally built :class:`~multigrad_tpu.serve.slo.SloMonitor`
    with ``budgets=False``, so the only delta is the history plane
    itself — not QoS, not the SLO histograms, not the telemetry
    logger.  Warm-up burst first, best-of-``reps`` per leg, same as
    the resource-monitor bench.

    Gated: ``rollup_speedup`` — history-on over history-off
    fits/hour (~1.0; regress fails if the rollup sink + budget
    engine cost more than the round's ``--pct``).
    """
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import FitScheduler
    from multigrad_tpu.serve.slo import SloMonitor
    from multigrad_tpu.telemetry import MemorySink, MetricsLogger

    model = SMFModel(aux_data=make_smf_data(n_halos, comm=None),
                     comm=None)
    rng = np.random.default_rng(5)
    slos = ["p95 < 30 for interactive"]

    def guesses(n):
        return np.column_stack([rng.uniform(-2.3, -1.5, n),
                                rng.uniform(0.35, 0.6, n)])

    warm = FitScheduler(model, buckets=(4,), batch_window_s=0.0,
                        retry_poisoned=False,
                        monitor_resources=False, history=False)
    try:
        for f in [warm.submit(g, nsteps=nsteps, learning_rate=0.03)
                  for g in guesses(4)]:
            f.result(timeout=600)
    finally:
        warm.close(drain=False)

    def leg(history):
        # BOTH legs log telemetry to a MemorySink and run QoS + the
        # SLO histograms, so the only delta is the history plane
        # (rollup folds, scrape thread, budget ledgers, usage
        # records) — not the cost of observability at all.
        sink = MemorySink()
        logger = MetricsLogger(sink)
        # history leg: scheduler builds SloMonitor(budgets=True)
        # from the strings; baseline leg: same monitor minus the
        # budget ledgers (same registry — none — on both legs).
        slo = (slos if history
               else SloMonitor(None, slos, budgets=False))
        best_wall, extra = None, {}
        for _ in range(reps):
            sched = FitScheduler(model, buckets=(4,), start=False,
                                 batch_window_s=0.0,
                                 retry_poisoned=False,
                                 telemetry=logger, qos=True,
                                 slo=slo,
                                 monitor_resources=False,
                                 history=history)
            try:
                t0 = time.perf_counter()
                futs = [sched.submit(g, nsteps=nsteps,
                                     learning_rate=0.03,
                                     tenant="bench",
                                     priority_class="interactive")
                        for g in guesses(n_fits)]
                sched.start()
                for f in futs:
                    f.result(timeout=600)
                wall = time.perf_counter() - t0
                if history and sched.rollup is not None:
                    extra = {"usage_pairs":
                             len(sched.rollup.usage_records())}
            finally:
                sched.close(drain=False)
            if best_wall is None or wall < best_wall:
                best_wall = wall
        logger.close()
        return {"wall_s": round(best_wall, 3),
                "fits_per_hour": round(3600.0 * n_fits / best_wall,
                                       1), **extra}

    off = leg(history=False)
    on = leg(history=True)
    return {
        "n_fits": n_fits, "n_halos": n_halos, "nsteps": nsteps,
        "history_off": off, "history_on": on,
        "rollup_speedup": round(
            on["fits_per_hour"] / max(off["fits_per_hour"], 1e-9),
            3),
        "note": ("same QoS-tagged burst, warm program cache, "
                 "best-of-reps per leg; speedup ~1.0 means the "
                 "rollup folds + scrape thread + budget ledgers are "
                 "free"),
    }


def bench_reference_style(data, rtt, guess):
    """The reference's execution shape, ported faithfully: per-bin
    jitted kernels in a Python loop, vjp/grad/collectives interleaved
    on the host, optimizer stepping in Python."""
    log_mh = jnp.asarray(data["log_halo_masses"])
    edges = np.asarray(data["smf_bin_edges"])
    volume = data["volume"]
    target = jnp.log10(jnp.asarray(data["target_sumstats"]))

    @jax.jit
    def calc_smf_bin(params, lo, hi):
        mean = log_mh + params[0]
        cdf_hi = 0.5 * (1 + jax.scipy.special.erf(
            (hi - mean) / (jnp.sqrt(2.0) * params[1])))
        cdf_lo = 0.5 * (1 + jax.scipy.special.erf(
            (lo - mean) / (jnp.sqrt(2.0) * params[1])))
        return jnp.sum(cdf_hi - cdf_lo) / volume / (hi - lo)

    def sumstats_fn(params):
        return jnp.array([calc_smf_bin(params, lo, hi)
                          for lo, hi in zip(edges[:-1], edges[1:])])

    def loss_fn(y):
        return jnp.mean((jnp.log10(y) - target) ** 2)

    grad_loss = jax.grad(loss_fn)

    def loss_and_grad(params):
        y, vjp = jax.vjp(sumstats_fn, params)
        dloss_dy = grad_loss(y)
        return loss_fn(y), vjp(dloss_dy)[0]

    tx = optax.adam(LR)

    def run(guess, nsteps):
        params = guess
        state = tx.init(params)
        for _ in range(nsteps):
            _, g = loss_and_grad(params)
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
        return np.asarray(params)         # host fetch = hard fence

    run(guess, 3)                         # warm-up/compile
    n = 20                                # host-loop is slow; sample
    best = 0.0
    for k in range(2):
        t0 = time.perf_counter()
        run(guess + 0.01 * (k + 1), n)
        best = max(best, n / _sub_rtt(time.perf_counter() - t0, rtt))
    return best


def main():
    import argparse

    ap = argparse.ArgumentParser(
        description="multigrad_tpu benchmark dossier driver")
    ap.add_argument(
        "--only", default=None,
        help="comma list of config names to measure (others are "
             "skipped entirely — used by CI's fused-bins A/B smoke "
             "step); default: the full dossier")
    ap.add_argument(
        "--fused-rows", type=int, default=None,
        help="row count for the fused-bins A/B (default: 4e6 on TPU, "
             "1e6 off-TPU; CI's smoke step passes a smaller value to "
             "fit the per-push budget)")
    ap.add_argument(
        "--serve-requests", type=int, default=None,
        help="request-burst size for the serve_fits_per_hour config "
             "(default: 64 on TPU, 48 off-TPU)")
    ap.add_argument(
        "--fleet-workers", type=int, default=None,
        help="max worker-process count for the fleet_fits_per_hour "
             "config (legs at 1/2/4 capped here; default 4 — CI's "
             "smoke step passes 2 to fit the per-push budget)")
    ap.add_argument(
        "--fleet-requests", type=int, default=None,
        help="burst size per fleet leg (default 64)")
    ap.add_argument(
        "--qos-heavy", type=int, default=None,
        help="heavy-tenant burst size for the qos_mixed_load config "
             "(default 40; the interactive burst stays at a 10:1 "
             "ratio unless --qos-interactive overrides it)")
    ap.add_argument(
        "--qos-interactive", type=int, default=None,
        help="protected-class burst size for qos_mixed_load "
             "(default: heavy/10, min 4)")
    ap.add_argument(
        "--pipeline-halos", type=int, default=None,
        help="wprp catalog rows for the posterior_pipeline_fits_"
             "per_hour config (SMF member gets 4x; default: 2048 on "
             "TPU, 512 off — CI's smoke step passes a smaller value "
             "to fit the per-push budget)")
    ap.add_argument(
        "--tuned", action="store_true",
        help="measure the tuned-vs-handset configs (tuned_defaults "
             "+ smf_1e6_tuned): run the autotuner, then record the "
             "tuner-resolved settings next to the hand-set defaults "
             "(+ tuning-table provenance) — the pairs the "
             "`telemetry.regress --tuned` gate judges.  Off by "
             "default (they are recorded as deliberately-skipped "
             "nulls, like TPU-only configs off-TPU)")
    ap.add_argument(
        "--tuning-table", default=None,
        help="tuning-table path for --tuned (default: "
             ".bench_tuning.<backend>.json beside the partial "
             "dossier — a re-run warm-starts from it with zero "
             "measured trials, recorded in the provenance)")
    ap.add_argument(
        "--serve", nargs="?", const=0, default=None, type=int,
        metavar="PORT",
        help="start the live observability endpoint for the run "
             "(/metrics Prometheus exposition + /status JSON, served "
             "from a daemon thread) — a dossier run takes tens of "
             "minutes through the tunnel, and this is how you watch "
             "it without tailing logs.  PORT 0 (the bare-flag "
             "default) picks a free port, printed to stderr")
    cli, _ = ap.parse_known_args()
    only = set(cli.only.split(",")) if cli.only else None

    try:
        # Persistent compilation cache: the dossier compiles ~8 large
        # programs; caching them (verified to work through the axon
        # tunnel) cuts repeat runs by minutes.
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/multigrad_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception as e:                 # older jax: no such flags
        print(f"compilation cache unavailable: {e}", file=sys.stderr)
    backend, _ = init_backend_with_retry()
    on_tpu = backend == "tpu"
    guess = jnp.array(GUESS)
    rtt = measure_fetch_rtt()

    # Incremental dossier: each config's number is persisted the
    # moment it exists, and a re-run re-measures only the holes — a
    # tunnel outage 20 minutes in no longer voids the 19 minutes of
    # numbers already taken (that failure mode cost round 4 its
    # entire TPU dossier).
    cfgs, measured_at = load_partial(backend)

    # Telemetry stream beside the timing JSON: one `bench` record per
    # measured config, run-record provenance up front, readable with
    # `python -m multigrad_tpu.telemetry.report <file>`.
    from multigrad_tpu.telemetry import JsonlSink, MetricsLogger
    partial = _partial_path(backend)
    telemetry_path = (partial[:-len(".json")]
                      if partial.endswith(".json") else partial) \
        + ".telemetry.jsonl"
    telemetry = MetricsLogger(
        JsonlSink(telemetry_path),
        run_config={"rtt_ms": round(rtt * 1e3, 3), "on_tpu": on_tpu})

    if cli.serve is not None:
        # Live view of the dossier run: every `bench` record lands in
        # the endpoint's registry as it is measured.  The server is a
        # daemon thread — it dies with the process.
        from multigrad_tpu.telemetry import LiveServer
        live_server = LiveServer(port=cli.serve)
        telemetry.add_sink(live_server)
        print(f"live endpoint: {live_server.url}/metrics  "
              f"{live_server.url}/status", file=sys.stderr)

    measured_now = set()   # configs actually measured THIS invocation

    def _record(pairs):
        for name, val in pairs:
            cfgs[name] = val
            measured_at[name] = time.time()
            measured_now.add(name)
            print(f"measured: {name} = {val}", file=sys.stderr)
            telemetry.log("bench", config=name, value=val)
        save_partial(backend, cfgs, measured_at)

    def measure(name, thunk, rnd_k=2):
        if only is not None and name not in only:
            return cfgs.get(name)
        if name in cfgs:
            print(f"cached: {name} = {cfgs[name]}", file=sys.stderr)
            return cfgs[name]
        val = thunk()
        if isinstance(val, float):
            val = round(val, rnd_k)
        _record([(name, val)])
        return val

    def attribute_roofline(config, model_thunk, steps_per_sec,
                           sources):
        """Join the static cost model against a measured rate: one
        ``roofline`` telemetry record per attributed config — "model
        says N·E erf + 48 B/step; chip delivered X% of roofline".
        Trace-only (zero device FLOPs); a failure only costs the
        record, never the dossier.  Runs only when one of ``sources``
        was measured THIS invocation: a fully-cached resume (or an
        --only run that skipped them) must not rebuild datasets nor
        append duplicate roofline records — the same skip semantics
        as ``measure`` itself."""
        if not steps_per_sec or not (set(sources) & measured_now):
            return
        try:
            from multigrad_tpu.telemetry import (model_cost,
                                                 roofline_record)
            cost = model_cost(model_thunk(), guess)
            telemetry.log("roofline", config=config,
                          **roofline_record(cost,
                                            1.0 / steps_per_sec))
        except Exception as e:
            print(f"roofline attribution for {config} skipped: {e}",
                  file=sys.stderr)

    def measure_pair(names, thunk, rnd_k=2):
        """Two configs that share one expensive setup (dataset build /
        warm state): measured together when either is missing."""
        if only is not None and not (set(names) & only):
            return tuple(cfgs.get(n) for n in names)
        if all(n in cfgs for n in names):
            for n in names:
                print(f"cached: {n} = {cfgs[n]}", file=sys.stderr)
            return tuple(cfgs[n] for n in names)
        vals = tuple(round(v, rnd_k) if isinstance(v, float) else v
                     for v in thunk())
        _record(list(zip(names, vals)))
        return vals

    # Off-TPU (the labelled fallback when the chip is unreachable)
    # the TPU-sized step counts would take an hour of CPU; scale the
    # fit lengths down — the metric name carries the backend, so the
    # number is never mistaken for a TPU result.
    nsteps = NSTEPS if on_tpu else NSTEPS // 10
    group_nsteps = 2000 if on_tpu else 200

    # The 1e6-halo dataset feeds four configs; build it at most once
    # per process (on a fully-cached resume: never).
    @functools.cache
    def data_1e6():
        return build_smf_data(NUM_HALOS)

    # Headline + kernel A/B at 1e6 halos.  Off-TPU only the XLA path
    # is measured (pallas would run in interpret mode — not a perf
    # path; "auto" makes the same call).
    sps_xla = measure(
        "smf_1e6_xla_steps_per_sec",
        lambda: bench_fused_fit(data_1e6(), nsteps, rtt, guess,
                                backend="xla"))
    sps_pallas = measure(
        "smf_1e6_pallas_steps_per_sec",
        lambda: bench_fused_fit(data_1e6(), nsteps, rtt, guess,
                                backend="pallas") if on_tpu else None)
    headline = max(sps_xla or 0.0, sps_pallas or 0.0)

    from multigrad_tpu.models.smf import SMFModel
    attribute_roofline(
        "smf_1e6_adam_step",
        lambda: SMFModel(aux_data=dict(data_1e6()), comm=None),
        headline,
        sources=("smf_1e6_xla_steps_per_sec",
                 "smf_1e6_pallas_steps_per_sec"))

    # 1e8 halos (BASELINE config 4's single-chip scale), both paths:
    # the XLA chunked + remat lax.scan tiling (ops/binned.py), and the
    # pallas kernel streaming VMEM-sized blocks over the same array.
    # The legs share the (expensive) dataset build via the lazy memo,
    # but each persists independently — a tunnel death during the
    # pallas leg must not discard the measured XLA number.
    @functools.cache
    def data_1e8():
        return build_smf_data(BIG_HALOS, chunk_size=BIG_CHUNK)

    big_xla_sps = measure(
        "smf_1e8_chunked_xla_steps_per_sec",
        lambda: bench_fused_fit(data_1e8(), BIG_NSTEPS, rtt, guess,
                                backend="xla", reps=2)
        if on_tpu else None)
    big_pallas_sps = measure(
        "smf_1e8_pallas_steps_per_sec",
        lambda: bench_fused_fit(data_1e8(), BIG_NSTEPS, rtt, guess,
                                backend="pallas", reps=2)
        if on_tpu else None)
    data_1e8.cache_clear()

    # 1e9 halos — the full-pod dataset size — streamed through ONE
    # chip's pallas kernel (4 GB of HBM; the XLA remat path works too
    # but the 1e8 A/B already records its cost).  A pod shards this
    # over the data axis for pure data-parallel speedup on top.
    def huge():
        if not on_tpu:
            return None
        data_1e9 = build_smf_data(HUGE_HALOS, chunk_size=BIG_CHUNK)
        return bench_fused_fit(data_1e9, HUGE_NSTEPS, rtt, guess,
                               backend="pallas", reps=2)

    huge_sps = measure("smf_1e9_pallas_steps_per_sec", huge)

    # wp(rp) pair-kernel A/B (fwd+bwd).
    wprp_xla = measure(
        "wprp_8192_fwdbwd_ms_xla",
        lambda: bench_wprp_eval(rtt, "xla") if on_tpu else None,
        rnd_k=3)
    wprp_pallas = measure(
        "wprp_8192_fwdbwd_ms_pallas",
        lambda: bench_wprp_eval(rtt, "pallas") if on_tpu else None,
        rnd_k=3)

    # Catalog-scale pair counts (the clustering workload's real
    # regime): 1e5 halos with a few amortized evals, 1e6 with one —
    # a single fwd+bwd at 1e6 is ~1e12 pair-bin ops.
    # XLA row_chunks must divide N and bound the (chunk, N) sep²
    # block (500 x 1e6 f32 = 2 GB); the pallas tile is VMEM-capped at
    # 512 regardless.  One rep at 1e6: a single fwd+bwd is O(1e12)
    # pair-bin ops (~minutes), and the warm-up penalty is <1% of it.
    pair_1e5_xla = measure(
        "pair_1e5_fwdbwd_s_xla",
        lambda: bench_pair_counts_scale(
            rtt, "xla", 100_000, row_chunk=4_000, inner=3)
        if on_tpu else None, rnd_k=3)
    pair_1e5_pallas = measure(
        "pair_1e5_fwdbwd_s_pallas",
        lambda: bench_pair_counts_scale(
            rtt, "pallas", 100_000, row_chunk=512, inner=3)
        if on_tpu else None, rnd_k=3)
    pair_1e6_xla = measure(
        "pair_1e6_fwdbwd_s_xla",
        lambda: bench_pair_counts_scale(
            rtt, "xla", 1_000_000, row_chunk=500, inner=1, reps=1)
        if on_tpu else None, rnd_k=3)
    pair_1e6_pallas = measure(
        "pair_1e6_fwdbwd_s_pallas",
        lambda: bench_pair_counts_scale(
            rtt, "pallas", 1_000_000, row_chunk=512, inner=1, reps=1)
        if on_tpu else None, rnd_k=3)
    hist_1e8_sps = measure(
        "galhalo_hist_1e8_adam_steps_per_sec",
        lambda: bench_galhalo_hist(rtt) if on_tpu else None)
    hist_1e9_s = measure(
        "galhalo_hist_1e9_loss_and_grad_s",
        lambda: bench_galhalo_hist_1e9(rtt) if on_tpu else None,
        rnd_k=3)

    # PR 7's three hot-path fronts, each as a measured A/B (the
    # acceptance evidence is a number in this dossier, not prose).
    # (1) Fused scatter-into-bins vs the dense edge sweep.
    from multigrad_tpu.ops.binned import fused_bin_window
    fused_ab = measure(
        "galhalo_hist_fused_bins_ab",
        lambda: bench_fused_bins_ab(
            rtt, cli.fused_rows
            or (4_000_000 if on_tpu else 1_000_000)), rnd_k=4)

    def hist_1e8_fused():
        edges = np.linspace(7.0, 11.75, 41)
        return bench_galhalo_hist(
            rtt, bin_edges=edges, obs_indices=(5, 7, 9, 11, 13, 15),
            bin_mode="fused", bin_window=fused_bin_window(edges, 0.32))

    hist_1e8_fused_sps = measure(
        "galhalo_hist_1e8_fused",
        lambda: hist_1e8_fused() if on_tpu else None)

    @functools.cache
    def data_1e6_fused():
        from multigrad_tpu.models.smf import make_smf_data
        edges = np.linspace(9, 10, 11)
        return make_smf_data(
            NUM_HALOS, comm=None, bin_mode="fused",
            bin_window=fused_bin_window(edges, 0.6))

    smf_fused_sps = measure(
        "smf_1e6_fused_bins",
        lambda: bench_fused_fit(data_1e6_fused(), nsteps, rtt, guess))
    attribute_roofline(
        "smf_1e6_fused_bins_step",
        lambda: SMFModel(aux_data=dict(data_1e6_fused()), comm=None),
        smf_fused_sps, sources=("smf_1e6_fused_bins",))

    # Autotuner A/B: the tuner-resolved default vs the hand-set knobs
    # on the fused-bins canonical fixture + the headline config
    # (--tuned; skipped-as-null otherwise, like TPU-only configs
    # off-TPU).  The tuning table lives beside the partial dossier so
    # a resumed round warm-starts with zero measured trials.
    tuning_table_path = cli.tuning_table \
        or os.environ.get("MGT_TUNING_TABLE") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f".bench_tuning.{backend}.json")
    if cli.tuned:
        # The tuned legs exercise the REAL consumer path — "auto"
        # knobs resolved at model construction — and that path reads
        # the default table location; point it at this round's table.
        os.environ["MGT_TUNING_TABLE"] = tuning_table_path
    tuned_ab = measure(
        "tuned_defaults",
        lambda: bench_tuned_defaults(
            rtt, cli.fused_rows or (4_000_000 if on_tpu
                                    else 1_000_000),
            tuning_table_path, telemetry=telemetry)
        if cli.tuned else None, rnd_k=4)
    smf_tuned = measure(
        "smf_1e6_tuned",
        lambda: bench_smf_tuned(data_1e6(), nsteps, rtt, guess,
                                tuning_table_path,
                                telemetry=telemetry)
        if cli.tuned else None)

    # (2) Donated vs copied Adam carry on the whole-fit scan.
    donated_ab = measure(
        "adam_donated_steps_per_sec",
        lambda: bench_adam_donated(data_1e6(), nsteps, rtt, guess))

    # (3) Overlapped vs serial streamed two-pass loss-and-grad.
    overlap_ab = measure(
        "streaming_overlap_frac",
        lambda: bench_streaming_overlap(
            rtt, guess, BIG_HALOS if on_tpu else NUM_HALOS,
            4_194_304 if on_tpu else 131_072,
            nsteps=5 if on_tpu else 3))

    # Fused-vs-hostloop joint fit: two numbers, one shared warm state.
    group_fused_sps, group_host_sps = measure_pair(
        ("group_2x5e5_fused_adam_steps_per_sec",
         "group_2x5e5_hostloop_adam_steps_per_sec"),
        lambda: bench_group_fit(rtt, guess, nsteps=group_nsteps,
                                host_nsteps=100 if on_tpu else 20))

    # Streaming (out-of-core) chunk-size sweep: steps/s + chunks/s +
    # bytes streamed + prefetch-stall fraction per chunk size.  On
    # TPU the sweep streams the 1e8-halo catalog; off-TPU a 1e6-halo
    # catalog keeps the labelled fallback cheap.
    streaming = measure(
        "smf_streaming_chunk_sweep",
        lambda: bench_streaming(
            rtt, guess, BIG_HALOS if on_tpu else NUM_HALOS,
            (1_048_576, 4_194_304, 16_777_216) if on_tpu
            else (131_072, 524_288),
            nsteps=5 if on_tpu else 3))

    # Sharded-K ensembles on the 2-level (replica, data) mesh:
    # max-runnable-K at equal per-device budget (memory-model rungs,
    # the widest one executed for real), fits/hour A/B replicated vs
    # K-partitioned at a common K, and the exact-arithmetic bitwise
    # equivalence proof.  Needs a multi-device mesh (recorded null
    # on a single device).
    sharded_k = measure(
        "ensemble_sharded_k_sweep",
        lambda: bench_ensemble_sharded(
            rtt, 100_000 if on_tpu else 20_000,
            ab_k=64 if on_tpu else 48))

    # Fit-fleet serving throughput: batched-bucket vs sequential
    # dispatch through the serve scheduler (PR 10's tentpole), on the
    # mesh when one exists.  Many small tenant fits is the workload;
    # the knobs ride in the record.
    serve_tp = measure(
        "serve_fits_per_hour",
        lambda: bench_serve(
            cli.serve_requests or (64 if on_tpu else 48),
            100_000 if on_tpu else 1_000,
            nsteps=200))

    # PR-11 fleet scaling: aggregate fits/hour at 1/2/4 worker
    # PROCESSES behind the config-affinity router, shared on-disk
    # compile cache — the ROADMAP's horizontal success metric.  The
    # chaos proof (kill-a-worker, zero lost) lives in the test suite
    # and the CI fleet-chaos smoke step; this records the scaling.
    fleet_tp = measure(
        "fleet_fits_per_hour",
        lambda: bench_fleet(
            cli.fleet_workers or 4,
            cli.fleet_requests or 64,
            n_halos=500, nsteps=20))

    # PR-16 job pipeline: the north-star joint-posterior workload as
    # ONE submitted job through the serve scheduler — scan →
    # ensemble → Laplace → HMC → predictive check on the fused
    # SMF+wprp group.  The chaos proof (SIGKILL a fleet worker
    # mid-ensemble, job completes) lives in the CI posterior-
    # pipeline smoke; this records the throughput and the gated
    # job-layer dispatch-overhead ratio.
    pipeline_tp = measure(
        "posterior_pipeline_fits_per_hour",
        lambda: bench_posterior_pipeline(
            rtt, cli.pipeline_halos or (2048 if on_tpu else 512)))

    # PR-17 multi-tenant QoS: FIFO vs DRR+EDF under a 10:1
    # mixed-tenant overload, same worst-case burst.  The protected
    # class's p95-meets-SLO proof lives in the CI qos-demo smoke;
    # this records the host-independent ratios the regress gate
    # tracks (interactive p95 returned to the light tenant, Jain
    # fairness over contended dispatch slots).
    qos_heavy_n = cli.qos_heavy or 40
    qos_load = measure(
        "qos_mixed_load",
        lambda: bench_qos_mixed_load(
            qos_heavy_n,
            cli.qos_interactive or max(4, qos_heavy_n // 10),
            n_halos=1_000, nsteps=10))

    # PR-18 resource observability: scheduler throughput with the
    # ResourceMonitor on vs off (gated ~1.0 ratio — "the sampler is
    # free"), plus the measured-vs-modeled memory drift the TPU
    # rounds gate (null off-TPU).
    res_overhead = measure(
        "resource_monitor_overhead",
        lambda: bench_resource_monitor_overhead(
            n_fits=24, n_halos=1_000, nsteps=100))

    # PR-20 telemetry history plane: rollup sink + SLO budget
    # ledgers on vs off (gated ~1.0 ratio — "history is free").
    rollup_overhead = measure(
        "rollup_overhead",
        lambda: bench_rollup_overhead(
            n_fits=24, n_halos=1_000, nsteps=100))

    # Inference workload: Fisher seconds + in-graph HMC rates on the
    # χ²-likelihood SMF model (1e6 halos on TPU, 1e5 off-TPU).
    inference = measure(
        "smf_inference_fisher_hmc",
        lambda: bench_inference(
            rtt, NUM_HALOS if on_tpu else 100_000,
            num_samples=500 if on_tpu else 100,
            num_warmup=250 if on_tpu else 50))

    bfgs = measure("bfgs_tutorial", lambda: bench_bfgs_tutorial(guess))

    ref_sps = measure(
        "reference_style_steps_per_sec",
        lambda: bench_reference_style(data_1e6(), rtt, guess))

    def rnd(x, k=2):
        return None if x is None else round(x, k)

    print(json.dumps({
        "metric": f"adam_steps_per_sec_smf_{NUM_HALOS:.0e}_halos_{backend}",
        "value": round(headline, 2),
        "unit": "steps/s",
        "vs_baseline": (round(headline / ref_sps, 2)
                        if ref_sps else None),
        "baseline": {
            "what": ("faithful same-chip port of the reference's "
                     "execution shape: per-bin jitted kernels, "
                     "host-interleaved two-stage VJP, host-loop Adam "
                     "(multigrad.py:508-538, adam.py:52-68)"),
            "defined_in": "bench.py:bench_reference_style",
            "steps_per_sec": rnd(ref_sps),
        },
        "protocol": ("warm-up + best-of-N reps, fresh inputs, "
                     "host-fetch fence, RTT subtracted; incremental "
                     "(partial dossier resumes from "
                     ".bench_partial.<backend>.json)"),
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
        "configs": {
            "smf_1e6_xla_steps_per_sec": rnd(sps_xla),
            "smf_1e6_pallas_steps_per_sec": rnd(sps_pallas),
            "smf_1e8_chunked_xla_steps_per_sec": rnd(big_xla_sps),
            "smf_1e8_pallas_steps_per_sec": rnd(big_pallas_sps),
            "smf_1e9_pallas_steps_per_sec": rnd(huge_sps),
            "wprp_8192_fwdbwd_ms_xla": rnd(wprp_xla, 3),
            "wprp_8192_fwdbwd_ms_pallas": rnd(wprp_pallas, 3),
            "pair_1e5_fwdbwd_s_xla": rnd(pair_1e5_xla, 3),
            "pair_1e5_fwdbwd_s_pallas": rnd(pair_1e5_pallas, 3),
            "pair_1e6_fwdbwd_s_xla": rnd(pair_1e6_xla, 3),
            "pair_1e6_fwdbwd_s_pallas": rnd(pair_1e6_pallas, 3),
            "galhalo_hist_1e8_adam_steps_per_sec": rnd(hist_1e8_sps),
            "galhalo_hist_1e9_loss_and_grad_s": rnd(hist_1e9_s, 3),
            "galhalo_hist_fused_bins_ab": fused_ab,
            "galhalo_hist_1e8_fused": rnd(hist_1e8_fused_sps),
            "smf_1e6_fused_bins": rnd(smf_fused_sps),
            "tuned_defaults": tuned_ab,
            "smf_1e6_tuned": smf_tuned,
            "adam_donated_steps_per_sec": donated_ab,
            "streaming_overlap_frac": overlap_ab,
            "group_2x5e5_fused_adam_steps_per_sec": rnd(group_fused_sps),
            "group_2x5e5_hostloop_adam_steps_per_sec": rnd(group_host_sps),
            "smf_streaming_chunk_sweep": streaming,
            "ensemble_sharded_k_sweep": sharded_k,
            "serve_fits_per_hour": serve_tp,
            "fleet_fits_per_hour": fleet_tp,
            "posterior_pipeline_fits_per_hour": pipeline_tp,
            "qos_mixed_load": qos_load,
            "resource_monitor_overhead": res_overhead,
            "rollup_overhead": rollup_overhead,
            "smf_inference_fisher_hmc": inference,
            "bfgs_tutorial": bfgs,
        },
        "notes": "BENCH_NOTES.md",
        "telemetry": telemetry_path,
    }))
    telemetry.close()


if __name__ == "__main__":
    main()
