"""Diffmah-style galaxy–halo history fit: multi-epoch SMF likelihood.

BASELINE config 4's workload ("diffmah/diffstar galaxy–halo model"):
every halo grows along a smooth, differentiable mass-accretion
history; stars form from the accreted baryons at a mass-dependent
efficiency; the model predicts the stellar mass function at several
observation epochs from the one cumulative (n, T) history table; and
all ten parameters — MAH indices and transition epoch, efficiency
peak/slopes, mass-dependent scatter — are fit by gradient descent
through the whole pipeline (:mod:`multigrad_tpu.models.galhalo_hist`).

Run distributed (halo axis sharded over the mesh, per-particle-sigma
erf kernel inside the fused SPMD program)::

    python examples/galhalo_history_fit.py --num-halos 100_000

(Set ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` with
``JAX_PLATFORMS=cpu`` to simulate the mesh on CPU; pass
``--num-halos 100_000_000 --chunk-size 1_000_000`` on a TPU pod for
the config-4 scale.)
"""
import argparse
import time

import numpy as np
from jax import numpy as jnp

import multigrad_tpu as mgt
from multigrad_tpu.models import GalhaloHistModel, make_galhalo_hist_data
from multigrad_tpu.models.galhalo_hist import TRUTH, GalhaloHistParams

parser = argparse.ArgumentParser(
    __file__,
    description="Multi-epoch galaxy-halo history fit with multigrad_tpu")
parser.add_argument("--num-halos", type=int, default=100_000)
parser.add_argument("--chunk-size", type=int, default=None,
                    help="tile the halo axis (required at 1e8+)")
parser.add_argument("--maxsteps", type=int, default=500)
parser.add_argument("--adam-steps", type=int, default=0,
                    help="optional Adam warm start before BFGS")
parser.add_argument("--single-device", action="store_true",
                    help="skip the mesh (comm=None)")

BOUNDS = [(1.0, 4.0), (0.1, 2.0), (-0.5, 1.0), (1.0, 6.0),
          (-2.0, 0.5), (10.5, 13.5), (0.3, 3.0), (0.2, 2.5),
          (0.05, 0.5), (-0.1, 0.05)]
GUESS_OFFSET = np.array([0.15, -0.1, 0.05, -0.2, 0.08,
                         -0.1, 0.1, -0.08, 0.02, 0.005])

if __name__ == "__main__":
    args = parser.parse_args()
    comm = None if args.single_device else mgt.global_comm()

    t0 = time.time()
    data = make_galhalo_hist_data(args.num_halos, comm=comm,
                                  chunk_size=args.chunk_size)
    model = GalhaloHistModel(aux_data=data, comm=comm)
    print(f"built {args.num_halos:_} halo histories "
          f"({data['time_grid'].shape[0]} epochs, "
          f"{len(data['obs_indices'])} observation readouts) "
          f"in {time.time() - t0:.1f}s on "
          f"{'1 device' if comm is None else f'{comm.size} devices'}")

    truth = np.array(TRUTH)
    guess = jnp.array(truth + GUESS_OFFSET)
    if args.adam_steps:
        traj = model.run_adam(guess=guess, nsteps=args.adam_steps,
                              param_bounds=BOUNDS, learning_rate=0.01,
                              progress=True)
        guess = jnp.asarray(traj[-1])
        print(f"Adam warm start -> loss "
              f"{float(model.calc_loss_from_params(guess)):.3e}")

    t0 = time.time()
    result = model.run_bfgs(guess=guess, maxsteps=args.maxsteps,
                            param_bounds=BOUNDS, progress=True)
    dt = time.time() - t0

    names = GalhaloHistParams._fields
    print(f"\nBFGS: nit={result.nit} nfev={result.nfev} "
          f"fun={result.fun:.3e} ({dt:.1f}s)")
    print(f"{'param':>12} {'truth':>8} {'fit':>9} {'error':>9}")
    for name, t, x in zip(names, truth, result.x):
        print(f"{name:>12} {t:8.3f} {x:9.4f} {x - t:+9.4f}")
    err = np.abs(result.x - truth)
    loose = np.array([f == "k_t" for f in names])
    ok = np.all(err[~loose] < 0.15) and np.all(err[loose] < 0.5)
    print("Final solution:", "RECOVERED" if ok else "DRIFTED",
          f"(max err {err.max():.3f})")
