"""Posterior inference on the SMF fit: ensemble -> Fisher -> HMC.

The full inference pipeline (``multigrad_tpu.inference``) on the
flagship stellar-mass-function workload:

1. **ensemble** — multi-start Adam fits, K initializations batched
   through ONE optimizer scan, rank the basins and take the winner;
2. **Fisher / Laplace** — the distributed sumstats Jacobian (per-shard
   ``∂y_r/∂p`` psums exactly like ``y_r``) gives the Gauss–Newton
   Fisher matrix ``Jᵀ H_y J`` in one data pass; its inverse is the
   Laplace error bar;
3. **HMC** — 4 chains vmapped inside the SPMD program, dual-averaged
   step size, preconditioned by the Laplace covariance; corner-style
   posterior stats (percentiles + correlations) and split R-hat / ESS
   diagnostics, cross-checked against the Laplace approximation.

Run (any backend; on CPU simulate a mesh with
``XLA_FLAGS=--xla_force_host_platform_device_count=8``)::

    python examples/smf_posterior.py --num-halos 20000 \
        --num-samples 500 --num-warmup 300
"""
import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import SMFChi2Model, make_smf_data

TRUTH = np.array([-2.0, 0.2])
NAMES = ("log_shmrat", "sigma_logsm")
BOUNDS = [(-4.0, 0.0), (0.02, 1.0)]


def corner_stats(samples, names):
    """Corner-plot numbers without the plot: per-parameter quantiles
    and the pairwise correlation matrix."""
    flat = samples.reshape(-1, samples.shape[-1])
    q = np.percentile(flat, [16, 50, 84], axis=0)
    corr = np.corrcoef(flat, rowvar=False)
    for i, name in enumerate(names):
        lo, med, hi = q[0, i], q[1, i], q[2, i]
        print(f"  {name:>12s} = {med:+.4f}  (+{hi - med:.4f} "
              f"/ -{med - lo:.4f})  [16/50/84%]")
    print("  correlation matrix:")
    for i, name in enumerate(names):
        row = "  ".join(f"{corr[i, j]:+.3f}"
                        for j in range(len(names)))
        print(f"  {name:>12s}  {row}")
    return q, corr


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-halos", type=int, default=20_000)
    ap.add_argument("--num-starts", type=int, default=6)
    ap.add_argument("--fit-steps", type=int, default=200)
    ap.add_argument("--num-chains", type=int, default=4)
    ap.add_argument("--num-samples", type=int, default=500)
    ap.add_argument("--num-warmup", type=int, default=300)
    ap.add_argument("--num-leapfrog", type=int, default=8)
    ap.add_argument("--sigma-frac", type=float, default=0.05,
                    help="fractional Gaussian error per SMF bin")
    ap.add_argument("--plot", default=None,
                    help="save a corner plot to this .png path")
    ap.add_argument("--telemetry", default=None,
                    help="write a telemetry JSONL stream (run record, "
                         "comm accounting, in-graph HMC taps) to this "
                         "path; summarize it with `python -m "
                         "multigrad_tpu.telemetry.report <path>`")
    args = ap.parse_args()

    telemetry = (mgt.MetricsLogger(mgt.JsonlSink(args.telemetry),
                                   run_config=vars(args))
                 if args.telemetry else None)
    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    # The χ²-likelihood SMF variant: exp(-loss) is a proper posterior
    # density (5% fractional error per bin), so Fisher error bars and
    # HMC draws have calibrated units — see SMFChi2Model's docstring.
    aux = dict(make_smf_data(args.num_halos, comm=comm),
               sigma_frac=args.sigma_frac)
    model = SMFChi2Model(aux_data=aux, comm=comm)
    print(f"SMF model: {args.num_halos} halos over "
          f"{comm.size if comm else 1} shard(s), "
          f"{args.sigma_frac:.0%} bin errors")

    # -- 1. basin-hop the loss surface ---------------------------------
    ens = mgt.run_multistart_adam(
        model, param_bounds=BOUNDS, n_starts=args.num_starts,
        nsteps=args.fit_steps, learning_rate=0.05, seed=0)
    print(f"ensemble: {ens.n_starts} Adam starts -> best loss "
          f"{ens.best_loss:.3e}, basin spread {ens.basin_spread():.3f}")
    # Polish the two best basins with the in-graph L-BFGS scan (the
    # compiled program is shared across starts).
    order = np.argsort(np.asarray(ens.losses))
    ens = mgt.run_multistart_lbfgs(
        model, inits=np.asarray(ens.params)[order[:2]], maxsteps=60,
        param_bounds=BOUNDS)
    best = np.asarray(ens.best_params)
    print(f"L-BFGS polish -> best loss {ens.best_loss:.3e} at "
          f"({best[0]:+.4f}, {best[1]:.4f})")

    if telemetry is not None:
        # Trace-time collective accounting: the measured
        # O(|sumstats|+|params|) bytes per loss-and-grad step.
        cc = mgt.measure_model_comm(model, ens.best_params)
        telemetry.log("comm", **cc.step_record(scope="loss_and_grad_step"))

    # -- 2. Laplace error bars from the distributed Fisher -------------
    fr = mgt.fisher_information(model, ens.best_params)
    stderr = np.asarray(fr.stderr())
    diag = fr.diagnostics()
    print("Laplace (Fisher) 1-sigma:",
          ", ".join(f"{n}={s:.4f}" for n, s in zip(NAMES, stderr)))
    print(f"Fisher condition number: {diag['condition_number']:.1f} "
          f"(identifiable: {diag['identifiable']})")

    # -- 3. HMC, warm-started and preconditioned -----------------------
    init = mgt.hmc_init_from_ensemble(
        ens, num_chains=args.num_chains, spread=1.0, stderr=stderr,
        randkey=1)
    # inv_mass ≈ posterior variances (the Laplace diagonal): the
    # preconditioning that makes one step size fit both parameters.
    res = mgt.run_hmc(
        model, init, num_samples=args.num_samples,
        num_warmup=args.num_warmup, num_leapfrog=args.num_leapfrog,
        step_size=0.1, inv_mass=stderr ** 2, randkey=2,
        telemetry=telemetry,
        log_every=max(1, args.num_samples // 10)
        if telemetry is not None else 0)
    print("sampler:", json.dumps(res.summary()))
    print("posterior (corner stats):")
    corner_stats(res.samples, NAMES)
    hmc_sd = res.samples.reshape(-1, 2).std(axis=0)
    print("HMC vs Laplace 1-sigma ratio:",
          ", ".join(f"{h / l:.2f}" for h, l in zip(hmc_sd, stderr)))

    if args.plot:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        flat = res.samples.reshape(-1, 2)
        fig, axes = plt.subplots(2, 2, figsize=(6, 6))
        for i in range(2):
            for j in range(2):
                ax = axes[i][j]
                if i == j:
                    ax.hist(flat[:, i], bins=40, color="C0")
                elif i > j:
                    ax.hist2d(flat[:, j], flat[:, i], bins=40)
                else:
                    ax.axis("off")
                if i == 1:
                    ax.set_xlabel(NAMES[j])
                if j == 0:
                    ax.set_ylabel(NAMES[i])
        fig.tight_layout()
        fig.savefig(args.plot, dpi=120)
        print(f"corner plot: {args.plot}")

    ok = (np.all(res.rhat < 1.05)
          and np.all(np.abs(res.mean() - TRUTH) < 5 * hmc_sd
                     + 5e-2))
    print(f"R-hat: {np.max(res.rhat):.4f}  min ESS: "
          f"{np.min(res.ess):.0f}")
    if telemetry is not None:
        jax.effects_barrier()          # flush in-flight tap callbacks
        telemetry.log("fit_summary", best_loss=float(ens.best_loss),
                      max_rhat=float(np.max(res.rhat)),
                      min_ess=float(np.min(res.ess)),
                      divergences=int(np.sum(res.divergences)))
        telemetry.close()
        print(f"telemetry: {args.telemetry}")
    print("SUCCESS" if ok else "FAILED: chains unconverged or truth "
          "outside the posterior")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
