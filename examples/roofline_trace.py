"""Capture a jax.profiler trace of the SMF Adam step and summarize
op-level device occupancy.

BENCH_NOTES' roofline section argues from arithmetic envelopes (so
many transcendentals at such-and-such throughput); this script makes
it trace-backed: it records a profiler trace of the 1e6-halo fused
fit (and optionally the 1e8 chunked config), parses the perfetto
trace JSON, and prints where the step time actually goes, op by op.

Run on the TPU (default backend)::

    python examples/roofline_trace.py            # 1e6 halos
    python examples/roofline_trace.py --big      # + 1e8 chunked

Off-TPU it traces the CPU backend — the parsing pipeline is the
same, which is how the script is smoke-tested in CI.
"""
import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict


def capture_trace(log_dir, nsteps=200, num_halos=1_000_000,
                  chunk_size=None, backend="auto"):
    """One warmed-up run_adam segment under the profiler."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.utils.profiling import trace

    model = SMFModel(aux_data=dict(
        make_smf_data(num_halos, chunk_size=chunk_size),
        backend=backend))
    guess = jnp.array([-1.0, 0.5])

    def run(g):
        traj = model.run_adam(guess=g, nsteps=nsteps, progress=False)
        return np.asarray(traj)

    run(guess)                        # compile outside the trace
    with trace(log_dir, perfetto=True):
        run(guess + 0.01)
    return nsteps


def summarize_perfetto(log_dir, top=12):
    """Aggregate device-track slice durations by op name.

    The perfetto trace's device tracks carry one slice per executed
    XLA op (fusions appear as single slices — XLA's fusion decisions
    are visible by name).  Returns [(name, total_us, count)] sorted
    by total duration.
    """
    paths = glob.glob(os.path.join(
        log_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise FileNotFoundError(
            f"no perfetto trace under {log_dir!r} — pass a log_dir "
            f"that capture_trace() wrote")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace

    # Execution tracks. On TPU the device is its own process
    # ("/device:TPU:0 ..."), every thread of which is device time; on
    # CPU the op slices live on the XLAPjRt executor threads of the
    # host process (the "python" thread is host-side bookkeeping).
    proc_names, thread_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = \
                e["args"].get("name", "")

    def on_device(e):
        proc = proc_names.get(e.get("pid"), "")
        if "TPU" in proc or ("/device:" in proc
                             and "CPU" not in proc):
            return True
        # CPU executor thread names vary by jax version: "XLAPjRt"
        # pools on newer releases, "tf_XLAEigen" eigen-threadpool
        # workers on older ones.
        tname = thread_names.get((e.get("pid"), e.get("tid")), "")
        return "XLAPjRt" in tname or "XLAEigen" in tname

    agg = defaultdict(lambda: [0.0, 0])
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or not on_device(e):
            continue
        name = e.get("name", "?")
        # "end: op" markers and container slices (the whole-program
        # executor, the scan's while wrapper, per-thunk "call.N"
        # brackets, threadpool bookkeeping) would double count the
        # op slices they bracket.
        if (name.startswith("end: ") or "Execute" in name
                or name.split(".")[0] in ("while", "condition",
                                          "body", "call")
                or name.startswith("jit_")
                or name.startswith("ThreadpoolListener")
                or name.startswith("TaskDispatcher")):
            continue
        dur = float(e.get("dur", 0.0))
        agg[name][0] += dur
        agg[name][1] += 1
        total += dur
    if total == 0.0:
        # An empty aggregate means the device-track filters matched
        # nothing (new backend process naming, empty trace dir, a
        # capture that never ran a program) — every caller would
        # otherwise divide by the zero total.
        raise RuntimeError(
            "no device-track slices matched in the trace under "
            f"{log_dir!r}: either the capture recorded no device ops "
            "or the process/thread-name filters need updating for "
            "this backend")
    rows = sorted(((name, d, c) for name, (d, c) in agg.items()),
                  key=lambda r: -r[1])
    return rows[:top], total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--big", action="store_true",
                    help="also trace the 1e8-halo chunked config")
    ap.add_argument("--log-dir", default="/tmp/mgt_roofline_trace")
    ap.add_argument("--nsteps", type=int, default=200)
    args = ap.parse_args()

    import jax

    configs = [("smf_1e6", dict(num_halos=1_000_000,
                                nsteps=args.nsteps))]
    if args.big:
        configs.append(("smf_1e8_chunked",
                        dict(num_halos=100_000_000,
                             chunk_size=4_000_000, nsteps=5)))

    out = {"backend": jax.default_backend()}
    for name, kw in configs:
        log_dir = os.path.join(args.log_dir, name)
        nsteps = capture_trace(log_dir, **kw)
        rows, total_us = summarize_perfetto(log_dir)
        print(f"\n== {name}: device op time over {nsteps} steps "
              f"({total_us / 1e3:.1f} ms total on-device)")
        for op, dur, count in rows:
            print(f"  {dur / total_us:6.1%}  {dur / 1e3:9.2f} ms  "
                  f"x{count:<6d} {op[:80]}")
        out[name] = {
            "total_device_us": round(total_us, 1),
            "per_step_us": round(total_us / nsteps, 1),
            "top_ops": [
                {"op": op[:120], "us": round(dur, 1), "count": count,
                 "frac": round(dur / total_us, 4)}
                for op, dur, count in rows],
        }
    print("\n" + json.dumps(out))


if __name__ == "__main__":
    main()
