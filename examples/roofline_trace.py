"""Capture a jax.profiler trace of the SMF Adam step and summarize
op-level device occupancy.

BENCH_NOTES' roofline section argues from arithmetic envelopes (so
many transcendentals at such-and-such throughput); this script makes
it trace-backed: it records a profiler trace of the 1e6-halo fused
fit (and optionally the 1e8 chunked config), parses the perfetto
trace JSON, and prints where the step time actually goes, op by op.

Run on the TPU (default backend)::

    python examples/roofline_trace.py            # 1e6 halos
    python examples/roofline_trace.py --big      # + 1e8 chunked

Off-TPU it traces the CPU backend — the parsing pipeline is the
same, which is how the script is smoke-tested in CI.
"""
import argparse
import json
import os


def capture_trace(log_dir, nsteps=200, num_halos=1_000_000,
                  chunk_size=None, backend="auto"):
    """One warmed-up run_adam segment under the profiler."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.utils.profiling import trace

    model = SMFModel(aux_data=dict(
        make_smf_data(num_halos, chunk_size=chunk_size),
        backend=backend))
    guess = jnp.array([-1.0, 0.5])

    def run(g):
        traj = model.run_adam(guess=g, nsteps=nsteps, progress=False)
        return np.asarray(traj)

    run(guess)                        # compile outside the trace
    with trace(log_dir, perfetto=True):
        run(guess + 0.01)
    return nsteps


def summarize_perfetto(log_dir, top=12):
    """Aggregate device-track slice durations by op name.

    The perfetto trace's device tracks carry one slice per executed
    XLA op (fusions appear as single slices — XLA's fusion decisions
    are visible by name).  Returns [(name, total_us, count)] sorted
    by total duration.

    The parsing/filters were hoisted into
    :func:`multigrad_tpu.telemetry.profile.summarize_device_trace`
    (the flight-recorder layer's shared machinery); this wrapper
    keeps the script's historical ``(rows, total_us)`` contract.
    """
    from multigrad_tpu.telemetry.profile import summarize_device_trace

    summary = summarize_device_trace(log_dir, top=top)
    rows = [(op["op"], op["us"], op["count"])
            for op in summary["ops"]]
    return rows, summary["total_us"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--big", action="store_true",
                    help="also trace the 1e8-halo chunked config")
    ap.add_argument("--log-dir", default="/tmp/mgt_roofline_trace")
    ap.add_argument("--nsteps", type=int, default=200)
    args = ap.parse_args()

    import jax

    configs = [("smf_1e6", dict(num_halos=1_000_000,
                                nsteps=args.nsteps))]
    if args.big:
        configs.append(("smf_1e8_chunked",
                        dict(num_halos=100_000_000,
                             chunk_size=4_000_000, nsteps=5)))

    out = {"backend": jax.default_backend()}
    for name, kw in configs:
        log_dir = os.path.join(args.log_dir, name)
        nsteps = capture_trace(log_dir, **kw)
        rows, total_us = summarize_perfetto(log_dir)
        print(f"\n== {name}: device op time over {nsteps} steps "
              f"({total_us / 1e3:.1f} ms total on-device)")
        for op, dur, count in rows:
            print(f"  {dur / total_us:6.1%}  {dur / 1e3:9.2f} ms  "
                  f"x{count:<6d} {op[:80]}")
        out[name] = {
            "total_device_us": round(total_us, 1),
            "per_step_us": round(total_us / nsteps, 1),
            "top_ops": [
                {"op": op[:120], "us": round(dur, 1), "count": count,
                 "frac": round(dur / total_us, 4)}
                for op, dur, count in rows],
        }
    print("\n" + json.dumps(out))


if __name__ == "__main__":
    main()
