"""Pod-scale checkpoint/resume with orbax.

The ``checkpoint_dir=`` path built into ``run_adam`` uses the
dependency-free ``.npz`` backend (``utils/checkpoint.py``).  On a real
pod you usually want `orbax.checkpoint` instead — async saves, a
step-indexed directory layout, and multi-host array handling — so this
example shows the same preemption-safe segmented-fit pattern driven by
:class:`multigrad_tpu.utils.checkpoint.OrbaxCheckpointer`:

    python examples/orbax_pod_checkpoint.py --ckpt-dir /tmp/podfit
    # ... preempt it at any point, then re-run the same command:
    python examples/orbax_pod_checkpoint.py --ckpt-dir /tmp/podfit

Each invocation restores the latest step (if any), advances the fit in
jitted whole-segment ``lax.scan`` programs, and checkpoints after each
segment.  ``--max-segments`` simulates a preemption window.
"""
import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data
from multigrad_tpu.utils.checkpoint import OrbaxCheckpointer

parser = argparse.ArgumentParser(
    __file__, description="Segmented Adam fit with orbax checkpointing")
parser.add_argument("--ckpt-dir", required=True)
parser.add_argument("--num-halos", type=int, default=10_000)
parser.add_argument("--num-steps", type=int, default=200)
parser.add_argument("--segment", type=int, default=50)
parser.add_argument("--learning-rate", type=float, default=0.01)
parser.add_argument("--max-segments", type=int, default=None,
                    help="stop after this many segments (simulated "
                         "preemption)")
parser.add_argument("--single-device", action="store_true")


def main():
    args = parser.parse_args()
    comm = None if args.single_device else mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(args.num_halos, comm=comm),
                     comm=comm)
    fn = model.loss_and_grad_fn()  # jitted (params, aux, key) program
    aux = model.aux_leaves()

    tx = optax.adam(args.learning_rate)
    guess = jnp.array([*ParamTuple(log_shmrat=-1.0, sigma_logsm=0.5)])
    # 0-d arrays, not numpy scalars: orbax's StandardRestore template
    # accepts arrays only.
    fresh = {"step": np.zeros((), np.int64), "params": guess,
             "opt_state": tx.init(guess)}

    ckpt = OrbaxCheckpointer(args.ckpt_dir)
    state = ckpt.restore_latest(fresh)
    if state is None:
        state = fresh
    else:
        # Restored arrays are committed to a single device; uncommit
        # through the host so jit re-replicates them over the mesh.
        state = jax.tree_util.tree_map(np.asarray, state)
        print(f"resumed from step {int(state['step'])}")

    from functools import partial

    @partial(jax.jit, static_argnames="nsteps")
    def segment(params, opt_state, nsteps):
        def body(carry, _):
            p, s = carry
            _, grad = fn(p, aux, jnp.zeros(()))
            updates, s = tx.update(grad, s, p)
            return (optax.apply_updates(p, updates), s), None

        (params, opt_state), _ = jax.lax.scan(
            body, (params, opt_state), None, length=nsteps)
        return params, opt_state

    step = int(state["step"])
    params, opt_state = state["params"], state["opt_state"]
    segments_done = 0
    while step < args.num_steps:
        if args.max_segments is not None \
                and segments_done >= args.max_segments:
            print(f"preempted at step {step}")
            ckpt.wait()
            return
        n = min(args.segment, args.num_steps - step)
        params, opt_state = segment(params, opt_state, n)
        step += n
        segments_done += 1
        ckpt.save(step, {"step": np.asarray(step, np.int64),
                         "params": np.asarray(params),
                         "opt_state": jax.tree_util.tree_map(
                             np.asarray, opt_state)})
    ckpt.wait()  # async saves must land before the job exits
    loss = float(np.asarray(model.calc_loss_from_params(params)))
    print(f"DONE step={step} params={np.asarray(params)} loss={loss:.3e}")


if __name__ == "__main__":
    main()
