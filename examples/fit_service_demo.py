"""Fits-as-a-service demo: a request burst through the fit-fleet
scheduler, poison isolation included.

The whole serving story in one run: the persistent compile cache is
enabled and the bucket programs pre-traced (:mod:`multigrad_tpu
.serve.compile_cache`), a burst of SMF fit requests — one of them
deliberately NaN-poisoned — flows through the batched
:class:`~multigrad_tpu.serve.FitScheduler`, the clean requests come
back as :class:`~multigrad_tpu.serve.FitResult`\\ s while the poison
request alone errors with a flight-recorder postmortem bundle, and
the scheduler's live gauges (queue depth, bucket occupancy,
fits/hour) are self-scraped over real HTTP from the PR-9
``/metrics`` endpoint.

CI runs this per push and greps the ``SERVE OK`` receipt (exit 0
only when every link of the chain worked)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/fit_service_demo.py
"""
import argparse
import os
import sys
import tempfile
import urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8,
                    help="clean fit requests in the burst (one "
                         "poison request rides along)")
    ap.add_argument("--num-halos", type=int, default=4096)
    ap.add_argument("--nsteps", type=int, default=60)
    ap.add_argument("--telemetry", default=None,
                    help="write the record stream (per-request "
                         "fit_summary records included) to this "
                         "JSONL")
    ap.add_argument("--dump-dir", default=None,
                    help="postmortem bundle directory (default: a "
                         "fresh temp dir)")
    ap.add_argument("--metrics-out", default=None,
                    help="save the /metrics scrape to this file")
    ap.add_argument("--compile-cache", default=None,
                    help="persistent XLA compile-cache dir (default: "
                         "a fresh temp dir)")
    args = ap.parse_args()

    import jax
    import numpy as np

    import multigrad_tpu as mgt
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.serve import (FitConfig, FitFailed,
                                     FitScheduler, cache_entries,
                                     enable_compile_cache)
    from multigrad_tpu.telemetry import (JsonlSink, LiveServer,
                                         MemorySink, MetricsLogger)

    # (1) Persistent compile cache + model on the mesh.
    cache_dir = enable_compile_cache(
        args.compile_cache or tempfile.mkdtemp(prefix="mgt_serve_cc_"))
    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    model = SMFModel(aux_data=make_smf_data(args.num_halos, comm=comm),
                     comm=comm)

    sinks = [MemorySink()]
    if args.telemetry:
        parent = os.path.dirname(os.path.abspath(args.telemetry))
        os.makedirs(parent, exist_ok=True)
        sinks.insert(0, JsonlSink(args.telemetry))
    logger = MetricsLogger(*sinks, run_config={"demo": "serve"})
    live = LiveServer(port=0)

    config = FitConfig(nsteps=args.nsteps, learning_rate=0.03)
    sched = FitScheduler(model, buckets=(1, 4, 16), telemetry=logger,
                         live=live, flight_dir=args.dump_dir,
                         batch_window_s=0.1)

    # (2) Warm the bucket programs (trace-only; the executables land
    # in the persistent cache for future processes).
    warm = sched.warmup(config, ndim=2)
    print(f"warmup: {len(warm)} bucket programs compiled, "
          f"{cache_entries(cache_dir)} persistent cache entries")

    # (3) The burst: N clean requests + one NaN poison.
    # Guesses inside the SMF loss's well-behaved region (a tiny
    # sigma guess empties every bin — log10(0) — which is the poison
    # request's job here, not the burst's).
    rng = np.random.default_rng(0)
    guesses = np.column_stack([
        rng.uniform(-2.3, -1.2, args.requests),
        rng.uniform(0.3, 0.8, args.requests)])
    futures = [sched.submit(g, config=config) for g in guesses]
    poison = sched.submit(np.array([np.nan, 0.5]), config=config)

    results = [f.result(timeout=600) for f in futures]
    poison_exc = poison.exception(timeout=600)

    ok = True
    losses = [r.loss for r in results]
    if not all(np.isfinite(losses)):
        print("ERROR: a clean request came back non-finite",
              file=sys.stderr)
        ok = False
    if not isinstance(poison_exc, FitFailed):
        print(f"ERROR: poison request resolved as "
              f"{type(poison_exc).__name__}, expected FitFailed",
              file=sys.stderr)
        ok = False
    elif not (poison_exc.bundle_path
              and os.path.exists(poison_exc.bundle_path)):
        print("ERROR: poison request has no postmortem bundle",
              file=sys.stderr)
        ok = False

    # (4) Self-scrape the scheduler gauges over real HTTP.
    with urllib.request.urlopen(live.url + "/metrics",
                                timeout=10) as resp:
        exposition = resp.read().decode()
    if args.metrics_out:
        os.makedirs(os.path.dirname(os.path.abspath(args.metrics_out)),
                    exist_ok=True)
        with open(args.metrics_out, "w") as f:
            f.write(exposition)
    for gauge in ("multigrad_serve_queue_depth",
                  "multigrad_serve_occupancy",
                  "multigrad_serve_fits_total",
                  "multigrad_serve_fits_per_hour"):
        if gauge not in exposition:
            print(f"ERROR: /metrics scrape missing {gauge}",
                  file=sys.stderr)
            ok = False

    stats = sched.stats
    sched.close()
    live.stop()
    logger.close()

    summaries = [r for r in sinks[-1].records
                 if r["event"] == "fit_summary" and r.get("serve")]
    if len(summaries) < len(results):
        print(f"ERROR: {len(summaries)} serve fit_summary records "
              f"for {len(results)} served fits", file=sys.stderr)
        ok = False

    if not ok:
        return 1
    rate = stats.get("fits_per_hour")
    print(f"served {len(results)} fits "
          f"(best loss {min(losses):.3g}) in "
          f"{stats['dispatches']} bucket dispatches "
          f"(buckets {stats['bucket_dispatches']}, "
          f"{stats['rows_padded']} padded rows"
          + (f", {rate:.0f} fits/h trailing" if rate else "") + ")")
    print(f"poison request errored as designed; "
          f"POSTMORTEM {poison_exc.bundle_path}")
    print(f"compile cache: {cache_entries(cache_dir)} entries in "
          f"{cache_dir}")
    print(f"SERVE OK {len(results)}/{len(futures)} clean fits, "
          f"1 poison isolated")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
