"""Multi-tenant QoS demo: a protected class rides out an overload.

The PR-17 tentpole's acceptance run: a 2-worker
:class:`~multigrad_tpu.serve.fleet.FleetRouter` with QoS on, two
tenants and three priority classes —

* ``hog``    — floods ``batch``-class fits 10x faster than anyone
  (the noisy neighbor), capped by a per-tenant quota;
* ``lab``    — a handful of ``standard`` fits plus the *protected*
  ``interactive`` work, with a declared SLO
  (``p95 < SLO s for interactive``).

Mid-burst the :class:`~multigrad_tpu.serve.chaos.ChaosController`
injects queue-full rejects on one worker (the overload worst case:
saturation on top of contention), so the run also exercises the
tagged reject path — reject *reasons* (``tenant_quota`` vs
``queue_full``), cumulative shed counters, and work stealing.

The receipt asserts what QoS promises: every interactive fit is
served, its measured p95 meets the declared SLO
(:class:`~multigrad_tpu.serve.slo.SloMonitor` judges live), and the
heavy tenant's overflow is pushed back with typed errors — never by
starving the protected class.  CI greps ``QOS OK`` per push.

The PR-20 flood leg rides the same burst: the ``batch`` class
declares a deliberately *tight* SLO, so the hog's flood violates it
on every fit and burns the batch error budget at ~1/budget — far
past the fast multi-window pair threshold — while the generous
interactive SLO leaves that class's budget whole.  The budget
receipt asserts the :class:`~multigrad_tpu.telemetry.BurnRateAlert`
fires exactly once (rising edge, held across ticks), the batch
budget's remaining fraction decreased, and the interactive budget
is untouched.  CI greps ``BUDGET OK``::

    JAX_PLATFORMS=cpu python examples/qos_demo.py \\
        --telemetry-dir /tmp/_qos
"""
import argparse
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--heavy", type=int, default=24,
                    help="hog tenant's batch-class burst size")
    ap.add_argument("--standard", type=int, default=6)
    ap.add_argument("--interactive", type=int, default=8,
                    help="protected-class request count")
    ap.add_argument("--num-halos", type=int, default=2000)
    ap.add_argument("--nsteps", type=int, default=200)
    ap.add_argument("--slo-s", type=float, default=120.0,
                    help="declared interactive p95 SLO (seconds, "
                         "end-to-end — generous for CPU CI hosts)")
    ap.add_argument("--batch-slo-s", type=float, default=0.001,
                    help="deliberately tight batch p95 SLO — the "
                         "flood leg burns its error budget (burn-"
                         "rate alert receipt)")
    ap.add_argument("--tenant-quota", type=int, default=16,
                    help="per-worker live-queued cap per tenant")
    ap.add_argument("--queue-full-rejects", type=int, default=4,
                    help="chaos: worker 0 rejects this many submits")
    ap.add_argument("--telemetry-dir", default=None)
    args = ap.parse_args()

    import numpy as np

    from multigrad_tpu.serve import (ChaosController, FleetRouter,
                                     QueueFullError)

    slo_text = f"p95 < {args.slo_s:g} s for interactive"
    batch_slo_text = f"p95 < {args.batch_slo_s:g} s for batch"
    router = FleetRouter(
        n_workers=args.workers,
        model_kwargs={"num_halos": args.num_halos},
        base_dir=args.telemetry_dir, devices=1,
        buckets=(1, 4, 16), batch_window_s=0.02,
        heartbeat_s=0.1, heartbeat_timeout_s=5.0,
        qos=True, tenant_quota=args.tenant_quota,
        slo=[slo_text, batch_slo_text], chaos=True)
    chaos = ChaosController(router)
    print(f"fleet up: {args.workers} QoS workers "
          f"(tenant_quota={args.tenant_quota}) in {router.base_dir}")
    print(f"declared SLO: {slo_text}")
    print(f"declared SLO: {batch_slo_text} (deliberately tight — "
          f"the flood leg burns its error budget)")

    rng = np.random.default_rng(0)

    def guesses(n):
        return np.column_stack([rng.uniform(-2.3, -1.5, n),
                                rng.uniform(0.35, 0.6, n)])

    # The chaos overload: on top of the hog's flood, worker 0
    # rejects its next few submits outright — saturation + quota
    # pressure at once.
    chaos.inject_queue_full(worker=0, n=args.queue_full_rejects)

    # One config per class so each class has its own bucket family
    # (distinct affinity homes keep both workers busy), submitted
    # hog-first: the worst arrival order for the protected class.
    t0 = time.time()
    heavy = [router.submit(g, nsteps=args.nsteps, learning_rate=0.03,
                           randkey=7, tenant="hog",
                           priority_class="batch")
             for g in guesses(args.heavy)]
    std = [router.submit(g, nsteps=args.nsteps, learning_rate=0.03,
                         randkey=8, tenant="lab",
                         priority_class="standard")
           for g in guesses(args.standard)]
    inter = [router.submit(g, nsteps=args.nsteps, learning_rate=0.03,
                           randkey=9, tenant="lab",
                           priority_class="interactive")
             for g in guesses(args.interactive)]

    ok = True
    outcomes = {"served": 0, "pushed_back": 0, "failed": 0}
    reasons: dict = {}
    for f in heavy + std:
        try:
            exc = f.exception(timeout=600)
        except TimeoutError:
            print(f"ERROR: request {f.request_id} HUNG",
                  file=sys.stderr)
            ok = False
            continue
        if exc is None:
            outcomes["served"] += 1
        elif isinstance(exc, QueueFullError):
            # Typed push-back (quota / saturation) is the QoS
            # CONTRACT under overload, not a failure.
            outcomes["pushed_back"] += 1
            reason = getattr(exc, "reason", "queue_full")
            reasons[reason] = reasons.get(reason, 0) + 1
        else:
            outcomes["failed"] += 1
            print(f"ERROR: {f.request_id}: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            ok = False

    # The protected class: EVERY interactive fit must be served —
    # higher classes are never shed for lower work, and the quota
    # belongs to the hog, not the lab.
    inter_served = 0
    for f in inter:
        try:
            exc = f.exception(timeout=600)
        except TimeoutError:
            print(f"ERROR: interactive {f.request_id} HUNG",
                  file=sys.stderr)
            ok = False
            continue
        if exc is None:
            inter_served += 1
        else:
            print(f"ERROR: interactive {f.request_id} not served: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            ok = False
    wall = time.time() - t0

    total = len(heavy) + len(std)
    print(f"burst done in {wall:.1f}s: hog+standard "
          f"{outcomes['served']}/{total} served, "
          f"{outcomes['pushed_back']} pushed back "
          f"{reasons or ''}, {outcomes['failed']} failed; "
          f"interactive {inter_served}/{len(inter)} served")
    if outcomes["served"] + outcomes["pushed_back"] != total:
        ok = False

    # The live SLO verdict — the same judgment /status exports.
    health = router.slo.evaluate()
    entry = health.get("interactive", {})
    p95 = entry.get("p95_s")
    verdict = (entry.get("slo") or {}).get("ok")
    for cls in sorted(health):
        e = health[cls]
        line = (f"  class {cls:<12} count={e['count']:<3} "
                f"p50={e['p50_s'] if e['p50_s'] is None else round(e['p50_s'], 2)}s "
                f"p95={e['p95_s'] if e['p95_s'] is None else round(e['p95_s'], 2)}s "
                f"shed={e['shed']}")
        if "slo" in e:
            line += f"  [{e['slo']['target']}: " \
                    f"{'MET' if e['slo']['ok'] else 'VIOLATED'}]"
        print(line)
    by_class, by_tenant = router.shed_counts()
    print(f"fleet shed counters: by_class={by_class} "
          f"by_tenant={by_tenant}")
    print(f"chaos log:\n{chaos.report()}")

    if inter_served != len(inter):
        print("ERROR: protected class lost requests",
              file=sys.stderr)
        ok = False
    if verdict is not True:
        print(f"ERROR: interactive SLO not met "
              f"(p95={p95}, declared {slo_text})", file=sys.stderr)
        ok = False

    # --- PR-20 flood leg: the hog's flood vs the batch error
    # budget.  Every heavy fit violated the tight batch SLO, so the
    # batch burn rate sits at ~1/budget (≈20x steady-state burn) —
    # over the fast multi-window pair threshold — while interactive
    # stayed within its SLO and its budget whole.
    from multigrad_tpu.telemetry import AlertEngine, BurnRateAlert
    engine = AlertEngine(rules=[BurnRateAlert(router.slo)])
    for _ in range(3):           # condition held across ticks ...
        engine.write({"event": "heartbeat"})
    burn_alerts = [a for a in engine.alerts
                   if a.get("rule") == "slo_burn_rate"]
    batch_snap = router.slo.budgets["batch"].snapshot()
    inter_snap = router.slo.budgets["interactive"].snapshot()
    print(f"budget: batch remaining="
          f"{batch_snap['remaining_frac']:.3f} "
          f"burn={batch_snap['burn_rate']}  interactive remaining="
          f"{inter_snap['remaining_frac']:.3f} "
          f"burn={inter_snap['burn_rate']}")
    if len(burn_alerts) != 1:    # ... yet fires ONCE (rising edge)
        print(f"ERROR: expected exactly one burn-rate alert, got "
              f"{len(burn_alerts)}", file=sys.stderr)
        ok = False
    elif "batch" not in burn_alerts[0].get("classes", {}):
        print(f"ERROR: burn-rate alert missed the batch class: "
              f"{burn_alerts[0]}", file=sys.stderr)
        ok = False
    if not batch_snap["remaining_frac"] < 1.0:
        print("ERROR: flood did not decrease the batch budget",
              file=sys.stderr)
        ok = False
    if inter_snap["remaining_frac"] != 1.0:
        print(f"ERROR: interactive budget touched "
              f"(remaining={inter_snap['remaining_frac']})",
              file=sys.stderr)
        ok = False

    chaos.close()
    router.close()
    if not ok:
        return 1
    print(f"QOS OK interactive p95 {p95:.2f}s within SLO "
          f"{args.slo_s:g}s, {inter_served}/{len(inter)} protected "
          f"fits served, {outcomes['pushed_back']} overflow "
          f"requests pushed back with typed errors, 0 lost")
    print(f"BUDGET OK burn-rate alert fired once "
          f"(batch burn={batch_snap['burn_rate']} > 14.4), batch "
          f"budget {batch_snap['remaining_frac']:.0%} remaining, "
          f"interactive budget untouched")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
