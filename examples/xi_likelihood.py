"""Sharded 3D two-point correlation likelihood fit — BASELINE config 3.

The diffdesi-style clustering workload: a galaxy-selection model over
a halo catalog, fit to a target xi(r) through the ring-sharded
differentiable pair counts.  Shows the full user path:

1. catalog prep with the diffdesi host-halo index utilities
   (``multigrad_tpu.utils.diffdesi``, C10 parity),
2. the :class:`~multigrad_tpu.models.XiModel` clustering likelihood
   (additive sumstats ``[DD..., W]``; xi(r) via the analytic-RR
   natural estimator in the loss),
3. a BFGS fit over the device mesh.

Run (8 virtual devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/xi_likelihood.py
"""
import argparse
import os

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    # Honor the env var even where a sitecustomize re-forces another
    # platform (the config API wins; cf. tests/conftest.py).
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import multigrad_tpu as mgt
from multigrad_tpu.models import XiModel, WprpParams, make_xi_data
from multigrad_tpu.models.wprp import TRUTH
from multigrad_tpu.utils import diffdesi


def prep_catalog_indices(num_halos):
    """Catalog-prep demo: resolve + sort by "ultimate top" host index
    (the diffdesi utilities' job on real DESI catalogs).  The mock's
    parents own themselves, so this is an identity reordering here —
    sort positions and masses *together* if you adapt this to a real
    host hierarchy."""
    host_idx = np.arange(num_halos)
    return diffdesi.find_ultimate_top_indices(host_idx)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-halos", type=int, default=2048)
    ap.add_argument("--box-size", type=float, default=75.0)
    ap.add_argument("--maxsteps", type=int, default=100)
    args = ap.parse_args()

    # Catalog prep (C10 utilities): in this self-owning mock the
    # ultimate-top resolution is the identity — assert that, so the
    # call has a visible contract instead of a discarded result.
    top = prep_catalog_indices(args.num_halos)
    assert np.array_equal(top, np.arange(args.num_halos))

    comm = mgt.global_comm()
    model = XiModel(aux_data=make_xi_data(args.num_halos, args.box_size,
                                          comm=comm), comm=comm)

    guess = WprpParams(log_shmrat=-1.7, log_softness=-0.7)
    # Collectives run on every process (SPMD); only printing is gated.
    loss0 = float(model.calc_loss_from_params(guess))
    if mgt.distributed.is_main_process():
        print(f"devices: {comm.size}; halos: {args.num_halos}")
        print("loss at guess:", loss0)

    result = model.run_bfgs(guess=guess, maxsteps=args.maxsteps,
                            progress=False)
    err = np.abs(np.asarray(result.x) - np.asarray(TRUTH)).max()
    if mgt.distributed.is_main_process():
        print(f"BFGS: nit={result.nit} nfev={result.nfev} "
              f"fun={float(result.fun):.3e}")
        print("Recovered params:", np.asarray(result.x),
              "truth:", np.asarray(TRUTH))
    assert err < 0.05, f"fit failed to recover truth (max err {err})"
    if mgt.distributed.is_main_process():
        print("Final solution OK")


if __name__ == "__main__":
    main()
