"""Out-of-core SMF fit: stream a memmapped halo catalog from disk.

Demonstrates the streaming data subsystem (``multigrad_tpu.data``):

1. write a halo catalog to a ``.npy`` file (stand-in for a real
   simulation catalog that would never fit in device memory),
2. wrap it in a :class:`MemmapSource` — chunks are read off disk on a
   background thread and ``device_put`` straight to the mesh shards
   (double-buffered: transfer of chunk k+1 overlaps compute on k),
3. fit the two-parameter SMF model with EXACT gradients via the
   two-pass streamed chain rule, and cross-check one loss/grad
   evaluation against the single-dispatch ``lax.scan`` path.

Run (any backend; on CPU simulate a mesh with
``XLA_FLAGS=--xla_force_host_platform_device_count=8``)::

    python examples/streaming_smf_fit.py --num-halos 100000 \
        --chunk-rows 16384 --num-steps 30
"""
import argparse
import json
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

import multigrad_tpu as mgt
from multigrad_tpu.data import MemmapSource, StreamingOnePointModel
from multigrad_tpu.models.smf import (ParamTuple, SMFModel,
                                      load_halo_masses, make_smf_data)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-halos", type=int, default=100_000)
    ap.add_argument("--chunk-rows", type=int, default=16_384)
    ap.add_argument("--num-steps", type=int, default=30)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--catalog", default=None,
                    help=".npy halo catalog (log10 masses); generated "
                         "into a temp dir when omitted")
    args = ap.parse_args()

    # -- 1. a catalog on disk ------------------------------------------
    path = args.catalog
    if path is None:
        path = os.path.join(tempfile.mkdtemp(prefix="mgt_stream_"),
                            "log_halo_masses.npy")
        np.save(path, np.asarray(
            jnp.log10(load_halo_masses(args.num_halos))))
        print(f"wrote synthetic catalog: {path}")
    source = MemmapSource(path)
    print(f"catalog: {source.n_rows} halos "
          f"({source.read(0, 1).dtype}, memmapped)")

    # -- 2. streaming model over the device mesh -----------------------
    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    aux = make_smf_data(source.n_rows, comm=None)
    del aux["log_halo_masses"]          # streamed, not resident
    model = StreamingOnePointModel(
        model=SMFModel(aux_data=aux, comm=comm),
        streams={"log_halo_masses": source},
        chunk_rows=args.chunk_rows)
    plan = model.plan()
    print(f"chunk plan: {plan.n_chunks} chunks x "
          f"{plan.rows_per_chunk} rows over "
          f"{plan.n_shards} shard(s), {plan.pad_rows} pad rows")

    # -- 3. fit with exact streamed gradients --------------------------
    guess = ParamTuple(log_shmrat=-1.0, sigma_logsm=0.5)
    traj = model.run_adam(guess=jnp.asarray(guess),
                          nsteps=args.num_steps,
                          learning_rate=args.learning_rate,
                          progress=False)
    final = np.asarray(traj[-1])
    print(f"fit: {guess} -> log_shmrat={final[0]:+.4f}, "
          f"sigma_logsm={final[1]:.4f} (truth -2.0, 0.2)")
    print("stream stats (last step):",
          json.dumps(model.last_stats.summary()))

    # Cross-check: the single-dispatch scan path agrees with the
    # two-pass stream at the solution.
    p = jnp.asarray(final)
    loss_stream, grad_stream = model.calc_loss_and_grad_from_params(p)
    loss_scan, grad_scan = model.calc_loss_and_grad_scan(p)
    print(f"two-pass stream: loss={float(loss_stream):.6f} "
          f"grad={np.asarray(grad_stream)}")
    print(f"scan (1 dispatch): loss={float(loss_scan):.6f} "
          f"grad={np.asarray(grad_scan)}")
    np.testing.assert_allclose(float(loss_stream), float(loss_scan),
                               rtol=1e-5)
    print("Final solution:", final)


if __name__ == "__main__":
    main()
