"""Live observability demo: HTTP endpoint + alert rules + dashboard.

Runs a real SMF mesh fit with the whole online stack attached — the
``LiveServer`` ``/metrics``+``/status`` endpoint, the ``AlertEngine``
non-fatal rules, the convergence diagnostics (loss-EMA plateau +
gradient-noise-scale taps) — then scrapes its own endpoint over a
real local HTTP request, injects a synthetic plateau stream so an
alert demonstrably fires, and leaves a JSONL behind for the terminal
dashboard::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/live_dashboard_demo.py --telemetry /tmp/live/run.jsonl
    python -m multigrad_tpu.telemetry.dashboard /tmp/live/run.jsonl --once

CI runs this per push, validates the saved ``/metrics`` scrape
against the Prometheus exposition grammar, renders the dashboard from
the JSONL, and uploads both as artifacts (exit 0 only when the scrape
served, the status reported step/loss/ETA, and the plateau alert
fired; ``LIVE OK`` is the greppable receipt).
"""
import argparse
import json
import os
import sys
import urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-halos", type=int, default=4096)
    ap.add_argument("--nsteps", type=int, default=60)
    ap.add_argument("--port", type=int, default=0,
                    help="endpoint port (0 = pick a free one)")
    ap.add_argument("--telemetry", default=None,
                    help="also write the record stream to this JSONL "
                         "(feed it to the dashboard CLI)")
    ap.add_argument("--metrics-out", default=None,
                    help="save the /metrics scrape here (CI validates "
                         "it against the exposition grammar)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import multigrad_tpu as mgt
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.telemetry import (AlertEngine, JsonlSink,
                                         LiveServer, MetricsLogger)

    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    model = SMFModel(aux_data=make_smf_data(args.num_halos, comm=comm),
                     comm=comm)

    sinks = []
    if args.telemetry:
        os.makedirs(os.path.dirname(os.path.abspath(args.telemetry)),
                    exist_ok=True)
        sinks.append(JsonlSink(args.telemetry))
    logger = MetricsLogger(*sinks, run_config={"demo": "live"})
    live = LiveServer(port=args.port)
    alerts = AlertEngine()
    print(f"live endpoint: {live.url}", file=sys.stderr)

    model.run_adam(guess=jnp.array([-1.0, 0.5]), nsteps=args.nsteps,
                   progress=False, telemetry=logger, log_every=5,
                   live=live, alerts=alerts, diagnostics=True)
    jax.effects_barrier()

    # -- scrape our own endpoint over real HTTP -------------------------
    status = json.load(urllib.request.urlopen(live.url + "/status",
                                              timeout=10))
    # every field may be None if the stack regressed — format
    # defensively so the structured error report below still runs
    loss = status["loss"]
    rate = status["steps_per_sec"]
    print(f"/status: phase={status['phase']} step={status['step']}"
          f"/{status['nsteps']} "
          f"loss={f'{loss:.4g}' if loss is not None else None} "
          f"steps/s={round(rate, 1) if rate is not None else None} "
          f"eta_s={status['eta_s']}")
    exposition = urllib.request.urlopen(live.url + "/metrics",
                                        timeout=10).read().decode()
    samples = [ln for ln in exposition.splitlines()
               if ln and not ln.startswith("#")]
    print(f"/metrics: {len(samples)} samples "
          f"({sum(1 for ln in exposition.splitlines() if ln.startswith('# TYPE'))} metrics)")
    if args.metrics_out:
        os.makedirs(os.path.dirname(os.path.abspath(args.metrics_out)),
                    exist_ok=True)
        with open(args.metrics_out, "w") as f:
            f.write(exposition)

    # -- inject a plateau so an alert demonstrably fires ----------------
    # (synthetic, clearly labeled: a fresh fit_plan + flat-loss tap
    # records — the exact stream a wedged fit would emit)
    logger.log("fit_plan", kind="synthetic_plateau", nsteps=40)
    for step in range(0, 40, 2):
        logger.log("adam", step=step, loss=0.5, grad_norm=0.01)
    fired = [a["rule"] for a in alerts.alerts]
    print(f"alerts fired: {fired}")
    logger.close()

    ok = (status["step"] is not None and status["loss"] is not None
          and status["eta_s"] is not None and samples
          and "loss_plateau" in fired)
    if not ok:
        print("ERROR: live stack incomplete "
              f"(status={status}, alerts={fired})", file=sys.stderr)
        return 1
    print(f"LIVE OK {live.url}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
