"""Sharded-K ensemble demo: 2-level mesh, ZeRO-partitioned Adam state.

Runs the same multi-start ensemble twice on one catalog —

* **replicated** (the historical path): flat data-parallel mesh, all
  K members' params/trajectories/Adam moments on every device;
* **sharded-K**: a 2-level ``(replica, data)`` mesh
  (:func:`multigrad_tpu.parallel.ensemble_comm`) where each replica
  slice owns K/R members and their optimizer state —

then proves three things and prints a greppable ``SHARD OK`` receipt:

1. the two layouts agree (float tolerance on the real SMF model —
   the data-axis reduction width differs — and BITWISE on an
   exact-arithmetic model whose reductions are exact in any
   association);
2. the trajectory's K axis really is partitioned over the replica
   axis (inspected off the returned array's sharding spec);
3. the memory model's headline: at an equal per-device budget the
   sharded layout admits R× the ensemble width, and the demo RUNS
   that width through the sharded path.

Usage (8 virtual CPU devices)::

    JAX_PLATFORMS=cpu \\
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/sharded_ensemble_demo.py
"""
import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np

import multigrad_tpu as mgt
from multigrad_tpu.inference import run_multistart_adam
from multigrad_tpu.inference.ensemble import (batched_fit_wrapper,
                                              ensemble_memory_model,
                                              max_k_for_budget)
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.optim import adam as _adam
from multigrad_tpu.parallel import ensemble_comm
from multigrad_tpu.utils.testing import bitwise_trajectory_pair

BOUNDS = [(-5.0, 1.0), (0.01, 2.0)]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--num-halos", type=int, default=20_000)
    ap.add_argument("--n-starts", type=int, default=16)
    ap.add_argument("--nsteps", type=int, default=30)
    ap.add_argument("--n-replicas", type=int, default=4)
    args = ap.parse_args(argv)

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % args.n_replicas:
        print(f"need a device count divisible by "
              f"{args.n_replicas} (got {n_dev}); set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8",
              file=sys.stderr)
        return 2
    R = args.n_replicas

    gcomm = mgt.global_comm()
    ecomm = ensemble_comm(R)
    rep_model = SMFModel(
        aux_data=make_smf_data(args.num_halos, comm=gcomm),
        comm=gcomm)
    sh_model = SMFModel(
        aux_data=make_smf_data(args.num_halos, comm=ecomm),
        comm=ecomm)
    print(f"mesh: {n_dev} devices -> (replica={R}, "
          f"data={n_dev // R});  K={args.n_starts} members, "
          f"{args.nsteps} steps")

    # 1) the same ensemble, both layouts ----------------------------
    res_rep = run_multistart_adam(
        rep_model, param_bounds=BOUNDS, n_starts=args.n_starts,
        nsteps=args.nsteps, k_sharded=False)
    res_sh = run_multistart_adam(
        sh_model, param_bounds=BOUNDS, n_starts=args.n_starts,
        nsteps=args.nsteps, k_sharded=True)
    assert res_sh.k_sharded and not res_rep.k_sharded
    pr = np.asarray(res_rep.params)
    ps = np.asarray(res_sh.params)
    finite = np.isfinite(pr).all(1) & np.isfinite(ps).all(1)
    assert np.array_equal(np.isfinite(pr).all(1),
                          np.isfinite(ps).all(1)), \
        "layouts disagree on which basins diverged"
    tol = float(np.max(np.abs(pr[finite] - ps[finite])))
    assert tol < 1e-4, f"layouts disagree beyond tolerance: {tol}"
    print(f"SMF ensemble: replicated vs sharded max|Δparams| = "
          f"{tol:.2e} over {int(finite.sum())} finite basins "
          f"(best loss {res_sh.best_loss:.5f} == "
          f"{res_rep.best_loss:.5f})")

    # 2) the K axis is really partitioned ---------------------------
    ks = sh_model.k_sharding(2)
    traj = _adam.run_adam_scan(
        batched_fit_wrapper(sh_model, False, k_sharded=True),
        jax.device_put(jnp.asarray(res_sh.inits), ks),
        nsteps=5, learning_rate=0.02, progress=False,
        fn_args=(sh_model.aux_leaves(),), carry_sharding=ks)
    spec = [s for s in jax.tree_util.tree_leaves(
        tuple(traj.sharding.spec)) if isinstance(s, str)]
    assert "replica" in spec, \
        f"trajectory K axis not partitioned: {traj.sharding}"
    print(f"trajectory sharding: {traj.sharding.spec} "
          "(K axis partitioned over the replica axis)")

    # 3) bitwise equivalence on the exact model ---------------------
    # The shared harness (utils/testing.py): same protocol as the
    # bench gate and the test suite.
    t_rep, t_sh = bitwise_trajectory_pair(gcomm, ecomm,
                                          n_devices=n_dev)
    assert np.array_equal(np.asarray(t_rep), np.asarray(t_sh)), \
        "exact-arithmetic trajectories are not bitwise equal"
    print("exact-arithmetic model: trajectories bitwise equal "
          "across layouts")

    # 4) the memory-model headline, executed ------------------------
    wide_nsteps = 10
    budget = 256 * ensemble_memory_model(1, 2, wide_nsteps)
    k_rep = max_k_for_budget(budget, 2, wide_nsteps)
    k_sh = max_k_for_budget(budget, 2, wide_nsteps, n_replicas=R)
    wide_model = SMFModel(
        aux_data=make_smf_data(2_000, comm=ecomm), comm=ecomm)
    rng = np.random.default_rng(0)
    wide = run_multistart_adam(
        wide_model, param_bounds=BOUNDS,
        inits=np.column_stack([rng.uniform(-2.3, -1.2, k_sh),
                               rng.uniform(0.3, 0.8, k_sh)]),
        nsteps=wide_nsteps, k_sharded=True)
    assert wide.n_starts == k_sh
    assert np.all(np.isfinite(np.asarray(wide.losses)))
    print(f"budget {budget} B/device admits K={k_rep} replicated, "
          f"K={k_sh} sharded — and the K={k_sh} ensemble RAN on "
          "the sharded path")

    print(f"SHARD OK K={args.n_starts} R={R} bitwise=1 "
          f"max_k x{k_sh // max(k_rep, 1)} wide_k={k_sh}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
