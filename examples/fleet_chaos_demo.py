"""Fleet chaos demo: kill a worker mid-burst, lose nothing.

The preemption-resilience story in one run: a
:class:`~multigrad_tpu.serve.fleet.FleetRouter` spawns N worker
processes (each its own jax runtime + ``FitScheduler``, all sharing
one on-disk XLA compile cache), a burst of SMF fit requests spreads
over them by config affinity, and then the
:class:`~multigrad_tpu.serve.chaos.ChaosController` SIGKILLs one
worker while ≥ half the burst is in flight — the spot-TPU
preemption worst case.  The router detects the loss (connection /
heartbeat), re-enqueues the dead worker's in-flight requests on the
survivors with their original deadlines and requeue history intact,
and every single future resolves.

CI runs this per push and greps the ``FLEET OK``, ``TRACE OK`` and
``RESOURCES OK`` receipts (exit 0 only when zero requests were lost,
every request's merged distributed trace reconstructs complete — the
killed ones with an explicit ``requeue`` hop — AND every worker's
utilization was heartbeat-sampled with the victim's final resource
ring captured in its ``worker_lost`` postmortem bundle)::

    JAX_PLATFORMS=cpu \\
        python examples/fleet_chaos_demo.py --telemetry-dir /tmp/_fleet

The telemetry dir afterwards holds per-worker JSONL streams (merged
by ``python -m multigrad_tpu.telemetry.aggregate w*.jsonl``), the
per-process trace files (waterfalls via ``python -m
multigrad_tpu.telemetry.trace *.trace.jsonl``), the ``worker_lost``
postmortem bundle, and the worker logs.
"""
import argparse
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--requests", type=int, default=24,
                    help="burst size (half lands on the victim)")
    ap.add_argument("--num-halos", type=int, default=2000)
    ap.add_argument("--nsteps", type=int, default=300)
    ap.add_argument("--kill-at-inflight", type=int, default=None,
                    help="SIGKILL the victim once this many requests "
                         "are in flight on it (default: half the "
                         "burst, min 16)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="fleet base dir (worker JSONLs, postmortem "
                         "bundles, logs, shared compile cache)")
    args = ap.parse_args()

    import numpy as np

    from multigrad_tpu.serve import ChaosController, FleetRouter
    from multigrad_tpu.serve.fleet import FleetRequest
    from multigrad_tpu.serve.queue import FitConfig, FitFuture

    kill_at = args.kill_at_inflight or max(16, args.requests // 2)

    router = FleetRouter(
        n_workers=args.workers,
        model_kwargs={"num_halos": args.num_halos},
        base_dir=args.telemetry_dir, devices=1,
        buckets=(1, 4, 16), batch_window_s=0.02,
        heartbeat_s=0.1, heartbeat_timeout_s=1.5, chaos=True)
    chaos = ChaosController(router)
    print(f"fleet up: {args.workers} workers in {router.base_dir}")

    # Two configs: the victim's (killed mid-burst) and a bystander's
    # (must be entirely undisturbed on its own worker).
    cfg_victim = FitConfig(nsteps=args.nsteps, learning_rate=0.03,
                           randkey=7)
    cfg_other = FitConfig(nsteps=args.nsteps, learning_rate=0.03,
                          randkey=8)
    probe = FleetRequest(id="probe", guess=np.zeros(2),
                         config=cfg_victim,
                         future=FitFuture("probe"))
    victim = router._affinity_order(probe.key)[0]
    print(f"victim by config affinity: {victim.id} "
          f"(pid {victim.pid})")

    rng = np.random.default_rng(0)
    n_victim = max(kill_at, args.requests // 2)
    n_other = max(args.requests - n_victim, 2)

    def guesses(n):
        return np.column_stack([rng.uniform(-2.3, -1.5, n),
                                rng.uniform(0.35, 0.6, n)])

    futs = [router.submit(g, config=cfg_victim)
            for g in guesses(n_victim)]
    futs += [router.submit(g, config=cfg_other)
             for g in guesses(n_other)]

    seen = {}

    def _kill():
        seen["inflight"] = len(victim.inflight)
        chaos.kill(victim.id)

    fired = chaos.when_inflight(kill_at, _kill, worker=victim.id)
    if not fired.wait(120):
        print("ERROR: kill injection never fired", file=sys.stderr)
        return 1
    print(f"SIGKILL'd {victim.id} with {seen['inflight']} requests "
          f"in flight")

    t0 = time.time()
    ok = True
    resolved, errors = 0, []
    for f in futs:
        try:
            exc = f.exception(timeout=600)
        except TimeoutError:
            print(f"ERROR: request {f.request_id} HUNG",
                  file=sys.stderr)
            ok = False
            continue
        resolved += 1
        if exc is not None:
            errors.append((f.request_id, type(exc).__name__))
    requeued = [f for f in futs if f.requeues]
    print(f"burst resolved in {time.time() - t0:.1f}s: "
          f"{resolved}/{len(futs)} futures settled, "
          f"{len(requeued)} requeued off the dead worker, "
          f"{len(errors)} errors")
    if errors:
        # A typed error is not a LOST request — but this demo's burst
        # is built to converge, so any error fails the receipt.
        print(f"ERROR: unexpected failures: {errors}",
              file=sys.stderr)
        ok = False
    if resolved != len(futs):
        ok = False
    if not requeued:
        print("ERROR: nothing requeued — the kill missed the burst",
              file=sys.stderr)
        ok = False
    survivors = {f._result.worker for f in requeued
                 if f._result is not None}
    if victim.id in survivors:
        print("ERROR: a requeued request claims the dead worker",
              file=sys.stderr)
        ok = False

    bundle = next((f.requeues[0]["bundle"] for f in requeued
                   if f.requeues and f.requeues[0]["bundle"]), None)
    stats = router.stats
    rate = stats["fits_per_hour"]      # None if nothing completed
    print(f"worker deaths: {stats.get('worker_deaths', 0)}, "
          f"requeues: {stats.get('requeued', 0)}"
          + (f", aggregate {rate:.0f} fits/hour" if rate else ""))
    print(f"chaos log:\n{chaos.report()}")
    if bundle:
        print(f"POSTMORTEM {bundle}")
    else:
        print("ERROR: no worker_lost postmortem bundle",
              file=sys.stderr)
        ok = False

    # The resource-observability receipt (PR 18): every worker's
    # utilization was heartbeat-sampled into the router's fleet view
    # — the DEAD one included (its last snapshots arrived before the
    # SIGKILL) — and the victim's final resource ring rode into its
    # worker_lost postmortem bundle (a SIGKILL'd process cannot dump
    # its own ring; the router's heartbeat copy IS the ring).
    from multigrad_tpu.telemetry.top import (_rows_from_status,
                                             render_rows)
    unsampled = [wid for wid, w in stats["workers"].items()
                 if not w.get("resources")]
    if unsampled:
        print(f"ERROR: workers never resource-sampled: {unsampled}",
              file=sys.stderr)
        ok = False
    victim_ring = []
    if bundle:
        import json as _json
        with open(bundle) as f:
            victim_ring = (_json.load(f).get("detail") or {}) \
                .get("resources") or []
        if not victim_ring:
            print("ERROR: worker_lost bundle has no resource ring",
                  file=sys.stderr)
            ok = False
    print("fleet top (from router.stats):")
    print(render_rows(_rows_from_status("router", stats,
                                        time.time())))
    if ok:
        print(f"RESOURCES OK {len(stats['workers'])} workers "
              f"sampled, victim ring {len(victim_ring)} snapshots "
              f"in postmortem, fleet busy_frac "
              f"{stats.get('fleet_busy_frac')}")

    chaos.close()
    trace_paths = router.trace_paths
    router.close()

    # The distributed-tracing receipt, from the JSONL files alone
    # (the router is closed — exactly the post-hoc triage posture):
    # every request's merged trace must reconstruct a complete
    # parent-linked waterfall, the killed requests' with an explicit
    # requeue hop naming both worker generations.
    from multigrad_tpu.telemetry.aggregate import merge_traces
    from multigrad_tpu.telemetry.trace import trace_summary
    by_trace = merge_traces(trace_paths)
    incomplete, coverages, requeue_hops = [], [], 0
    for f in futs:
        summary = trace_summary(f.trace_id,
                                by_trace.get(f.trace_id, []))
        if not summary["complete"]:
            incomplete.append(f.trace_id)
        if summary["coverage"] is not None:
            coverages.append(summary["coverage"])
        requeue_hops += len(summary["requeues"])
        if f in requeued and not summary["requeues"]:
            print(f"ERROR: requeued request {f.request_id} has no "
                  f"requeue span in trace {f.trace_id[:12]}",
                  file=sys.stderr)
            ok = False
    if incomplete:
        print(f"ERROR: {len(incomplete)} incomplete traces "
              f"(orphan spans / unresolved parents): "
              f"{[t[:12] for t in incomplete[:5]]}",
              file=sys.stderr)
        ok = False
    if len(by_trace) < len(futs):
        print(f"ERROR: only {len(by_trace)} traces for "
              f"{len(futs)} requests", file=sys.stderr)
        ok = False

    if not ok:
        return 1
    print(f"TRACE OK {len(by_trace)} traces complete, "
          f"{requeue_hops} requeue hops, min coverage "
          f"{min(coverages):.0%}"
          + (f" (waterfalls: python -m multigrad_tpu.telemetry"
             f".trace {trace_paths[0]} ...)" if trace_paths else ""))
    print(f"FLEET OK {resolved}/{len(futs)} futures resolved, "
          f"{len(requeued)} requeued, 0 lost")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
