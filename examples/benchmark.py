"""Speed-test the SMF pipeline (CLI parity with the reference).

Port of ``/root/reference/tests/smf_example/benchmark.py`` with the
same flags and record format — minus MPI: device count comes from the
mesh, timing from ``time.perf_counter`` instead of ``MPI.Wtime``, and
the fit runs as one in-graph scan.

    python examples/benchmark.py --num-halos 1_000_000 --num-steps 100 \\
        --save bench.txt
"""
import argparse
import time

import jax

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import ParamTuple, SMFModel, make_smf_data

parser = argparse.ArgumentParser(
    __file__,
    description="Speed test multigrad_tpu with the SMF pipeline.")
parser.add_argument("--num-halos", type=int, default=10_000)
parser.add_argument("--num-steps", type=int, default=100)
parser.add_argument("--learning-rate", type=float, default=1e-3)
parser.add_argument("--save", type=str, default=None)
parser.add_argument("--optimizer", choices=["gd", "adam"], default="gd")
parser.add_argument("--single-device", action="store_true")


def speedtest(model, guess, nsteps, learning_rate, optimizer):
    if optimizer == "adam":
        out = model.run_adam(guess=guess, nsteps=nsteps,
                             learning_rate=learning_rate, progress=False)
    else:
        out = model.run_simple_grad_descent(
            guess=guess, nsteps=nsteps, learning_rate=learning_rate).params
    # Fetch to host rather than block_until_ready: on async/tunneled
    # runtimes the latter can return before execution drains, which
    # silently inflates the measured rate (see bench.py).
    import numpy as np
    return np.asarray(out)


if __name__ == "__main__":
    args = parser.parse_args()
    comm = None if args.single_device else mgt.global_comm()
    model = SMFModel(aux_data=make_smf_data(args.num_halos, comm=comm),
                     comm=comm)
    guess = ParamTuple(log_shmrat=-1, sigma_logsm=0.5)

    # Run once to compile JIT methods (reference benchmark.py:41-42);
    # same nsteps so the scanned executable is the cached one.
    speedtest(model, guess, args.num_steps, args.learning_rate,
              args.optimizer)
    t0 = time.perf_counter()
    speedtest(model, guess, args.num_steps, args.learning_rate,
              args.optimizer)
    t = time.perf_counter() - t0

    if mgt.distributed.is_main_process():
        calls_per_sec = args.num_steps / t
        n_dev = 1 if comm is None else comm.size

        print(f"Benchmark with {n_dev} devices {args}")
        print("=" * 70)
        print(f"Grad descent iterations/sec = {calls_per_sec}")
        print()

        if args.save is not None:
            result = dict(calls_per_sec=calls_per_sec,
                          num_devices=n_dev,
                          num_halos=args.num_halos,
                          num_steps=args.num_steps,
                          learning_rate=args.learning_rate,
                          optimizer=args.optimizer)
            with open(args.save, "a+") as f:
                f.write(f"{repr(result)}\n")
