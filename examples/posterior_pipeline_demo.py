"""Posterior pipeline demo: one job, the whole north-star workload.

The joint-posterior-as-a-service story in one run: a single
:class:`~multigrad_tpu.serve.jobs.Job` — scan → ensemble → Laplace →
HMC → posterior-predictive check over the fused SMF+wprp joint
likelihood (:func:`~multigrad_tpu.models.joint.make_joint_smf_wprp`)
— submitted to a :class:`~multigrad_tpu.serve.jobs.JobRunner` backed
by a 2-worker :class:`~multigrad_tpu.serve.fleet.FleetRouter`, each
worker its own jax runtime serving the same joint model.  Mid-way
through the ensemble stage the
:class:`~multigrad_tpu.serve.chaos.ChaosController` SIGKILLs the
worker holding the ensemble burst — the spot-preemption worst case —
and the router requeues its in-flight fits on the survivor, so the
job completes without re-running any settled stage.

CI runs this per push and greps the ``JOB OK`` and ``0 incomplete``
receipts (exit 0 only when the job settles ok with every stage
accounted for, the kill demonstrably requeued work, AND the job's
single merged distributed trace reconstructs complete — root ``job``
span, one ``stage`` span per stage, every fit's ``request`` span and
its scheduler hops parent-resolved)::

    JAX_PLATFORMS=cpu \\
        python examples/posterior_pipeline_demo.py --telemetry-dir /tmp/_job

Afterwards the telemetry dir holds the per-worker JSONL streams and
trace files (waterfall via ``python -m multigrad_tpu.telemetry.trace
<dir>/*.trace.jsonl``, grouped by stage), the ``job_summary`` /
``predictive_check`` records (``python -m
multigrad_tpu.telemetry.report``), and the job's stage-boundary
checkpoint under ``jobs/``.
"""
import argparse
import os
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-halos", type=int, default=512,
                    help="wprp catalog rows (SMF member gets 4x)")
    ap.add_argument("--ensemble-starts", type=int, default=8)
    ap.add_argument("--ensemble-nsteps", type=int, default=250)
    ap.add_argument("--hmc-samples", type=int, default=80)
    ap.add_argument("--hmc-warmup", type=int, default=100)
    ap.add_argument("--kill-at-inflight", type=int, default=4,
                    help="SIGKILL the ensemble-affinity worker once "
                         "this many of the stage's fits are in "
                         "flight on it")
    ap.add_argument("--telemetry-dir", default=None,
                    help="fleet base dir (worker JSONLs, traces, "
                         "job checkpoints, logs)")
    args = ap.parse_args()

    import numpy as np

    from multigrad_tpu.models import JOINT_TRUTH, make_joint_smf_wprp
    from multigrad_tpu.serve import (ChaosController, EnsembleStage,
                                     FleetRouter, HmcStage, Job,
                                     JobRunner, LaplaceStage,
                                     PredictiveCheckStage, SweepStage)
    from multigrad_tpu.serve.fleet import FleetRequest
    from multigrad_tpu.serve.queue import FitConfig, FitFuture

    bounds = ((-3.5, -0.5), (0.02, 1.0), (-2.5, 0.5))

    # Both workers serve the SAME joint model the host-side stages
    # use (same factory, same seed → same synthetic catalogs), via
    # the worker's "module:factory" spec hook.
    router = FleetRouter(
        n_workers=2,
        model="multigrad_tpu.models.joint:make_joint_smf_wprp",
        model_kwargs={"num_halos": args.num_halos, "seed": 1},
        base_dir=args.telemetry_dir, devices=1,
        buckets=(1, 4, 8), batch_window_s=0.02,
        heartbeat_s=0.1, heartbeat_timeout_s=1.5, chaos=True)
    chaos = ChaosController(router)
    print(f"fleet up: 2 workers in {router.base_dir}")

    local_model = make_joint_smf_wprp(num_halos=args.num_halos,
                                      seed=1)
    runner = JobRunner(
        router, model=local_model,
        checkpoint_dir=os.path.join(router.base_dir, "jobs"))

    job = Job(job_id="job-demo", stages=[
        SweepStage(name="scan", n_points=8, nsteps=40,
                   learning_rate=0.1, param_bounds=bounds),
        EnsembleStage(name="ensemble", deps=("scan",),
                      n_starts=args.ensemble_starts,
                      nsteps=args.ensemble_nsteps,
                      learning_rate=0.02, param_bounds=bounds),
        LaplaceStage(name="laplace", deps=("ensemble",)),
        HmcStage(name="hmc", deps=("laplace",),
                 num_samples=args.hmc_samples,
                 num_warmup=args.hmc_warmup, num_chains=2),
        PredictiveCheckStage(name="check", deps=("hmc",),
                             max_draws=16),
    ])

    # Victim by config affinity: the ensemble stage's whole burst
    # shares ONE stage-stamped FitConfig, so the identical probe
    # config names the worker that will hold it.
    cfg_ens = FitConfig(nsteps=args.ensemble_nsteps,
                        learning_rate=0.02, param_bounds=bounds,
                        job_id=job.job_id, stage="ensemble")
    probe = FleetRequest(id="probe", guess=np.zeros(3),
                         config=cfg_ens, future=FitFuture("probe"))
    victim = router._affinity_order(probe.key)[0]
    print(f"ensemble affinity victim: {victim.id} "
          f"(pid {victim.pid})")

    fut = runner.submit(job)
    print(f"submitted {job.job_id}: "
          + " -> ".join(s.name for s in job.stages))

    # Arm the kill only once the scan stage has settled, so the
    # SIGKILL lands mid-ENSEMBLE (the acceptance scenario) rather
    # than somewhere random in the pipeline.
    deadline = time.time() + 600
    scan = None
    while time.time() < deadline:
        scan = fut.stage_results.get("scan")
        if scan is not None:
            break
        time.sleep(0.05)
    if scan is None or not scan.ok:
        print(f"ERROR: scan stage did not settle ok: {scan}",
              file=sys.stderr)
        router.close(drain=False)
        return 1
    print(f"scan settled ({scan.elapsed_s:.1f}s); arming SIGKILL at "
          f"{args.kill_at_inflight} in-flight on {victim.id}")

    seen = {}

    def _kill():
        seen["inflight"] = len(victim.inflight)
        chaos.kill(victim.id)

    fired = chaos.when_inflight(args.kill_at_inflight, _kill,
                                worker=victim.id)
    ok = True
    if not fired.wait(300):
        print("ERROR: kill injection never fired (ensemble burst "
              "missed the victim)", file=sys.stderr)
        ok = False
    else:
        print(f"SIGKILL'd {victim.id} with {seen['inflight']} "
              f"ensemble fits in flight")

    result = fut.result(timeout=1200)
    print(f"job settled in {result.elapsed_s:.1f}s: "
          f"ok={result.ok}  outcomes={result.outcomes()}")
    if not result.ok:
        for name, res in result.stages.items():
            if not res.ok:
                print(f"ERROR: stage {name} {res.outcome}: "
                      f"{res.error}", file=sys.stderr)
        ok = False
    else:
        best = result.artifact("ensemble").get("best_params")
        check = result.artifact("check")
        hmc = result.artifact("hmc")
        print(f"ensemble best: {np.round(best, 3).tolist()} "
              f"(truth {JOINT_TRUTH})")
        print(f"hmc: accept={hmc.get('accept_prob')}  "
              f"rhat={hmc.get('rhat')}")
        print(f"predictive check: ok={check.get('ok')}  "
              f"verdicts={check.get('verdicts')}")
        if not check.get("ok"):
            print("ERROR: posterior predictive check failed",
                  file=sys.stderr)
            ok = False
        if not np.all(np.isfinite(np.asarray(best, dtype=float))):
            print("ERROR: non-finite ensemble best",
                  file=sys.stderr)
            ok = False

    stats = router.stats
    requeued = stats.get("requeued", 0)
    deaths = stats.get("worker_deaths", 0)
    rate = stats.get("fits_per_hour")
    print(f"worker deaths: {deaths}, requeues: {requeued}"
          + (f", aggregate {rate:.0f} fits/hour" if rate else ""))
    print(f"chaos log:\n{chaos.report()}")
    if fired.is_set() and not requeued:
        print("ERROR: the kill requeued nothing — it missed the "
              "ensemble burst", file=sys.stderr)
        ok = False

    chaos.close()
    trace_paths = router.trace_paths
    router.close()

    # The tracing receipt, from the JSONL files alone (router
    # closed — the post-hoc triage posture): the job's ONE merged
    # trace must reconstruct a complete parent-linked waterfall —
    # root `job` span, a `stage` span per stage, every fit's
    # `request` span and scheduler hops resolved.
    from multigrad_tpu.telemetry.aggregate import merge_traces
    from multigrad_tpu.telemetry.trace import trace_summary
    by_trace = merge_traces(trace_paths)
    spans = by_trace.get(result.trace_id, [])
    summary = trace_summary(result.trace_id, spans)
    incomplete = [] if summary["complete"] else [result.trace_id]
    stage_rollup = summary.get("stages", {})
    missing = [s.name for s in job.stages
               if s.name not in stage_rollup]
    if missing:
        print(f"ERROR: trace has no stage span for {missing}",
              file=sys.stderr)
        ok = False
    if incomplete:
        print(f"ERROR: job trace incomplete (orphan spans / "
              f"unresolved parents) — {len(spans)} spans",
              file=sys.stderr)
        ok = False

    if not ok:
        print(f"{len(incomplete) or 1} incomplete", file=sys.stderr)
        return 1
    print(f"TRACE OK {len(spans)} spans, {len(stage_rollup)} stage "
          f"spans, {len(incomplete)} incomplete"
          + (f" (waterfall: python -m multigrad_tpu.telemetry"
             f".trace {trace_paths[0]} ...)" if trace_paths else ""))
    print(f"JOB OK {job.job_id}: {len(result.stages)} stages ok, "
          f"{deaths} worker death, {requeued} fits requeued, "
          f"0 lost")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
