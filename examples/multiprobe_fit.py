"""Multi-probe joint fit: SMF + wp(rp) over a shared parameter space.

The reference's north-star workload list ends with "Multi-probe
(SMF + wp(rp)) joint fit" (``BASELINE.json`` config 5); its own
:class:`OnePointGroup` only supports homogeneous parameterizations
(every model receives the identical params vector,
``/root/reference/multigrad/multigrad.py:571-580``).  Here the two
probes constrain a three-parameter joint space

    (log_shmrat, sigma_logsm, log_softness)

with ``log_shmrat`` shared: the stellar mass function pins the
mass-ratio + scatter, the projected correlation function pins the
selection softness, and :func:`multigrad_tpu.param_view` adapters
route each model's slice of the joint vector (gradients scatter back
automatically through the gather's VJP).

By default each probe runs on its own sub-mesh (true MPMD, the
reference's subcomm pattern); with ``--shared-mesh`` both probes
share the full mesh and the joint step compiles into ONE fused XLA
program (``group.fused``) instead:

    python examples/multiprobe_fit.py --num-halos 10_000
    python examples/multiprobe_fit.py --shared-mesh

(Set ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` with
``JAX_PLATFORMS=cpu`` to simulate the mesh on CPU.)
"""
import argparse
import time

import numpy as np
from jax import numpy as jnp

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import SMFModel, make_smf_data
from multigrad_tpu.models.wprp import WprpModel, make_wprp_data

parser = argparse.ArgumentParser(
    __file__, description="Joint SMF + wp(rp) fit with multigrad_tpu")
parser.add_argument("--num-halos", type=int, default=10_000,
                    help="halos in the SMF probe")
parser.add_argument("--num-clustering-halos", type=int, default=768,
                    help="halos in the wp(rp) probe (O(N^2) pairs)")
parser.add_argument("--maxsteps", type=int, default=150)
parser.add_argument(
    "--shared-mesh", action="store_true",
    help="put both probes on the full mesh instead of disjoint "
         "sub-meshes: the joint step then compiles into ONE fused "
         "XLA program (group.fused) — the fast path when you don't "
         "need MPMD device partitioning")

JOINT_TRUTH = np.array([-2.0, 0.2, -1.0])
GUESS = jnp.array([-1.7, 0.35, -0.6])
BOUNDS = [(-4.0, 0.0), (0.01, 1.0), (-2.0, 0.0)]

if __name__ == "__main__":
    args = parser.parse_args()

    comm = mgt.global_comm()
    if args.shared_mesh:
        comms = (comm, comm)
    else:
        subcomms, _, _ = mgt.split_subcomms(num_groups=2, comm=comm)
        comms = subcomms

    smf = SMFModel(aux_data=make_smf_data(args.num_halos,
                                          comm=comms[0]),
                   comm=comms[0])
    wp = WprpModel(aux_data=make_wprp_data(args.num_clustering_halos,
                                           comm=comms[1]),
                   comm=comms[1])
    group = mgt.OnePointGroup(models=(
        mgt.param_view(smf, [0, 1]),   # (log_shmrat, sigma_logsm)
        mgt.param_view(wp, [0, 2]),    # (log_shmrat, log_softness)
    ))
    if mgt.distributed.is_main_process():
        print("joint-step path:",
              "fused (one XLA program)" if group.fused
              else "MPMD (async per-submesh dispatch)")

    t0 = time.time()
    result = group.run_bfgs(guess=GUESS, maxsteps=args.maxsteps,
                            param_bounds=BOUNDS, progress=False)
    elapsed = time.time() - t0

    if mgt.distributed.is_main_process():
        print(f"Joint BFGS finished in {elapsed:.1f}s "
              f"(nit={result.nit}, nfev={result.nfev})")
        print(f"loss      = {result.fun:.3e}")
        print(f"recovered = {np.round(np.asarray(result.x), 4)}")
        print(f"truth     = {JOINT_TRUTH}")
        err = np.max(np.abs(np.asarray(result.x) - JOINT_TRUTH))
        print(f"max |err| = {err:.2e}")
        assert err < 0.05, "joint fit failed to recover the truth"
        print("SUCCESS")
