"""Generate TPU-pod scaling-benchmark launch commands.

Port of the reference's Slurm sweep generator
(``/root/reference/tests/smf_example/submit_benchmark_jobs.py``),
retargeted from ``sbatch``/``srun`` on CPU nodes to Cloud TPU pod
slices: for each slice size it emits (or runs) the ``gcloud`` command
that executes ``examples/benchmark.py`` on every host of the slice.
Each host runs the *same* SPMD program (``multigrad_tpu.distributed
.initialize`` wires the slice together) — there is no rank-count
argument because the mesh discovers its own devices.

    python examples/submit_benchmark_jobs.py --print-only \\
        --accelerators v4-8 v4-16 v4-32 --num-halos 100_000
"""
import argparse
import subprocess

parser = argparse.ArgumentParser(
    __file__, description="Generate TPU-pod benchmark launch commands")
parser.add_argument("--tpu-name", type=str, default="multigrad-bench")
parser.add_argument("--zone", type=str, default="us-central2-b")
parser.add_argument("--accelerators", nargs="+",
                    default=["v4-8", "v4-16", "v4-32"])
parser.add_argument("--num-halos", type=int, default=100_000)
parser.add_argument("--num-steps", type=int, default=100)
parser.add_argument("--learning-rate", type=float, default=1e-3)
parser.add_argument("--save", type=str, default="bench.txt")
parser.add_argument("--print-only", action="store_true",
                    help="print the commands instead of running them")

WORKER_CMD = ("python examples/benchmark.py --num-halos {num_halos} "
              "--num-steps {num_steps} --learning-rate {learning_rate} "
              "--optimizer adam --save {save}")


def make_commands(args):
    """One (create, run, delete) command triple per slice size."""
    triples = []
    for acc in args.accelerators:
        name = f"{args.tpu_name}-{acc}"
        worker = WORKER_CMD.format(
            num_halos=args.num_halos, num_steps=args.num_steps,
            learning_rate=args.learning_rate, save=args.save)
        create = (f"gcloud compute tpus tpu-vm create {name} "
                  f"--zone {args.zone} --accelerator-type {acc} "
                  f"--version tpu-ubuntu2204-base")
        run = (f"gcloud compute tpus tpu-vm ssh {name} --zone {args.zone} "
               f"--worker=all --command '{worker}'")
        delete = (f"gcloud compute tpus tpu-vm delete {name} "
                  f"--zone {args.zone} --quiet")
        triples.append((create, run, delete))
    return triples


if __name__ == "__main__":
    args = parser.parse_args()
    for create, run, delete in make_commands(args):
        if args.print_only:
            print(create)
            print(run)
            print(delete)
            print()
        else:
            subprocess.run(create, shell=True, check=True)
            try:
                subprocess.run(run, shell=True, check=True)
            finally:
                subprocess.run(delete, shell=True, check=False)
