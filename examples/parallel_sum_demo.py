"""Minimal reduce_sum demo — each shard contributes its index.

TPU-native analog of the reference's 14-line mpi4py teaching demo
(``/root/reference/tests/smf_example/parallel_sum_mpi4py_demo.py``):
there, each MPI rank contributes its rank number and ``COMM.Reduce``
sums them; here each mesh shard's block plays the rank's role and the
sum is one ``reduce_sum`` over the comm.

Run on N virtual devices:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/parallel_sum_demo.py
"""
import os

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    # Honor the env var even where a sitecustomize re-forces another
    # platform: the config API wins (same workaround as
    # tests/conftest.py and __graft_entry__.dryrun_multichip).
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import multigrad_tpu as mgt

comm = mgt.global_comm()
contributions = np.arange(comm.size, dtype=np.float64)  # shard i -> i
sharded = mgt.scatter_nd(contributions, comm=comm)
total = mgt.reduce_sum(sharded, comm=comm)
print(f"{comm.size} shards, sum of shard indices = {float(np.asarray(total)[0])}")
assert float(np.asarray(total)[0]) == comm.size * (comm.size - 1) / 2
