"""NaN-seeded Adam fit -> postmortem bundle: the flight-recorder demo.

Seeds the SMF model with an impossible target (negative sumstats, so
``log10`` makes the loss NaN from step 0), arms the flight recorder,
and shows the full failure path: the in-graph non-finite sentinel
fires inside the jitted scan, the recorder dumps a self-contained
postmortem bundle (the tapped step records, run record, jaxpr
digest), the ``fit_summary`` telemetry record carries the bundle
path, and the fit raises ``FlightRecorderTripped``.

CI runs this per push and uploads the bundle as a workflow artifact
— living proof the recorder fires (exit 0 only when the whole chain
worked; the ``POSTMORTEM <path>`` line is the greppable receipt)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/flight_recorder_demo.py --dump-dir /tmp/postmortems
"""
import argparse
import json
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dump-dir", default=None,
                    help="postmortem bundle directory (default: a "
                         "fresh temp dir)")
    ap.add_argument("--num-halos", type=int, default=4096)
    ap.add_argument("--nsteps", type=int, default=10)
    ap.add_argument("--telemetry", default=None,
                    help="also write the record stream to this JSONL")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import multigrad_tpu as mgt
    from multigrad_tpu.models.smf import SMFModel, make_smf_data
    from multigrad_tpu.telemetry import (FlightRecorder,
                                         FlightRecorderTripped,
                                         JsonlSink, MemorySink,
                                         MetricsLogger)

    comm = mgt.global_comm() if len(jax.devices()) > 1 else None
    aux = make_smf_data(args.num_halos, comm=comm)
    # The seed: a negative target makes log10(target) NaN, so the
    # loss is NaN from the first step — deterministically.
    aux["target_sumstats"] = -jnp.asarray(aux["target_sumstats"])
    model = SMFModel(aux_data=aux, comm=comm)

    recorder = FlightRecorder(dump_dir=args.dump_dir)
    sinks = [MemorySink(), recorder]
    if args.telemetry:
        # JsonlSink appends to an existing path; the CI invocation
        # points it inside the (not-yet-created) dump dir.
        parent = os.path.dirname(os.path.abspath(args.telemetry))
        os.makedirs(parent, exist_ok=True)
        sinks.insert(0, JsonlSink(args.telemetry))
    logger = MetricsLogger(*sinks, run_config={"demo": "flight"})

    try:
        model.run_adam(guess=jnp.array([-1.0, 0.5]),
                       nsteps=args.nsteps, progress=False,
                       telemetry=logger, log_every=1,
                       flight=recorder)
    except FlightRecorderTripped as e:
        logger.close()
        with open(e.bundle_path) as f:
            bundle = json.load(f)
        ring_events = [r.get("event") for r in bundle["ring"]]
        print(f"tripped as designed: {e.reason} at step {e.step}")
        print(f"bundle ring: {len(bundle['ring'])} records "
              f"({sorted(set(ring_events))})")
        print(f"jaxpr digests: {bundle['jaxpr_digests']}")
        print(f"POSTMORTEM {e.bundle_path}")
        return 0
    print("ERROR: the NaN-seeded fit did not trip the flight "
          "recorder", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
