"""Canonical end-to-end SMF fitting pipeline.

TPU-native port of the reference example
(``/root/reference/tests/smf_example/smf_grad_descent.py``): fit the
two-parameter galaxy–halo model to the stellar mass function by
gradient descent, then produce the same five diagnostic plots.

Where the reference ran ``mpiexec -n 3 python smf_grad_descent.py``,
here every addressable device joins a mesh automatically:

    python examples/smf_grad_descent.py --num-halos 1_000_000

(Set ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` with
``JAX_PLATFORMS=cpu`` to simulate a mesh on CPU.)
"""
import argparse
import time

import jax
import numpy as np
from jax import numpy as jnp

import multigrad_tpu as mgt
from multigrad_tpu.models.smf import (ParamTuple, SMFModel, load_halo_masses,
                                      make_smf_data)

parser = argparse.ArgumentParser(
    __file__,
    description="Example pipeline using multigrad_tpu to fit the SMF")
parser.add_argument("--num-halos", type=int, default=10_000)
parser.add_argument("--num-steps", type=int, default=2000)
parser.add_argument("--learning-rate", type=float, default=1e-3)
parser.add_argument("--optimizer", choices=["gd", "adam", "bfgs"],
                    default="gd")
parser.add_argument("--no-plots", action="store_true")
parser.add_argument("--single-device", action="store_true",
                    help="skip the mesh (one-chip fast path)")

if __name__ == "__main__":
    args = parser.parse_args()
    comm = None if args.single_device else mgt.global_comm()
    data = make_smf_data(args.num_halos, comm=comm)
    model = SMFModel(aux_data=data, comm=comm)

    guess = ParamTuple(log_shmrat=-1, sigma_logsm=0.5)
    t0 = time.time()
    if args.optimizer == "gd":
        gd_iterations = model.run_simple_grad_descent(
            guess=guess, nsteps=args.num_steps,
            learning_rate=args.learning_rate)
        gd_loss, gd_params = gd_iterations.loss, gd_iterations.params
    elif args.optimizer == "adam":
        gd_params = model.run_adam(
            guess=guess, nsteps=args.num_steps,
            learning_rate=args.learning_rate)
        # Subsample the trajectory for loss evaluation, keeping the
        # true step index for plotting.
        loss_steps = np.arange(0, len(gd_params),
                               max(1, len(gd_params) // 50))
        gd_loss = jnp.array([model.calc_loss_from_params(gd_params[i])
                             for i in loss_steps])
    else:
        result = model.run_bfgs(guess=guess, maxsteps=args.num_steps)
        gd_params = jnp.array([[*guess], result.x])
        gd_loss = jnp.array([result.fun])
    t = time.time() - t0

    # Parallel calculations needed for plots
    truth = ParamTuple(log_shmrat=-2.0, sigma_logsm=0.2)
    final = ParamTuple(*np.asarray(gd_params[-1]).tolist())
    guess_smf = model.calc_sumstats_from_params(guess)
    true_smf = model.calc_sumstats_from_params(truth)
    final_smf = model.calc_sumstats_from_params(final)

    # Report results and make plots on the main process only
    # (reference: `if not MPI.COMM_WORLD.Get_rank()`, line 123)
    if not args.no_plots and mgt.distributed.is_main_process():
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        print(f"Initial guess: {guess} ... {t} seconds later ...")
        print(f"Final solution: {final}")
        print(f"Truth: {truth}")
        print(f"True SMF: {repr(true_smf)}")

        # Plot the HMF (per-shard coloring replaced by a single global
        # histogram: shards are mesh-internal here)
        log_mh_global = np.log10(np.asarray(
            load_halo_masses(args.num_halos)))
        bins = jnp.linspace(log_mh_global.min(), log_mh_global.max(), 101)
        plt.hist(log_mh_global, bins=np.asarray(bins))
        plt.semilogy()
        plt.xlabel("$\\log M_h$", fontsize=16)
        plt.ylabel("$N$", fontsize=16)
        plt.savefig("hmf_model.png", bbox_inches="tight")
        plt.clf()

        # Plot the SMF target, initial guess, and final solution
        smf_bin_cens = 0.5 * (data["smf_bin_edges"][:-1]
                              + data["smf_bin_edges"][1:])
        plt.semilogy(smf_bin_cens, true_smf, "go", label="Truth")
        plt.semilogy(smf_bin_cens, data["target_sumstats"], "rx",
                     label="Target")
        plt.plot(smf_bin_cens, guess_smf, "k--", label="Initial guess")
        plt.plot(smf_bin_cens, final_smf, label="Final solution")
        plt.xlabel("$\\log(M_\\star)$", fontsize=16)
        plt.ylabel("$\\Phi(M_\\star)\\ [h^3{\\rm Mpc^{-3} dex^{-1}}]$",
                   fontsize=16)
        plt.legend(frameon=False, fontsize=16)
        plt.savefig("smf_fit.png", bbox_inches="tight")
        plt.clf()

        # Loss per iteration
        if args.optimizer == "adam":
            plt.plot(loss_steps, gd_loss)
        else:
            plt.plot(gd_loss)
        plt.semilogy()
        plt.xlabel("$N_{\\rm step}$", fontsize=16)
        plt.ylabel("$\\chi_\\nu^2$ loss", fontsize=16)
        plt.savefig("gd_loss.png", bbox_inches="tight")
        plt.clf()

        # Params per iteration
        nrows = gd_params.shape[1]
        fig, axes = plt.subplots(nrows=nrows, figsize=(6.4, 4 * nrows))
        for i in range(nrows):
            axes[i].plot(gd_params[:, i], label=ParamTuple._fields[i])
            axes[i].axhline(truth[i], color="r", ls="--", label="truth")
            if i == nrows - 1:
                axes[i].set_xlabel("$N_{\\rm step}$", fontsize=16)
            axes[i].set_ylabel(ParamTuple._fields[i], fontsize=16)
        plt.savefig("gd_param.png", bbox_inches="tight")
        plt.clf()

        # 2D parameter path
        plt.scatter(gd_params[:, 0], gd_params[:, 1], s=2)
        plt.plot(*truth, "rx", label="Truth")
        plt.xlabel(ParamTuple._fields[0], fontsize=16)
        plt.ylabel(ParamTuple._fields[1], fontsize=16)
        plt.legend(frameon=False, fontsize=16)
        plt.savefig("gd_param_path.png", bbox_inches="tight")
        plt.clf()
