"""Parameter-bounds bijections (bounded <-> unbounded space).

Port of the reference's transform system
(``/root/reference/multigrad/adam.py:192-239``): two-sided bounds use a
tan/arctan bijection, one-sided bounds use the shifted-reciprocal /
sqrt bijection, unbounded parameters pass through.

TPU-first redesign: the reference dispatches per parameter on a
*static* bounds tuple (``@partial(jax.jit, static_argnums=[1])``,
building a Python list per call and a dense ``jax.jacobian`` for the
chain rule).  Here bounds are encoded once as ``(low, high)`` arrays
with ±inf for open ends, and every transform is a single branchless
``jnp.where`` program — vectorized over parameters, scan/vmap-safe,
no recompilation when bounds change, and the chain-rule Jacobian is
computed elementwise (it is diagonal by construction — cf. SURVEY §7
"Bounded-Adam Jacobian").

The scalar parity functions :func:`transform` / :func:`inverse_transform`
(same signatures as the reference) are kept for API compatibility.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def bounds_to_arrays(param_bounds: Optional[Sequence], ndim: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Normalize the reference's bounds format — a sequence of
    ``None | (low, high)`` with ``None`` entries for open ends
    (``adam.py:148-150``) — into ``(low, high)`` arrays with ±inf."""
    low = np.full(ndim, -np.inf)
    high = np.full(ndim, np.inf)
    if param_bounds is not None:
        if hasattr(param_bounds, "tolist"):
            param_bounds = param_bounds.tolist()
        if len(param_bounds) != ndim:
            # Explicit raise (not assert): user-facing validation
            # must survive `python -O`.
            raise ValueError(
                "param_bounds must have one entry per parameter: "
                f"got {len(param_bounds)} bounds for ndim={ndim}")
        for i, b in enumerate(param_bounds):
            if b is None:
                continue
            lo, hi = b
            low[i] = -np.inf if lo is None or not np.isfinite(lo) else lo
            high[i] = np.inf if hi is None or not np.isfinite(hi) else hi
    return jnp.asarray(low), jnp.asarray(high)


def check_strictly_inside(params, low, high, param_bounds) -> None:
    """Reject a guess on or outside its bounds, at fit setup.

    A boundary point maps to ±inf through the bijections below, after
    which the fit silently pins to the bound; fail loudly instead.
    Host-side only (``params`` must be concrete).
    """
    p = np.asarray(params)
    if not (np.all(p > np.asarray(low)) and np.all(p < np.asarray(high))):
        raise ValueError(
            f"guess {p.tolist()} must lie strictly inside param_bounds "
            f"{param_bounds} (the bounds bijection maps boundary "
            "points to infinity)")


def _branch_masks(low, high):
    finite_low = jnp.isfinite(low)
    finite_high = jnp.isfinite(high)
    return (finite_low & finite_high,          # two-sided
            finite_low & ~finite_high,         # lower bound only
            ~finite_low & finite_high)         # upper bound only


def transform_array(params, low, high):
    """Map bounded params to unbounded space, elementwise.

    Branchless equivalent of the reference's scalar ``transform``
    (``adam.py:202-219``).  Inputs to inactive branches are sanitized
    before use so ``jnp.where`` gradients stay NaN-free.
    """
    params = jnp.asarray(params)
    both, lo_only, hi_only = _branch_masks(low, high)

    # two-sided: scale * tan((p - mid) / scale)
    l2 = jnp.where(both, low, 0.0)
    h2 = jnp.where(both, high, 1.0)
    p2 = jnp.where(both, params, 0.5)
    mid = 0.5 * (h2 + l2)
    scale = (h2 - l2) / jnp.pi
    t_both = scale * jnp.tan((p2 - mid) / scale)

    # one-sided low: p - low + 1/(low - p)
    lL = jnp.where(lo_only, low, 0.0)
    pL = jnp.where(lo_only, params, 1.0)
    t_low = pL - lL + 1.0 / (lL - pL)

    # one-sided high: p - high + 1/(high - p)
    hH = jnp.where(hi_only, high, 1.0)
    pH = jnp.where(hi_only, params, 0.0)
    t_high = pH - hH + 1.0 / (hH - pH)

    out = jnp.where(both, t_both,
                    jnp.where(lo_only, t_low,
                              jnp.where(hi_only, t_high, params)))
    return out


def inverse_transform_array(uparams, low, high):
    """Map unbounded params back into their bounds, elementwise.

    Branchless equivalent of the reference's scalar
    ``inverse_transform`` (``adam.py:222-239``).
    """
    uparams = jnp.asarray(uparams)
    both, lo_only, hi_only = _branch_masks(low, high)

    l2 = jnp.where(both, low, 0.0)
    h2 = jnp.where(both, high, 1.0)
    mid = 0.5 * (h2 + l2)
    scale = (h2 - l2) / jnp.pi
    p_both = mid + scale * jnp.arctan(uparams / scale)

    lL = jnp.where(lo_only, low, 0.0)
    p_low = 0.5 * (2.0 * lL + uparams + jnp.sqrt(uparams ** 2 + 4.0))

    hH = jnp.where(hi_only, high, 1.0)
    p_high = 0.5 * (2.0 * hH + uparams - jnp.sqrt(uparams ** 2 + 4.0))

    return jnp.where(both, p_both,
                     jnp.where(lo_only, p_low,
                               jnp.where(hi_only, p_high, uparams)))


def inverse_transform_diag_jacobian(uparams, low, high):
    """d(inverse_transform)/d(uparams), elementwise.

    The bijection acts independently per parameter, so its Jacobian is
    diagonal; the reference materializes it densely with
    ``jax.jacobian`` (``adam.py:174-181``) — this scales past toy ndim
    by computing only the diagonal via per-element ``jax.grad``.
    """
    grad_fn = jax.vmap(jax.grad(
        lambda u, lo, hi: inverse_transform_array(u, lo, hi)))
    u = jnp.atleast_1d(uparams)
    # Batched callers (a (n_starts, ndim) multi-start matrix) share
    # one (ndim,) bounds row; broadcast it up before flattening so
    # the elementwise vmap sees aligned axes.
    lo = jnp.broadcast_to(jnp.atleast_1d(low), u.shape)
    hi = jnp.broadcast_to(jnp.atleast_1d(high), u.shape)
    diag = grad_fn(u.ravel(), lo.ravel(), hi.ravel())
    # atleast_1d lifts 0-d inputs; hand scalar callers their shape
    # back so the chain-rule product doesn't broadcast () -> (1,).
    return diag.reshape(jnp.shape(uparams))


# --------------------------------------------------------------------- #
# Scalar parity API (signatures of /root/reference/multigrad/adam.py)
# --------------------------------------------------------------------- #
def apply_transforms(params, bounds):
    """Vectorized transform over a bounds list (parity: ``adam.py:192-194``)."""
    low, high = bounds_to_arrays(bounds, len(params))
    return transform_array(jnp.asarray(params), low, high)


def apply_inverse_transforms(uparams, bounds):
    """Vectorized inverse (parity: ``adam.py:197-199``)."""
    low, high = bounds_to_arrays(bounds, len(uparams))
    return inverse_transform_array(jnp.asarray(uparams), low, high)


@partial(jax.jit, static_argnums=[1])
def transform(param, bounds):
    """Transform one param into unbound space (parity: ``adam.py:202-219``)."""
    if bounds is None:
        return jnp.asarray(param)
    low = -np.inf if bounds[0] is None else bounds[0]
    high = np.inf if bounds[1] is None else bounds[1]
    return transform_array(param, jnp.asarray(low), jnp.asarray(high))


@partial(jax.jit, static_argnums=[1])
def inverse_transform(uparam, bounds):
    """Transform one unbound param back (parity: ``adam.py:222-239``)."""
    if bounds is None:
        return jnp.asarray(uparam)
    low = -np.inf if bounds[0] is None else bounds[0]
    high = np.inf if bounds[1] is None else bounds[1]
    return inverse_transform_array(uparam, jnp.asarray(low),
                                   jnp.asarray(high))
