from .adam import (gen_new_key, init_randkey, run_adam, run_adam_scan,
                   run_adam_unbounded)
from .bfgs import run_bfgs, run_lbfgs_scan
from .transforms import (apply_inverse_transforms, apply_transforms,
                         bounds_to_arrays, inverse_transform,
                         inverse_transform_array,
                         inverse_transform_diag_jacobian, transform,
                         transform_array)

__all__ = [
    "run_adam", "run_adam_scan", "run_adam_unbounded", "run_bfgs",
    "run_lbfgs_scan", "init_randkey", "gen_new_key",
    "transform", "inverse_transform", "apply_transforms",
    "apply_inverse_transforms", "transform_array",
    "inverse_transform_array", "inverse_transform_diag_jacobian",
    "bounds_to_arrays",
]
