"""Adam optimization, in-graph.

Port of ``/root/reference/multigrad/adam.py``.  The reference runs a
host-side Python loop on rank 0 that broadcasts ``"compute"`` commands
and parameters to worker ranks every step (``adam.py:39-49,102-130``).
Under SPMD none of that machinery exists: the fast path
(:func:`run_adam_scan`) compiles the whole optimization — optax Adam
update included — into a single ``lax.scan``, so ``nsteps`` of
training execute as one XLA call with zero host round-trips.

The reference's generic entry points (:func:`run_adam`,
:func:`run_adam_unbounded`) are kept with the same signatures for
arbitrary (possibly non-jittable) ``loss_and_grad_fn`` callables —
e.g. an :class:`~multigrad_tpu.core.group.OnePointGroup` whose models
live on disjoint sub-meshes.

Optax replaces ``jax.example_libraries.optimizers`` — the migration
the reference itself recommends (``adam.py:54``).  Default
hyper-parameters (b1=0.9, b2=0.999, eps=1e-8) are identical.

PRNG semantics: one consistent per-step ``randkey, key_i =
jax.random.split(randkey)`` chain, matching the reference's rank-0
scheme (``adam.py:60-62``).  (The reference's workers used a
*different* split — ``split(key, 1)[0]`` — an asymmetry SURVEY §2.1/C6
flags as a bug; SPMD has a single key stream, so it cannot recur.)
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from .transforms import (bounds_to_arrays, inverse_transform_array,
                         inverse_transform_diag_jacobian, transform_array)
from ..utils.util import cached_program, tqdm, trange


def adam_trange(n):
    return trange(n, desc="Adam Gradient Descent Progress")


def init_randkey(randkey):
    """Check that randkey is a PRNG key or create one from an int
    (parity: ``adam.py:242-251``)."""
    if isinstance(randkey, (int, np.integer)):
        randkey = jax.random.key(int(randkey))
    else:
        msg = f"Invalid {type(randkey)=}: Must be int or PRNG Key"
        assert hasattr(randkey, "dtype"), msg
        assert jnp.issubdtype(randkey.dtype, jax.dtypes.prng_key), msg
    return randkey


@jax.jit
def gen_new_key(randkey):
    """Split a PRNG key to generate a new one (parity: ``adam.py:254-257``)."""
    return jax.random.split(randkey, 1)[0]


def _wrap_bounded(loss_and_grad, low, high):
    """Loss-and-grad in unbounded space with the diagonal chain rule.

    Equivalent of the reference's ``unbound_loss_and_grad``
    (``adam.py:176-181``) with the dense ``jax.jacobian`` replaced by
    the elementwise diagonal (the bijection is separable).
    """
    def unbound_loss_and_grad(uparams, *args, **kwargs):
        params = inverse_transform_array(uparams, low, high)
        loss, dloss_dparams = loss_and_grad(params, *args, **kwargs)
        diag = inverse_transform_diag_jacobian(uparams, low, high)
        return loss, dloss_dparams * diag
    return unbound_loss_and_grad


def _adam_scan_program(fn, nsteps, learning_rate, with_key, const_randkey,
                       bounded):
    """Whole-optimization jitted scan, cached per callable
    (:func:`~multigrad_tpu.utils.util.cached_program`) so repeat fits
    reuse the executable without pinning ``fn`` — and whatever it
    closes over — in jit's global cache.  ``fn_args`` (e.g. a model's
    aux-data leaves) are runtime arguments, so data swaps never hit
    stale trace-time constants."""
    def build():
        tx = optax.adam(learning_rate)

        @jax.jit
        def program(u0, key0, low, high, fn_args):
            def base(u, key):
                return fn(u, key, *fn_args)

            wrapped = _wrap_bounded(base, low, high) if bounded else base

            def step(carry, _):
                u, opt_state, key = carry
                if with_key and not const_randkey:
                    key, key_i = jax.random.split(key)
                else:
                    key_i = key
                _, grad = wrapped(u, key_i)
                updates, opt_state = tx.update(grad, opt_state, u)
                u = optax.apply_updates(u, updates)
                return (u, opt_state, key), u

            opt_state = tx.init(u0)
            (_, _, _), us = lax.scan(step, (u0, opt_state, key0),
                                     None, length=nsteps)
            return jnp.concatenate([u0[None], us], axis=0)
        return program

    key = ("adam_scan", nsteps, learning_rate, with_key, const_randkey,
           bounded)
    return cached_program(fn, key, build)


def run_adam_scan(loss_and_grad: Callable, params, nsteps: int = 100,
                  param_bounds=None, learning_rate: float = 0.01,
                  randkey=None, const_randkey: bool = False,
                  progress: bool = False, fn_args=()):
    """Whole-optimization ``lax.scan``: the TPU-native Adam fast path.

    Parameters
    ----------
    loss_and_grad : callable
        Jittable ``(params, key, *fn_args) -> (loss, grad)``.  ``key``
        is a PRNG key (ignored by the callee when keys are unused).
        Pass a *stable* function object (not a fresh closure per
        call): the compiled executable is cached on its identity.
    params : array-like
        Initial parameters.
    param_bounds : sequence of None | (low, high), optional
        Same format as the reference (``adam.py:148-150``); the loop
        runs in unbounded space through the bijection.
    randkey : int | PRNG key, optional
        Per-step subkeys are split off inside the scan; with
        ``const_randkey`` the initial key is used at every step
        (parity: ``multigrad.py:291-300``).

    Returns
    -------
    jnp.ndarray, shape ``(nsteps + 1, ndim)``
        Full parameter trajectory including the starting point — the
        same contract as the reference (``adam.py:58-68``).
    """
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    ndim = params.shape[0]
    low, high = bounds_to_arrays(param_bounds, ndim)
    bounded = param_bounds is not None

    u0 = transform_array(params, low, high) if bounded else params

    with_key = randkey is not None
    key0 = init_randkey(randkey) if with_key else jax.random.key(0)

    program = _adam_scan_program(
        loss_and_grad, nsteps, float(learning_rate), with_key,
        const_randkey, bounded)
    traj_u = program(u0, key0, low, high, tuple(fn_args))
    if progress and tqdm is not None and jax.process_index() == 0:
        # The scan is a single device-side call; report completion only.
        with tqdm.tqdm(total=nsteps,
                       desc="Adam Gradient Descent Progress") as bar:
            traj_u.block_until_ready()
            bar.update(nsteps)
    if bounded:
        return inverse_transform_array(traj_u, low, high)
    return traj_u


def run_adam_unbounded(logloss_and_grad_fn, params, data, nsteps=100,
                       learning_rate=0.01, randkey=None, progress=True):
    """Host-loop Adam for arbitrary callables (parity: ``adam.py:71-130``).

    Signature contract matches the reference:
    ``logloss_and_grad_fn(params, data[, randkey=...]) -> (loss, grad)``.
    Runs on every host identically (no root/worker protocol) and
    returns the full parameter trajectory, shape ``(nsteps+1, ndim)``.
    """
    kwargs = {}
    if randkey is not None:
        randkey = init_randkey(randkey)

    params = jnp.asarray(params, dtype=jnp.result_type(float))
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)
    update = jax.jit(tx.update)
    apply_updates = jax.jit(optax.apply_updates)

    param_steps = [params]
    steps = (adam_trange(nsteps) if progress and jax.process_index() == 0
             else range(nsteps))
    for _step in steps:
        if randkey is not None:
            randkey, key_i = jax.random.split(randkey)
            kwargs["randkey"] = key_i
        _, grad = logloss_and_grad_fn(params, data, **kwargs)
        updates, opt_state = update(grad, opt_state, params)
        params = apply_updates(params, updates)
        param_steps.append(params)

    return jnp.array(param_steps)


def run_adam(logloss_and_grad_fn, params, data, nsteps=100, param_bounds=None,
             learning_rate=0.01, randkey=None, progress=True):
    """Generic Adam entry point (parity: ``adam.py:133-189``).

    Dispatches to :func:`run_adam_unbounded` directly or through the
    bounds bijection.  Unlike the reference — where only rank 0
    returned the trajectory and everyone else got ``None``
    (``adam.py:128-130``) — every caller receives the full trajectory.
    """
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    if param_bounds is None:
        return run_adam_unbounded(
            logloss_and_grad_fn, params, data, nsteps=nsteps,
            learning_rate=learning_rate, randkey=randkey, progress=progress)

    assert len(params) == len(param_bounds)
    low, high = bounds_to_arrays(param_bounds, len(params))
    unbound_fn = _wrap_bounded(logloss_and_grad_fn, low, high)
    uparams = transform_array(params, low, high)
    traj_u = run_adam_unbounded(
        unbound_fn, uparams, data, nsteps=nsteps,
        learning_rate=learning_rate, randkey=randkey, progress=progress)
    return inverse_transform_array(traj_u, low, high)
