"""Adam optimization, in-graph.

Port of ``/root/reference/multigrad/adam.py``.  The reference runs a
host-side Python loop on rank 0 that broadcasts ``"compute"`` commands
and parameters to worker ranks every step (``adam.py:39-49,102-130``).
Under SPMD none of that machinery exists: the fast path
(:func:`run_adam_scan`) compiles the whole optimization — optax Adam
update included — into a single ``lax.scan``, so ``nsteps`` of
training execute as one XLA call with zero host round-trips.

The reference's generic entry points (:func:`run_adam`,
:func:`run_adam_unbounded`) are kept with the same signatures for
arbitrary (possibly non-jittable) ``loss_and_grad_fn`` callables —
e.g. an :class:`~multigrad_tpu.core.group.OnePointGroup` whose models
live on disjoint sub-meshes.

Optax replaces ``jax.example_libraries.optimizers`` — the migration
the reference itself recommends (``adam.py:54``).  Default
hyper-parameters (b1=0.9, b2=0.999, eps=1e-8) are identical.

PRNG semantics: one consistent per-step ``randkey, key_i =
jax.random.split(randkey)`` chain, matching the reference's rank-0
scheme (``adam.py:60-62``).  (The reference's workers used a
*different* split — ``split(key, 1)[0]`` — an asymmetry SURVEY §2.1/C6
flags as a bug; SPMD has a single key stream, so it cannot recur.)
"""
from __future__ import annotations

import contextlib
import os
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from .transforms import (bounds_to_arrays, check_strictly_inside,
                         inverse_transform_array,
                         inverse_transform_diag_jacobian, transform_array)
from ..utils.util import cached_program, evict_cached_programs, tqdm, trange


def adam_trange(n):
    return trange(n, desc="Adam Gradient Descent Progress")


def init_randkey(randkey):
    """Check that randkey is a PRNG key or create one from an int
    (parity: ``adam.py:242-251``)."""
    if isinstance(randkey, (int, np.integer)):
        randkey = jax.random.key(int(randkey))
    elif not (hasattr(randkey, "dtype")
              and jnp.issubdtype(randkey.dtype, jax.dtypes.prng_key)):
        # Explicit raise (not assert): argument validation must
        # survive `python -O`.
        raise TypeError(
            f"Invalid {type(randkey)=}: Must be int or PRNG Key")
    return randkey


@jax.jit
def gen_new_key(randkey):
    """Split a PRNG key to generate a new one (parity: ``adam.py:254-257``)."""
    return jax.random.split(randkey, 1)[0]


def resolve_donate(donate_carry) -> bool:
    """Resolve the ``donate_carry`` knob: None = auto (donate on
    TPU/GPU, where XLA aliases the optimizer carry's input and output
    buffers and the per-segment HBM high-water mark drops by one full
    ``(params, mu, nu)`` copy; off on CPU, where donation is a no-op
    that only emits "donated buffer not usable" warnings)."""
    if donate_carry is None:
        return jax.default_backend() in ("tpu", "gpu")
    return bool(donate_carry)


def _carry_copy(u, key):
    """Defensive copies of caller-owned carry leaves before donation.

    Donating an argument invalidates ITS buffer; ``u``/``key`` may be
    (views of) arrays the caller still holds — e.g. an unbounded fit
    passes ``params`` straight through, and ``init_randkey`` returns a
    caller-supplied PRNG key as-is.  Copying is O(ndim) — nothing next
    to one optimizer step — and makes donation invisible to callers.
    """
    u = jnp.array(u, copy=True)
    try:
        key = jax.random.clone(key)
    except AttributeError:    # older jax: no clone; copy the words
        key = jax.random.wrap_key_data(
            jnp.array(jax.random.key_data(key), copy=True),
            impl=jax.random.key_impl(key))
    return u, key


def _wrap_bounded(loss_and_grad, low, high, with_diag=False):
    """Loss-and-grad in unbounded space with the diagonal chain rule.

    Equivalent of the reference's ``unbound_loss_and_grad``
    (``adam.py:176-181``) with the dense ``jax.jacobian`` replaced by
    the elementwise diagonal (the bijection is separable).  With
    ``with_diag`` the callee returns a third diagnostics dict (the
    gradient-noise-scale convention, see ``fn_diag`` on
    :func:`_adam_segment_program`) that rides through untransformed —
    its entries are scalar summaries, not parameter-space vectors.
    """
    def unbound_loss_and_grad(uparams, *args, **kwargs):
        params = inverse_transform_array(uparams, low, high)
        out = loss_and_grad(params, *args, **kwargs)
        if with_diag:
            loss, dloss_dparams, fdiag = out
        else:
            loss, dloss_dparams = out
        diag = inverse_transform_diag_jacobian(uparams, low, high)
        if with_diag:
            return loss, dloss_dparams * diag, fdiag
        return loss, dloss_dparams * diag
    return unbound_loss_and_grad


# Decay of the in-graph loss-EMA plateau diagnostic (half-life ~34
# steps): long enough that per-step optimizer noise averages out,
# short enough that a genuine plateau shows within ~2 tap windows.
PLATEAU_EMA_DECAY = 0.98


def _adam_segment_program(fn, seg_len, learning_rate, with_key,
                          const_randkey, bounded, tap=None,
                          donate=False, sentinel=None,
                          ema_decay=None, fn_diag=False,
                          carry_sharding=None):
    """Jitted Adam scan over ``seg_len`` steps: advances
    ``(u, opt_state, key)`` and returns the segment's parameter
    trajectory.  The single building block for both the whole-fit
    scan (one segment of ``nsteps``) and the checkpointed drive
    (optimizer state crosses the program boundary so fits survive
    preemption).  Cached per callable
    (:func:`~multigrad_tpu.utils.util.cached_program`) so repeat fits
    reuse the executable without pinning ``fn`` — and whatever it
    closes over — in jit's global cache; ``fn_args`` (e.g. a model's
    aux-data leaves) are runtime arguments, so data swaps never hit
    stale trace-time constants.

    ``tap`` (a :class:`~multigrad_tpu.telemetry.ScalarTap`) emits
    loss / |grad| / |params| / |update| from *inside* the scan every
    ``tap.log_every`` steps via a ``lax.cond``-gated debug callback.
    The tap joins the cache key (its ``log_every`` is static in the
    trace), so a given tap builds once and every segment — and every
    repeat fit through it — reuses the executable: enabling taps adds
    ZERO retraces.  ``step0`` (the segment's global start step, a
    traced scalar so resumed/segmented fits number steps globally)
    exists only in instrumented (tapped/watched) programs; plain
    programs keep the historical 6-argument signature.

    ``sentinel`` (a :class:`~multigrad_tpu.telemetry.flight
    .NonFiniteSentinel`) arms the flight recorder's in-graph
    non-finite watch: a ``lax.cond``-gated callback fires the first
    time loss or |grad| goes NaN/Inf inside the scan.  Like the tap
    it is static — it joins the cache key and hashes by recorder
    identity, so arming it costs one build and zero retraces across
    repeat fits with the same recorder.

    With ``donate`` the Adam carry ``(u, opt_state, key)`` — argument
    positions 0–2 — is donated to XLA: on TPU/GPU the output carry
    aliases the input buffers, so a segment holds ONE ``(params, mu,
    nu)`` set in HBM instead of two (for the ``(K, ndim)`` ensemble
    scan that is the difference between K and 2K resident moment
    sets).  ``donate`` joins the cache key, so toggling it can never
    silently retrace an in-flight fit's program, and every driver
    below rebinds the carry from the program's outputs — the donated
    buffers are never read again (callers' arrays are defensively
    copied at the entry points, see :func:`_carry_copy`).

    ``carry_sharding`` (a :class:`~jax.sharding.NamedSharding`, or
    None) is the **partitioned-carry variant** — ZeRO for the
    ensemble axis: the whole Adam carry ``(u, m, v)`` of a
    ``(K, ndim)`` batched fit is constrained to the sharding (the
    K axis partitioned over a 2-level mesh's replica axis, see
    :func:`~multigrad_tpu.parallel.ensemble_comm`), so each device
    holds ``K/R`` rows of params, BOTH Adam moment sets and the
    trajectory instead of all K — total optimizer state per device
    drops ÷R, which is what lets K exceed one device's memory.
    Adam's update is elementwise along K, so partitioning is
    numerically invisible; the constraint (not just propagation
    from the input) makes the layout a guarantee rather than a
    GSPMD heuristic.  It is hashable and joins the cache key, so
    sharded and replicated fits of the same config are sibling
    executables — toggling never retraces an existing program.

    ``ema_decay`` (a float; active only alongside a tap) compiles the
    **loss-EMA plateau diagnostic** into the scan: a bias-corrected
    exponential moving average of the loss rides in the carry and
    every tap record gains ``loss_ema`` plus ``loss_ema_slope`` — the
    per-step EMA change since the previous emit, ~0 when the fit has
    plateaued (the alert rules and the dashboard read it).  The EMA
    restarts at each segment boundary (the carry is per-program),
    which only shortens its warm-up; segments are ≥ 100 steps on
    every driver.  ``fn_diag`` declares that ``fn`` returns ``(loss,
    grad, diagnostics_dict)`` — the gradient-noise-scale convention
    of the model entry points — and the dict's scalars merge into
    each tap record.  Both are static and join the cache key, so
    like the tap itself they cost one build and zero retraces.
    """
    instrumented = tap is not None or sentinel is not None
    ema = ema_decay is not None and tap is not None

    def build():
        tx = optax.adam(learning_rate)

        @partial(jax.jit, donate_argnums=(0, 1, 2) if donate else ())
        def program(u, opt_state, key, low, high, fn_args, step0=0):
            if carry_sharding is not None:
                # Pin the WHOLE carry — params and both Adam moment
                # sets — to the K-sharded layout.  The moments are
                # the leaves shaped like u (optax's count scalar and
                # empty states pass through untouched).
                u = lax.with_sharding_constraint(u, carry_sharding)
                opt_state = jax.tree_util.tree_map(
                    lambda s: lax.with_sharding_constraint(
                        s, carry_sharding)
                    if getattr(s, "shape", None) == u.shape else s,
                    opt_state)

            def base(u_, key_):
                return fn(u_, key_, *fn_args)

            wrapped = _wrap_bounded(base, low, high,
                                    with_diag=fn_diag) \
                if bounded else base

            def step(carry, i):
                u_, opt_state_, key_ = carry[:3]
                idx = 3
                if sentinel is not None:
                    fired = carry[idx]
                    idx += 1
                if ema:
                    ema_m, ema_prev = carry[idx], carry[idx + 1]
                if with_key and not const_randkey:
                    key_, key_i = jax.random.split(key_)
                else:
                    key_i = key_
                if fn_diag:
                    loss, grad, fdiag = wrapped(u_, key_i)
                else:
                    loss, grad = wrapped(u_, key_i)
                    fdiag = {}
                updates, opt_state_ = tx.update(grad, opt_state_, u_)
                u_new = optax.apply_updates(u_, updates)
                new_carry = (u_new, opt_state_, key_)
                if instrumented:
                    from ..telemetry.taps import batch_norm
                    grad_norm = batch_norm(grad)
                    if tap is not None:
                        scalars = dict(
                            loss=loss, grad_norm=grad_norm,
                            param_norm=batch_norm(u_new),
                            update_norm=batch_norm(updates))
                        scalars.update(fdiag)
                        if ema:
                            ema_m = ema_decay * ema_m \
                                + (1.0 - ema_decay) * loss
                            corrected = ema_m / (1.0 - jnp.power(
                                jnp.asarray(ema_decay, ema_m.dtype),
                                i + 1))
                            # Slope per STEP since the last emitted
                            # EMA; the first emit (prev still inf)
                            # reports 0, not a NaN every strict JSON
                            # consumer downstream would choke on.
                            have_prev = jnp.all(jnp.isfinite(ema_prev))
                            slope = jnp.where(
                                have_prev,
                                (corrected - ema_prev) / tap.log_every,
                                jnp.zeros_like(corrected))
                            scalars["loss_ema"] = corrected
                            scalars["loss_ema_slope"] = slope
                            emit_now = \
                                ((step0 + i) % tap.log_every) == 0
                            ema_prev = jnp.where(emit_now, corrected,
                                                 ema_prev)
                        tap.maybe_emit(step0 + i, scalars)
                    if sentinel is not None:
                        # Latched: once NaN, every later step is NaN
                        # too — fire the host callback exactly once.
                        bad = sentinel.watch(
                            step0 + i,
                            dict(loss=loss, grad_norm=grad_norm),
                            gate=~fired)
                        new_carry = new_carry + (fired | bad,)
                if ema:
                    new_carry = new_carry + (ema_m, ema_prev)
                return new_carry, u_new

            xs = jnp.arange(seg_len) if instrumented else None
            carry0 = (u, opt_state, key)
            if sentinel is not None:
                carry0 = carry0 + (jnp.zeros((), bool),)
            if ema:
                # Loss shape == the params' leading (batch) shape:
                # scalar for a 1-D fit, (K,) for an ensemble scan.
                shape = u.shape[:-1]
                carry0 = carry0 + (jnp.zeros(shape, u.dtype),
                                   jnp.full(shape, jnp.inf, u.dtype))
            out_carry, us = lax.scan(
                step, carry0, xs,
                length=None if instrumented else seg_len)
            u, opt_state, key = out_carry[:3]
            return u, opt_state, key, us
        return program

    key = ("adam_segment", seg_len, learning_rate, with_key,
           const_randkey, bounded, donate)
    if carry_sharding is not None:
        # Appended (not a base slot) so replicated fits keep the
        # historical 7-element key layout; NamedSharding is hashable,
        # so sharded configs are ordinary sibling cache entries.
        key = key + (("carry", carry_sharding),)
    if not instrumented and not fn_diag:
        return cached_program(fn, key, build)
    base = key
    key = key + tuple(x for x in (tap, sentinel) if x is not None)
    if ema or fn_diag:
        key = key + (("diag", ema_decay if ema else None, fn_diag),)
    program = cached_program(fn, key, build)
    # Keep at most ONE instrumented variant per base config: a
    # tap/sentinel key embeds its logger/recorder, so fits that each
    # construct a fresh one would otherwise pin one more compiled
    # program (and the closed logger behind it) per fit, forever.
    # Reusing the same logger+recorder across fits still hits the
    # cache (zero retraces); swapping them recompiles once and frees
    # the predecessor.
    evict_cached_programs(
        fn, lambda k: len(k) > len(base) and k[:len(base)] == base,
        keep=key)
    return program


def adam_fit_program(loss_and_grad: Callable, nsteps: int,
                     learning_rate: float = 0.01,
                     with_key: bool = False,
                     const_randkey: bool = False,
                     bounded: bool = False, tap=None,
                     donate_carry=None, sentinel=None,
                     ema_decay=None, fn_diag: bool = False,
                     carry_sharding=None):
    """Program-access hook: the whole-fit Adam scan, uncalled.

    Returns the SAME jitted segment program every ``run_adam`` entry
    point executes — ``(u, opt_state, key, low, high, fn_args[,
    step0]) -> (u, opt_state, key, trajectory)`` (``step0`` only in
    tapped programs) — without running a step.  The static
    shard-safety analyzer traces it to verify the REAL training loop
    (optimizer update, bounds bijection and telemetry tap included)
    rather than a reconstruction of it; see
    :func:`multigrad_tpu.analysis.analyze_fit`.  Programs come from
    the same per-callable cache as live fits, so analysis never
    causes a recompile — ``donate_carry`` defaults to the same
    backend-auto resolution live fits use (:func:`resolve_donate`)
    for exactly that reason.
    """
    return _adam_segment_program(
        loss_and_grad, int(nsteps), float(learning_rate),
        bool(with_key), bool(const_randkey), bool(bounded), tap=tap,
        donate=resolve_donate(donate_carry), sentinel=sentinel,
        ema_decay=ema_decay, fn_diag=bool(fn_diag),
        carry_sharding=carry_sharding)


# Smallest slice the live-progress drive will cut a fit into.  The
# floor keeps the bar from ever degrading the execution shape: a
# short fit (nsteps <= the floor) runs as ONE program exactly like
# progress=False, and a long fit pays at most nsteps/floor dispatch
# fences — noise next to its compute.  Without it, nsteps < 40 with
# the default progress=True would degenerate to per-step dispatch,
# the host-loop pattern the scan fast path exists to beat.
_PROGRESS_MIN_SEG = 100


def _drive_segments(loss_and_grad, u, opt_state, key, low, high,
                    fn_args, nsteps, seg_size, learning_rate,
                    with_key, const_randkey, bounded, progress,
                    on_segment, start=0, tap=None, donate=False,
                    sentinel=None, ema_decay=None, fn_diag=False,
                    carry_sharding=None):
    """Advance an Adam fit from ``start`` to ``nsteps`` in slices of
    ``seg_size`` through the cached segment-program family, with a
    live progress bar on process 0.

    The single driver behind both the checkpointed drive (per-segment
    restart-state save) and the plain live-progress path (per-segment
    trajectory collection) — ``on_segment(start_step, us, u,
    opt_state, key)`` is the only difference between them.  Each
    segment is fenced before the callback/bar so progress reflects
    work that actually landed.  The bar is display-only: every
    process drives the same segment schedule, so multi-host
    collective schedules cannot diverge (reference UX: adam.py:32-36).
    """
    bar = (tqdm.tqdm(total=nsteps, initial=start,
                     desc="Adam Gradient Descent Progress")
           if progress and tqdm is not None
           and jax.process_index() == 0 else None)
    step = start
    instrumented = tap is not None or sentinel is not None
    try:
        while step < nsteps:
            n = min(seg_size, nsteps - step)
            program = _adam_segment_program(
                loss_and_grad, n, learning_rate, with_key,
                const_randkey, bounded, tap=tap, donate=donate,
                sentinel=sentinel, ema_decay=ema_decay,
                fn_diag=fn_diag, carry_sharding=carry_sharding)
            # step0 rides along only for instrumented programs
            # (global step numbering across segments/resumes); it is
            # a traced scalar, so varying it never retraces.
            extra = (jnp.asarray(step, jnp.int32),) \
                if instrumented else ()
            u, opt_state, key, us = program(u, opt_state, key, low,
                                            high, tuple(fn_args),
                                            *extra)
            us.block_until_ready()
            if sentinel is not None:
                # The segment is fenced, so any in-graph non-finite
                # watch has fired by now; a fatal trip stops the
                # drive at the failing segment BEFORE on_segment
                # runs — the checkpointed drive must not overwrite
                # the last good restart state (the one the
                # postmortem bundle points at) with NaN-poisoned
                # carry, and later segments would only iterate NaNs.
                jax.effects_barrier()
                if sentinel.recorder.fatal:
                    break
            on_segment(step, us, u, opt_state, key)
            step += n
            if bar is not None:
                bar.update(n)
    finally:
        if bar is not None:
            bar.close()
    return u, opt_state, key


@jax.jit
def _digest_leaf(x):
    """Two exact modular checksums over ALL of a leaf's elements.

    Element bit-patterns (floats bitcast, ints value-cast) are reduced
    as uint32 wraparound sums — plain and position-weighted (Knuth
    multiplicative hash weights).  Integer arithmetic makes the digest
    exact at any array size: any single-element edit shifts both sums,
    and a permutation shifts the weighted one (a float reduction would
    drown a one-element edit below its rounding noise at 1e9
    elements).  One fused device pass; the iota never materializes.
    """
    flat = jnp.ravel(x)
    itemsize = flat.dtype.itemsize
    if itemsize % 4 == 0:
        # 32-bit dtypes bitcast directly; 64/128-bit ones to uint32
        # word groups (a trailing dim) — never a value-narrowing cast,
        # which would alias sub-float32 edits (e.g. a 1e-12 nudge
        # under x64) to the same digest.
        bits = jnp.ravel(lax.bitcast_convert_type(flat, jnp.uint32))
    elif itemsize == 2:
        bits = lax.bitcast_convert_type(flat, jnp.uint16
                                        ).astype(jnp.uint32)
    else:
        # 1-byte dtypes (incl. bool): value cast is already injective.
        bits = flat.astype(jnp.uint32)
    idx = lax.iota(jnp.uint32, bits.shape[0])
    weights = idx * jnp.uint32(2654435761) + jnp.uint32(1)
    return jnp.stack([jnp.sum(bits, dtype=jnp.uint32),
                      jnp.sum(bits * weights, dtype=jnp.uint32)])


# Version of the resume data-guard's fingerprint scheme.  v1 was a
# 16-sample strided CRC (shape-(1,) config_args, no version word);
# v2 is the full-array on-device digest above.  Bump whenever
# _args_fingerprint's output changes meaning for identical data.
_DATA_GUARD_VERSION = 2


def _args_fingerprint(fn_args):
    """Fingerprint of the training data for the resume guard.

    Per-leaf shape/dtype plus :func:`_digest_leaf`'s full-array
    checksums, computed on device — only two scalars per leaf ever
    cross to the host, so the cost at 1e9 elements is one HBM sweep.
    (The previous 16-sample CRC let e.g. a 17th-element edit resume
    silently against a stale trajectory prefix.)  Leaves that cannot
    be digested contribute shape/dtype only.
    """
    import zlib

    sig = []
    for leaf in jax.tree_util.tree_leaves(fn_args):
        entry = [str(getattr(leaf, "shape", ())),
                 str(getattr(leaf, "dtype", type(leaf).__name__))]
        try:
            entry.append(np.asarray(
                _digest_leaf(jnp.asarray(leaf))).tobytes().hex())
        except (TypeError, ValueError):
            # Leaf is not convertible to a jax array (an exotic
            # static object riding in fn_args): its shape/dtype entry
            # above still guards it structurally.  Anything else —
            # device OOM, internal jax errors — must propagate, not
            # silently weaken the resume guard.
            pass
        sig.append(tuple(entry))
    return np.uint32(zlib.crc32(repr(sig).encode()))


def _run_adam_checkpointed(loss_and_grad, u0, key0, low, high, fn_args,
                           nsteps, learning_rate, with_key,
                           const_randkey, bounded, checkpoint_dir,
                           checkpoint_every, progress=False, tap=None,
                           donate=False, sentinel=None,
                           ema_decay=None, fn_diag=False):
    """Segmented Adam drive with preemption-safe resume.

    The fit advances in segments of ``checkpoint_every`` steps; after
    each segment the full restart state — step counter, unbounded
    params, optimizer state, PRNG key, and the trajectory so far — is
    atomically written to ``checkpoint_dir/adam_state.npz``
    (:func:`multigrad_tpu.utils.checkpoint.save`).  A re-invocation
    with the same arguments resumes from the last completed segment;
    a finished fit is a pure checkpoint read.
    """
    from ..utils import checkpoint as _ckpt

    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, "adam_state")
    tx = optax.adam(learning_rate)
    # The fit configuration rides inside the checkpoint; resuming
    # with different arguments must fail loudly, not silently return
    # or continue a stale fit.
    # float64 on the host (not jnp, which would silently downcast to
    # float32 without x64): guesses/bounds/lr differing below float32
    # resolution must not alias to "same config".
    config = np.concatenate([
        np.asarray(u0, np.float64),
        np.asarray(low, np.float64), np.asarray(high, np.float64),
        np.asarray([learning_rate, float(with_key),
                    float(const_randkey)], np.float64),
    ])
    # Key data stays uint32: a float32 cast would alias keys whose
    # words differ below the 24-bit mantissa (e.g. split() siblings).
    config_key = jnp.asarray(jax.random.key_data(key0).ravel())
    # Fingerprint the training data too: resuming mid-fit against a
    # silently-changed dataset would keep a stale trajectory prefix.
    # The guard scheme version rides alongside, so a checkpoint
    # written under an older fingerprint format is reported as such
    # instead of as a phantom "your data changed".
    config_args = jnp.asarray(
        [_DATA_GUARD_VERSION, _args_fingerprint(fn_args)], jnp.uint32)
    if jax.process_count() > 1:
        # Per-host data shards give each process a different local
        # fingerprint; agree on process 0's so the saved guard and
        # every process's comparison use the same value (otherwise a
        # valid resume would be rejected on processes 1..N-1 while
        # process 0 blocks in the state broadcast below).
        from jax.experimental import multihost_utils
        config_args = jnp.asarray(
            multihost_utils.broadcast_one_to_all(config_args))
    state = {
        "step": jnp.zeros((), jnp.int32),
        "u": u0,
        "opt_state": tx.init(u0),
        "key": key0,
        "traj": jnp.zeros((nsteps + 1, u0.shape[0]),
                          u0.dtype).at[0].set(u0),
        "config": config,
        "config_key": config_key,
        "config_args": config_args,
    }
    if os.path.exists(path + ".npz"):
        try:
            saved = _ckpt.load(path, state)
        except ValueError as e:
            # checkpoint.load's messages are specific (format-version
            # mismatch vs leaf-count mismatch each carry their own
            # remedy); keep them in the primary message instead of
            # burying them in the chained traceback.
            raise ValueError(
                "cannot resume from checkpoint in {!r}: {} "
                "(use a fresh checkpoint_dir to start over)".format(
                    checkpoint_dir, e)
            ) from e
        if saved["traj"].shape[0] != nsteps + 1:
            raise ValueError(
                "checkpoint in {!r} was written for a different "
                "nsteps; use a fresh checkpoint_dir".format(
                    checkpoint_dir))
        if not (np.array_equal(np.asarray(saved["config"]),
                               np.asarray(config))
                and np.array_equal(np.asarray(saved["config_key"]),
                                   np.asarray(config_key))):
            raise ValueError(
                "checkpoint in {!r} was written for a different fit "
                "configuration (guess/bounds/learning_rate/randkey); "
                "use a fresh checkpoint_dir".format(checkpoint_dir))
        saved_args = np.asarray(saved["config_args"])
        if not np.array_equal(saved_args, np.asarray(config_args)):
            if (saved_args.shape != np.shape(config_args)
                    or saved_args[0] != _DATA_GUARD_VERSION):
                # Scheme mismatch, not a data mismatch: the checkpoint
                # predates the current fingerprint format, so its
                # digest says nothing about whether the data changed.
                raise ValueError(
                    "checkpoint in {!r} was written by a library "
                    "version with an older data-guard format; its "
                    "data fingerprint cannot be validated — use a "
                    "fresh checkpoint_dir (or re-save by finishing "
                    "the fit under the old version)".format(
                        checkpoint_dir))
            raise ValueError(
                "checkpoint in {!r} was written for different "
                "training data (aux-data fingerprint mismatch); use "
                "a fresh checkpoint_dir".format(checkpoint_dir))
        state = saved
    if jax.process_count() > 1:
        # Multi-host: every process must resume from the same step or
        # their collective schedules diverge (host-local disks may not
        # all hold the checkpoint).  Adopt process 0's state.
        # ``broadcast_one_to_all`` applies ``np.zeros_like`` to every
        # leaf, which raises on typed PRNG keys — so the key travels
        # as raw uint32 words and is re-wrapped after (the same
        # convention utils/checkpoint.save uses on disk).
        from jax.experimental import multihost_utils
        key_impl = jax.random.key_impl(state["key"])
        plain = {k: v for k, v in state.items()
                 if k not in ("key", "config", "config_key",
                              "config_args")}
        plain["key_data"] = jax.random.key_data(state["key"])
        plain = multihost_utils.broadcast_one_to_all(plain)
        key = jax.random.wrap_key_data(jnp.asarray(plain.pop("key_data")),
                                       impl=key_impl)
        # config* leaves are recomputed identically on every process
        # from the call arguments; broadcasting them would round-trip
        # the float64 guard through the device (and downcast it).
        state = dict(plain, key=key, config=config,
                     config_key=config_key, config_args=config_args)

    step = int(state["step"])
    traj_box = [jnp.asarray(state["traj"])]

    def checkpoint_segment(start_step, us, u, opt_state, key):
        from ..telemetry.spans import span

        traj = lax.dynamic_update_slice_in_dim(
            traj_box[0], us, start_step + 1, axis=0)
        traj_box[0] = traj
        done = start_step + us.shape[0]
        if jax.process_index() == 0:
            with span(tap.logger if tap is not None else None,
                      "checkpoint", step=int(done)):
                _ckpt.save(path, {
                    "step": jnp.asarray(done, jnp.int32), "u": u,
                    "opt_state": opt_state, "key": key, "traj": traj,
                    "config": config, "config_key": config_key,
                    "config_args": config_args})

    _drive_segments(loss_and_grad, state["u"], state["opt_state"],
                    state["key"], low, high, fn_args, nsteps,
                    checkpoint_every, learning_rate, with_key,
                    const_randkey, bounded, progress,
                    checkpoint_segment, start=step, tap=tap,
                    donate=donate, sentinel=sentinel,
                    ema_decay=ema_decay, fn_diag=fn_diag)
    return traj_box[0]


def run_adam_scan(loss_and_grad: Callable, params, nsteps: int = 100,
                  param_bounds=None, learning_rate: float = 0.01,
                  randkey=None, const_randkey: bool = False,
                  progress: bool = False, fn_args=(),
                  checkpoint_dir: Optional[str] = None,
                  checkpoint_every: Optional[int] = None,
                  telemetry=None, log_every: int = 0,
                  donate_carry: Optional[bool] = None,
                  flight=None, live=None, alerts=None,
                  diagnostics: bool = False, fn_diag: bool = False,
                  carry_sharding=None):
    """Whole-optimization ``lax.scan``: the TPU-native Adam fast path.

    Parameters
    ----------
    loss_and_grad : callable
        Jittable ``(params, key, *fn_args) -> (loss, grad)``.  ``key``
        is a PRNG key (ignored by the callee when keys are unused).
        Pass a *stable* function object (not a fresh closure per
        call): the compiled executable is cached on its identity.
    params : array-like
        Initial parameters.  May carry leading batch dimensions
        (e.g. a ``(n_starts, ndim)`` multi-start matrix — Adam's
        update is elementwise, so the batch advances as independent
        fits); bounds apply along the LAST axis.  Checkpointing
        requires 1-D params.
    param_bounds : sequence of None | (low, high), optional
        Same format as the reference (``adam.py:148-150``); the loop
        runs in unbounded space through the bijection.
    randkey : int | PRNG key, optional
        Per-step subkeys are split off inside the scan; with
        ``const_randkey`` the initial key is used at every step
        (parity: ``multigrad.py:291-300``).
    checkpoint_dir : str, optional
        Directory for preemption-safe restart state.  The fit runs in
        segments of ``checkpoint_every`` steps (default
        ``max(1, nsteps // 10)``), atomically checkpointing
        ``(step, params, opt_state, key, trajectory)`` after each;
        re-invoking with the same arguments resumes where it left
        off.  A capability *addition* over the reference (SURVEY
        §5.4: it has no checkpointing; pod jobs preempt).
    telemetry : MetricsLogger, optional
        With ``log_every > 0``, an in-graph tap
        (:class:`multigrad_tpu.telemetry.ScalarTap`) emits ``adam``
        records — loss, |grad|, |params|, |update| (unbounded space)
        — every ``log_every``-th step from INSIDE the jitted scan.
        ``log_every`` is static (part of the compiled program), the
        emit gate is a ``lax.cond``, and the callback is unordered,
        so taps cost no retraces and no device stalls; records are
        written on process 0 only.
    donate_carry : bool, optional
        Donate the Adam carry ``(params, opt_state, key)`` to each
        segment program, aliasing the carry's input and output HBM
        buffers.  Default ``None`` = auto: on for TPU/GPU backends,
        off on CPU (where donation is a warning-emitting no-op).
        Numerically invisible; caller-held arrays are defensively
        copied first, so they stay valid.
    flight : FlightRecorder, optional
        Arm the in-graph non-finite sentinel
        (:mod:`multigrad_tpu.telemetry.flight`): the first NaN/Inf
        loss or |grad| inside the scan dumps a self-contained
        postmortem bundle (the recorder's ring of recent records,
        run record, jaxpr digest, last checkpoint path) and the fit
        raises :class:`~multigrad_tpu.telemetry.flight
        .FlightRecorderTripped` with the bundle path — also stamped
        into a ``fit_summary`` record when ``telemetry`` is set.
        Segmented drives stop at the failing segment.  Add the
        recorder as a sink of ``telemetry`` so the bundle carries
        the tapped step records.
    live : LiveServer | LiveSink, optional
        Attach the live-observability layer
        (:mod:`multigrad_tpu.telemetry.live`): the monitor joins the
        record stream as an extra sink (a logger is created if
        ``telemetry`` is None, and ``log_every`` defaults on so the
        view is not empty), and a ``fit_plan`` record announces
        ``nsteps`` up front — the ``/status`` endpoint's ETA and the
        dashboard's progress bar are computed against it.
    alerts : AlertEngine, optional
        Evaluate non-fatal alert rules
        (:mod:`multigrad_tpu.telemetry.alerts`) on the record stream;
        fired rules emit ``alert`` records back into it (and
        optionally escalate to a flight recorder).
    diagnostics : bool
        Compile the in-graph convergence diagnostics into the tapped
        scan: every ``adam`` record gains ``loss_ema`` and
        ``loss_ema_slope`` (the plateau signal).  Static like the tap
        — zero extra retraces.  No-op without telemetry/``log_every``.
    fn_diag : bool
        Declares that ``loss_and_grad`` returns a third dict of
        diagnostic scalars, merged into each tap record — the
        contract ``OnePointModel.run_adam(diagnostics=True)`` uses
        for its gradient-noise-scale kernel.
    carry_sharding : NamedSharding, optional
        Partition a batched ``(K, ndim)`` fit's whole Adam carry —
        params AND both moment sets AND the trajectory — K-sharded
        over a 2-level mesh's replica axis (obtain it from
        ``model.k_sharding()``): per-device optimizer state is K/R,
        the ZeRO-style layout of the sharded-K ensemble path.  The
        initial params are re-placed with it here, so callers may
        pass host arrays.  Incompatible with ``checkpoint_dir``
        (which requires 1-D params).

    Returns
    -------
    jnp.ndarray, shape ``(nsteps + 1, ndim)``
        Full parameter trajectory including the starting point — the
        same contract as the reference (``adam.py:58-68``).
    """
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    ndim = params.shape[-1]
    low, high = bounds_to_arrays(param_bounds, ndim)
    bounded = param_bounds is not None

    if bounded:
        check_strictly_inside(params, low, high, param_bounds)

    u0 = transform_array(params, low, high) if bounded else params
    if carry_sharding is not None:
        # Place the unbounded carry on the K-sharded layout up front:
        # the segment program's constraint then never moves data, and
        # host/replicated inits work transparently.
        u0 = jax.device_put(u0, carry_sharding)

    with_key = randkey is not None
    key0 = init_randkey(randkey) if with_key else jax.random.key(0)
    donate = resolve_donate(donate_carry)
    if donate:
        # The segment programs invalidate their carry arguments; the
        # caller may still hold (views of) u0/key0.
        u0, key0 = _carry_copy(u0, key0)
    head = u0[None]  # trajectory row 0, snapshotted BEFORE donation

    from ..telemetry.live import wire_monitoring
    from ..telemetry.taps import make_tap
    telemetry, log_every, owned = wire_monitoring(
        telemetry, log_every, live, alerts)
    tap = make_tap(telemetry, "adam", log_every)
    # The in-graph loss-EMA plateau diagnostic rides on the tap.
    ema_decay = PLATEAU_EMA_DECAY \
        if diagnostics and tap is not None else None
    fn_diag = bool(fn_diag)
    sentinel = flight.sentinel("adam") if flight is not None else None
    if flight is not None and checkpoint_dir is not None:
        flight.attach(last_checkpoint=os.path.join(
            checkpoint_dir, "adam_state.npz"))
    if telemetry is not None:
        # The fit plan up front: the live /status endpoint and the
        # dashboard compute ETA against it (the segment schedule the
        # drive below executes).
        telemetry.log("fit_plan", kind="adam_scan", nsteps=int(nsteps),
                      log_every=int(log_every),
                      checkpoint_every=(int(checkpoint_every)
                                        if checkpoint_every else None))
    try:
        return _run_adam_scan_body(
            loss_and_grad, params, nsteps, learning_rate,
            const_randkey, progress, fn_args, checkpoint_dir,
            checkpoint_every, telemetry, flight, low, high, bounded,
            u0, key0, with_key, donate, head, tap, sentinel,
            ema_decay, fn_diag, carry_sharding)
    finally:
        if owned is not None:
            owned.close()


def _run_adam_scan_body(loss_and_grad, params, nsteps, learning_rate,
                        const_randkey, progress, fn_args,
                        checkpoint_dir, checkpoint_every, telemetry,
                        flight, low, high, bounded, u0, key0,
                        with_key, donate, head, tap, sentinel,
                        ema_decay, fn_diag, carry_sharding=None):
    """The drive half of :func:`run_adam_scan`, split out so the
    monitor wiring can own the logger lifetime in one try/finally."""
    if checkpoint_dir is not None and params.ndim != 1:
        raise ValueError(
            "checkpoint_dir requires 1-D params (the restart state "
            f"layout is per-fit); got shape {params.shape}")
    if checkpoint_dir is not None:
        traj_u = _run_adam_checkpointed(
            loss_and_grad, u0, key0, low, high, fn_args, nsteps,
            float(learning_rate), with_key, const_randkey, bounded,
            checkpoint_dir,
            checkpoint_every or max(1, nsteps // 10),
            progress=progress, tap=tap, donate=donate,
            sentinel=sentinel, ema_decay=ema_decay, fn_diag=fn_diag)
    elif progress and tqdm is not None:
        # Live per-step progress without leaving the fast path: drive
        # the same cached segment-program family in ~20 slices (never
        # smaller than _PROGRESS_MIN_SEG — a short fit stays ONE
        # program, identical to progress=False), fencing each so the
        # bar advances as work actually lands (the reference shows a
        # moving bar, adam.py:32-36; a single whole-fit scan can only
        # report completion).  The path choice is identical on every
        # process — ``tqdm`` presence and ``progress`` are
        # environment/argument facts, not rank facts — so multi-host
        # collective schedules stay in lock step; only the bar itself
        # is rank-gated (inside _drive_segments).
        seg = max(_PROGRESS_MIN_SEG, nsteps // 20)
        opt_state = optax.adam(float(learning_rate)).init(u0)
        chunks = []
        _drive_segments(
            loss_and_grad, u0, opt_state, key0, low, high, fn_args,
            nsteps, seg, float(learning_rate), with_key,
            const_randkey, bounded, True,
            lambda _s, us, *_: chunks.append(us), tap=tap,
            donate=donate, sentinel=sentinel, ema_decay=ema_decay,
            fn_diag=fn_diag, carry_sharding=carry_sharding)
        traj_u = jnp.concatenate([head, *chunks], axis=0)
    else:
        # Whole fit = one segment of nsteps (same cached program
        # family as the checkpointed/progress drives, so the paths
        # can never diverge numerically).
        program = _adam_segment_program(
            loss_and_grad, nsteps, float(learning_rate), with_key,
            const_randkey, bounded, tap=tap, donate=donate,
            sentinel=sentinel, ema_decay=ema_decay, fn_diag=fn_diag,
            carry_sharding=carry_sharding)
        opt_state = optax.adam(float(learning_rate)).init(u0)
        instrumented = tap is not None or sentinel is not None
        extra = (jnp.asarray(0, jnp.int32),) if instrumented else ()
        if flight is not None:
            # Postmortem context: a zero-FLOP digest of the whole-fit
            # program, computed only if a bundle is actually dumped.
            flight.watch_program(
                "adam_segment_program",
                program, (u0, opt_state, key0, low, high,
                          tuple(fn_args)) + extra)
        _, _, _, us = program(u0, opt_state, key0, low, high,
                              tuple(fn_args), *extra)
        traj_u = jnp.concatenate([head, us], axis=0)
    if tap is not None or sentinel is not None:
        # Tap/sentinel callbacks are unordered effects; without a
        # barrier, in-flight records could land after the caller's
        # telemetry.close() (silently dropped) or out of file order.
        jax.effects_barrier()
    if flight is not None and flight.fatal:
        if telemetry is not None:
            telemetry.log("fit_summary", steps=nsteps,
                          final_loss=None,
                          postmortem_bundle=flight.bundle_path)
        flight.raise_if_fatal()
    if telemetry is not None and jax.process_index() == 0:
        # Close the fit in the stream (after the barrier above, so
        # every tap record precedes it): live consumers flip from
        # "fitting" to "done" on this record.  The final loss lives
        # in the last tap record — the scan returns params only, and
        # re-evaluating here would cost a full extra step.
        summary = {"steps": int(nsteps)}
        if flight is not None and flight.bundle_path:
            summary["postmortem_bundle"] = flight.bundle_path
        telemetry.log("fit_summary", **summary)
    if bounded:
        return inverse_transform_array(traj_u, low, high)
    return traj_u


# Jitted Adam-update programs for the streamed host loop, keyed on
# (learning_rate, donate): tiny programs (O(ndim) work), shared across
# fits — the donate variant aliases the (u, opt_state) carry buffers
# so the host loop, like the scan path, holds one moment set.
_STREAM_UPDATE_CACHE: dict = {}


def _streamed_update_program(learning_rate: float, donate: bool):
    cache_key = (float(learning_rate), bool(donate))
    if cache_key not in _STREAM_UPDATE_CACHE:
        tx = optax.adam(learning_rate)

        @partial(jax.jit, donate_argnums=(1, 2) if donate else ())
        def update(grad, u, opt_state):
            updates, opt_state = tx.update(grad, opt_state, u)
            return optax.apply_updates(u, updates), opt_state, updates

        _STREAM_UPDATE_CACHE[cache_key] = update
    return _STREAM_UPDATE_CACHE[cache_key]


def run_adam_streamed(loss_and_grad, params, nsteps=100,
                      param_bounds=None, learning_rate=0.01,
                      randkey=None, const_randkey=False, progress=True,
                      checkpoint_dir: Optional[str] = None,
                      checkpoint_every: Optional[int] = None,
                      telemetry=None, log_every: int = 0,
                      heartbeat_s: Optional[float] = None,
                      donate_carry: Optional[bool] = None,
                      stream_stats: Optional[Callable] = None,
                      flight=None, live=None, alerts=None,
                      diagnostics: bool = False):
    """Host-loop Adam over a *streamed* loss-and-grad callable.

    The fit loop for :class:`multigrad_tpu.data.streaming
    .StreamingOnePointModel`: each step calls
    ``loss_and_grad(params[, randkey=...]) -> (loss, grad)``, which
    for a streamed model runs the two-pass chunked algebra (or the
    single-dispatch scan program) — so the callable is deliberately
    NOT traced into a whole-fit ``lax.scan``: its chunk loop lives on
    the host by construction.  Bounds ride through the same bijection
    as every other Adam entry point, and the return contract matches
    :func:`run_adam_scan`: the full trajectory, ``(nsteps+1, ndim)``.

    With ``checkpoint_dir`` the restart state — step counter,
    unbounded params, optimizer state, PRNG key, trajectory — is
    atomically saved every ``checkpoint_every`` steps (default
    ``max(1, nsteps // 10)``) and a re-invocation with the same
    arguments resumes from the last completed step: streamed fits are
    the LONGEST fits (out-of-core catalogs), so preemption safety
    matters most here.  Config mismatches fail loudly, same contract
    as :func:`run_adam_scan`; the streamed *data* is not fingerprinted
    (the callable closes over its sources — keep them fixed across a
    resume).

    With ``telemetry`` (a :class:`multigrad_tpu.telemetry
    .MetricsLogger`): ``adam`` records (loss + norms, every
    ``log_every``-th step, process 0 only — this loop is host-side,
    so no in-graph tap is needed), a ``fit`` span, ``checkpoint``
    spans, and a ``fit_summary`` whose ``steps_per_sec`` excludes the
    first (compile) step (:class:`~multigrad_tpu.utils.profiling
    .StepsPerSecond` is reset after it).  ``heartbeat_s`` starts a
    :class:`~multigrad_tpu.telemetry.Heartbeat` thread — liveness +
    stall records for fits long enough to be preempted or wedged.

    ``live``/``alerts`` attach the online monitors exactly as on
    :func:`run_adam_scan` (extra sinks, default ``log_every``, a
    ``fit_plan`` record carrying ``nsteps`` and the resume ``start``
    for ETA); ``diagnostics`` adds ``loss_ema``/``loss_ema_slope`` to
    the emitted ``adam`` records — here the EMA is a host-side float
    (this loop already holds each step's loss), same fields and decay
    as the in-graph tap.

    ``donate_carry`` (None = backend auto, like :func:`run_adam_scan`)
    routes each step's optimizer update through a jitted program that
    donates ``(u, opt_state)``, so even this host loop keeps ONE
    moment set resident.  ``stream_stats`` — a zero-argument callable
    returning the current :class:`~multigrad_tpu.utils.profiling
    .StreamStats` (or None) — lets streamed models surface the
    prefetcher's per-pass overlap counters in the closing
    ``fit_summary`` record (``overlap_frac`` + per-pass fractions).

    ``flight`` (a :class:`~multigrad_tpu.telemetry.flight
    .FlightRecorder`) arms the non-finite watch on this host loop:
    the loop already fetches each step's loss and parameters, so the
    check is free — a NaN/Inf loss or parameter trips the recorder
    (postmortem bundle dumped), the loop stops, the closing
    ``fit_summary`` carries ``postmortem_bundle``, and the fit
    raises :class:`~multigrad_tpu.telemetry.flight
    .FlightRecorderTripped`.  Heartbeat stalls reach the recorder
    through the record stream (add it as a sink of ``telemetry``).
    """
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    ndim = params.shape[0]
    low, high = bounds_to_arrays(param_bounds, ndim)
    bounded = param_bounds is not None
    if bounded:
        check_strictly_inside(params, low, high, param_bounds)

    def base(u_, key_):
        kwargs = {} if key_ is None else {"randkey": key_}
        return loss_and_grad(u_, **kwargs)

    wrapped = _wrap_bounded(base, low, high) if bounded else base
    key = init_randkey(randkey) if randkey is not None else None
    if const_randkey and key is None:
        raise ValueError("Must pass randkey if const_randkey")

    u = transform_array(params, low, high) if bounded else params
    donate = resolve_donate(donate_carry)
    if donate and not bounded:
        # The donated update program invalidates u's buffer; unbounded
        # fits pass the caller's params array straight through.
        u = jnp.array(u, copy=True)
    tx = optax.adam(learning_rate)
    opt_state = tx.init(u)
    update_program = _streamed_update_program(learning_rate, donate)
    # Host buffer assigned in place: a jnp .at[].set per step outside
    # jit would copy the whole (nsteps+1, ndim) array every step.
    traj = np.zeros((nsteps + 1, ndim), np.asarray(u).dtype)
    traj[0] = np.asarray(u)
    start = 0

    ckpt_path = config = config_key = None
    # PRNG keys can't ride in the state dict as-is on every jax
    # (checkpoint handles them, but the no-key case needs a stable
    # placeholder for structural equality across save/load).
    key0 = key if key is not None else jax.random.key(0)
    if checkpoint_dir is not None:
        from ..utils import checkpoint as _ckpt

        os.makedirs(checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(checkpoint_dir, "adam_streamed_state")
        if flight is not None:
            # Same bundle context run_adam_scan attaches: the
            # postmortem must point at the last good restart state —
            # streamed fits are the longest, so it matters most here.
            flight.attach(last_checkpoint=ckpt_path + ".npz")
        # Same loud-mismatch guard as _run_adam_checkpointed: float64
        # on the host so sub-float32 config diffs don't alias.
        config = np.concatenate([
            np.asarray(u, np.float64),
            np.asarray(low, np.float64), np.asarray(high, np.float64),
            np.asarray([learning_rate, float(randkey is not None),
                        float(const_randkey)], np.float64)])
        config_key = jnp.asarray(jax.random.key_data(key0).ravel())
        state = {"step": jnp.zeros((), jnp.int32), "u": u,
                 "opt_state": opt_state, "key": key0, "traj": traj,
                 "config": config, "config_key": config_key}
        if os.path.exists(ckpt_path + ".npz"):
            try:
                saved = _ckpt.load(ckpt_path, state)
            except ValueError as e:
                raise ValueError(
                    "cannot resume from checkpoint in {!r}: {} (use a "
                    "fresh checkpoint_dir to start over)".format(
                        checkpoint_dir, e)) from e
            if saved["traj"].shape[0] != nsteps + 1:
                raise ValueError(
                    "checkpoint in {!r} was written for a different "
                    "nsteps; use a fresh checkpoint_dir".format(
                        checkpoint_dir))
            if not (np.array_equal(np.asarray(saved["config"]), config)
                    and np.array_equal(np.asarray(saved["config_key"]),
                                       np.asarray(config_key))):
                raise ValueError(
                    "checkpoint in {!r} was written for a different "
                    "fit configuration (guess/bounds/learning_rate/"
                    "randkey); use a fresh checkpoint_dir".format(
                        checkpoint_dir))
            start = int(saved["step"])
            u = jnp.asarray(saved["u"])
            opt_state = saved["opt_state"]
            traj = np.array(saved["traj"])
            if key is not None:
                key = saved["key"]
        if jax.process_count() > 1:
            # Saves are process-0-only and disks may be host-local:
            # every process must adopt process 0's restart state or
            # the streamed chunk programs' collective schedules
            # diverge on resume (same contract as
            # _run_adam_checkpointed; the key travels as raw words —
            # broadcast_one_to_all can't zeros_like a typed key).
            from jax.experimental import multihost_utils
            live_key = key if key is not None else key0
            plain = {"step": jnp.asarray(start, jnp.int32), "u": u,
                     "traj": traj, "opt_state": opt_state,
                     "key_data": jax.random.key_data(live_key)}
            plain = multihost_utils.broadcast_one_to_all(plain)
            start = int(plain["step"])
            u = jnp.asarray(plain["u"])
            traj = np.array(plain["traj"])
            opt_state = plain["opt_state"]
            if key is not None:
                key = jax.random.wrap_key_data(
                    jnp.asarray(plain["key_data"]),
                    impl=jax.random.key_impl(live_key))
        checkpoint_every = checkpoint_every or max(1, nsteps // 10)

    from ..telemetry.live import wire_monitoring
    from ..telemetry.spans import Heartbeat, span
    from ..telemetry.taps import batch_norm
    from ..utils.profiling import StepsPerSecond

    # Live/alert monitors join the stream after resume resolution, so
    # the fit_plan they key ETA off carries the real start step.  An
    # `owned` logger (monitors with no caller logger) holds no files,
    # so closing it only on the happy path is safe.
    telemetry, log_every, owned = wire_monitoring(
        telemetry, log_every, live, alerts)
    if telemetry is not None:
        telemetry.log("fit_plan", kind="adam_streamed",
                      nsteps=int(nsteps), start=int(start),
                      log_every=int(log_every))
    # Host-side twin of the in-graph loss-EMA plateau diagnostic
    # (this loop already holds each step's loss as a float).
    ema_m, ema_n, ema_prev = 0.0, 0, None

    def save_state(done):
        if ckpt_path is not None and jax.process_index() == 0:
            from ..utils import checkpoint as _ckpt
            with span(telemetry, "checkpoint", step=int(done)):
                _ckpt.save(ckpt_path, {
                    "step": jnp.asarray(done, jnp.int32), "u": u,
                    "opt_state": opt_state,
                    "key": key if key is not None else key0,
                    "traj": traj, "config": config,
                    "config_key": config_key})

    emit = (telemetry is not None and log_every > 0
            and jax.process_index() == 0)
    meter = StepsPerSecond()
    last_loss = None
    heartbeat = Heartbeat(telemetry, interval=heartbeat_s) \
        if (telemetry is not None and heartbeat_s) else None
    steps = (adam_trange(nsteps) if progress and jax.process_index() == 0
             else range(nsteps))
    it = iter(steps)
    for _ in range(start):           # keep the bar honest on resume
        next(it, None)
    with span(telemetry, "fit", nsteps=nsteps, start=start), \
            (heartbeat or contextlib.nullcontext()):
        for step in range(start, nsteps):
            next(it, None)
            if key is not None and not const_randkey:
                key, key_i = jax.random.split(key)
            else:
                key_i = key
            loss, grad = wrapped(u, key_i)
            last_loss = loss
            u, opt_state, updates = update_program(grad, u, opt_state)
            traj[step + 1] = np.asarray(u)
            if flight is not None and not (
                    np.isfinite(np.asarray(loss))
                    and np.all(np.isfinite(traj[step + 1]))):
                # Host loop = free sentinel: loss and params are
                # already fetched each step.  Stop at the failure —
                # further steps only iterate NaNs.
                flight.trip("non_finite_adam", fatal=True, step=step,
                            loss=float(np.asarray(loss)))
                break
            meter.tick()
            if step == start:
                # The first step paid trace/compile; drop it from the
                # steady-state rate (StepsPerSecond.reset contract).
                meter.reset()
            if heartbeat is not None:
                heartbeat.tick(step + 1)
            if diagnostics:
                ema_n += 1
                ema_m = PLATEAU_EMA_DECAY * ema_m \
                    + (1.0 - PLATEAU_EMA_DECAY) * float(loss)
            if emit and step % log_every == 0:
                diag = {}
                if diagnostics:
                    corrected = ema_m / (1.0 - PLATEAU_EMA_DECAY
                                         ** ema_n)
                    prev, ema_prev = ema_prev, (step, corrected)
                    diag["loss_ema"] = corrected
                    diag["loss_ema_slope"] = (
                        (corrected - prev[1]) / (step - prev[0])
                        if prev is not None and step > prev[0]
                        else 0.0)
                telemetry.log(
                    "adam", step=step, loss=float(loss),
                    grad_norm=float(batch_norm(grad)),
                    param_norm=float(batch_norm(u)),
                    update_norm=float(batch_norm(updates)), **diag)
            if ckpt_path is not None and (
                    (step + 1) % checkpoint_every == 0
                    or step + 1 == nsteps):
                save_state(step + 1)
    if hasattr(steps, "close"):
        steps.close()
    if telemetry is not None and jax.process_index() == 0:
        # last_loss is the loop's final evaluation (pre-update, the
        # same convention as the tap records); re-evaluating here
        # would cost a full extra pass over a streamed catalog — and
        # on multi-host would run a collective on process 0 only.
        extra = {}
        if stream_stats is not None:
            st = stream_stats()
            if st is not None:
                # The last step's stream counters: prefetch overlap
                # achieved (1 = consumer never starved after the
                # pipeline primed; 0 = fully serial), per pass.
                extra["overlap_frac"] = round(st.overlap_fraction, 4)
                extra["pass_overlap"] = {
                    name: p["overlap_frac"]
                    for name, p in st.pass_summary().items()}
        if flight is not None and flight.bundle_path:
            # Fatal trips AND non-fatal dumps (a heartbeat stall the
            # fit survived) both point the summary at their bundle.
            extra["postmortem_bundle"] = flight.bundle_path
        telemetry.log("fit_summary", steps=nsteps,
                      steps_per_sec=round(meter.rate, 4),
                      final_loss=(float(last_loss)
                                  if last_loss is not None else None),
                      **extra)
    if owned is not None:
        owned.close()
    if flight is not None:
        flight.raise_if_fatal()
    traj = jnp.asarray(traj)
    return inverse_transform_array(traj, low, high) if bounded \
        else traj


def run_adam_unbounded(logloss_and_grad_fn, params, data, nsteps=100,
                       learning_rate=0.01, randkey=None, progress=True):
    """Host-loop Adam for arbitrary callables (parity: ``adam.py:71-130``).

    Signature contract matches the reference:
    ``logloss_and_grad_fn(params, data[, randkey=...]) -> (loss, grad)``.
    Runs on every host identically (no root/worker protocol) and
    returns the full parameter trajectory, shape ``(nsteps+1, ndim)``.
    """
    kwargs = {}
    if randkey is not None:
        randkey = init_randkey(randkey)

    params = jnp.asarray(params, dtype=jnp.result_type(float))
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)
    update = jax.jit(tx.update)
    apply_updates = jax.jit(optax.apply_updates)

    param_steps = [params]
    steps = (adam_trange(nsteps) if progress and jax.process_index() == 0
             else range(nsteps))
    for _step in steps:
        if randkey is not None:
            randkey, key_i = jax.random.split(randkey)
            kwargs["randkey"] = key_i
        _, grad = logloss_and_grad_fn(params, data, **kwargs)
        updates, opt_state = update(grad, opt_state, params)
        params = apply_updates(params, updates)
        param_steps.append(params)

    return jnp.array(param_steps)


def run_adam(logloss_and_grad_fn, params, data, nsteps=100, param_bounds=None,
             learning_rate=0.01, randkey=None, progress=True):
    """Generic Adam entry point (parity: ``adam.py:133-189``).

    Dispatches to :func:`run_adam_unbounded` directly or through the
    bounds bijection.  Unlike the reference — where only rank 0
    returned the trajectory and everyone else got ``None``
    (``adam.py:128-130``) — every caller receives the full trajectory.
    """
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    if param_bounds is None:
        return run_adam_unbounded(
            logloss_and_grad_fn, params, data, nsteps=nsteps,
            learning_rate=learning_rate, randkey=randkey, progress=progress)

    if len(params) != len(param_bounds):
        raise ValueError(
            f"param_bounds must have one entry per parameter: got "
            f"{len(param_bounds)} bounds for {len(params)} params")
    low, high = bounds_to_arrays(param_bounds, len(params))
    check_strictly_inside(params, low, high, param_bounds)
    unbound_fn = _wrap_bounded(logloss_and_grad_fn, low, high)
    uparams = transform_array(params, low, high)
    traj_u = run_adam_unbounded(
        unbound_fn, uparams, data, nsteps=nsteps,
        learning_rate=learning_rate, randkey=randkey, progress=progress)
    return inverse_transform_array(traj_u, low, high)
