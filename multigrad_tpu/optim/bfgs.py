"""L-BFGS-B optimization.

Port of ``/root/reference/multigrad/bfgs.py``.  The reference keeps
scipy's sequential L-BFGS-B on rank 0 and turns every other rank into
a command-loop worker serving distributed loss evaluations
(``bfgs.py:68-111``).  Under single-controller SPMD the distributed
loss-and-grad is just a function call (the collectives are inside the
jitted program), so scipy drives it directly — and in multi-host mode
every host runs the *same* scipy loop deterministically: its inputs
are psum results, which are bitwise-identical on all hosts, so all
hosts follow identical control flow and return identical results.
This reproduces the reference's "all ranks return identical
OptimizeResult" contract (``bfgs.py:108-113``) with no broadcast.

An in-graph alternative (:func:`run_lbfgs_scan`, optax L-BFGS inside
``lax.scan``) is provided for fully on-device fitting where scipy's
host-side line search would dominate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import scipy.optimize

from .adam import _wrap_bounded, init_randkey
from .transforms import (bounds_to_arrays, check_strictly_inside,
                         inverse_transform_array, transform_array)
from ..utils.util import cached_program, trange, trange_no_tqdm


def bfgs_trange(n):
    return trange(n, desc="BFGS Gradient Descent Progress", leave=True)


def run_bfgs(loss_and_grad_fn, params, maxsteps=100, param_bounds=None,
             randkey=None, comm=None, progress=True):
    """Run scipy L-BFGS-B on a distributed loss-and-grad function.

    Parity with ``/root/reference/multigrad/bfgs.py:32-113``: same
    signature (``comm`` is accepted and ignored — there is no worker
    protocol to scope), same ``randkey`` held constant across
    iterations (BFGS needs a deterministic objective,
    ``bfgs.py:47-48,63-66``), same ``OptimizeResult`` return contract
    (message, success, fun, x, jac, nfev, nit).
    """
    del comm
    kwargs = {}
    if randkey is not None:
        kwargs["randkey"] = init_randkey(randkey)

    show = progress and jax.process_index() == 0
    pbar = bfgs_trange(maxsteps) if show else trange_no_tqdm(maxsteps)

    # Outside the model's domain (e.g. sigma <= 0) the loss can go
    # NaN/inf.  scipy's line search must see a *finite, moderate*
    # penalty there: non-finite values make it extrapolate instead of
    # backtrack, and magnitudes more than ~1e4 above the objective
    # scale break its quadratic interpolation (measured: premature
    # stalls at 1e5x and above; 3x-1e4x all recover and converge in
    # the reference's ~16 iterations).  100x the running max — seeded
    # by the (required-finite) starting loss — keeps a safe margin on
    # both sides.
    max_finite_loss = [None]

    def fun(x):
        loss, grad = loss_and_grad_fn(jnp.asarray(x), **kwargs)
        # scipy line searches in float64; round-trip through numpy.
        loss = np.asarray(loss, dtype=np.float64)
        grad = np.asarray(grad, dtype=np.float64)
        if np.isfinite(loss):
            prev = max_finite_loss[0]
            max_finite_loss[0] = max(prev or 1.0, abs(float(loss)), 1.0)
        elif max_finite_loss[0] is None:
            # Non-finite at the starting point: a zero-grad penalty
            # would read as instant (false) convergence — fail fast.
            raise ValueError(
                f"run_bfgs: loss is non-finite ({loss}) at the initial "
                f"guess {np.asarray(x)}; start inside the model's domain "
                "or pass param_bounds")
        else:
            loss = np.float64(100.0 * max_finite_loss[0])
            grad = np.where(np.isfinite(grad), grad, 0.0)
        return loss, grad

    def callback(*_args, **_kwargs):
        if hasattr(pbar, "update"):
            pbar.update()

    result = scipy.optimize.minimize(
        fun, x0=np.asarray(params, dtype=np.float64), method="L-BFGS-B",
        jac=True, options=dict(maxiter=maxsteps), callback=callback,
        bounds=param_bounds)

    if hasattr(pbar, "close"):
        pbar.close()
    return result


def _lbfgs_scan_program(fn, maxsteps, memory_size, with_key, bounded):
    """Whole-fit jitted scan, cached per callable
    (:func:`~multigrad_tpu.utils.util.cached_program` — avoids pinning
    ``fn`` and its closure in jit's global cache).  With ``bounded``
    the loop runs in unbounded space through the bijection; ``low`` /
    ``high`` are runtime arguments, so bounds changes never recompile.
    """
    def build():
        tx = optax.lbfgs(memory_size=memory_size)

        @jax.jit
        def program(u0, key, low, high):
            kwargs = {"randkey": key} if with_key else {}

            def base(p):
                return fn(p, **kwargs)

            opt_fn = _wrap_bounded(base, low, high) if bounded else base

            def value_fn(u):
                loss, _ = opt_fn(u)
                return loss

            def step(carry, _):
                u, state = carry
                loss, grad = opt_fn(u)
                updates, state = tx.update(
                    grad, state, u, value=loss, grad=grad,
                    value_fn=value_fn)
                u = optax.apply_updates(u, updates)
                return (u, state), loss

            state0 = tx.init(u0)
            (u, _), losses = jax.lax.scan(step, (u0, state0), None,
                                          length=maxsteps)
            return u, losses
        return program

    return cached_program(fn, ("lbfgs_scan", maxsteps, memory_size,
                               with_key, bounded), build)


def run_lbfgs_scan(loss_and_grad_fn, params, maxsteps=100, randkey=None,
                   memory_size=10, param_bounds=None):
    """Fully in-graph L-BFGS via optax, as one ``lax.scan``.

    A capability addition over the reference (flagged as such): no host
    round-trips at all — appropriate when evaluations are fast and
    scipy's Python-side loop would dominate.  ``param_bounds`` (the
    reference's ``None | (low, high)`` per-parameter format) composes
    the :mod:`~multigrad_tpu.optim.transforms` bijections into the
    scan, making this the in-graph counterpart of L-BFGS-**B**: the
    loop optimizes unbounded coordinates and every iterate maps back
    strictly inside its box.

    Returns ``(final_params, losses)`` with the loss trajectory.
    """
    with_key = randkey is not None
    key = init_randkey(randkey) if with_key else jnp.zeros(())
    params = jnp.asarray(params, dtype=jnp.result_type(float))
    bounded = param_bounds is not None
    scalar = params.ndim == 0
    if bounded:
        if scalar:
            # 0-d params are a supported input (the objective sees the
            # same scalar back); the bounds machinery is 1-d, so ride
            # through a one-element view — param_bounds then has the
            # usual one entry per parameter, here exactly one — and
            # squeeze everything back to 0-d so the in-scan objective
            # still receives a true scalar.
            params = params.reshape(1)
        low, high = bounds_to_arrays(param_bounds, params.shape[0])
        check_strictly_inside(params, low, high, param_bounds)
        if scalar:
            params, low, high = (params.reshape(()), low.reshape(()),
                                 high.reshape(()))
        params = transform_array(params, low, high)
    else:
        # Unused by the unbounded program; 0-d placeholders keep
        # scalar-params calls working (no shape[0] poke).
        low = high = jnp.zeros(())
    program = _lbfgs_scan_program(loss_and_grad_fn, maxsteps, memory_size,
                                  with_key, bounded)
    u, losses = program(params, key, low, high)
    if bounded:
        return inverse_transform_array(u, low, high), losses
    return u, losses
