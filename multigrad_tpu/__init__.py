"""multigrad_tpu — TPU-native differentiable data-parallel model fitting.

A ground-up JAX/XLA re-design of the capabilities of
``AlanPearl/multigrad`` ("Differentiable Multiprocessing for Gradient
Descent with JAX"): fit differentiable models whose summary statistics
are additive over data shards, with communication volume
O(|sumstats| + |params|) regardless of data size — on TPU meshes
instead of MPI clusters.

Public surface (parity with ``multigrad/__init__.py:3-9`` of the
reference, plus TPU-native additions):

* :class:`OnePointModel`, :class:`OnePointGroup` — the model API.
* :func:`reduce_sum`, :func:`split_subcomms`,
  :func:`split_subcomms_by_node` — collectives & topology.
* :mod:`util` — simple GD, LHS sampling, scatter helpers.
* :class:`MeshComm`, :func:`global_comm`, :func:`scatter_nd`,
  :mod:`distributed` — the TPU mesh/communicator layer (replaces
  mpi4py communicators).
"""
from ._version import __version__  # noqa: F401

from .parallel.mesh import (MeshComm, ensemble_comm,  # noqa
                            ensemble_mesh, global_comm, hybrid_comm,
                            hybrid_mesh, split_subcomms,
                            split_subcomms_by_node)
from .parallel.collectives import (all_gather, reduce_sum,  # noqa
                                   scatter_from_local, scatter_nd)
from .parallel import distributed  # noqa: F401
from .core.model import OnePointModel  # noqa: F401
from .core.group import OnePointGroup, param_view  # noqa: F401
from . import data  # noqa: F401
from .data import (ArraySource, CatalogSource, ChunkPrefetcher,  # noqa
                   MemmapSource, NpzSource, StreamingOnePointModel)
from . import inference  # noqa: F401
from .inference import (EnsembleResult, FisherResult, HMCResult,  # noqa
                        ensemble_memory_model, fisher_information,
                        hmc_init_from_ensemble, laplace_covariance,
                        max_k_for_budget, run_hmc,
                        run_multistart_adam, run_multistart_lbfgs,
                        sumstats_jacobian)
from . import telemetry  # noqa: F401
from .telemetry import (AlertEngine, CommCounter, FlightRecorder,  # noqa
                        FlightRecorderTripped, Heartbeat, JsonlSink,
                        LiveMetrics, LiveServer, MemorySink,
                        MetricsLogger, ScalarTap, measure_model_comm,
                        model_cost, profiled_fit, roofline_record,
                        run_record)
from . import analysis  # noqa: F401
from .analysis import (Finding, analyze, analyze_concurrency,  # noqa
                       analyze_fit, analyze_model, analyze_program,
                       assert_clean)
from . import serve  # noqa: F401
from .serve import (FitConfig, FitFuture, FitResult,  # noqa
                    FitScheduler, enable_compile_cache,
                    warmup_buckets)
from . import tune  # noqa: F401
from .tune import (TuneResult, TuningTable, tune_buckets,  # noqa
                   tune_model, tune_streaming)
from .optim.adam import (gen_new_key, init_randkey, run_adam,  # noqa
                         run_adam_scan, run_adam_unbounded)
from .optim.bfgs import run_bfgs, run_lbfgs_scan  # noqa: F401
from .optim.transforms import (apply_inverse_transforms,  # noqa
                               apply_transforms, inverse_transform,
                               transform)
from .utils import util  # noqa: F401
from .utils.util import (GradDescentResult, latin_hypercube_sampler,  # noqa
                         simple_grad_descent)

__all__ = [
    # reference parity surface (multigrad/__init__.py:6-9)
    "OnePointModel", "OnePointGroup", "param_view", "reduce_sum",
    "split_subcomms", "split_subcomms_by_node", "util",
    # TPU-native communicator layer
    "MeshComm", "ensemble_comm", "ensemble_mesh", "global_comm",
    "hybrid_comm", "hybrid_mesh", "scatter_nd",
    "scatter_from_local", "all_gather", "distributed",
    # streaming data subsystem (out-of-core catalogs)
    "data", "StreamingOnePointModel", "CatalogSource", "ArraySource",
    "NpzSource", "MemmapSource", "ChunkPrefetcher",
    # inference subsystem (uncertainty quantification)
    "inference", "FisherResult", "fisher_information",
    "laplace_covariance", "sumstats_jacobian", "HMCResult", "run_hmc",
    "EnsembleResult", "run_multistart_adam", "run_multistart_lbfgs",
    "hmc_init_from_ensemble", "ensemble_memory_model",
    "max_k_for_budget",
    # telemetry subsystem (observability)
    "telemetry", "MetricsLogger", "JsonlSink", "MemorySink",
    "ScalarTap", "CommCounter", "Heartbeat", "measure_model_comm",
    "run_record",
    # flight recorder & perf attribution
    "FlightRecorder", "FlightRecorderTripped", "profiled_fit",
    "model_cost", "roofline_record",
    # live observability (endpoint, alert rules)
    "LiveMetrics", "LiveServer", "AlertEngine",
    # static shard-safety analysis
    "analysis", "Finding", "analyze", "analyze_model",
    "analyze_program", "analyze_fit", "assert_clean",
    # fit-fleet serving layer (fits as a service)
    "serve", "FitScheduler", "FitConfig", "FitFuture", "FitResult",
    "enable_compile_cache", "warmup_buckets",
    # cost-model-driven autotuner (tuned defaults)
    "tune", "TuningTable", "TuneResult", "tune_model",
    "tune_buckets", "tune_streaming",
    # optimizers
    "run_adam", "run_adam_scan", "run_adam_unbounded", "run_bfgs",
    "run_lbfgs_scan", "simple_grad_descent", "GradDescentResult",
    "latin_hypercube_sampler",
    # bounds bijections
    "transform", "inverse_transform", "apply_transforms",
    "apply_inverse_transforms", "init_randkey", "gen_new_key",
    "__version__",
]
