from .smf import SMFModel, ParamTuple, load_halo_masses, make_smf_data

__all__ = ["SMFModel", "ParamTuple", "load_halo_masses", "make_smf_data"]
