from .smf import (SMFChi2Model, SMFModel, ParamTuple,
                  load_halo_masses, make_smf_data)
from .wprp import (WprpModel, WprpParams, XiModel, make_galaxy_mock,
                   make_wprp_data, make_xi_data,
                   selection_weights)
from .galhalo import (GalhaloModel, GalhaloParams, make_galhalo_data,
                      mean_logsm, sample_log_halo_masses)
from .galhalo_hist import (GalhaloHistModel, GalhaloHistParams,
                           make_galhalo_hist_data, mean_log_mstar,
                           scatter_sigma)
from .joint import (JOINT_PARAM_NAMES, JOINT_TRUTH,
                    make_joint_smf_wprp)

__all__ = ["SMFModel", "SMFChi2Model", "ParamTuple",
           "load_halo_masses", "make_smf_data",
           "WprpModel", "WprpParams", "XiModel", "make_galaxy_mock",
           "make_wprp_data", "make_xi_data",
           "selection_weights", "GalhaloModel", "GalhaloParams",
           "make_galhalo_data", "mean_logsm", "sample_log_halo_masses",
           "GalhaloHistModel", "GalhaloHistParams",
           "make_galhalo_hist_data", "mean_log_mstar", "scatter_sigma",
           "JOINT_PARAM_NAMES", "JOINT_TRUTH", "make_joint_smf_wprp"]
