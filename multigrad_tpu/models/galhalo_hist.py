"""Galaxy–halo model with diffmah-style mass-accretion histories.

BASELINE config 4 names a "diffmah/diffstar galaxy–halo model, 1e8
halos" as a target workload; the reference contains no such model
(its ``diffdesi_experimental`` stops at index bookkeeping).  The
static-SHMR :class:`~multigrad_tpu.models.galhalo.GalhaloModel`
supplies the *execution* shape; this module supplies the *physics*
shape that defines the diffmah/diffstar family — **time structure**:

* **MAH (diffmah idiom)** — each halo grows along a smooth power law
  in cosmic time whose index rolls from an early-time to a late-time
  value through a sigmoid at a transition epoch::

      log10 Mh(t) = logm0 + alpha(t) * log10(t / T0)
      alpha(t)    = alpha_late + (alpha_early - alpha_late)
                    * sigmoid(k_t * log10(tc / t))

  ``Mh(T0) = 10**logm0`` exactly (the halo's observed mass anchors
  the history), ``alpha -> alpha_early`` for ``t << tc`` (fast early
  assembly) and ``-> alpha_late`` after.  ``d Mh/dt`` is closed-form
  (see :func:`_dlogmh_dt`) — no autodiff-through-time needed.

* **SFH (diffstar idiom)** — stars form from the accreted baryons at
  a mass-dependent efficiency peaking at ``logm_crit``::

      SFR(t)  = eps(Mh(t)) * F_B * dMh/dt
      M*(T0)  = integral_0^T0 SFR dt          (fixed T-point trapezoid)

  ``lg eps`` is a smooth two-slope peak built from softplus ramps
  (rising ``eps_lo`` below the critical mass, falling ``eps_hi``
  above), normalized so ``lg eps(logm_crit) = lgeps_max``.  The
  running integral is read out at several **observation epochs**
  (``obs_indices`` of the time grid) and the sumstats are the
  concatenated per-epoch stellar mass functions — multi-redshift
  data is what makes assembly-history parameters identifiable, and
  the cumulative-trapezoid readout provides every epoch from the one
  (n, T) table.

* **Scatter** — log-normal scatter about the mean ``log M*`` with a
  *mass-dependent* width ``sigma(logm0) = sigma_0 + sigma_slope *
  (logm0 - 13)``, entering the binned SMF analytically through the
  per-particle-sigma erf kernel (:mod:`multigrad_tpu.ops.binned`) —
  no Monte Carlo, exact gradients through every one of the 10
  parameters.

Execution shape: the whole pipeline — history integration, epoch
readout, scatter widths, and the erf-CDF binned reduction — runs
*inside* one rematerialized ``lax.scan`` over halo chunks
(:func:`_chunk_epoch_smfs`), each chunk contributing a ``(K, B)``
partial-density stack to the running total.  Peak memory is
``O(N + chunk * T)`` independent of the epoch count: no ``(N, K)``
readout or ``(N,)`` sigma array is ever materialized, so the same
single-chip streaming that carries the SMF family to 1e9 halos
(BENCH_NOTES §5) carries the history model too.  (Standalone
:func:`mean_log_mstar` still returns per-halo readouts for users who
want the table itself.)
Distribution is inherited from :class:`~multigrad_tpu.core.model
.OnePointModel` — shard the halo axis with ``scatter_nd``, totals by
in-graph psum.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.model import OnePointModel
from ..ops.binned import binned_density
from ..parallel._shard_map_compat import pvary_like
from ..parallel.collectives import scatter_nd
from ..parallel.mesh import MeshComm
from ..utils.util import pad_to_multiple
from .galhalo import sample_log_halo_masses

T0_GYR = 13.8          # age of the universe: the histories' endpoint
F_BARYON = 0.156       # cosmic baryon fraction Omega_b / Omega_m
_LN10 = 2.302585092994046
_PAD_LOGM = 1e9        # pad sentinel on the halo-mass axis
_PAD_OUT = 1e18        # emitted log-M* for pad halos (neutral in the
                       # erf kernels — beyond every finite bin edge,
                       # zero forward contribution and zero gradient)


class GalhaloHistParams(NamedTuple):
    """Ten-parameter MAH + SFH + scatter family (all differentiable)."""
    alpha_early: float = 2.5    # early-time accretion index
    alpha_late: float = 0.8     # late-time accretion index
    lg_tc: float = 0.3          # log10 of the MAH transition time [Gyr]
    k_t: float = 3.0            # sharpness of the index rollover
    lgeps_max: float = -0.7     # peak star-formation efficiency (log10)
    logm_crit: float = 12.0     # halo mass of peak efficiency
    eps_lo: float = 1.5         # efficiency rise below logm_crit
    eps_hi: float = 1.0         # efficiency fall above logm_crit
    sigma_0: float = 0.2        # log-normal scatter at logm0 = 13
    sigma_slope: float = -0.03  # d sigma / d logm0


TRUTH = GalhaloHistParams()


def default_time_grid(n_times: int = 16):
    """Log-spaced integration grid over (0.5, T0] Gyr.

    Early times contribute little mass but steep efficiency slopes;
    log spacing resolves the transition epoch without wasting points
    on the quiescent late history.
    """
    return jnp.logspace(jnp.log10(0.5), jnp.log10(T0_GYR), n_times)


def mah_alpha(t, params):
    """The rolling accretion index alpha(t) (see module docstring)."""
    p = GalhaloHistParams(*params)
    return p.alpha_late + (p.alpha_early - p.alpha_late) * jax.nn.sigmoid(
        p.k_t * (p.lg_tc - jnp.log10(t)))


def log_mh_at_t(log_mh0, t, params):
    """log10 Mh(t) for halos of z=0 mass ``log_mh0`` (broadcasting)."""
    lam = jnp.log10(t / T0_GYR)
    return log_mh0 + mah_alpha(t, params) * lam


def _dlogmh_dt(log_mh0, t, params):
    """d(log10 Mh)/dt, closed form.

    With ``lam = log10(t/T0)`` and ``s = sigmoid(k_t (lg_tc - lg t))``:

        d alpha/dt  = -(a_e - a_l) s (1 - s) k_t / (t ln 10)
        d lam /dt   = 1 / (t ln 10)
        d logMh/dt  = lam * d alpha/dt + alpha / (t ln 10)
    """
    p = GalhaloHistParams(*params)
    del log_mh0  # the index is mass-independent in this family
    s = jax.nn.sigmoid(p.k_t * (p.lg_tc - jnp.log10(t)))
    alpha = p.alpha_late + (p.alpha_early - p.alpha_late) * s
    dalpha_dt = -(p.alpha_early - p.alpha_late) * s * (1.0 - s) \
        * p.k_t / (t * _LN10)
    lam = jnp.log10(t / T0_GYR)
    return lam * dalpha_dt + alpha / (t * _LN10)


def lg_sfr_efficiency(log_mh, params):
    """log10 of the star-formation efficiency eps(Mh).

    Two softplus ramps joined at ``logm_crit`` (rising ``eps_lo``,
    falling ``eps_hi``), shifted so the peak value is exactly
    ``lgeps_max`` at the critical mass.
    """
    p = GalhaloHistParams(*params)
    k = 2.0  # fixed join sharpness; the slopes carry the physics
    x = log_mh - p.logm_crit
    softplus = jax.nn.softplus
    ramp = (p.eps_lo / k) * softplus(-k * x) \
        + (p.eps_hi / k) * softplus(k * x)
    ramp0 = (p.eps_lo + p.eps_hi) / k * softplus(0.0)
    return p.lgeps_max - (ramp - ramp0)


def _check_obs_indices(obs_indices, t_grid):
    """Observation epochs are configuration, not data: they must be
    concrete so their range can be validated at trace time.

    Index 0 has no cumulative integral yet — ``jnp.take`` would wrap
    ``0 - 1`` to the LAST column and silently hand back the final
    epoch as the "earliest" one, so a traced index that cannot be
    range-checked is rejected outright rather than risked.
    """
    if isinstance(obs_indices, jax.core.Tracer):
        raise TypeError(
            "obs_indices must be concrete (a static tuple of grid "
            "indices), not a traced value: store a Python tuple — "
            "not an array — in aux_data/arguments so the epoch "
            "configuration stays in the jitted program's closure "
            "(GalhaloHistModel normalizes this automatically)")
    oi = np.asarray(obs_indices)
    if oi.min() < 1 or oi.max() >= t_grid.shape[0]:
        raise ValueError(
            f"obs_indices must lie in [1, {t_grid.shape[0] - 1}] "
            f"(grid indices with at least one trapezoid step "
            f"before them), got {oi.tolist()}")


def _mean_log_mstar_block(log_mh0, params, t_grid, obs_indices):
    """Mean log10 M*(t_obs) for a block of halos at each observation
    epoch — the (n, T) history, read out at ``obs_indices`` of the
    grid via the cumulative SFH integral (shape (n, K)).

    Pad halos (``log_mh0 > 100``) are computed at a sanitized mass and
    overwritten with the neutral sentinel afterwards; the ``where``
    transpose zeroes their cotangents, so neither forward nor backward
    sees the garbage branch (the 0*inf-NaN padding trap).
    """
    pad = log_mh0 > 100.0
    lm_safe = jnp.where(pad, 13.0, log_mh0)[:, None]      # (n, 1)
    t = t_grid[None, :]                                   # (1, T)

    log_mh_t = log_mh_at_t(lm_safe, t, params)            # (n, T)
    # dM/dt = M ln10 dlogM/dt; assemble SFR in log space so the huge
    # dynamic range (Mh spans ~10 dex across the grid) stays in the
    # exponent until the final, well-scaled integrand.
    lg_dmh_dt = log_mh_t + jnp.log10(
        jnp.clip(_dlogmh_dt(lm_safe, t, params), 1e-30) * _LN10)
    lg_sfr = lg_sfr_efficiency(log_mh_t, params) \
        + jnp.log10(F_BARYON) + lg_dmh_dt                 # [Msun/Gyr]
    # Cumulative trapezoid in linear SFR, rescaled by the block
    # maximum so the exponentials stay in float32 range at any halo
    # mass; M*(t_k) is then a gather of the running integral.
    lg_ref = jnp.max(lg_sfr, axis=1, keepdims=True)
    sfr = 10.0 ** (lg_sfr - lg_ref)
    dt = jnp.diff(t_grid)[None, :]
    increments = 0.5 * (sfr[:, 1:] + sfr[:, :-1]) * dt    # (n, T-1)
    mstar_cum = jnp.cumsum(increments, axis=1)            # up to t_k
    cols = jnp.take(mstar_cum, obs_indices - 1, axis=1)   # (n, K)
    logsm = lg_ref + jnp.log10(jnp.clip(cols, 1e-30))
    return jnp.where(pad[:, None], _PAD_OUT, logsm)


def mean_log_mstar(log_mh0, params, t_grid=None,
                   chunk_size: Optional[int] = None,
                   obs_indices=None):
    """Mean log10 M* for halos of z=0 mass ``log_mh0``.

    Parameters
    ----------
    obs_indices : int array, optional
        Grid indices (>= 1) of the observation epochs; default: the
        final grid point only, returned as shape ``(n,)``.  With K
        explicit indices the return is ``(n, K)`` — the multi-epoch
        readout that makes the MAH parameters identifiable (the z=0
        SMF alone is degenerate along assembly-history directions;
        early-epoch mass functions are what pin them down, the same
        reason diffstar fits use multi-redshift data).
    chunk_size : int, optional
        Tile the halo axis with a rematerialized ``lax.scan`` so the
        (n, T) history table never exceeds ``chunk_size * T`` elements
        in HBM — required at 1e8+ halos (T=16 histories at 1e8 halos
        would otherwise be a 6.4 GB intermediate, plus VJP residuals).
    """
    log_mh0 = jnp.asarray(log_mh0)
    if t_grid is None:
        t_grid = default_time_grid()
    squeeze = obs_indices is None
    if squeeze:
        obs_indices = (t_grid.shape[0] - 1,)
    _check_obs_indices(obs_indices, t_grid)
    obs_indices = jnp.asarray(obs_indices)
    n_obs = obs_indices.shape[0]
    n = log_mh0.shape[0]
    if chunk_size is None or n <= chunk_size:
        out = _mean_log_mstar_block(log_mh0, params, t_grid,
                                    obs_indices)
        return out[:, 0] if squeeze else out

    # Ragged tail: pad to the next chunk multiple with the neutral
    # sentinel (> 100 -> _PAD_OUT, zero contribution downstream) and
    # slice back.  Matters inside shard_map, where the shard-local N
    # is set by the mesh, not the caller, and need not be a chunk
    # multiple.
    lm, _ = pad_to_multiple(log_mh0, chunk_size, pad_value=_PAD_LOGM)
    n_pad = lm.shape[0]

    @jax.checkpoint
    def body(_, lm_chunk):
        return None, _mean_log_mstar_block(lm_chunk, params, t_grid,
                                           obs_indices)

    _, out = lax.scan(body, None,
                      lm.reshape(n_pad // chunk_size, chunk_size))
    out = out.reshape(n_pad, n_obs)[:n]
    return out[:, 0] if squeeze else out


def scatter_sigma(log_mh0, params):
    """Mass-dependent log-normal scatter width, floored away from 0."""
    p = GalhaloHistParams(*params)
    pad = log_mh0 > 100.0
    sig = p.sigma_0 + p.sigma_slope * (jnp.where(pad, 13.0, log_mh0)
                                       - 13.0)
    return jnp.clip(sig, 0.02)


def _chunk_epoch_smfs(lm_chunk, params, aux, obs_indices):
    """One chunk's (K, B) partial SMF stack — history integration,
    epoch readout, and the erf-CDF binned reduction all inside the
    chunk, so nothing of size O(chunk·K) ever escapes the caller's
    rematerialized scan."""
    logsm = _mean_log_mstar_block(lm_chunk, params, aux["time_grid"],
                                  obs_indices)           # (c, K)
    sigma = scatter_sigma(lm_chunk, params)              # (c,)
    return jnp.stack([
        binned_density(logsm[:, k], aux["bin_edges"], sigma,
                       aux["volume"],
                       backend=aux.get("backend", "auto"),
                       bin_mode=aux.get("bin_mode", "dense"),
                       bin_window=aux.get("bin_window"))
        for k in range(logsm.shape[1])])                 # (K, B)


def _multi_epoch_smf(log_mh, params, aux):
    """Concatenated SMFs at every observation epoch (the sumstats).

    Chunked execution folds the binned reduction *into* the
    rematerialized chunk scan: each chunk contributes a (K, B)
    partial-density stack to the running total, so peak memory is
    O(N + chunk·T) regardless of the epoch count — no (N, K) epoch
    readout or (N,) sigma array is ever materialized (the O(N·K)
    floor that previously capped this model at ~1e8 halos per chip).
    """
    log_mh = jnp.asarray(log_mh)
    chunk_size = aux.get("chunk_size")
    _check_obs_indices(aux["obs_indices"], aux["time_grid"])
    obs_indices = jnp.asarray(aux["obs_indices"])
    if chunk_size is None or log_mh.shape[0] <= chunk_size:
        return _chunk_epoch_smfs(log_mh, params, aux,
                                 obs_indices).reshape(-1)

    # Ragged tail: the sentinel pad is neutral through the whole
    # fused body (history -> _PAD_OUT readout -> zero erf counts).
    lm, _ = pad_to_multiple(log_mh, chunk_size, pad_value=_PAD_LOGM)

    # Remat the fused body: its VJP would otherwise save each chunk's
    # (c, T) history and (B+1, c) cdf residuals — exactly the memory
    # the chunking exists to bound.
    @jax.checkpoint
    def body(acc, lm_chunk):
        return acc + _chunk_epoch_smfs(lm_chunk, params, aux,
                                       obs_indices), None

    n_bins = jnp.shape(aux["bin_edges"])[0] - 1
    init = pvary_like(jnp.zeros((obs_indices.shape[0], n_bins),
                                dtype=jnp.result_type(float)), log_mh)
    acc, _ = lax.scan(body, init,
                      lm.reshape(-1, chunk_size))
    return acc.reshape(-1)


#: Default ``sigma_max`` bound for ``bin_mode="auto"``: the TRUTH
#: scatter (sigma_0 = 0.2) plus the mass-slope excursion over the
#: sampled halo range — bench.py's fused-window convention for this
#: model.
DEFAULT_SIGMA_MAX = 0.32


def make_galhalo_hist_data(num_halos=100_000,
                           comm: Optional[MeshComm] = None,
                           chunk_size: Optional[int] = None,
                           bin_edges=None, volume_per_halo=50.0,
                           n_times: int = 16, obs_indices=(7, 12, 15),
                           backend: str = "auto",
                           bin_mode: str = "dense",
                           bin_window: Optional[int] = None,
                           sigma_max: Optional[float] = None):
    """Build the history-model fit's aux_data dict.

    The target — the SMF at each of the ``obs_indices`` epochs of the
    time grid (default: three epochs, ~2.0 / 6.5 / 13.8 Gyr with the
    default 16-point grid) — is computed at TRUTH on the global
    catalog before sharding (the golden-vector convention of
    ``/root/reference/tests/test_mpi.py:44-48``), with the same kernel
    backend the fit will use.  ``bin_mode="fused"`` routes the binned
    reduction through the windowed scatter-into-bins kernel with the
    static ``bin_window`` (see :func:`multigrad_tpu.ops.binned
    .fused_bin_window`) — the win grows with the bin count, so
    fine-grained multi-epoch binnings are where to use it.
    ``bin_mode="auto"`` / ``chunk_size="auto"`` defer to the
    autotuner's tuning table (:mod:`multigrad_tpu.tune`; resolved at
    model construction, historical defaults on a cold table);
    ``sigma_max`` bounds the fused window auto may pick (default
    :data:`DEFAULT_SIGMA_MAX`).
    """
    if bin_edges is None:
        bin_edges = jnp.linspace(7.0, 11.75, 14)
    bin_edges = jnp.asarray(bin_edges)
    t_grid = default_time_grid(n_times)
    log_mh = sample_log_halo_masses(num_halos)
    volume = volume_per_halo * num_halos

    if bin_mode == "auto" and sigma_max is None:
        sigma_max = DEFAULT_SIGMA_MAX
    if bin_mode in ("auto", "fused") and bin_window is None \
            and sigma_max is not None:
        from ..ops.binned import fused_bin_window
        bin_window = fused_bin_window(np.asarray(bin_edges),
                                      float(sigma_max))

    aux = dict(
        bin_edges=bin_edges,
        time_grid=t_grid,
        # Static tuple (not an array): epoch indices are
        # configuration, so they stay concrete in the jitted
        # program's closure instead of riding as a traced leaf.
        obs_indices=tuple(int(i) for i in obs_indices),
        volume=volume,
        chunk_size=chunk_size,
        backend=backend,
        bin_mode=bin_mode,
        bin_window=bin_window,
    )
    if sigma_max is not None:
        aux["sigma_max"] = float(sigma_max)
    # The golden target must be computed on concrete knobs: "auto"
    # resolves only at model construction (tuning-table lookup), and
    # a str chunk_size would break the chunking arithmetic — any
    # bin_mode yields identical float32 target values anyway.
    target_aux = dict(aux)
    if target_aux.get("bin_mode") == "auto":
        target_aux["bin_mode"] = "dense"
    if target_aux.get("chunk_size") == "auto":
        target_aux["chunk_size"] = None
    aux["target_sumstats"] = _multi_epoch_smf(log_mh, TRUTH,
                                              target_aux)

    if comm is not None:
        log_mh = scatter_nd(log_mh, axis=0, comm=comm,
                            pad_value=_PAD_LOGM)

    aux["log_halo_masses"] = log_mh
    return aux


@dataclass
class GalhaloHistModel(OnePointModel):
    """Ten-parameter MAH + SFH fit to the stellar mass function.

    Same OnePointModel contract as every family
    (``/root/reference/multigrad/multigrad.py:212-223``): partial
    sumstats per shard, additive totals, loss from totals.  The
    per-particle scatter widths ride the vec-sigma erf kernel.
    """

    aux_data: dict = field(default_factory=dict)

    def __post_init__(self):
        # "auto" perf knobs resolve through the autotuner's tuning
        # table once, at construction, before any program is built
        # (tracer-safe: only shapes are read; in-trace aux rebinds
        # see the already-concrete statics and skip straight
        # through).  Cold table = historical defaults.
        if isinstance(self.aux_data, dict):
            from ..tune.resolve import resolve_auto_aux
            self.aux_data = resolve_auto_aux(
                type(self).__name__, self.aux_data, self.comm)
        # Epoch indices are configuration, not data: an array-typed
        # aux leaf would be promoted to a traced jit argument by the
        # model core (core/model.py:_split_aux), defeating the static
        # range check.  Normalize concrete arrays to the static-tuple
        # convention make_galhalo_hist_data uses.
        oi = self.aux_data.get("obs_indices")
        if oi is not None and not isinstance(oi, jax.core.Tracer):
            # atleast_1d: a scalar / 0-d "single epoch" spec is valid
            # configuration — without the lift, iterating a 0-d array
            # raises an opaque "iteration over a 0-d array" TypeError.
            self.aux_data = dict(self.aux_data,
                                 obs_indices=tuple(
                                     int(i) for i in
                                     np.atleast_1d(np.asarray(oi))))
        super().__post_init__()

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        aux = self.aux_data
        return _multi_epoch_smf(jnp.asarray(aux["log_halo_masses"]),
                                params, aux)

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        # Floored log: early-epoch high-mass bins can be genuinely
        # empty (nothing that massive has formed yet), and log10(0)
        # would poison the whole loss; bins empty in both prediction
        # and target then contribute exactly 0.
        target = jnp.asarray(self.aux_data["target_sumstats"])

        def lg(x):
            return jnp.log10(jnp.clip(x, 1e-12))

        return jnp.mean((lg(sumstats) - lg(target)) ** 2)
