"""Galaxy–halo model family: smooth SHMR + scatter, fit to the SMF.

The reference's north star names "diffmah/diffstar galaxy–halo model,
1e8 halos" as a target workload (``BASELINE.json`` config 4) but
contains no such model; this module supplies the family in the
diffmah idiom — sigmoid-controlled smooth parametric forms, every
parameter differentiable — on the reference's ``OnePointModel``
contract (``/root/reference/multigrad/multigrad.py:212-223``).

The stellar-to-halo-mass relation (SHMR) is a smoothly-broken double
power law: the local slope interpolates between ``alpha_lo`` (faint
end) and ``alpha_hi`` (bright end) through a sigmoid at
``logmh_crit``, which integrates to a closed form with a softplus —
no branches, XLA-friendly, curvature everywhere finite:

    slope(x)  = α_lo + (α_hi − α_lo) · sigmoid(k·x),  x = log Mh − log Mh_crit
    logsm(x)  = logsm_crit + α_lo·x + (α_hi − α_lo)/k · softplus(k·x)
                − (α_hi − α_lo)/k · softplus(0)          [so logsm(0) = logsm_crit]

Log-normal scatter ``sigma_logsm`` about the mean relation enters the
binned SMF analytically through the erf-CDF kernel
(:mod:`multigrad_tpu.ops.binned`) — no Monte Carlo sampling, exact
gradients.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core.model import OnePointModel
from ..ops.binned import binned_density
from ..parallel.collectives import scatter_nd
from ..parallel.mesh import MeshComm

_SLOPE_K = 2.0  # fixed sigmoid sharpness of the slope transition


class GalhaloParams(NamedTuple):
    """Five-parameter smooth SHMR + scatter."""
    logsm_crit: float = 10.5    # log M* at the critical halo mass
    logmh_crit: float = 12.5    # log Mh of the slope transition
    alpha_lo: float = 2.0       # faint-end slope (steep)
    alpha_hi: float = 0.5       # bright-end slope (shallow)
    sigma_logsm: float = 0.2    # log-normal scatter in log M*


TRUTH = GalhaloParams()


def mean_logsm(log_mh, params):
    """Mean log stellar mass of a halo of mass ``log_mh`` (see module
    docstring for the closed form)."""
    p = GalhaloParams(*params)
    x = jnp.asarray(log_mh) - p.logmh_crit
    dalpha = p.alpha_hi - p.alpha_lo
    softplus = jax.nn.softplus
    return (p.logsm_crit + p.alpha_lo * x
            + dalpha / _SLOPE_K * (softplus(_SLOPE_K * x)
                                   - softplus(0.0)))


def sample_log_halo_masses(num_halos=100_000, logmh_min=11.0,
                           logmh_max=15.0, slope=-1.5):
    """Deterministic power-law halo mass function sample.

    Inverse-CDF of ``dn/dM ∝ M^slope`` over ``[10^logmh_min,
    10^logmh_max)`` on a uniform grid — synthetic and in-process like
    the reference's fixture
    (``/root/reference/tests/smf_example/smf_grad_descent.py:23-28``),
    but spanning the cluster-scale dynamic range config 4 implies.
    """
    q = jnp.linspace(0.0, 1.0, num_halos, endpoint=False)
    a = slope + 1.0
    m_lo, m_hi = 10.0 ** logmh_min, 10.0 ** logmh_max
    masses = (m_lo ** a + q * (m_hi ** a - m_lo ** a)) ** (1.0 / a)
    return jnp.log10(masses)


def make_galhalo_data(num_halos=100_000, comm: Optional[MeshComm] = None,
                      chunk_size: Optional[int] = None,
                      bin_edges=None, volume_per_halo=50.0,
                      backend: str = "auto"):
    """Build the galaxy–halo fit's aux_data dict.

    The target SMF is computed at TRUTH on the global catalog before
    sharding (the build-time analog of the reference's golden vector,
    ``test_mpi.py:44-48``).
    """
    if bin_edges is None:
        bin_edges = jnp.linspace(9.0, 12.0, 13)
    bin_edges = jnp.asarray(bin_edges)
    log_mh = sample_log_halo_masses(num_halos)
    volume = volume_per_halo * num_halos

    # Same backend as the model will use: the golden target and the
    # fit's sumstats must come from the same kernel (the two paths
    # agree only to ~2e-3 relative).
    target = binned_density(mean_logsm(log_mh, TRUTH), bin_edges,
                            TRUTH.sigma_logsm, volume,
                            chunk_size=chunk_size, backend=backend)

    if comm is not None:
        # Pad with a large *finite* mass: mean_logsm(+inf) would be
        # inf − inf = NaN (softplus(inf) times a negative Δα), while
        # 1e9 maps to logsm ≈ α_hi·1e9 — far beyond every bin edge,
        # so the erf kernel's forward contribution and gradient are
        # both exactly 0 (the pdf underflows).
        log_mh = scatter_nd(log_mh, axis=0, comm=comm, pad_value=1e9)

    return dict(
        log_halo_masses=log_mh,
        bin_edges=bin_edges,
        volume=volume,
        target_sumstats=target,
        chunk_size=chunk_size,
        backend=backend,
    )


@dataclass
class GalhaloModel(OnePointModel):
    """Five-parameter SHMR fit to the stellar mass function.

    The same execution shape as :class:`~multigrad_tpu.models.smf
    .SMFModel` — one fused erf-CDF pass per shard, totals by in-graph
    psum — with the richer diffmah-style parametrization.
    """

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        aux = self.aux_data
        p = GalhaloParams(*params)
        logsm = mean_logsm(jnp.asarray(aux["log_halo_masses"]), p)
        return binned_density(logsm, aux["bin_edges"], p.sigma_logsm,
                              aux["volume"],
                              chunk_size=aux.get("chunk_size"),
                              backend=aux.get("backend", "auto"))

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.asarray(self.aux_data["target_sumstats"])
        return jnp.mean((jnp.log10(sumstats) - jnp.log10(target)) ** 2)
