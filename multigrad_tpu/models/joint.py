"""Joint SMF + wp(rp) likelihood — the paper's north-star workload.

The whole point of additive sumstats is that *different probes*
compose: an abundance measurement (the SMF's erf-CDF binned counts)
and a clustering measurement (wp(rp)'s ring-sharded pair counts,
:mod:`multigrad_tpu.ops.pairwise`) each reduce to a per-shard partial
sum, so their joint likelihood is one fused SPMD program over one
shared mesh.  This module packages that composition as a single
factory, :func:`make_joint_smf_wprp`:

* :class:`~multigrad_tpu.models.smf.SMFChi2Model` reads joint slots
  ``(log_shmrat, sigma_logsm)``;
* :class:`~multigrad_tpu.models.wprp.WprpModel` reads joint slots
  ``(log_shmrat, log_softness)``;
* :func:`~multigrad_tpu.core.group.param_view` wires each into the
  shared 3-vector, and the returned fused
  :class:`~multigrad_tpu.core.group.OnePointGroup` serves, sweeps,
  and samples through every solo-model entry point (the group's
  serving surface) — including fleet workers, via the
  ``"multigrad_tpu.models.joint:make_joint_smf_wprp"`` model spec.

Both probes share the halo catalog's ``log_shmrat`` truth (-2.0), so
the joint posterior is a genuine multi-probe constraint, not two
disjoint fits stapled together.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.group import OnePointGroup, param_view
from .smf import SMFChi2Model, make_smf_data
from .wprp import WprpModel, make_wprp_data

__all__ = ["JOINT_PARAM_NAMES", "JOINT_TRUTH", "make_joint_smf_wprp"]

#: Joint parameter vector layout.
JOINT_PARAM_NAMES = ("log_shmrat", "sigma_logsm", "log_softness")

#: Truth values of the joint vector (SMF truth + wp(rp) truth; the
#: shared slot agrees by construction).
JOINT_TRUTH = np.array([-2.0, 0.2, -1.0])


def make_joint_smf_wprp(num_halos: int = 2048,
                        smf_num_halos: Optional[int] = None,
                        comm="auto",
                        seed: int = 0,
                        smf_kwargs: Optional[dict] = None,
                        wprp_kwargs: Optional[dict] = None
                        ) -> OnePointGroup:
    """Build the fused joint SMF+wp(rp) group on one shared comm.

    Parameters
    ----------
    num_halos : int
        wp(rp) mock size (pair counting is O(N²); keep modest).
    smf_num_halos : int, optional
        SMF halo sample size (defaults to ``4 * num_halos`` — the
        SMF kernel is O(N), so it affords a larger sample).
    comm : MeshComm | None | "auto"
        The shared communicator.  ``"auto"`` (the fleet-worker
        default): the global single-axis comm when this process has
        more than one device, else ``None``.
    seed : int
        wp(rp) mock realization seed.
    smf_kwargs, wprp_kwargs : dict, optional
        Extra keyword arguments forwarded to
        :func:`~multigrad_tpu.models.smf.make_smf_data` /
        :func:`~multigrad_tpu.models.wprp.make_wprp_data`.
    """
    if comm == "auto":
        import jax

        from ..parallel.mesh import global_comm
        comm = global_comm() if len(jax.devices()) > 1 else None
    smf_n = int(smf_num_halos) if smf_num_halos is not None \
        else 4 * int(num_halos)
    smf = SMFChi2Model(
        aux_data=make_smf_data(smf_n, comm=comm,
                               **(smf_kwargs or {})),
        comm=comm)
    wprp = WprpModel(
        aux_data=make_wprp_data(int(num_halos), comm=comm, seed=seed,
                                **(wprp_kwargs or {})),
        comm=comm)
    return OnePointGroup(models=(
        param_view(smf, (0, 1)),     # (log_shmrat, sigma_logsm)
        param_view(wprp, (0, 2)),    # (log_shmrat, log_softness)
    ))
