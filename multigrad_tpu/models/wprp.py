"""Projected two-point correlation model — the clustering workload.

The reference's north-star workloads include a 2pt-correlation
likelihood and a joint SMF + wp(rp) fit (``BASELINE.json`` configs
3 and 5) but ship no clustering code; this model supplies it on the
same :class:`~multigrad_tpu.core.model.OnePointModel` contract the
reference defines (``/root/reference/multigrad/multigrad.py:212-223``):
partial sumstats additive over shards, loss from totals.

Physics shape: a galaxy-selection model over a fixed halo catalog.
Parameters control each halo's *selection weight* (a smooth sigmoid
cut in stellar mass); the sumstats are the weighted DD pair counts in
projected-separation bins plus the total selected weight; the loss
compares the derived wp(rp) to a target.  Gradients flow through the
weights and around the ``lax.ppermute`` ring
(:mod:`multigrad_tpu.ops.pairwise`).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core.model import OnePointModel
from ..ops.pairwise import ring_weighted_pair_counts, wp_from_counts
from ..parallel.collectives import scatter_nd
from ..parallel.mesh import MeshComm


class WprpParams(NamedTuple):
    """log stellar-to-halo-mass ratio + log selection softness.

    (A cut *location* parameter would be exactly degenerate with
    ``log_shmrat`` — only their difference would enter the weights —
    so the second parameter is the cut's transition width instead.)
    """
    log_shmrat: float = -2.0
    log_softness: float = -1.0


TRUTH = WprpParams()
LOGSM_CUT = 8.6


def make_galaxy_mock(num_halos=2048, box_size=100.0, seed=0,
                     satellites_per_parent=4, sat_sigma=1.5):
    """Deterministic clustered mock: uniform parents + NFW-ish satellite
    clouds, with satellites assigned lower halo masses.

    The mass–clustering correlation is what makes wp(rp)
    parameter-sensitive: raising the stellar-mass cut removes
    satellites first, suppressing the small-scale (one-halo) signal.
    Synthetic and in-process, like the reference's power-law halo
    fixture (``/root/reference/tests/smf_example/smf_grad_descent.py:23-28``).
    """
    n_parents = max(1, num_halos // (1 + satellites_per_parent))
    n_sats = num_halos - n_parents
    kp, ks, km = jax.random.split(jax.random.PRNGKey(seed), 3)

    parent_pos = jax.random.uniform(kp, (n_parents, 3)) * box_size
    host = jnp.arange(n_sats) % n_parents
    offsets = jax.random.normal(ks, (n_sats, 3)) * sat_sigma
    sat_pos = (parent_pos[host] + offsets) % box_size

    # Parents: truncated power law in [1e10.5, 1e12); satellites: [1e10, 1e11)
    q = jnp.linspace(0.0, 0.95, n_parents)
    parent_logm = 10.5 + 1.5 * (1 - (1 - q) ** 2)
    sat_logm = 10.0 + jax.random.uniform(km, (n_sats,))

    positions = jnp.concatenate([parent_pos, sat_pos])
    log_mass = jnp.concatenate([parent_logm, sat_logm])
    return positions, log_mass


def selection_weights(log_mass, params):
    """Smooth selection probability of each halo's galaxy.

    ``sigmoid((log M* − cut) / softness)`` with
    ``log M* = log M_h + log_shmrat`` and ``softness =
    10**log_softness`` — differentiable wrt both parameters (the hard
    step's smooth relaxation).
    """
    p = WprpParams(*params)
    logsm = log_mass + p.log_shmrat
    return jax.nn.sigmoid((logsm - LOGSM_CUT) / 10.0 ** p.log_softness)


def shard_catalog(positions, log_mass, comm: Optional[MeshComm]):
    """Pad a (positions, log_mass) catalog to shard evenly and scatter
    it over `comm`; returns ``(positions, log_mass, ring_axis)``.

    Weight-0 padding is exactly neutral for every pair count.  The
    mass pad must be a large *finite* value: -inf would give sigmoid
    argument -inf, whose VJP chain is 0 * inf = NaN; at -1e9 the
    sigmoid underflows to exactly 0 with gradient 0.
    """
    if comm is None:
        return positions, log_mass, None
    return (scatter_nd(positions, axis=0, comm=comm, pad_value=0.0),
            scatter_nd(log_mass, axis=0, comm=comm, pad_value=-1e9),
            comm.axis_name)


def make_wprp_data(num_halos=2048, box_size=100.0, pimax=20.0,
                   comm: Optional[MeshComm] = None,
                   rp_bin_edges=None, row_chunk: Optional[int] = None,
                   seed=0, backend: str = "auto"):
    """Build the wp(rp) fit's aux_data dict.

    The target wp is computed at the TRUTH parameters on the host
    (single-block path) before sharding — the analog of the
    reference's golden target vector (``test_mpi.py:44-48``), except
    derived at build time because it depends on the mock realization.
    """
    if rp_bin_edges is None:
        rp_bin_edges = jnp.logspace(-0.5, 1.2, 9)
    rp_bin_edges = jnp.asarray(rp_bin_edges)
    positions, log_mass = make_galaxy_mock(num_halos, box_size,
                                           seed=seed)

    w_truth = selection_weights(log_mass, TRUTH)
    # Same backend as the model will use: target and sumstats must
    # come from the same kernel (the paths agree only to ~2e-3).
    dd = ring_weighted_pair_counts(positions, w_truth, rp_bin_edges,
                                   box_size=box_size, pimax=pimax,
                                   row_chunk=row_chunk, backend=backend)
    target_wp = wp_from_counts(dd, jnp.sum(w_truth), rp_bin_edges,
                               pimax, box_size ** 3)

    positions, log_mass, ring_axis = shard_catalog(positions, log_mass,
                                                   comm)

    return dict(
        positions=positions,
        log_mass=log_mass,
        rp_bin_edges=rp_bin_edges,
        pimax=pimax,
        box_size=box_size,
        target_wp=target_wp,
        ring_axis=ring_axis,   # str/None -> static in the SPMD closure
        row_chunk=row_chunk,   # int/None -> static
        backend=backend,       # "xla" | "pallas" -> static
    )


@dataclass
class WprpModel(OnePointModel):
    """wp(rp) clustering fit over a ring-sharded halo catalog.

    Sumstats layout: ``[DD_0 … DD_{B-1}, W]`` — per-bin weighted DD
    partial counts plus this shard's selected weight, all additive.
    """

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        aux = self.aux_data
        # log_mass = -1e9 padding gives weight exactly 0 (neutral in
        # forward and backward passes; see make_wprp_data)
        w = selection_weights(jnp.asarray(aux["log_mass"]), params)
        dd = ring_weighted_pair_counts(
            jnp.asarray(aux["positions"]), w, aux["rp_bin_edges"],
            axis_name=aux["ring_axis"], box_size=aux["box_size"],
            pimax=aux["pimax"], row_chunk=aux["row_chunk"],
            backend=aux.get("backend", "auto"))
        return jnp.concatenate([dd, jnp.sum(w)[None]])

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        aux = self.aux_data
        dd, w_tot = sumstats[:-1], sumstats[-1]
        box_volume = aux["box_size"] ** 3
        wp = wp_from_counts(dd, w_tot, aux["rp_bin_edges"],
                            aux["pimax"], box_volume)
        target = jnp.asarray(aux["target_wp"])
        scale = jnp.mean(target ** 2)
        return jnp.mean((wp - target) ** 2) / scale


@dataclass
class XiModel(OnePointModel):
    """3D two-point correlation fit: the diffdesi-style clustering
    likelihood (BASELINE config 3).

    Same selection model and additive-sumstat layout as
    :class:`WprpModel` (``[DD_0 .. DD_{B-1}, W]``) with 3D separation
    bins (no line-of-sight cut); the loss compares ``xi(r)`` from the
    analytic-RR natural estimator to a target.
    """

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        aux = self.aux_data
        w = selection_weights(jnp.asarray(aux["log_mass"]), params)
        dd = ring_weighted_pair_counts(
            jnp.asarray(aux["positions"]), w, aux["bin_edges"],
            axis_name=aux["ring_axis"], box_size=aux["box_size"],
            backend=aux.get("backend", "auto"))
        return jnp.concatenate([dd, jnp.sum(w)[None]])

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        from ..ops.pairwise import xi_from_counts
        aux = self.aux_data
        dd, w_tot = sumstats[:-1], sumstats[-1]
        xi = xi_from_counts(dd, w_tot, aux["bin_edges"],
                            aux["box_size"] ** 3)
        target = jnp.asarray(aux["target_xi"])
        return jnp.mean((xi - target) ** 2 / (1.0 + target ** 2))


def make_xi_data(num_halos=2048, box_size=75.0,
                 comm: Optional[MeshComm] = None, bin_edges=None,
                 seed=0, backend: str = "auto"):
    """Build the xi(r) fit's aux_data dict (target at TRUTH params,
    computed single-block before sharding — cf. :func:`make_wprp_data`)."""
    from ..ops.pairwise import xi_from_counts

    if bin_edges is None:
        bin_edges = jnp.logspace(-0.3, 1.1, 8)
    bin_edges = jnp.asarray(bin_edges)
    positions, log_mass = make_galaxy_mock(num_halos, box_size,
                                           seed=seed)

    w_truth = selection_weights(log_mass, TRUTH)
    # Same-kernel invariant as make_wprp_data's target.
    dd = ring_weighted_pair_counts(positions, w_truth, bin_edges,
                                   box_size=box_size, backend=backend)
    target_xi = xi_from_counts(dd, jnp.sum(w_truth), bin_edges,
                               box_size ** 3)

    positions, log_mass, ring_axis = shard_catalog(positions, log_mass,
                                                   comm)
    return dict(positions=positions, log_mass=log_mass,
                bin_edges=bin_edges, box_size=box_size,
                target_xi=target_xi, ring_axis=ring_axis,
                backend=backend)
