"""Stellar-mass-function model — the flagship end-to-end workload.

TPU-native port of the reference's canonical example
(``/root/reference/tests/smf_example/smf_grad_descent.py``): a two-
parameter galaxy–halo model (log stellar-to-halo-mass ratio + scatter)
fit to a 10-bin stellar mass function, distributed over the particle
(halo) axis.

The sumstats kernel uses :mod:`multigrad_tpu.ops.binned` — one fused
pass over the halos instead of the reference's per-bin Python loop.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..core.model import OnePointModel
from ..ops.binned import binned_density, fused_bin_window
from ..parallel.collectives import scatter_nd
from ..parallel.mesh import MeshComm

#: Default ``sigma_max`` bound for ``bin_mode="auto"`` (the largest
#: scatter the canonical SMF fits reach — bench.py's fused-window
#: convention); override per fit from ``param_bounds``.
DEFAULT_SIGMA_MAX = 0.6

# SMF target at truth params (-2.0, 0.2): the reference's golden
# regression fixture, rank/shard-count-invariant by additivity
# (/root/reference/tests/test_mpi.py:44-47).
TARGET_SUMSTATS = np.array([
    2.30178721e-02, 1.69728529e-02, 1.16054425e-02, 7.10532581e-03,
    3.77187086e-03, 1.69136131e-03, 6.28149020e-04, 1.90466686e-04,
    4.66692982e-05, 9.17260695e-06])


class ParamTuple(NamedTuple):
    """Parity: ``smf_grad_descent.py:17-19``."""
    log_shmrat: float = -2.0
    sigma_logsm: float = 0.2


def load_halo_masses(num_halos=10_000, slope=-2, mmin=10.0 ** 10,
                     qmax=0.95):
    """Truncated power-law halo mass sample (parity:
    ``smf_grad_descent.py:23-28``), as one *global* array.

    The reference ``np.array_split``s this across MPI ranks; here
    sharding happens via :func:`make_smf_data`'s ``scatter_nd``.
    """
    q = jnp.linspace(0, qmax, num_halos)
    return mmin * (1 - q) ** (1 / (slope + 1))


def make_smf_data(num_halos=10_000, comm: Optional[MeshComm] = None,
                  chunk_size: Optional[int] = None,
                  backend: str = "auto", bin_mode: str = "dense",
                  bin_window: Optional[int] = None,
                  sigma_max: Optional[float] = None):
    """Build the SMF fit's aux_data dict (parity:
    ``smf_grad_descent.py:93-101`` / ``test_mpi.py:40-48``).

    With a ``comm``, halo masses are padded (with ``inf`` — neutral
    for the erf-CDF counts) to shard evenly and scattered over the
    comm's mesh axis.  ``backend="pallas"`` routes the sumstats kernel
    through the hand-written Pallas op (:mod:`multigrad_tpu.ops
    .pallas_kernels`).  ``bin_mode="fused"`` selects the windowed
    scatter-into-bins kernel; ``bin_window`` is its static edge
    window (derive with :func:`multigrad_tpu.ops.binned
    .fused_bin_window` from the largest sigma the fit can reach —
    both are plain Python values, so they stay static configuration
    in the compiled program).  ``bin_mode="auto"`` defers the choice
    to the autotuner's tuning table (:mod:`multigrad_tpu.tune` —
    resolved at model construction, dense on a cold table);
    ``sigma_max`` bounds the fused window it may pick (default
    :data:`DEFAULT_SIGMA_MAX`).  ``chunk_size="auto"`` resolves the
    same way (``None`` cold).
    """
    log_mh = jnp.log10(load_halo_masses(num_halos))
    if comm is not None:
        log_mh = scatter_nd(log_mh, axis=0, comm=comm,
                            pad_value=jnp.inf)
    edges = jnp.linspace(9, 10, 11)
    if bin_mode == "auto" and sigma_max is None:
        sigma_max = DEFAULT_SIGMA_MAX
    if bin_mode in ("auto", "fused") and bin_window is None \
            and sigma_max is not None:
        bin_window = fused_bin_window(np.asarray(edges),
                                      float(sigma_max))
    out = dict(
        log_halo_masses=log_mh,
        smf_bin_edges=edges,
        volume=10.0 * num_halos,  # Mpc^3/h^3
        target_sumstats=jnp.asarray(TARGET_SUMSTATS),
        chunk_size=chunk_size,
        backend=backend,
        bin_mode=bin_mode,
        bin_window=bin_window,
    )
    if sigma_max is not None:
        out["sigma_max"] = float(sigma_max)
    return out


@dataclass
class SMFModel(OnePointModel):
    """Two-parameter SMF model (parity: ``smf_grad_descent.py:52-82``)."""

    aux_data: dict = field(default_factory=dict)

    def __post_init__(self):
        # "auto" perf knobs (bin_mode / chunk_size) resolve through
        # the autotuner's tuning table ONCE, here, before any program
        # is built — so the compiled program sees concrete statics and
        # in-trace aux rebinds (_local_model) never re-resolve.  A
        # cold table resolves to the historical defaults.
        if isinstance(self.aux_data, dict):
            from ..tune.resolve import resolve_auto_aux
            self.aux_data = resolve_auto_aux(
                type(self).__name__, self.aux_data, self.comm)
        super().__post_init__()

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        """SMF of this shard's halos — totals sum over shards."""
        params = ParamTuple(*params)
        log_mh = jnp.asarray(self.aux_data["log_halo_masses"])
        bin_edges = jnp.asarray(self.aux_data["smf_bin_edges"])
        volume = self.aux_data["volume"]
        chunk_size = self.aux_data.get("chunk_size")

        mean_logsm = log_mh + params.log_shmrat
        return binned_density(mean_logsm, bin_edges, params.sigma_logsm,
                              volume, chunk_size=chunk_size,
                              backend=self.aux_data.get("backend", "auto"),
                              bin_mode=self.aux_data.get("bin_mode",
                                                         "dense"),
                              bin_window=self.aux_data.get("bin_window"))

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        """MSE in log10 space (parity: ``smf_grad_descent.py:78-82``)."""
        target = jnp.log10(jnp.asarray(self.aux_data["target_sumstats"]))
        return jnp.mean((jnp.log10(sumstats) - target) ** 2)


@dataclass
class SMFChi2Model(SMFModel):
    """SMF model with a Gaussian (½ χ²) likelihood — posterior-ready.

    The parity model's log10-MSE loss is a fitting objective, not a
    negative log-density: it is NaN where a bin empties (``log10(0)``)
    and its scale carries no observational meaning, so sampling
    ``exp(-loss)`` with :func:`multigrad_tpu.inference.run_hmc` (or
    reading absolute Laplace errors off its Fisher) is ill-posed.
    This variant swaps in

        loss = ½ Σ_b ((y_b - t_b) / σ_b)²,    σ_b = sigma_frac · t_b

    — fractional Gaussian errors per SMF bin (``aux_data
    ["sigma_frac"]``, default 5%), finite everywhere, whose Fisher and
    posterior have calibrated units.  The sumstats kernel (and its
    distributed execution) is inherited unchanged.
    """

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.asarray(self.aux_data["target_sumstats"])
        sigma = self.aux_data.get("sigma_frac", 0.05) * target
        return 0.5 * jnp.sum(((sumstats - target) / sigma) ** 2)
