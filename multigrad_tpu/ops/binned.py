"""Binned smoothed-count sumstat kernels, TPU-optimized.

The hot op of the reference workloads is the erf-CDF binned count — a
smoothed histogram of per-particle quantities (the stellar-mass
function, ``/root/reference/tests/smf_example/smf_grad_descent.py:32-48``).
The reference computes it with a Python loop over bins, each bin doing
two full passes over the particle array (cdf at both edges): for B
bins, ``2B·N`` erf evaluations and ``2B`` HBM sweeps.

TPU redesign here:

* **Edge vectorization**: the cdf is evaluated at all ``B+1`` edges in
  one ``(B+1, N)`` broadcast — ``(B+1)·N`` erf evaluations and *one*
  data sweep instead of ``2B·N`` and ``2B`` sweeps — then differenced
  along the edge axis *per halo* before the particle reduction.
  (Diff-then-sum, not sum-then-diff: subtracting two O(N) partial
  sums would lose float32 precision on sparsely-populated bins; the
  per-halo differences are small positives that sum accurately, same
  as the reference's formulation.)
* **Chunking**: the ``(B+1, N)`` broadcast is tiled with ``lax.scan``
  so HBM working-set stays at ``(B+1)·chunk`` regardless of N —
  required at the 1e8–1e9-particle scale (SURVEY §5.7).
* **Neutral padding**: a particle at ``+inf`` contributes cdf 0 at
  every finite edge, so padding (for shardability or chunk
  divisibility) is exactly neutral — see
  :func:`multigrad_tpu.utils.util.pad_to_multiple`.
* **Fused scatter-into-bins** (``bin_mode="fused"``): the dense path
  pays ``(B+1)·N`` erf evaluations even though a particle's Gaussian
  mass is *exactly* zero (in float32 — see :data:`SAT_Z`) outside
  ``±4·√2·sigma`` of its value.  The fused path evaluates the cdf at
  only a static ``bin_window`` of consecutive edges around each
  particle (``searchsorted`` locates the window) and scatter-adds the
  per-particle bin masses into the count vector with a
  ``segment_sum`` — ``O(N·W)`` transcendentals instead of
  ``O(N·B)``, a real win whenever the bin grid is finer than the
  smoothing scale (many-bin histograms, small-scatter models).  With
  an adequate window (:func:`fused_bin_window`) the result matches
  the dense path bin-for-bin *exactly* at float32 (XLA's f32 erf
  clamps its argument to ±4, so every out-of-window cdf saturates to
  the identical constant and dense bin differences are exact zeros).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..parallel._shard_map_compat import pvary_like
from ..utils.util import pad_to_multiple

_SQRT2 = 1.4142135623730951

#: |z| beyond which XLA's float32 erf is *exactly* saturated: the f32
#: lowering clamps its argument to [-4, 4] before the rational
#: approximation, so every |z| >= 4 evaluates to the identical value
#: and cdf *differences* outside a ±4·√2·sigma window are exact zeros.
#: The fused window half-width is ``SAT_Z * √2 * sigma``.
SAT_Z = 4.0

# Sentinel clamp for padded particles.  Padding the particle axis with
# ±inf is forward-neutral (cdf saturates) but poisons the VJP:
# dz/dsigma = ±inf and the zero cotangent gives 0*inf = NaN.  Clipping
# the *values* maps ±inf to ±1e18 — still far beyond any finite bin
# edge (cdf contribution exactly 0/1 at float32) — and clip's gradient
# is exactly 0 for clamped entries, so padded particles contribute
# nothing to forward or backward passes.  1e18 keeps z**2 finite in
# float32 for sigma >= ~0.1 and merely underflows exp(-z**2) to 0
# otherwise.
_PAD_CLIP = 1e18


def _resolve_backend(backend: str) -> str:
    """Resolve the kernel backend, including the "auto" policy.

    "auto" picks the hand-written Pallas kernels on TPU (measured
    faster — see BENCH_NOTES.md) and XLA elsewhere, where compiled
    Mosaic is unavailable and interpret mode would be slow.  Shared by
    :mod:`~multigrad_tpu.ops.binned` and
    :mod:`~multigrad_tpu.ops.pairwise`.
    """
    if backend == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if backend not in ("xla", "pallas"):
        raise ValueError(f"unknown backend {backend!r}; "
                         "expected 'xla', 'pallas' or 'auto'")
    return backend


def norm_cdf(x, mean, sigma):
    """Gaussian CDF — parity with ``calc_smf_cdf``
    (``smf_grad_descent.py:32-35``)."""
    return 0.5 * (1.0 + jax.scipy.special.erf(
        (x - mean) / (_SQRT2 * sigma)))


def _bin_sums(values, edges, sigma):
    """counts[b] = sum_i (cdf(edge_{b+1}) - cdf(edge_b)); one fused pass.

    The cdf matrix is (B+1, N); diff along the edge axis happens
    per-halo (small positive masses) before the N-reduction.
    """
    values = jnp.clip(values, -_PAD_CLIP, _PAD_CLIP)  # see _PAD_CLIP
    z = (edges[:, None] - values[None, :]) / (_SQRT2 * sigma)
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z))
    return jnp.sum(jnp.diff(cdf, axis=0), axis=1)


def fused_bin_window(bin_edges, sigma_max, sat_z: float = SAT_Z) -> int:
    """Minimal static edge window for float32-exact fused binning.

    ``bin_edges`` and ``sigma_max`` must be concrete (the window is a
    static shape in the compiled program).  Returns the number of
    consecutive edges ``W`` such that a window of ``W`` edges starting
    at the last edge <= ``value - sat_z*√2*sigma`` always covers
    ``value + sat_z*√2*sigma`` — outside it, f32 cdf differences are
    exact zeros, so ``bin_mode="fused"`` with this window reproduces
    the dense path bin-for-bin.  ``sigma_max`` is the largest
    smoothing width the kernel will see (for fit parameters, bound it
    from ``param_bounds``).
    """
    edges = np.asarray(bin_edges, np.float64)
    if edges.ndim != 1 or edges.shape[0] < 2:
        raise ValueError("bin_edges must be a 1-D array of >= 2 edges")
    half = float(sat_z) * float(np.sqrt(2.0)) * float(sigma_max)
    dmin = float(np.min(np.diff(edges)))
    if dmin <= 0:
        raise ValueError("bin_edges must be strictly increasing")
    w = int(np.ceil(2.0 * half / dmin)) + 2
    return int(min(max(w, 2), edges.shape[0]))


def window_starts(values, edges, sigma, window: int):
    """Per-particle start edge of the fused window (int32, (N,)).

    The last edge <= ``value - SAT_Z*√2*sigma``, clipped so the
    window of ``window`` consecutive edges stays in range.  Shared by
    the XLA fused path and the Pallas fused kernel (the segment ids of
    the scatter-add are ``starts[:, None] + arange(window - 1)``).
    """
    half = SAT_Z * _SQRT2 * jnp.asarray(sigma)
    start = jnp.searchsorted(edges, values - half, side="right") - 1
    return jnp.clip(start, 0, edges.shape[0] - window).astype(jnp.int32)


def _bin_sums_fused(values, edges, sigma, window: int):
    """Windowed counts: searchsorted + per-particle cdf window +
    scatter-add (``segment_sum``) — the ``bin_mode="fused"`` kernel.

    Each particle evaluates the cdf at ``window`` consecutive edges
    around its value and scatter-adds the ``window - 1`` bin masses;
    out-of-window bins receive exactly what the dense path computes
    for them at float32: zero (see module docstring).  Cost is
    ``O(N·window)`` transcendentals independent of the bin count.

    The scatter runs as ONE row-wise ``segment_sum`` keyed on the
    window *start* (``S[s, w] = Σ_{start_i = s} masses[i, w]``)
    followed by a static ``window - 1``-term diagonal reassembly
    (``counts[b] = Σ_w S[b - w, w]``) — measured 5–6x faster than the
    equivalent elementwise scatter on CPU (contiguous row adds
    vectorize; per-element scatter does not), and *more* accurate:
    each segment accumulates N/|starts| rows instead of
    N·W/|bins| scalars.
    """
    values = jnp.clip(values, -_PAD_CLIP, _PAD_CLIP)  # see _PAD_CLIP
    n_edges = edges.shape[0]
    window = int(min(window, n_edges))
    if window < 2:
        raise ValueError("bin_window must be >= 2")
    sig = jnp.asarray(sigma)
    start = window_starts(values, edges, sig, window)
    offs = start[:, None] + jnp.arange(window, dtype=jnp.int32)[None, :]
    ewin = edges[offs]                                  # (N, W)
    inv = 1.0 / (_SQRT2 * (sig[:, None] if sig.ndim else sig))
    z = (ewin - values[:, None]) * inv
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z))
    masses = jnp.diff(cdf, axis=1)                      # (N, W-1)
    return scatter_bin_masses(masses, start, n_edges)


def scatter_bin_masses(masses, start, n_edges: int):
    """Scatter per-particle window masses into the count vector.

    ``counts[b] = Σ_{i,w} masses[i, w] · [start_i + w == b]`` via the
    row-segment_sum + diagonal-reassembly trick (see
    :func:`_bin_sums_fused`).  Shared by the XLA fused path and the
    Pallas fused kernel's host-side accumulation.
    """
    window_m1 = masses.shape[-1]
    s_rows = jax.ops.segment_sum(masses, start,
                                 num_segments=n_edges)  # (E, W-1)
    out = pvary_like(jnp.zeros(n_edges - 1, masses.dtype), masses)
    for w in range(window_m1):
        out = out.at[w:].add(s_rows[:n_edges - 1 - w, w])
    return out


def binned_erf_counts(values, bin_edges, sigma, chunk_size: Optional[int]
                      = None, backend: str = "auto",
                      bin_mode: str = "dense",
                      bin_window: Optional[int] = None):
    """Smoothed per-bin counts of `values` over `bin_edges`.

    Each particle contributes ``cdf(high) - cdf(low)`` to a bin — the
    probability mass of a Gaussian centered on the particle's value
    with width ``sigma``.  Returns shape ``(len(bin_edges) - 1,)``.

    Parameters
    ----------
    values : (N,) array
        Per-particle values (e.g. mean log stellar masses).
    sigma : scalar or (N,) array
        Gaussian smoothing width per particle.
    chunk_size : int, optional
        Tile the particle axis to bound memory at
        ``(B+1) * chunk_size``.  A ragged tail is padded internally
        with ``inf`` (exactly neutral, see module docstring).
    backend : {"xla", "pallas", "auto"}
        "pallas" routes to the hand-written TPU kernel
        (:func:`multigrad_tpu.ops.pallas_kernels.binned_erf_counts_pallas`;
        scalar or per-particle sigma; analytic custom VJP;
        interpret-mode off-TPU).
        Measured on TPU v5 lite (BENCH_NOTES.md, round 3): at 1e6
        halos the pallas kernel runs the fused Adam fit at parity to
        ~4% faster than the XLA path (both VPU-transcendental-bound);
        at 1e8 halos it is **2.5x** (31.7 vs 12.9 steps/s) — the
        analytic VJP recomputes z on the fly and needs no remat,
        while the XLA chunked path pays the checkpoint recompute.
        "auto" resolves to "pallas" on TPU backends and "xla"
        elsewhere (CPU pallas would run in slow interpret mode).
    bin_mode : {"dense", "fused"}
        "dense" evaluates the cdf at every edge for every particle
        (the historical path).  "fused" evaluates only a
        ``bin_window``-edge window around each particle and
        scatter-adds the masses (see module docstring) — requires
        ``bin_window`` (use :func:`fused_bin_window` to derive the
        float32-exact minimum from concrete edges and the largest
        sigma).  Pays off when the bin grid is finer than the
        smoothing scale; with ``bin_window >= len(bin_edges)`` it is
        the dense result computed the slow way.
    bin_window : int, optional
        Static edge-window size for ``bin_mode="fused"``.

    ``bin_mode="auto"`` resolves through the autotuner's on-disk
    tuning table (:mod:`multigrad_tpu.tune`): the tuned mode for this
    (rows, edges, window) shape on this backend, or ``"dense"`` (the
    historical default) on a cold table.  Models resolve ``"auto"``
    themselves first under their class-named key
    (:func:`multigrad_tpu.tune.resolve.resolve_auto_aux`); this is
    the standalone-op fallback.  Resolution is shape-only and happens
    at trace time — the resolved mode is as static as a hand-set one.
    """
    if bin_mode == "auto":
        from ..tune.resolve import resolve_op_bin_mode
        bin_mode, bin_window = resolve_op_bin_mode(
            jnp.shape(values)[0], jnp.shape(bin_edges)[0], bin_window)
    if bin_mode not in ("dense", "fused"):
        raise ValueError(f"unknown bin_mode {bin_mode!r}; "
                         "expected 'dense', 'fused' or 'auto'")
    if bin_mode == "fused" and bin_window is None:
        raise ValueError(
            "bin_mode='fused' needs a static bin_window (edge count); "
            "derive it with fused_bin_window(bin_edges, sigma_max)")
    fused = bin_mode == "fused"
    requested = backend
    backend = _resolve_backend(backend)
    if requested == "auto" and backend == "pallas":
        from .pallas_kernels import _LANES
        window_eff = (min(int(bin_window), int(jnp.shape(bin_edges)[0]))
                      if fused else 0)
        if ((not fused and jnp.shape(bin_edges)[0] > _LANES)
                or window_eff > _LANES
                or (jnp.ndim(sigma) > 0
                    and jnp.shape(sigma) != jnp.shape(values))):
            # "auto" is a pick-what-works policy: fall back to XLA
            # outside the pallas kernel's envelope — more edges than
            # the accumulator lane row holds (dense kernel; the fused
            # kernel has no edge-count cap but its window must fit
            # the 128-slot block layout), or a broadcastable-but-
            # not-(N,) sigma (e.g. shape (1,)), which XLA's broadcast
            # handles but the kernel's tile layout does not — instead
            # of surfacing the kernel's precondition error.  An
            # explicit backend="pallas" still raises.  (A per-particle
            # (N,) sigma IS in the kernel's envelope — it streams as a
            # second value tile.)
            backend = "xla"
    if backend == "pallas":
        kwargs = {}
        if chunk_size is not None:
            # chunk_size bounds the *HBM* working set on the XLA path;
            # a pallas block lives in VMEM (~128 MB total), so honor
            # the caller's bound only up to a VMEM-safe block — the
            # kernel's grid streams any N through it either way.
            # 2^18 particles = (8, 32768) f32 tiles: ~1 MB per live
            # block, measured safe on v5e including the backward pass.
            kwargs["block_size"] = min(
                -(-chunk_size // 1024) * 1024, 262_144)
        if fused:
            from .pallas_kernels import binned_erf_counts_fused_pallas
            return binned_erf_counts_fused_pallas(
                values, bin_edges, sigma, bin_window, **kwargs)
        from .pallas_kernels import binned_erf_counts_pallas
        return binned_erf_counts_pallas(values, bin_edges, sigma,
                                        **kwargs)
    values = jnp.asarray(values)
    bin_edges = jnp.asarray(bin_edges)

    def bin_fn(vals, sig):
        if fused:
            return _bin_sums_fused(vals, bin_edges, sig, bin_window)
        return _bin_sums(vals, bin_edges, sig)

    if chunk_size is None or values.shape[0] <= chunk_size:
        return bin_fn(values, sigma)

    n = values.shape[0]
    # Ragged tail: pad to the next chunk multiple with +inf — exactly
    # neutral for every count (module docstring) — rather than
    # erroring.  Matters inside shard_map, where the shard-local N is
    # set by the mesh, not the caller.
    values, _ = pad_to_multiple(values, chunk_size, pad_value=jnp.inf)
    n_pad = values.shape[0]
    chunks = values.reshape(n_pad // chunk_size, chunk_size)
    sigma_chunks = None
    if jnp.ndim(sigma) > 0:
        # Any finite positive pad width works: the padded values' cdf
        # saturates identically for all of them.
        sigma_b, _ = pad_to_multiple(jnp.broadcast_to(sigma, (n,)),
                                     chunk_size, pad_value=1.0)
        sigma_chunks = sigma_b.reshape(n_pad // chunk_size, chunk_size)

    # Remat the chunk body: without it the scan's VJP saves each
    # chunk's (B+1, chunk) cdf residuals — O(B·N) memory, defeating
    # the chunking (at 1e9 particles that is ~40 GB).  Recomputing the
    # erf in the backward pass keeps memory at O(N + B·chunk).
    @jax.checkpoint
    def body(acc, inputs):
        if sigma_chunks is None:
            acc = acc + bin_fn(inputs, sigma)
        else:
            chunk, sig = inputs
            acc = acc + bin_fn(chunk, sig)
        return acc, None

    # Under shard_map the body's output is device-varying (it reads
    # the shard's values); the replicated zeros init must be cast to
    # match or the scan's carry types disagree (jax vma typing).
    init = pvary_like(jnp.zeros(bin_edges.shape[0] - 1,
                                dtype=values.dtype), values)
    xs = chunks if sigma_chunks is None else (chunks, sigma_chunks)
    counts, _ = lax.scan(body, init, xs)
    return counts


def binned_density(values, bin_edges, sigma, volume,
                   chunk_size: Optional[int] = None,
                   backend: str = "auto", bin_mode: str = "dense",
                   bin_window: Optional[int] = None):
    """Binned number *density* per unit bin width — the SMF estimator.

    Equivalent to the reference's per-bin
    ``sum(cdf_high - cdf_low) / volume / bin_width``
    (``smf_grad_descent.py:39-48``), computed in one pass.
    ``bin_mode``/``bin_window`` select the fused scatter-into-bins
    kernel (see :func:`binned_erf_counts`).
    """
    counts = binned_erf_counts(values, bin_edges, sigma,
                               chunk_size=chunk_size, backend=backend,
                               bin_mode=bin_mode, bin_window=bin_window)
    widths = jnp.diff(jnp.asarray(bin_edges))
    return counts / volume / widths


@partial(jax.jit, static_argnames=("chunk_size", "backend", "bin_mode",
                                   "bin_window"))
def binned_density_jit(values, bin_edges, sigma, volume,
                       chunk_size: Optional[int] = None,
                       backend: str = "auto", bin_mode: str = "dense",
                       bin_window: Optional[int] = None):
    return binned_density(values, bin_edges, sigma, volume,
                          chunk_size=chunk_size, backend=backend,
                          bin_mode=bin_mode, bin_window=bin_window)
