"""Ring-sharded differentiable pair counting, TPU-native.

The reference's north star includes two-point clustering workloads
(``BASELINE.json`` configs: "diffdesi_experimental 2pt-correlation
likelihood" and "Multi-probe (SMF + wp(rp)) joint fit"), but the
reference itself never ships a pair-counting kernel — its
``diffdesi_experimental/util.py`` stops at halo-index bookkeeping.
This module supplies the missing capability in the idiomatic TPU
shape: a **ring exchange** over the data mesh axis (``lax.ppermute``),
the same pattern ring attention uses for long sequences, applied to
the particle axis.

Differentiability model
-----------------------
Positions are fixed data; the *per-particle weights* are the
differentiable quantity (selection probabilities, HOD occupations,
completeness — anything the model parameters control).  Weighted pair
counts

    DD_b = sum_{i,j} w_i w_j [r_ij in bin b]

are then smooth in ``w`` while the bin masks are constants, so the VJP
is two masked matvecs — no smoothing kernels needed, and gradients
flow *through the ring*: ``ppermute``'s transpose is the reverse-ring
``ppermute``, which XLA schedules on the same ICI links.

Sharding / additivity contract
------------------------------
Each shard holds a block of particles.  ``ring_weighted_pair_counts``
returns the counts of all **ordered** pairs whose *first* member lives
on the calling shard; summing over shards (``lax.psum`` — done by the
:class:`~multigrad_tpu.core.model.OnePointModel` core) yields the
total ordered-pair counts.  That makes DD a valid additive sumstat:
communication stays O(blocks) per step and O(|bins|) at the end,
never O(N²).

Scaling: per ring step each shard computes an
``(n_local, n_local)``-pair block; ``row_chunk`` tiles the local rows
with ``lax.scan`` so HBM working set stays at ``row_chunk × n_local``
per step regardless of N.  Pad ragged shards with ``weight = 0`` —
exactly neutral for every count (cf. ``utils.pad_to_multiple``).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax


def _min_image(diff, box_size):
    """Periodic minimum-image displacement (box_size may be None)."""
    if box_size is None:
        return diff
    return diff - box_size * jnp.round(diff / box_size)


def _pair_metrics(pos1, pos2, box_size, projected):
    """Squared separations for an (n, m) pair block.

    Returns ``(rsq, pi_abs)`` where ``rsq`` is the full 3D squared
    separation (``projected=False``) or the transverse (x, y) squared
    separation r_p² (``projected=True``), and ``pi_abs`` is the
    absolute line-of-sight (z) separation (None unless projected).
    """
    diff = _min_image(pos1[:, None, :] - pos2[None, :, :], box_size)
    if not projected:
        return jnp.sum(diff * diff, axis=-1), None
    rp_sq = diff[..., 0] ** 2 + diff[..., 1] ** 2
    return rp_sq, jnp.abs(diff[..., 2])


def _block_counts(pos1, w1, pos2, w2, edges_sq, box_size, pimax):
    """Per-bin weighted ordered-pair counts between two blocks.

    counts[b] = Σ_ij w1_i w2_j [edges_sq[b] <= sep² < edges_sq[b+1]]
    (∧ |π| < pimax when projected).  One bin mask → one matvec on the
    MXU: Σ_ij w1_i M_ij w2_j = w1 · (M @ w2).  Bins are computed with
    direct masks (not cumulative-count differences) so float32 counts
    of sparse bins never come from subtracting two large partials.
    """
    projected = pimax is not None
    sep_sq, pi_abs = _pair_metrics(pos1, pos2, box_size, projected)
    pi_ok = (pi_abs < pimax) if projected else None

    def one_bin(lo, hi):
        mask = (sep_sq >= lo) & (sep_sq < hi)
        if projected:
            mask = mask & pi_ok
        return w1 @ (mask.astype(w1.dtype) @ w2)

    return jnp.stack([one_bin(edges_sq[b], edges_sq[b + 1])
                      for b in range(edges_sq.shape[0] - 1)])


def _block_counts_chunked(pos1, w1, pos2, w2, edges_sq, box_size,
                          pimax, row_chunk):
    """Tile pos1's rows with lax.scan to bound the pair-block size.

    A ragged tail is padded internally with weight-0 rows — exactly
    neutral for every count — so ``row_chunk`` need not divide the
    (possibly shard-local, mesh-determined) particle count.
    """
    n = pos1.shape[0]
    if row_chunk is None or n <= row_chunk:
        return _block_counts(pos1, w1, pos2, w2, edges_sq, box_size,
                             pimax)
    from ..utils.util import pad_to_multiple
    pos1, _ = pad_to_multiple(pos1, row_chunk)
    w1, _ = pad_to_multiple(w1, row_chunk)
    n_pad = w1.shape[0]
    pos_rows = pos1.reshape(n_pad // row_chunk, row_chunk,
                            pos1.shape[-1])
    w_rows = w1.reshape(n_pad // row_chunk, row_chunk)

    def body(acc, chunk):
        p, w = chunk
        return acc + _block_counts(p, w, pos2, w2, edges_sq, box_size,
                                   pimax), None

    init = jnp.zeros(edges_sq.shape[0] - 1, dtype=w1.dtype)
    counts, _ = lax.scan(body, init, (pos_rows, w_rows))
    return counts


def _self_pair_counts(w, edges_sq):
    """Σ_i w_i² placed in the bin containing sep² = 0 (for exclusion)."""
    zero_in_bin = (edges_sq[:-1] <= 0.0) & (0.0 < edges_sq[1:])
    return zero_in_bin.astype(w.dtype) * jnp.sum(w * w)


def ring_weighted_pair_counts(positions, weights, bin_edges,
                              axis_name: Optional[str] = None,
                              box_size: Optional[float] = None,
                              pimax: Optional[float] = None,
                              exclude_self: bool = True,
                              row_chunk: Optional[int] = None,
                              backend: str = "auto"):
    """Weighted ordered-pair counts of the full dataset, ring-sharded.

    Parameters
    ----------
    positions : (n_local, 3) array
        This shard's particle positions (the *global* array when
        ``axis_name is None``).
    weights : (n_local,) array
        Differentiable per-particle weights.
    bin_edges : (B+1,) array
        Separation bin edges (3D ``r``, or transverse ``r_p`` when
        ``pimax`` is given).  Monotonic, non-negative.
    axis_name : str or tuple of str, optional
        Mesh axis (or axes, for a hybrid ICI/DCN mesh — the ring then
        rides the linearized axis product) to ring over.  ``None`` →
        single-block all-pairs (the ``comm is None`` fallback,
        mirroring the reference's MPI-less mode,
        ``/root/reference/multigrad/multigrad.py:23-27``).
        Must be called inside ``shard_map`` over the axis/axes —
        :class:`OnePointModel` does this automatically for sumstats
        kernels.
    box_size : float, optional
        Periodic box side; applies minimum-image convention.
    pimax : float, optional
        If given, count pairs in *projected* bins: transverse
        separation ``r_p`` binned by ``bin_edges`` with line-of-sight
        ``|π| < pimax`` (the wp(rp) estimator's DD).
    exclude_self : bool
        Remove the i == j self-pair term (only nonzero when
        ``bin_edges[0] == 0``).
    row_chunk : int, optional
        Tile local rows to bound memory at ``row_chunk × n_local``
        pairs per ring step.
    backend : {"xla", "pallas", "auto"}
        "pallas" computes each pair block with the hand-written TPU
        kernel (:func:`multigrad_tpu.ops.pallas_kernels
        .pair_counts_pallas`) — the (tile, tile) separation block
        stays in VMEM across all bins.  Measured on TPU v5 lite
        (BENCH_NOTES.md, round 3): **1.4-1.9x** the XLA path on the
        fwd+bwd wp(rp) evaluation across sessions (2.50-3.41 vs
        ~4.8 ms at 8192 halos).  "auto" resolves to "pallas" on TPU
        and "xla" elsewhere.

    Returns
    -------
    counts : (B,) array
        This shard's partial counts — ordered pairs (i local,
        j anywhere).  ``lax.psum`` over ``axis_name`` gives the total;
        every unordered pair is counted twice (both orders), the
        standard N(N-1) DD convention.
    """
    positions = jnp.asarray(positions)
    weights = jnp.asarray(weights)
    edges = jnp.asarray(bin_edges)
    edges_sq = edges * edges

    from .binned import _resolve_backend
    requested = backend
    backend = _resolve_backend(backend)
    if requested == "auto" and backend == "pallas":
        from .pallas_kernels import _LANES
        if edges.shape[0] - 1 > _LANES:
            # "auto" falls back to XLA outside the pallas kernel's
            # envelope (one lane row of bins); explicit "pallas"
            # still raises.
            backend = "xla"
    if backend == "pallas":
        from .pallas_kernels import pair_counts_pallas
        # row_chunk bounds a (row_chunk, n_local) block on the XLA
        # path; the pallas kernel's working set is a (tile, tile)
        # square, so round to lane granularity AND cap at the largest
        # VMEM-safe tile (512 — measured limit on v5e; larger tiles
        # fail Mosaic's scoped-vmem allocation in the backward pass).
        tile_kw = {} if row_chunk is None \
            else {"tile": min(512, max(128, -(-row_chunk // 128) * 128))}

        def block_counts(p1, w1, p2, w2):
            return pair_counts_pallas(p1, w1, p2, w2, edges,
                                      box_size=box_size, pimax=pimax,
                                      **tile_kw)
    else:
        def block_counts(p1, w1, p2, w2):
            return _block_counts_chunked(p1, w1, p2, w2, edges_sq,
                                         box_size, pimax, row_chunk)

    if axis_name is None:
        counts = block_counts(positions, weights, positions, weights)
        if exclude_self:
            counts = counts - _self_pair_counts(weights, edges_sq)
        return counts

    if not isinstance(axis_name, str):
        # Multi-axis (hybrid ICI/DCN) comm: ring over the linearized
        # index of the axis product — ppermute accepts a tuple of axis
        # names and numbers shards in mesh-major order.  A ring over a
        # hybrid mesh crosses DCN on the outer-axis wrap steps either
        # way, so flattening loses nothing vs. a hierarchical scheme.
        try:
            axis_name = tuple(axis_name)
            valid = all(isinstance(a, str) for a in axis_name)
        except TypeError:
            valid = False
        if not valid:
            raise TypeError(
                f"axis_name must be a mesh axis name or a tuple of "
                f"them, got {axis_name!r}")

    n_shards = lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(carry, _):
        other_pos, other_w, acc = carry
        acc = acc + block_counts(positions, weights, other_pos, other_w)
        # Pass the visiting block to the next shard around the ring;
        # after n_shards steps every (local, remote) block pair has
        # been counted exactly once.
        other_pos = lax.ppermute(other_pos, axis_name, perm)
        other_w = lax.ppermute(other_w, axis_name, perm)
        return (other_pos, other_w, acc), None

    from ..parallel._shard_map_compat import pvary

    # The accumulator is device-varying (each shard accumulates its own
    # rows); mark the replicated zeros init accordingly (jax vma types).
    init_acc = pvary(jnp.zeros(edges.shape[0] - 1, dtype=weights.dtype),
                     axis_name)
    (_, _, counts), _ = lax.scan(
        body, (positions, weights, init_acc), None, length=n_shards)
    if exclude_self:
        counts = counts - _self_pair_counts(weights, edges_sq)
    return counts


def analytic_rr_counts(total_weight, bin_edges, box_volume,
                       pimax: Optional[float] = None):
    """Expected random-random ordered-pair counts in a periodic box.

    For a uniform random field of total weight W in volume V, the
    expected ordered pair count in a separation bin is
    ``W² × V_bin / V`` where ``V_bin`` is the bin's search volume:
    spherical shell ``4π/3 (r₂³ − r₁³)`` in 3D, or cylindrical annulus
    ``π (rp₂² − rp₁²) × 2 π_max`` for projected bins.  Periodicity
    makes this exact (no edge corrections), which is why clustering
    codes use the analytic RR for box data.
    """
    edges = jnp.asarray(bin_edges)
    if pimax is None:
        vbin = 4.0 * jnp.pi / 3.0 * (edges[1:] ** 3 - edges[:-1] ** 3)
    else:
        vbin = jnp.pi * (edges[1:] ** 2 - edges[:-1] ** 2) * 2.0 * pimax
    return total_weight ** 2 * vbin / box_volume


def wp_from_counts(dd_counts, total_weight, rp_bin_edges, pimax,
                   box_volume):
    """Projected correlation function wp(rp) from DD counts.

    ``wp(rp_b) = (DD_b / RR_b − 1) × 2 π_max`` — the natural-estimator
    ξ integrated over the line of sight, using the analytic RR of
    :func:`analytic_rr_counts`.  All inputs are additive sumstats
    (DD per shard, W per shard), so this belongs in
    ``calc_loss_from_sumstats`` where totals are available.
    """
    rr = analytic_rr_counts(total_weight, rp_bin_edges, box_volume,
                            pimax=pimax)
    return (dd_counts / rr - 1.0) * 2.0 * pimax


def xi_from_counts(dd_counts, total_weight, bin_edges, box_volume):
    """3D two-point correlation function ξ(r) from DD counts
    (natural estimator ``DD/RR − 1`` with analytic RR)."""
    rr = analytic_rr_counts(total_weight, bin_edges, box_volume)
    return dd_counts / rr - 1.0
